"""
Golden axis-matrix differential suite: reductions, cumulatives, manipulations and
indexing vs NumPy over every (shape, split, axis) combination — the reference's
`assert_func_equal` all-splits strategy (test_suites/basic_test.py:~150) widened to
negative axes, keepdims, tuple axes, mixed-split binaries and broadcast operands.
"""

import numpy as np
import pytest

import heat_tpu as ht

SHAPES = [(7,), (4, 5), (3, 4, 5), (2, 3, 4, 2)]
RNG = np.random.default_rng(7)
DATA = {s: (RNG.standard_normal(s).astype(np.float32) * 3) for s in SHAPES}


def _chk(got, want, tol=1e-4):
    got = got.numpy() if hasattr(got, "numpy") else np.asarray(got)
    want = np.asarray(want)
    assert got.shape == want.shape, f"shape {got.shape} vs {want.shape}"
    if want.dtype.kind in "fc":
        np.testing.assert_allclose(got, want, rtol=tol, atol=tol)
    else:
        np.testing.assert_array_equal(got, want)


def _splits(shape):
    return [None] + list(range(len(shape)))


def _axes(shape):
    nd = len(shape)
    return [None] + list(range(-nd, nd))


CASES = [(s, sp, ax) for s in SHAPES for sp in _splits(s) for ax in _axes(s)]


@pytest.mark.parametrize("shape,split,ax", CASES)
def test_reductions_axis_matrix(shape, split, ax):
    a = DATA[shape]
    x = ht.array(a, split=split)
    _chk(ht.sum(x, axis=ax), a.sum(axis=ax), tol=1e-3)
    _chk(ht.mean(x, axis=ax), a.mean(axis=ax))
    _chk(ht.max(x, axis=ax), a.max(axis=ax))
    _chk(ht.min(x, axis=ax, keepdim=True), a.min(axis=ax, keepdims=True))
    _chk(ht.argmax(x, axis=ax), a.argmax(axis=ax))
    _chk(ht.std(x, axis=ax), a.std(axis=ax))
    _chk(ht.median(x, axis=ax), np.median(a, axis=ax))
    _chk(ht.prod(x / 2.0, axis=ax), (a / 2.0).prod(axis=ax), tol=1e-3)


@pytest.mark.parametrize(
    "shape,split,ax",
    [(s, sp, ax) for s in SHAPES for sp in _splits(s) for ax in range(len(s))],
)
def test_axiswise_ops_matrix(shape, split, ax):
    a = DATA[shape]
    x = ht.array(a, split=split)
    _chk(ht.cumsum(x, axis=ax), a.cumsum(axis=ax), tol=1e-3)
    _chk(ht.sort(x, axis=ax)[0], np.sort(a, axis=ax))
    _chk(ht.flip(x, axis=ax), np.flip(a, axis=ax))
    _chk(ht.roll(x, 2, axis=ax), np.roll(a, 2, axis=ax))
    _chk(ht.percentile(x, [25.0, 75.0], axis=ax), np.percentile(a, [25.0, 75.0], axis=ax))


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("split", [None, 0])
def test_manipulations_matrix(shape, split):
    a = DATA[shape]
    nd = len(shape)
    x = ht.array(a, split=split)
    _chk(ht.reshape(x, (-1,)), a.reshape(-1))
    _chk(ht.ravel(x), a.ravel())
    _chk(ht.expand_dims(x, 0), np.expand_dims(a, 0))
    _chk(ht.squeeze(x), np.squeeze(a))
    _chk(ht.repeat(x, 2, axis=0), np.repeat(a, 2, axis=0))
    _chk(ht.tile(x, (2,) * nd), np.tile(a, (2,) * nd))
    _chk(ht.concatenate([x, x], axis=0), np.concatenate([a, a], axis=0))
    _chk(ht.stack([x, x], axis=0), np.stack([a, a], axis=0))
    _chk(ht.pad(x, [(1, 2)] * nd), np.pad(a, [(1, 2)] * nd))
    if nd >= 2:
        _chk(x.T, a.T)
        _chk(ht.swapaxes(x, 0, 1), np.swapaxes(a, 0, 1))
        _chk(ht.sum(x, axis=(0, 1)), a.sum(axis=(0, 1)), tol=1e-3)
        _chk(ht.var(x, axis=0, ddof=1), a.var(axis=0, ddof=1))


@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("split", [None, 0])
def test_indexing_matrix(shape, split):
    a = DATA[shape]
    x = ht.array(a, split=split)
    _chk(x[0], a[0])
    _chk(x[-1], a[-1])
    _chk(x[1:3], a[1:3])
    _chk(x[::2], a[::2])
    _chk(x[x > 0], a[a > 0])
    _chk(ht.where(x > 0, x, -x), np.where(a > 0, a, -a))
    nz_want = np.nonzero(a > 0)
    nz_want = nz_want[0] if len(shape) == 1 else np.stack(nz_want, axis=1)
    _chk(ht.nonzero(x > 0), nz_want)
    if shape[0] >= 3:
        _chk(x[[0, 2]], a[[0, 2]])
    y = ht.array(a.copy(), split=split)
    y[0] = 5.0
    w = a.copy()
    w[0] = 5.0
    _chk(y, w)


@pytest.mark.parametrize("shape", [(4, 5), (3, 4, 5)])
def test_mixed_split_binaries(shape):
    a = DATA[shape]
    b = RNG.standard_normal(shape).astype(np.float32)
    for sx in _splits(shape):
        x = ht.array(a, split=sx)
        for sz in _splits(shape):
            z = ht.array(b, split=sz)
            _chk(x + z, a + b)
            _chk(x * z + x / (ht.abs(z) + 1), a * b + a / (np.abs(b) + 1))
    c = RNG.standard_normal(shape[-1:]).astype(np.float32)
    zc = ht.array(c)
    x0 = ht.array(a, split=0)
    _chk(x0 + zc, a + c)
    _chk(2.5 * x0 - 1, 2.5 * a - 1)


@pytest.mark.parametrize("split", [None, 0, 1])
def test_integer_ops_matrix(split):
    ai = RNG.integers(0, 10, (6, 5)).astype(np.int32)
    xi = ht.array(ai, split=split)
    _chk(xi % 3, ai % 3)
    _chk(xi // 2, ai // 2)
    _chk(xi & 3, ai & 3)
    _chk(xi << 1, ai << 1)
    _chk(ht.invert(xi), ~ai)
    _chk(ht.unique(xi, sorted=True), np.unique(ai))
    _chk(ht.bincount(ht.ravel(xi)), np.bincount(ai.ravel()))
    _chk(ht.diff(xi, axis=0), np.diff(ai, axis=0))
    _chk(ht.diff(xi, axis=1), np.diff(ai, axis=1))
    got, _ = ht.topk(xi.astype(ht.float32), 3, dim=1)
    _chk(got, -np.sort(-ai.astype(np.float32), axis=1)[:, :3])


REDUCERS = [
    ("sum", lambda h, **k: ht.sum(h, **k), np.sum, {}),
    ("prod", lambda h, **k: ht.prod(h, **k), np.prod, {}),
    ("max", lambda h, **k: ht.max(h, **k), np.max, {}),
    ("min", lambda h, **k: ht.min(h, **k), np.min, {}),
    ("mean", lambda h, **k: ht.mean(h, **k), np.mean, {}),
]


@pytest.mark.parametrize("name,hfn,nfn,kw", REDUCERS)
@pytest.mark.parametrize("split", [None, 0, 1, 2])
@pytest.mark.parametrize("axis", [None, 0, 1, 2, (0, 1), (1, 2), (0, 2)])
def test_reduction_multiaxis_matrix(name, hfn, nfn, kw, split, axis):
    rng = np.random.default_rng(123)
    a_np = (rng.uniform(0.5, 1.5, size=(5, 7, 3))).astype(np.float32)
    a = ht.array(a_np, split=split)
    kd_variants = [False, True] if name in ("sum", "max", "mean") else [False]
    for keepdim in kd_variants:
        extra = {"keepdim": keepdim} if keepdim else {}
        if name == "mean":
            got = hfn(a, axis=axis, keepdims=keepdim) if keepdim else hfn(a, axis=axis)
        else:
            got = hfn(a, axis=axis, **extra)
        want = nfn(a_np, axis=axis, keepdims=keepdim)
        np.testing.assert_allclose(
            got.numpy(), want, rtol=1e-4, atol=1e-5,
            err_msg=f"{name} split={split} axis={axis} keepdim={keepdim}",
        )


@pytest.mark.parametrize("split", [None, 0, 1])
def test_logical_reductions_matrix(split):
    rng = np.random.default_rng(124)
    a_np = rng.integers(0, 2, size=(6, 8)).astype(bool)
    a = ht.array(a_np, split=split)
    for axis in (None, 0, 1):
        np.testing.assert_array_equal(
            ht.all(a, axis=axis).numpy(), np.all(a_np, axis=axis)
        )
        np.testing.assert_array_equal(
            ht.any(a, axis=axis).numpy(), np.any(a_np, axis=axis)
        )
    np.testing.assert_array_equal(
        ht.logical_and(a, ~a).numpy(), np.logical_and(a_np, ~a_np)
    )
    np.testing.assert_array_equal(
        ht.logical_xor(a, a).numpy(), np.logical_xor(a_np, a_np)
    )
    assert bool(ht.all(ht.logical_or(a, ~a)).numpy())


def test_isclose_allclose_tolerance_grid():
    a = ht.array(np.array([1.0, 1.0001, np.nan, np.inf], np.float32), split=0)
    b = ht.array(np.array([1.0, 1.0002, np.nan, np.inf], np.float32), split=0)
    np.testing.assert_array_equal(
        ht.isclose(a, b, atol=1e-3).numpy(), [True, True, False, True]
    )
    np.testing.assert_array_equal(
        ht.isclose(a, b, atol=1e-3, equal_nan=True).numpy(), [True, True, True, True]
    )
    assert not bool(ht.allclose(a, b, atol=1e-6))
    assert bool(ht.allclose(a, b, atol=1e-2, equal_nan=True))


@pytest.mark.parametrize("split", [None, 0])
def test_nan_reductions_matrix(split):
    a_np = np.array([[1.0, np.nan, 3.0], [np.nan, 5.0, 6.0]], np.float32)
    a = ht.array(a_np, split=split)
    np.testing.assert_allclose(ht.nansum(a).numpy(), np.nansum(a_np), rtol=1e-6)
    np.testing.assert_allclose(
        ht.nansum(a, axis=0).numpy(), np.nansum(a_np, axis=0), rtol=1e-6
    )
    if hasattr(ht, "nanmax"):
        np.testing.assert_allclose(ht.nanmax(a).numpy(), np.nanmax(a_np), rtol=1e-6)
        np.testing.assert_allclose(ht.nanmin(a).numpy(), np.nanmin(a_np), rtol=1e-6)
