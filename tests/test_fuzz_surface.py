"""
Surface-sweep differential fuzzer (VERDICT r4 #6).

The chain fuzzer (test_fuzz_differential.py) composes deep op chains over a
small op table; this module is the *width* counterpart: one spec per public
``ht.*`` callable, each swept over a randomized case matrix of

  shape      — even-over-mesh, ragged prime (5/7/11/13), tiny, and 0-size axes
  split      — None or any axis
  dtype      — float32, int32, bool, complex64 (where the backend has it),
               and float64 under a genuine ``jax.enable_x64`` context

with a numpy (or scipy, for the stats heads) shadow oracle and the
three-level comparator ``heat_tpu.testing.assert_array_equal`` (dtype,
per-shard placement, values). numpy semantics ARE the reference's contract —
its API is numpy-compatible by design (SURVEY.md §2.2); where the reference
deliberately follows torch instead (topk/histc/bucketize/nonzero), the oracle
encodes the torch convention, cited in the spec.

* Reproducible: every case is determined by (op name, case index) via
  ``crc32`` — a failure message names both, and ``run_case(name, i)`` replays.
* Coverage is enforced: ``test_surface_coverage`` computes the fraction of
  top-level ``ht.*`` functions exercised by this sweep plus the chain
  fuzzer's table and fails below 80% (VERDICT r4 #6 acceptance bar).
* Teeth: ``test_planted_bug_is_caught`` skews one op and asserts the sweep
  fails it.

Case count scales via ``HEAT_TPU_FUZZ_CASES`` (CI's fuzz job raises it so the
total sweep lands at ~10^4 cases, ci.yaml).
"""

import inspect
import os
import types
import zlib

import numpy as np
import pytest
import scipy.stats as sps

import jax
from heat_tpu.core import _compat

import heat_tpu as ht
import heat_tpu.testing as htt
from heat_tpu.core.dndarray import DNDarray

from _accel import COMPLEX_SUPPORTED, ON_ACCELERATOR, tol

# real-accelerator dispatch is ~100 ms/op through the tunnel: keep a thin slice
# there, full width on the CPU mesh / CI
N_CASES = int(os.environ.get("HEAT_TPU_FUZZ_CASES", "2" if ON_ACCELERATOR else "5"))

P = ht.WORLD.size


# ------------------------------------------------------------------- registry
class Spec:
    __slots__ = ("name", "fn", "dtypes", "min_ndim", "empty_ok", "kind", "check_dtype")

    def __init__(self, name, fn, dtypes, min_ndim, empty_ok, kind, check_dtype):
        self.name, self.fn, self.dtypes = name, fn, dtypes
        self.min_ndim, self.empty_ok, self.kind = min_ndim, empty_ok, kind
        self.check_dtype = check_dtype


SPECS = {}

SKIP = object()  # a spec returns this when the drawn input doesn't suit it


def reg(name, fn, dtypes="f", min_ndim=1, empty_ok=True, kind="arr", check_dtype=True):
    assert name not in SPECS, name
    assert callable(getattr(ht, name)), name
    SPECS[name] = Spec(name, fn, dtypes, min_ndim, empty_ok, kind, check_dtype)


# dtype letters: f=float, i=int, b=bool, c=complex. Drawn per case; the x64
# case upgrades f->float64 inside jax.enable_x64.
def _np_dtype(letter, x64):
    return {
        "f": np.float64 if x64 else np.float32,
        "i": np.int32,
        "b": np.bool_,
        "c": np.complex64,
    }[letter]


def unary(name, dtypes="f", np_fn=None, prep=None, **kw):
    """fn(x) with a same-named numpy oracle (or np_fn); prep conditions the
    drawn data into the op's domain (numpy-level, before wrapping)."""
    npf = np_fn if np_fn is not None else getattr(np, name)
    htf = getattr(ht, name)

    def fn(rng, h, a):
        return htf(h), npf(a)

    reg(name, fn, dtypes, **kw)
    if prep is not None:
        PREP[name] = prep


def binary(name, dtypes="f", np_fn=None, other="like", **kw):
    """fn(x, y): y is a same-shape array ("like"), a broadcastable row
    ("bcast"), a positive array ("pos"), or a small non-negative int array
    ("shift")."""
    npf = np_fn if np_fn is not None else getattr(np, name)
    htf = getattr(ht, name)

    def fn(rng, h, a):
        b = _second_operand(rng, a, other)
        split = h.split if b.shape == a.shape else None
        hb = ht.array(b, split=split)
        return htf(h, hb), npf(a, b)

    reg(name, fn, dtypes, **kw)


def reduction(name, dtypes="f", np_fn=None, axis_none_ok=True, **kw):
    """fn(x, axis=...) over a randomly drawn non-empty axis (or full)."""
    npf = np_fn if np_fn is not None else getattr(np, name)
    htf = getattr(ht, name)

    def fn(rng, h, a):
        ax = _nonempty_axis(rng, a, none_ok=axis_none_ok)
        if ax is SKIP:
            return SKIP
        return htf(h, axis=ax), npf(a, axis=ax)

    kw.setdefault("empty_ok", True)
    reg(name, fn, dtypes, **kw)


PREP = {}


def _second_operand(rng, a, other):
    if other == "like":
        b = rng.standard_normal(a.shape)
    elif other == "bcast":
        b = rng.standard_normal(a.shape[-1:] if a.ndim else ())
    elif other == "pos":
        b = np.abs(rng.standard_normal(a.shape)) + 0.5
    elif other == "shift":
        return rng.integers(0, 5, size=a.shape).astype(a.dtype)
    else:  # pragma: no cover
        raise ValueError(other)
    if a.dtype.kind in "iu":
        b = np.round(b * 3).astype(a.dtype)
        if other == "pos":
            b = np.abs(b) + 1
    elif a.dtype.kind == "b":
        b = (b > 0).astype(np.bool_)
    elif a.dtype.kind == "c":
        b = (b + 1j * rng.standard_normal(b.shape)).astype(a.dtype)
    else:
        b = b.astype(a.dtype)
    return b


def _nonempty_axis(rng, a, none_ok=True):
    """An axis with nonzero extent; None (full reduction) only when the whole
    array is nonempty."""
    axes = [d for d in range(a.ndim) if a.shape[d] > 0]
    if none_ok and a.size > 0 and rng.integers(0, 4) == 0:
        return None
    if not axes:
        return SKIP
    return int(axes[rng.integers(0, len(axes))])


def _rand_axis(rng, a):
    return int(rng.integers(0, a.ndim)) if a.ndim else 0


# =========================================================== elementwise unary
_clip4 = lambda a: np.clip(a, -4.0, 4.0)
_unit = lambda a: np.tanh(a) * 0.99  # into (-1, 1) for arc domains
_pos = lambda a: np.abs(a) + 0.5

for n in ["sin", "cos", "tan", "sinh", "cosh", "tanh"]:
    unary(n, prep=_clip4)
for n, npn in [("arcsin", None), ("arccos", None), ("arctanh", None),
               ("asin", "arcsin"), ("acos", "arccos"), ("atanh", "arctanh")]:
    unary(n, np_fn=getattr(np, npn) if npn else None, prep=_unit)
for n, npn in [("arccosh", None), ("acosh", "arccosh")]:
    unary(n, np_fn=getattr(np, npn) if npn else None, prep=lambda a: 1.0 + np.abs(a))
for n, npn in [("arctan", None), ("arcsinh", None), ("atan", "arctan"),
               ("asinh", "arcsinh")]:
    unary(n, np_fn=getattr(np, npn) if npn else None)
for n in ["deg2rad", "rad2deg", "degrees", "radians"]:
    unary(n)
for n in ["exp", "exp2", "expm1"]:
    unary(n, prep=_clip4)
for n in ["log", "log2", "log10"]:
    unary(n, prep=_pos)
unary("log1p", prep=lambda a: np.abs(a))
unary("sqrt", prep=lambda a: np.abs(a))
unary("square", dtypes="fi")
unary("fabs")
for n in ["floor", "ceil", "trunc"]:
    unary(n)
unary("round", dtypes="f")
unary("abs", dtypes="fi")
unary("absolute", dtypes="fi", np_fn=np.abs)
unary("neg", dtypes="fi", np_fn=np.negative)
unary("negative", dtypes="fi")
unary("pos", dtypes="fi", np_fn=np.positive)
unary("positive", dtypes="fi")
unary("sign", dtypes="fi")
unary("sgn", dtypes="fi", np_fn=np.sign)
unary("signbit")

# NaN/Inf probes get NaN and +-Inf planted into the drawn data
_naninf = lambda a: _plant_naninf(a)


def _plant_naninf(a):
    a = a.copy()  # keep the drawn shape: the probes must see every split axis
    if a.size >= 3:
        a.flat[0], a.flat[1], a.flat[2] = np.nan, np.inf, -np.inf
    return a


for n in ["isfinite", "isnan", "isinf", "isneginf", "isposinf"]:
    unary(n, prep=_naninf)
unary("nan_to_num", prep=_naninf)
unary("bitwise_not", dtypes="ib", np_fn=np.bitwise_not)
unary("invert", dtypes="ib")
unary("logical_not", dtypes="bif")

_cplx = "c" if COMPLEX_SUPPORTED else "f"
unary("conj", dtypes=_cplx)
unary("conjugate", dtypes=_cplx)
unary("real", dtypes=_cplx)
unary("angle", dtypes=_cplx)
# imag/iscomplex/isreal: the complex-dtype case is the interesting one where
# the backend has complex; the real-dtype identities (0 / False / True) still
# exercise shape/split propagation everywhere else
unary("imag", dtypes=_cplx + "f")
unary("iscomplex", dtypes=_cplx + "f")
unary("isreal", dtypes=_cplx + "f")

# ========================================================== elementwise binary
for n in ["add", "sub", "mul", "div"]:
    binary(n, dtypes="fi",
           np_fn={"sub": np.subtract, "mul": np.multiply, "div": np.divide}.get(n),
           other="pos" if n == "div" else "like")
binary("subtract", dtypes="fi")
binary("multiply", dtypes="fi")
binary("divide", dtypes="f", other="pos")
binary("floordiv", dtypes="fi", np_fn=np.floor_divide, other="pos")
binary("floor_divide", dtypes="fi", other="pos")
binary("mod", dtypes="fi", np_fn=np.mod, other="pos")
binary("fmod", dtypes="fi", other="pos")
binary("remainder", dtypes="fi", other="pos")
binary("pow", dtypes="f", np_fn=np.power, other="shift")
binary("power", dtypes="f", other="shift")
binary("arctan2", dtypes="f")
binary("atan2", dtypes="f", np_fn=np.arctan2)
binary("hypot", dtypes="f")
binary("copysign", dtypes="f")
binary("logaddexp", dtypes="f")
binary("logaddexp2", dtypes="f")
binary("maximum", dtypes="fi")
binary("minimum", dtypes="fi")
binary("left_shift", dtypes="i", other="shift")
binary("right_shift", dtypes="i", other="shift")
for n in ["bitwise_and", "bitwise_or", "bitwise_xor"]:
    binary(n, dtypes="ib")
for n in ["logical_and", "logical_or", "logical_xor"]:
    binary(n, dtypes="b")
for n, npn in [("eq", "equal"), ("ne", "not_equal"), ("lt", "less"),
               ("le", "less_equal"), ("gt", "greater"), ("ge", "greater_equal")]:
    binary(n, dtypes="fi", np_fn=getattr(np, npn))
for n in ["not_equal", "less", "less_equal", "greater", "greater_equal"]:
    binary(n, dtypes="fi")
binary("isclose", dtypes="f")


def _allclose(rng, h, a):
    b = a + (1e-9 if a.dtype.kind == "f" else 0)
    return ht.allclose(h, ht.array(b, split=h.split)), np.allclose(a, b)


def _equal(rng, h, a):
    # whole-array equality -> python bool (reference relational.py equal ==
    # torch.equal semantics; elementwise spelling is ht.eq)
    same = bool(rng.integers(0, 2))
    b = a if same else _second_operand(rng, a, "like")
    return ht.equal(h, ht.array(b, split=h.split)), np.array_equal(a, b)


reg("equal", _equal, "fi")


reg("allclose", _allclose, "fi")

# ================================================================= reductions
reduction("sum", dtypes="fi")
reduction("prod", dtypes="f")
reduction("nansum", dtypes="f")
reduction("nanprod", dtypes="f")
reduction("max", dtypes="fi", axis_none_ok=False, empty_ok=False)
reduction("min", dtypes="fi", axis_none_ok=False, empty_ok=False)
reduction("nanmax", dtypes="f", axis_none_ok=False, empty_ok=False)
reduction("nanmin", dtypes="f", axis_none_ok=False, empty_ok=False)
reduction("mean", dtypes="f")
reduction("nanmean", dtypes="f")
reduction("median", dtypes="f", axis_none_ok=False, empty_ok=False)
reduction("std", dtypes="f")
reduction("var", dtypes="f")
reduction("argmax", dtypes="f", axis_none_ok=False, empty_ok=False)
reduction("argmin", dtypes="f", axis_none_ok=False, empty_ok=False)
reduction("any", dtypes="b")
reduction("all", dtypes="b")
reduction("count_nonzero", dtypes="fib")


def _cum(name, npf):
    htf = getattr(ht, name)

    def fn(rng, h, a):
        ax = _rand_axis(rng, a)
        return htf(h, axis=ax), npf(a, axis=ax)

    reg(name, fn, "fi")


_cum("cumsum", np.cumsum)
_cum("cumprod", np.cumprod)
_cum("cumproduct", np.cumprod)


def _average(rng, h, a):
    ax = _nonempty_axis(rng, a, none_ok=False)
    if ax is SKIP:
        return SKIP
    w = np.abs(np.random.default_rng(0).standard_normal(a.shape[ax])) + 0.1
    w = w.astype(a.dtype)
    return (
        ht.average(h, axis=ax, weights=ht.array(w)),
        np.average(a, axis=ax, weights=w),
    )


reg("average", _average, "f", empty_ok=False)


def _skew(rng, h, a):
    ax = _nonempty_axis(rng, a, none_ok=False)
    if ax is SKIP or a.shape[ax] < 3:
        return SKIP
    return ht.skew(h, axis=ax, unbiased=False), sps.skew(a, axis=ax, bias=True)


def _kurtosis(rng, h, a):
    ax = _nonempty_axis(rng, a, none_ok=False)
    if ax is SKIP or a.shape[ax] < 4:
        return SKIP
    return (
        ht.kurtosis(h, axis=ax, unbiased=False),
        sps.kurtosis(a, axis=ax, fisher=True, bias=True),
    )


reg("skew", _skew, "f", empty_ok=False, check_dtype=False)
reg("kurtosis", _kurtosis, "f", empty_ok=False, check_dtype=False)


def _percentile(rng, h, a):
    ax = _nonempty_axis(rng, a, none_ok=False)
    if ax is SKIP:
        return SKIP
    q = float(rng.integers(0, 101))
    return (
        ht.percentile(h, q, axis=ax),
        np.percentile(a.astype(np.float64), q, axis=ax, method="linear"),
    )


reg("percentile", _percentile, "f", empty_ok=False, check_dtype=False)


def _cov(rng, h, a):
    n, m = int(rng.integers(2, 7)), int(rng.integers(3, 9))
    x = rng.standard_normal((n, m)).astype(np.float32)
    hx = ht.array(x, split=int(rng.integers(0, 2)) if rng.integers(0, 2) else None)
    return ht.cov(hx), np.cov(x)


reg("cov", _cov, "f", kind="none", check_dtype=False)

# ============================================================== manipulations
def _axed(name, npf=None, dtypes="fib"):
    htf = getattr(ht, name)
    npf = npf or getattr(np, name)

    def fn(rng, h, a):
        ax = _rand_axis(rng, a)
        return htf(h, ax), npf(a, ax)

    reg(name, fn, dtypes)


_axed("flip")


def _roll(rng, h, a):
    ax = _rand_axis(rng, a)
    k = int(rng.integers(-3, 4))
    return ht.roll(h, k, axis=ax), np.roll(a, k, axis=ax)


reg("roll", _roll, "fib")


def _fliplr(rng, h, a):
    return ht.fliplr(h), np.fliplr(a)


def _flipud(rng, h, a):
    return ht.flipud(h), np.flipud(a)


reg("fliplr", _fliplr, "fib", min_ndim=2)
reg("flipud", _flipud, "fib")


def _rot90(rng, h, a):
    k = int(rng.integers(-1, 3))
    return ht.rot90(h, k), np.rot90(a, k)


reg("rot90", _rot90, "fi", min_ndim=2)


def _squeeze(rng, h, a):
    ax = int(rng.integers(0, a.ndim + 1))
    return ht.squeeze(ht.expand_dims(h, ax), ax), a


reg("squeeze", _squeeze, "fib")


def _expand_dims(rng, h, a):
    ax = int(rng.integers(0, a.ndim + 1))
    return ht.expand_dims(h, ax), np.expand_dims(a, ax)


reg("expand_dims", _expand_dims, "fib")


def _reshape(rng, h, a):
    return ht.reshape(h, (-1,)), a.reshape(-1)


reg("reshape", _reshape, "fib")
reg("ravel", lambda rng, h, a: (ht.ravel(h), np.ravel(a)), "fib")
reg("flatten", lambda rng, h, a: (ht.flatten(h), a.reshape(-1)), "fib")


def _moveaxis(rng, h, a):
    if a.ndim < 2:
        return SKIP
    s = _rand_axis(rng, a)
    d = _rand_axis(rng, a)
    return ht.moveaxis(h, s, d), np.moveaxis(a, s, d)


def _swapaxes(rng, h, a):
    if a.ndim < 2:
        return SKIP
    s = _rand_axis(rng, a)
    d = _rand_axis(rng, a)
    return ht.swapaxes(h, s, d), np.swapaxes(a, s, d)


reg("moveaxis", _moveaxis, "fib", min_ndim=2)
reg("swapaxes", _swapaxes, "fib", min_ndim=2)
reg("transpose", lambda rng, h, a: (ht.transpose(h), a.T), "fib")


def _repeat(rng, h, a):
    r = int(rng.integers(1, 4))
    ax = _rand_axis(rng, a)
    return ht.repeat(h, r, axis=ax), np.repeat(a, r, axis=ax)


reg("repeat", _repeat, "fi")


def _tile(rng, h, a):
    reps = tuple(int(rng.integers(1, 3)) for _ in range(a.ndim))
    return ht.tile(h, reps), np.tile(a, reps)


reg("tile", _tile, "fi")


def _pad(rng, h, a):
    w = tuple((int(rng.integers(0, 3)), int(rng.integers(0, 3))) for _ in range(a.ndim))
    return ht.pad(h, w), np.pad(a, w)


reg("pad", _pad, "fi")


def _broadcast_to(rng, h, a):
    tgt = (3,) + a.shape
    return ht.broadcast_to(h, tgt), np.broadcast_to(a, tgt)


reg("broadcast_to", _broadcast_to, "fi")


def _concat(rng, h, a):
    ax = _rand_axis(rng, a)
    return ht.concatenate([h, h], axis=ax), np.concatenate([a, a], axis=ax)


reg("concatenate", _concat, "fib")


def _stack(rng, h, a):
    ax = int(rng.integers(0, a.ndim + 1))
    return ht.stack([h, h], axis=ax), np.stack([a, a], axis=ax)


reg("stack", _stack, "fib")

def _mk_stack(name, npf):
    htf = getattr(ht, name)

    def fn(rng, h, a):
        return htf([h, h]), npf([a, a])

    reg(name, fn, "fi")


_mk_stack("hstack", np.hstack)
_mk_stack("vstack", np.vstack)
_mk_stack("column_stack", np.column_stack)
_mk_stack("row_stack", np.vstack)


def _split(rng, h, a):
    n = 2 * int(rng.integers(1, 9))
    x = rng.standard_normal((n, int(rng.integers(1, 5)))).astype(np.float32)
    hx = ht.array(x, split=int(rng.integers(0, 2)) if rng.integers(0, 2) else None)
    return ht.split(hx, 2, axis=0), np.split(x, 2, axis=0)


reg("split", _split, "fi", kind="none")


def _mk_xsplit(name, npf, need_dim):
    htf = getattr(ht, name)
    axis = {"hsplit": 1, "vsplit": 0, "dsplit": 2}[name]

    def fn(rng, h, a):
        # the split axis must be even: trim an odd tail (keeps the generic
        # draw's dtype/x64/ragged/split coverage, never self-skips)
        m = a.shape[axis] - a.shape[axis] % 2
        if m == 0:  # extent-1 axis: double it instead of skipping
            h = ht.concatenate([h, h], axis=axis)
            a = np.concatenate([a, a], axis=axis)
            m = 2
        sl = tuple(
            slice(0, m) if d == axis else slice(None) for d in range(a.ndim)
        )
        return htf(h[sl], 2), npf(a[sl], 2)

    reg(name, fn, "fi", min_ndim=need_dim, empty_ok=False)


_mk_xsplit("hsplit", np.hsplit, 2)
_mk_xsplit("vsplit", np.vsplit, 2)
_mk_xsplit("dsplit", np.dsplit, 3)


def _sort(rng, h, a):
    ax = _rand_axis(rng, a)
    desc = bool(rng.integers(0, 2))
    v, idx = ht.sort(h, axis=ax, descending=desc)
    ref = np.sort(a, axis=ax, kind="stable")
    if desc:
        ref = np.flip(ref, axis=ax)
    return v, ref


reg("sort", _sort, "fi")


def _argsort(rng, h, a):
    ax = _rand_axis(rng, a)
    idx = ht.argsort(h, axis=ax)
    # indices are only well-defined for unique values; compare through gather
    gathered = np.take_along_axis(a, idx.numpy().astype(np.int64), axis=ax)
    return ht.array(gathered, split=None), np.sort(a, axis=ax, kind="stable")


reg("argsort", _argsort, "fi", check_dtype=False)


def _topk(rng, h, a):
    # torch convention (reference manipulations: topk mirrors torch.topk)
    if a.shape[-1] == 0:
        return SKIP
    k = int(rng.integers(1, a.shape[-1] + 1))
    v, idx = ht.topk(h, k, dim=-1, largest=True, sorted=True)
    ref = np.flip(np.sort(a, axis=-1), axis=-1)[..., :k]
    return v, ref


reg("topk", _topk, "fi", empty_ok=False)


def _unique(rng, h, a):
    return ht.unique(h, sorted=True), np.unique(a)


reg("unique", _unique, "fi", check_dtype=False)


def _searchsorted(rng, h, a):
    if a.ndim != 1:
        return SKIP
    srt = np.sort(a.astype(np.float64)).astype(a.dtype)
    v = rng.standard_normal(4).astype(a.dtype) if a.dtype.kind == "f" else rng.integers(
        -5, 6, 4
    ).astype(a.dtype)
    side = "right" if rng.integers(0, 2) else "left"
    return (
        ht.searchsorted(ht.array(srt), ht.array(v), side=side),
        np.searchsorted(srt, v, side=side),
    )


reg("searchsorted", _searchsorted, "fi", check_dtype=False, kind="vec")


def _digitize(rng, h, a):
    bins = np.array([-2.0, -0.5, 0.5, 2.0], dtype=np.float32)
    right = bool(rng.integers(0, 2))
    return ht.digitize(h, ht.array(bins), right=right), np.digitize(
        np.asarray(a, np.float32), bins, right=right
    )


reg("digitize", _digitize, "f", check_dtype=False)


def _bucketize(rng, h, a):
    # torch convention: right=False counts boundaries <= x (reference
    # statistics.py bucketize == torch.bucketize == searchsorted flip)
    bins = np.array([-2.0, -0.5, 0.5, 2.0], dtype=np.float32)
    right = bool(rng.integers(0, 2))
    return ht.bucketize(h, ht.array(bins), right=right), np.searchsorted(
        bins, np.asarray(a, np.float32), side="right" if right else "left"
    )


reg("bucketize", _bucketize, "f", check_dtype=False)


def _bincount(rng, h, a):
    if a.ndim != 1:
        return SKIP
    v = np.abs(a).astype(np.int32) % 7
    return ht.bincount(ht.array(v, split=h.split)), np.bincount(v)


reg("bincount", _bincount, "i", check_dtype=False, kind="vec")


def _histc(rng, h, a):
    # torch convention (reference statistics.py histc == torch.histc)
    return ht.histc(h, bins=8, min=-2.0, max=2.0), np.histogram(
        a, bins=8, range=(-2.0, 2.0)
    )[0].astype(np.float32)


reg("histc", _histc, "f", check_dtype=False)


def _histogram(rng, h, a):
    hist, edges = ht.histogram(h, bins=6)
    # edges must equal numpy's f64-derived edges (to f32 rounding); counts are
    # compared THROUGH those returned edges — numpy's int-bins path places
    # exact-edge samples by comparing against its f64 edges, which no f32
    # device placement can reproduce (a sample ON an edge may land one bin
    # over, mega-fuzz cases 49/93), while explicit-edge placement is
    # deterministic in both libraries
    ne = np.histogram_bin_edges(a, bins=6)
    nh, _ = np.histogram(a, bins=edges.numpy())
    return (hist, edges), (nh, ne)


reg("histogram", _histogram, "f", empty_ok=False, check_dtype=False)


def _isin(rng, h, a):
    test = rng.integers(-3, 4, 4).astype(a.dtype)
    return ht.isin(h, ht.array(test)), np.isin(a, test)


reg("isin", _isin, "i")


def _nonzero(rng, h, a):
    # torch convention: an (n, ndim) index matrix for ndim>=2 (reference
    # indexing.py nonzero == torch.nonzero); numpy tuple-stack as oracle
    r = ht.nonzero(h)
    if a.ndim == 1:
        ref = np.nonzero(a)[0]
    else:
        ref = np.stack(np.nonzero(a), axis=1) if a.size else np.zeros((0, a.ndim))
    return r, ref


reg("nonzero", _nonzero, "fib", check_dtype=False)


def _where(rng, h, a):
    return ht.where(h > 0, h, -h), np.where(a > 0, a, -a)


reg("where", _where, "f")


def _take(rng, h, a):
    if a.shape[0] == 0:
        return SKIP
    idx = rng.integers(0, a.shape[0], 5)
    return ht.take(h, ht.array(idx.astype(np.int32)), axis=0), np.take(a, idx, axis=0)


reg("take", _take, "fi", empty_ok=False)


def _take_along_axis(rng, h, a):
    ax = _rand_axis(rng, a)
    if a.shape[ax] == 0:
        return SKIP
    idx = np.argsort(a.astype(np.float64), axis=ax)
    return (
        ht.take_along_axis(h, ht.array(idx.astype(np.int32)), axis=ax),
        np.take_along_axis(a, idx, axis=ax),
    )


reg("take_along_axis", _take_along_axis, "f", empty_ok=False)


def _clip(rng, h, a):
    return ht.clip(h, -1.0, 1.0), np.clip(a, -1.0, 1.0)


reg("clip", _clip, "f")


def _diff(rng, h, a):
    ax = _rand_axis(rng, a)
    if a.shape[ax] < 2:
        return SKIP
    if rng.integers(0, 2):
        return ht.diff(h, axis=ax), np.diff(a, axis=ax)
    return ht.diff(h, axis=ax, append=h), np.diff(a, axis=ax, append=a)


reg("diff", _diff, "fi", empty_ok=False)


def _modf(rng, h, a):
    frac, whole = ht.modf(h)
    nf, nw = np.modf(a)
    return (frac, whole), (nf, nw)


reg("modf", _modf, "f")


def _diag(rng, h, a):
    if a.ndim > 2:
        return SKIP
    off = int(rng.integers(-1, 2))
    return ht.diag(h, off), np.diag(a, off)


reg("diag", _diag, "fi", empty_ok=False)


def _diagonal(rng, h, a):
    if a.ndim < 2:
        return SKIP
    off = int(rng.integers(-1, 2))
    return ht.diagonal(h, off), np.diagonal(a, off)


reg("diagonal", _diagonal, "fi", min_ndim=2, empty_ok=False)


def _tri(name, npf):
    htf = getattr(ht, name)

    def fn(rng, h, a):
        if a.ndim < 2:
            return SKIP
        k = int(rng.integers(-1, 2))
        return htf(h, k), npf(a, k)

    reg(name, fn, "fi", min_ndim=2)


_tri("tril", np.tril)
_tri("triu", np.triu)


def _trace(rng, h, a):
    if a.ndim < 2 or min(a.shape[:2]) == 0:
        return SKIP
    return ht.trace(h), np.trace(a)


reg("trace", _trace, "fi", min_ndim=2, empty_ok=False, check_dtype=False)


def _identityish(name):
    htf = getattr(ht, name)

    def fn(rng, h, a):
        return htf(h), a

    reg(name, fn, "fib")


_identityish("copy")
_identityish("balance")


def _resplit(rng, h, a):
    tgt = [None, *range(a.ndim)][int(rng.integers(0, a.ndim + 1))]
    return ht.resplit(h, tgt), a


reg("resplit", _resplit, "fib")


def _redistribute(rng, h, a):
    return ht.redistribute(h), a


reg("redistribute", _redistribute, "fib")

# ===================================================================== linalg


def _sqmat(rng, n, dtype, x64=False):
    """A well-conditioned square matrix."""
    a = rng.standard_normal((n, n)) + n * np.eye(n)
    return a.astype(dtype)


def _matmul(rng, h, a):
    if a.ndim != 2 or 0 in a.shape:
        return SKIP
    b = rng.standard_normal((a.shape[1], 3)).astype(a.dtype)
    return ht.matmul(h, ht.array(b)), a @ b


reg("matmul", _matmul, "f", min_ndim=2, empty_ok=False)


def _dot(rng, h, a):
    if a.ndim != 1 or a.size == 0:
        return SKIP
    b = rng.standard_normal(a.shape).astype(a.dtype)
    return ht.dot(h, ht.array(b, split=h.split)), np.dot(a, b)


reg("dot", _dot, "f", empty_ok=False, kind="vec")


def _outer(rng, h, a):
    if a.ndim != 1 or a.size == 0:
        return SKIP
    b = rng.standard_normal(3).astype(a.dtype)
    return ht.outer(h, ht.array(b)), np.outer(a, b)


reg("outer", _outer, "f", empty_ok=False, kind="vec")


def _vdot(rng, h, a):
    if a.ndim != 1 or a.size == 0:
        return SKIP
    b = rng.standard_normal(a.shape).astype(a.dtype)
    return ht.vdot(h, ht.array(b, split=h.split)), np.vdot(a, b)


reg("vdot", _vdot, "f", empty_ok=False, kind="vec")


def _vecdot(rng, h, a):
    if a.ndim < 1 or a.shape[-1] == 0:
        return SKIP
    b = rng.standard_normal(a.shape).astype(a.dtype)
    return (
        ht.vecdot(h, ht.array(b, split=h.split)),
        np.einsum("...i,...i->...", a, b),
    )


reg("vecdot", _vecdot, "f", empty_ok=False)


def _cross(rng, h, a):
    n = int(rng.integers(1, 9))
    x = rng.standard_normal((n, 3)).astype(np.float32)
    b = rng.standard_normal((n, 3)).astype(np.float32)
    split = 0 if rng.integers(0, 2) else None
    return ht.cross(ht.array(x, split=split), ht.array(b, split=split)), np.cross(x, b)


reg("cross", _cross, "f", kind="none")


def _projection(rng, h, a):
    if a.ndim != 1 or a.size == 0:
        return SKIP
    b = rng.standard_normal(a.shape).astype(a.dtype) + 0.5
    ref = (np.dot(a, b) / np.dot(b, b)) * b
    return ht.projection(h, ht.array(b, split=h.split)), ref


reg("projection", _projection, "f", empty_ok=False, kind="vec")


def _linalg_sq(name, npf):
    htf = getattr(ht, name)

    def fn(rng, h, a):
        n = int(rng.integers(2, 7))
        m = _sqmat(rng, n, a.dtype)
        split = int(rng.integers(0, 2)) if rng.integers(0, 2) else None
        hm = ht.array(m, split=split)
        return htf(hm), npf(m.astype(np.float64))

    reg(name, fn, "f", check_dtype=False)


_linalg_sq("det", np.linalg.det)
_linalg_sq("inv", np.linalg.inv)


def _slogdet(rng, h, a):
    n = int(rng.integers(2, 7))
    m = _sqmat(rng, n, a.dtype)
    hm = ht.array(m, split=0 if rng.integers(0, 2) else None)
    s, ld = ht.slogdet(hm)
    ns, nld = np.linalg.slogdet(m.astype(np.float64))
    return (s, ld), (ns, nld)


reg("slogdet", _slogdet, "f", check_dtype=False)


def _solve(rng, h, a):
    n = int(rng.integers(2, 7))
    m = _sqmat(rng, n, a.dtype)
    b = rng.standard_normal((n, 2)).astype(a.dtype)
    hm = ht.array(m, split=0 if rng.integers(0, 2) else None)
    return ht.solve(hm, ht.array(b)), np.linalg.solve(
        m.astype(np.float64), b.astype(np.float64)
    )


reg("solve", _solve, "f", check_dtype=False)


def _cg(rng, h, a):
    n = int(rng.integers(3, 7))
    r = rng.standard_normal((n, n))
    spd = (r @ r.T + n * np.eye(n)).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    x0 = np.zeros(n, dtype=np.float32)
    got = ht.cg(ht.array(spd), ht.array(b), ht.array(x0))
    ref = np.linalg.solve(spd.astype(np.float64), b.astype(np.float64))
    return got, ref


reg("cg", _cg, "f", check_dtype=False)


def _qr(rng, h, a):
    m, n = int(rng.integers(3, 9)), int(rng.integers(2, 5))
    if m < n:
        m, n = n, m
    x = rng.standard_normal((m, n)).astype(np.float32)
    hx = ht.array(x, split=0 if rng.integers(0, 2) else None)
    q, r = ht.qr(hx)
    qn, rn = q.numpy(), r.numpy()
    np.testing.assert_allclose(qn @ rn, x, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(qn.T @ qn, np.eye(qn.shape[1]), atol=5e-4)
    return ht.array(qn @ rn), x  # reconstruction comparison drives the engine


reg("qr", _qr, "f", check_dtype=False)


def _svd(rng, h, a):
    m, n = int(rng.integers(3, 9)), int(rng.integers(2, 5))
    x = rng.standard_normal((m, n)).astype(np.float32)
    hx = ht.array(x, split=0 if rng.integers(0, 2) else None)
    u, s, vt = ht.svd(hx)
    rec = u.numpy() @ np.diag(s.numpy()) @ vt.numpy()
    np.testing.assert_allclose(
        np.sort(s.numpy())[::-1], np.linalg.svd(x, compute_uv=False), rtol=5e-4, atol=5e-4
    )
    return ht.array(rec), x


reg("svd", _svd, "f", check_dtype=False)


def _rsvd(rng, h, a):
    m, n, r = 12, 6, 3
    lo = rng.standard_normal((m, r)).astype(np.float32)
    hi = rng.standard_normal((r, n)).astype(np.float32)
    x = lo @ hi
    u, s, vt = ht.rsvd(ht.array(x, split=0), rank=r, random_state=0)
    rec = u.numpy() @ np.diag(s.numpy()) @ vt.numpy()
    return ht.array(rec), x


reg("rsvd", _rsvd, "f", check_dtype=False)


def _lanczos(rng, h, a):
    n, m = 8, 4
    r = rng.standard_normal((n, n))
    spd = (r @ r.T + n * np.eye(n)).astype(np.float32)
    V, T = ht.lanczos(ht.array(spd), m)
    Vn, Tn = V.numpy(), T.numpy()
    np.testing.assert_allclose(Vn.T @ Vn, np.eye(Vn.shape[1]), atol=1e-3)
    return ht.array(Vn.T @ (spd @ Vn)), Tn


reg("lanczos", _lanczos, "f", check_dtype=False)


def _norm(rng, h, a):
    return ht.norm(h), np.linalg.norm(np.asarray(a, np.float64).reshape(-1))


reg("norm", _norm, "f", empty_ok=False, check_dtype=False)


def _vector_norm(rng, h, a):
    ax = _nonempty_axis(rng, a, none_ok=False)
    if ax is SKIP:
        return SKIP
    return (
        ht.vector_norm(h, axis=ax),
        np.linalg.norm(np.asarray(a, np.float64), axis=ax),
    )


reg("vector_norm", _vector_norm, "f", empty_ok=False, check_dtype=False)


def _matrix_norm(rng, h, a):
    n, m = int(rng.integers(1, 8)), int(rng.integers(1, 8))
    x = rng.standard_normal((n, m)).astype(np.float32)
    hx = ht.array(x, split=int(rng.integers(0, 2)) if rng.integers(0, 2) else None)
    return ht.matrix_norm(hx, axis=(0, 1)), np.linalg.norm(
        np.asarray(x, np.float64), "fro"
    )


reg("matrix_norm", _matrix_norm, "f", kind="none", check_dtype=False)

# ================================================================== factories


def _factory_spec(name, fn, **kw):
    reg(name, fn, dtypes="f", kind="none", **kw)


def _arange(rng, h, a):
    n = int(rng.integers(1, 17))
    return ht.arange(n, split=0), np.arange(n)


def _linspace(rng, h, a):
    n = int(rng.integers(2, 17))
    return ht.linspace(-2.0, 3.0, n, split=0), np.linspace(-2.0, 3.0, n, dtype=np.float32)


def _logspace(rng, h, a):
    n = int(rng.integers(2, 9))
    return ht.logspace(0.0, 2.0, n), np.logspace(0.0, 2.0, n, dtype=np.float32)


def _eye(rng, h, a):
    n = int(rng.integers(1, 9))
    return ht.eye(n, split=0), np.eye(n, dtype=np.float32)


_factory_spec("arange", _arange, check_dtype=False)
_factory_spec("linspace", _linspace, check_dtype=False)
_factory_spec("logspace", _logspace, check_dtype=False)
_factory_spec("eye", _eye, check_dtype=False)


def _shape_draw(rng):
    nd = int(rng.integers(1, 4))
    return tuple(int(rng.integers(1, 5)) for _ in range(nd))


def _mk_filled(name, npf, val=None):
    htf = getattr(ht, name)

    def fn(rng, h, a):
        shp = _shape_draw(rng)
        split = int(rng.integers(0, len(shp))) if rng.integers(0, 2) else None
        if val is None:
            return htf(shp, split=split), npf(shp, dtype=np.float32)
        return htf(shp, val, split=split), npf(shp, val, dtype=np.float32)

    _factory_spec(name, fn)


_mk_filled("ones", np.ones)
_mk_filled("zeros", np.zeros)
_mk_filled("full", np.full, val=2.5)


def _empty(rng, h, a):
    shp = _shape_draw(rng)
    e = ht.empty(shp, split=0)
    assert tuple(e.shape) == shp and e.split == 0
    return ht.zeros(shp), np.zeros(shp, dtype=np.float32)


_factory_spec("empty", _empty)


def _mk_like(name, npf):
    htf = getattr(ht, name)

    def fn(rng, h, a):
        return htf(h), npf(a)

    reg(name, fn, "fi")


_mk_like("ones_like", np.ones_like)
_mk_like("zeros_like", np.zeros_like)


def _full_like(rng, h, a):
    return ht.full_like(h, 3), np.full_like(a, 3)


reg("full_like", _full_like, "fi")


def _empty_like(rng, h, a):
    e = ht.empty_like(h)
    assert tuple(e.shape) == a.shape
    return ht.zeros_like(h), np.zeros_like(a)


reg("empty_like", _empty_like, "fi")


def _meshgrid(rng, h, a):
    x = np.arange(3, dtype=np.float32)
    y = np.arange(4, dtype=np.float32)
    gh = ht.meshgrid(ht.array(x), ht.array(y))
    gn = np.meshgrid(x, y)
    return tuple(gh), tuple(gn)


_factory_spec("meshgrid", _meshgrid)


def _array(rng, h, a):
    return ht.array(a, split=h.split), a


def _asarray(rng, h, a):
    return ht.asarray(a), a


def _from_numpy(rng, h, a):
    return ht.from_numpy(a), a


reg("array", _array, "fib")
reg("asarray", _asarray, "fib")
reg("from_numpy", _from_numpy, "fib")

# ============================================================== type helpers


def _type_smoke(name, fn):
    reg(name, fn, dtypes="f", kind="none")


def _promote(rng, h, a):
    assert ht.promote_types(ht.float32, ht.int32) is ht.float32
    assert ht.promote_types(ht.uint8, ht.int8) is ht.int16
    return None, None


def _result_type(rng, h, a):
    assert ht.result_type(ht.int32, ht.float32) is ht.float32
    with _compat.enable_x64(True):
        assert ht.result_type(ht.float32, ht.float64) is ht.float64
    return None, None


def _can_cast(rng, h, a):
    assert ht.can_cast(ht.int32, ht.float64)
    assert not ht.can_cast(ht.float64, ht.int32, casting="safe")
    return None, None


def _issubdtype(rng, h, a):
    assert ht.issubdtype(ht.float32, ht.floating)
    assert not ht.issubdtype(ht.int32, ht.floating)
    return None, None


def _heat_type_of(rng, h, a):
    assert ht.heat_type_of(np.float32(1.0)) is ht.float32
    return None, None


def _heat_type_is_exact(rng, h, a):
    assert ht.heat_type_is_exact(ht.int32) and not ht.heat_type_is_exact(ht.float32)
    return None, None


def _heat_type_is_inexact(rng, h, a):
    assert ht.heat_type_is_inexact(ht.float32) and not ht.heat_type_is_inexact(ht.int32)
    return None, None


def _canonical(rng, h, a):
    assert ht.canonical_heat_type(np.float32) is ht.float32
    return None, None


def _broadcast_shape(rng, h, a):
    assert ht.broadcast_shape((4, 1), (3,)) == np.broadcast_shapes((4, 1), (3,))
    return None, None


def _broadcast_shapes(rng, h, a):
    assert ht.broadcast_shapes((2, 1), (1, 5), (2, 5)) == np.broadcast_shapes(
        (2, 1), (1, 5), (2, 5)
    )
    return None, None


def _shape(rng, h, a):
    assert ht.shape(h) == a.shape
    return None, None


_type_smoke("promote_types", _promote)
_type_smoke("result_type", _result_type)
_type_smoke("can_cast", _can_cast)
_type_smoke("issubdtype", _issubdtype)
_type_smoke("heat_type_of", _heat_type_of)
_type_smoke("heat_type_is_exact", _heat_type_is_exact)
_type_smoke("heat_type_is_inexact", _heat_type_is_inexact)
_type_smoke("canonical_heat_type", _canonical)
_type_smoke("broadcast_shape", _broadcast_shape)
_type_smoke("broadcast_shapes", _broadcast_shapes)
reg("shape", _shape, "fib")


# ================================================================== the engine
def _draw_input(rng, spec, x64, dtype_letter):
    """Draw (h, a) for a spec: random ndim/shape (ragged primes, even-over-
    mesh, tiny, occasional 0-size axis), random split, requested dtype."""
    if spec.kind == "vec":
        nd = 1
    else:
        nd = int(rng.integers(max(spec.min_ndim, 1), 4))
    dims = []
    for _ in range(nd):
        kind = rng.integers(0, 4)
        if kind == 0:
            dims.append(int(rng.integers(1, 4)) * P)  # even over the mesh
        elif kind == 1:
            dims.append(int(rng.choice([5, 7, 11, 13])))  # ragged prime
        elif kind == 2 and spec.empty_ok:
            dims.append(0)  # 0-size axis
        else:
            dims.append(int(rng.integers(1, 9)))
    shape = tuple(dims)
    dt = _np_dtype(dtype_letter, x64)
    if dtype_letter == "b":
        a = rng.integers(0, 2, size=shape).astype(np.bool_)
    elif dtype_letter == "i":
        a = rng.integers(-5, 6, size=shape).astype(dt)
    elif dtype_letter == "c":
        a = (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(dt)
    else:
        a = rng.standard_normal(shape).astype(dt)
    if spec.name in PREP:
        a = np.asarray(PREP[spec.name](a), dtype=dt)
    split = [None, *range(a.ndim)][int(rng.integers(0, a.ndim + 1))]
    return ht.array(a.copy(), split=split), a


# specs whose internals run in float32 regardless of the input dtype schedule
# (they build their own f32 operands) — the x64 tight tolerance never applies
_F32_INTERNAL = frozenset({"cg", "rsvd", "lanczos", "svd", "qr", "skew",
                           "kurtosis", "cov", "cross", "matrix_norm", "split"})


def _tolkw(spec, dtype_letter, x64):
    if spec.name == "rsvd" and ON_ACCELERATOR:
        # the randomized range-finder's sketch GEMMs deliberately run at
        # Precision.DEFAULT (svd.py:128-136) — bf16 passes on the MXU — so
        # exact-rank reconstruction carries ~1e-3-level roundoff there
        return dict(rtol=2e-2, atol=2e-3)
    if spec.name in _F32_INTERNAL:
        return dict(rtol=5e-3, atol=5e-4)
    if x64 and dtype_letter == "f":
        if spec.name in {"percentile", "std", "var", "logspace", "linspace"}:
            return dict(rtol=1e-6, atol=1e-8)
        return dict(rtol=1e-8, atol=1e-10)
    if spec.name in {"det", "inv", "solve", "slogdet", "norm", "vector_norm",
                     "matrix_norm", "percentile", "std", "var", "matmul", "dot",
                     "vdot", "vecdot", "outer", "projection", "mean", "nanmean",
                     "average", "prod", "cumprod", "cumproduct", "logaddexp",
                     "logaddexp2", "hypot", "logspace", "linspace"}:
        return dict(rtol=2e-4, atol=2e-5)
    return tol(spec.name)


def _check(out_h, out_np, tolkw, spec, msg):
    if out_h is None and out_np is None:
        return
    if isinstance(out_h, (tuple, list)):
        assert isinstance(out_np, (tuple, list)) and len(out_h) == len(out_np), msg
        for oh, on in zip(out_h, out_np):
            _check(oh, on, tolkw, spec, msg)
        return
    if isinstance(out_h, DNDarray):
        try:
            htt.assert_array_equal(
                out_h, np.asarray(out_np), check_dtype=spec.check_dtype, **tolkw
            )
        except AssertionError as e:
            raise AssertionError(f"{e}\n{msg}") from e
    else:
        np.testing.assert_allclose(
            np.asarray(out_h), np.asarray(out_np), err_msg=msg, **tolkw
        )


def run_case(name, i):
    """Replay case ``i`` of op ``name`` — fully determined by (name, i)."""
    spec = SPECS[name]
    rng = np.random.default_rng([zlib.crc32(name.encode()), i])
    # dtype schedule: case 0 first float candidate, case 1 the x64 float
    # variant, later cases cycle the op's full dtype set
    letters = list(spec.dtypes)
    x64 = False
    if i == 1 and "f" in letters and not ON_ACCELERATOR:
        letter, x64 = "f", True
    else:
        letter = letters[i % len(letters)]
    if letter == "c" and not COMPLEX_SUPPORTED:
        letter = "f" if "f" in letters else letters[0]
    ctx = _compat.enable_x64(True) if x64 else None
    msg = f"surface fuzz op={name} case={i} dtype={letter} x64={x64}"
    try:
        if ctx is not None:
            ctx.__enter__()
        if spec.kind == "none":
            out = spec.fn(rng, None, None)
        else:
            h, a = _draw_input(rng, spec, x64, letter)
            out = spec.fn(rng, h, a)
        if out is SKIP:
            return "skip"
        _check(out[0], out[1], _tolkw(spec, letter, x64), spec, msg)
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
    return "ok"


@pytest.mark.parametrize("name", sorted(SPECS))
def test_surface_op(name):
    ran = 0
    for i in range(N_CASES):
        if run_case(name, i) == "ok":
            ran += 1
    assert ran > 0, f"every drawn case for {name} self-skipped — widen its draw"


# ------------------------------------------------------------------- coverage
# ht.* callables the sweep deliberately does not drive: IO round-trips,
# printing, comm/device configuration, and estimator/sanitation helpers all
# have dedicated suites (test_io.py, test_misc.py, test_communication.py,
# test_sanitation.py) — a differential fuzzer adds nothing over those.
EXCLUDED = frozenset({
    "load", "load_csv", "load_hdf5", "save", "save_csv", "save_hdf5",
    "supports_hdf5", "supports_netcdf",
    "print0", "local_printing", "global_printing", "get_printoptions",
    "set_printoptions",
    "use_comm", "use_device", "get_comm", "get_device", "distributed_init",
    "is_classifier", "is_estimator", "is_regressor", "is_transformer",
    "scalar_to_1d",
})

# chain-fuzzer table contributions (test_fuzz_differential.py OPS) that the
# sweep doesn't re-register under the same public name
CHAIN_COVERED = frozenset({"exp", "sqrt", "log1p", "round", "sign", "sum",
                           "mean", "max", "any", "all", "cumsum", "transpose",
                           "flip", "reshape", "squeeze", "expand_dims", "roll",
                           "sort", "concatenate", "where", "maximum", "abs",
                           "clip"})


def _toplevel_functions():
    out = []
    for s in sorted(dir(ht)):
        if s.startswith("_"):
            continue
        o = getattr(ht, s)
        if callable(o) and not inspect.isclass(o) and not isinstance(o, types.ModuleType):
            out.append(s)
    return out

def test_surface_coverage():
    """VERDICT r4 #6 acceptance bar: the fuzz layer exercises >=80% of the
    top-level ``ht.*`` callables (sanitation helpers excluded: they are the
    validation layer the fuzzed ops already route through)."""
    fns = [f for f in _toplevel_functions() if not f.startswith("sanitize_")]
    covered = (set(SPECS) | CHAIN_COVERED) & set(fns)
    # EXCLUDED ops are out of the denominator too: they're covered by
    # dedicated suites, not "missing" fuzz targets
    fuzzable = [f for f in fns if f not in EXCLUDED]
    frac = len(covered & set(fuzzable)) / len(fuzzable)
    missing = sorted(set(fuzzable) - set(SPECS) - CHAIN_COVERED)
    assert frac >= 0.80, (
        f"surface fuzz coverage {frac:.1%} < 80% — unswept ops: {missing}"
    )


def test_case_is_reproducible():
    assert run_case("add", 0) == run_case("add", 0)


@pytest.mark.skipif(ON_ACCELERATOR, reason="harness-teeth proof runs on the CPU mesh")
def test_planted_bug_is_caught(monkeypatch):
    """A 1e-3 skew planted into ht.add must fail its sweep."""
    real_add = ht.add

    def bad_add(x, y, *a, **k):
        return real_add(x, y, *a, **k) * 1.001

    monkeypatch.setattr(ht, "add", bad_add)
    # rebuild the spec closure against the patched symbol
    spec = SPECS["add"]
    caught = 0
    for i in range(8):
        try:
            b_rng = np.random.default_rng([zlib.crc32(b"add"), i])
            h, a = _draw_input(b_rng, spec, False, "f")
            if a.size == 0:
                continue
            b = _second_operand(b_rng, a, "like")
            _check(bad_add(h, ht.array(b, split=h.split)), a + b,
                   _tolkw(spec, "f", False), spec, "plant")
        except AssertionError:
            caught += 1
    assert caught > 0, "numeric plant survived every case"
