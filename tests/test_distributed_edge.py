"""
Edge-case distributed mechanics: ragged (non-evenly-shardable) shapes, negative-step
slicing, cross-split operand mixes, split round-trips, and the RNG's device-count
invariance — the failure modes SURVEY §7 flags as the hard parts ((a) ragged
distributions, (b) distributed indexing, (e) dominant-operand semantics).
"""

import numpy as np
import pytest

import heat_tpu as ht

RNG = np.random.default_rng(7)
# 11 and 13 are coprime with the 8-device mesh: every split is ragged
R = RNG.normal(size=(11, 13)).astype(np.float32)
S = RNG.normal(size=(11, 13)).astype(np.float32)


@pytest.mark.parametrize("split", [None, 0, 1])
def test_ragged_binary_and_reduce(split):
    a = ht.array(R, split=split)
    b = ht.array(S, split=split)
    # atol: the fused a*b+a kernel contracts to an FMA (single rounding,
    # doc/fusion_notes.md), so a cancellation element can sit ~2 ulp of the
    # PRODUCT away from numpy's double-rounded reference — an absolute-scale
    # effect, not a relative one
    np.testing.assert_allclose((a * b + a).numpy(), R * S + R, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ht.sum(a, axis=0).numpy(), R.sum(0), rtol=1e-5)
    np.testing.assert_allclose(ht.sum(a, axis=1).numpy(), R.sum(1), rtol=1e-5)
    assert a.shape == (11, 13) and a.split == split


@pytest.mark.parametrize("sa", [None, 0, 1])
@pytest.mark.parametrize("sb", [None, 0, 1])
def test_cross_split_binary(sa, sb):
    """Dominant-operand distribution matching (reference _operations.py:57-165)."""
    a = ht.array(R, split=sa)
    b = ht.array(S, split=sb)
    out = a + b
    np.testing.assert_allclose(out.numpy(), R + S, rtol=1e-6)


@pytest.mark.parametrize("split", [0, 1])
def test_ragged_resplit_roundtrip(split):
    a = ht.array(R, split=split)
    other = 1 - split
    b = ht.resplit(a, other)
    assert b.split == other
    np.testing.assert_allclose(b.numpy(), R)
    c = ht.resplit(b, None)
    assert c.split is None
    np.testing.assert_allclose(c.numpy(), R)
    d = ht.resplit(c, split)
    assert d.split == split
    np.testing.assert_allclose(d.numpy(), R)


@pytest.mark.parametrize("split", [None, 0, 1])
def test_negative_step_slicing(split):
    a = ht.array(R, split=split)
    np.testing.assert_allclose(a[::-1].numpy(), R[::-1])
    np.testing.assert_allclose(a[::-2, ::-1].numpy(), R[::-2, ::-1])
    np.testing.assert_allclose(a[8:2:-2, 1:11:3].numpy(), R[8:2:-2, 1:11:3])


@pytest.mark.parametrize("split", [None, 0, 1])
def test_getitem_with_dndarray_index(split):
    a = ht.array(R, split=split)
    idx_np = np.array([7, 0, 3, 3, 10])
    idx = ht.array(idx_np, split=0)
    np.testing.assert_allclose(a[idx].numpy(), R[idx_np])


@pytest.mark.parametrize("split", [None, 0, 1])
def test_setitem_with_array_value(split):
    a = ht.array(R, split=split)
    a_np = R.copy()
    val = np.full((3, 13), 2.5, np.float32)
    a[2:5] = ht.array(val, split=split)
    a_np[2:5] = val
    np.testing.assert_allclose(a.numpy(), a_np)
    a[:, 1] = 0.0
    a_np[:, 1] = 0.0
    np.testing.assert_allclose(a.numpy(), a_np)


@pytest.mark.parametrize("split", [None, 0, 1])
def test_boolean_mask_getitem(split):
    a = ht.array(R, split=split)
    mask_np = R[:, 0] > 0
    got = a[ht.array(mask_np, split=0 if split == 0 else None)]
    np.testing.assert_allclose(got.numpy(), R[mask_np])


def test_scalar_broadcast_ops_on_ragged():
    a = ht.array(R, split=0)
    np.testing.assert_allclose((2.0 * a - 1.0).numpy(), 2.0 * R - 1.0, rtol=1e-6)
    row = ht.array(R[0], split=None)
    np.testing.assert_allclose((a - row).numpy(), R - R[0], rtol=1e-6)


def test_concat_mixed_splits_ragged():
    a = ht.array(R, split=0)
    b = ht.array(S, split=1)
    np.testing.assert_allclose(
        ht.concatenate([a, b], axis=0).numpy(), np.concatenate([R, S], 0), rtol=1e-6
    )


@pytest.mark.parametrize("fn", ["rand", "randn"])
def test_rng_split_invariance(fn):
    """Counter-based RNG: the stream depends only on the global shape and seed, not
    on how the result is split (reference random.py:55-202 contract)."""
    draws = {}
    for split in (None, 0, 1):
        ht.random.seed(42)
        draws[split] = getattr(ht.random, fn)(9, 10, split=split).numpy()
    np.testing.assert_array_equal(draws[None], draws[0])
    np.testing.assert_array_equal(draws[None], draws[1])


def test_randint_bounds_and_invariance():
    ht.random.seed(3)
    a = ht.random.randint(5, 17, size=(100,), split=0)
    arr = a.numpy()
    assert arr.min() >= 5 and arr.max() < 17
    ht.random.seed(3)
    b = ht.random.randint(5, 17, size=(100,), split=None)
    np.testing.assert_array_equal(arr, b.numpy())


def test_randperm_permutation():
    ht.random.seed(0)
    p = ht.random.randperm(50, split=0).numpy()
    assert sorted(p.tolist()) == list(range(50))


@pytest.mark.parametrize("split", [None, 0, 1])
def test_empty_slice_and_size_one(split):
    a = ht.array(R, split=split)
    assert a[3:3].shape[0] == 0
    one = a[4:5, 6:7]
    assert one.shape == (1, 1)
    np.testing.assert_allclose(one.numpy(), R[4:5, 6:7])


def test_is_split_adoption():
    """Factories with is_split adopt pre-distributed chunks (reference
    factories.py:150-433: gshape inferred by allreduce)."""
    full = np.arange(64, dtype=np.float32).reshape(16, 4)
    a = ht.array(full, is_split=0)
    assert a.shape[1] == 4
    got = a.numpy()
    assert got.shape[0] >= 16  # world of 1 controller: adopted as the global rows
    np.testing.assert_allclose(got[:16], full)


@pytest.mark.parametrize("split", [0, 1])
def test_ragged_matmul(split):
    a = ht.array(R, split=split)
    b = ht.array(S.T.copy(), split=split)
    np.testing.assert_allclose(ht.matmul(a, b).numpy(), R @ S.T, rtol=1e-4)


def test_float64_gate_and_int_promotion():
    a = ht.array(np.array([1, 2, 3], np.int32))
    b = ht.array(np.array([0.5, 1.5, 2.5], np.float32))
    out = a + b
    assert out.dtype == ht.float32
    np.testing.assert_allclose(out.numpy(), [1.5, 3.5, 5.5])
