"""
Halo-exchange contract tests (reference heat/core/dndarray.py:360-446):
``get_halo(h)`` must deliver each shard its NEIGHBORS' boundary slabs — shard
i's ``halo_prev`` is shard i-1's last h split-rows, ``halo_next`` is shard
i+1's first h rows, outer boundaries zero (the reference's per-rank ``None``) —
and ``array_with_halos`` stacks ``[prev; local; next]`` per shard.
"""

import numpy as np
import pytest

import jax
from heat_tpu.core import _compat

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication


def _comm(p=None):
    devs = jax.devices()
    if p is None:
        p = len(devs)
    if len(devs) < p or p < 2:
        pytest.skip("needs a multi-device mesh")
    return MeshCommunication(devices=devs[:p]), p


@pytest.mark.parametrize("h", [1, 2])
def test_halo_neighbor_contract_split0(h):
    comm, p = _comm()
    c = 4
    a = np.arange(p * c * 3, dtype=np.float32).reshape(p * c, 3)
    x = ht.array(a, split=0, comm=comm)
    x.get_halo(h)
    hp = np.asarray(x.halo_prev)
    hn = np.asarray(x.halo_next)
    assert hp.shape == (p * h, 3) and hn.shape == (p * h, 3)
    for i in range(p):
        want_prev = a[i * c - h : i * c] if i > 0 else np.zeros((h, 3), np.float32)
        np.testing.assert_array_equal(hp[i * h : (i + 1) * h], want_prev)
        want_next = (
            a[(i + 1) * c : (i + 1) * c + h] if i < p - 1 else np.zeros((h, 3), np.float32)
        )
        np.testing.assert_array_equal(hn[i * h : (i + 1) * h], want_next)
    awh = np.asarray(x.array_with_halos)
    assert awh.shape == (p, c + 2 * h, 3)
    for i in range(p):
        np.testing.assert_array_equal(awh[i, h : h + c], a[i * c : (i + 1) * c])
    # the stacked blocks stay sharded — one block per device
    assert len(x.array_with_halos.addressable_shards) == p


def test_halo_split1():
    comm, p = _comm()
    c = 3
    a = np.arange(2 * p * c, dtype=np.float32).reshape(2, p * c)
    x = ht.array(a, split=1, comm=comm)
    x.get_halo(1)
    hp = np.asarray(x.halo_prev)  # (2, p)
    assert hp.shape == (2, p)
    for i in range(1, p):
        np.testing.assert_array_equal(hp[:, i], a[:, i * c - 1])
    np.testing.assert_array_equal(hp[:, 0], np.zeros(2, np.float32))
    awh = np.asarray(x.array_with_halos)  # (p, c+2, 2): split axis moved to pos 1
    assert awh.shape == (p, c + 2, 2)
    for i in range(p):
        np.testing.assert_array_equal(awh[i, 1 : 1 + c], a[:, i * c : (i + 1) * c].T)


def test_halo_ragged_zero_pads():
    comm, p = _comm()
    n = 3 * p + 1  # ragged: last shard mostly pad
    a = np.arange(n, dtype=np.float32) + 1.0  # nonzero everywhere
    x = ht.array(a, split=0, comm=comm)
    x.get_halo(1)
    hp = np.asarray(x.halo_prev)
    c = x.pshape[0] // p
    # shard p-1's prev slab is shard p-2's last PHYSICAL row — zero-filled if pad
    for i in range(1, p):
        src = i * c - 1
        want = a[src] if src < n else 0.0
        assert hp[i] == want


def test_halo_errors_and_noop():
    comm, p = _comm()
    x = ht.array(np.arange(p * 2, dtype=np.float32), split=0, comm=comm)
    with pytest.raises(TypeError):
        x.get_halo("x")
    with pytest.raises(ValueError):
        x.get_halo(-1)
    with pytest.raises(ValueError):
        x.get_halo(100)  # bigger than any chunk
    y = ht.array(np.arange(8, dtype=np.float32))  # not split
    y.get_halo(1)
    assert y.halo_prev is None and y.halo_next is None


def test_stencil_consumer_matches_serial():
    """The shipped pattern: per-shard Laplacian over array_with_halos equals the
    serial stencil (examples/stencil/demo_heat_equation.py)."""
    comm, p = _comm()
    n = p * 16
    rng = np.random.default_rng(0)
    a = rng.standard_normal(n).astype(np.float32)
    x = ht.array(a, split=0, comm=comm)
    x.get_halo(1)
    blocks = x.array_with_halos
    lap = blocks[:, :-2] - 2.0 * blocks[:, 1:-1] + blocks[:, 2:]
    got = np.asarray(lap).reshape(-1)
    want = np.zeros_like(a)
    want[1:-1] = a[:-2] - 2 * a[1:-1] + a[2:]
    # boundary blocks see zero halos; interior must match exactly
    np.testing.assert_allclose(got[1:-1], want[1:-1], rtol=1e-6)


def test_halo_caches_invalidate_on_mutation():
    """Mutating the array drops fetched halos; get_halo(0) clears them too."""
    comm, p = _comm()
    a = np.arange(p * 4, dtype=np.float32)
    x = ht.array(a, split=0, comm=comm)
    x.get_halo(1)
    assert x.halo_prev is not None
    x[0] = 99.0  # mutation invalidates
    assert x.halo_prev is None and x.halo_next is None
    np.testing.assert_array_equal(np.asarray(x.array_with_halos), np.asarray(x.larray))
    x.get_halo(1)
    stale = np.asarray(x.halo_next).copy()
    x.resplit_(None)
    assert x.halo_next is None  # resplit drops halos oriented to the old layout
    y = ht.array(a, split=0, comm=comm)
    y.get_halo(2)
    y.get_halo(0)  # explicit no-halo request clears previous fetch
    assert y.halo_prev is None and y.halo_next is None


def test_halo_exchange_is_collective_permute():
    comm, p = _comm()
    x = ht.array(np.arange(p * 8, dtype=np.float32), split=0, comm=comm)
    x.get_halo(1)  # builds + runs the exchange program (also warms the cache)
    # lower an identical exchange and inspect: neighbor slabs ride ppermute
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp

    def ex(blk):
        last = blk[-1:]
        out = jax.lax.ppermute(last, comm.axis_name, [(i, (i + 1) % p) for i in range(p)])
        return out

    t = (
        jax.jit(_compat.shard_map(ex, mesh=comm.mesh, in_specs=P(comm.axis_name),
                              out_specs=P(comm.axis_name), check_vma=False))
        .lower(x.parray)
        .compile()
        .as_text()
    )
    assert "collective-permute" in t
    assert "all-gather" not in t
