"""
Comm-edge matrix: systematic value-level coverage of every collective shim
over dtype x shape x op, mirroring the density of the reference's
``heat/core/tests/test_communication.py`` (2,482 LoC: per-collective test
families sweeping contiguous/non-contiguous buffers, counts/displacements,
every reduction op, and rank-boundary shapes).

The reference's edge families map onto this backend as:

* derived-datatype tests (strided/non-contiguous send buffers, reference
  test_communication.py throughout) -> non-contiguous *logical* inputs:
  transposed, stepped, and flipped views handed to the shims, which must
  produce the same values as their contiguous copies;
* counts/displacements (v-collectives) -> ragged axes riding the padded
  physical layout: prime lengths, lengths smaller than the mesh (zero-size
  shards), and 1-element chunks;
* the op x dtype product (MPI.SUM/PROD/MIN/MAX/LAND/LOR over the full dtype
  table, incl. the custom bf16/f16 ops of reference dp_optimizer.py:21-43)
  -> the ``_REDUCERS`` table over bf16/f16/f32/int8/int32/bool/complex64.

Every expectation is computed independently with numpy chunk arithmetic —
the shims are never compared against themselves. ``test_mutation_is_caught``
proves the harness has teeth: a deliberately mis-displaced Alltoallv and a
sign-flipped Allreduce must both fail the value checks.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication

from _accel import requires_complex


@pytest.fixture(scope="module")
def comm():
    return MeshCommunication(devices=jax.devices())


# ------------------------------------------------------------------ dtype table
# name -> (numpy-side dtype used to build data, jnp dtype handed to the shim)
DTYPES = {
    "f32": (np.float32, jnp.float32),
    "bf16": (np.float32, jnp.bfloat16),
    "f16": (np.float16, jnp.float16),
    "i8": (np.int8, jnp.int8),
    "i32": (np.int32, jnp.int32),
    "bool": (np.bool_, jnp.bool_),
    "c64": (np.complex64, jnp.complex64),
}

# comparison tolerance per dtype name (None = exact)
TOLS = {
    "f32": dict(rtol=1e-6, atol=1e-6),
    "bf16": dict(rtol=3e-2, atol=3e-2),
    "f16": dict(rtol=2e-3, atol=2e-3),
    "i8": None,
    "i32": None,
    "bool": None,
    "c64": dict(rtol=1e-6, atol=1e-6),
}

REDUCE_OPS = ("sum", "prod", "max", "min", "land", "lor")

# which reduction ops are exercised per dtype (complex has no ordering;
# land/lor are truthiness-based and defined for every dtype)
OPS_FOR = {
    "f32": REDUCE_OPS,
    "bf16": ("sum", "max", "min"),  # bf16 prod drifts past any honest bound
    "f16": ("sum", "prod", "max", "min"),
    "i8": REDUCE_OPS,
    "i32": REDUCE_OPS,
    "bool": ("land", "lor", "max", "min"),
    "c64": ("sum", "prod", "land", "lor"),
}


def _mk(shape, dname, seed=0):
    """Random data kept near 1 so p-fold products stay representable in every
    dtype; returns (numpy array, jnp array in the shim dtype)."""
    np_dt, j_dt = DTYPES[dname]
    rng = np.random.default_rng(seed)
    if dname == "bool":
        a = rng.integers(0, 2, size=shape).astype(np.bool_)
    elif dname in ("i8", "i32"):
        a = rng.integers(1, 4, size=shape).astype(np_dt)
    elif dname == "c64":
        a = (rng.uniform(0.5, 1.5, size=shape) + 1j * rng.uniform(-0.5, 0.5, size=shape)).astype(
            np_dt
        )
    else:
        a = rng.uniform(0.5, 1.5, size=shape).astype(np_dt)
    xj = jnp.asarray(a).astype(j_dt)
    # expectation math runs on the dtype-rounded values: bf16/f16 round on the
    # cast (read back through f32); exact dtypes keep their numpy type so
    # neutral-element expectations use the right iinfo
    if dname in ("bf16", "f16"):
        a = np.asarray(xj.astype(jnp.float32))
    return a, xj


def _chunks(a, p, axis):
    assert a.shape[axis] % p == 0
    return np.split(a, p, axis=axis)


def _np_reduce(chunks, op):
    if op == "sum":
        return np.add.reduce(chunks)
    if op == "prod":
        return np.multiply.reduce(chunks)
    if op == "max":
        return np.maximum.reduce(chunks)
    if op == "min":
        return np.minimum.reduce(chunks)
    if op == "land":
        return np.logical_and.reduce([c != 0 for c in chunks])
    if op == "lor":
        return np.logical_or.reduce([c != 0 for c in chunks])
    raise AssertionError(op)


def _check(got, expected, dname, op=None):
    got = np.asarray(
        got.astype(jnp.complex64) if dname == "c64" and op not in ("land", "lor") else got
    )
    if op in ("land", "lor"):
        assert got.dtype == np.bool_, f"logical reduce must return bool, got {got.dtype}"
        np.testing.assert_array_equal(got, expected)
        return
    if dname in ("bf16", "f16", "f32"):
        got = got.astype(np.float32)
    tol = TOLS[dname]
    if tol is None:
        np.testing.assert_array_equal(got, expected.astype(got.dtype))
    else:
        np.testing.assert_allclose(got, expected.astype(got.dtype), **tol)


def _skip_complex_off_cpu(dname):
    if dname == "c64":
        from _accel import COMPLEX_SUPPORTED

        if not COMPLEX_SUPPORTED:
            pytest.skip("backend has no complex support")


# ================================================================== Allreduce
@pytest.mark.parametrize("dname", list(DTYPES))
def test_allreduce_dtype_op_matrix(comm, dname):
    """Reference Allreduce op x dtype family (test_communication.py Allreduce
    tests + the custom bf16/f16 sum ops of dp_optimizer.py:21-43)."""
    _skip_complex_off_cpu(dname)
    p = comm.size
    a, xj = _mk((p * 2, 3), dname, seed=1)
    for op in OPS_FOR[dname]:
        expected = _np_reduce(_chunks(a, p, 0), op)
        got = comm.Allreduce(xj, op=op)
        assert tuple(got.shape) == (2, 3)
        _check(got, expected, dname, op)
        # Reduce is the same collective delivered at a root
        _check(comm.Reduce(xj, op=op, root=comm.size - 1), expected, dname, op)


@pytest.mark.parametrize("dname", ["f32", "i32", "bool"])
@pytest.mark.parametrize("rows_per_dev", [1, 2])
def test_allreduce_split1_and_one_element_chunks(comm, dname, rows_per_dev):
    """Chunks of a single element and reduction over a non-leading axis."""
    p = comm.size
    a, xj = _mk((3, p * rows_per_dev), dname, seed=2)
    for op in OPS_FOR[dname][:3]:
        expected = _np_reduce(_chunks(a, p, 1), op)
        got = comm.Allreduce(xj, op=op, split=1)
        assert tuple(got.shape) == (3, rows_per_dev)
        _check(got, expected, dname, op)


def test_allreduce_zero_size_chunks(comm):
    """A 0-length split axis shards into p empty chunks; the reduction is the
    empty chunk (reference zero-count collective edge)."""
    x = jnp.zeros((0, 4), jnp.float32)
    got = comm.Allreduce(x, op="sum")
    assert tuple(got.shape) == (0, 4)


def test_allreduce_3d_middle_split(comm):
    p = comm.size
    a, xj = _mk((2, p * 2, 3), "f32", seed=3)
    expected = _np_reduce(_chunks(a, p, 1), "sum")
    got = comm.Allreduce(xj, op="sum", split=1)
    assert tuple(got.shape) == (2, 2, 3)
    _check(got, expected, "f32", "sum")


def test_allreduce_unknown_op_raises(comm):
    with pytest.raises(ValueError, match="unknown reduction op"):
        comm.Allreduce(jnp.ones((comm.size, 2)), op="bogus")


# ================================================================ Scan/Exscan
@pytest.mark.parametrize("dname", ["f32", "f16", "i8", "i32", "bool"])
def test_scan_dtype_op_matrix(comm, dname):
    """Inclusive prefix over the chunk sequence: chunk i of the result is the
    reduce of chunks 0..i (reference Scan family)."""
    p = comm.size
    a, xj = _mk((p * 2, 3), dname, seed=4)
    chunks = _chunks(a, p, 0)
    for op in OPS_FOR[dname]:
        expected = np.concatenate(
            [_np_reduce(chunks[: i + 1], op) for i in range(p)], axis=0
        )
        got = comm.Scan(xj, op=op)
        assert tuple(got.shape) == tuple(a.shape)
        _check(got, expected, dname, op)


@pytest.mark.parametrize("dname", ["f32", "i32", "bool"])
def test_exscan_dtype_op_matrix(comm, dname):
    """Exclusive prefix: chunk 0 is the op's neutral element, chunk i the
    reduce of chunks 0..i-1 (reference Exscan family)."""
    p = comm.size
    a, xj = _mk((p * 2, 3), dname, seed=5)
    chunks = _chunks(a, p, 0)
    neutral = {
        "sum": np.zeros_like(chunks[0]),
        "prod": np.ones_like(chunks[0]),
        "max": np.full_like(chunks[0], _finfo_min(a.dtype)),
        "min": np.full_like(chunks[0], _finfo_max(a.dtype)),
        "land": np.ones(chunks[0].shape, np.bool_),
        "lor": np.zeros(chunks[0].shape, np.bool_),
    }
    for op in OPS_FOR[dname]:
        expected = np.concatenate(
            [neutral[op]] + [_np_reduce(chunks[: i + 1], op) for i in range(p - 1)],
            axis=0,
        )
        got = comm.Exscan(xj, op=op)
        assert tuple(got.shape) == tuple(a.shape)
        _check(got, expected, dname, op)


def _finfo_min(dt):
    if np.issubdtype(dt, np.floating):
        return np.finfo(dt).min
    if dt == np.bool_:
        return False
    return np.iinfo(dt).min


def _finfo_max(dt):
    if np.issubdtype(dt, np.floating):
        return np.finfo(dt).max
    if dt == np.bool_:
        return True
    return np.iinfo(dt).max


@pytest.mark.parametrize("op", ["sum", "prod"])
@pytest.mark.parametrize("dname", ["f32", "i32"])
def test_cum_along_split_matrix(comm, op, dname):
    """Cum = elementwise cumulative ALONG the split axis (the __cum_op
    transport, reference _operations.py:185-281)."""
    p = comm.size
    a, xj = _mk((p * 3, 2), dname, seed=6)
    expected = np.cumsum(a, axis=0) if op == "sum" else np.cumprod(a, axis=0)
    got = comm.Cum(xj, op=op)
    assert tuple(got.shape) == tuple(a.shape)
    _check(got, expected, dname, op)


def test_cum_rejects_non_cumulative_ops(comm):
    with pytest.raises(ValueError, match="'sum' or 'prod'"):
        comm.Cum(jnp.ones((comm.size, 2)), op="max")


# ===================================================================== Bcast
@pytest.mark.parametrize("dname", ["f32", "bf16", "i8", "bool", "c64"])
def test_bcast_roots_matrix(comm, dname):
    """Every device's chunk becomes the root's chunk; first, last, and a
    middle root (reference Bcast family, communication.py:689-747)."""
    _skip_complex_off_cpu(dname)
    p = comm.size
    a, xj = _mk((p * 2, 3), dname, seed=7)
    chunks = _chunks(a, p, 0)
    for root in {0, p // 2, p - 1}:
        expected = np.concatenate([chunks[root]] * p, axis=0)
        got = comm.Bcast(xj, root=root)
        assert tuple(got.shape) == tuple(a.shape)
        _check(got, expected, dname)


def test_bcast_split1_and_root_validation(comm):
    p = comm.size
    a, xj = _mk((2, p * 2), "f32", seed=8)
    chunks = _chunks(a, p, 1)
    got = comm.Bcast(xj, root=p - 1, split=1)
    _check(got, np.concatenate([chunks[p - 1]] * p, axis=1), "f32")
    for bad in (-1, p, p + 3):
        with pytest.raises(ValueError, match="root"):
            comm.Bcast(xj, root=bad, split=1)


# ================================================================== Ppermute
@pytest.mark.parametrize("dname", ["f32", "i32", "bool"])
def test_ppermute_shift_matrix(comm, dname):
    """Ring rotation of chunks (the Send/Recv ring analog): result chunk i is
    input chunk (i - shift) mod p, for forward, backward, and half-ring
    shifts."""
    p = comm.size
    a, xj = _mk((p * 2, 3), dname, seed=9)
    chunks = _chunks(a, p, 0)
    for shift in {1, -1, p // 2, p + 1}:
        expected = np.concatenate([chunks[(i - shift) % p] for i in range(p)], axis=0)
        got = comm.Ppermute(xj, shift=shift)
        assert tuple(got.shape) == tuple(a.shape)
        _check(got, expected, dname)


def test_ppermute_full_cycle_is_identity(comm):
    p = comm.size
    a, xj = _mk((p, 2), "f32", seed=10)
    got = xj
    for _ in range(p):
        got = comm.Ppermute(got, shift=1)
    _check(got, a, "f32")


# ============================================================ gather / scatter
@pytest.mark.parametrize("dname", list(DTYPES))
def test_allgather_gather_scatter_roundtrip(comm, dname):
    """Allgather/Gather replicate the logical array; Scatter re-partitions it;
    all are value-identities with different placements (reference
    Allgatherv/Scatterv families, communication.py:1002-1873)."""
    _skip_complex_off_cpu(dname)
    p = comm.size
    a, xj = _mk((p * 2, 3), dname, seed=11)
    for fn in (comm.Allgather, lambda x, split=0: comm.Gather(x, root=0, split=split)):
        got = fn(xj)
        assert tuple(got.shape) == tuple(a.shape)
        _check(got, a, dname)
    scat = comm.Scatter(xj, root=0)
    assert tuple(scat.shape) == tuple(a.shape)
    _check(scat, a, dname)
    if comm.is_distributed():
        # placement: the scatter result is genuinely sharded on axis 0
        shards = scat.addressable_shards
        assert len(shards) == p
        assert all(s.data.shape[0] == a.shape[0] // p for s in shards)


def _padded_rows(n, p):
    return -(-n // p) * p


@pytest.mark.parametrize("n", [13, 17, 1])
def test_v_variants_ragged_prime(comm, n):
    """Ragged counts: prime (or single-element) split axes that no mesh size
    divides — the v-collectives' counts/displacements job (reference
    counts_displs_shape, communication.py:211-240). Allgatherv/Gatherv return
    the *logical* array (pad sliced off); Scatterv returns the padded physical
    placement whose logical prefix is the data (the documented contract)."""
    p = comm.size
    a, xj = _mk((n, 3), "f32", seed=12)
    for fn in (comm.Allgatherv, lambda x, split=0: comm.Gatherv(x, root=0, split=split)):
        got = fn(xj)
        assert tuple(got.shape) == (n, 3)
        _check(got, a, "f32")
    scat = comm.Scatterv(xj, root=0)
    assert tuple(scat.shape) == (_padded_rows(n, p), 3)
    _check(scat[:n], a, "f32")
    if comm.is_distributed():
        shards = scat.addressable_shards
        assert len(shards) == p
        assert all(s.data.shape[0] == _padded_rows(n, p) // p for s in shards)


def test_v_variants_zero_size_shards(comm):
    """A split axis shorter than the mesh: tail devices own zero logical rows
    (pure pad). Values must survive the round trip exactly."""
    p = comm.size
    if p < 2:
        pytest.skip("needs a multi-device mesh")
    n = max(1, p - 1)  # at least one device ends up with no logical rows
    a, xj = _mk((n, 2), "i32", seed=13)
    _check(comm.Allgatherv(xj), a, "i32")
    _check(comm.Scatterv(xj)[:n], a, "i32")


def test_nonshardable_raises_for_nonv_shims(comm):
    """The non-v shims require even partition, exactly as the reference's
    fixed-count collectives require matching counts."""
    p = comm.size
    if p < 2:
        pytest.skip("needs a multi-device mesh")
    x = jnp.ones((p + 1, 2), jnp.float32)
    for call in (
        lambda: comm.Allreduce(x),
        lambda: comm.Scan(x),
        lambda: comm.Exscan(x),
        lambda: comm.Allgather(x),
        lambda: comm.Scatter(x),
        lambda: comm.Bcast(x),
        lambda: comm.Ppermute(x),
        lambda: comm.Cum(x),
    ):
        with pytest.raises(ValueError, match="does not partition evenly"):
            call()


def test_scalar_input_raises_everywhere(comm):
    x = jnp.float32(3.0)
    for call in (
        lambda: comm.Allreduce(x),
        lambda: comm.Allgatherv(x),
        lambda: comm.Scatterv(x),
        lambda: comm.Alltoall(x, 0, 1),
        lambda: comm.Alltoallv(x, 0, 1),
    ):
        with pytest.raises(ValueError, match="scalar"):
            call()


# =================================================================== Alltoall
@pytest.mark.parametrize("dname", ["f32", "i8", "bool"])
@pytest.mark.parametrize("axes", [(0, 1), (1, 0)])
def test_alltoall_axis_rotation(comm, dname, axes):
    """Alltoall re-chunks from concat_axis to split_axis — a logical identity
    whose *placement* moves (reference Alltoallw axis rotation,
    communication.py:1199-1475)."""
    p = comm.size
    sa, ca = axes
    a, xj = _mk((p * 2, p * 3), dname, seed=14)
    got = comm.Alltoall(xj, split_axis=sa, concat_axis=ca)
    assert tuple(got.shape) == tuple(a.shape)
    _check(got, a, dname)
    if comm.is_distributed():
        shards = got.addressable_shards
        assert len(shards) == p
        assert all(s.data.shape[sa] == a.shape[sa] // p for s in shards)
        assert all(s.data.shape[ca] == a.shape[ca] for s in shards)


def test_alltoall_3d_and_same_axis_raises(comm):
    p = comm.size
    a, xj = _mk((p * 2, 2, p * 2), "f32", seed=15)
    got = comm.Alltoall(xj, split_axis=2, concat_axis=0)
    _check(got, a, "f32")
    with pytest.raises(ValueError, match="must differ"):
        comm.Alltoall(xj, split_axis=1, concat_axis=1)
    with pytest.raises(ValueError, match="must differ"):
        comm.Alltoallv(xj, split_axis=1, concat_axis=1)


@pytest.mark.parametrize("shape", [(13, 6), (5, 7), (3, 11)])
def test_alltoallv_ragged_rotation(comm, shape):
    """Alltoallv accepts ragged axes on either side: the result is the padded
    physical placement on ``split_axis`` whose logical prefix is the data
    (per-rank counts/displacements ride the pad)."""
    p = comm.size
    a, xj = _mk(shape, "f32", seed=16)
    got = comm.Alltoallv(xj, split_axis=1, concat_axis=0)
    n1 = a.shape[1]
    exp_cols = n1 if n1 % p == 0 and a.shape[0] % p == 0 else _padded_rows(n1, p)
    assert tuple(got.shape) == (a.shape[0], exp_cols)
    _check(got[:, :n1], a, "f32")
    if comm.is_distributed():
        shards = got.addressable_shards
        assert len(shards) == p
        assert all(s.data.shape[1] == exp_cols // p for s in shards)


# ======================================================= non-contiguous inputs
def test_noncontiguous_views_match_contiguous(comm):
    """The reference builds derived MPI datatypes for strided buffers
    (communication.py:242-298); here the logical array abstraction must make
    a transposed / stepped / flipped view indistinguishable from its
    contiguous copy in every collective."""
    p = comm.size
    base = np.arange(p * 4 * 6, dtype=np.float32).reshape(p * 4, 6)
    views = {
        "transpose": (base.T, 1),  # split the (6, p*4) view on axis 1
        "stepped": (base[::2], 0),  # (p*2, 6) non-unit stride
        "flipped": (base[::-1], 0),
    }
    for name, (v, split) in views.items():
        contig = np.ascontiguousarray(v)
        for op in ("sum", "max"):
            got_v = comm.Allreduce(jnp.asarray(v), op=op, split=split)
            got_c = comm.Allreduce(jnp.asarray(contig), op=op, split=split)
            np.testing.assert_allclose(
                np.asarray(got_v), np.asarray(got_c), rtol=1e-6,
                err_msg=f"{name} view diverged from contiguous copy",
            )
        got_v = comm.Ppermute(jnp.asarray(v), shift=1, split=split)
        got_c = comm.Ppermute(jnp.asarray(contig), shift=1, split=split)
        np.testing.assert_array_equal(np.asarray(got_v), np.asarray(got_c))


def test_jnp_transposed_input(comm):
    """A lazily-transposed jnp array (XLA layout change, the closest analog of
    a strided device buffer) through Scan and Allgather."""
    p = comm.size
    a, xj = _mk((3, p * 2), "f32", seed=17)
    at, xt = a.T.copy(), jnp.transpose(xj)
    chunks = _chunks(at, p, 0)
    expected = np.concatenate([_np_reduce(chunks[: i + 1], "sum") for i in range(p)], 0)
    _check(comm.Scan(xt, op="sum"), expected, "f32", "sum")
    _check(comm.Allgather(xt), at, "f32")


# ====================================================================== Split
def test_split_subgroup_allreduce_values(comm):
    """Sub-communicator collectives see only the member devices' chunks
    (reference communicator Split + DASO groups, dp_optimizer.py:182-199)."""
    p = comm.size
    if p < 4 or p % 2:
        pytest.skip("needs an even mesh of >= 4 devices")
    sub = comm.Split(devices=list(range(p // 2)))
    assert sub.size == p // 2
    a = np.arange(p // 2 * 2 * 3, dtype=np.float32).reshape(p // 2 * 2, 3)
    expected = _np_reduce(_chunks(a, p // 2, 0), "sum")
    _check(sub.Allreduce(jnp.asarray(a), op="sum"), expected, "f32", "sum")


def test_split_validation_matrix(comm):
    p = comm.size
    with pytest.raises(ValueError, match="exactly one"):
        comm.Split()
    with pytest.raises(ValueError, match="exactly one"):
        comm.Split(devices=[0], color=[0] * p)
    with pytest.raises(ValueError, match="length"):
        comm.Split(color=[0] * (p + 1))  # wrong length at ANY mesh size
    if p >= 2:
        with pytest.raises(ValueError, match="duplicate"):
            comm.Split(devices=[0, 0])
        with pytest.raises(ValueError, match="out of range"):
            comm.Split(devices=[0, p + 5])


# ============================================================ mutation defense
def test_mutation_is_caught(comm, monkeypatch):
    """Prove the matrix has teeth (VERDICT r3 #3 done-criterion): seed two
    bugs — a wrong-displacement Alltoallv and a sign-flipped Allreduce — and
    assert the value checks actually fail."""
    p = comm.size
    if p < 2:
        pytest.skip("needs a multi-device mesh")

    # (a) displacement bug: Alltoallv's ragged path delivers the re-chunked
    # placement; shift the logical rows by one (an off-by-one displacement)
    real_placed = type(comm).placed

    def bad_placed(self, x, split):
        return real_placed(self, jnp.roll(x, 1, axis=split), split)

    monkeypatch.setattr(type(comm), "placed", bad_placed)
    a, xj = _mk((13, 4), "f32", seed=18)
    got = comm.Alltoallv(xj, split_axis=1, concat_axis=0)
    with pytest.raises(AssertionError):
        # compare the logical prefix — the displacement bug must fail VALUES,
        # not shapes
        np.testing.assert_allclose(np.asarray(got)[:, : a.shape[1]], a, rtol=1e-6)
    monkeypatch.undo()

    # (b) numeric bug: negate one chunk's contribution inside Allreduce
    real_allreduce = type(comm).Allreduce

    def bad_allreduce(self, x, op="sum", split=0):
        x = jnp.asarray(x)
        chunk = x.shape[split] // self.size
        sl = tuple(
            slice(0, chunk) if d == split else slice(None) for d in range(x.ndim)
        )
        x = x.at[sl].multiply(-1)
        return real_allreduce(self, x, op=op, split=split)

    monkeypatch.setattr(type(comm), "Allreduce", bad_allreduce)
    a, xj = _mk((p * 2, 3), "f32", seed=19)
    expected = _np_reduce(_chunks(a, p, 0), "sum")
    got = comm.Allreduce(xj, op="sum")
    with pytest.raises(AssertionError):
        np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-6)


# ================================================================ 1-D families
# 1-D buffers are MPI's native shape and the reference's most-tested case;
# they also hit XLA's most aggressive layout packing (lane-dim tiling).


@pytest.mark.parametrize("dname", ["f32", "bf16", "i32", "bool"])
def test_1d_allreduce_scan(comm, dname):
    p = comm.size
    a, xj = _mk((p * 4,), dname, seed=20)
    chunks = _chunks(a, p, 0)
    for op in OPS_FOR[dname][:2]:
        _check(comm.Allreduce(xj, op=op), _np_reduce(chunks, op), dname, op)
    op = OPS_FOR[dname][0]
    expected = np.concatenate([_np_reduce(chunks[: i + 1], op) for i in range(p)])
    _check(comm.Scan(xj, op=op), expected, dname, op)


@pytest.mark.parametrize("n_extra", [0, 1, 3])
def test_1d_ragged_gatherv(comm, n_extra):
    n = comm.size * 2 + n_extra
    a, xj = _mk((n,), "f32", seed=21)
    _check(comm.Allgatherv(xj), a, "f32")
    _check(comm.Scatterv(xj)[:n], a, "f32")


def test_1d_ppermute_and_bcast(comm):
    p = comm.size
    a, xj = _mk((p * 2,), "i32", seed=22)
    chunks = _chunks(a, p, 0)
    _check(
        comm.Ppermute(xj, shift=1),
        np.concatenate([chunks[(i - 1) % p] for i in range(p)]),
        "i32",
    )
    _check(comm.Bcast(xj, root=p - 1), np.concatenate([chunks[p - 1]] * p), "i32")


# ========================================================== cumulative dtypes
@pytest.mark.parametrize("dname", ["bf16", "f16", "i8"])
def test_cum_more_dtypes(comm, dname):
    """Cum across the low-precision table (the reference's custom bf16/f16
    MPI ops exist precisely because these dtypes cross the wire in training,
    dp_optimizer.py:21-43)."""
    p = comm.size
    a, xj = _mk((p * 2, 2), dname, seed=23)
    expected = np.cumsum(a, axis=0, dtype=np.float64 if dname != "i8" else np.int64)
    got = comm.Cum(xj, op="sum")
    _check(got, expected.astype(a.dtype), dname, "sum")


# ============================================================== compositions
# Round-trip identities — the cheapest way to catch displacement/offset bugs
# in any single collective, mirroring the reference's send-then-receive pairs.


def test_scatter_allgather_roundtrip(comm):
    p = comm.size
    a, xj = _mk((p * 3, 4), "f32", seed=24)
    _check(comm.Allgather(comm.Scatter(xj, root=0)), a, "f32")


def test_alltoall_there_and_back(comm):
    p = comm.size
    a, xj = _mk((p * 2, p * 2), "f32", seed=25)
    once = comm.Alltoall(xj, split_axis=1, concat_axis=0)
    back = comm.Alltoall(once, split_axis=0, concat_axis=1)
    _check(back, a, "f32")


def test_ppermute_inverse_shifts(comm):
    p = comm.size
    a, xj = _mk((p * 2, 3), "f32", seed=26)
    _check(comm.Ppermute(comm.Ppermute(xj, shift=1), shift=-1), a, "f32")


def test_scan_equals_exscan_combined_with_own_chunk(comm):
    """scan_i == op(exscan_i, chunk_i) — the defining relation between the two
    prefixes (reference Scan/Exscan contract)."""
    p = comm.size
    a, xj = _mk((p * 2, 3), "f32", seed=27)
    scan = np.asarray(comm.Scan(xj, op="sum"))
    exscan = np.asarray(comm.Exscan(xj, op="sum"))
    np.testing.assert_allclose(scan, exscan + a, rtol=1e-5)


def test_bcast_is_allreduce_of_onehot(comm):
    """Cross-validate Bcast against an independent psum formulation."""
    p = comm.size
    a, xj = _mk((p * 2, 3), "f32", seed=28)
    chunks = _chunks(a, p, 0)
    for root in (0, p - 1):
        got = np.asarray(comm.Bcast(xj, root=root))
        manual = np.concatenate([chunks[root]] * p, axis=0)
        np.testing.assert_allclose(got, manual, rtol=1e-6)


def test_allreduce_sum_equals_scan_last_chunk(comm):
    p = comm.size
    a, xj = _mk((p * 2, 3), "f32", seed=29)
    allred = np.asarray(comm.Allreduce(xj, op="sum"))
    scan_last = np.asarray(comm.Scan(xj, op="sum"))[-2:]
    np.testing.assert_allclose(allred, scan_last, rtol=1e-5)


# ======================================================== more edge families
@pytest.mark.parametrize("shape,split", [((2, 13, 3), 1), ((5, 2, 9), 2), ((11, 2, 2), 0)])
def test_v_variants_3d_ragged_any_axis(comm, shape, split):
    """Ragged middle/trailing axes of 3-D buffers through the v-collectives
    (the reference's counts/displs work for any split dim)."""
    a, xj = _mk(shape, "f32", seed=30)
    got = comm.Allgatherv(xj, split=split)
    assert tuple(got.shape) == tuple(shape)
    _check(got, a, "f32")
    scat = comm.Scatterv(xj, split=split)
    sl = tuple(slice(0, shape[d]) for d in range(3))
    _check(scat[sl], a, "f32")


@pytest.mark.parametrize("dname", ["bf16", "f16", "i32", "c64"])
def test_alltoall_dtype_sweep(comm, dname):
    """Axis rotation across the dtype table (the reference's Alltoallw runs on
    every derived datatype)."""
    _skip_complex_off_cpu(dname)
    p = comm.size
    a, xj = _mk((p * 2, p * 2), dname, seed=31)
    got = comm.Alltoall(xj, split_axis=1, concat_axis=0)
    assert tuple(got.shape) == tuple(a.shape)
    _check(got, a, dname)


def test_collective_cache_no_collisions(comm):
    """Interleave shapes, dtypes, ops, and splits through the same shims: the
    compiled-program cache must key every one distinctly (a collision returns
    a program built for the wrong geometry — exactly the bug class the
    reference's per-call derived datatypes cannot have)."""
    p = comm.size
    cases = []
    for seed, (shape, split) in enumerate(
        [((p, 2), 0), ((p * 2, 3), 0), ((2, p), 1), ((p, 2, 2), 0), ((4, p * 3), 1)]
    ):
        a, xj = _mk(shape, "f32", seed=40 + seed)
        cases.append((a, xj, split))
    for _ in range(2):  # second pass hits the cache
        for a, xj, split in cases:
            expected = _np_reduce(_chunks(a, p, split), "sum")
            _check(comm.Allreduce(xj, op="sum", split=split), expected, "f32", "sum")
            chunks = _chunks(a, p, split)
            exp_b = np.concatenate([chunks[0]] * p, axis=split)
            _check(comm.Bcast(xj, root=0, split=split), exp_b, "f32")


def test_ppermute_zero_shift_identity(comm):
    p = comm.size
    a, xj = _mk((p, 3), "f32", seed=50)
    _check(comm.Ppermute(xj, shift=0), a, "f32")
    _check(comm.Ppermute(xj, shift=p), a, "f32")  # full cycle normalizes to 0


def test_single_element_total(comm):
    """One element per device along split — the smallest legal collective."""
    p = comm.size
    a, xj = _mk((p, 1), "i32", seed=51)
    chunks = _chunks(a, p, 0)
    _check(comm.Allreduce(xj, op="max"), _np_reduce(chunks, "max"), "i32", "max")
    _check(comm.Bcast(xj, root=0), np.concatenate([chunks[0]] * p, axis=0), "i32")
    got = comm.Scan(xj, op="sum")
    expected = np.concatenate([_np_reduce(chunks[: i + 1], "sum") for i in range(p)], 0)
    _check(got, expected, "i32", "sum")


def test_exscan_f16_and_bf16_sum(comm):
    """Exclusive prefix in the wire dtypes of gradient compression."""
    p = comm.size
    for dname in ("f16", "bf16"):
        a, xj = _mk((p * 2, 2), dname, seed=52)
        chunks = _chunks(a, p, 0)
        expected = np.concatenate(
            [np.zeros_like(chunks[0])]
            + [_np_reduce(chunks[: i + 1], "sum") for i in range(p - 1)],
            axis=0,
        )
        _check(comm.Exscan(xj, op="sum"), expected, dname, "sum")


# ============================================== shim-vs-op cross-validation
# The op templates (__reduce_op / __cum_op) and the named shims must agree —
# two independent routes to the same collective (the reference funnels both
# through the same MPI call; here they are separate compiled programs).


def test_reduce_op_agrees_with_allreduce_shim(comm):
    p = comm.size
    a, xj = _mk((p * 2, 3), "f32", seed=60)
    h = ht.array(np.asarray(a), split=0)
    via_op = ht.sum(h, axis=0).numpy()
    via_shim = np.asarray(comm.Allreduce(xj, op="sum")).sum(axis=0)
    np.testing.assert_allclose(via_op, via_shim, rtol=1e-5)


def test_cum_op_agrees_with_cum_shim(comm):
    p = comm.size
    a, xj = _mk((p * 2, 3), "f32", seed=61)
    h = ht.array(np.asarray(a), split=0)
    via_op = ht.cumsum(h, axis=0).numpy()
    via_shim = np.asarray(comm.Cum(xj, op="sum"))
    np.testing.assert_allclose(via_op, via_shim, rtol=1e-5)


@pytest.mark.parametrize("dname", ["f16", "i8", "bool"])
def test_allgather_dtype_sweep(comm, dname):
    p = comm.size
    a, xj = _mk((p * 3, 2), dname, seed=62)
    got = comm.Allgather(xj)
    assert tuple(got.shape) == tuple(a.shape)
    _check(got, a, dname)
    got1 = comm.Allgather(jnp.transpose(xj), split=1)
    _check(got1, a.T, dname)
