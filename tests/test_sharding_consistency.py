"""
Physical-placement ↔ metadata consistency.

A DNDarray's ``split`` metadata promises a physical layout: ``split=k`` means the
backing ``jax.Array`` is partitioned along axis ``k`` over the mesh (replicated only
when the axis is not divisible by the mesh size — the documented graceful
degradation). If an op silently drops the sharding, the framework still computes
correct values but loses all parallelism — exactly the failure mode this suite
guards against, across a representative slice of the op surface (the reference has
no analog: its locality is structural, one torch tensor per MPI rank).
"""

import numpy as np
import pytest

import jax
from heat_tpu.core import _compat

import heat_tpu as ht
from heat_tpu.spatial import cdist


N_DEV = len(jax.devices())


def phys_split(d):
    """Infer the physically sharded axis of the backing array (None = replicated).
    Ragged arrays are judged by their PADDED physical form — the logical view is
    a slice whose sharding XLA may canonicalize away."""
    arr = d.parray if getattr(d, "is_padded", False) else d.larray
    sh = arr.sharding
    if hasattr(sh, "spec"):
        for i, s in enumerate(sh.spec):
            if s is not None:
                return i
        return None
    # GSPMD sharding (e.g. out of jnp.pad): infer from shard shapes
    local = arr.addressable_shards[0].data.shape
    if tuple(local) == tuple(arr.shape):
        return None
    for i, (g, l) in enumerate(zip(arr.shape, local)):
        if g != l:
            return i
    return None


def assert_consistent(d, label=""):
    if N_DEV == 1:
        # a single-device "sharding" is indistinguishable from replication; there
        # is no physical layout to hold the metadata to
        return
    ps = phys_split(d)
    if d.split is None:
        # replicated metadata must not claim a distributed layout it cannot use,
        # but a physically-sharded backing is harmless (extra locality); only the
        # reverse direction (promised split, replicated data on a divisible axis)
        # loses parallelism.
        return
    if ps == d.split:
        return
    if ps is None and d.shape[d.split] % N_DEV != 0:
        return  # documented ragged fallback
    raise AssertionError(
        f"{label}: split metadata {d.split} but physical sharding {ps} "
        f"(shape {d.shape}, {N_DEV} devices)"
    )


@pytest.fixture(scope="module")
def b():
    return ht.arange(64 * 32, dtype=ht.float32, split=0).reshape((64, 32))


def test_factories_sharded(b):
    assert_consistent(ht.ones((64, 32), split=0), "ones")
    assert_consistent(ht.zeros((64, 32), split=1), "zeros s1")
    assert_consistent(b, "arange.reshape")
    assert_consistent(ht.random.rand(64, 32, split=0), "random.rand")
    assert_consistent(ht.full((64, 8), 3.0, split=0), "full")


def test_elementwise_and_binary(b):
    a = ht.ones((64, 32), split=0)
    c = ht.ones((64, 32), split=1)
    for label, r in [
        ("add", a + b),
        ("add scalar", a + 3),
        ("exp", ht.exp(a)),
        ("pow", b**2),
        ("clip", ht.clip(b, 10, 50)),
        ("where", ht.where(b > 100, b, -b)),
        ("mixed splits", a + c),
        ("cast", ht.float16(b)),
    ]:
        assert_consistent(r, label)
    import jax

    with _compat.enable_x64(True):  # the f64 cast, genuinely 64-bit
        assert_consistent(ht.float64(b), "cast f64")


def test_reductions_keep_surviving_split(b):
    for label, r in [
        ("sum ax1", ht.sum(b, axis=1)),
        ("mean ax1", ht.mean(b, axis=1)),
        ("std ax1", ht.std(b, axis=1)),
        ("median ax1", ht.median(b, axis=1)),
        ("percentile ax1", ht.percentile(b, 50.0, axis=1)),
        ("argmax ax1", ht.argmax(b, axis=1)),
        ("cumsum ax0", ht.cumsum(b, axis=0)),
    ]:
        assert_consistent(r, label)


def test_percentile_split_metadata(b):
    # axis=1 reduction on a split=0 array: result stays split=0
    r = ht.percentile(b, 50.0, axis=1)
    assert r.split == 0
    # vector q prepends an axis: surviving split shifts to 1
    rq = ht.percentile(b, ht.array([25.0, 50.0, 75.0]), axis=1)
    assert rq.shape == (3, 64)
    assert rq.split == 1
    assert_consistent(rq, "percentile vector q")
    # reducing the split axis drops the split
    assert ht.percentile(b, 50.0, axis=0).split is None
    # tuple axes containing the split axis drop it (regression: tuple<int compare)
    rt = ht.percentile(b, 50.0, axis=(0, 1))
    assert rt.split is None
    np.testing.assert_allclose(
        rt.numpy(), np.percentile(b.numpy(), 50.0, axis=(0, 1)), rtol=1e-6
    )
    np.testing.assert_allclose(
        ht.percentile(b, 30.0, axis=1).numpy(),
        np.percentile(b.numpy(), 30.0, axis=1).astype(np.float32),
        rtol=1e-6,
    )


def test_manipulations(b):
    a = ht.ones((64, 32), split=0)
    for label, r in [
        ("sort ax1", ht.sort(b, axis=1)[0]),
        ("sort ax0 (split)", ht.sort(b, axis=0)[0]),
        ("concatenate", ht.concatenate([a, b], axis=0)),
        ("transpose", b.T),
        ("reshape", b.reshape((32, 64))),
        ("roll", ht.roll(b, 3, axis=0)),
        ("flip", ht.flip(b, axis=0)),
        ("pad", ht.pad(b, ((1, 1), (0, 0)))),
        ("stack", ht.stack([b, b], axis=1)),
        ("repeat ax1", ht.repeat(b, 2, axis=1)),
        ("expand_dims", ht.expand_dims(b, 1)),
        ("triu", ht.triu(b)),
        ("getitem cols", b[:, :16]),
    ]:
        assert_consistent(r, label)


def test_linalg_and_ml():
    x = ht.random.randn(64, 8, split=0)
    assert_consistent(ht.matmul(x, ht.ones((8, 16))), "matmul s0xNone")
    q, r = ht.linalg.qr(x)
    assert_consistent(q, "qr Q")
    assert_consistent(cdist(x, x), "cdist")


@pytest.mark.parametrize("n", [32, 13])
def test_round3_ops_stay_sharded(n):
    # the ops that gained distributed formulations in round 3 must return
    # PHYSICALLY sharded results where their metadata promises a split
    if N_DEV < 2:
        pytest.skip("needs a multi-device mesh")
    rng = np.random.default_rng(55)
    a = ht.array(rng.normal(size=(n, 4)).astype(np.float32), split=0)

    c = ht.cumsum(a, axis=0)
    assert c.split == 0 and phys_split(c) == 0

    v, i = ht.sort(a, axis=0)
    assert v.split == 0 and phys_split(v) == 0
    assert i.split == 0 and phys_split(i) == 0

    idx = np.arange(n) % (n - 1)
    g = a[idx, np.arange(n) % 4]  # multi-advanced keys, result length n
    assert g.split == 0 and phys_split(g) == 0

    ls = ht.linspace(0.0, 1.0, n, split=0)
    assert phys_split(ls) == 0

    r = ht.random.randint(0, 9, (n,), split=0)
    assert phys_split(r) == 0

    h = ht.ones((n, 4), split=0, dtype=ht.bfloat16)
    assert phys_split(h) == 0
