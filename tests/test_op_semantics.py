"""
Operator-semantics families: the argument conventions that differ between
implementations and therefore need pinning — sign of mod vs fmod, floordiv on
negatives, diff's prepend/append, round's half-even ties, clip forms, modf's
pair, allclose/isclose NaN handling, `equal`'s scalar-AND contract (reference
heat/core/tests/{test_arithmetics, test_rounding, test_logical,
test_relational}.py families). numpy is the oracle throughout, at every split.
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0]


# ----------------------------------------------------------- mod / fmod signs
@pytest.mark.parametrize("split", SPLITS)
def test_mod_follows_divisor_sign(split):
    """mod/remainder: numpy semantics (result has the divisor's sign);
    fmod: C semantics (result has the dividend's sign) — the reference keeps
    both (arithmetics.py mod/fmod/remainder)."""
    a = np.array([7, -7, 7, -7, 0, 5], np.float32)
    b = np.array([3, 3, -3, -3, 3, -2], np.float32)
    ha, hb = ht.array(a, split=split), ht.array(b, split=split)
    np.testing.assert_allclose(ht.mod(ha, hb).numpy(), np.mod(a, b), rtol=1e-6)
    np.testing.assert_allclose(ht.remainder(ha, hb).numpy(), np.remainder(a, b), rtol=1e-6)
    np.testing.assert_allclose(ht.fmod(ha, hb).numpy(), np.fmod(a, b), rtol=1e-6)


@pytest.mark.parametrize("split", SPLITS)
def test_floordiv_negatives(split):
    a = np.array([7, -7, 7, -7, 1], np.float32)
    b = np.array([2, 2, -2, -2, 3], np.float32)
    ha, hb = ht.array(a, split=split), ht.array(b, split=split)
    np.testing.assert_allclose(ht.floordiv(ha, hb).numpy(), np.floor_divide(a, b), rtol=1e-6)
    np.testing.assert_allclose((ha // hb).numpy(), a // b, rtol=1e-6)


def test_integer_mod_matches_numpy():
    a = np.array([7, -7, 7, -7], np.int32)
    b = np.array([3, 3, -3, -3], np.int32)
    np.testing.assert_array_equal(
        ht.mod(ht.array(a, split=0), ht.array(b, split=0)).numpy(), np.mod(a, b)
    )


# ------------------------------------------------------------------- diff
@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("n", [1, 2, 3])
def test_diff_orders(split, n):
    """Higher-order diffs along the split axis cross shard boundaries — the
    reference sends boundary rows between neighbors (arithmetics.py diff)."""
    a = np.cumsum(np.arange(16, dtype=np.float32) % 5).reshape(8, 2)
    h = ht.array(a, split=split)
    np.testing.assert_allclose(ht.diff(h, n=n, axis=0).numpy(), np.diff(a, n=n, axis=0), rtol=1e-5)


@pytest.mark.parametrize("split", SPLITS)
def test_diff_prepend_append(split):
    a = np.arange(12, dtype=np.float32).reshape(6, 2) ** 2
    h = ht.array(a, split=split)
    np.testing.assert_allclose(
        ht.diff(h, axis=0, prepend=0).numpy(), np.diff(a, axis=0, prepend=0), rtol=1e-6
    )
    app = np.full((1, 2), 7.0, np.float32)
    np.testing.assert_allclose(
        ht.diff(h, axis=0, append=app).numpy(), np.diff(a, axis=0, append=app), rtol=1e-6
    )


# ---------------------------------------------------------------- rounding
@pytest.mark.parametrize("split", SPLITS)
def test_round_half_even_and_decimals(split):
    a = np.array([0.5, 1.5, 2.5, -0.5, -1.5, 2.675, -2.675, 3.14159], np.float32)
    h = ht.array(a, split=split)
    np.testing.assert_allclose(ht.round(h).numpy(), np.round(a), rtol=1e-6)
    np.testing.assert_allclose(ht.round(h, 2).numpy(), np.round(a, 2), atol=1e-6)


@pytest.mark.parametrize("split", SPLITS)
def test_clip_forms(split):
    a = np.linspace(-5, 5, 12).astype(np.float32)
    h = ht.array(a, split=split)
    np.testing.assert_allclose(ht.clip(h, -2, 2).numpy(), np.clip(a, -2, 2), rtol=1e-6)
    lo = np.full_like(a, -1.0)
    np.testing.assert_allclose(
        ht.clip(h, ht.array(lo, split=split), 3).numpy(), np.clip(a, lo, 3), rtol=1e-6
    )


@pytest.mark.parametrize("split", SPLITS)
def test_modf_pair(split):
    a = np.array([1.75, -1.75, 0.0, 3.5, -0.25], np.float32)
    h = ht.array(a, split=split)
    frac, integ = ht.modf(h)
    nf, ni = np.modf(a)
    np.testing.assert_allclose(frac.numpy(), nf, rtol=1e-6)
    np.testing.assert_allclose(integ.numpy(), ni, rtol=1e-6)


@pytest.mark.parametrize("split", SPLITS)
def test_trunc_floor_ceil_negatives(split):
    a = np.array([1.7, -1.7, 2.5, -2.5, 0.0], np.float32)
    h = ht.array(a, split=split)
    np.testing.assert_array_equal(ht.trunc(h).numpy(), np.trunc(a))
    np.testing.assert_array_equal(ht.floor(h).numpy(), np.floor(a))
    np.testing.assert_array_equal(ht.ceil(h).numpy(), np.ceil(a))
    np.testing.assert_array_equal(ht.sign(h).numpy(), np.sign(a))


# ------------------------------------------------------- allclose / isclose
@pytest.mark.parametrize("split", SPLITS)
def test_isclose_nan_handling(split):
    a = np.array([1.0, np.nan, np.inf, 1.0], np.float32)
    b = np.array([1.0 + 1e-9, np.nan, np.inf, 2.0], np.float32)
    ha, hb = ht.array(a, split=split), ht.array(b, split=split)
    np.testing.assert_array_equal(ht.isclose(ha, hb).numpy(), np.isclose(a, b))
    np.testing.assert_array_equal(
        ht.isclose(ha, hb, equal_nan=True).numpy(), np.isclose(a, b, equal_nan=True)
    )
    assert ht.allclose(ha, hb) is False
    assert ht.allclose(ha, ha, equal_nan=True) is True
    assert ht.allclose(ha, ha) is False  # nan != nan by default


@pytest.mark.parametrize("split", SPLITS)
def test_isclose_tolerances(split):
    a = np.array([1.0, 100.0], np.float32)
    b = np.array([1.001, 100.1], np.float32)
    ha, hb = ht.array(a, split=split), ht.array(b, split=split)
    np.testing.assert_array_equal(
        ht.isclose(ha, hb, rtol=1e-2).numpy(), np.isclose(a, b, rtol=1e-2)
    )
    np.testing.assert_array_equal(
        ht.isclose(ha, hb, rtol=0, atol=0.05).numpy(), np.isclose(a, b, rtol=0, atol=0.05)
    )


# ------------------------------------------------------------------ equal
@pytest.mark.parametrize("split", SPLITS)
def test_equal_scalar_and(split):
    """`ht.equal` returns ONE python bool — the global AND (the reference
    allreduces a scalar AND, relational.py equal)."""
    a = np.arange(12, dtype=np.float32)
    h = ht.array(a, split=split)
    assert ht.equal(h, ht.array(a.copy(), split=split)) is True
    b = a.copy()
    b[-1] += 1
    assert ht.equal(h, ht.array(b, split=split)) is False
    assert ht.equal(h, h) is True


# -------------------------------------------------------- nan propagation
@pytest.mark.parametrize("split", SPLITS)
def test_nan_propagation_reductions(split):
    a = np.array([1.0, np.nan, 3.0, 4.0], np.float32)
    h = ht.array(a, split=split)
    assert np.isnan(float(ht.sum(h).larray))
    assert np.isnan(float(ht.max(h).larray))
    np.testing.assert_allclose(float(ht.nansum(h).larray), np.nansum(a), rtol=1e-6)
    np.testing.assert_array_equal(ht.isnan(h).numpy(), np.isnan(a))
    np.testing.assert_array_equal(ht.isfinite(h).numpy(), np.isfinite(a))


# ---------------------------------------------------------- bitwise/shift
@pytest.mark.parametrize("split", SPLITS)
def test_bitwise_family(split):
    a = np.array([0b1100, 0b1010, 0b0001, 0b1111], np.int32)
    b = np.array([0b1010, 0b0110, 0b0001, 0b0000], np.int32)
    ha, hb = ht.array(a, split=split), ht.array(b, split=split)
    np.testing.assert_array_equal(ht.bitwise_and(ha, hb).numpy(), a & b)
    np.testing.assert_array_equal(ht.bitwise_or(ha, hb).numpy(), a | b)
    np.testing.assert_array_equal(ht.bitwise_xor(ha, hb).numpy(), a ^ b)
    np.testing.assert_array_equal(ht.invert(ha).numpy(), ~a)
    np.testing.assert_array_equal(ht.left_shift(ha, 2).numpy(), a << 2)
    np.testing.assert_array_equal(ht.right_shift(ha, 1).numpy(), a >> 1)
    with pytest.raises(TypeError):
        ht.bitwise_and(ht.array(a.astype(np.float32)), hb)


# ----------------------------------------------------------- pow semantics
@pytest.mark.parametrize("split", SPLITS)
def test_pow_edge_values(split):
    a = np.array([2.0, -2.0, 0.0, 4.0], np.float32)
    h = ht.array(a, split=split)
    np.testing.assert_allclose((h**2).numpy(), a**2, rtol=1e-6)
    np.testing.assert_allclose((h**0).numpy(), a**0, rtol=1e-6)
    np.testing.assert_allclose((2.0**h).numpy(), 2.0**a, rtol=1e-5)
    np.testing.assert_allclose(ht.pow(h, 0.5).numpy(), a**0.5, rtol=1e-5, equal_nan=True)


# ------------------------------------------- keepdim/keepdims normalization
@pytest.mark.parametrize("split", SPLITS)
def test_keepdims_spellings_everywhere(split):
    """Every reducer accepts BOTH the torch-style keepdim (the reference's
    spelling) and numpy's keepdims, with identical results — and std/var
    really keep the dim (r4 review: they silently dropped it)."""
    a = np.random.default_rng(0).standard_normal((6, 4)).astype(np.float32)
    h = ht.array(a, split=split)
    cases = [
        (ht.sum, np.sum, {}),
        (ht.prod, np.prod, {}),
        (ht.max, np.max, {}),
        (ht.min, np.min, {}),
        (ht.mean, np.mean, {}),
        (ht.any, np.any, {}),
        (ht.all, np.all, {}),
        (ht.std, lambda x, **kw: x.std(**kw), {}),
        (ht.var, lambda x, **kw: x.var(**kw), {}),
    ]
    for fn, nfn, extra in cases:
        for spelled in ({"keepdim": True}, {"keepdims": True}):
            got = fn(h, axis=0, **spelled, **extra)
            exp = nfn(a, axis=0, keepdims=True)
            assert tuple(got.shape) == tuple(np.shape(exp)), (fn.__name__, spelled)
            np.testing.assert_allclose(
                got.numpy().astype(np.float64), np.asarray(exp, np.float64),
                rtol=1e-4, atol=1e-5, err_msg=f"{fn.__name__} {spelled}",
            )


def test_keepdims_conflict_raises():
    h = ht.ones((4, 3), split=0)
    with pytest.raises(ValueError, match="conflicting"):
        ht.sum(h, axis=0, keepdim=True, keepdims=False)
    # mean historically collapsed keepdims=False to None and silently kept
    # dims (ADVICE r4 low): it must raise like the other reducers
    with pytest.raises(ValueError, match="conflicting"):
        ht.mean(h, axis=0, keepdim=True, keepdims=False)
    assert ht.mean(h, axis=0, keepdims=False).shape == (3,)
    assert ht.mean(h, axis=0, keepdims=True).shape == (1, 3)


def test_std_var_keepdims_split_metadata():
    """keepdims reductions over a non-split axis keep a VALID split index."""
    h = ht.ones((3, 8), split=1)
    r = ht.std(h, axis=0, keepdims=True)
    assert tuple(r.shape) == (1, 8)
    assert r.split in (None, 1)
    if r.split is not None:
        assert 0 <= r.split < r.ndim


# ------------------------------------------------------ trig / exponential
@pytest.mark.parametrize("split", SPLITS)
def test_atan2_quadrants(split):
    """All four quadrants plus the axes — the sign conventions that separate
    atan2 from atan (reference trigonometrics.py atan2)."""
    y = np.array([1.0, 1.0, -1.0, -1.0, 0.0, 1.0, 0.0, -0.0], np.float32)
    x = np.array([1.0, -1.0, 1.0, -1.0, 1.0, 0.0, -1.0, -1.0], np.float32)
    hy, hx = ht.array(y, split=split), ht.array(x, split=split)
    np.testing.assert_allclose(ht.atan2(hy, hx).numpy(), np.arctan2(y, x), rtol=1e-6)


@pytest.mark.parametrize("split", SPLITS)
def test_degrees_radians_roundtrip(split):
    a = np.array([0.0, 90.0, -180.0, 270.0, 45.5], np.float32)
    h = ht.array(a, split=split)
    np.testing.assert_allclose(ht.deg2rad(h).numpy(), np.deg2rad(a), rtol=1e-6)
    np.testing.assert_allclose(ht.radians(h).numpy(), np.radians(a), rtol=1e-6)
    back = ht.rad2deg(ht.deg2rad(h))
    np.testing.assert_allclose(back.numpy(), a, rtol=1e-5)
    np.testing.assert_allclose(ht.degrees(ht.radians(h)).numpy(), a, rtol=1e-5)


@pytest.mark.parametrize("split", SPLITS)
def test_logaddexp_extremes(split):
    """-inf identities and the overflow-free property logaddexp exists for."""
    a = np.array([0.0, -np.inf, 50.0, -50.0], np.float32)
    b = np.array([0.0, 3.0, 50.0, 50.0], np.float32)
    ha, hb = ht.array(a, split=split), ht.array(b, split=split)
    np.testing.assert_allclose(
        ht.logaddexp(ha, hb).numpy(), np.logaddexp(a, b), rtol=1e-5
    )
    np.testing.assert_allclose(
        ht.logaddexp2(ha, hb).numpy(), np.logaddexp2(a, b), rtol=1e-5
    )


@pytest.mark.parametrize("split", SPLITS)
def test_expm1_log1p_small_x_precision(split):
    """The tiny-x regime is these functions' reason to exist: plain
    exp(x)-1 / log(1+x) would round to 0 in f32."""
    a = np.array([1e-7, -1e-7, 1e-6, 0.0], np.float32)
    h = ht.array(a, split=split)
    np.testing.assert_allclose(ht.expm1(h).numpy(), np.expm1(a), rtol=1e-6)
    np.testing.assert_allclose(ht.log1p(h).numpy(), np.log1p(a), rtol=1e-6)
    got = ht.expm1(h).numpy()
    assert got[0] != 0.0 and got[1] != 0.0  # not the naive cancellation


@pytest.mark.parametrize("split", SPLITS)
def test_log_domain_edges(split):
    a = np.array([1.0, 0.0, -1.0, np.inf], np.float32)
    h = ht.array(a, split=split)
    with np.errstate(divide="ignore", invalid="ignore"):
        np.testing.assert_allclose(
            ht.log(h).numpy(), np.log(a), rtol=1e-6, equal_nan=True
        )
        np.testing.assert_allclose(
            ht.sqrt(h).numpy(), np.sqrt(a), rtol=1e-6, equal_nan=True
        )


@pytest.mark.parametrize("split", SPLITS)
def test_hyperbolic_inverses_domain(split):
    from _accel import tol

    kw = tol("arctanh")  # VPU polynomial approximations on real accelerators
    a = np.array([0.0, 0.5, -0.5, 0.99], np.float32)
    h = ht.array(a, split=split)
    np.testing.assert_allclose(ht.arctanh(h).numpy(), np.arctanh(a), **kw)
    b = np.array([1.0, 1.5, 10.0], np.float32)  # arccosh domain starts at 1
    np.testing.assert_allclose(
        ht.arccosh(ht.array(b, split=split)).numpy(), np.arccosh(b), **kw
    )
    np.testing.assert_allclose(ht.arcsinh(h).numpy(), np.arcsinh(a), **kw)
