"""
Test harness configuration.

Mirrors the reference's CI strategy (Jenkinsfile:24-31: the whole suite under
mpirun -n 1..8) in single-controller form: the suite runs once over a *forced
8-device CPU mesh* (`xla_force_host_platform_device_count`), so every test that
builds a split DNDarray exercises real multi-device sharding and the collectives XLA
emits for it. The counter-based RNG keeps results device-count-invariant.
"""

import os

# device count of the virtual mesh (the reference's mpirun -n {1..8} matrix maps
# to HEAT_TPU_TEST_DEVICES ∈ {1,2,4,8}; default 8)
_n = os.environ.get("HEAT_TPU_TEST_DEVICES", "8")

# must happen before any JAX backend initialisation
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={_n}"
)

import jax

# HEAT_TPU_TEST_REAL_DEVICE=1 runs the suite on whatever accelerator JAX finds
# (e.g. the one real TPU chip) instead of the virtual CPU mesh — used to validate
# the op surface against real-hardware numerics/lowering. Default: CPU mesh.
if os.environ.get("HEAT_TPU_TEST_REAL_DEVICE") != "1":
    jax.config.update("jax_platforms", "cpu")
