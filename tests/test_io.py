"""Tests for I/O (parity model: reference heat/core/tests/test_io.py)."""

import os

import numpy as np
import pytest

import heat_tpu as ht


def test_supports():
    assert ht.supports_hdf5()  # h5py is baked in
    assert isinstance(ht.supports_netcdf(), bool)


def test_hdf5_roundtrip(tmp_path):
    path = str(tmp_path / "data.h5")
    data = np.arange(64.0, dtype=np.float32).reshape(16, 4)
    a = ht.array(data, split=0)
    ht.save_hdf5(a, path, "mydata")
    b = ht.load_hdf5(path, "mydata", split=0)
    np.testing.assert_array_equal(b.numpy(), data)
    assert b.split == 0
    c = ht.load(path, "mydata")
    np.testing.assert_array_equal(c.numpy(), data)
    with pytest.raises(TypeError):
        ht.load_hdf5(1, "x")
    with pytest.raises(TypeError):
        ht.load_hdf5(path, 1)
    with pytest.raises(TypeError):
        ht.save_hdf5("no", path, "x")


def test_save_load_dispatch(tmp_path):
    a = ht.ones((4, 2))
    h5 = str(tmp_path / "a.h5")
    ht.save(a, h5, "data")
    np.testing.assert_array_equal(ht.load(h5, "data").numpy(), a.numpy())
    with pytest.raises(ValueError):
        ht.save(a, str(tmp_path / "a.xyz"))
    with pytest.raises(ValueError):
        ht.load(str(tmp_path / "a.xyz"))
    with pytest.raises(TypeError):
        ht.load(17)


def test_csv_roundtrip(tmp_path):
    path = str(tmp_path / "data.csv")
    data = np.arange(12.0, dtype=np.float32).reshape(4, 3)
    ht.save_csv(ht.array(data), path)
    b = ht.load_csv(path, split=0)
    np.testing.assert_allclose(b.numpy(), data)
    # header lines and custom sep
    path2 = str(tmp_path / "data2.csv")
    ht.save_csv(ht.array(data), path2, header_lines="a;b;c", sep=";")
    c = ht.load_csv(path2, header_lines=1, sep=";")
    np.testing.assert_allclose(c.numpy(), data)
    with pytest.raises(TypeError):
        ht.load_csv(1)
    with pytest.raises(TypeError):
        ht.load_csv(path, sep=5)
    with pytest.raises(TypeError):
        ht.load_csv(path, header_lines="x")
    with pytest.raises(ValueError):
        ht.save_csv(ht.ones((2, 2, 2)), path)


def test_dndarray_save_method(tmp_path):
    path = str(tmp_path / "m.h5")
    a = ht.ones((4,))
    a.save(path, "d")
    np.testing.assert_array_equal(ht.load(path, "d").numpy(), a.numpy())


def test_hdf5_sharded_slab_load_and_save(tmp_path):
    if not ht.supports_hdf5():
        pytest.skip("h5py unavailable")
    path = str(tmp_path / "slab.h5")
    data = np.arange(16 * 6, dtype=np.float32).reshape(16, 6)
    ht.save_hdf5(ht.array(data, split=0), path, "d")
    np.testing.assert_array_equal(ht.load_hdf5(path, "d").numpy(), data)
    # slab-wise distributed load: one shard per device, correct layout + values
    x = ht.load_hdf5(path, "d", split=0)
    assert x.split == 0
    n_dev = len(x.comm.mesh.devices.ravel())
    if 16 % n_dev == 0:  # ragged counts fall back to replicated placement
        assert len(x.larray.addressable_shards) == n_dev
        shard0 = x.larray.addressable_shards[0]
        assert shard0.data.shape[0] == 16 // n_dev
    np.testing.assert_array_equal(x.numpy(), data)
    # split=1 slab load
    y = ht.load_hdf5(path, "d", split=1)
    np.testing.assert_array_equal(y.numpy(), data)
    # ragged (not divisible) falls back to replicated placement, keeps metadata
    path2 = str(tmp_path / "rag.h5")
    rag = np.arange(7 * 3, dtype=np.float32).reshape(7, 3)
    ht.save_hdf5(ht.array(rag), path2, "d")
    z = ht.load_hdf5(path2, "d", split=0)
    assert z.split == 0
    np.testing.assert_array_equal(z.numpy(), rag)


def test_netcdf_sharded_slab_load(tmp_path):
    if not ht.supports_netcdf():
        pytest.skip("netCDF4 unavailable")
    path = str(tmp_path / "slab.nc")
    data = np.arange(8 * 5, dtype=np.float32).reshape(8, 5)
    ht.save_netcdf(ht.array(data), path, "v")
    x = ht.load_netcdf(path, "v", split=0)
    assert x.split == 0
    np.testing.assert_array_equal(x.numpy(), data)


def test_io_failure_paths(tmp_path):
    # VERDICT r2 #6: the reference's io error matrix (reference
    # heat/core/tests/test_io.py): wrong types, missing files/datasets,
    # unsupported extensions, truncated CSV input
    a = ht.arange(8, split=0)
    with pytest.raises(TypeError):
        ht.load(42)
    with pytest.raises(ValueError):
        ht.load(str(tmp_path / "x.unsupported"))
    with pytest.raises(TypeError):
        ht.save(42, str(tmp_path / "x.h5"))
    with pytest.raises(ValueError):
        ht.save(a, str(tmp_path / "x.unsupported"))
    if ht.io.supports_hdf5():
        with pytest.raises(TypeError):
            ht.io.load_hdf5(42, "data")
        with pytest.raises(TypeError):
            ht.io.load_hdf5(str(tmp_path / "x.h5"), dataset=7)
        with pytest.raises(TypeError):
            ht.io.save_hdf5("notadnd", str(tmp_path / "x.h5"), "data")
        with pytest.raises((IOError, OSError)):
            ht.io.load_hdf5(str(tmp_path / "missing.h5"), "data")
        ht.io.save_hdf5(a, str(tmp_path / "ok.h5"), "data")
        with pytest.raises(KeyError):
            ht.io.load_hdf5(str(tmp_path / "ok.h5"), "wrong_dataset")
    with pytest.raises(TypeError):
        ht.load_csv(42)
    with pytest.raises(TypeError):
        ht.load_csv(str(tmp_path / "x.csv"), sep=4)
    with pytest.raises(TypeError):
        ht.load_csv(str(tmp_path / "x.csv"), header_lines="two")
    with pytest.raises(TypeError):
        ht.save_csv("nope", str(tmp_path / "x.csv"))
    with pytest.raises(ValueError):
        ht.save_csv(ht.ones((2, 2, 2)), str(tmp_path / "x.csv"))
    with pytest.raises((IOError, OSError, RuntimeError, FileNotFoundError)):
        ht.load_csv(str(tmp_path / "missing.csv"))
    # ragged trailing line (truncated write) -> the native reader must not crash
    p = tmp_path / "trunc.csv"
    p.write_text("1,2,3\n4,5,6\n7,8\n")
    try:
        r = ht.load_csv(str(p))
        assert r.shape[0] in (2, 3)
    except (ValueError, IOError, RuntimeError):
        pass  # a clear error is acceptable; silent corruption is not


def test_csv_matrix(tmp_path):
    # separators, headers, dtype inference, 1-D columns (reference
    # test_io.py CSV coverage on the native threaded reader)
    p = tmp_path / "m.csv"
    p.write_text("# c1;c2;c3\n1.5;2;3\n4;5.5;6\n7;8;9.5\n")
    r = ht.load_csv(str(p), sep=";", header_lines=1)
    np.testing.assert_allclose(
        r.numpy(), np.array([[1.5, 2, 3], [4, 5.5, 6], [7, 8, 9.5]], np.float32)
    )
    # split load of a taller file
    rows = "\n".join(",".join(str(i * 3 + j) for j in range(3)) for i in range(17))
    p2 = tmp_path / "tall.csv"
    p2.write_text(rows + "\n")
    r2 = ht.load_csv(str(p2), split=0)
    assert r2.shape == (17, 3) and r2.split == 0
    np.testing.assert_allclose(r2.numpy()[:, 0], np.arange(17) * 3)
    # save round-trip with a ragged split
    a = ht.arange(13, split=0).astype(ht.float32).reshape((13, 1))
    out = tmp_path / "rt.csv"
    ht.save_csv(a, str(out))
    back = ht.load_csv(str(out))
    np.testing.assert_allclose(back.numpy().reshape(-1), np.arange(13))


def test_hdf5_multi_dataset_modes(tmp_path):
    if not ht.io.supports_hdf5():
        pytest.skip("h5py missing")
    p = str(tmp_path / "multi.h5")
    a = ht.arange(16, split=0).astype(ht.float32)
    b = ht.ones((4, 4))
    ht.io.save_hdf5(a, p, "a", mode="w")
    ht.io.save_hdf5(b, p, "b", mode="a")  # append second dataset
    ra = ht.io.load_hdf5(p, "a", split=0)
    rb = ht.io.load_hdf5(p, "b")
    np.testing.assert_array_equal(ra.numpy(), np.arange(16, dtype=np.float32))
    np.testing.assert_array_equal(rb.numpy(), np.ones((4, 4), np.float32))
    # overwrite mode drops previous content
    ht.io.save_hdf5(b, p, "only", mode="w")
    with pytest.raises(KeyError):
        ht.io.load_hdf5(p, "a")


def test_hdf5_split1_and_dtype_roundtrip(tmp_path):
    if not ht.io.supports_hdf5():
        pytest.skip("h5py missing")
    p = str(tmp_path / "s1.h5")
    a_np = np.arange(24, dtype=np.int32).reshape(3, 8)
    a = ht.array(a_np, split=1)
    ht.save(a, p, "d")
    back = ht.load(p, dataset="d", split=1, dtype=ht.int32)
    assert back.split == 1
    # the reference's load_hdf5 defaults dtype to float32 (reference io.py:57-61)
    assert np.dtype(ht.load(p, dataset="d").dtype.char()) == np.float32
    assert np.dtype(back.dtype.char()) == np.int32
    np.testing.assert_array_equal(back.numpy(), a_np)
    # ragged split load
    r = ht.load(p, dataset="d", split=0)
    assert r.split == 0 and r.shape == (3, 8)
