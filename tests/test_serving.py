"""
Serving-runtime suite (``heat_tpu/serving/``, ISSUE 8): persistent
compilation cache, aval bucketing, shape corpus + AOT warmup, async flush
scheduler.

Guarantees pinned here:

* **Cross-process persistence** (the acceptance bar): a fresh process
  replaying a workload against a warmed ``HEAT_TPU_CACHE_DIR`` performs
  ZERO fused-kernel compiles — every flush is an L1 miss → disk hit →
  deserialized executable, bit-identical to the compiling process.
* **Bucketed ≡ exact**: results under ``HEAT_TPU_SHAPE_BUCKETS`` are
  bit-for-bit those of ``HEAT_TPU_SHAPE_BUCKETS=0`` across split
  {None, 0, 1} × even/ragged × f32/bf16, while the kernel count is bounded
  by buckets instead of distinct shapes.
* **Degradation discipline** (PR 6): a corrupt/truncated disk entry or an
  injected ``serving.cache_read`` fault is counted and falls back to a
  fresh compile — the cache can never crash a flush; the fingerprint check
  recompiles rather than loading a foreign executable.
* **Warmup**: ``serving.warmup`` rebuilds corpus recipes through fusion's
  memoized factories and AOT-compiles them into the cache; the CLI wraps it.
* **Concurrency**: independent DAGs flushed through the scheduler match
  sequential results; dispatch latency lands in telemetry.
* **Telemetry** (satellite): ``fusion_trace_cache`` (cache_info incl. the
  poisoned count and both cache capacities) and the cache-hit-rate SLO are
  exported by ``report.telemetry()``.
"""

import json
import os
import pickle
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import serving
from heat_tpu.core import fusion
from heat_tpu.monitoring import registry, report
from heat_tpu.robustness import faultinject
from heat_tpu.serving import buckets as sbuckets
from heat_tpu.serving import cache as scache
from heat_tpu.serving import corpus as scorpus

pytestmark = pytest.mark.serving


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh counters and trace cache on both sides; the disk cache is
    opt-in per test (a shared HEAT_TPU_CACHE_DIR would cross-couple entry
    counts between tests). HEAT_TPU_SHAPE_BUCKETS is deliberately NOT
    cleared: the CI serving-smoke leg runs this whole suite under
    ``HEAT_TPU_SHAPE_BUCKETS=0`` and bucketing-asserting tests pin their own
    policy via monkeypatch (the PR 5 pin-the-gate-ON precedent)."""
    from heat_tpu.robustness import breaker

    registry.reset()
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    monkeypatch.delenv("HEAT_TPU_CACHE_DIR", raising=False)
    monkeypatch.delenv("HEAT_TPU_SHAPE_CORPUS", raising=False)
    monkeypatch.delenv("HEAT_TPU_SHAPE_CORPUS_MAX", raising=False)
    # ISSUE 9 knobs default to current behavior; clear any ambient tuning
    # (breaker STATE resets too — the force-open env pin, when a CI leg sets
    # it, deliberately survives: it is what that leg proves)
    monkeypatch.delenv("HEAT_TPU_CACHE_MAX_BYTES", raising=False)
    monkeypatch.delenv("HEAT_TPU_SERVING_QUEUE_MAX", raising=False)
    monkeypatch.delenv("HEAT_TPU_SERVING_OVERFLOW", raising=False)
    monkeypatch.delenv("HEAT_TPU_FLUSH_DEADLINE_MS", raising=False)
    breaker.reset()
    fusion.clear_cache()
    yield
    fusion.clear_cache()
    registry.reset()


@pytest.fixture
def no_faults(monkeypatch):
    """Pin fault injection OFF for compile/cache-count-asserting tests (the
    PR 6 precedent: a standing CI fault plan makes count assertions
    meaningless while results stay bit-identical). ISSUE 9 extends the same
    precedent to the standing chaos schedule and the forced-open breaker CI
    legs — both keep results bit-identical through the degraded paths, which
    is exactly what count-agnostic tests prove."""
    from heat_tpu.robustness import breaker

    monkeypatch.delenv("HEAT_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("HEAT_TPU_CHAOS", raising=False)
    monkeypatch.delenv("HEAT_TPU_BREAKER_FORCE_OPEN", raising=False)
    # ISSUE 12: the standing audit/corruption legs change compile counts and
    # disk-cache traffic (eager-replay jits, checksum fallbacks)
    monkeypatch.delenv("HEAT_TPU_AUDIT_RATE", raising=False)
    monkeypatch.delenv("HEAT_TPU_COLLECTIVE_CHECKSUM", raising=False)
    faultinject.clear()
    breaker.reset()
    fusion.clear_cache()


def _compiles() -> int:
    return registry.REGISTRY.counter("fusion.kernels_compiled").get()


def _disk(label: str) -> int:
    return registry.REGISTRY.counter("serving.disk_cache").get(label)


def _chain(x):
    return (x * 2.0 + 1.0) / 3.0


def _fresh(shape=(5, 12), seed=0, dtype=np.float32, split=None):
    data = np.random.default_rng(seed).normal(size=shape).astype(dtype)
    return ht.array(data, split=split)


def _bitwise(a: np.ndarray, b: np.ndarray) -> bool:
    return a.shape == b.shape and a.dtype == b.dtype and a.tobytes() == b.tobytes()


# ------------------------------------------------------------------ disk cache
def test_disk_cache_write_then_l2_hit_zero_compiles(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        r1 = _chain(_fresh()).numpy()
        assert _disk("miss") == 1 and _disk("write") == 1
        assert len(os.listdir(tmp_path / "exec")) == 1
        # L1 hit on the second identical chain: the disk is not consulted
        r2 = _chain(_fresh()).numpy()
        assert _disk("miss") == 1 and _disk("hit") == 0
        # cold L1 (process-restart stand-in): served from disk, zero compiles
        fusion.clear_cache()
        before = _compiles()
        r3 = _chain(_fresh()).numpy()
        assert _compiles() == before
        assert _disk("hit") == 1
    assert _bitwise(r1, r2) and _bitwise(r1, r3)


def test_disk_cache_bit_parity_vs_eager(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_FUSION", "0")
    x = _fresh(seed=3)
    eager = _chain(x).numpy()
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    _chain(_fresh(seed=3)).numpy()  # compile + store
    fusion.clear_cache()
    with registry.capture():
        served = _chain(_fresh(seed=3)).numpy()
        assert _disk("hit") == 1
    # FMA carve-out does not apply: add/div chain has no mul->add contraction
    assert _bitwise(eager, served)


def test_sink_and_gemm_programs_persist(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))

    def work():
        a = _fresh((8, 6), seed=5)
        w = _fresh((6, 4), seed=6)
        loss = ((a @ w) + 1.0).sum()
        return np.asarray(loss.larray)

    with registry.capture():
        r1 = work()
        writes = _disk("write")
        assert writes >= 1
        fusion.clear_cache()
        before = _compiles()
        r2 = work()
        assert _compiles() == before  # GEMM + epilogue + sink served from disk
        assert _disk("hit") >= 1
    assert _bitwise(r1, r2)


def test_cross_process_persistence_zero_compiles(tmp_path):
    """A SECOND process with the same HEAT_TPU_CACHE_DIR performs zero fused
    compiles and serves every flush from the disk cache (acceptance bar)."""
    prog = textwrap.dedent(
        """
        import os, json
        import numpy as np
        os.environ["HEAT_TPU_MONITORING"] = "1"
        import heat_tpu as ht
        from heat_tpu.monitoring import registry
        x = ht.array(np.arange(60, dtype=np.float32).reshape(5, 12))
        r = ((x * 2.0 + 1.0) / 3.0).numpy()
        y = ht.array(np.linspace(0.1, 1.0, 24, dtype=np.float32).reshape(4, 6))
        s = np.asarray((y * y + y).sum().larray)
        c = registry.snapshot()["counters"].get("serving.disk_cache", {})
        labels = c.get("labels", {}) if isinstance(c, dict) else {}
        print(json.dumps({
            "compiles": registry.REGISTRY.counter("fusion.kernels_compiled").get(),
            "hits": labels.get("hit", 0),
            "checksum": [float(r.sum()), float(s)],
        }))
        """
    )
    env = dict(os.environ, HEAT_TPU_CACHE_DIR=str(tmp_path))
    env.pop("HEAT_TPU_FAULT_PLAN", None)
    env.pop("HEAT_TPU_SHAPE_BUCKETS", None)
    env.pop("HEAT_TPU_CHAOS", None)
    env.pop("HEAT_TPU_BREAKER_FORCE_OPEN", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def run():
        out = subprocess.run(
            [sys.executable, "-c", prog], env=env, cwd=repo,
            capture_output=True, text=True, timeout=240,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    first = run()
    second = run()
    assert first["compiles"] >= 1
    assert second["compiles"] == 0, second
    assert second["hits"] > 0
    assert first["checksum"] == second["checksum"]


def test_corrupt_entry_counted_and_recompiled(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    r1 = _chain(_fresh(seed=9)).numpy()
    (entry,) = (tmp_path / "exec").iterdir()
    entry.write_bytes(b"\x00truncated-garbage")
    fusion.clear_cache()
    with registry.capture():
        r2 = _chain(_fresh(seed=9)).numpy()
        assert _disk("corrupt") == 1
        # the recompile re-stored a good entry over the corrupt one
        assert _disk("write") == 1
    assert _bitwise(r1, r2)
    fusion.clear_cache()
    with registry.capture():
        r3 = _chain(_fresh(seed=9)).numpy()
        assert _disk("hit") == 1
    assert _bitwise(r1, r3)


def test_cache_read_fault_site_falls_back(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    r1 = _chain(_fresh(seed=11)).numpy()
    fusion.clear_cache()
    with registry.capture():
        with faultinject.inject("serving.cache_read", OSError, at_calls=[1]) as plan:
            r2 = _chain(_fresh(seed=11)).numpy()
        assert plan.fired == [1]
        assert _disk("corrupt") == 1
        assert registry.REGISTRY.counter("faults.injected").get("serving.cache_read") == 1
    assert _bitwise(r1, r2)


def test_fingerprint_mismatch_counted_incompatible(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    _chain(_fresh(seed=13)).numpy()
    (path,) = (tmp_path / "exec").iterdir()
    entry = pickle.loads(path.read_bytes())
    entry["fp"] = ("jax-from-another-life", "0.0.0", "cpu", "")
    path.write_bytes(pickle.dumps(entry))
    fusion.clear_cache()
    with registry.capture():
        _chain(_fresh(seed=13)).numpy()
        assert _disk("incompatible") == 1
        assert _disk("hit") == 0


def test_collective_programs_stay_in_memory(monkeypatch, tmp_path, no_faults):
    """A resplit-bearing program has no stable identity: counted
    incompatible, never written, still correct."""
    comm = ht.core.communication.get_comm()
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        x = _fresh((12, 6), seed=17, split=0)
        y = x * 2.0 + 1.0
        y.resplit_(1)
        r = (y + 0.5).numpy()
        assert _disk("incompatible") >= 1
        assert _disk("write") == 0
    assert not (tmp_path / "exec").exists()
    ref = (np.asarray(
        np.random.default_rng(17).normal(size=(12, 6)).astype(np.float32)
    ) * 2.0 + 1.0) + 0.5
    np.testing.assert_allclose(r, ref, rtol=1e-6)


def test_disabled_serving_is_inert(monkeypatch, tmp_path, no_faults):
    """No HEAT_TPU_CACHE_DIR, no HEAT_TPU_SHAPE_BUCKETS: no files, no
    serving counters, flushes unchanged (the cold-dir CI leg contract)."""
    monkeypatch.delenv("HEAT_TPU_SHAPE_BUCKETS", raising=False)
    with registry.capture():
        r = _chain(_fresh(seed=19)).numpy()
        snap = registry.snapshot()["counters"]
        assert not any(k.startswith("serving.") for k in snap)
    assert r.shape == (5, 12)
    assert list(tmp_path.iterdir()) == []


# ------------------------------------------------------------------ bucketing
@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize(
    "shape", [(12, 8), (11, 7)], ids=["even", "ragged"]
)
@pytest.mark.parametrize("dtype", [np.float32, "bfloat16"], ids=["f32", "bf16"])
def test_bucketed_bit_parity_matrix(monkeypatch, split, shape, dtype, no_faults):
    """Bucketed results are bit-identical to HEAT_TPU_SHAPE_BUCKETS=0 across
    split/ragged/dtype (distributed operands take the exact path — parity
    must hold there too)."""
    dt = np.dtype(dtype)
    data = (
        np.random.default_rng(int(np.prod(shape))).normal(size=shape).astype(np.float32)
    ).astype(dt)

    def work():
        x = ht.array(data.copy(), split=split)
        y = ht.where(x > 0, x * 3.0, x + 1.0)
        return np.asarray((y - 0.25).larray)

    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "0")
    exact = work()
    fusion.clear_cache()
    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "pow2")
    bucketed = work()
    assert _bitwise(exact, bucketed)


def test_bucketing_bounds_kernel_count(monkeypatch, no_faults):
    shapes = [(97, 5), (100, 7), (128, 8), (111, 6)]

    def sweep():
        out = []
        for i, s in enumerate(shapes):
            out.append(_chain(_fresh(s, seed=i)).numpy())
        return out

    with registry.capture():
        before = _compiles()
        exact = sweep()
        unbucketed = _compiles() - before
        fusion.clear_cache()
        monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "pow2")
        before = _compiles()
        bucketed = sweep()
        n_bucketed = _compiles() - before
        waste = registry.REGISTRY.counter("serving.bucket").get("pad_waste_bytes")
        hits = registry.REGISTRY.counter("serving.bucket").get("hit")
    assert unbucketed == len(shapes)  # one kernel per distinct shape
    assert n_bucketed == 1  # all four shapes round to the (128, 8) bucket
    assert hits == len(shapes)
    assert waste > 0
    for e, b in zip(exact, bucketed):
        assert _bitwise(e, b)


def test_bucketing_skips_reduction_programs(monkeypatch, no_faults):
    """A sink-rooted program is not pointwise: bucketing must decline (the
    pad would enter the sum) and the result must match the exact path."""
    data = np.random.default_rng(23).normal(size=(10, 3)).astype(np.float32)
    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "0")
    exact = np.asarray((ht.array(data.copy()) * 2.0).sum().larray)
    fusion.clear_cache()
    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "pow2")
    with registry.capture():
        bucketed = np.asarray((ht.array(data.copy()) * 2.0).sum().larray)
        assert registry.REGISTRY.counter("serving.bucket").get("hit") == 0
    assert _bitwise(exact, bucketed)


def test_bucket_policy_parse():
    assert sbuckets.policy("0") is None
    assert sbuckets.policy("") is None
    edges, tail = sbuckets.policy("pow2:16")
    assert edges == (1, 2, 4, 8, 16) and tail == 16
    assert sbuckets.bucket_dim(17, edges, tail) == 32  # linear tail
    assert sbuckets.bucket_dim(5, edges, tail) == 8
    edges, tail = sbuckets.policy("8,64,512")
    assert sbuckets.bucket_shape((3, 65, 1000), edges, tail) == (8, 512, 1024)
    with pytest.raises(ValueError):
        sbuckets.policy("pow2:banana")
    with pytest.raises(ValueError):
        sbuckets.policy("64,8")  # not ascending


def test_bucketing_composes_with_disk_cache(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "pow2")
    r1 = _chain(_fresh((97, 5), seed=1)).numpy()
    r2 = _chain(_fresh((100, 7), seed=2)).numpy()
    # both shapes share one bucketed kernel -> one exec entry on disk
    assert len(os.listdir(tmp_path / "exec")) == 1
    fusion.clear_cache()
    with registry.capture():
        before = _compiles()
        r1b = _chain(_fresh((97, 5), seed=1)).numpy()
        r2b = _chain(_fresh((100, 7), seed=2)).numpy()
        assert _compiles() == before
        assert _disk("hit") >= 1
    assert _bitwise(r1, r1b) and _bitwise(r2, r2b)


# ------------------------------------------------------------------ corpus + warmup
def test_corpus_records_bounded_and_deduped(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("HEAT_TPU_SHAPE_CORPUS_MAX", "2")
    scorpus._seen.clear()
    with registry.capture():
        for i, s in enumerate([(4, 4), (5, 5), (6, 6)]):
            _chain(_fresh(s, seed=i)).numpy()
        # repeat shape: dedup, no new entry
        fusion.clear_cache()
        _chain(_fresh((4, 4), seed=0)).numpy()
        assert scorpus.size(str(tmp_path / "corpus")) == 2
        c = registry.REGISTRY.counter("serving.corpus")
        assert c.get("recorded") == 2 and c.get("full") == 1


def test_warmup_compiles_corpus_into_fresh_cache(monkeypatch, tmp_path, no_faults):
    warm_dir = tmp_path / "warm"
    cold_dir = tmp_path / "cold"
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(warm_dir))
    scorpus._seen.clear()
    shapes = [(4, 6), (3, 9)]
    ref = [
        _chain(_fresh(s, seed=i)).numpy() for i, s in enumerate(shapes)
    ]
    stats = serving.warmup(
        corpus=str(warm_dir / "corpus"), cache_dir=str(cold_dir)
    )
    assert stats["entries"] == len(shapes)
    assert stats["compiled"] == len(shapes)
    assert stats["errors"] == 0
    # the freshly warmed dir serves a cold L1 with zero compiles
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(cold_dir))
    fusion.clear_cache()
    with registry.capture():
        before = _compiles()
        out = [_chain(_fresh(s, seed=i)).numpy() for i, s in enumerate(shapes)]
        assert _compiles() == before
        assert _disk("hit") == len(shapes)
    for a, b in zip(ref, out):
        assert _bitwise(a, b)
    # idempotent second warmup: everything already cached
    stats2 = serving.warmup(corpus=str(warm_dir / "corpus"), cache_dir=str(cold_dir))
    assert stats2["cached"] == len(shapes) and stats2["compiled"] == 0


def test_warmup_skips_foreign_fingerprint_and_garbage(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    scorpus._seen.clear()
    _chain(_fresh(seed=31)).numpy()
    cdir = tmp_path / "corpus"
    (entry,) = cdir.iterdir()
    recipe = pickle.loads(entry.read_bytes())
    recipe["fp"] = ("other-jax", "0", "tpu", "")
    (cdir / ("f" * 64 + ".pkl")).write_bytes(pickle.dumps(recipe))
    (cdir / ("e" * 64 + ".pkl")).write_bytes(b"not a pickle")
    with registry.capture():
        stats = serving.warmup(cache_dir=str(tmp_path))
    assert stats == {
        "entries": 2, "compiled": 0, "cached": 1, "skipped": 1, "errors": 0,
        "budget_cut": 0, "saved_s": 0.0,
    }
    assert registry.REGISTRY.counter("serving.corpus").get("corrupt") == 1


def test_warmup_cli_main(monkeypatch, tmp_path, capsys, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    scorpus._seen.clear()
    _chain(_fresh(seed=37)).numpy()
    import importlib

    # the package re-exports the warmup FUNCTION under the submodule's name
    wmod = importlib.import_module("heat_tpu.serving.warmup")

    rc = wmod.main(["--cache-dir", str(tmp_path)])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip())
    assert stats["entries"] == 1 and stats["cached"] == 1
    monkeypatch.delenv("HEAT_TPU_CACHE_DIR")
    assert wmod.main([]) == 2  # no cache dir: usage error, not a crash


# ------------------------------------------------------------------ scheduler
def test_concurrent_flushes_match_sequential(no_faults):
    rng = np.random.default_rng(41)
    datas = [rng.normal(size=(16, 8)).astype(np.float32) for _ in range(12)]
    expected = [
        np.asarray(_chain(ht.array(d.copy())).larray) for d in datas
    ]
    pending = [_chain(ht.array(d.copy())) for d in datas]
    with serving.FlushScheduler(max_workers=4) as sched:
        done = sched.flush_all(pending)
    for p, e in zip(done, expected):
        assert _bitwise(np.asarray(p.larray), e)


def test_scheduler_latency_telemetry_and_flush_async(no_faults):
    with registry.capture():
        x = _chain(_fresh(seed=43))
        fut = x.flush_async()
        assert fut.result() is x
        serving.flush_all([_chain(_fresh(seed=44)), _fresh(seed=45)])
        tel = report.telemetry()
    lat = tel["serving_dispatch_latency"]
    assert lat["count"] == 3
    assert lat["p50_us"] >= 0 and lat["p99_us"] >= lat["p50_us"]
    reasons = tel.get("fusion_flush_reasons", {})
    assert reasons.get("serving", 0) >= 2


def test_concurrent_flushes_under_disk_cache(monkeypatch, tmp_path, no_faults):
    """Scheduler + L2 compose: concurrent same-signature flushes settle to
    one disk entry and correct results (benign races allowed, crashes not)."""
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    datas = [np.full((8, 8), float(i), np.float32) for i in range(8)]
    pending = [_chain(ht.array(d)) for d in datas]
    with serving.FlushScheduler(max_workers=4) as sched:
        sched.flush_all(pending)
    for i, p in enumerate(pending):
        assert _bitwise(
            np.asarray(p.larray), np.asarray(_chain(ht.array(datas[i])).larray)
        )
    assert len(os.listdir(tmp_path / "exec")) == 1


# ------------------------------------------------------------------ telemetry + cache fix
def test_telemetry_exports_fusion_trace_cache_and_slo(monkeypatch, tmp_path, no_faults):
    """Satellite regression: cache_info (entries/hits/misses/evictions +
    poisoned + both capacities) and the SLO reach report.telemetry()."""
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    ci0 = fusion.cache_info()  # the fusion stats are process-cumulative
    with registry.capture():
        _chain(_fresh(seed=47)).numpy()   # miss + write
        _chain(_fresh(seed=47)).numpy()   # L1 hit
        fusion.clear_cache()
        _chain(_fresh(seed=47)).numpy()   # L2 hit
        tel = report.telemetry()
    tc = tel["fusion_trace_cache"]
    for k in ("entries", "max", "hits", "misses", "evictions", "poisoned",
              "eval_entries", "eval_max"):
        assert k in tc, k
    assert tc["max"] == 4096 and tc["eval_max"] == 4096
    assert tc["hits"] - ci0["hits"] == 1
    assert tc["misses"] - ci0["misses"] == 2  # cold compile + L2-served miss
    slo = tel["serving_cache_slo"]
    assert slo["l2_hits"] == 1
    assert slo["l1_hits"] == tc["hits"]
    assert slo["hit_rate"] is not None and 0.0 < slo["hit_rate"] <= 1.0
    assert tel["serving_disk_cache"]["write"] == 1


def test_clear_cache_clears_eval_memo_coherently(no_faults):
    """Satellite: the trace LRU and the eval-node memo are cleared together
    and both capacities are surfaced."""
    _chain(_fresh(seed=53)).numpy()
    info = fusion.cache_info()
    assert info["entries"] >= 1 and info["eval_entries"] >= 1
    fusion.clear_cache()
    info = fusion.cache_info()
    assert info["entries"] == 0 and info["eval_entries"] == 0
    assert info["poisoned"] == 0
    assert info["max"] == info["eval_max"] == 4096


# ------------------------------------------------------------------ admission control
def _shed_count(label: str) -> int:
    return registry.REGISTRY.counter("serving.shed").get(label)


def test_queue_bound_shed_policy_is_exact(no_faults):
    """Overflowed schedules are refused (counted) but results never change:
    the owner read still materializes every shed chain synchronously."""
    rng = np.random.default_rng(0)
    datas = [rng.normal(size=(16, 16)).astype(np.float32) for _ in range(8)]
    with registry.capture():
        sched = serving.FlushScheduler(max_workers=1, queue_max=1, overflow="shed")
        try:
            arrs = [_chain(ht.array(d)) for d in datas]
            futs = [sched.schedule(a) for a in arrs]
            outs = [f.result().numpy() for f in futs]
        finally:
            sched.shutdown()
        assert _shed_count("queue-full") > 0  # the bound actually bit
    for d, out in zip(datas, outs):
        ref = _chain(ht.array(d)).numpy()
        assert _bitwise(out, ref)


def test_queue_bound_block_policy_drains_without_deadlock(no_faults):
    rng = np.random.default_rng(1)
    datas = [rng.normal(size=(16, 16)).astype(np.float32) for _ in range(6)]
    with registry.capture():
        with serving.FlushScheduler(max_workers=2, queue_max=2, overflow="block") as sched:
            arrs = [_chain(ht.array(d)) for d in datas]
            futs = [sched.schedule(a) for a in arrs]
            for f in futs:
                f.result()
        assert _shed_count("queue-full") == 0  # block policy never sheds
        assert registry.REGISTRY.counter("serving.shed").get() == 0
    for d, a in zip(datas, arrs):
        assert _bitwise(a.numpy(), _chain(ht.array(d)).numpy())


def test_deadline_sheds_at_dequeue_never_wrong(no_faults):
    """A microscopic deadline with a saturated single worker: queued flushes
    are past-deadline at dequeue and shed BEFORE dispatch — and every value
    still reads back exactly."""
    rng = np.random.default_rng(2)
    datas = [rng.normal(size=(64, 64)).astype(np.float32) for _ in range(8)]
    with registry.capture():
        sched = serving.FlushScheduler(max_workers=1, deadline_ms=0.0001)
        try:
            arrs = [_chain(ht.array(d)) for d in datas]
            futs = [sched.schedule(a) for a in arrs]
            for f in futs:
                f.result()
        finally:
            sched.shutdown()
        assert _shed_count("deadline") > 0
    for d, a in zip(datas, arrs):
        assert _bitwise(a.numpy(), _chain(ht.array(d)).numpy())


def test_deadline_watchdog_counts_inflight_misses(no_faults):
    """Work that entered dispatch in time but exceeded the deadline in flight
    is counted and logged, never aborted."""
    import time as _time

    class _Slow:
        def _flush(self, _reason):
            _time.sleep(0.02)

    with registry.capture():
        sched = serving.FlushScheduler(max_workers=1, deadline_ms=5.0)
        try:
            sched.schedule(_Slow()).result()
        finally:
            sched.shutdown()
        assert (
            registry.REGISTRY.counter("serving.deadline_miss").get("in-flight") == 1
        )
        assert _shed_count("deadline") == 0  # it was dispatched, not shed


def test_scheduler_env_knobs_and_gauge(monkeypatch, no_faults):
    monkeypatch.setenv("HEAT_TPU_SERVING_QUEUE_MAX", "3")
    monkeypatch.setenv("HEAT_TPU_SERVING_OVERFLOW", "shed")
    monkeypatch.setenv("HEAT_TPU_FLUSH_DEADLINE_MS", "5000")
    sched = serving.FlushScheduler(max_workers=1)
    assert sched._queue_bound() == 3
    assert sched._overflow_policy() == "shed"
    assert sched._deadline_s() == 5.0
    monkeypatch.delenv("HEAT_TPU_SERVING_QUEUE_MAX")
    monkeypatch.delenv("HEAT_TPU_SERVING_OVERFLOW")
    monkeypatch.delenv("HEAT_TPU_FLUSH_DEADLINE_MS")
    # defaults: unbounded, block, no deadline — the PR 8 behavior
    assert sched._queue_bound() == 0
    assert sched._overflow_policy() == "block"
    assert sched._deadline_s() is None
    with registry.capture():
        x = _chain(_fresh(seed=40))
        sched.schedule(x).result()
        sched.shutdown()
        tele = report.telemetry()
    assert tele.get("serving_queue_depth") == 0  # drained back to zero


# ------------------------------------------------------------------ disk-cache janitor
from heat_tpu.serving import janitor as sjanitor  # noqa: E402


def _fill_cache(tmp_path, n=4, seed0=50):
    """n distinct-shape chains -> n exec entries (+ n corpus recipes)."""
    outs = []
    for i in range(n):
        x = _fresh(shape=(5 + i, 7), seed=seed0 + i)
        outs.append(_chain(x).numpy())
    return outs


def _cache_bytes(tmp_path) -> int:
    total = 0
    for sub in ("exec", "corpus"):
        d = tmp_path / sub
        if d.is_dir():
            total += sum(f.stat().st_size for f in d.iterdir() if f.is_file())
    return int(total)


def test_janitor_evicts_lru_to_bound(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        _fill_cache(tmp_path)
        before = _cache_bytes(tmp_path)
        assert before > 0
        # age the first entry so LRU order is deterministic
        victim = sorted((tmp_path / "exec").iterdir())[0]
        os.utime(victim, (1, 1))
        stats = sjanitor.sweep(str(tmp_path), limit=before - 1, validate=False)
        assert stats["evicted"] >= 1
        assert stats["bytes"] <= before - 1
        assert _cache_bytes(tmp_path) == stats["bytes"]
        assert not victim.exists()  # oldest mtime went first
        tele = report.telemetry()
    assert tele["serving_janitor"]["evicted"] == stats["evicted"]
    assert tele["serving_janitor"]["runs"] == 1


def test_janitor_quarantines_corrupt_entries(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        _fill_cache(tmp_path, n=2)
        entries = sorted((tmp_path / "exec").iterdir())
        entries[0].write_bytes(b"\x00garbage")
        stats = sjanitor.sweep(str(tmp_path), validate=True)
        assert stats["quarantined"] == 1
        assert not entries[0].exists()
        assert (tmp_path / "quarantine" / entries[0].name).exists()
        # the poisoned file is out of every future scan
        stats2 = sjanitor.sweep(str(tmp_path), validate=True)
        assert stats2["quarantined"] == 0
        assert entries[1].exists()  # the healthy entry untouched


def test_corrupt_entry_quarantined_at_read_time(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        r1 = _chain(_fresh(seed=60)).numpy()
        entry = next((tmp_path / "exec").iterdir())
        entry.write_bytes(b"truncated")
        fusion.clear_cache()
        r2 = _chain(_fresh(seed=60)).numpy()  # corrupt read -> recompile
        assert _disk("corrupt") == 1
        assert (tmp_path / "quarantine" / entry.name).exists()
        # the recompile re-stored a good entry under the same digest
        assert entry.exists()
    assert _bitwise(r1, r2)


def test_janitor_orphan_tempfile_sweep(tmp_path, no_faults):
    (tmp_path / "exec").mkdir()
    orphan = tmp_path / "exec" / ".tmp-dead.bin"
    orphan.write_bytes(b"half a write")
    fresh = tmp_path / "exec" / ".tmp-live.bin"
    fresh.write_bytes(b"in flight")
    with registry.capture():
        stats = sjanitor.sweep(str(tmp_path), orphan_age_s=3600.0)
        assert stats["orphans"] == 0 and orphan.exists()  # age gate holds
        stats = sjanitor.sweep(str(tmp_path), orphan_age_s=0.0)
        assert stats["orphans"] == 2
    assert not orphan.exists() and not fresh.exists()


def test_store_time_inline_sweep_enforces_bound(monkeypatch, tmp_path, no_faults):
    """HEAT_TPU_CACHE_MAX_BYTES holds while traffic keeps storing: fill past
    the bound and the inline sweep (cache.persist) evicts back under it —
    with hit-rate telemetry intact."""
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        _fill_cache(tmp_path, n=2, seed0=70)
        bound = _cache_bytes(tmp_path)  # room for ~2 entries' worth
        monkeypatch.setenv("HEAT_TPU_CACHE_MAX_BYTES", str(bound))
        _fill_cache(tmp_path, n=4, seed0=80)  # 4 more stores, each sweeping
        assert _cache_bytes(tmp_path) <= bound
        assert registry.REGISTRY.counter("serving.janitor").get("evicted") > 0
        tele = report.telemetry()
    assert "serving_cache_slo" in tele and tele["serving_cache_slo"]["l1_hits"] >= 0
    assert tele["serving_janitor"]["evicted"] > 0


def test_janitor_cli(monkeypatch, tmp_path, no_faults, capsys):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    _fill_cache(tmp_path, n=2, seed0=90)
    rc = sjanitor.main(["--max-bytes", "1", "--orphan-age", "0"])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip())
    assert stats["evicted"] >= 1 and stats["bytes"] <= 1
    monkeypatch.delenv("HEAT_TPU_CACHE_DIR")
    assert sjanitor.main([]) == 2  # no cache dir: config error


def test_reader_tolerates_concurrent_eviction(monkeypatch, tmp_path, no_faults):
    """A reader hammering cache.load while the janitor evicts underneath
    never crashes: it sees hits or clean misses (satellite: evict-while-read
    tolerance)."""
    import threading

    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        _chain(_fresh(seed=95)).numpy()
        digest = next((tmp_path / "exec").iterdir()).name[: -len(".bin")]
        errors = []

        def hammer():
            try:
                for _ in range(200):
                    scache.load(str(tmp_path), digest)
            except Exception as e:  # any leak here is the bug
                errors.append(e)

        t = threading.Thread(target=hammer)
        t.start()
        for _ in range(20):
            sjanitor.sweep(str(tmp_path), limit=0, validate=False)
        t.join()
    assert errors == []


# ------------------------------------------------------------------ multi-process contention
def _writer_prog(shape=(5, 12)):
    return (
        "import os, numpy as np\n"
        "os.environ['JAX_PLATFORMS']='cpu'\n"
        "import heat_tpu as ht\n"
        "x = ht.array(np.random.default_rng(0).normal(size=%r).astype(np.float32))\n"
        "r = ((x * 2.0 + 1.0) / 3.0).numpy()\n"
        "print(float(r.sum()))\n" % (shape,)
    )


def test_two_writers_racing_same_key(monkeypatch, tmp_path, no_faults):
    """Two processes computing the identical chain against one cache dir:
    both land, exactly one valid entry remains, and a fresh in-process read
    is served from it (satellite: same-key write race)."""
    env = dict(os.environ)
    env.update(HEAT_TPU_CACHE_DIR=str(tmp_path), JAX_PLATFORMS="cpu")
    env.pop("HEAT_TPU_FAULT_PLAN", None)
    env.pop("HEAT_TPU_CHAOS", None)
    env.pop("HEAT_TPU_BREAKER_FORCE_OPEN", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _writer_prog()],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for _ in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err[-800:]
        outs.append(out.strip())
    assert outs[0] == outs[1]
    entries = list((tmp_path / "exec").iterdir())
    assert len(entries) == 1  # same digest: last atomic replace wins
    assert sjanitor._valid_entry(str(entries[0]))
    # and the shared entry actually serves this process
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        fusion.clear_cache()
        before = _compiles()
        _chain(_fresh(shape=(5, 12), seed=0)).numpy()
        assert _disk("hit") == 1 and _compiles() == before


# ------------------------------------------------------------------ cache-read breaker
def test_cache_read_breaker_serves_memory_only(monkeypatch, tmp_path, no_faults):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("HEAT_TPU_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("HEAT_TPU_BREAKER_COOLDOWN", "100")
    from heat_tpu.robustness import breaker as rbreaker

    with registry.capture():
        r1 = _chain(_fresh(seed=97)).numpy()  # stores the entry
        with faultinject.inject("serving.cache_read", OSError, at_calls="*"):
            for seed in (97, 97, 97):
                fusion.clear_cache()
                r = _chain(_fresh(seed=seed)).numpy()
                assert _bitwise(r, r1)
            consulted = faultinject.call_count("serving.cache_read")
        # two failing reads opened the breaker; the third flush never touched
        # the disk (served by a fresh in-memory compile)
        assert consulted == 2
        assert rbreaker.breaker("serving.cache_read").state() == "open"
        assert _disk("corrupt") == 2
        assert _disk("breaker-open") == 1
        tele = report.telemetry()
    assert tele["robustness_breakers"]["serving.cache_read:open"] == 1


# ------------------------------------------------------------------ warmup CLI gating
def test_warmup_cli_exit_codes_and_summary(monkeypatch, tmp_path, capsys, no_faults):
    """Satellite: error > 0 exits nonzero, --strict also gates on skips, and
    the stderr summary line is CI-greppable."""
    import importlib

    # the package re-exports the warmup FUNCTION under the submodule's name
    swarmup = importlib.import_module("heat_tpu.serving.warmup")

    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    scorpus._seen.clear()  # digests are deduped process-wide
    with registry.capture():
        _chain(_fresh(seed=99)).numpy()  # one good corpus recipe
    corpus_dir = tmp_path / "corpus"
    good = next(corpus_dir.iterdir())
    entry = pickle.loads(good.read_bytes())
    # a foreign-fingerprint recipe: skipped (not an error)
    foreign = dict(entry, fp=("other", "toolchain", "cpu", "v0"))
    (corpus_dir / ("f" * 64 + ".pkl")).write_bytes(pickle.dumps(foreign))
    # a same-fingerprint recipe that cannot compile: leaf specs reference a
    # leaf that does not exist -> an error, not a skip
    broken = dict(entry, leaf_descs=())
    (corpus_dir / ("e" * 64 + ".pkl")).write_bytes(pickle.dumps(broken))

    rc = swarmup.main(["--cache-dir", str(tmp_path)])
    captured = capsys.readouterr()
    assert rc == 1  # errors > 0 now fails (a fully-failed warmup used to exit 0)
    stats = json.loads(captured.out.strip())
    assert stats["errors"] == 1 and stats["skipped"] == 1 and stats["cached"] == 1
    assert "warmup: 3 entries" in captured.err

    os.unlink(str(corpus_dir / ("e" * 64 + ".pkl")))
    rc = swarmup.main(["--cache-dir", str(tmp_path)])
    capsys.readouterr()
    assert rc == 0  # skips alone pass by default...
    rc = swarmup.main(["--cache-dir", str(tmp_path), "--strict"])
    capsys.readouterr()
    assert rc == 1  # ...but --strict gates on them


# ------------------------------------------------------------------ symbolic AOT (ISSUE 17)
def _sym(label: str) -> int:
    return registry.REGISTRY.counter("serving.symbolic").get(label)


def _sym_chain(x):
    # scalar Python operands become weak-typed scalar leaves — the family
    # eligibility rule must carry them (the bench-mix shape)
    return ht.sin((x * 2.0 + 1.0) / 3.0 - 0.5)


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(12, 8), (11, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [np.float32, "bfloat16"], ids=["f32", "bf16"])
def test_symbolic_aot_differential_matrix(monkeypatch, split, shape, dtype, no_faults):
    """The bit-parity gate: HEAT_TPU_SYMBOLIC_AOT=1 must be byte-identical
    to the hatch pinned off across split × even/ragged × dtype (split
    arrays are family-ineligible and prove the exact-path fallback)."""
    monkeypatch.setenv("HEAT_TPU_SYMBOLIC_AOT", "0")
    ref = np.asarray(_sym_chain(_fresh(shape, seed=7, dtype=dtype, split=split)).larray)
    fusion.clear_cache()
    monkeypatch.setenv("HEAT_TPU_SYMBOLIC_AOT", "1")
    out = np.asarray(_sym_chain(_fresh(shape, seed=7, dtype=dtype, split=split)).larray)
    assert _bitwise(ref, out)


def test_symbolic_one_family_one_compile_many_shapes(monkeypatch, tmp_path, no_faults):
    """The tentpole bar: N distinct shapes of one pointwise program under
    the symbolic hatch cost ONE compile (the family export) — below the
    bucketing floor — with zero bucket pad waste, one ``sym-`` L2 entry and
    one ``sym-`` corpus recipe."""
    monkeypatch.setenv("HEAT_TPU_SYMBOLIC_AOT", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    shapes = [(33, 5), (48, 12), (57, 7), (64, 5), (97, 12), (120, 31)]
    with registry.capture():
        for i, s in enumerate(shapes):
            _sym_chain(_fresh(s, seed=i)).numpy()
        assert _compiles() == 1  # one export, five family serves
        assert _sym("export") == 1 and _sym("served") == len(shapes)
        assert registry.REGISTRY.counter("serving.bucket").get("pad_waste_bytes") == 0
    execs = os.listdir(tmp_path / "exec")
    assert len(execs) == 1 and execs[0].startswith("sym-")
    recipes = os.listdir(tmp_path / "corpus")
    assert len(recipes) == 1 and recipes[0].startswith("sym-")


def test_symbolic_cross_process_three_sizes_zero_compiles(monkeypatch, tmp_path, no_faults):
    """Acceptance: a fresh process serves THREE distinct sizes of one
    family from the symbolic L2 entry with ``fusion.kernels_compiled == 0``,
    each bit-identical to this process's exact-path reference."""
    # exact-path references first (hatch off), then the family export
    monkeypatch.setenv("HEAT_TPU_SYMBOLIC_AOT", "0")
    sizes = [(9, 4), (17, 11), (40, 3)]
    refs = [np.asarray(_sym_chain(_fresh(s, seed=i)).larray) for i, s in enumerate(sizes)]
    fusion.clear_cache()
    monkeypatch.setenv("HEAT_TPU_SYMBOLIC_AOT", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    _sym_chain(_fresh((5, 7), seed=99)).numpy()  # a FOURTH size writes the family
    prog = textwrap.dedent(
        """
        import json, os, sys
        import numpy as np
        os.environ["JAX_PLATFORMS"] = "cpu"
        import heat_tpu as ht
        from heat_tpu.monitoring import registry
        registry.STATE.enabled = True
        outs = []
        for i, s in enumerate(%r):
            data = np.random.default_rng(i).normal(size=tuple(s)).astype(np.float32)
            r = ht.sin((ht.array(data) * 2.0 + 1.0) / 3.0 - 0.5).numpy()
            outs.append(r.tobytes().hex())
        print(json.dumps({
            "compiled": registry.REGISTRY.counter("fusion.kernels_compiled").get(),
            "sym_hit": registry.REGISTRY.counter("serving.symbolic").get("hit"),
            "outs": outs,
        }))
        """
        % (sizes,)
    )
    env = dict(os.environ)
    env.update(
        HEAT_TPU_CACHE_DIR=str(tmp_path), HEAT_TPU_SYMBOLIC_AOT="1",
        JAX_PLATFORMS="cpu", HEAT_TPU_FUSION="1",
    )
    for k in ("HEAT_TPU_FAULT_PLAN", "HEAT_TPU_CHAOS", "HEAT_TPU_BREAKER_FORCE_OPEN",
              "HEAT_TPU_AUDIT_RATE", "HEAT_TPU_SHAPE_BUCKETS"):
        env.pop(k, None)
    res = subprocess.run(
        [sys.executable, "-c", prog], env=env, capture_output=True, text=True,
        timeout=240,
    )
    assert res.returncode == 0, res.stderr[-800:]
    got = json.loads(res.stdout.strip().splitlines()[-1])
    assert got["compiled"] == 0  # three sizes, zero compiles, one L2 read
    assert got["sym_hit"] == 1
    for ref, hexed in zip(refs, got["outs"]):
        assert ref.tobytes().hex() == hexed


def test_symbolic_fingerprint_mismatch_reexports(monkeypatch, tmp_path, no_faults):
    """A symbolic entry from a foreign toolchain must never deserialize:
    counted ``incompatible``, re-exported fresh, results bit-identical."""
    monkeypatch.setenv("HEAT_TPU_SYMBOLIC_AOT", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        r1 = _sym_chain(_fresh(seed=21)).numpy()
        (entry,) = (tmp_path / "exec").iterdir()
        payload, _ = scache.split_footer(entry.read_bytes())
        doctored = pickle.loads(payload)
        doctored["fp"] = ("other-jax", "0", "tpu", "")
        entry.write_bytes(scache.with_footer(pickle.dumps(doctored, protocol=2)))
        fusion.clear_cache()
        r2 = _sym_chain(_fresh(seed=21)).numpy()
        assert _sym("incompatible") >= 1
        assert _sym("export") == 2  # the mismatch forced a fresh export
    assert _bitwise(r1, r2)


def test_symbolic_corrupt_entry_quarantined_reexports(monkeypatch, tmp_path, no_faults):
    """A bit-flipped symbolic entry fails the sha256 footer (``checksum``),
    is quarantined and re-exported; footer-less garbage is ``corrupt`` with
    the same quarantine discipline — never a crash either way."""
    monkeypatch.setenv("HEAT_TPU_SYMBOLIC_AOT", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        r1 = _sym_chain(_fresh(seed=22)).numpy()
        (entry,) = (tmp_path / "exec").iterdir()
        blob = bytearray(entry.read_bytes())
        blob[len(blob) // 2] ^= 0xFF  # body flip: footer present, sha mismatch
        entry.write_bytes(bytes(blob))
        fusion.clear_cache()
        r2 = _sym_chain(_fresh(seed=22)).numpy()
        assert _sym("checksum") == 1
        assert (tmp_path / "quarantine" / entry.name).exists()
        assert entry.exists()  # re-export re-stored a good entry
        entry.write_bytes(b"not an exported family")  # no footer at all
        fusion.clear_cache()
        r3 = _sym_chain(_fresh(seed=22)).numpy()
        assert _sym("corrupt") == 1
    assert _bitwise(r1, r2) and _bitwise(r1, r3)


def test_symbolic_off_is_inert(monkeypatch, tmp_path, no_faults):
    """Hatch off (pinned "0"): the exact per-shape path, no symbolic
    counters, no ``sym-`` artifacts — bit-for-bit the PR 16 behavior."""
    monkeypatch.setenv("HEAT_TPU_SYMBOLIC_AOT", "0")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        _sym_chain(_fresh((6, 4), seed=1)).numpy()
        _sym_chain(_fresh((8, 3), seed=2)).numpy()
        assert _compiles() == 2  # one exact kernel per shape
        for label in ("served", "export", "hit", "miss", "write"):
            assert _sym(label) == 0
    assert not [f for f in os.listdir(tmp_path / "exec") if f.startswith("sym-")]


# ------------------------------------------------------------------ predictive warmup (ISSUE 17)
def _spool_snapshot(spool, pid, freq_by_digest):
    """One fabricated telemetry-spool snapshot carrying a per-signature
    frequency table (the exact shape ``aggregate.build_snapshot`` publishes
    when the flight recorder is armed)."""
    import time as _time

    snap = {
        "schema": 1, "pid": pid, "nonce": "t%d" % pid, "time": _time.time(),
        "flight": {
            "enabled": True,
            "per_signature": {
                d: {"flushes": n, "wall_s": 0.0} for d, n in freq_by_digest.items()
            },
        },
    }
    os.makedirs(spool, exist_ok=True)
    with open(os.path.join(spool, "%d-t.json" % pid), "w") as f:
        json.dump(snap, f)


def test_warmup_predictive_order_deterministic_and_budget(
    monkeypatch, tmp_path, no_faults
):
    """Predictive ordering: frequency × compile-cost rank mined from a
    seeded spool is deterministic, --top cuts the tail as ``budget_cut``
    (never skipped/errored — the strict exit contract is load-independent),
    and the hottest digest warms first."""
    import importlib

    swarmup = importlib.import_module("heat_tpu.serving.warmup")
    warm = tmp_path / "warm"
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(warm))
    scorpus._seen.clear()
    digests = []
    for i, s in enumerate([(4, 6), (3, 9), (8, 2)]):
        before = set(os.listdir(warm / "exec")) if (warm / "exec").exists() else set()
        _chain(_fresh(s, seed=i)).numpy()
        (fresh,) = set(os.listdir(warm / "exec")) - before
        digests.append(fresh[: -len(".bin")])
    spool = tmp_path / "spool"
    # the middle digest is by far the hottest across two fleet processes
    _spool_snapshot(str(spool), 101, {digests[1]: 40, digests[0]: 2})
    _spool_snapshot(str(spool), 102, {digests[1]: 25})
    items = list(scorpus.entries(str(warm / "corpus")))
    ranked1, predicted = swarmup._predictive_order(items, str(warm), str(spool))
    ranked2, _ = swarmup._predictive_order(items, str(warm), str(spool))
    assert [d for d, _ in ranked1] == [d for d, _ in ranked2]  # deterministic
    assert ranked1[0][0] == digests[1]  # hottest first (65 flushes summed)
    assert predicted == {digests[0], digests[1]}
    cold = tmp_path / "cold"
    with registry.capture():
        stats = swarmup.warmup(
            corpus=str(warm / "corpus"), cache_dir=str(cold),
            order="predictive", spool=str(spool), top=1,
        )
        assert registry.REGISTRY.counter("serving.warmup").get("predicted") == 1
        assert registry.REGISTRY.counter("serving.warmup").get("budget-cut") == 2
    assert stats["compiled"] == 1 and stats["budget_cut"] == 2
    assert stats["skipped"] == 0 and stats["errors"] == 0
    (warmed,) = os.listdir(cold / "exec")
    assert warmed[: -len(".bin")] == digests[1]  # the budget went to the hottest


def test_warmup_cli_predictive_flags_and_summary(monkeypatch, tmp_path, capsys, no_faults):
    """CLI hardening satellite: --order/--spool/--top parse, the corpus
    default is untouched, budget-cut entries do not trip --strict, and the
    stderr summary reports the cut + estimated compile-seconds saved."""
    import importlib

    swarmup = importlib.import_module("heat_tpu.serving.warmup")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    scorpus._seen.clear()
    _chain(_fresh(seed=71)).numpy()
    _chain(_fresh((7, 3), seed=72)).numpy()
    rc = swarmup.main(
        ["--cache-dir", str(tmp_path), "--order", "predictive", "--top", "1",
         "--spool", str(tmp_path / "no-such-spool"), "--strict"]
    )
    captured = capsys.readouterr()
    assert rc == 0  # cached+budget_cut only: strict gates on SKIPS, not cuts
    stats = json.loads(captured.out.strip())
    assert stats["entries"] == 2 and stats["budget_cut"] == 1
    assert "budget-cut" in captured.err and "compile saved" in captured.err
