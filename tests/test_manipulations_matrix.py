"""
Manipulations case matrix: the reference's strongest coverage area (reference
heat/core/tests/test_manipulations.py, 3.6k LoC) ported onto the golden
harness — every op over split ∈ {None, 0, 1} × even/ragged shapes against numpy
ground truth, plus split-metadata tracking and error contracts.
"""

import numpy as np
import pytest

import jax

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication


def _comm():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs a multi-device mesh")
    return MeshCommunication(devices=devs)


SHAPES_2D = [(16, 6), (13, 5)]
SPLITS = [None, 0, 1]


def _mk(shape, split, comm, dtype=np.float32):
    a = (np.arange(np.prod(shape)) % 23).astype(dtype).reshape(shape)
    return a, ht.array(a.copy(), split=split, comm=comm)


# ------------------------------------------------------------------ concatenate
@pytest.mark.parametrize("shape", SHAPES_2D)
@pytest.mark.parametrize("s1", SPLITS)
@pytest.mark.parametrize("s2", SPLITS)
@pytest.mark.parametrize("axis", [0, 1])
def test_concatenate_mixed_splits(shape, s1, s2, axis):
    comm = _comm()
    a, x = _mk(shape, s1, comm)
    b, y = _mk(shape, s2, comm)
    want = np.concatenate([a, b], axis=axis)
    got = ht.concatenate([x, y], axis=axis)
    np.testing.assert_array_equal(got.numpy(), want)
    assert got.shape == want.shape


def test_concatenate_dtype_promotion_and_errors():
    comm = _comm()
    x = ht.array(np.ones((4, 3), np.float32), split=0, comm=comm)
    y = ht.array(np.ones((2, 3), np.int32), split=0, comm=comm)
    out = ht.concatenate([x, y], axis=0)
    assert out.dtype == ht.float32 and out.shape == (6, 3)
    with pytest.raises(TypeError):
        ht.concatenate("nope")
    with pytest.raises(ValueError):
        ht.concatenate([x, ht.ones((4, 3, 1), comm=comm)])


@pytest.mark.parametrize("split", SPLITS)
def test_stack_family(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    for fn, ref in [
        (ht.vstack, np.vstack),
        (ht.hstack, np.hstack),
        (ht.column_stack, np.column_stack),
        (ht.row_stack, np.vstack),
    ]:
        np.testing.assert_array_equal(fn([x, x]).numpy(), ref([a, a]))
    for axis in (0, 1, 2, -1):
        np.testing.assert_array_equal(
            ht.stack([x, x], axis=axis).numpy(), np.stack([a, a], axis=axis)
        )
    with pytest.raises(ValueError):
        ht.stack([x, ht.ones((2, 2), comm=comm)])
    v = ht.array(np.arange(6, dtype=np.float32), split=0, comm=comm)
    np.testing.assert_array_equal(
        ht.column_stack([v, v]).numpy(), np.column_stack([np.arange(6.0), np.arange(6.0)])
    )


# ------------------------------------------------------------------------- pad
@pytest.mark.parametrize("shape", SHAPES_2D)
@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("mode", ["constant", "edge", "reflect", "wrap"])
def test_pad_modes(shape, split, mode):
    comm = _comm()
    a, x = _mk(shape, split, comm)
    widths = ((1, 2), (0, 3))
    kw = {"constant_values": 4.0} if mode == "constant" else {}
    want = np.pad(a, widths, mode=mode, **kw)
    got = ht.pad(x, widths, mode=mode, **kw)
    np.testing.assert_array_equal(got.numpy(), want)
    assert got.split == split


@pytest.mark.parametrize("split", [None, 0])
def test_pad_width_forms(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    for widths in [2, (1, 3), ((2, 2), (1, 1))]:
        np.testing.assert_array_equal(ht.pad(x, widths).numpy(), np.pad(a, widths))


# ------------------------------------------------------------------ split family
@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("sections", [2, [2, 5], [1, 4, 10]])
def test_split_sections(split, sections):
    comm = _comm()
    a, x = _mk((16, 6), split, comm)
    want = np.split(a, sections, axis=0)
    got = ht.split(x, sections, axis=0)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.numpy(), w)


def test_split_errors_and_variants():
    comm = _comm()
    a, x = _mk((16, 6), 0, comm)
    with pytest.raises(ValueError):
        ht.split(x, 5, axis=0)  # 16 not divisible by 5
    for fn, ref, kw in [
        (ht.vsplit, np.vsplit, {}),
        (ht.hsplit, np.hsplit, {}),
    ]:
        got = fn(x, 2)
        want = ref(a, 2)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g.numpy(), w)
    a3 = np.arange(24.0, dtype=np.float32).reshape(2, 3, 4)
    x3 = ht.array(a3, comm=comm)
    for g, w in zip(ht.dsplit(x3, 2), np.dsplit(a3, 2)):
        np.testing.assert_array_equal(g.numpy(), w)


# --------------------------------------------------------------------- reshape
@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("new_shape", [(80,), (8, 10), (4, 20), (2, 2, 20), (-1, 16)])
def test_reshape_matrix(split, new_shape):
    comm = _comm()
    a, x = _mk((16, 5), split, comm)
    want = a.reshape(new_shape)
    got = ht.reshape(x, new_shape)
    np.testing.assert_array_equal(got.numpy(), want)


@pytest.mark.parametrize("split", SPLITS)
def test_flatten_ravel(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    np.testing.assert_array_equal(ht.flatten(x).numpy(), a.reshape(-1))
    np.testing.assert_array_equal(ht.ravel(x).numpy(), a.ravel())
    if split is not None:
        assert ht.flatten(x).split == 0


# ------------------------------------------------------------------- axis moves
@pytest.mark.parametrize("split", SPLITS)
def test_axis_rearrangers(split):
    comm = _comm()
    a = np.arange(2 * 13 * 4, dtype=np.float32).reshape(2, 13, 4)
    x = ht.array(a, split=split, comm=comm)
    np.testing.assert_array_equal(ht.moveaxis(x, 0, 2).numpy(), np.moveaxis(a, 0, 2))
    np.testing.assert_array_equal(ht.moveaxis(x, [0, 1], [1, 0]).numpy(), np.moveaxis(a, [0, 1], [1, 0]))
    np.testing.assert_array_equal(ht.swapaxes(x, 0, 2).numpy(), np.swapaxes(a, 0, 2))
    np.testing.assert_array_equal(ht.expand_dims(x, 1).numpy(), np.expand_dims(a, 1))
    np.testing.assert_array_equal(ht.expand_dims(x, -1).numpy(), np.expand_dims(a, -1))
    # split follows its axis
    if split == 1:
        assert ht.swapaxes(x, 0, 1).split == 0
        assert ht.moveaxis(x, 1, 0).split == 0
        assert ht.expand_dims(x, 0).split == 2


@pytest.mark.parametrize("split", SPLITS)
def test_squeeze_matrix(split):
    comm = _comm()
    a = np.arange(13.0, dtype=np.float32).reshape(1, 13, 1)
    x = ht.array(a, split=split)
    np.testing.assert_array_equal(ht.squeeze(x).numpy(), np.squeeze(a))
    np.testing.assert_array_equal(ht.squeeze(x, axis=0).numpy(), np.squeeze(a, axis=0))
    np.testing.assert_array_equal(ht.squeeze(x, axis=-1).numpy(), np.squeeze(a, axis=2))
    with pytest.raises(ValueError):
        ht.squeeze(x, axis=1)
    if split == 1:
        assert ht.squeeze(x).split == 0


# ------------------------------------------------------------------ flip / roll
@pytest.mark.parametrize("shape", SHAPES_2D)
@pytest.mark.parametrize("split", SPLITS)
def test_flip_roll_rot(shape, split):
    comm = _comm()
    a, x = _mk(shape, split, comm)
    np.testing.assert_array_equal(ht.flip(x).numpy(), np.flip(a))
    np.testing.assert_array_equal(ht.flip(x, 0).numpy(), np.flip(a, 0))
    np.testing.assert_array_equal(ht.flip(x, (0, 1)).numpy(), np.flip(a, (0, 1)))
    np.testing.assert_array_equal(ht.fliplr(x).numpy(), np.fliplr(a))
    np.testing.assert_array_equal(ht.flipud(x).numpy(), np.flipud(a))
    np.testing.assert_array_equal(ht.roll(x, 3).numpy(), np.roll(a, 3))
    np.testing.assert_array_equal(ht.roll(x, -2, axis=0).numpy(), np.roll(a, -2, axis=0))
    np.testing.assert_array_equal(
        ht.roll(x, (1, 2), axis=(0, 1)).numpy(), np.roll(a, (1, 2), axis=(0, 1))
    )
    for k in range(-1, 5):
        np.testing.assert_array_equal(ht.rot90(x, k=k).numpy(), np.rot90(a, k=k))
    assert ht.flip(x, 0).split == split


# ---------------------------------------------------------------- repeat / tile
@pytest.mark.parametrize("split", SPLITS)
def test_repeat_tile(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    np.testing.assert_array_equal(ht.repeat(x, 2).numpy(), np.repeat(a, 2))
    np.testing.assert_array_equal(ht.repeat(x, 3, axis=0).numpy(), np.repeat(a, 3, axis=0))
    np.testing.assert_array_equal(ht.repeat(x, 2, axis=1).numpy(), np.repeat(a, 2, axis=1))
    reps = np.arange(13) % 3
    np.testing.assert_array_equal(
        ht.repeat(x, reps, axis=0).numpy(), np.repeat(a, reps, axis=0)
    )
    np.testing.assert_array_equal(ht.tile(x, (2, 3)).numpy(), np.tile(a, (2, 3)))
    np.testing.assert_array_equal(ht.tile(x, 2).numpy(), np.tile(a, 2))
    np.testing.assert_array_equal(ht.tile(x, (2, 1, 1)).numpy(), np.tile(a, (2, 1, 1)))


# -------------------------------------------------------------- diag / diagonal
@pytest.mark.parametrize("split", [None, 0])
@pytest.mark.parametrize("offset", [-2, -1, 0, 1, 3])
def test_diag_diagonal(split, offset):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    np.testing.assert_array_equal(ht.diagonal(x, offset=offset).numpy(), np.diagonal(a, offset=offset))
    v = ht.array(np.arange(5, dtype=np.float32), split=split, comm=comm)
    np.testing.assert_array_equal(ht.diag(v, offset).numpy(), np.diag(np.arange(5.0), offset))
    np.testing.assert_array_equal(ht.diag(x, offset).numpy(), np.diag(a, offset))


# ------------------------------------------------------------------------- sort
@pytest.mark.parametrize("shape", SHAPES_2D)
@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("axis", [0, 1, -1])
@pytest.mark.parametrize("descending", [False, True])
def test_sort_matrix(shape, split, axis, descending):
    comm = _comm()
    rng = np.random.default_rng(abs(hash((shape, split, axis))) % 2**31)
    a = rng.integers(0, 9, size=shape).astype(np.float32)  # duplicates galore
    x = ht.array(a, split=split, comm=comm)
    v, i = ht.sort(x, axis=axis, descending=descending)
    want = np.sort(a, axis=axis)
    if descending:
        want = np.flip(want, axis=axis)
    np.testing.assert_array_equal(v.numpy(), want)
    np.testing.assert_array_equal(
        np.take_along_axis(a, i.numpy().astype(np.int64), axis=axis), v.numpy()
    )


# ------------------------------------------------------------------------- topk
@pytest.mark.parametrize("split", [None, 0])
@pytest.mark.parametrize("largest", [True, False])
def test_topk(split, largest):
    comm = _comm()
    rng = np.random.default_rng(3)
    a = rng.standard_normal((13, 6)).astype(np.float32)
    x = ht.array(a, split=split, comm=comm)
    v, i = ht.topk(x, 3, largest=largest)
    ref = np.sort(a, axis=-1)
    ref = ref[:, ::-1][:, :3] if largest else ref[:, :3]
    np.testing.assert_allclose(v.numpy(), ref, rtol=1e-6)
    np.testing.assert_array_equal(np.take_along_axis(a, i.numpy().astype(np.int64), -1), v.numpy())


# ------------------------------------------------------------------------ unique
@pytest.mark.parametrize("split", [None, 0])
def test_unique_matrix(split):
    comm = _comm()
    rng = np.random.default_rng(4)
    a = rng.integers(0, 7, size=29).astype(np.int32)
    x = ht.array(a, split=split, comm=comm)
    np.testing.assert_array_equal(ht.unique(x).numpy(), np.unique(a))
    vals, inv = ht.unique(x, return_inverse=True)
    w_vals, w_inv = np.unique(a, return_inverse=True)
    np.testing.assert_array_equal(np.asarray(vals.numpy()), w_vals)
    np.testing.assert_array_equal(np.asarray(inv.numpy()).reshape(-1), w_inv.reshape(-1))
    # floats with exact duplicates
    f = np.round(rng.standard_normal(31), 1).astype(np.float32)
    y = ht.array(f, split=split, comm=comm)
    np.testing.assert_array_equal(ht.unique(y).numpy(), np.unique(f))


# ----------------------------------------------------------------- broadcast_to
@pytest.mark.parametrize("split", [None, 0])
def test_broadcast_to(split):
    comm = _comm()
    v = ht.array(np.arange(5, dtype=np.float32), split=split, comm=comm)
    got = ht.broadcast_to(v, (3, 5))
    np.testing.assert_array_equal(got.numpy(), np.broadcast_to(np.arange(5.0), (3, 5)))
    a, x = _mk((13, 1), split, comm)
    got = ht.broadcast_to(x, (13, 4))
    np.testing.assert_array_equal(got.numpy(), np.broadcast_to(a, (13, 4)))
    if split == 0:
        assert got.split == 0


# -------------------------------------------------------------------- resplit
@pytest.mark.parametrize("shape", SHAPES_2D)
def test_resplit_round_trips(shape):
    comm = _comm()
    a, x = _mk(shape, 0, comm)
    for target in (1, None, 0, 1, 0, None, 0):
        x = ht.resplit(x, target) if target is not None else ht.resplit(x, None)
        assert x.split == target
        np.testing.assert_array_equal(x.numpy(), a)
    r = ht.redistribute(x)
    np.testing.assert_array_equal(r.numpy(), a)


# ------------------------------------------------------------------- shape util
def test_shape_and_balance_helpers():
    comm = _comm()
    a, x = _mk((13, 5), 0, comm)
    assert tuple(ht.shape(x)) == (13, 5)
    b = ht.balance(x, copy=True)
    np.testing.assert_array_equal(b.numpy(), a)
    assert b is not x
