"""
Differential fuzz harness vs numpy (VERDICT r3 #4).

A seeded generator composes random op chains — factory -> elementwise /
reduction / manipulation / indexing steps — over random (split, dtype,
even/ragged shape) and checks every intermediate against a numpy shadow
computation: values (dtype-aware tolerance), global shape, and per-shard
placement (via ``heat_tpu.testing.assert_array_equal``, so a lying ``split``
is caught, not just a wrong value). numpy semantics ARE the reference's
contract — the reference API is numpy-compatible by design (SURVEY.md §2.2).

* Reproducible: the chain is fully determined by its seed; a failure message
  prints the seed and the op trace so the exact chain replays with
  ``run_chain(seed)``.
* Teeth: ``test_planted_numeric_bug_is_caught`` and
  ``test_planted_metadata_bug_is_caught`` monkeypatch a deliberately wrong op
  (a 1e-3 value skew; an off-by-one split announcement) and assert the
  harness actually fails the chain.

The default run covers ``N_CHAINS`` seeds; CI's fuzz job widens it via the
``HEAT_TPU_FUZZ_CHAINS`` env var (ci.yaml).
"""

import os

import numpy as np
import pytest

import heat_tpu as ht
import heat_tpu.testing as htt
from heat_tpu.core.dndarray import DNDarray

from _accel import ON_ACCELERATOR

# real-accelerator runs dispatch eagerly through the tunnel (~100 ms/op): keep
# a representative slice there, full width on the CPU mesh / CI
N_CHAINS = int(os.environ.get("HEAT_TPU_FUZZ_CHAINS", "6" if ON_ACCELERATOR else "24"))
OPS_PER_CHAIN = 6

# f32 chains accumulate a few ulp per step on the CPU mesh; accelerator VPU
# transcendentals (~2.2e-4 relative) get amplified by cancellation-type chain
# steps (sorted-neighbor diff, log near 0), so the accelerator bound is the
# amplified one — the CPU mesh remains the tight primary bug-finder
TOL = dict(rtol=5e-3, atol=1e-4) if ON_ACCELERATOR else dict(rtol=2e-4, atol=2e-5)


# --------------------------------------------------------------------- op table
# Each op: (name, applicable?, ht_fn, np_fn). Ops receive (h, a, rng) and
# return the new (h, a). Inapplicable ops are skipped at draw time, so any
# seed yields a valid chain.


def _rand_axis(a, rng):
    return int(rng.integers(0, a.ndim)) if a.ndim else 0


def _clip_small(a):
    return np.clip(a, -4.0, 4.0)


OPS = []


def op(name, applicable=lambda a: True):
    def deco(fn):
        OPS.append((name, applicable, fn))
        return fn

    return deco


# ----- elementwise unary
@op("abs")
def _abs(h, a, rng):
    return ht.abs(h), np.abs(a)


@op("neg", lambda a: a.dtype != np.bool_)
def _neg(h, a, rng):
    return -h, -a


@op("exp", lambda a: a.dtype.kind == "f")
def _exp(h, a, rng):
    return ht.exp(ht.clip(h, -4.0, 4.0)), np.exp(_clip_small(a))


@op("sqrt_abs", lambda a: a.dtype.kind == "f")
def _sqrt(h, a, rng):
    return ht.sqrt(ht.abs(h)), np.sqrt(np.abs(a))


@op("log1p_abs", lambda a: a.dtype.kind == "f")
def _log1p(h, a, rng):
    return ht.log1p(ht.abs(h)), np.log1p(np.abs(a))


@op("round", lambda a: a.dtype.kind == "f")
def _round(h, a, rng):
    return ht.round(h), np.round(a)


@op("sign", lambda a: a.dtype != np.bool_)
def _sign(h, a, rng):
    return ht.sign(h), np.sign(a)


# ----- elementwise binary (scalar or broadcast second operand)
@op("add_scalar", lambda a: a.dtype != np.bool_)
def _add_s(h, a, rng):
    s = float(rng.integers(-3, 4))
    if a.dtype.kind in "iu":
        s = int(s)
    return h + s, a + s


@op("mul_scalar", lambda a: a.dtype != np.bool_)
def _mul_s(h, a, rng):
    s = int(rng.integers(1, 4))
    return h * s, a * s


@op("sub_self", lambda a: a.dtype != np.bool_)
def _sub_self(h, a, rng):
    return h - h, a - a


@op("maximum_flip", lambda a: a.dtype != np.bool_ and a.ndim >= 1)
def _max_flip(h, a, rng):
    ax = _rand_axis(a, rng)
    return ht.maximum(h, ht.flip(h, ax)), np.maximum(a, np.flip(a, ax))


@op("compare_lt", lambda a: a.dtype != np.bool_)
def _lt(h, a, rng):
    return h < 1, a < 1


# ----- reductions
@op("sum_axis", lambda a: a.ndim >= 1 and a.dtype != np.bool_)
def _sum(h, a, rng):
    ax = _rand_axis(a, rng)
    keep = bool(rng.integers(0, 2))
    # torch-style keepdim= is the reference's spelling (arithmetics.py:946+)
    return ht.sum(h, axis=ax, keepdim=keep), np.sum(a, axis=ax, keepdims=keep)


@op("mean_axis", lambda a: a.ndim >= 1 and a.dtype.kind == "f")
def _mean(h, a, rng):
    ax = _rand_axis(a, rng)
    return ht.mean(h, axis=ax), np.mean(a, axis=ax)


@op("max_axis", lambda a: a.ndim >= 1 and a.dtype != np.bool_)
def _maxax(h, a, rng):
    ax = _rand_axis(a, rng)
    return ht.max(h, axis=ax), np.max(a, axis=ax)


@op("any_all", lambda a: a.ndim >= 1)
def _any(h, a, rng):
    if rng.integers(0, 2):
        return ht.any(h, axis=0), np.any(a, axis=0)
    return ht.all(h, axis=0), np.all(a, axis=0)


@op("cumsum", lambda a: a.ndim >= 1 and a.dtype != np.bool_)
def _cumsum(h, a, rng):
    ax = _rand_axis(a, rng)
    return ht.cumsum(h, axis=ax), np.cumsum(a, axis=ax)


# ----- manipulations
@op("transpose", lambda a: a.ndim >= 2)
def _transpose(h, a, rng):
    return ht.transpose(h), a.T


@op("flip", lambda a: a.ndim >= 1)
def _flip(h, a, rng):
    ax = _rand_axis(a, rng)
    return ht.flip(h, ax), np.flip(a, ax)


@op("reshape_flat", lambda a: a.ndim >= 1 and a.size > 0)
def _reshape(h, a, rng):
    return ht.reshape(h, (-1,)), a.reshape(-1)


@op("expand_squeeze", lambda a: a.ndim >= 1)
def _expand(h, a, rng):
    ax = int(rng.integers(0, a.ndim + 1))
    return ht.squeeze(ht.expand_dims(h, ax), ax), a


@op("roll", lambda a: a.ndim >= 1)
def _roll(h, a, rng):
    ax = _rand_axis(a, rng)
    k = int(rng.integers(-3, 4))
    return ht.roll(h, k, axis=ax), np.roll(a, k, axis=ax)


@op("sort_values", lambda a: a.ndim >= 1 and a.dtype != np.bool_ and a.shape[-1] > 0)
def _sort(h, a, rng):
    v, _ = ht.sort(h, axis=a.ndim - 1)
    return v, np.sort(a, axis=a.ndim - 1, kind="stable")


@op("concat_self", lambda a: a.ndim >= 1)
def _concat(h, a, rng):
    ax = _rand_axis(a, rng)
    return ht.concatenate([h, h], axis=ax), np.concatenate([a, a], axis=ax)


# ----- indexing
@op("slice_step", lambda a: a.ndim >= 1 and a.shape[0] >= 2)
def _slice(h, a, rng):
    n = a.shape[0]
    start = int(rng.integers(0, n // 2))
    step = int(rng.integers(1, 3))
    return h[start::step], a[start::step]


@op("fancy_rows", lambda a: a.ndim >= 1 and a.shape[0] >= 2)
def _fancy(h, a, rng):
    idx = rng.integers(0, a.shape[0], size=3)
    return h[idx.tolist()], a[idx]


@op("where", lambda a: a.dtype.kind == "f")
def _where(h, a, rng):
    return ht.where(h > 0, h, -h), np.where(a > 0, a, -a)


# ----- round-5 widening: ops whose bugs only surface mid-chain (resplit state,
# pad interactions, index-then-reduce compositions)
@op("resplit", lambda a: a.ndim >= 1)
def _resplit(h, a, rng):
    tgt = [None, *range(a.ndim)][int(rng.integers(0, a.ndim + 1))]
    return ht.resplit(h, tgt), a


@op("pad_const", lambda a: a.ndim >= 1 and a.dtype.kind in "fi")
def _pad(h, a, rng):
    w = tuple((int(rng.integers(0, 2)), int(rng.integers(0, 2))) for _ in range(a.ndim))
    return ht.pad(h, w), np.pad(a, w)


@op("clip_band", lambda a: a.dtype.kind == "f")
def _clip(h, a, rng):
    lo = float(rng.uniform(-2, 0))
    return ht.clip(h, lo, lo + 2.0), np.clip(a, lo, lo + 2.0)


@op("diff", lambda a: a.ndim >= 1 and a.dtype.kind in "fi" and min(a.shape) >= 2)
def _diff(h, a, rng):
    ax = _rand_axis(a, rng)
    return ht.diff(h, axis=ax), np.diff(a, axis=ax)


@op("take_rows", lambda a: a.ndim >= 1 and a.shape[0] >= 2)
def _take(h, a, rng):
    idx = rng.integers(0, a.shape[0], 4).astype(np.int32)
    return ht.take(h, ht.array(idx), axis=0), np.take(a, idx, axis=0)


@op("repeat2", lambda a: a.ndim >= 1 and a.dtype.kind in "fi")
def _repeat(h, a, rng):
    ax = _rand_axis(a, rng)
    return ht.repeat(h, 2, axis=ax), np.repeat(a, 2, axis=ax)


@op("argmax_gather", lambda a: a.ndim >= 1 and a.dtype.kind == "f" and min(a.shape) >= 1)
def _argmax(h, a, rng):
    ax = _rand_axis(a, rng)
    i = ht.argmax(h, axis=ax)
    gathered = np.take_along_axis(
        a, np.expand_dims(i.numpy().astype(np.int64), ax), axis=ax
    ).squeeze(ax)
    return ht.array(gathered), np.max(a, axis=ax)


@op("swapaxes", lambda a: a.ndim >= 2)
def _swap(h, a, rng):
    i = _rand_axis(a, rng)
    j = _rand_axis(a, rng)
    return ht.swapaxes(h, i, j), np.swapaxes(a, i, j)


@op("tril", lambda a: a.ndim >= 2 and a.dtype.kind in "fi")
def _tril(h, a, rng):
    return ht.tril(h), np.tril(a)


@op("nan_guard", lambda a: a.dtype.kind == "f")
def _nanguard(h, a, rng):
    # oracle must mirror the full NaN flow: log(|NaN|)=NaN -> nan_to_num -> 0,
    # exactly like the heat side (a where= mask would leave -inf for NaN input)
    with np.errstate(divide="ignore", invalid="ignore"):
        ref = np.nan_to_num(np.log(np.abs(a)))
    return ht.nan_to_num(ht.log(ht.abs(h))), ref


# ------------------------------------------------------------------ the engine
DTYPES = [np.float32, np.int32, np.bool_]


def _factory(rng):
    ndim = int(rng.integers(1, 4))
    p = ht.WORLD.size
    dims = []
    for _ in range(ndim):
        kind = rng.integers(0, 3)
        if kind == 0:
            dims.append(int(rng.integers(1, 4)) * p)  # even over the mesh
        elif kind == 1:
            dims.append(int(rng.choice([5, 7, 11, 13])))  # ragged prime
        else:
            dims.append(int(rng.integers(1, 9)))
    shape = tuple(dims)
    dtype = DTYPES[int(rng.integers(0, len(DTYPES)))]
    if dtype == np.bool_:
        a = rng.integers(0, 2, size=shape).astype(np.bool_)
    elif dtype == np.int32:
        a = rng.integers(-5, 6, size=shape).astype(np.int32)
    else:
        a = rng.standard_normal(shape).astype(np.float32)
    split = [None, *range(ndim)][int(rng.integers(0, ndim + 1))]
    return ht.array(a.copy(), split=split), a


def _compare(h, a, trace, seed):
    msg = f"fuzz seed={seed}, chain: {' -> '.join(trace)}"
    if isinstance(h, DNDarray):
        assert tuple(h.shape) == tuple(np.shape(a)), f"shape diverged; {msg}"
        if h.split is not None:
            assert 0 <= h.split < max(h.ndim, 1), f"invalid split metadata; {msg}"
        try:
            htt.assert_array_equal(h, np.asarray(a), **TOL)
        except AssertionError as e:
            raise AssertionError(f"{e}\n{msg}") from e
    else:  # scalar extraction
        np.testing.assert_allclose(np.asarray(h), np.asarray(a), err_msg=msg, **TOL)


def run_chain(seed, n_ops=OPS_PER_CHAIN):
    """Run one seeded chain; raises AssertionError with the seed + op trace on
    the first divergence from numpy."""
    rng = np.random.default_rng(seed)
    h, a = _factory(rng)
    trace = [f"factory{a.shape}/{a.dtype}/split={h.split}"]
    _compare(h, a, trace, seed)
    for _ in range(n_ops):
        if not isinstance(h, DNDarray) or h.ndim == 0 or h.size == 0:
            break  # chain collapsed to a scalar/empty; done
        candidates = [(n, fn) for n, ok, fn in OPS if ok(a)]
        name, fn = candidates[int(rng.integers(0, len(candidates)))]
        h, a = fn(h, a, rng)
        trace.append(name)
        _compare(h, a, trace, seed)
    return trace


@pytest.mark.parametrize("seed", range(N_CHAINS))
def test_fuzz_chain(seed):
    run_chain(seed)


def test_chain_is_reproducible():
    t1 = run_chain(12345)
    t2 = run_chain(12345)
    assert t1 == t2


# ------------------------------------------------------------- planted bugs
# The plants prove the HARNESS catches bugs — a property of the harness, not
# of the backend numerics; the CPU-mesh proof covers it without spending
# ~80 tunnel-dispatched chains on the real chip.
pytestmark_plants = pytest.mark.skipif(
    ON_ACCELERATOR, reason="harness-teeth proof runs on the CPU mesh"
)


@pytestmark_plants
def test_planted_numeric_bug_is_caught(monkeypatch):
    """A 1e-3 multiplicative skew in one elementwise op must fail a chain."""
    real_abs = ht.abs

    def bad_abs(x, *args, **kw):
        return real_abs(x, *args, **kw) * 1.001

    monkeypatch.setattr(ht, "abs", bad_abs)
    caught = 0
    for seed in range(40):
        try:
            run_chain(seed)
        except AssertionError:
            caught += 1
    assert caught > 0, "numeric plant survived every chain"


@pytestmark_plants
def test_planted_metadata_bug_is_caught(monkeypatch):
    """An op that lies about its result's split (claims replicated while the
    values are one shard's worth) must fail the placement/shape checks."""
    real_flip = ht.flip

    def bad_flip(x, axis):
        r = real_flip(x, axis)
        if r.split is not None and r.comm.is_distributed():
            # metadata lie: rewrap the PHYSICAL first chunk as the whole array
            chunk = r.parray.shape[r.split] // r.comm.size
            sl = tuple(
                slice(0, chunk) if d == r.split else slice(None) for d in range(r.ndim)
            )
            return DNDarray(
                r.parray[sl], r.shape, r.dtype, None, r.device, r.comm, True
            )
        return r

    monkeypatch.setattr(ht, "flip", bad_flip)
    caught = 0
    for seed in range(40):
        try:
            run_chain(seed)
        except (AssertionError, ValueError, TypeError):
            caught += 1
    assert caught > 0, "metadata plant survived every chain"
