"""
End-to-end smoke: the shipped example scripts and benchmark harnesses run to
completion on the virtual CPU mesh (the reference ships runnable demos +
benchmarks/ as its outermost layer — SURVEY §1 layer 9; the driver exercises
bench.py, this exercises the rest).
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=420):
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "PYTHONPATH")}
    env["PYTHONPATH"] = ROOT
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    r = subprocess.run(
        [sys.executable] + args, cwd=ROOT, env=env,
        capture_output=True, text=True, timeout=timeout,
    )
    assert r.returncode == 0, f"{args}:\n{r.stdout[-2000:]}\n{r.stderr[-2000:]}"
    return r.stdout


def test_cluster_demo_runs():
    _run(["examples/cluster/demo_kclustering.py"])


def test_knn_demo_runs():
    _run(["examples/classification/demo_knn.py"])


def test_lasso_demo_runs():
    _run(["examples/lasso/demo.py"])


@pytest.mark.parametrize(
    "script,extra",
    [
        ("benchmarks/kmeans_bench.py", ["--n", "4096", "--f", "8", "--trials", "1", "--iters", "3"]),
        ("benchmarks/statistical_moments_bench.py", ["--n", "4096", "--f", "8", "--trials", "1"]),
        ("benchmarks/distance_matrix_bench.py", ["--n", "512", "--f", "8", "--trials", "1"]),
        ("benchmarks/lasso_bench.py", ["--n", "2048", "--f", "8", "--trials", "1"]),
        ("benchmarks/allreduce_bandwidth_bench.py", ["--sizes-mb", "1", "--trials", "1"]),
    ],
)
def test_benchmark_scripts_run(script, extra):
    out = _run([script] + extra)
    assert "{" in out  # each prints a JSON line


def test_stencil_demo_runs():
    # halo-exchange stencil demo (the get_halo ppermute machinery end-to-end)
    _run(["examples/stencil/demo_heat_equation.py"])


def test_long_context_demo_runs():
    out = _run(["examples/nn/long_context.py", "--seq", "1024"])
    assert "ring == ulysses" in out
