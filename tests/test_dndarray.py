"""Tests for the DNDarray container (parity model: reference
heat/core/tests/test_dndarray.py)."""

import numpy as np
import pytest

import heat_tpu as ht


def test_properties():
    a = ht.zeros((16, 4), split=0)
    assert a.shape == (16, 4)
    assert a.gshape == (16, 4)
    assert a.ndim == 2
    assert a.size == 64
    assert a.gnumel == 64
    assert a.split == 0
    assert a.balanced
    assert a.is_balanced()
    assert a.dtype is ht.float32
    assert a.itemsize == 4
    assert a.nbytes == 256
    assert len(a) == 16


def test_lshape_map():
    a = ht.zeros((16, 4), split=0)
    m = a.lshape_map
    assert m.shape == (ht.get_comm().size, 2)
    assert m[:, 0].sum() == 16
    counts, displs = a.counts_displs()
    assert sum(counts) == 16
    b = ht.zeros((4,))
    with pytest.raises(ValueError):
        b.counts_displs()


def test_astype():
    a = ht.ones((4,), dtype=ht.float32)
    b = a.astype(ht.int32)
    assert b.dtype is ht.int32
    assert a.dtype is ht.float32
    a.astype(ht.int8, copy=False)
    assert a.dtype is ht.int8


def test_item_scalar_conversions():
    a = ht.full((1,), 5.0)
    assert a.item() == 5.0
    assert int(a) == 5
    assert float(a) == 5.0
    assert bool(a)
    with pytest.raises(ValueError):
        ht.ones((3,)).item()


def test_numpy_tolist_array_protocol():
    a = ht.arange(6, split=0)
    np.testing.assert_array_equal(a.numpy(), np.arange(6))
    assert a.tolist() == list(range(6))
    np.testing.assert_array_equal(np.asarray(a), np.arange(6))


def test_getitem_basic():
    data = np.arange(64.0).reshape(16, 4)
    a = ht.array(data, split=0)
    np.testing.assert_array_equal(a[0].numpy(), data[0])
    np.testing.assert_array_equal(a[2:5].numpy(), data[2:5])
    np.testing.assert_array_equal(a[:, 1].numpy(), data[:, 1])
    np.testing.assert_array_equal(a[3, 2].numpy(), data[3, 2])
    np.testing.assert_array_equal(a[..., -1].numpy(), data[..., -1])
    # split axis untouched -> retained
    assert a[:, 1:3].split == 0
    # split axis sliced -> distribution retained (reference dndarray.py:656-915)
    assert a[2:5].split == 0
    assert a[::2].split == 0
    assert a[::-1].split == 0
    # split axis consumed by an int -> gone
    assert a[3].split is None


def test_getitem_advanced():
    data = np.arange(20).reshape(4, 5)
    a = ht.array(data)
    idx = ht.array([0, 2])
    np.testing.assert_array_equal(a[idx].numpy(), data[[0, 2]])
    mask = data > 10
    np.testing.assert_array_equal(a[ht.array(mask)].numpy(), data[mask])


def test_setitem():
    data = np.zeros((4, 4))
    a = ht.array(data.copy())
    a[1] = 5.0
    data[1] = 5.0
    np.testing.assert_array_equal(a.numpy(), data)
    a[:, 2] = ht.full((4,), 7.0)
    data[:, 2] = 7.0
    np.testing.assert_array_equal(a.numpy(), data)
    a[0, 0] = -1
    data[0, 0] = -1
    np.testing.assert_array_equal(a.numpy(), data)
    mask = data > 4
    a[ht.array(mask)] = 0.0
    data[mask] = 0.0
    np.testing.assert_array_equal(a.numpy(), data)


def test_resplit():
    a = ht.zeros((16, 8), split=0)
    a.resplit_(1)
    assert a.split == 1
    a.resplit_(None)
    assert a.split is None
    b = a.resplit(0)
    assert b.split == 0 and a.split is None
    np.testing.assert_array_equal(b.numpy(), a.numpy())


def test_balance_redistribute_noop():
    a = ht.zeros((10, 3), split=0)  # 10 not divisible by 8: replicated fallback
    a.balance_()
    a.redistribute_()
    assert a.is_balanced()
    with pytest.raises(ValueError):
        a.redistribute_(target_map=np.zeros((8, 2), dtype=int))


def test_halo():
    a = ht.array(np.arange(32.0).reshape(16, 2), split=0)
    a.get_halo(1)
    if ht.get_comm().size > 1:
        # edge shards have one neighbor; a 1-device world has none
        assert a.halo_prev is not None and a.halo_next is not None
    with pytest.raises(TypeError):
        a.get_halo("x")
    with pytest.raises(ValueError):
        a.get_halo(-1)


def test_lloc():
    a = ht.zeros((4, 4))
    a.lloc[0, 0] = 3.0
    assert a.larray[0, 0] == 3.0
    assert float(a.lloc[0, 0]) == 3.0


def test_iter_and_T():
    a = ht.array(np.arange(6.0).reshape(3, 2))
    rows = [r.numpy() for r in a]
    assert len(rows) == 3
    np.testing.assert_array_equal(a.T.numpy(), a.numpy().T)


def test_repr():
    s = repr(ht.ones((2, 2), split=0))
    assert "DNDarray" in s and "float32" in s and "split=0" in s


def test_cpu():
    a = ht.ones((2,), split=0)
    b = a.cpu()
    assert b.device.device_type == "cpu"
    np.testing.assert_array_equal(b.numpy(), a.numpy())


def test_reference_method_surface():
    """Every `DNDarray.<name> = ...` attachment in the reference exists here."""
    x = ht.array(np.linspace(0.1, 0.9, 12).reshape(3, 4).astype(np.float32), split=0)
    # the long-tail method attachments (heat_tpu/__init__.py) actually dispatch
    np.testing.assert_allclose(x.sin().numpy(), np.sin(x.numpy()), rtol=1e-6)
    np.testing.assert_allclose(x.square().numpy(), x.numpy() ** 2, rtol=1e-6)
    np.testing.assert_allclose(float(x.trace()), np.trace(x.numpy()), rtol=1e-5)
    assert x.rot90().shape == (4, 3)
    assert x.swapaxes(0, 1).shape == (4, 3)
    assert bool(x.allclose(x))
    for name in (
        "absolute", "acos", "asin", "atan", "atan2", "balance", "ceil", "conj",
        "cos", "cosh", "exp2", "expm1", "fabs", "floor", "isclose", "kurtosis",
        "log10", "log1p", "log2", "modf", "nonzero", "norm", "redistribute",
        "sinh", "skew", "tan", "tanh", "tril", "triu", "trunc",
    ):
        assert hasattr(ht.DNDarray, name), name


def test_dndarray_api_surface():
    # item/tolist/astype-copy/len/iter/contains-style surface (reference
    # test_dndarray.py API coverage)
    a = ht.arange(6, split=0).astype(ht.float32)
    assert float(a[3].item()) == 3.0
    with pytest.raises((TypeError, ValueError)):
        ht.ones((2, 2)).item()  # not a scalar
    assert a.tolist() == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
    b = a.astype(ht.int32)
    assert b.dtype is ht.int32 and a.dtype is ht.float32
    assert len(a) == 6
    assert [float(v.item()) for v in a] == a.tolist()
    # properties
    assert a.gnumel == 6 and a.nbytes == 24
    assert a.device is not None
    t = a.T if a.ndim == 2 else ht.ones((2, 3), split=0).T
    assert t.shape == (3, 2) and t.split == 1
    # fill_diagonal parity
    m = ht.zeros((4, 4), split=0)
    m.fill_diagonal(5.0)
    np.testing.assert_array_equal(np.diag(m.numpy()), np.full(4, 5.0, np.float32))


def test_scalar_conversions_and_bool_protocol():
    a = ht.array(3.5)
    assert float(a) == 3.5 and int(a) == 3 and bool(a)
    assert complex(a) == 3.5 + 0j
    with pytest.raises((ValueError, TypeError)):
        bool(ht.ones(4))


def test_halo_roundtrip_values():
    p = ht.get_comm().size
    if p < 2:
        pytest.skip("needs a multi-device mesh")
    a = ht.arange(4 * p, split=0).astype(ht.float32)
    a.get_halo(1)
    hn = a.halo_next
    hp = a.halo_prev
    assert hn is not None or hp is not None


def test_dlpack_torch_interchange():
    # the reference exposes torch interop via __torch_proxy__; here the
    # standard DLPack protocol: torch consumes a DNDarray directly
    import torch

    a = ht.arange(6, split=0).astype(ht.float32)
    t = torch.from_dlpack(a)
    assert t.tolist() == [0.0, 1.0, 2.0, 3.0, 4.0, 5.0]
    t2 = torch.from_dlpack(ht.ones((2, 3)))
    assert tuple(t2.shape) == (2, 3)


# ------------------------------------------------- round-4 depth families
# Negative-step slicing, setitem broadcasting/step forms, boolean masks,
# fill_diagonal, and the size/byte properties — the remaining families of
# reference test_dndarray.py (1,572 LoC) not yet pinned above.


@pytest.mark.parametrize("split", [None, 0, 1])
def test_negative_step_slices(split):
    a = np.arange(48, dtype=np.float32).reshape(8, 6)
    h = ht.array(a, split=split)
    for key in (
        (slice(None, None, -1), slice(None)),
        (slice(6, 1, -2), slice(None)),
        (slice(None), slice(None, None, -1)),
        (slice(None, None, -3), slice(None, None, 2)),
    ):
        np.testing.assert_array_equal(h[key].numpy(), a[key])


@pytest.mark.parametrize("split", [None, 0, 1])
def test_setitem_broadcast_and_steps(split):
    a = np.arange(48, dtype=np.float32).reshape(8, 6)
    h = ht.array(a.copy(), split=split)
    h[1:7:2] = 5.0  # scalar broadcast over stepped rows
    a[1:7:2] = 5.0
    np.testing.assert_array_equal(h.numpy(), a)
    h[:, 2] = np.arange(8, dtype=np.float32)  # row vector into a column
    a[:, 2] = np.arange(8, dtype=np.float32)
    np.testing.assert_array_equal(h.numpy(), a)
    h[2] = np.full(6, -1, np.float32)
    a[2] = -1
    np.testing.assert_array_equal(h.numpy(), a)
    assert h.split == split  # metadata survives every mutation


@pytest.mark.parametrize("split", [None, 0])
def test_boolean_mask_getitem(split):
    a = np.arange(20, dtype=np.float32)
    h = ht.array(a, split=split)
    mask = a % 3 == 0
    got = h[ht.array(mask, split=split)]
    np.testing.assert_array_equal(np.sort(got.numpy()), a[mask])


@pytest.mark.parametrize("split", [None, 0, 1])
def test_fill_diagonal(split):
    a = np.arange(30, dtype=np.float32).reshape(6, 5)
    h = ht.array(a.copy(), split=split)
    got = h.fill_diagonal(9.5)
    np.fill_diagonal(a, 9.5)
    np.testing.assert_array_equal(got.numpy(), a)
    assert got.split == split


def test_size_byte_properties():
    h = ht.zeros((6, 4), split=0)
    assert h.size == 24 and h.gnumel == 24
    assert h.gnbytes == 24 * 4
    assert h.nbytes == h.gnbytes
    # lnbytes reports this controller's share of the physical bytes
    assert 0 < h.lnbytes <= h.gnbytes or h.comm.size == 1
    assert h.ndim == 2


@pytest.mark.parametrize("split", [None, 0])
def test_inplace_arithmetic_keeps_metadata(split):
    a = np.arange(12, dtype=np.float32).reshape(6, 2)
    h = ht.array(a.copy(), split=split)
    h += 2
    a += 2
    np.testing.assert_array_equal(h.numpy(), a)
    h *= 3
    a *= 3
    np.testing.assert_array_equal(h.numpy(), a)
    assert h.split == split and h.shape == (6, 2)


def test_comparisons_produce_bool_dndarrays():
    h = ht.arange(10, split=0, dtype=ht.float32)
    for res, exp in (
        (h < 5, np.arange(10) < 5),
        (h >= 7, np.arange(10) >= 7),
        (h == 3, np.arange(10) == 3),
        (h != 3, np.arange(10) != 3),
    ):
        assert res.dtype == ht.bool
        assert res.split == 0
        np.testing.assert_array_equal(res.numpy(), exp)


def test_scalar_conversion_errors_on_nonscalar():
    h = ht.ones((3, 3), split=0)
    with pytest.raises((ValueError, TypeError)):
        float(h)
    with pytest.raises((ValueError, TypeError)):
        int(h)
    with pytest.raises((ValueError, TypeError)):
        h.item()


@pytest.mark.parametrize("split", [None, 0, 1])
def test_resplit_all_pairs(split):
    """resplit_ between every (from, to) split pair keeps values and updates
    placement (the reference's Allgatherv / SplitTiles exchange,
    dndarray.py:1239-1362 — a resharding placement here)."""
    a = np.arange(35, dtype=np.float32).reshape(7, 5)  # ragged both axes
    for target in (None, 0, 1):
        h = ht.array(a, split=split)
        h.resplit_(target)
        assert h.split == target
        np.testing.assert_array_equal(h.numpy(), a)


def test_halo_wider_than_shard_raises_or_clamps():
    p = ht.WORLD.size
    if p < 2:
        pytest.skip("needs a multi-device mesh")
    h = ht.arange(p * 2, split=0, dtype=ht.float32)
    try:
        h.get_halo(3)  # wider than the 2-row shard
    except ValueError:
        return  # explicit rejection is fine (reference raises too)
    assert h.array_with_halos is not None


def test_stride_strides_is_distributed():
    """The last 3 public surface methods (reference dndarray.py:308,315,956):
    torch-like element strides via a.stride(), numpy-like byte strides via
    a.strides, and the split-and-multi-device predicate."""
    a = ht.zeros((4, 6, 5), dtype=ht.float32, split=0)
    assert a.stride() == (30, 5, 1)  # C-order over lshape (== logical shape)
    assert a.strides == (120, 20, 4)  # elements * 4-byte itemsize
    i = ht.zeros((3, 2), dtype=ht.int64)
    assert i.stride() == (2, 1) and i.strides == (16, 8)
    assert ht.zeros(()).stride() == () and ht.zeros(()).strides == ()
    assert a.is_distributed() == (ht.WORLD.size > 1)
    assert not ht.zeros((4, 4), split=None).is_distributed()
