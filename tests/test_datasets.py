"""Bundled datasets + offline ingest tooling (reference heat/datasets/ fixtures and
heat/utils/data/_utils.py merge tooling)."""

import os

import numpy as np
import pytest

import heat_tpu as ht

h5py = pytest.importorskip("h5py")


def test_iris_loaders():
    x = ht.datasets.load_iris(split=0)
    assert x.shape == (150, 4)
    assert x.split == 0
    x2, y = ht.datasets.load_iris(return_labels=True)
    assert y.shape == (150,)
    assert sorted(np.unique(y.numpy())) == [0, 1, 2]


def test_diabetes_loaders():
    x, y = ht.datasets.load_diabetes(split=0, return_target=True)
    assert x.shape == (442, 10)
    assert y.shape == (442,)


def test_materialised_files_roundtrip():
    # iris.h5 through the parallel loader
    path = ht.datasets.path("iris.h5")
    assert os.path.exists(path)
    data = ht.load_hdf5(path, dataset="data", split=0)
    np.testing.assert_allclose(data.numpy(), ht.datasets.load_iris().numpy())

    # diabetes.h5 carries x and y (reference examples/lasso/demo.py:23-24)
    dpath = ht.datasets.path("diabetes.h5")
    with h5py.File(dpath, "r") as f:
        assert f["x"].shape == (442, 10)
        assert f["y"].shape == (442,)

    # csv fixture parses with the csv loader
    cpath = ht.datasets.path("iris.csv")
    csv = ht.load_csv(cpath, sep=";", split=0)
    assert csv.shape == (150, 4)

    # kNN demo fixtures exist and partition 150 rows
    tr = np.loadtxt(ht.datasets.path("iris_X_train.csv"), delimiter=";")
    te = np.loadtxt(ht.datasets.path("iris_X_test.csv"), delimiter=";")
    assert tr.shape[0] + te.shape[0] == 150


def test_merge_npz_to_h5(tmp_path):
    from heat_tpu.utils.data._utils import merge_npz_to_h5

    files = []
    for i in range(3):
        p = tmp_path / f"shard{i}.npz"
        np.savez(p, data=np.full((4, 2), i, np.float32), labels=np.arange(4) + 10 * i)
        files.append(str(p))
    out = merge_npz_to_h5(files, str(tmp_path / "merged.h5"))
    with h5py.File(out, "r") as f:
        assert f["data"].shape == (12, 2)
        np.testing.assert_array_equal(f["data"][4:8], np.full((4, 2), 1, np.float32))
        np.testing.assert_array_equal(f["labels"][8:], np.arange(4) + 20)
    # merged file feeds PartialH5Dataset
    ds = ht.utils.data.PartialH5Dataset(out, dataset_names=["data", "labels"], initial_load=8, load_length=4)
    x, y = ds[0]
    assert x.shape == (2,)
    ds.close()


def test_generate_jobscripts(tmp_path):
    import subprocess
    import sys

    out = tmp_path / "jobs"
    res = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..", "benchmarks", "generate_jobscripts.py"),
         "--out", str(out)],
        capture_output=True, text=True,
    )
    assert res.returncode == 0, res.stderr
    scripts = list(out.glob("*.sh"))
    assert len(scripts) > 10
    body = (out / "kmeans_strong_8dev.sh").read_text()
    assert "--xla_force_host_platform_device_count=8" in body
