"""
Collective-shim EDGE matrix (VERDICT r2 #6): the reference exercises every
collective over dtype × shape × split grids plus error paths in 2,482 LoC of
test_communication.py; this file ports that coverage to the MeshCommunication
shims. Ground truth is numpy chunk math (chunks of the split axis = the
reference's per-rank buffers). The reference's non-blocking I-variants
(Iallgather, Ibcast, …) have no analog to test separately: JAX dispatch is
always asynchronous, so the blocking shim IS the non-blocking one.

Device-count agnostic: runs at any HEAT_TPU_TEST_DEVICES dividing 16.
"""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication, get_comm


@pytest.fixture(scope="module")
def comm() -> MeshCommunication:
    c = get_comm()
    if 16 % c.size != 0:
        pytest.skip(f"chunk ground truth needs a device count dividing 16, got {c.size}")
    return c


RNG = np.random.default_rng(11)

DTYPES = [
    np.float32,
    np.int32,
    np.uint8,
    np.bool_,
]


def _data(shape, dt):
    if dt is np.bool_:
        return RNG.integers(0, 2, size=shape).astype(bool)
    if np.issubdtype(dt, np.integer):
        return RNG.integers(0, 64, size=shape).astype(dt)
    return RNG.standard_normal(shape).astype(dt)


def _chunks(comm, x, split):
    return np.split(x, comm.size, axis=split)


@pytest.mark.parametrize("dt", DTYPES)
@pytest.mark.parametrize("shape,split", [((16, 6), 0), ((6, 16), 1), ((4, 16, 3), 1), ((16,), 0)])
def test_allreduce_matrix(comm, dt, shape, split):
    x = _data(shape, dt)
    chunks = _chunks(comm, x, split)
    got = np.asarray(comm.Allreduce(x, op="sum", split=split))
    # accumulate wide, then wrap to the buffer dtype — MPI SUM on uint8 wraps
    # mod 256 and the psum shim must match
    want = np.add.reduce([c.astype(np.int64 if dt is not np.float32 else dt) for c in chunks])
    if dt is not np.float32 and dt is not np.bool_:
        want = want.astype(dt)
    np.testing.assert_allclose(got.astype(np.float64), want.astype(np.float64), rtol=1e-5)


@pytest.mark.parametrize("op,ref", [("max", np.maximum.reduce), ("min", np.minimum.reduce)])
@pytest.mark.parametrize("dt", [np.float32, np.int32])
def test_allreduce_extrema_matrix(comm, op, ref, dt):
    x = _data((16, 5), dt)
    got = np.asarray(comm.Allreduce(x, op=op, split=0))
    np.testing.assert_array_equal(got, ref(_chunks(comm, x, 0)))


@pytest.mark.parametrize("op", ["land", "lor"])
def test_allreduce_logical_truthiness(comm, op):
    # 256 and 0.5 are logically true — the shim must not lossily cast
    x = np.zeros((16, 3), np.float32)
    x[0] = 256.0
    x[1] = 0.5
    got = np.asarray(comm.Allreduce(x, op=op, split=0))
    chunks = [c != 0 for c in _chunks(comm, x, 0)]
    want = np.logical_and.reduce(chunks) if op == "land" else np.logical_or.reduce(chunks)
    np.testing.assert_array_equal(got.astype(bool), want)


@pytest.mark.parametrize("dt", DTYPES)
@pytest.mark.parametrize("split", [0, 1])
def test_allgather_matrix(comm, dt, split):
    shape = (16, 6) if split == 0 else (6, 16)
    x = _data(shape, dt)
    got = np.asarray(comm.Allgather(x, split=split))
    np.testing.assert_array_equal(got, x)  # gather of the split chunks = the array


@pytest.mark.parametrize("n", [5, 13, 17])
def test_allgatherv_ragged_matrix(comm, n):
    # ragged axes the plain shim rejects — the v-variant must accept
    x = _data((n, 3), np.float32)
    if n % comm.size != 0:
        with pytest.raises(ValueError):
            comm.Allgather(x, split=0)
    got = np.asarray(comm.Allgatherv(x, split=0))
    np.testing.assert_array_equal(got, x)


@pytest.mark.parametrize("dt", [np.float32, np.int32])
@pytest.mark.parametrize("root", [0, -1])
def test_bcast_roots_matrix(comm, dt, root):
    x = _data((16, 4), dt)
    r = root % comm.size
    got = np.asarray(comm.Bcast(x, root=r))
    want = np.concatenate([_chunks(comm, x, 0)[r]] * comm.size, axis=0)
    np.testing.assert_array_equal(got, want)


def test_bcast_bool_restores_dtype(comm):
    x = _data((16, 4), np.bool_)
    got = np.asarray(comm.Bcast(x, root=0))
    assert got.dtype == np.bool_


@pytest.mark.parametrize("op", ["sum", "prod", "max", "min"])
@pytest.mark.parametrize("exclusive", [False, True])
def test_scan_exscan_matrix(comm, op, exclusive):
    x = np.abs(_data((16, 3), np.float32)) * 0.5 + 0.5
    chunks = _chunks(comm, x, 0)
    fn = {"sum": np.add, "prod": np.multiply, "max": np.maximum, "min": np.minimum}[op]
    got = np.asarray((comm.Exscan if exclusive else comm.Scan)(x, op=op, split=0))
    acc = None
    outs = []
    for c in chunks:
        if exclusive:
            if acc is None:
                neutral = {"sum": 0.0, "prod": 1.0, "max": -np.inf, "min": np.inf}[op]
                if op in ("max", "min"):
                    neutral = np.finfo(np.float32).min if op == "max" else np.finfo(np.float32).max
                outs.append(np.full_like(c, neutral))
            else:
                outs.append(acc.copy())
        acc = c if acc is None else fn(acc, c)
        if not exclusive:
            outs.append(acc.copy())
    want = np.concatenate(outs, axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5)


@pytest.mark.parametrize("sa,ca", [(1, 0), (0, 1)])
def test_alltoall_axis_rotations(comm, sa, ca):
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    x = _data((16, 16), np.float32)
    got = np.asarray(comm.Alltoall(x, split_axis=sa, concat_axis=ca))
    # semantic check: re-rotating back restores the array
    back = np.asarray(comm.Alltoall(got, split_axis=ca, concat_axis=sa))
    np.testing.assert_array_equal(back, x)


def test_alltoall_validates(comm):
    x = _data((16, 16), np.float32)
    with pytest.raises(ValueError):
        comm.Alltoall(x, split_axis=0, concat_axis=0)
    with pytest.raises(ValueError):
        comm.Alltoall(np.float32(3.0), split_axis=0, concat_axis=1)


@pytest.mark.parametrize("n", [5, 13])
def test_scatterv_gatherv_ragged_roundtrip(comm, n):
    x = _data((n, 4), np.float32)
    placed = comm.Scatterv(x, split=0)
    back = np.asarray(comm.Gatherv(placed[:n] if hasattr(placed, "shape") else placed, split=0))
    np.testing.assert_array_equal(back[:n], x)


def test_cum_matrix(comm):
    x = _data((16, 4), np.float32)
    np.testing.assert_allclose(
        np.asarray(comm.Cum(x, op="sum", split=0)), np.cumsum(x, axis=0), rtol=1e-4, atol=1e-6
    )
    y = np.abs(x) * 0.1 + 0.95
    np.testing.assert_allclose(
        np.asarray(comm.Cum(y, op="prod", split=0)), np.cumprod(y, axis=0), rtol=1e-3
    )


def test_scalar_and_unknown_op_errors(comm):
    with pytest.raises(ValueError):
        comm.Allreduce(np.float32(1.0))
    with pytest.raises(ValueError):
        comm.Allgatherv(np.float32(1.0))
    with pytest.raises(ValueError):
        comm.Scatterv(np.float32(1.0))
    with pytest.raises(ValueError):
        comm.Allreduce(np.ones(16, np.float32), op="mean")
    with pytest.raises(ValueError):
        comm.Bcast(np.ones(16, np.float32), root=comm.size)
    with pytest.raises(ValueError):
        comm.Cum(np.ones(16, np.float32), op="max")


def test_split_subcommunicators(comm):
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    sub = comm.Split(list(range(comm.size // 2)))
    assert sub.size == comm.size // 2
    x = _data((sub.size * 2, 3), np.float32)
    got = np.asarray(sub.Allreduce(x, op="sum", split=0))
    want = np.add.reduce(np.split(x, sub.size, axis=0))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_barrier_single_controller_noop(comm):
    # Barrier is a process fence: trivially returns under one controller (the
    # multi-controller path is exercised by tests/test_multihost.py)
    comm.Barrier()
