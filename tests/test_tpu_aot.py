"""
AOT real-TPU multi-chip compile proof for every flagship shard_map kernel
(VERDICT r4 next-round #2).

The environment has one physical chip, but the real TPU toolchain can
AOT-compile for arbitrary v5e topologies with no hardware
(`jax.experimental.topologies.get_topology_desc` + `.lower(avals).compile()`)
— the trick test_hlo_contract.py:430 established for the sort exchange. This
module extends it to the remaining flagship kernels, so the *real TPU
partitioner* (not just the CPU-mesh lowering) certifies each kernel's
collective structure and per-device memory:

* det / inv / solve blocked panel elimination (linalg/_elimination.py;
  reference basics.py:160-423)
* TSQR split-0 and BCGS2 split-1 QR (linalg/qr.py; reference qr.py:319-1042)
* ring cdist (spatial/distance.py; reference distance.py:209-494)
* distributed sort, N-D payload (core/_sort.py)
* DASO hierarchical local step + bf16 global sync (optim/dp_optimizer.py;
  reference dp_optimizer.py:432-652)

None of these tests skip on a 1-chip (or 0-chip) host — they only skip when
the TPU AOT compiler itself is absent from the jax install.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# the first TPU-AOT compile in a process pays ~460 s of XLA:TPU compiler
# initialization on this image (measured: a single panel_det[4] test alone
# costs 468 s; each subsequent AOT compile is seconds). The whole module
# therefore lives in the slow/CI selection — the shard_map compat shim made
# these tests runnable at all; tier-1's fixed budget cannot absorb the
# one-time warmup.
pytestmark = pytest.mark.slow


def _topo_mesh(p: int, shape2d=None):
    """1-D (or 2-D) mesh over an AOT v5e topology of ``p`` chips."""
    try:
        from jax.experimental import topologies

        name = {4: "v5e:2x2x1", 8: "v5e:2x4x1", 16: "v5e:4x4x1"}[p]
        topo = topologies.get_topology_desc(platform="tpu", topology_name=name)
    except Exception as e:  # no TPU AOT compiler in this environment
        pytest.skip(f"TPU AOT topology unavailable: {e}")
    devs = np.asarray(topo.devices)
    if shape2d is not None:
        return Mesh(devs.reshape(shape2d), ("node", "local"))
    return Mesh(devs.reshape(p), ("d",))


def _aval(shape, dtype, mesh, spec):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=NamedSharding(mesh, spec))


_AOT_PROBE = []  # memoised: [] unprobed, [None] available, [err] unavailable


def _aot_error():
    """One sentinel compile of a trivial sharded program per session: if THIS
    fails, the TPU AOT toolchain is genuinely absent and tests skip; if it
    succeeds, a failing kernel compile is a real regression and must FAIL,
    not skip (r5 review finding on the r4 catch-all)."""
    if not _AOT_PROBE:
        try:
            # BaseException: _topo_mesh's own pytest.skip (a Skipped outcome)
            # must also be memoised, or every test re-probes the topology
            mesh = _topo_mesh(8)
            aval = _aval((8, 8), jnp.float32, mesh, P("d", None))
            jax.jit(lambda x: x + 1).lower(aval).compile()
            _AOT_PROBE.append(None)
        except (Exception, pytest.skip.Exception) as e:
            # Skipped (from _topo_mesh's pytest.skip) must be memoised too;
            # KeyboardInterrupt/SystemExit still propagate
            _AOT_PROBE.append(f"{type(e).__name__}: {e}")
    return _AOT_PROBE[0]


def _compile(fn, *avals):
    err = _aot_error()
    if err is not None:
        pytest.skip(f"TPU AOT compile unavailable: {err}")
    return fn.lower(*avals).compile()


def _dims_in(text: str):
    """Every tensor dimension mentioned in the HLO's shape literals."""
    return {
        int(d)
        for m in re.finditer(r"[sufbc]\w*\[([0-9,]+)\]", text)
        for d in m.group(1).split(",")
    }


# ---------------------------------------------------------------- linalg panels


@pytest.mark.parametrize("p", [4, 16])
def test_panel_det_aot(p):
    """Blocked panel LU determinant: psum broadcasts of (m, n) panels only —
    the full matrix never assembles on one device (temp stays under ONE copy
    of the matrix at every p; it does not shrink 1/p because the unrolled
    k-loop keeps a few panel temps live per step)."""
    from heat_tpu.core.linalg._elimination import _build_panel_det

    n = 1024
    mesh = _topo_mesh(p)
    fn = _build_panel_det(mesh, "d", p, n // p, "float32")
    comp = _compile(fn, _aval((n, n), jnp.float32, mesh, P("d", None)))
    t = comp.as_text()
    assert "all-reduce" in t  # the one-hot psum broadcast
    temp = comp.memory_analysis().temp_size_in_bytes
    # per-device working set: panel temps, never the full n^2 matrix
    assert temp < n * n * 4, (p, temp)


@pytest.mark.parametrize("p", [4])
def test_panel_inv_aot(p):
    from heat_tpu.core.linalg._elimination import _build_panel_inv

    n = 1024
    mesh = _topo_mesh(p)
    fn = _build_panel_inv(mesh, "d", p, n // p, "float32")
    comp = _compile(fn, _aval((n, n), jnp.float32, mesh, P("d", None)))
    t = comp.as_text()
    assert "all-reduce" in t
    assert "all-gather" not in t, "inv panel path must stay gather-free"
    # inverse panels + refinement residuals are all (n/p, n): a handful of
    # panel-sized temps, never multiple full copies of the matrix
    assert comp.memory_analysis().temp_size_in_bytes < 3 * n * n * 4


@pytest.mark.parametrize("p", [4])
def test_panel_solve_aot(p):
    from heat_tpu.core.linalg._elimination import _build_panel_solve

    n, k = 1024, 16
    mesh = _topo_mesh(p)
    fn = _build_panel_solve(mesh, "d", p, n // p, k, "float32")
    comp = _compile(
        fn,
        _aval((n, n), jnp.float32, mesh, P("d", None)),
        _aval((n, k), jnp.float32, mesh, P("d", None)),
    )
    t = comp.as_text()
    assert "all-reduce" in t
    assert "all-gather" not in t, "solve panel path must stay gather-free"
    assert comp.memory_analysis().temp_size_in_bytes < 3 * n * n * 4


# ------------------------------------------------------------------------- QR


@pytest.mark.parametrize("p", [4, 16])
def test_tsqr_aot(p):
    """TSQR: the ONLY all-gather moves the (n, n) R factors — no shape in the
    compiled program carries the full row count m."""
    from heat_tpu.core.linalg.qr import _build_tsqr

    m, n = 4096, 32
    mesh = _topo_mesh(p)
    fn = _build_tsqr(mesh, "d", p)
    comp = _compile(fn, _aval((m, n), jnp.float32, mesh, P("d", None)))
    t = comp.as_text()
    assert "all-gather" in t  # of the stacked (p, n, n) R factors
    assert m not in _dims_in(t), "full-height tensor in per-device TSQR HLO"
    # per-device: the (m/p, n) panel plus small (p*n, n) stacks
    assert comp.memory_analysis().temp_size_in_bytes < 3 * (m // p) * n * 4 + 4 * p * n * n * 4


@pytest.mark.parametrize("p", [4])
def test_bcgs2_aot(p):
    """Split-1 BCGS2 sweep: panel broadcasts ride psum (all-reduce); no
    all-gather of the column panels; no shape carries the full width n."""
    import sys

    import heat_tpu.core.linalg.qr  # noqa: F401  (ensure the submodule is loaded)

    # the package re-exports the qr FUNCTION under the submodule's name, so
    # `import ... as` would bind the function — fetch the module itself
    qr_mod = sys.modules["heat_tpu.core.linalg.qr"]
    m, n = 2048, 64
    mesh = _topo_mesh(p)
    fn = getattr(qr_mod, "__build_bcgs")(mesh, "d", p, m, n, "float32")
    comp = _compile(fn, _aval((m, n), jnp.float32, mesh, P(None, "d")))
    t = comp.as_text()
    assert "all-reduce" in t
    assert "all-gather" not in t, "BCGS2 must broadcast panels via psum only"
    # per-device column panel (m, n/p) + a few panel temps
    assert comp.memory_analysis().temp_size_in_bytes < 6 * m * (n // p) * 4


# ------------------------------------------------------------------ ring cdist


def _ring_cdist_temp(p):
    from heat_tpu.spatial.distance import _build_ring, _euclidian

    n, f = 4096, 32
    mesh = _topo_mesh(p)
    fn = _build_ring(_euclidian, (), mesh, "d", p)
    comp = _compile(
        fn,
        _aval((n, f), jnp.float32, mesh, P("d", None)),
        _aval((n, f), jnp.float32, mesh, P("d", None)),
    )
    t = comp.as_text()
    assert "collective-permute" in t
    temp = comp.memory_analysis().temp_size_in_bytes
    assert temp < 3 * (n // p) * n * 4, (p, temp)  # row-block of the result, not n^2
    return temp


def test_ring_cdist_aot_memory_scales():
    """Ring cdist: y blocks rotate via collective-permute; the per-device live
    set is the O(n*m/p) row block of the result (never the full (n, n)
    matrix) and SHRINKS as the mesh grows."""
    t4 = _ring_cdist_temp(4)
    t16 = _ring_cdist_temp(16)
    assert t16 < t4, (t4, t16)


# ------------------------------------------------------------------- sort N-D


@pytest.mark.parametrize("p", [4])
def test_sort_nd_aot(p):
    """Distributed sort with an N-D payload (sort axis 0 of an (n, 8) array):
    ring exchange, O(N/p) per-device memory, no full-length dimension."""
    from heat_tpu.core._sort import _build_sort

    n = 1 << 18
    mesh = _topo_mesh(p)
    fn = _build_sort(mesh, "d", p, (n, 8), 0, "<f4", exchange="ring")
    comp = _compile(
        fn, _aval((n, 8), jnp.float32, mesh, P("d", None))
    )
    t = comp.as_text()
    assert "collective-permute" in t
    assert n not in _dims_in(t), "full-length tensor in N-D sort HLO"
    # O(N/p) in ROWS; the narrow R=8 column payload lane-pads to 128 in the
    # scatter buffers (the same 128-lane padding rule the r3
    # ragged_all_to_all investigation documented — see _sort.py), so the
    # byte bound carries a 128/R inflation factor, not an O(N) term
    assert comp.memory_analysis().temp_size_in_bytes < 4 * (n // p) * 128 * 4


# ----------------------------------------------------------------------- DASO


def test_daso_hierarchical_step_aot():
    """DASO local step compiled by the real TPU partitioner for a 2x4 v5e
    (node, local) mesh: gradients all-reduce; the global sync is a separate
    bf16 program. Avals stand in for params (init() would need real buffers)."""
    import optax
    import flax.linen as fnn

    from heat_tpu.core.communication import MeshCommunication
    from heat_tpu.optim.dp_optimizer import DASO

    mesh1d = _topo_mesh(8)
    comm = MeshCommunication(mesh=mesh1d)
    daso = DASO(local_optimizer=optax.sgd(1e-2), total_epochs=2, comm=comm, nodes=2)
    assert daso.nodes == 2 and daso.local_size == 4

    class M(fnn.Module):
        @fnn.compact
        def __call__(self, x):
            return fnn.Dense(2)(x)

    m = M()
    x_aval = jax.ShapeDtypeStruct((16, 4), jnp.float32)
    y_aval = jax.ShapeDtypeStruct((16, 2), jnp.float32)
    p_base = jax.eval_shape(m.init, jax.random.PRNGKey(0), x_aval)
    stack = lambda a: jax.ShapeDtypeStruct((daso.nodes,) + a.shape, a.dtype)
    daso.params = jax.tree.map(stack, p_base)
    s_base = jax.eval_shape(daso.local_optimizer.init, p_base)
    daso.opt_state = jax.tree.map(stack, s_base)

    def mse(p, apply_fn, xx, yy):
        return jnp.mean((apply_fn(p, xx) - yy) ** 2)

    daso.make_train_step(mse, m.apply)
    comp = _compile(daso._local_step, daso.params, daso.opt_state, x_aval, y_aval)
    assert "all-reduce" in comp.as_text()  # local-axis gradient pmean
    gcomp = _compile(daso._global_mean, daso.params)
    tg = gcomp.as_text()
    assert "all-reduce" in tg and "bf16" in tg  # bf16 node sync
