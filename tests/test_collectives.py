"""
Named collective shims on MeshCommunication vs the reference MPI semantics
(chunks of the split axis = per-rank local buffers; reference
heat/core/communication.py:521-1873). Ground truth computed with numpy chunk math.
"""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication, get_comm


@pytest.fixture(scope="module")
def comm() -> MeshCommunication:
    c = get_comm()
    assert c.size == 8, "suite expects the 8-device CPU mesh"
    return c


RNG = np.random.default_rng(3)
X = RNG.standard_normal((16, 6)).astype(np.float32)
CHUNKS = np.split(X, 8, axis=0)


def test_allreduce_ops(comm):
    for op, ref in (
        ("sum", np.add.reduce),
        ("max", np.maximum.reduce),
        ("min", np.minimum.reduce),
        ("prod", lambda c: np.multiply.reduce(c)),
    ):
        got = np.asarray(comm.Allreduce(X, op=op))
        want = ref(np.stack(CHUNKS))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    b = X > 0
    np.testing.assert_array_equal(
        np.asarray(comm.Allreduce(b, op="land")), np.logical_and.reduce(np.split(b, 8))
    )
    np.testing.assert_array_equal(
        np.asarray(comm.Allreduce(b, op="lor")), np.logical_or.reduce(np.split(b, 8))
    )
    # Reduce is the same collective under one controller
    np.testing.assert_allclose(
        np.asarray(comm.Reduce(X, op="sum", root=3)), np.add.reduce(np.stack(CHUNKS))
    )


def test_allgather_variants(comm):
    for fn in (comm.Allgather, comm.Allgatherv, comm.Gather, comm.Gatherv):
        np.testing.assert_array_equal(np.asarray(fn(X)), X)


def test_scatter_places_chunks(comm):
    y = comm.Scatter(X, split=0)
    assert len(y.addressable_shards) == 8
    np.testing.assert_array_equal(np.asarray(y), X)
    shard0 = y.addressable_shards[0]
    assert shard0.data.shape == (2, 6)


def test_bcast_replicates_root_chunk(comm):
    got = np.asarray(comm.Bcast(X, root=3))
    want = np.concatenate([CHUNKS[3]] * 8, axis=0)
    np.testing.assert_array_equal(got, want)


def test_scan_exscan(comm):
    got = np.asarray(comm.Scan(X, op="sum"))
    want = np.concatenate(list(np.cumsum(np.stack(CHUNKS), axis=0)), axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    got_ex = np.asarray(comm.Exscan(X, op="sum"))
    prefix = np.cumsum(np.stack(CHUNKS), axis=0)
    want_ex = np.concatenate([np.zeros_like(CHUNKS[0])] + list(prefix[:-1]), axis=0)
    np.testing.assert_allclose(got_ex, want_ex, rtol=1e-5)
    # max scan
    got_mx = np.asarray(comm.Scan(X, op="max"))
    want_mx = np.concatenate(list(np.maximum.accumulate(np.stack(CHUNKS), axis=0)), axis=0)
    np.testing.assert_array_equal(got_mx, want_mx)


def test_alltoall_resplits_without_changing_values(comm):
    a = RNG.standard_normal((8, 16)).astype(np.float32)
    out = comm.Alltoall(a, split_axis=1, concat_axis=0)
    np.testing.assert_array_equal(np.asarray(out), a)
    # physically sharded on the new axis now
    shard0 = out.addressable_shards[0]
    assert shard0.data.shape == (8, 2)
    np.testing.assert_array_equal(np.asarray(comm.Alltoallv(a, 1, 0)), a)
    with pytest.raises(ValueError):
        comm.Alltoall(a, split_axis=0, concat_axis=0)


def test_ppermute_rotates_chunks(comm):
    got = np.asarray(comm.Ppermute(X, shift=1, split=0))
    want = np.concatenate([CHUNKS[-1]] + CHUNKS[:-1], axis=0)
    np.testing.assert_array_equal(got, want)
    got2 = np.asarray(comm.Ppermute(X, shift=-1, split=0))
    want2 = np.concatenate(CHUNKS[1:] + [CHUNKS[0]], axis=0)
    np.testing.assert_array_equal(got2, want2)


def test_split_subcommunicator(comm):
    sub = comm.Split([0, 1, 2, 3])
    assert sub.size == 4
    y = np.arange(8.0, dtype=np.float32).reshape(8, 1)
    np.testing.assert_allclose(
        np.asarray(sub.Allreduce(y, op="sum")), np.add.reduce(np.split(y, 4))
    )
    # color semantics: two groups of four; group of color of device 0
    sub2 = comm.Split(color=[0, 0, 0, 0, 1, 1, 1, 1])
    assert sub2.size == 4
    with pytest.raises(ValueError):
        comm.Split([])
    with pytest.raises(ValueError):
        comm.Split([0, 1], color=[0] * 8)  # exactly one of devices/color
    with pytest.raises(ValueError):
        comm.Split(color=[0, 1])  # wrong color-list length


def test_collective_errors(comm):
    with pytest.raises(ValueError):
        comm.Allreduce(np.float32(3.0))  # scalar
    with pytest.raises(ValueError):
        comm.Allreduce(np.ones((7, 3), np.float32))  # not evenly partitionable
    with pytest.raises(ValueError):
        comm.Scatter(np.ones(7, np.float32))  # Scatter validates like the others
    with pytest.raises(ValueError):
        comm.Bcast(X, root=8)  # out-of-range root must not silently zero


def test_logical_ops_use_truthiness(comm):
    # 256 wraps to 0 under a uint8 cast and 0.5 truncates to 0 under an int cast;
    # both are logically true
    big = np.full((8, 2), 256, np.int32)
    assert bool(np.all(np.asarray(comm.Allreduce(big, op="land"))))
    halves = np.full((8, 2), 0.5, np.float32)
    assert bool(np.all(np.asarray(comm.Allreduce(halves, op="land"))))


def test_bcast_preserves_dtype(comm):
    b = (X > 0)
    out = comm.Bcast(b, root=2)
    assert np.asarray(out).dtype == np.bool_
    np.testing.assert_array_equal(
        np.asarray(out), np.concatenate([np.split(b, 8)[2]] * 8, axis=0)
    )
