"""
Named collective shims on MeshCommunication vs the reference MPI semantics
(chunks of the split axis = per-rank local buffers; reference
heat/core/communication.py:521-1873). Ground truth computed with numpy chunk math.
Device-count agnostic: runs at any HEAT_TPU_TEST_DEVICES in {1, 2, 4, 8, 16}.
"""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication, get_comm


@pytest.fixture(scope="module")
def comm() -> MeshCommunication:
    c = get_comm()
    if 16 % c.size != 0:
        pytest.skip(f"chunk ground truth needs a device count dividing 16, got {c.size}")
    return c


RNG = np.random.default_rng(3)
X = RNG.standard_normal((16, 6)).astype(np.float32)


def _chunks(comm, x=X):
    return np.split(x, comm.size, axis=0)


def test_allreduce_ops(comm):
    chunks = _chunks(comm)
    for op, ref in (
        ("sum", np.add.reduce),
        ("max", np.maximum.reduce),
        ("min", np.minimum.reduce),
        ("prod", lambda c: np.multiply.reduce(c)),
    ):
        got = np.asarray(comm.Allreduce(X, op=op))
        want = ref(np.stack(chunks))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    b = X > 0
    np.testing.assert_array_equal(
        np.asarray(comm.Allreduce(b, op="land")),
        np.logical_and.reduce(np.split(b, comm.size)),
    )
    np.testing.assert_array_equal(
        np.asarray(comm.Allreduce(b, op="lor")),
        np.logical_or.reduce(np.split(b, comm.size)),
    )
    # Reduce is the same collective under one controller
    np.testing.assert_allclose(
        np.asarray(comm.Reduce(X, op="sum", root=0)), np.add.reduce(np.stack(chunks))
    )


def test_allgather_variants(comm):
    for fn in (comm.Allgather, comm.Allgatherv, comm.Gather, comm.Gatherv):
        np.testing.assert_array_equal(np.asarray(fn(X)), X)


def test_scatter_places_chunks(comm):
    y = comm.Scatter(X, split=0)
    assert len(y.addressable_shards) == comm.size
    np.testing.assert_array_equal(np.asarray(y), X)
    shard0 = y.addressable_shards[0]
    assert shard0.data.shape == (16 // comm.size, 6)


def test_bcast_replicates_root_chunk(comm):
    root = comm.size - 1
    got = np.asarray(comm.Bcast(X, root=root))
    want = np.concatenate([_chunks(comm)[root]] * comm.size, axis=0)
    np.testing.assert_array_equal(got, want)


def test_scan_exscan(comm):
    chunks = np.stack(_chunks(comm))
    got = np.asarray(comm.Scan(X, op="sum"))
    want = np.concatenate(list(np.cumsum(chunks, axis=0)), axis=0)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    got_ex = np.asarray(comm.Exscan(X, op="sum"))
    prefix = np.cumsum(chunks, axis=0)
    want_ex = np.concatenate([np.zeros_like(chunks[0])] + list(prefix[:-1]), axis=0)
    np.testing.assert_allclose(got_ex, want_ex, rtol=1e-5)
    got_mx = np.asarray(comm.Scan(X, op="max"))
    want_mx = np.concatenate(list(np.maximum.accumulate(chunks, axis=0)), axis=0)
    np.testing.assert_array_equal(got_mx, want_mx)


def test_alltoall_resplits_without_changing_values(comm):
    a = RNG.standard_normal((16, 16)).astype(np.float32)
    out = comm.Alltoall(a, split_axis=1, concat_axis=0)
    np.testing.assert_array_equal(np.asarray(out), a)
    shard0 = out.addressable_shards[0]
    assert shard0.data.shape == (16, 16 // comm.size)
    np.testing.assert_array_equal(np.asarray(comm.Alltoallv(a, 1, 0)), a)
    with pytest.raises(ValueError):
        comm.Alltoall(a, split_axis=0, concat_axis=0)


def test_ppermute_rotates_chunks(comm):
    chunks = _chunks(comm)
    got = np.asarray(comm.Ppermute(X, shift=1, split=0))
    want = np.concatenate([chunks[-1]] + chunks[:-1], axis=0)
    np.testing.assert_array_equal(got, want)
    got2 = np.asarray(comm.Ppermute(X, shift=-1, split=0))
    want2 = np.concatenate(chunks[1:] + [chunks[0]], axis=0)
    np.testing.assert_array_equal(got2, want2)


def test_split_subcommunicator(comm):
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    half = comm.size // 2
    sub = comm.Split(list(range(half)))
    assert sub.size == half
    y = np.arange(16.0, dtype=np.float32).reshape(16, 1)
    np.testing.assert_allclose(
        np.asarray(sub.Allreduce(y, op="sum")), np.add.reduce(np.split(y, half))
    )
    # color semantics: two groups; group of the color of device 0
    colors = [0] * half + [1] * (comm.size - half)
    sub2 = comm.Split(color=colors)
    assert sub2.size == half
    with pytest.raises(ValueError):
        comm.Split([])
    with pytest.raises(ValueError):
        comm.Split([0], color=colors)  # exactly one of devices/color
    with pytest.raises(ValueError):
        comm.Split(color=[0])  # wrong color-list length


def test_collective_errors(comm):
    with pytest.raises(ValueError):
        comm.Allreduce(np.float32(3.0))  # scalar
    if comm.size > 1:
        ragged = np.ones((comm.size + 1, 3), np.float32)
        with pytest.raises(ValueError):
            comm.Allreduce(ragged)  # not evenly partitionable
        with pytest.raises(ValueError):
            comm.Scatter(np.ones(comm.size + 1, np.float32))
    with pytest.raises(ValueError):
        comm.Bcast(X, root=comm.size)  # out-of-range root must not silently zero


def test_logical_ops_use_truthiness(comm):
    # 256 wraps to 0 under a uint8 cast and 0.5 truncates to 0 under an int cast;
    # both are logically true
    big = np.full((16, 2), 256, np.int32)
    assert bool(np.all(np.asarray(comm.Allreduce(big, op="land"))))
    halves = np.full((16, 2), 0.5, np.float32)
    assert bool(np.all(np.asarray(comm.Allreduce(halves, op="land"))))


def test_bcast_preserves_dtype(comm):
    b = X > 0
    out = comm.Bcast(b, root=0)
    assert np.asarray(out).dtype == np.bool_
    np.testing.assert_array_equal(
        np.asarray(out),
        np.concatenate([np.split(b, comm.size)[0]] * comm.size, axis=0),
    )


def test_split_validates_indices(comm):
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    with pytest.raises(ValueError):
        comm.Split([0, 0])  # duplicates
    with pytest.raises(ValueError):
        comm.Split([0, comm.size])  # out of range
    with pytest.raises(ValueError):
        comm.Split([0, -1])  # negatives don't silently wrap


def test_unknown_op_raises_value_error(comm):
    with pytest.raises(ValueError):
        comm.Allreduce(X, op="avg")
    with pytest.raises(ValueError):
        comm.Scan(X, op="Sum")


def test_cum_shim(comm):
    # Cum = element-wise cumulative ALONG the split axis, result stays sharded
    # (local cum + block-total exscan + combine; reference _operations.py:185-281)
    got = np.asarray(comm.Cum(X, op="sum", split=0))
    np.testing.assert_allclose(got, np.cumsum(X, axis=0), rtol=1e-5, atol=1e-5)
    x1 = np.abs(X[:, : comm.size].T.copy()) * 0.5 + 0.75
    got = np.asarray(comm.Cum(x1, op="prod", split=1))
    np.testing.assert_allclose(got, np.cumprod(x1, axis=1), rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError):
        comm.Cum(X, op="max")
