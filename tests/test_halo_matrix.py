"""
Halo-exchange matrix: sizes, splits, dtypes, and the stacked per-device view —
the reference's get_halo Isend/Irecv pairs (dndarray.py:360-473) as one
compiled ppermute program, validated value-exactly against the logical
neighborhood (extends tests/test_halo.py with the stacked view and dtypes).
"""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.communication import get_comm


def _comm_or_skip():
    comm = get_comm()
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    return comm


@pytest.mark.parametrize("halo", [1, 2])
@pytest.mark.parametrize("dt", [ht.float32, ht.int32])
def test_stacked_view_matrix(halo, dt):
    comm = _comm_or_skip()
    p = comm.size
    c = 4  # rows per device
    a_np = np.arange(p * c * 3).reshape(p * c, 3)
    a = ht.array(a_np, split=0, dtype=dt)
    a.get_halo(halo)
    st = np.asarray(a.array_with_halos)
    # per device: [prev-halo | chunk | next-halo]; edges zero-filled
    assert st.shape == (p, c + 2 * halo, 3)
    for r in range(p):
        chunk = a_np[r * c : (r + 1) * c]
        np.testing.assert_array_equal(st[r, halo : halo + c], chunk)
        if r > 0:
            np.testing.assert_array_equal(st[r, :halo], a_np[r * c - halo : r * c])
        else:
            assert (st[r, :halo] == 0).all()
        if r < p - 1:
            np.testing.assert_array_equal(
                st[r, halo + c :], a_np[(r + 1) * c : (r + 1) * c + halo]
            )
        else:
            assert (st[r, halo + c :] == 0).all()


def test_halo_bfloat16():
    comm = _comm_or_skip()
    p = comm.size
    a = ht.ones((4 * p, 2), split=0, dtype=ht.bfloat16)
    a.get_halo(1)
    hp = np.asarray(a.halo_prev).astype(np.float32)
    assert hp.shape == (p, 2)
    assert (hp[1:] == 1.0).all() and (hp[0] == 0.0).all()


def test_halo_invalidated_by_mutation():
    comm = _comm_or_skip()
    a = ht.arange(4 * comm.size, split=0).astype(ht.float32)
    a.get_halo(1)
    assert a.halo_prev is not None
    a[0] = 99.0  # mutation must drop the stale halos
    assert a.halo_prev is None and a.halo_next is None


def test_halo_size_validation():
    comm = _comm_or_skip()
    a = ht.arange(4 * comm.size, split=0).astype(ht.float32)
    with pytest.raises((ValueError, TypeError)):
        a.get_halo(-1)
    with pytest.raises((ValueError, TypeError)):
        a.get_halo("two")
