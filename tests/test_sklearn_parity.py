"""
External-oracle parity: the ML estimators against scikit-learn / scipy on
identical data (both are baked into the environment). This is a stronger
check than the reference's own ML tests (which assert convergence and
hand-computed values, reference heat/cluster/tests + naive_bayes/tests):
algorithmic output is pinned to an independent production implementation.
"""

import numpy as np
import pytest

import heat_tpu as ht


def _blobs(n=240, f=5, k=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=6.0, size=(k, f)).astype(np.float32)
    labels = rng.integers(0, k, size=n)
    x = centers[labels] + rng.normal(scale=0.4, size=(n, f)).astype(np.float32)
    return x.astype(np.float32), labels, centers


def test_kmeans_matches_sklearn_lloyd():
    """Same explicit init + Lloyd iterations -> same centroids/assignment
    (Lloyd is deterministic given the init)."""
    from sklearn.cluster import KMeans as SkKMeans

    x, _, centers = _blobs()
    init = x[:3].copy()
    sk = SkKMeans(n_clusters=3, init=init, n_init=1, max_iter=50, tol=1e-6, algorithm="lloyd").fit(
        x.astype(np.float64)
    )
    km = ht.cluster.KMeans(n_clusters=3, init=ht.array(init), max_iter=50, tol=1e-6).fit(
        ht.array(x, split=0)
    )
    got = np.asarray(km.cluster_centers_.numpy(), np.float64)
    # centroid sets match up to permutation
    from scipy.spatial.distance import cdist as sp_cdist

    d = sp_cdist(got, sk.cluster_centers_)
    assert d.min(axis=1).max() < 1e-2, d
    # labels agree up to the same permutation
    perm = d.argmin(axis=1)
    ht_labels = np.asarray(km.predict(ht.array(x, split=0)).numpy()).ravel()
    np.testing.assert_array_equal(perm[ht_labels], sk.predict(x.astype(np.float64)))


def test_gaussian_nb_matches_sklearn():
    from sklearn.naive_bayes import GaussianNB as SkNB

    x, y, _ = _blobs(seed=1)
    xt, yt = x[:200], y[:200]
    xq = x[200:]
    sk = SkNB().fit(xt.astype(np.float64), yt)
    nb = ht.naive_bayes.GaussianNB().fit(ht.array(xt, split=0), ht.array(yt.astype(np.int32), split=0))
    np.testing.assert_allclose(np.asarray(nb.theta_.numpy()), sk.theta_, rtol=1e-4, atol=1e-5)
    # the reference (heat 1.1.1 era, sklearn <1.0 naming) calls it sigma_
    np.testing.assert_allclose(np.asarray(nb.sigma_.numpy()), sk.var_, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(nb.class_prior_.numpy()), sk.class_prior_, rtol=1e-6
    )
    got = np.asarray(nb.predict(ht.array(xq, split=0)).numpy()).ravel()
    np.testing.assert_array_equal(got, sk.predict(xq.astype(np.float64)))


def test_knn_matches_sklearn():
    from sklearn.neighbors import KNeighborsClassifier as SkKNN

    x, y, _ = _blobs(seed=2)
    xt, yt = x[:200], y[:200]
    xq = x[200:]
    sk = SkKNN(n_neighbors=5).fit(xt.astype(np.float64), yt)
    knn = ht.classification.KNeighborsClassifier(n_neighbors=5)
    knn.fit(ht.array(xt, split=0), ht.array(yt.astype(np.int32), split=0))
    got = np.asarray(knn.predict(ht.array(xq, split=0)).numpy()).ravel()
    sk_pred = sk.predict(xq.astype(np.float64))
    # k-NN votes can tie; demand >= 97% agreement rather than bitwise equality
    assert (got == sk_pred).mean() >= 0.97


def test_cdist_matches_scipy():
    from scipy.spatial.distance import cdist as sp_cdist

    rng = np.random.default_rng(3)
    a = rng.standard_normal((40, 6)).astype(np.float32)
    b = rng.standard_normal((25, 6)).astype(np.float32)
    ha, hb = ht.array(a, split=0), ht.array(b)
    np.testing.assert_allclose(
        ht.spatial.cdist(ha, hb).numpy(), sp_cdist(a, b), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(
        ht.spatial.manhattan(ha, hb).numpy(), sp_cdist(a, b, metric="cityblock"),
        rtol=1e-4, atol=1e-4,
    )


def test_laplacian_matches_sklearn_rbf_graph():
    """Fully-connected RBF similarity graph Laplacian vs the direct formula on
    sklearn's rbf_kernel."""
    from sklearn.metrics.pairwise import rbf_kernel

    rng = np.random.default_rng(4)
    x = rng.standard_normal((30, 4)).astype(np.float32)
    sigma = 1.7
    lap = ht.graph.Laplacian(
        lambda a: ht.spatial.rbf(a, sigma=sigma), definition="simple", mode="fully_connected"
    )
    got = np.asarray(lap.construct(ht.array(x, split=0)).numpy(), np.float64)
    # rbf(x) uses exp(-d^2 / (2 sigma^2)); sklearn's gamma = 1/(2 sigma^2)
    s = rbf_kernel(x.astype(np.float64), gamma=1.0 / (2 * sigma**2))
    np.fill_diagonal(s, 0.0)  # no self-loops in the graph form
    expected = np.diag(s.sum(axis=1)) - s
    np.testing.assert_allclose(got, expected, rtol=1e-3, atol=1e-3)


def test_lasso_matches_sklearn_direction():
    """Coordinate-descent Lasso: sparsity pattern and signs match sklearn's at
    matched regularization (objective scalings differ by convention, so the
    support/sign structure — what Lasso is FOR — is the invariant checked)."""
    from sklearn.linear_model import Lasso as SkLasso

    rng = np.random.default_rng(5)
    n, f = 120, 8
    x = rng.standard_normal((n, f)).astype(np.float32)
    true_w = np.zeros(f, np.float32)
    true_w[[1, 4]] = [2.5, -3.0]
    y = x @ true_w + 0.01 * rng.standard_normal(n).astype(np.float32)
    sk = SkLasso(alpha=0.1, fit_intercept=True).fit(x.astype(np.float64), y)
    las = ht.regression.Lasso(lam=0.1, max_iter=200)
    las.fit(ht.array(x, split=0), ht.array(y.reshape(-1, 1), split=0))
    got = np.asarray(las.coef_.numpy()).ravel()
    sk_w = sk.coef_
    on = np.abs(sk_w) > 1e-3
    assert (np.abs(got[on]) > 1e-3).all(), (got, sk_w)
    assert (np.sign(got[on]) == np.sign(sk_w[on])).all()
    # the true zeros stay (near) zero
    off = ~on
    assert (np.abs(got[off]) < 0.5).all(), (got, sk_w)
