"""Tests for array creation (parity model: reference heat/core/tests/test_factories.py)."""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import _compat
import heat_tpu.testing as htt

SPLITS = [None, 0, 1]


def test_array_basic():
    a = ht.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.split is None
    np.testing.assert_array_equal(a.numpy(), [[1, 2], [3, 4]])


@pytest.mark.parametrize("split", [None, 0])
def test_array_split(split):
    data = np.arange(32.0).reshape(16, 2)
    a = ht.array(data, split=split)
    assert a.split == split
    assert a.shape == (16, 2)
    # public helper: checks per-shard placement, not just the gathered values
    htt.assert_array_equal(a, data)


def test_array_is_split():
    data = np.arange(8.0)
    a = ht.array(data, is_split=0)
    assert a.split == 0
    htt.assert_array_equal(a, data)


def test_array_dtype_ndmin():
    a = ht.array([1, 2, 3], dtype=ht.float32, ndmin=3)
    assert a.dtype is ht.float32
    assert a.shape == (1, 1, 3)
    with pytest.raises(ValueError):
        ht.array([1], order="X")
    with pytest.raises(ValueError):
        ht.array([1], split=0, is_split=0)


def test_asarray_passthrough():
    a = ht.ones((3,))
    assert ht.asarray(a) is a


def test_arange():
    np.testing.assert_array_equal(ht.arange(10).numpy(), np.arange(10))
    np.testing.assert_array_equal(ht.arange(2, 10).numpy(), np.arange(2, 10))
    np.testing.assert_array_equal(ht.arange(2, 10, 3).numpy(), np.arange(2, 10, 3))
    a = ht.arange(16, split=0)
    assert a.split == 0
    with pytest.raises(TypeError):
        ht.arange()


def test_linspace_logspace():
    np.testing.assert_allclose(ht.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6)
    arr, step = ht.linspace(0, 10, 11, retstep=True)
    assert step == 1.0
    np.testing.assert_allclose(
        ht.logspace(0, 2, 4).numpy(), np.logspace(0, 2, 4).astype(np.float32), rtol=1e-5
    )
    # num == 0 is a valid empty result (numpy semantics); negative raises
    assert ht.linspace(0, 1, 0).shape == (0,)
    with pytest.raises(ValueError):
        ht.linspace(0, 1, -1)


def test_linspace_retstep_numpy_exact():
    # step must match np.linspace exactly across the degenerate edges:
    # nan for num=0 (both endpoints) and num=1 with endpoint=True; delta for
    # num=1 with endpoint=False (the old (stop-start)/max(1, num-endpoint)
    # formula returned delta for all of these — see PARITY.md history)
    for num in (0, 1, 2, 7):
        for ep in (True, False):
            n_val, n_step = np.linspace(2.0, 10.0, num=num, endpoint=ep, retstep=True)
            h_val, h_step = ht.linspace(2.0, 10.0, num=num, endpoint=ep, retstep=True)
            assert (np.isnan(n_step) and np.isnan(h_step)) or n_step == h_step, (num, ep)
            np.testing.assert_allclose(h_val.numpy(), n_val.astype(np.float32), rtol=1e-6)


@pytest.mark.parametrize("split", [None, 0])
def test_logspace_num_edges(split):
    # logspace inherits linspace's empty/one-point edges through its build
    for num in (0, 1, 5):
        n_val = np.logspace(0.0, 3.0, num=num)
        h = ht.logspace(0.0, 3.0, num=num, split=split)
        assert h.shape == (num,)
        np.testing.assert_allclose(h.numpy(), n_val.astype(np.float32), rtol=1e-5)


@pytest.mark.parametrize("split", [None, 0])
def test_eye(split):
    e = ht.eye(6, split=split)
    np.testing.assert_array_equal(e.numpy(), np.eye(6, dtype=np.float32))
    e2 = ht.eye((4, 6))
    assert e2.shape == (4, 6)


@pytest.mark.parametrize("split", [None, 0, 1])
def test_zeros_ones_full(split):
    shape = (8, 4)
    z = ht.zeros(shape, split=split)
    o = ht.ones(shape, split=split)
    f = ht.full(shape, 7.0, split=split)
    np.testing.assert_array_equal(z.numpy(), np.zeros(shape))
    np.testing.assert_array_equal(o.numpy(), np.ones(shape))
    np.testing.assert_array_equal(f.numpy(), np.full(shape, 7.0))
    assert z.split == split and o.split == split and f.split == split


def test_like_factories():
    a = ht.ones((4, 4), dtype=ht.int32, split=0)
    z = ht.zeros_like(a)
    assert z.shape == a.shape and z.dtype is a.dtype and z.split == a.split
    o = ht.ones_like(a, dtype=ht.float32)
    assert o.dtype is ht.float32
    f = ht.full_like(a, 3)
    assert (f.numpy() == 3).all()
    e = ht.empty_like(a)
    assert e.shape == a.shape


def test_empty():
    import jax

    # f64 runs under real x64 — no silent truncation on the default suite
    with _compat.enable_x64(True):
        e = ht.empty((2, 3), dtype=ht.float64)
        assert e.shape == (2, 3)
        assert e.larray.dtype == np.float64
    e32 = ht.empty((4,), dtype=ht.float32)
    assert e32.shape == (4,)


def test_meshgrid():
    x = ht.arange(3)
    y = ht.arange(4, split=0)
    xx, yy = ht.meshgrid(x, y)
    nx, ny = np.meshgrid(np.arange(3), np.arange(4))
    np.testing.assert_array_equal(xx.numpy(), nx)
    np.testing.assert_array_equal(yy.numpy(), ny)
    assert ht.meshgrid() == []
    with pytest.raises(ValueError):
        ht.meshgrid(x, indexing="ab")


def test_linspace_endpoint_pinned_distributed():
    # ADVICE r2: the distributed affine path could miss `stop` by float
    # rounding at i = num-1; it must now pin the endpoint exactly, matching
    # the replicated jnp.linspace path
    import numpy as np

    for num in (7, 13, 50):
        x = ht.linspace(0.1, 0.7, num, split=0)
        assert float(x[-1].numpy()) == np.float32(0.7), (num, float(x[-1].numpy()))
        y = ht.linspace(0.1, 0.7, num)  # replicated path
        np.testing.assert_allclose(x.numpy(), y.numpy(), rtol=2e-7, atol=2e-7)
    # endpoint=False unchanged: stop excluded
    z = ht.linspace(0.0, 1.0, 8, endpoint=False, split=0)
    assert float(z[-1].numpy()) < 1.0


def test_arange_dtype_inference_grid():
    for args, want in [
        ((5,), np.int32),
        ((0.0, 5.0, 1.0), np.float32),
        ((0, 10, 2), np.int32),
    ]:
        a = ht.arange(*args)
        assert np.dtype(a.dtype.char()) == want, (args, a.dtype)
        np.testing.assert_array_equal(a.numpy(), np.arange(*args).astype(want))
    for split in (None, 0):
        a = ht.arange(17, split=split, dtype=ht.float32)
        np.testing.assert_array_equal(a.numpy(), np.arange(17, dtype=np.float32))
    with pytest.raises(ValueError):
        ht.arange(0, 10, 0)


def test_eye_rectangular_and_split_grid():
    for shape in (5, (3, 7), (7, 3)):
        for split in (None, 0, 1):
            if isinstance(shape, int) and split == 1:
                continue
            e = ht.eye(shape, split=split)
            n, m = (shape, shape) if isinstance(shape, int) else (
                (shape[0], shape[0]) if len(shape) == 1 else shape
            )
            np.testing.assert_array_equal(e.numpy(), np.eye(n, m, dtype=np.float32))


def test_like_family_and_meshgrid():
    a = ht.array(np.arange(12.0, dtype=np.float32).reshape(3, 4), split=0)
    for fn, val in [(ht.zeros_like, 0.0), (ht.ones_like, 1.0)]:
        r = fn(a)
        assert r.shape == a.shape and r.split == a.split
        assert float(r.numpy().ravel()[0]) == val
    f = ht.full_like(a, 7.5)
    assert (f.numpy() == 7.5).all()
    e = ht.empty_like(a)
    assert e.shape == a.shape
    xs, ys = ht.meshgrid(ht.arange(3), ht.arange(4))
    nx, ny = np.meshgrid(np.arange(3), np.arange(4))
    np.testing.assert_array_equal(xs.numpy(), nx)
    np.testing.assert_array_equal(ys.numpy(), ny)


def test_logspace_geomspace_grid():
    np.testing.assert_allclose(
        ht.logspace(0, 3, 7, split=0).numpy(), np.logspace(0, 3, 7), rtol=1e-4
    )
    np.testing.assert_allclose(
        ht.logspace(0, 3, 7, base=2.0).numpy(), np.logspace(0, 3, 7, base=2.0), rtol=1e-4
    )
    if hasattr(ht, "geomspace"):
        np.testing.assert_allclose(
            ht.geomspace(1.0, 256.0, 9).numpy(), np.geomspace(1.0, 256.0, 9), rtol=1e-4
        )


def test_asarray_copy_semantics():
    a_np = np.arange(4.0, dtype=np.float32)
    a = ht.asarray(a_np)
    assert a.shape == (4,)
    b = ht.array(a)  # wrapping a DNDarray
    np.testing.assert_array_equal(b.numpy(), a_np)
    c = ht.array([[True, False], [False, True]])
    assert c.dtype is ht.bool
    d = ht.array(np.arange(4), dtype=ht.float32, split=0)
    assert d.dtype is ht.float32


def test_half_dtype_sharded_factories():
    # regression (r3): sharded builders keyed dtypes via np.dtype(...).str,
    # which mangles bfloat16 to raw-void '|V2' and broke every distributed
    # bf16/f16 factory; keys are canonical dtype NAMES now
    p = ht.get_comm().size
    for dt in (ht.bfloat16, ht.float16):
        a = ht.ones((4 * p, 2), split=0, dtype=dt)
        assert a.dtype is dt
        assert float(np.asarray(a.numpy()).astype(np.float32).sum()) == 8.0 * p
        z = ht.zeros((4 * p,), split=0, dtype=dt)
        assert float(np.asarray(z.numpy()).astype(np.float32).sum()) == 0.0
        f = ht.full((4 * p,), 2.0, split=0, dtype=dt)
        assert float(np.asarray(f.numpy()).astype(np.float32)[0]) == 2.0
        r = ht.arange(4 * p, split=0, dtype=dt)
        assert r.dtype is dt
