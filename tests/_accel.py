"""
Shared real-accelerator test policy.

On the CPU mesh (default) everything matches numpy/libm tightly. On a real
accelerator (``HEAT_TPU_TEST_REAL_DEVICE=1``) two hardware realities apply
(documented in doc/performance.md):

- VPU transcendentals are fast polynomial approximations (≤ ~2.2e-4 relative
  on v5e) → :func:`tol` widens the comparison for those ops;
- some backends have no complex-dtype support (TPU v5e) → tests exercising
  complex64/128 guard with :data:`requires_complex`.
"""

import os

import jax
import pytest

ON_ACCELERATOR = jax.default_backend() != "cpu"

TRANSCENDENTAL_RTOL = 5e-4

# includes numpy ufunc spellings ("power", "arctan2") since callers key by
# np_op.__name__ as well as by the ht-op label
TRANSCENDENTALS = frozenset(
    {"exp", "expm1", "exp2", "log", "log2", "log10", "log1p", "sqrt",
     "sin", "cos", "tan", "sinh", "cosh", "tanh",
     "arcsin", "arccos", "arctan", "arcsinh", "arccosh", "arctanh",
     "asin", "acos", "atan", "asinh", "acosh", "atanh",  # torch-alias spellings
     "logaddexp", "logaddexp2", "atan2", "arctan2", "pow", "power"}
)


def tol(name, rtol=2e-5, atol=1e-6):
    """Comparison tolerance for op ``name``: the accelerator transcendental
    relaxation when it applies, the given defaults otherwise."""
    if ON_ACCELERATOR and name in TRANSCENDENTALS:
        return dict(rtol=TRANSCENDENTAL_RTOL, atol=1e-5)
    return dict(rtol=rtol, atol=atol)


# TPUs have no complex-dtype support; probing with a live complex op is not safe
# (a failed complex lowering can poison the whole backend for the process — and on
# deferred-execution runtimes the probe's try/except never even sees the failure).
# Static rule scoped to TPU-family backends (GPU supports complex and keeps
# coverage), overridable via HEAT_TPU_TEST_COMPLEX=1:
COMPLEX_SUPPORTED = (
    jax.default_backend() not in ("tpu", "axon")
    or os.environ.get("HEAT_TPU_TEST_COMPLEX") == "1"
)

requires_complex = pytest.mark.skipif(
    not COMPLEX_SUPPORTED, reason="backend has no complex-dtype support (e.g. TPU v5e)"
)


# TPU-family chips have no native f64: under x64 they run software-emulated
# doubles whose ulp behavior differs from IEEE and whose linalg custom calls
# (LU) have no f64 lowering at all. GPU f64 is native — scope the skip to the
# TPU family exactly like COMPLEX_SUPPORTED above, so GPU keeps x64 coverage.
NATIVE_F64 = jax.default_backend() not in ("tpu", "axon")

requires_native_f64 = pytest.mark.skipif(
    not NATIVE_F64, reason="TPU-family f64 is emulated (no native doubles/f64 LU)"
)
