"""Tests for the mesh communication substrate (parity model: reference
heat/core/tests/test_communication.py chunk checks :23-40)."""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication, WORLD, get_comm, sanitize_comm, use_comm


def test_world_size():
    import jax

    assert WORLD.size == len(jax.devices())
    assert WORLD.rank == 0
    assert WORLD.is_distributed() == (WORLD.size > 1)


@pytest.mark.parametrize("n", [8, 10, 17, 64, 3])
def test_chunk_partition(n):
    shape = (n, 5)
    total = 0
    prev_end = 0
    for r in range(WORLD.size):
        offset, lshape, slices = WORLD.chunk(shape, 0, rank=r)
        assert offset == prev_end
        assert lshape[1] == 5
        total += lshape[0]
        prev_end = offset + lshape[0]
        assert slices[0] == slice(offset, offset + lshape[0])
        assert slices[1] == slice(None)
    assert total == n
    # sizes differ by at most one, larger chunks first
    sizes = [WORLD.chunk(shape, 0, rank=r)[1][0] for r in range(WORLD.size)]
    assert max(sizes) - min(sizes) <= 1
    assert sizes == sorted(sizes, reverse=True)


def test_chunk_none_split():
    offset, lshape, slices = WORLD.chunk((4, 4), None)
    assert offset == 0
    assert lshape == (4, 4)
    assert slices == (slice(None), slice(None))


def test_counts_displs():
    counts, displs = WORLD.counts_displs((20, 3), 0)
    assert sum(counts) == 20
    assert displs[0] == 0
    assert all(displs[i + 1] == displs[i] + counts[i] for i in range(len(counts) - 1))


def test_lshape_map():
    m = WORLD.lshape_map((16, 4), 0)
    assert m.shape == (WORLD.size, 2)
    assert m[:, 0].sum() == 16
    assert (m[:, 1] == 4).all()


def test_is_shardable():
    assert WORLD.is_shardable((WORLD.size * 2, 4), 0)
    assert not WORLD.is_shardable((WORLD.size + 1, 4), 0) or WORLD.size == 1
    assert WORLD.is_shardable((10, 4), None)


def test_shard_places_data():
    import jax.numpy as jnp

    n = WORLD.size * 2
    x = jnp.arange(float(n))
    xs = WORLD.shard(x, 0)
    shard_shapes = sorted(s.data.shape for s in xs.addressable_shards)
    assert shard_shapes == [(2,)] * WORLD.size


def test_sanitize_use_comm():
    assert sanitize_comm(None) is get_comm()
    assert sanitize_comm(WORLD) is WORLD
    with pytest.raises(TypeError):
        sanitize_comm("nope")
    use_comm(WORLD)
    assert get_comm() is WORLD


def test_mpi_world_alias():
    assert ht.MPI_WORLD is ht.WORLD


def test_lshape_map_matches_padded_physical_layout():
    # ADVICE r2: lshape_map must agree with the padded physical shards
    # (ceil(n/p) per device, clamped; tail devices may own 0 rows), not the
    # reference's remainder-spread — code mixing it with addressable_shards
    # sees consistent extents
    p = WORLD.size
    for n in (13, 16, 5, p + 1):
        c = -(-n // p)
        expect = [max(0, min(c, n - r * c)) for r in range(p)]
        m = WORLD.lshape_map((n, 3), 0)
        assert m[:, 0].tolist() == expect, (n, m[:, 0].tolist(), expect)
        assert (m[:, 1] == 3).all()
        counts, displs = WORLD.counts_displs((n, 3), 0)
        assert list(counts) == expect
        assert all(displs[r] == min(r * c, n) for r in range(p))


def test_lshape_map_consistent_with_shards():
    import heat_tpu as ht

    a = ht.zeros((13, 3), split=0)
    m = a.lshape_map
    assert m[:, 0].sum() == 13
    if hasattr(a.parray, "addressable_shards") and WORLD.is_distributed():
        # physical shards are all ceil(13/p) rows; owned logical rows are the
        # clamped extents lshape_map reports
        c = -(-13 // WORLD.size)
        for sh in a.parray.addressable_shards:
            assert sh.data.shape[0] == c
