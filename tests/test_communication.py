"""Tests for the mesh communication substrate (parity model: reference
heat/core/tests/test_communication.py chunk checks :23-40)."""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication, WORLD, get_comm, sanitize_comm, use_comm


def test_world_size():
    import jax

    assert WORLD.size == len(jax.devices())
    assert WORLD.rank == 0
    assert WORLD.is_distributed() == (WORLD.size > 1)


@pytest.mark.parametrize("n", [8, 10, 17, 64, 3])
def test_chunk_partition(n):
    shape = (n, 5)
    total = 0
    prev_end = 0
    for r in range(WORLD.size):
        offset, lshape, slices = WORLD.chunk(shape, 0, rank=r)
        assert offset == prev_end
        assert lshape[1] == 5
        total += lshape[0]
        prev_end = offset + lshape[0]
        assert slices[0] == slice(offset, offset + lshape[0])
        assert slices[1] == slice(None)
    assert total == n
    # sizes differ by at most one, larger chunks first
    sizes = [WORLD.chunk(shape, 0, rank=r)[1][0] for r in range(WORLD.size)]
    assert max(sizes) - min(sizes) <= 1
    assert sizes == sorted(sizes, reverse=True)


def test_chunk_none_split():
    offset, lshape, slices = WORLD.chunk((4, 4), None)
    assert offset == 0
    assert lshape == (4, 4)
    assert slices == (slice(None), slice(None))


def test_counts_displs():
    counts, displs = WORLD.counts_displs((20, 3), 0)
    assert sum(counts) == 20
    assert displs[0] == 0
    assert all(displs[i + 1] == displs[i] + counts[i] for i in range(len(counts) - 1))


def test_lshape_map():
    m = WORLD.lshape_map((16, 4), 0)
    assert m.shape == (WORLD.size, 2)
    assert m[:, 0].sum() == 16
    assert (m[:, 1] == 4).all()


def test_is_shardable():
    assert WORLD.is_shardable((WORLD.size * 2, 4), 0)
    assert not WORLD.is_shardable((WORLD.size + 1, 4), 0) or WORLD.size == 1
    assert WORLD.is_shardable((10, 4), None)


def test_shard_places_data():
    import jax.numpy as jnp

    n = WORLD.size * 2
    x = jnp.arange(float(n))
    xs = WORLD.shard(x, 0)
    shard_shapes = sorted(s.data.shape for s in xs.addressable_shards)
    assert shard_shapes == [(2,)] * WORLD.size


def test_sanitize_use_comm():
    assert sanitize_comm(None) is get_comm()
    assert sanitize_comm(WORLD) is WORLD
    with pytest.raises(TypeError):
        sanitize_comm("nope")
    use_comm(WORLD)
    assert get_comm() is WORLD


def test_mpi_world_alias():
    assert ht.MPI_WORLD is ht.WORLD


def test_lshape_map_matches_padded_physical_layout():
    # ADVICE r2: lshape_map must agree with the padded physical shards
    # (ceil(n/p) per device, clamped; tail devices may own 0 rows), not the
    # reference's remainder-spread — code mixing it with addressable_shards
    # sees consistent extents
    p = WORLD.size
    for n in (13, 16, 5, p + 1):
        c = -(-n // p)
        expect = [max(0, min(c, n - r * c)) for r in range(p)]
        m = WORLD.lshape_map((n, 3), 0)
        assert m[:, 0].tolist() == expect, (n, m[:, 0].tolist(), expect)
        assert (m[:, 1] == 3).all()
        counts, displs = WORLD.counts_displs((n, 3), 0)
        assert list(counts) == expect
        assert all(displs[r] == min(r * c, n) for r in range(p))


def test_lshape_map_consistent_with_shards():
    import heat_tpu as ht

    a = ht.zeros((13, 3), split=0)
    m = a.lshape_map
    assert m[:, 0].sum() == 13
    if hasattr(a.parray, "addressable_shards") and WORLD.is_distributed():
        # physical shards are all ceil(13/p) rows; owned logical rows are the
        # clamped extents lshape_map reports
        c = -(-13 // WORLD.size)
        for sh in a.parray.addressable_shards:
            assert sh.data.shape[0] == c


# ---------------------------------------------------------- chunk math families
# Reference test_communication.py:23-120 sweeps chunk offsets over dims and
# splits; these families pin the same arithmetic for every axis and rank.


@pytest.mark.parametrize("shape", [(12,), (7, 9), (4, 10, 6), (5, 3, 8, 2)])
def test_chunk_every_axis_partitions(shape):
    """chunk() covers [0, n) exactly once on every split axis of 1-D..4-D
    shapes, with non-split extents untouched."""
    for split in range(len(shape)):
        prev_end = 0
        for r in range(WORLD.size):
            offset, lshape, slices = WORLD.chunk(shape, split, rank=r)
            assert offset == prev_end
            prev_end = offset + lshape[split]
            for d in range(len(shape)):
                if d == split:
                    assert slices[d] == slice(offset, offset + lshape[d])
                else:
                    assert lshape[d] == shape[d]
                    assert slices[d] == slice(None)
        assert prev_end == shape[split]


@pytest.mark.parametrize("split", [-1, -2])
def test_chunk_negative_split(split):
    shape = (6, 8)
    pos = split % len(shape)
    for r in range(WORLD.size):
        assert WORLD.chunk(shape, split, rank=r) == WORLD.chunk(shape, pos, rank=r)


def test_chunk_default_rank_is_zero():
    shape = (WORLD.size * 3 + 1, 2)
    assert WORLD.chunk(shape, 0) == WORLD.chunk(shape, 0, rank=0)


def test_chunk_reference_remainder_spread():
    """chunk() keeps the REFERENCE layout: the first n % p ranks carry one
    extra row (reference communication.py:161-210) — deliberately different
    from the padded-physical counts_displs/lshape_map geometry (see
    PARITY.md layout-divergence note)."""
    p = WORLD.size
    n = 2 * p + max(1, p - 1)  # remainder of p-1 (or 1 for p == 1)
    rem = n % p
    sizes = [WORLD.chunk((n,), 0, rank=r)[1][0] for r in range(p)]
    assert all(s == n // p + 1 for s in sizes[:rem])
    assert all(s == n // p for s in sizes[rem:])


def test_chunk_vs_counts_displs_divergence_documented():
    """The two deliberately different geometries for the same array (ADVICE
    r3): chunk = remainder-spread, counts_displs = padded ceil(n/p) with a
    clamped tail. Pin both so neither silently drifts into the other."""
    p = WORLD.size
    if p < 2:
        pytest.skip("identical layouts on one device")
    n = p + 1  # maximal divergence: chunk spreads, padded clamps the tail
    chunk_sizes = [WORLD.chunk((n,), 0, rank=r)[1][0] for r in range(p)]
    counts, _ = WORLD.counts_displs((n,), 0)
    c = -(-n // p)
    assert chunk_sizes == [2] + [1] * (p - 1)
    assert list(counts) == [max(0, min(c, n - r * c)) for r in range(p)]
    assert sum(chunk_sizes) == sum(counts) == n


@pytest.mark.parametrize("shape,split", [((20, 3), 0), ((3, 20), 1), ((4, 5, 6), 2)])
def test_counts_displs_properties(shape, split):
    counts, displs = WORLD.counts_displs(shape, split)
    assert len(counts) == len(displs) == WORLD.size
    assert sum(counts) == shape[split]
    assert displs[0] == 0
    assert all(c >= 0 for c in counts)
    assert all(
        displs[i + 1] == displs[i] + counts[i] for i in range(len(counts) - 1)
    )


def test_counts_displs_zero_tail():
    """Axes shorter than the mesh: padded layout gives the tail devices zero
    logical rows — counts must say so (the zero-count v-collective edge)."""
    p = WORLD.size
    if p < 2:
        pytest.skip("needs a multi-device mesh")
    counts, displs = WORLD.counts_displs((1, 4), 0)
    assert counts[0] == 1 and all(c == 0 for c in counts[1:])
    assert all(d == 1 for d in displs[1:])


def test_spec_and_sharding_shapes():
    from jax.sharding import PartitionSpec

    for ndim in (1, 2, 3):
        assert WORLD.spec(ndim, None) == PartitionSpec()
        for split in range(ndim):
            s = WORLD.spec(ndim, split)
            assert len([a for a in s if a is not None]) == 1
            assert s[split] is not None
    sh = WORLD.sharding(2, 0)
    assert sh.mesh.devices.size == WORLD.size


def test_barrier_single_controller_noop():
    # single controller: must return immediately (multi-controller behavior is
    # exercised in tests/test_multihost.py)
    WORLD.Barrier()


def test_split_by_color_groups():
    p = WORLD.size
    if p < 4 or p % 2:
        pytest.skip("needs an even mesh of >= 4 devices")
    # alternating colors: device 0's color selects the even slots
    sub = WORLD.Split(color=[i % 2 for i in range(p)])
    assert sub.size == p // 2
    import jax.numpy as jnp

    out = sub.Allreduce(jnp.ones((sub.size, 2)), op="sum")
    assert out.shape == (1, 2)
    assert float(out[0, 0]) == sub.size


def test_counts_displs_shape_reference_math():
    """Reference-name alias (heat/core/communication.py:211-240): remainder-
    spread counts (NOT the padded physical placement of counts_displs),
    cumsum displacements, and the all-equal-inputs receive shape."""
    comm = ht.WORLD
    p = comm.size
    shape = (p * 3 + 1, 7)  # ragged along axis 0
    counts, displs, out_shape = comm.counts_displs_shape(shape, 0)
    assert len(counts) == p and sum(counts) == shape[0]
    assert max(counts) - min(counts) <= 1  # remainder-spread, first ranks +1
    assert counts[0] == 4 if p > 1 else counts[0] == shape[0]
    assert displs == tuple(sum(counts[:r]) for r in range(p))
    assert out_shape == (p * counts[comm.rank], 7)
    # explicit-rank receive shape
    _, _, tail_shape = comm.counts_displs_shape(shape, 0, rank=p - 1)
    assert tail_shape == (p * counts[p - 1], 7)
