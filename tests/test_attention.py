"""Ring / Ulysses sequence-parallel attention vs the dense reference, on the forced
8-device CPU mesh (SURVEY §5 long-context: the ring `_dist` pattern generalized)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication
from heat_tpu.nn import ring_attention, scaled_dot_product_attention, ulysses_attention


@pytest.fixture(scope="module")
def comm():
    return MeshCommunication()


def _qkv(b=2, s=64, h=8, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(comm, causal):
    q, k, v = _qkv()
    want = scaled_dot_product_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, comm=comm, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(comm, causal):
    q, k, v = _qkv()
    want = scaled_dot_product_attention(q, k, v, causal=causal)
    got = ulysses_attention(q, k, v, comm=comm, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


def test_ring_jit_grad(comm):
    """The ring is differentiable and jittable end-to-end (training usable)."""
    q, k, v = _qkv(s=32, h=4, d=8)

    def loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, comm=comm, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss))(q, k, v)
    g_dense = jax.grad(
        lambda q, k, v: jnp.sum(scaled_dot_product_attention(q, k, v, causal=True) ** 2)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), rtol=1e-3, atol=1e-4)


def test_dndarray_frontend(comm):
    q, k, v = _qkv(s=32)
    hq = ht.array(np.asarray(q), split=1)
    hk = ht.array(np.asarray(k), split=1)
    hv = ht.array(np.asarray(v), split=1)
    want = scaled_dot_product_attention(q, k, v, causal=True)
    out = ring_attention(hq, hk, hv, causal=True)
    assert out.split == 1 and out.shape == tuple(q.shape)
    np.testing.assert_allclose(out.numpy(), np.asarray(want), rtol=2e-4, atol=2e-5)
    out2 = ulysses_attention(hq, hk, hv, causal=True)
    np.testing.assert_allclose(out2.numpy(), np.asarray(want), rtol=2e-4, atol=2e-5)


def test_uneven_seq_falls_back(comm):
    q, k, v = _qkv(s=33)  # 33 not divisible by 8 -> dense fallback
    want = scaled_dot_product_attention(q, k, v)
    got = ring_attention(q, k, v, comm=comm)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


def test_sdpa_impl_flag():
    """impl='auto' falls back to dense off-TPU; explicit impl='dense' matches."""
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 64, 2, 16)), jnp.float32) for _ in range(3))
    auto = scaled_dot_product_attention(q, k, v, causal=True)
    dense = scaled_dot_product_attention(q, k, v, causal=True, impl="dense")
    np.testing.assert_allclose(np.asarray(auto), np.asarray(dense), atol=1e-6)


def test_flash_matches_dense_on_tpu():
    """On a real TPU the pallas flash path must agree with the dense formulation
    (and it is the only path that compiles at very long sequence lengths — the
    capability win recorded in doc/performance.md)."""
    import jax as _jax

    if _jax.default_backend() != "tpu" or _jax.device_count() != 1:
        pytest.skip("needs a single real TPU device")
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, 256, 4, 128)).astype(np.float32), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((1, 256, 4, 128)).astype(np.float32), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, 256, 4, 128)).astype(np.float32), jnp.bfloat16)
    dense = scaled_dot_product_attention(q, k, v, causal=True, impl="dense")
    flash = scaled_dot_product_attention(q, k, v, causal=True, impl="flash")
    np.testing.assert_allclose(
        np.asarray(dense, np.float32), np.asarray(flash, np.float32), atol=2e-2
    )


@pytest.mark.parametrize("heads,dim", [(1, 16), (4, 8)])
def test_ring_attention_shape_grid(comm, heads, dim):
    # head-count x head-dim grid, both causal modes, vs the dense reference
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    p = comm.size
    seq = 4 * p
    rng = np.random.default_rng(71)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, seq, heads, dim)).astype(np.float32) * 0.3)
        for _ in range(3)
    )
    for causal in (False, True):
        dense = scaled_dot_product_attention(q, k, v, causal=causal)
        ring = ht.nn.ring_attention(q, k, v, comm=comm, causal=causal)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=2e-4, atol=2e-4)


def test_attention_scale_override(comm):
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    p = comm.size
    rng = np.random.default_rng(72)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 4 * p, 2, 8)).astype(np.float32) * 0.3)
        for _ in range(3)
    )
    s1 = ht.nn.ring_attention(q, k, v, comm=comm, scale=1.0)
    s2 = ht.nn.ring_attention(q, k, v, comm=comm, scale=0.125)
    assert not np.allclose(np.asarray(s1), np.asarray(s2))
    d2 = scaled_dot_product_attention(q, k, v, causal=False, scale=0.125)
    np.testing.assert_allclose(np.asarray(s2), np.asarray(d2), rtol=2e-4, atol=2e-4)


def test_attention_numerical_stability_large_logits(comm):
    # the online-softmax running max must survive +-40 logits without overflow
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    p = comm.size
    rng = np.random.default_rng(73)
    q = jnp.asarray(rng.normal(size=(1, 2 * p, 1, 8)).astype(np.float32) * 20.0)
    k = jnp.asarray(rng.normal(size=(1, 2 * p, 1, 8)).astype(np.float32) * 20.0)
    v = jnp.asarray(rng.normal(size=(1, 2 * p, 1, 8)).astype(np.float32))
    out = np.asarray(ht.nn.ring_attention(q, k, v, comm=comm))
    assert np.isfinite(out).all()
    dense = np.asarray(scaled_dot_product_attention(q, k, v, causal=False))
    np.testing.assert_allclose(out, dense, rtol=1e-3, atol=1e-3)


def test_flash_impl_off_tpu_raises_clear_error():
    """ISSUE 10 satellite: impl='flash' off-TPU used to die inside the
    jax.experimental.pallas TPU kernel import/lowering — it must name the
    platform requirement instead."""
    if jax.default_backend() == "tpu":
        pytest.skip("flash impl is legitimate on a TPU backend")
    q, k, v = _qkv(b=1, s=32, h=2, d=8)
    with pytest.raises(ValueError, match="TPU backend"):
        scaled_dot_product_attention(q, k, v, impl="flash")
