"""Tests for manipulations (parity model: reference
heat/core/tests/test_manipulations.py)."""

import numpy as np
import pytest

import heat_tpu as ht
import heat_tpu.testing as htt

SPLITS = [None, 0, 1]


def _arr(split=0, shape=(8, 4)):
    a = np.arange(np.prod(shape), dtype=np.float32).reshape(shape)
    return ht.array(a, split=split), a


def test_manipulations_func_equal_matrix():
    """Public heat_tpu.testing sweep: shape manipulations over every split and
    the x64-aware dtype matrix, with per-shard placement checks."""
    htt.assert_func_equal((6, 4), lambda x: ht.flip(x, 0), lambda x: np.flip(x, 0))
    htt.assert_func_equal((3, 5), lambda x: ht.ravel(x), np.ravel)
    htt.assert_func_equal(
        (4, 6), lambda x: ht.reshape(x, (8, 3)), lambda x: np.reshape(x, (8, 3))
    )


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("axis", [0, 1])
def test_concatenate(split, axis):
    h1, a1 = _arr(split)
    h2, a2 = _arr(split)
    res = ht.concatenate([h1, h2], axis=axis)
    np.testing.assert_array_equal(res.numpy(), np.concatenate([a1, a2], axis=axis))
    assert res.split == split
    with pytest.raises(TypeError):
        ht.concatenate([])


def test_stack_hstack_vstack_dstack_analogs():
    h, a = _arr(None, (4, 3))
    np.testing.assert_array_equal(ht.stack([h, h], axis=0).numpy(), np.stack([a, a]))
    np.testing.assert_array_equal(ht.stack([h, h], axis=2).numpy(), np.stack([a, a], axis=2))
    np.testing.assert_array_equal(ht.hstack([h, h]).numpy(), np.hstack([a, a]))
    np.testing.assert_array_equal(ht.vstack([h, h]).numpy(), np.vstack([a, a]))
    np.testing.assert_array_equal(ht.column_stack([h, h]).numpy(), np.column_stack([a, a]))
    np.testing.assert_array_equal(ht.row_stack([h, h]).numpy(), np.row_stack([a, a]))
    v = ht.arange(3)
    np.testing.assert_array_equal(ht.hstack([v, v]).numpy(), np.hstack([np.arange(3)] * 2))
    with pytest.raises(ValueError):
        ht.stack([h, ht.ones((2, 2))])


@pytest.mark.parametrize("split", [None, 0])
def test_reshape_ravel_flatten(split):
    h, a = _arr(split, (8, 4))
    np.testing.assert_array_equal(ht.reshape(h, (4, 8)).numpy(), a.reshape(4, 8))
    np.testing.assert_array_equal(ht.reshape(h, 32).numpy(), a.reshape(32))
    np.testing.assert_array_equal(ht.reshape(h, (-1, 2)).numpy(), a.reshape(-1, 2))
    np.testing.assert_array_equal(ht.flatten(h).numpy(), a.flatten())
    np.testing.assert_array_equal(ht.ravel(h).numpy(), a.ravel())
    assert ht.reshape(h, (4, 8), new_split=1).split == 1
    with pytest.raises(ValueError):
        ht.reshape(h, (-1, -1))


def test_sort_topk():
    rng = np.random.default_rng(3)
    a = rng.normal(size=(8, 6)).astype(np.float32)
    h = ht.array(a, split=0)
    v, i = ht.sort(h, axis=1)
    np.testing.assert_array_equal(v.numpy(), np.sort(a, axis=1))
    np.testing.assert_array_equal(i.numpy(), np.argsort(a, axis=1, kind="stable"))
    vd, _ = ht.sort(h, axis=0, descending=True)
    np.testing.assert_array_equal(vd.numpy(), -np.sort(-a, axis=0))
    tv, ti = ht.topk(h, 3, dim=1)
    np.testing.assert_array_equal(tv.numpy(), -np.sort(-a, axis=1)[:, :3])
    sv, si = ht.topk(h, 2, dim=1, largest=False)
    np.testing.assert_array_equal(sv.numpy(), np.sort(a, axis=1)[:, :2])


def test_unique():
    a = np.array([3, 1, 3, 2, 2, 7, 1, 0])
    h = ht.array(a, split=0)
    np.testing.assert_array_equal(ht.unique(h).numpy(), np.unique(a))
    vals, inv = ht.unique(h, return_inverse=True)
    nv, ni = np.unique(a, return_inverse=True)
    np.testing.assert_array_equal(vals.numpy(), nv)
    np.testing.assert_array_equal(inv.numpy().reshape(-1), ni)


def test_pad_roll_flip():
    h, a = _arr(0, (8, 4))
    np.testing.assert_array_equal(
        ht.pad(h, ((1, 1), (0, 2))).numpy(), np.pad(a, ((1, 1), (0, 2)))
    )
    np.testing.assert_array_equal(ht.roll(h, 2, axis=0).numpy(), np.roll(a, 2, axis=0))
    np.testing.assert_array_equal(ht.roll(h, -1).numpy(), np.roll(a, -1))
    np.testing.assert_array_equal(ht.flip(h, 0).numpy(), np.flip(a, 0))
    np.testing.assert_array_equal(ht.flipud(h).numpy(), np.flipud(a))
    np.testing.assert_array_equal(ht.fliplr(h).numpy(), np.fliplr(a))
    with pytest.raises(IndexError):
        ht.fliplr(ht.arange(3))


def test_squeeze_expand_dims_broadcast_to():
    h = ht.ones((1, 8, 1, 4), split=1)
    s = ht.squeeze(h)
    assert s.shape == (8, 4)
    assert s.split == 0
    e = ht.expand_dims(ht.arange(8, split=0), 0)
    assert e.shape == (1, 8)
    assert e.split == 1
    b = ht.broadcast_to(ht.arange(4), (3, 4))
    assert b.shape == (3, 4)


def test_diag_diagonal():
    h, a = _arr(None, (4, 4))
    np.testing.assert_array_equal(ht.diag(h).numpy(), np.diag(a))
    np.testing.assert_array_equal(ht.diagonal(h, offset=1).numpy(), np.diagonal(a, offset=1))
    v = ht.arange(3)
    np.testing.assert_array_equal(ht.diag(v).numpy(), np.diag(np.arange(3)))
    with pytest.raises(ValueError):
        ht.diag(ht.ones((2, 2, 2)))


def test_split_family():
    h, a = _arr(None, (8, 4))
    parts = ht.split(h, 4, axis=0)
    assert len(parts) == 4
    np.testing.assert_array_equal(parts[0].numpy(), a[:2])
    hs = ht.hsplit(h, 2)
    np.testing.assert_array_equal(hs[1].numpy(), a[:, 2:])
    vs = ht.vsplit(h, 2)
    np.testing.assert_array_equal(vs[1].numpy(), a[4:])
    d = ht.ones((2, 2, 4))
    ds = ht.dsplit(d, 2)
    assert ds[0].shape == (2, 2, 2)
    with pytest.raises(ValueError):
        ht.split(h, 3, axis=0)


def test_moveaxis_swapaxes_rot90_tile_repeat():
    h, a = _arr(0, (8, 4))
    np.testing.assert_array_equal(ht.moveaxis(h, 0, 1).numpy(), np.moveaxis(a, 0, 1))
    sw = ht.swapaxes(h, 0, 1)
    np.testing.assert_array_equal(sw.numpy(), np.swapaxes(a, 0, 1))
    assert sw.split == 1
    np.testing.assert_array_equal(ht.rot90(h).numpy(), np.rot90(a))
    np.testing.assert_array_equal(ht.tile(h, (2, 1)).numpy(), np.tile(a, (2, 1)))
    np.testing.assert_array_equal(ht.repeat(h, 2, axis=1).numpy(), np.repeat(a, 2, axis=1))
    np.testing.assert_array_equal(ht.repeat(h, 2).numpy(), np.repeat(a, 2))


def test_resplit_redistribute_balance_shape():
    h, a = _arr(0, (16, 4))
    r = ht.resplit(h, 1)
    assert r.split == 1 and h.split == 0
    rr = ht.redistribute(h)
    np.testing.assert_array_equal(rr.numpy(), a)
    assert ht.balance(h) is h
    assert ht.manipulations.shape(h) == (16, 4) if hasattr(ht, "manipulations") else True
    from heat_tpu.core.manipulations import shape as _shape

    assert _shape(h) == (16, 4)


def test_diagonal_batch_split_remap():
    a = ht.ones((2, 3, 8), split=2)
    d = ht.diagonal(a, dim1=0, dim2=1)  # batch axis 2 survives, shifts to 0
    assert d.split == 0
    assert d.shape == (8, 2)


def test_sort_nd_along_split():
    # VERDICT r2 #3a: N-D sorts along the split axis take the exact-rank
    # distributed path (divisible + ragged, both split positions, descending)
    rng = np.random.default_rng(7)
    for shape, split in [((16, 5), 0), ((13, 5), 0), ((5, 16), 1), ((5, 13), 1), ((4, 13, 3), 1)]:
        a_np = rng.normal(size=shape).astype(np.float32)
        a = ht.array(a_np, split=split)
        v, i = ht.sort(a, axis=split)
        np.testing.assert_array_equal(v.numpy(), np.sort(a_np, axis=split))
        np.testing.assert_array_equal(
            np.take_along_axis(a_np, i.numpy(), axis=split), np.sort(a_np, axis=split)
        )
        assert v.split == split
        vd, _ = ht.sort(a, axis=split, descending=True)
        np.testing.assert_array_equal(vd.numpy(), -np.sort(-a_np, axis=split))


def test_sort_8byte_dtypes_x64_subprocess():
    # VERDICT r2 #3b: f64/i64 sorts stay distributed under x64 (u64 key
    # transform); x64 must be configured before backend init -> subprocess
    import os
    import subprocess
    import sys

    code = """
import numpy as np
import heat_tpu as ht
rng = np.random.default_rng(1)
a_np = rng.normal(size=(13, 4))
a = ht.array(a_np, split=0)
assert a.dtype is ht.float64
v, i = ht.sort(a, axis=0)
np.testing.assert_array_equal(v.numpy(), np.sort(a_np, axis=0))
b_np = rng.integers(-2**40, 2**40, size=16)
b = ht.array(b_np, split=0)
v, i = ht.sort(b, axis=0)
np.testing.assert_array_equal(v.numpy(), np.sort(b_np))
from heat_tpu.core._sort import can_distribute_sort
assert can_distribute_sort(a, 0) and can_distribute_sort(b, 0)
print('OK')
"""
    env = dict(
        os.environ,
        PYTHONPATH="",
        JAX_ENABLE_X64="1",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
    )
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True, text=True, timeout=300
    )
    assert out.returncode == 0 and "OK" in out.stdout, out.stdout + out.stderr


def test_topk_distributed_along_split():
    rng = np.random.default_rng(8)
    for shape, split in [((24, 5), 0), ((26, 3), 0), ((5, 24), 1)]:
        a_np = rng.normal(size=shape).astype(np.float32)
        a = ht.array(a_np, split=split)
        for k in (1, 2):
            for largest in (True, False):
                v, i = ht.topk(a, k, dim=split, largest=largest)
                sign = -1 if largest else 1
                e_idx = np.take(
                    np.argsort(sign * a_np, axis=split, kind="stable"), range(k), axis=split
                )
                e_val = np.take_along_axis(a_np, e_idx, axis=split)
                np.testing.assert_array_equal(v.numpy(), e_val)
                np.testing.assert_array_equal(
                    np.take_along_axis(a_np, i.numpy(), axis=split), e_val
                )
    # tie-breaking matches torch: lowest global index wins
    b_np = np.array([5, 1, 5, 3, 5, 2, 5, 0, 5, 4, 5, 9, 5, 7, 5, 8], np.int32)
    b = ht.array(b_np, split=0)
    v, i = ht.topk(b, 3, dim=0)
    assert v.numpy().tolist() == [9, 8, 7]
    v, i = ht.topk(b, 2, dim=0, largest=False)
    assert v.numpy().tolist() == [0, 1]


def test_concatenate_edge_matrix():
    rng = np.random.default_rng(31)
    p = ht.get_comm().size
    a_np = rng.normal(size=(2 * p, 3)).astype(np.float32)
    b_np = rng.normal(size=(p + 1, 3)).astype(np.float32)  # ragged partner
    for sa, sb in [(0, 0), (0, None), (None, 0), (1, 1)]:
        got = ht.concatenate(
            [ht.array(a_np, split=sa), ht.array(b_np, split=sb)], axis=0
        )
        np.testing.assert_array_equal(got.numpy(), np.concatenate([a_np, b_np]))
    # dtype promotion across operands
    c = ht.concatenate([ht.ones(4, dtype=ht.int32), ht.ones(4, dtype=ht.float32)])
    assert c.dtype is ht.float32
    with pytest.raises((ValueError, TypeError)):
        ht.concatenate([ht.ones((2, 3)), ht.ones((2, 4))], axis=0)


def test_pad_modes_on_split_axis():
    rng = np.random.default_rng(32)
    a_np = rng.normal(size=(13, 3)).astype(np.float32)
    a = ht.array(a_np, split=0)
    for width in [(1, 2), ((1, 2), (0, 0)), 2]:
        got = ht.pad(a, width)
        np.testing.assert_array_equal(
            got.numpy(),
            np.pad(a_np, width if not isinstance(width, int) else 2),
        )
    got = ht.pad(a, ((1, 1), (1, 1)), constant_values=7.0)
    np.testing.assert_array_equal(
        got.numpy(), np.pad(a_np, ((1, 1), (1, 1)), constant_values=7.0)
    )


def test_roll_flip_rot90_split_matrix():
    rng = np.random.default_rng(33)
    a_np = rng.normal(size=(13, 6)).astype(np.float32)
    for split in (0, 1):
        a = ht.array(a_np, split=split)
        for shift, axis in [(3, 0), (-2, 1), (5, None)]:
            np.testing.assert_array_equal(
                ht.roll(a, shift, axis=axis).numpy(), np.roll(a_np, shift, axis=axis)
            )
        np.testing.assert_array_equal(ht.flip(a, 0).numpy(), np.flip(a_np, 0))
        np.testing.assert_array_equal(ht.fliplr(a).numpy(), np.fliplr(a_np))
        np.testing.assert_array_equal(ht.flipud(a).numpy(), np.flipud(a_np))
    r = ht.rot90(ht.array(a_np, split=0))
    np.testing.assert_array_equal(r.numpy(), np.rot90(a_np))


def test_reshape_across_splits():
    a_np = np.arange(48, dtype=np.float32)
    a = ht.array(a_np, split=0)
    for shape in [(6, 8), (8, 6), (2, 4, 6), (48,), (-1, 12)]:
        got = ht.reshape(a, shape)
        np.testing.assert_array_equal(got.numpy(), a_np.reshape(shape))
    b = ht.array(a_np.reshape(6, 8), split=1)
    np.testing.assert_array_equal(ht.reshape(b, (48,)).numpy(), a_np)
    with pytest.raises((ValueError, TypeError)):
        ht.reshape(a, (7, 7))


def test_numpy_completion_surface():
    # argsort/searchsorted/take/take_along_axis/isin/count_nonzero (numpy-API
    # completions; argsort rides the distributed sort along split axes)
    rng = np.random.default_rng(77)
    a_np = rng.normal(size=(13, 4)).astype(np.float32)
    a = ht.array(a_np, split=0)
    r = ht.argsort(a, axis=0)
    np.testing.assert_array_equal(r.numpy(), np.argsort(a_np, axis=0, kind="stable"))
    assert r.split == 0  # distributed path
    h = ht.array(np.array([1.0, 3.0, 5.0, 7.0], np.float32))
    for side in ("left", "right"):
        np.testing.assert_array_equal(
            ht.searchsorted(h, ht.array(np.array([0.0, 3.0, 8.0], np.float32)), side=side).numpy(),
            np.searchsorted([1, 3, 5, 7], [0, 3, 8], side=side),
        )
    with pytest.raises(ValueError):
        ht.searchsorted(h, h, side="middle")
    np.testing.assert_array_equal(
        ht.take(a, np.array([2, 0, 5]), axis=0).numpy(), np.take(a_np, [2, 0, 5], axis=0)
    )
    assert ht.take(a, np.array([2, 0, 5]), axis=0).split == 0
    np.testing.assert_array_equal(
        ht.take(a, np.array([1, 3]), axis=1).numpy(), np.take(a_np, [1, 3], axis=1)
    )
    np.testing.assert_array_equal(
        ht.take(a, np.array([5, 2])).numpy(), np.take(a_np, [5, 2])
    )
    # multi-dimensional index arrays keep numpy's indices-shaped result
    # (round-3 advisor finding: axis=None used to flatten to 1-D)
    idx2 = np.array([[0, 1], [2, 3], [5, 4]])
    np.testing.assert_array_equal(ht.take(a, idx2).numpy(), np.take(a_np, idx2))
    np.testing.assert_array_equal(
        ht.take(a, idx2, axis=0).numpy(), np.take(a_np, idx2, axis=0)
    )
    np.testing.assert_array_equal(
        ht.take(a, np.array([[1, 3], [0, 2]]), axis=1).numpy(),
        np.take(a_np, [[1, 3], [0, 2]], axis=1),
    )
    idx = np.argsort(a_np, axis=1)
    np.testing.assert_array_equal(
        ht.take_along_axis(a, idx, axis=1).numpy(), np.take_along_axis(a_np, idx, axis=1)
    )
    e = ht.array(np.array([1, 2, 3, 4, 5], np.int32), split=0)
    np.testing.assert_array_equal(
        ht.isin(e, [2, 4]).numpy(), np.isin([1, 2, 3, 4, 5], [2, 4])
    )
    np.testing.assert_array_equal(
        ht.isin(e, [2, 4], invert=True).numpy(), np.isin([1, 2, 3, 4, 5], [2, 4], invert=True)
    )
    assert int(ht.count_nonzero(ht.array(np.array([0, 1, 0, 3]), split=0)).numpy()) == 2
    np.testing.assert_array_equal(
        ht.count_nonzero(a > 0, axis=0).numpy(), np.count_nonzero(a_np > 0, axis=0)
    )
