"""Tests for manipulations (parity model: reference
heat/core/tests/test_manipulations.py)."""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0, 1]


def _arr(split=0, shape=(8, 4)):
    a = np.arange(np.prod(shape), dtype=np.float32).reshape(shape)
    return ht.array(a, split=split), a


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("axis", [0, 1])
def test_concatenate(split, axis):
    h1, a1 = _arr(split)
    h2, a2 = _arr(split)
    res = ht.concatenate([h1, h2], axis=axis)
    np.testing.assert_array_equal(res.numpy(), np.concatenate([a1, a2], axis=axis))
    assert res.split == split
    with pytest.raises(TypeError):
        ht.concatenate([])


def test_stack_hstack_vstack_dstack_analogs():
    h, a = _arr(None, (4, 3))
    np.testing.assert_array_equal(ht.stack([h, h], axis=0).numpy(), np.stack([a, a]))
    np.testing.assert_array_equal(ht.stack([h, h], axis=2).numpy(), np.stack([a, a], axis=2))
    np.testing.assert_array_equal(ht.hstack([h, h]).numpy(), np.hstack([a, a]))
    np.testing.assert_array_equal(ht.vstack([h, h]).numpy(), np.vstack([a, a]))
    np.testing.assert_array_equal(ht.column_stack([h, h]).numpy(), np.column_stack([a, a]))
    np.testing.assert_array_equal(ht.row_stack([h, h]).numpy(), np.row_stack([a, a]))
    v = ht.arange(3)
    np.testing.assert_array_equal(ht.hstack([v, v]).numpy(), np.hstack([np.arange(3)] * 2))
    with pytest.raises(ValueError):
        ht.stack([h, ht.ones((2, 2))])


@pytest.mark.parametrize("split", [None, 0])
def test_reshape_ravel_flatten(split):
    h, a = _arr(split, (8, 4))
    np.testing.assert_array_equal(ht.reshape(h, (4, 8)).numpy(), a.reshape(4, 8))
    np.testing.assert_array_equal(ht.reshape(h, 32).numpy(), a.reshape(32))
    np.testing.assert_array_equal(ht.reshape(h, (-1, 2)).numpy(), a.reshape(-1, 2))
    np.testing.assert_array_equal(ht.flatten(h).numpy(), a.flatten())
    np.testing.assert_array_equal(ht.ravel(h).numpy(), a.ravel())
    assert ht.reshape(h, (4, 8), new_split=1).split == 1
    with pytest.raises(ValueError):
        ht.reshape(h, (-1, -1))


def test_sort_topk():
    rng = np.random.default_rng(3)
    a = rng.normal(size=(8, 6)).astype(np.float32)
    h = ht.array(a, split=0)
    v, i = ht.sort(h, axis=1)
    np.testing.assert_array_equal(v.numpy(), np.sort(a, axis=1))
    np.testing.assert_array_equal(i.numpy(), np.argsort(a, axis=1, kind="stable"))
    vd, _ = ht.sort(h, axis=0, descending=True)
    np.testing.assert_array_equal(vd.numpy(), -np.sort(-a, axis=0))
    tv, ti = ht.topk(h, 3, dim=1)
    np.testing.assert_array_equal(tv.numpy(), -np.sort(-a, axis=1)[:, :3])
    sv, si = ht.topk(h, 2, dim=1, largest=False)
    np.testing.assert_array_equal(sv.numpy(), np.sort(a, axis=1)[:, :2])


def test_unique():
    a = np.array([3, 1, 3, 2, 2, 7, 1, 0])
    h = ht.array(a, split=0)
    np.testing.assert_array_equal(ht.unique(h).numpy(), np.unique(a))
    vals, inv = ht.unique(h, return_inverse=True)
    nv, ni = np.unique(a, return_inverse=True)
    np.testing.assert_array_equal(vals.numpy(), nv)
    np.testing.assert_array_equal(inv.numpy().reshape(-1), ni)


def test_pad_roll_flip():
    h, a = _arr(0, (8, 4))
    np.testing.assert_array_equal(
        ht.pad(h, ((1, 1), (0, 2))).numpy(), np.pad(a, ((1, 1), (0, 2)))
    )
    np.testing.assert_array_equal(ht.roll(h, 2, axis=0).numpy(), np.roll(a, 2, axis=0))
    np.testing.assert_array_equal(ht.roll(h, -1).numpy(), np.roll(a, -1))
    np.testing.assert_array_equal(ht.flip(h, 0).numpy(), np.flip(a, 0))
    np.testing.assert_array_equal(ht.flipud(h).numpy(), np.flipud(a))
    np.testing.assert_array_equal(ht.fliplr(h).numpy(), np.fliplr(a))
    with pytest.raises(IndexError):
        ht.fliplr(ht.arange(3))


def test_squeeze_expand_dims_broadcast_to():
    h = ht.ones((1, 8, 1, 4), split=1)
    s = ht.squeeze(h)
    assert s.shape == (8, 4)
    assert s.split == 0
    e = ht.expand_dims(ht.arange(8, split=0), 0)
    assert e.shape == (1, 8)
    assert e.split == 1
    b = ht.broadcast_to(ht.arange(4), (3, 4))
    assert b.shape == (3, 4)


def test_diag_diagonal():
    h, a = _arr(None, (4, 4))
    np.testing.assert_array_equal(ht.diag(h).numpy(), np.diag(a))
    np.testing.assert_array_equal(ht.diagonal(h, offset=1).numpy(), np.diagonal(a, offset=1))
    v = ht.arange(3)
    np.testing.assert_array_equal(ht.diag(v).numpy(), np.diag(np.arange(3)))
    with pytest.raises(ValueError):
        ht.diag(ht.ones((2, 2, 2)))


def test_split_family():
    h, a = _arr(None, (8, 4))
    parts = ht.split(h, 4, axis=0)
    assert len(parts) == 4
    np.testing.assert_array_equal(parts[0].numpy(), a[:2])
    hs = ht.hsplit(h, 2)
    np.testing.assert_array_equal(hs[1].numpy(), a[:, 2:])
    vs = ht.vsplit(h, 2)
    np.testing.assert_array_equal(vs[1].numpy(), a[4:])
    d = ht.ones((2, 2, 4))
    ds = ht.dsplit(d, 2)
    assert ds[0].shape == (2, 2, 2)
    with pytest.raises(ValueError):
        ht.split(h, 3, axis=0)


def test_moveaxis_swapaxes_rot90_tile_repeat():
    h, a = _arr(0, (8, 4))
    np.testing.assert_array_equal(ht.moveaxis(h, 0, 1).numpy(), np.moveaxis(a, 0, 1))
    sw = ht.swapaxes(h, 0, 1)
    np.testing.assert_array_equal(sw.numpy(), np.swapaxes(a, 0, 1))
    assert sw.split == 1
    np.testing.assert_array_equal(ht.rot90(h).numpy(), np.rot90(a))
    np.testing.assert_array_equal(ht.tile(h, (2, 1)).numpy(), np.tile(a, (2, 1)))
    np.testing.assert_array_equal(ht.repeat(h, 2, axis=1).numpy(), np.repeat(a, 2, axis=1))
    np.testing.assert_array_equal(ht.repeat(h, 2).numpy(), np.repeat(a, 2))


def test_resplit_redistribute_balance_shape():
    h, a = _arr(0, (16, 4))
    r = ht.resplit(h, 1)
    assert r.split == 1 and h.split == 0
    rr = ht.redistribute(h)
    np.testing.assert_array_equal(rr.numpy(), a)
    assert ht.balance(h) is h
    assert ht.manipulations.shape(h) == (16, 4) if hasattr(ht, "manipulations") else True
    from heat_tpu.core.manipulations import shape as _shape

    assert _shape(h) == (16, 4)


def test_diagonal_batch_split_remap():
    a = ht.ones((2, 3, 8), split=2)
    d = ht.diagonal(a, dim1=0, dim2=1)  # batch axis 2 survives, shifts to 0
    assert d.split == 0
    assert d.shape == (8, 2)
