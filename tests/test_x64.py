"""
Real 64-bit coverage (VERDICT r3 weak #4 / #6): every test here runs inside
``_compat.enable_x64(True)`` so f64/i64/c128 are *genuinely* 64-bit — results are
asserted at precisions/magnitudes a silently-truncated 32-bit run cannot
reach, which makes the tests self-proving (a truncation would fail them, not
quietly pass). Mirrors the reference's f64 default coverage
(torch.float64 is its promoted default in many tests).
"""

import numpy as np
import pytest

import jax
from heat_tpu.core import _compat

import heat_tpu as ht

from _accel import requires_native_f64


@pytest.fixture(autouse=True)
def _x64():
    with _compat.enable_x64(True):
        yield


def test_f64_beyond_f32_precision():
    """Sum that only a real f64 accumulator resolves: 1 + k*2^-30 per element
    (the 2^-30 offsets are below f32's 2^-23 resolution near 1.0)."""
    n = 64
    vals = 1.0 + np.arange(n, dtype=np.float64) * 2.0**-30
    a = ht.array(vals, split=0)
    assert a.larray.dtype == np.float64
    got = float(ht.sum(a).larray)
    expected = float(vals.sum())
    assert got == pytest.approx(expected, abs=1e-12)
    assert abs(got - n) > 1e-7  # an f32 truncation would collapse to exactly n


def test_i64_beyond_i32_range():
    vals = np.array([2**40, -(2**41), 2**62], dtype=np.int64)
    a = ht.array(vals, split=0)
    assert a.dtype == ht.int64 and a.larray.dtype == np.int64
    np.testing.assert_array_equal(a.numpy(), vals)
    assert int(ht.max(a).larray) == 2**62
    assert int(ht.sum(a).larray) == int(vals.sum())


@requires_native_f64
@pytest.mark.parametrize("split", [None, 0, 1])
def test_f64_elementwise_and_reduction_matrix(split):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((9, 5))
    h = ht.array(a, split=split)
    assert h.larray.dtype == np.float64
    np.testing.assert_allclose(ht.exp(h).numpy(), np.exp(a), rtol=1e-14)
    np.testing.assert_allclose(float(ht.mean(h).larray), a.mean(), rtol=1e-14)
    np.testing.assert_allclose(ht.cumsum(h, axis=0).numpy(), np.cumsum(a, 0), rtol=1e-13)


@requires_native_f64
def test_f64_distributed_sort():
    """The exact-rank distributed sort's u64 total-order transform path."""
    rng = np.random.default_rng(1)
    a = rng.standard_normal(37)  # ragged over any mesh
    h = ht.array(a, split=0)
    v, idx = ht.sort(h)
    assert v.larray.dtype == np.float64
    np.testing.assert_array_equal(v.numpy(), np.sort(a, kind="stable"))
    np.testing.assert_array_equal(idx.numpy(), np.argsort(a, kind="stable"))


def test_f64_matmul_precision():
    """A Hilbert-style ill-conditioned product that f32 GEMM cannot get to
    1e-10: the linalg path must run a true f64 contraction."""
    n = 24
    i = np.arange(1, n + 1)
    a = 1.0 / (i[:, None] + i[None, :] - 1.0)
    h = ht.array(a, split=0)
    got = ht.matmul(h, h).numpy()
    np.testing.assert_allclose(got, a @ a, rtol=1e-12)


def test_i64_collectives():
    from heat_tpu.core.communication import get_comm
    import jax.numpy as jnp

    comm = get_comm()
    p = comm.size
    big = 2**40
    x = jnp.asarray(np.full((p, 2), big, dtype=np.int64))
    assert x.dtype == np.int64
    got = np.asarray(comm.Allreduce(x, op="sum"))
    assert got.dtype == np.int64
    np.testing.assert_array_equal(got, np.full((1, 2), big * p, dtype=np.int64))
    sc = np.asarray(comm.Scan(x, op="sum"))
    np.testing.assert_array_equal(sc[:, 0], big * np.arange(1, p + 1))


def test_f64_random_mantissa():
    """random.rand draws 53-bit mantissas under x64 (random.py f64 path) —
    values must not be representable in f32."""
    ht.random.seed(7)
    r = ht.random.rand(4096, dtype=ht.float64, split=0)
    assert r.larray.dtype == np.float64
    vals = r.numpy()
    # a 24-bit-mantissa (f32) sample would round-trip exactly through float32
    roundtrip = vals.astype(np.float32).astype(np.float64)
    assert (roundtrip != vals).any()
    assert ((0.0 <= vals) & (vals < 1.0)).all()


def test_c128_when_supported():
    from _accel import COMPLEX_SUPPORTED

    if not COMPLEX_SUPPORTED:
        pytest.skip("backend has no complex support")
    a = np.array([1 + 2j, 3 - 4j], dtype=np.complex128)
    h = ht.array(a, split=0)
    assert h.larray.dtype == np.complex128
    np.testing.assert_allclose(ht.real(h).numpy(), a.real, rtol=1e-15)
    np.testing.assert_allclose(ht.conj(h).numpy(), a.conj(), rtol=1e-15)


@requires_native_f64
def test_f64_acceptance_tol_scales_with_dtype():
    """ADVICE r4 low: the panel solve's residual gate must scale with the
    working precision. A cond~1e12 f64 system certifies a ~3e-6 panel
    residual — silently accepted by a flat 1e-3 gate, but ~8 digits short of
    what f64 LAPACK delivers. It must warn-fallback and come back
    backward-stable at f64 grade."""
    from heat_tpu.core.linalg import _elimination

    assert _elimination.acceptance_tol(np.float64) < 1e-6 < _elimination.acceptance_tol(np.float32) * 1e3
    if not ht.get_comm().is_distributed():
        pytest.skip("needs a multi-device mesh")
    rng = np.random.default_rng(13)
    n = 64
    u, _ = np.linalg.qr(rng.standard_normal((n, n)))
    v, _ = np.linalg.qr(rng.standard_normal((n, n)))
    a_np = (u * np.logspace(0, -12, n)) @ v.T
    b_np = rng.standard_normal(n)
    with pytest.warns(UserWarning, match="falling back"):
        x = ht.solve(ht.array(a_np, split=0), ht.array(b_np, split=0))
    xn = x.numpy()
    resid = np.abs(a_np @ xn - b_np).max() / (np.abs(xn).max() * np.abs(a_np).max())
    assert resid < 1e-12, resid  # f64-grade backward stability, not f32-grade


@pytest.mark.slow  # f64 duplicate of test_linalg's panel differential;
# unfiltered device-matrix CI job keeps coverage (ISSUE 16 tier-1 rebalance)
@requires_native_f64
def test_f64_det_inv_distributed():
    """The round-4 blocked elimination path under x64 (the CPU-mesh numerics
    it was validated against)."""
    rng = np.random.default_rng(2)
    n = 32
    a = rng.standard_normal((n, n)) + 3 * np.eye(n)
    h = ht.array(a, split=0)
    d = ht.linalg.det(h)
    np.testing.assert_allclose(float(d.larray), np.linalg.det(a), rtol=1e-10)
    iv = ht.linalg.inv(h)
    np.testing.assert_allclose(iv.numpy(), np.linalg.inv(a), rtol=1e-9, atol=1e-10)


def test_median_percentile_split_axis_keep_f64():
    """The distributed-selection median/percentile must compute in f64 under
    x64 — a hardcoded f32 working dtype rounded split-axis medians to 7
    digits (caught by the x64 surface-fuzz case at mesh size 3)."""
    a = 1.0 + np.arange(21, dtype=np.float64).reshape(7, 3) * 2.0**-40
    h = ht.array(a, split=0)
    m = ht.median(h, axis=0)
    assert m.larray.dtype == np.float64
    np.testing.assert_array_equal(m.numpy(), np.median(a, axis=0))
    p = ht.percentile(h, 31.25, axis=0)
    assert p.larray.dtype == np.float64
    np.testing.assert_allclose(
        p.numpy(), np.percentile(a, 31.25, axis=0), rtol=0, atol=2.0**-52
    )
    # int64 input: the WEAK-float working dtype must give an exact f64 median
    iv = np.array([0, 2**40 + 1, 2**53, 5, 7], dtype=np.int64)
    im = ht.median(ht.array(iv, split=0), axis=0)
    assert im.larray.dtype == np.float64
    assert float(im.numpy()) == float(np.median(iv))
