"""
Runtime observability subsystem (heat_tpu/monitoring/): registry semantics,
disabled-mode no-op guarantees, span nesting, and the instrumented hot paths —
the resharding counter fires exactly once per forced resplit, kmeans emits one
step span per iteration, lasso one sweep span per iteration, IO records bytes
and duration, and the dispatch counters see every generic-template op.
"""

import json

import jax
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import monitoring
from heat_tpu.monitoring import events, instrument, registry, report
from heat_tpu.core.communication import get_comm

# the collective shims compile shard_map programs through the version-compat
# wrapper (heat_tpu/core/_compat.py), available on every supported jax
_HAS_SHARD_MAP = True

pytestmark = pytest.mark.monitoring


@pytest.fixture(autouse=True)
def _isolated_monitoring():
    """Every test starts from empty metrics/events and ends disabled."""
    prev = registry.STATE.enabled
    registry.STATE.enabled = False
    monitoring.reset()
    yield
    registry.STATE.enabled = prev
    monitoring.reset()


# ---------------------------------------------------------------- registry
def test_counter_gauge_histogram_and_snapshot_shape():
    reg = registry.MetricsRegistry()
    c = reg.counter("ops")
    c.inc()
    c.inc(2, label="binary")
    assert c.get() == 3
    assert c.get("binary") == 2
    assert reg.counter("ops") is c  # name-keyed identity

    reg.gauge("hbm").set(1234)
    h = reg.histogram("lat")
    for v in (1e-6, 1e-3, 0.5, 1e9):  # spans the buckets incl. overflow
        h.observe(v)

    snap = reg.snapshot()
    assert set(snap) == {"counters", "gauges", "histograms"}
    assert snap["counters"]["ops"] == {"total": 3, "labels": {"binary": 2}}
    assert snap["gauges"]["hbm"] == 1234
    hs = snap["histograms"]["lat"]
    assert hs["count"] == 4
    assert hs["sum"] == pytest.approx(1e9 + 0.5 + 1e-3 + 1e-6)
    # fixed log-scale buckets: counts has one overflow slot beyond bounds
    assert len(hs["counts"]) == len(hs["buckets"]) + 1
    assert hs["counts"][-1] == 1  # 1e9 overflows the top bucket
    assert sum(hs["counts"]) == 4
    assert list(hs["buckets"]) == sorted(hs["buckets"])
    json.dumps(snap)  # plain-dict contract: JSON-serialisable as-is

    reg.reset()
    assert reg.snapshot() == {"counters": {}, "gauges": {}, "histograms": {}}


def test_env_gate_and_capture_restores():
    assert not monitoring.enabled()
    with monitoring.capture():
        assert monitoring.enabled()
        with monitoring.capture():  # re-entrant
            assert monitoring.enabled()
        assert monitoring.enabled()  # inner exit must not disable the outer
    assert not monitoring.enabled()


# ------------------------------------------------------------- disabled mode
def test_disabled_mode_accumulates_nothing():
    a = ht.arange(24, split=0).astype(ht.float32)
    b = a + 1.0
    ht.sum(b)
    a.resplit_(None)
    with events.span("should.not.record", k=1) as sp:
        sp.set(x=2).mark("m")
    events.event("nope")
    snap = report.snapshot()
    assert snap["metrics"]["counters"] == {}
    assert snap["spans"] == {}
    assert events.records() == []
    # the disabled span() hands back the shared no-op object
    assert events.span("x") is events.span("y")


# ------------------------------------------------------------------- spans
def test_span_nesting_depth_parent_and_jsonl():
    with monitoring.capture():
        with events.span("outer", phase="a"):
            with events.span("inner") as sp:
                sp.set(delta=0.5)
            events.event("tick", n=1)
    recs = {r["name"]: r for r in events.records()}
    assert recs["inner"]["parent"] == "outer"
    assert recs["inner"]["depth"] == 1
    assert recs["inner"]["attrs"]["delta"] == 0.5
    assert recs["outer"]["parent"] is None
    assert recs["outer"]["depth"] == 0
    assert recs["outer"]["wall_s"] >= recs["inner"]["wall_s"] >= 0.0
    assert recs["tick"]["type"] == "event"
    assert recs["tick"]["parent"] == "outer"
    # inner closed before outer -> listed first in the jsonl export
    lines = [json.loads(l) for l in events.export_jsonl().splitlines()]
    assert [l["name"] for l in lines] == ["inner", "tick", "outer"]


def test_span_device_time_mark():
    import jax.numpy as jnp

    with monitoring.capture():
        with events.span("devwork") as sp:
            out = jnp.arange(128) * 2
            sp.mark("ready", block_on=out)
    (rec,) = events.records("devwork")
    assert rec["marks"][0]["name"] == "ready"
    assert 0.0 <= rec["marks"][0]["at_s"] <= rec["wall_s"]


# -------------------------------------------------------- instrumented paths
def test_op_dispatch_counters_fire():
    with monitoring.capture():
        a = ht.arange(12, split=0).astype(ht.float32)
        _ = a + 1.0          # binary
        _ = ht.sum(a)        # reduce
        _ = ht.exp(a)        # local
        # replicated operand: the cum template dispatches without needing the
        # shard_map Cum collective (absent on old jax builds)
        _ = ht.cumsum(ht.arange(12).astype(ht.float32), 0)
    counters = report.snapshot()["metrics"]["counters"]
    labels = counters["ops.dispatch"]["labels"]
    for kind in ("binary", "reduce", "local", "cum"):
        assert labels.get(kind, 0) >= 1, (kind, labels)


def test_resharding_counter_fires_exactly_once_on_forced_resplit():
    comm = get_comm()
    if not comm.is_distributed():
        pytest.skip("resharding requires a multi-device mesh")
    a = ht.arange(4 * comm.size, split=0)
    with monitoring.capture():
        a.resplit_(None)  # forced split change -> one resharding event
        a.resplit_(None)  # no-op: same split, must NOT count
    counters = report.snapshot()["metrics"]["counters"]
    assert counters["comm.resharding"]["total"] == 1
    assert counters["comm.resharding"]["labels"] == {"0->None": 1}
    (rec,) = events.records("comm.resharding")
    assert rec["attrs"] == {"old_split": 0, "new_split": None}


def test_collective_counter_labels():
    comm = get_comm()
    if not comm.is_distributed():
        pytest.skip("collectives require a multi-device mesh")
    if not _HAS_SHARD_MAP:
        pytest.skip("jax.shard_map unavailable: collective shims cannot compile")
    import jax.numpy as jnp

    x = jnp.arange(comm.size * 3, dtype=jnp.float32)
    with monitoring.capture():
        comm.Allreduce(x, op="sum")
        comm.Allgather(x)
    labels = report.snapshot()["metrics"]["counters"]["comm.collective"]["labels"]
    assert labels.get("allreduce") == 1
    assert labels.get("allgather") == 1


def test_kmeans_emits_one_step_span_per_iteration():
    rng = np.random.default_rng(0)
    x = ht.array(rng.standard_normal((96, 4)).astype(np.float32), split=0)
    km = ht.cluster.KMeans(n_clusters=3, init="random", max_iter=20, random_state=1)
    with monitoring.capture():
        km.fit(x)
    steps = events.records("kmeans.step")
    assert km.n_iter_ >= 1
    assert len(steps) == km.n_iter_
    assert [s["attrs"]["iteration"] for s in steps] == list(range(km.n_iter_))
    for s in steps:
        assert s["parent"] == "kmeans.fit"
        assert np.isfinite(s["attrs"]["shift"])
    counters = report.snapshot()["metrics"]["counters"]
    assert counters["kmeans.iterations"] == km.n_iter_
    (fit_rec,) = events.records("kmeans.fit")
    assert fit_rec["attrs"]["n_iter"] == km.n_iter_
    # acceptance: a monitored fit also exercises the generic dispatch layer
    # (the final inertia reduce runs through the framework's own ops)
    assert counters["ops.dispatch"]["total"] >= 1


def test_kmeans_monitored_fit_matches_unmonitored():
    """The observed host loop must implement the same Lloyd recurrence as the
    fused on-device loop — identical centers/labels/iteration count."""
    rng = np.random.default_rng(3)
    data = rng.standard_normal((80, 3)).astype(np.float32)
    x = ht.array(data.copy(), split=0)

    plain = ht.cluster.KMeans(n_clusters=4, init="random", max_iter=25, random_state=7).fit(x)
    with monitoring.capture():
        observed = ht.cluster.KMeans(
            n_clusters=4, init="random", max_iter=25, random_state=7
        ).fit(x)
    assert observed.n_iter_ == plain.n_iter_
    np.testing.assert_allclose(
        observed.cluster_centers_.numpy(), plain.cluster_centers_.numpy(), rtol=1e-5
    )
    np.testing.assert_array_equal(observed.labels_.numpy(), plain.labels_.numpy())
    assert observed.inertia_ == pytest.approx(plain.inertia_, rel=1e-5)


def test_lasso_emits_sweep_spans():
    rng = np.random.default_rng(5)
    X = ht.array(rng.standard_normal((32, 6)).astype(np.float32), split=0)
    y = ht.array(rng.standard_normal((32,)).astype(np.float32), split=0)
    model = ht.regression.Lasso(lam=0.05, max_iter=15)
    with monitoring.capture():
        model.fit(X, y)
    sweeps = events.records("lasso.sweep")
    assert len(sweeps) == model.n_iter
    assert all(s["parent"] == "lasso.fit" for s in sweeps)
    assert all(np.isfinite(s["attrs"]["delta"]) for s in sweeps)


def test_io_records_bytes_and_duration(tmp_path):
    path = str(tmp_path / "obs.csv")
    data = ht.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    with monitoring.capture():
        ht.save_csv(data, path)
        loaded = ht.load_csv(path)
    counters = report.snapshot()["metrics"]["counters"]
    assert counters["io.calls"]["labels"] == {"save_csv": 1, "load_csv": 1}
    assert counters["io.bytes_written"] > 0
    assert counters["io.bytes_read"] == loaded.nbytes
    hist = report.snapshot()["metrics"]["histograms"]["io.seconds"]
    assert hist["count"] == 2
    (w,) = events.records("io.save_csv")
    assert w["attrs"]["path"] == path and w["attrs"]["bytes"] > 0


def test_jit_compile_miss_counter():
    import jax.numpy as jnp

    def compiles():
        return report.snapshot()["metrics"]["counters"].get("jit.compiles", 0)

    with monitoring.capture():

        @jax.jit
        def f(v):
            return v * 3 + 1

        # build inputs first: eager jnp ops compile tiny programs of their own
        x7, x9 = jnp.arange(7), jnp.arange(9)
        f(x7)                    # miss: compile
        base = compiles()
        f(x7)                    # hit: cached executable, no compile event
        assert compiles() == base
        f(x9)                    # new shape: a second miss
        after = compiles()
    if base == 0:
        pytest.skip("jax.monitoring compile events unavailable in this jax")
    assert after == base + 1


def test_report_render_and_telemetry_shapes():
    with monitoring.capture():
        a = ht.arange(8, split=0) * 2
        with events.span("phase"):
            pass
    text = report.render()
    assert "ops.dispatch" in text and "phase" in text
    tel = report.telemetry()
    assert tel["counters"]["ops.dispatch"] >= 1
    assert tel["spans"]["phase"]["n"] == 1
    json.dumps(tel)


def test_memory_gauges_shape():
    out = instrument.sample_memory()  # CPU backends typically report nothing
    for name, val in out.items():
        assert name.startswith("memory.") and isinstance(val, int)


# --------------------------------------------- statistics fixes (satellites)
def test_histogram_rejects_invalid_ranges():
    """__f64_edges validation (ADVICE r5): decreasing or non-finite ranges —
    supplied or data-derived — raise ValueError like numpy/torch instead of
    producing decreasing/garbage bin edges."""
    a = ht.array(np.array([1.0, 2.0, 3.0], dtype=np.float32))
    with pytest.raises(ValueError, match="max must be larger than min"):
        ht.histogram(a, bins=4, range=(5.0, 1.0))
    with pytest.raises(ValueError, match="not finite"):
        ht.histogram(a, bins=4, range=(0.0, float("nan")))
    with pytest.raises(ValueError, match="not finite"):
        ht.histogram(ht.array(np.array([1.0, np.inf], dtype=np.float32)), bins=4)
    # histc shares the edge builder
    with pytest.raises(ValueError, match="max must be larger than min"):
        ht.histc(a, bins=4, min=3.0, max=1.0)
    # an EQUAL range is still legal: expanded ±0.5 first (numpy
    # _get_outer_edges semantics), never rejected
    _, edges = ht.histogram(ht.array(np.full(5, 2.0, dtype=np.float32)), bins=4)
    np.testing.assert_allclose(edges.numpy(), np.linspace(1.5, 2.5, 5))


def test_histogram_integer_bins_under_jit():
    """Integer-bins histogram used to concretize float(jnp.min/max) on the
    host, raising ConcretizationTypeError under jit/vmap (ADVICE r5); a Tracer
    operand now takes the pure-jnp path and traces fine."""
    import jax.numpy as jnp

    data = np.linspace(0.0, 1.0, 32, dtype=np.float32)

    def f(arr):
        hist, edges = ht.histogram(ht.array(arr), bins=5)
        return hist.larray, edges.larray

    hist, edges = jax.jit(f)(jnp.asarray(data))
    ref_hist, ref_edges = np.histogram(data, bins=5)
    np.testing.assert_array_equal(np.asarray(hist), ref_hist)
    np.testing.assert_allclose(np.asarray(edges), ref_edges, rtol=1e-6)
