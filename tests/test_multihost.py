"""
Multi-controller (multi-host) validation: N OS processes join one JAX runtime
via ``ht.distributed_init`` (the reference becomes multi-node via
`mpirun -n N`, SURVEY §5 distributed-backend row) and run sharded ops whose
collectives cross the process boundary over the gloo CPU client — the CPU
stand-in for a multi-host ICI/DCN pod.

Round-4 matrix (VERDICT r3 #7): parametrized over 2 and 4 controller
processes; a full named-shim sweep (every collective once, cross-host); and a
multi-controller DASO run whose (node, local) mesh spans processes with
node_count > 1 — the hierarchy's global sync genuinely crosses hosts.
"""

import os
import socket
import subprocess
import sys
import textwrap

import jax
import pytest

# jax 0.4.x ships a gloo TCP transport with a framing bug
# ("op.preamble.length <= op.nbytes") that kills one worker under 4-way
# concurrent CPU collectives; 2-process runs are unaffected
_LEGACY_GLOO = tuple(int(v) for v in jax.__version__.split(".")[:2]) < (0, 5)

WORKER = textwrap.dedent(
    """
    import os, sys
    nprocs = int(sys.argv[1]); pid = int(sys.argv[2]); port = sys.argv[3]; tmp = sys.argv[4]
    os.environ["JAX_PLATFORMS"] = "cpu"
    import heat_tpu as ht
    from heat_tpu.core.communication import distributed_init
    comm = distributed_init(f"127.0.0.1:{port}", num_processes=nprocs, process_id=pid,
                            local_devices=2)
    import jax
    import numpy as np
    ndev = 2 * nprocs
    assert jax.process_count() == nprocs, jax.process_count()
    assert jax.device_count() == ndev
    assert comm.size == ndev

    # ---- sharded op layer across hosts
    x = ht.arange(4 * ndev, split=0, dtype=ht.float32)
    n = 4 * ndev
    assert float(ht.sum(x).item()) == n * (n - 1) / 2.0   # psum across hosts
    m = ht.matmul(ht.ones((8, 8), split=0), ht.ones((8, 8)))
    assert float(m.numpy()[0, 0]) == 8.0                  # cross-host gather in numpy()

    # ---- named-shim sweep: every collective once, cross-host (VERDICT r3 #7)
    p = comm.size
    base = np.arange(p * 2 * 3, dtype=np.float32).reshape(p * 2, 3)
    chunks = np.split(base, p, axis=0)
    from jax.experimental import multihost_utils
    def fetch(a):
        # sharded results span non-addressable devices under multi-controller;
        # gather across processes before comparing
        if hasattr(a, "is_fully_addressable") and not a.is_fully_addressable:
            return np.asarray(multihost_utils.process_allgather(a, tiled=True))
        return np.asarray(a)
    def same(a, b):
        got = fetch(a)
        assert np.allclose(got, b), (got, b)
    same(comm.Allreduce(base, op="sum"), np.add.reduce(chunks))
    same(comm.Allreduce(base, op="max"), np.maximum.reduce(chunks))
    same(comm.Reduce(base, op="min", root=0), np.minimum.reduce(chunks))
    same(comm.Allgather(base), base)
    same(comm.Gather(base, root=0), base)
    same(comm.Scatter(base, root=0), base)
    same(comm.Bcast(base, root=p - 1), np.concatenate([chunks[p - 1]] * p, axis=0))
    same(comm.Scan(base, op="sum"),
         np.concatenate([np.add.reduce(chunks[:i + 1]) for i in range(p)], axis=0))
    same(comm.Exscan(base, op="sum"),
         np.concatenate([np.zeros_like(chunks[0])]
                        + [np.add.reduce(chunks[:i + 1]) for i in range(p - 1)], axis=0))
    same(comm.Cum(base, op="sum"), np.cumsum(base, axis=0))
    same(comm.Ppermute(base, shift=1),
         np.concatenate([chunks[(i - 1) % p] for i in range(p)], axis=0))
    sq = np.arange(p * p * 4, dtype=np.float32).reshape(p * 2, p * 2)
    same(comm.Alltoall(sq, split_axis=1, concat_axis=0), sq)
    ragged = np.arange(13 * 3, dtype=np.float32).reshape(13, 3)
    same(comm.Allgatherv(ragged), ragged)
    same(fetch(comm.Scatterv(ragged))[:13], ragged)
    same(fetch(comm.Alltoallv(ragged, split_axis=1, concat_axis=0))[:, :3], ragged)

    # ---- multi-controller branches from round 2/3
    u = ht.unique(ht.array(np.tile(np.arange(6, dtype=np.float32), 4), split=0))
    assert sorted(np.asarray(u.larray).tolist()) == list(range(6)), u.larray
    s_np = np.asarray([7, 1, 5, 3, 9, 0, 2, 8, 6, 4, 11, 10, 13], np.float32)
    sv, si = ht.sort(ht.array(s_np, split=0))
    assert (sv.numpy() == np.sort(s_np)).all()

    # ---- DASO on a (node, local) mesh spanning processes (VERDICT r3 #7):
    # with 2*nprocs devices the default near-square factorization gives
    # node_count > 1, so the global bf16 sync crosses the host boundary
    import optax
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(nn.tanh(nn.Dense(8)(x)))

    rngd = np.random.default_rng(0)
    xd = np.asarray(rngd.standard_normal((ndev * 8, 4)), np.float32)
    yd = (xd.sum(axis=1, keepdims=True) > 0).astype(np.float32)

    def mse(params, apply_fn, xb, yb):
        return ((apply_fn(params, xb) - yb) ** 2).mean()

    model = MLP()
    daso = ht.optim.DASO(local_optimizer=optax.sgd(1e-2), total_epochs=2,
                         warmup_epochs=0, cooldown_epochs=0, max_global_skips=2)
    assert daso.nodes * daso.local_size == ndev
    assert daso.nodes > 1, "hierarchy must have multiple node groups"
    params = model.init(jax.random.PRNGKey(0), xd[:2])
    daso.init(params)
    daso.make_train_step(mse, model.apply)
    daso.last_batch = 3
    losses = []
    for epoch in range(2):
        for b in range(3):
            loss = daso.step(xd, yd)
        losses.append(float(loss))
        daso.epoch_loss_logic(losses[-1])
    assert np.isfinite(losses).all()
    merged = daso.merged_params
    out = model.apply(merged, xd)
    assert out.shape == (ndev * 8, 1)

    # ---- io + checkpoint across processes
    if ht.io.supports_hdf5():
        a = ht.arange(24, split=0, dtype=ht.float32) * 0.5
        ht.save(a, f"{tmp}/mh.h5", "data")
        comm.Barrier()
        b = ht.load(f"{tmp}/mh.h5", dataset="data", split=0)
        assert b.shape == (24,)
        assert abs(float(ht.sum(b).item()) - float(ht.sum(a).item())) < 1e-5

        from heat_tpu.utils.checkpoint import save_checkpoint, load_checkpoint
        state = {"w": ht.arange(12, split=0, dtype=ht.float32), "step": 3}
        save_checkpoint(f"{tmp}/ck_{pid}.h5", state)
        back = load_checkpoint(
            f"{tmp}/ck_{pid}.h5",
            {"w": ht.zeros(12, split=0, dtype=ht.float32), "step": 0},
            comm=comm,
        )
        assert back["step"] == 3
        assert back["w"].split == 0
        assert abs(float(ht.sum(back["w"]).item()) - 66.0) < 1e-5
    print(f"worker{pid} ok", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow  # ~2 min of real 2-process gloo boot; the dedicated
# multihost CI job runs this file unfiltered (ISSUE 16 tier-1 rebalance)
@pytest.mark.parametrize(
    "nprocs",
    [
        2,
        pytest.param(
            4,
            marks=pytest.mark.skipif(
                _LEGACY_GLOO, reason="jax<0.5 gloo tcp framing bug under 4-way collectives"
            ),
        ),
    ],
)
def test_multiprocess_distributed_init(tmp_path, nprocs):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "PYTHONPATH")}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # jax 0.4.x's gloo tcp transport intermittently drops a connection under
    # host load ("Connection reset by peer" mid-allreduce -> coordination
    # heartbeat cascade); that is runtime flakiness, not a framework defect —
    # retry the whole spawn on legacy jax when the crash signature matches
    # two attempts: the race hits maybe half the time, and each failing spawn
    # burns ~70 s of coordination timeouts — tier-1's budget caps the retry
    attempts = 2 if _LEGACY_GLOO else 1
    gloo_flake = False
    for attempt in range(attempts):
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(nprocs), str(pid), str(port), str(tmp_path)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            for pid in range(nprocs)
        ]
        outs = []
        try:
            for p in procs:
                # generous: the workers compile dozens of sharded programs and the
                # suite may be saturating every host core around this test
                out, _ = p.communicate(timeout=900)
                outs.append(out)
        finally:
            for p in procs:  # a hung worker must not outlive the test
                if p.poll() is None:
                    p.kill()
                    p.communicate()
        if all(p.returncode == 0 for p in procs):
            break
        blob = "\n".join(outs)
        gloo_flake = (
            "Connection reset by peer" in blob
            or "heartbeat timeout" in blob
            or "gloo" in blob.lower()
        )
        if not (gloo_flake and attempt + 1 < attempts):
            break
    if _LEGACY_GLOO and gloo_flake and any(p.returncode != 0 for p in procs):
        # reproduced standalone: gloo's tcp pair aborts with
        # "op.preamble.length <= op.nbytes" (a transport framing race fixed in
        # newer jax/gloo) — an environment defect, not a framework one; on
        # newer jax the same crash stays a hard failure
        pytest.skip("jax<0.5 gloo tcp framing race killed a worker (retries exhausted)")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker{pid} failed:\n{out[-3000:]}"
        assert f"worker{pid} ok" in out
