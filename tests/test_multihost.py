"""
Multi-controller (multi-host) validation: two OS processes join one JAX runtime via
``ht.distributed_init`` (the reference becomes multi-node via `mpirun -n N`,
SURVEY §5 distributed-backend row) and run sharded ops whose collectives cross the
process boundary over the gloo CPU client — the CPU stand-in for a multi-host
ICI/DCN pod.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = textwrap.dedent(
    """
    import os, sys
    pid = int(sys.argv[1]); port = sys.argv[2]; tmp = sys.argv[3]
    os.environ["JAX_PLATFORMS"] = "cpu"
    import heat_tpu as ht
    from heat_tpu.core.communication import distributed_init
    comm = distributed_init(f"127.0.0.1:{port}", num_processes=2, process_id=pid,
                            local_devices=2)
    import jax
    import numpy as np
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4
    assert comm.size == 4
    x = ht.arange(16, split=0, dtype=ht.float32)
    assert float(ht.sum(x).item()) == 120.0          # psum across hosts
    m = ht.matmul(ht.ones((8, 8), split=0), ht.ones((8, 8)))
    assert float(m.numpy()[0, 0]) == 8.0             # cross-host gather in numpy()
    ar = comm.Allreduce(np.ones((4, 2), np.float32))
    assert float(np.asarray(ar)[0, 0]) == 4.0        # named collective across hosts

    # VERDICT r2 #9 multi-controller branches:
    # unique (manipulations.py multi-host compressed-gather branch)
    u = ht.unique(ht.array(np.tile(np.arange(6, dtype=np.float32), 4), split=0))
    assert sorted(np.asarray(u.larray).tolist()) == list(range(6)), u.larray

    # ragged distributed sort across hosts
    s_np = np.asarray([7, 1, 5, 3, 9, 0, 2, 8, 6, 4, 11, 10, 13], np.float32)
    sv, si = ht.sort(ht.array(s_np, split=0))
    assert (sv.numpy() == np.sort(s_np)).all()

    if ht.io.supports_hdf5():
        # split-io save + sharded load round-trip (io.py multi-host slab branch);
        # save gathers collectively but only process 0 writes the file — the
        # Barrier keeps process 1 from racing ahead to the read
        a = ht.arange(24, split=0, dtype=ht.float32) * 0.5
        ht.save(a, f"{tmp}/mh.h5", "data")
        comm.Barrier()
        b = ht.load(f"{tmp}/mh.h5", dataset="data", split=0)
        assert b.shape == (24,)
        assert abs(float(ht.sum(b).item()) - float(ht.sum(a).item())) < 1e-5

        # checkpoint save/restore across 2 processes
        from heat_tpu.utils.checkpoint import save_checkpoint, load_checkpoint
        state = {"w": ht.arange(12, split=0, dtype=ht.float32), "step": 3}
        save_checkpoint(f"{tmp}/ck_{pid}.h5", state)
        back = load_checkpoint(
            f"{tmp}/ck_{pid}.h5",
            {"w": ht.zeros(12, split=0, dtype=ht.float32), "step": 0},
            comm=comm,
        )
        assert back["step"] == 3
        assert back["w"].split == 0
        assert abs(float(ht.sum(back["w"]).item()) - 66.0) < 1e-5
    print(f"worker{pid} ok", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_init(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "PYTHONPATH")}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            # generous: the workers compile a dozen sharded programs and the
            # suite may be saturating every host core around this test
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:  # a hung worker must not outlive the test
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker{pid} failed:\n{out[-3000:]}"
        assert f"worker{pid} ok" in out
