"""
Elastic multi-host runtime suite (ISSUE 11): topology-aware two-tier meshes,
peer-failure detection, and checkpoint-restore onto a shrunk mesh.

The guarantees pinned here:

* **Two-tier meshes.** ``MeshCommunication.two_tier`` factors the flat split
  axis as ``dcn x ici`` (ici-inner device order); hierarchical
  ``Allreduce``/``Bcast`` lower two-level (reduce in ICI, cross DCN once) and
  match the flat programs exactly for order-free ops and within reassociation
  tolerance for f32 sums; ``HEAT_TPU_TWO_TIER=0`` restores the flat programs
  bit for bit; tiered and flat comms over the same devices never share
  compiled collective programs.
* **Watchdog.** ``HEAT_TPU_COLLECTIVE_TIMEOUT_MS`` counts + logs in-flight
  overruns (``comm.collective_timeout{kind}``, exported by telemetry) and
  never interrupts a running program; unset = zero behavior change.
* **Wiring validation.** ``distributed_init`` rejects partial explicit wiring
  with a ``ValueError`` before it can become an opaque coordination hang, and
  the gloo-missing branch degrades to a ``RuntimeWarning``.
* **Peer-failure detection.** A peer is lost after exactly
  ``miss_threshold`` consecutive conclusive no-advance probes (call-count
  deterministic); an injected ``distributed.peer`` fault is inconclusive; the
  ``distributed.heartbeat``/``distributed.peer`` breakers degrade fail-safe
  (open probe breaker => nobody is ever declared lost).
* **Elastic restart.** On detected loss the trainers drain pending fused
  flushes, checkpoint through the PR 6 preemption-safe path, and raise
  ``PeerLostError``; ``restore_latest_valid`` re-lays every split array out
  on a SHRUNK mesh with exact params/step/RNG. The ``kill -9`` acceptance
  test proves the whole choreography across real OS processes over
  ``jax.distributed`` (gloo permitting; the in-process dryrun proof pins the
  same contract unconditionally).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time
import zlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import heat_tpu as ht
from heat_tpu import monitoring
from heat_tpu.core import communication as comm_mod
from heat_tpu.core import fusion
from heat_tpu.core.communication import MeshCommunication, distributed_init
from heat_tpu.monitoring import registry, report
from heat_tpu.nn.data_parallel import DataParallel
from heat_tpu.optim.dp_optimizer import DASO
from heat_tpu.robustness import breaker, chaos, elastic, faultinject
from heat_tpu.utils.checkpoint import CheckpointManager

pytestmark = pytest.mark.robustness

_DEVS = jax.devices()


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    registry.reset()
    faultinject.clear()
    breaker.reset()
    # this suite asserts exact counts and schedules its own faults — standing
    # CI envs (fault-plan / chaos / forced-open legs) are pinned off
    monkeypatch.delenv("HEAT_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("HEAT_TPU_CHAOS", raising=False)
    monkeypatch.delenv("HEAT_TPU_BREAKER_FORCE_OPEN", raising=False)
    monkeypatch.delenv("HEAT_TPU_TWO_TIER", raising=False)
    monkeypatch.delenv("HEAT_TPU_COLLECTIVE_TIMEOUT_MS", raising=False)
    yield
    faultinject.clear()
    breaker.reset()
    registry.reset()


# ------------------------------------------------------------------ two-tier meshes
def test_two_tier_constructor_and_validation():
    n = len(_DEVS)
    c = MeshCommunication.two_tier(ici=n // 2, dcn=2) if n % 2 == 0 else None
    if c is not None:
        assert c.tiers == (2, n // 2)
        assert c.tier_mesh.axis_names == ("dcn", "ici")
        assert c.tier_mesh.devices.shape == (2, n // 2)
        assert c.size == n
        assert "tiers" in repr(c)
        # one explicit size infers the other
        assert MeshCommunication.two_tier(ici=n // 2).tiers == (2, n // 2)
        # sub-communicators are flat: the tier annotation describes THIS world
        assert c.Split(devices=list(range(n // 2))).tiers is None
    assert MeshCommunication(devices=_DEVS).tiers is None
    with pytest.raises(ValueError):
        MeshCommunication.two_tier(ici=3, dcn=3, devices=_DEVS[:8])
    with pytest.raises(ValueError):
        MeshCommunication.two_tier(ici=0, dcn=1, devices=_DEVS[:1])
    with pytest.raises(ValueError):
        MeshCommunication(devices=_DEVS[:2], tiers=(0, 2))


@pytest.mark.skipif(len(_DEVS) < 4, reason="needs a multi-device mesh to factor")
@pytest.mark.parametrize("dcn", [2, len(_DEVS) // 2])
def test_two_tier_allreduce_matches_flat(dcn):
    tiered = MeshCommunication.two_tier(dcn=dcn)
    flat = MeshCommunication(devices=_DEVS)
    p = len(_DEVS)
    x = np.arange(p * 2 * 3, dtype=np.float32).reshape(p * 2, 3) / 7.0
    xi = np.arange(p * 2 * 3, dtype=np.int32).reshape(p * 2, 3)
    xb = (xi % 5) > 1
    # order-free ops: exact whatever the tiering
    for op in ("max", "min"):
        assert np.array_equal(
            np.asarray(tiered.Allreduce(x, op=op)), np.asarray(flat.Allreduce(x, op=op))
        )
    for op in ("land", "lor"):
        assert np.array_equal(
            np.asarray(tiered.Allreduce(xb, op=op)), np.asarray(flat.Allreduce(xb, op=op))
        )
    # exact dtypes: associativity cannot bite
    assert np.array_equal(
        np.asarray(tiered.Allreduce(xi, op="sum")), np.asarray(flat.Allreduce(xi, op="sum"))
    )
    # f32 sum/prod: the two-level combine reassociates — equal within one
    # reassociation bound (the documented two-tier numerics carve-out)
    for op in ("sum", "prod"):
        np.testing.assert_allclose(
            np.asarray(tiered.Allreduce(x, op=op)),
            np.asarray(flat.Allreduce(x, op=op)),
            rtol=1e-6,
        )
    # bcast: pure selection — exact for every root incl. cross-tier ones
    for root in (0, p // 2, p - 1):
        assert np.array_equal(
            np.asarray(tiered.Bcast(x, root=root)), np.asarray(flat.Bcast(x, root=root))
        )


@pytest.mark.skipif(len(_DEVS) < 4, reason="needs a multi-device mesh to factor")
def test_two_tier_hatch_is_bit_identical_to_flat(monkeypatch):
    tiered = MeshCommunication.two_tier(dcn=2)
    flat = MeshCommunication(devices=_DEVS)
    p = len(_DEVS)
    x = np.arange(p * 3, dtype=np.float32).reshape(p, 3) / 7.0
    ref = np.asarray(flat.Allreduce(x, op="sum"))
    monkeypatch.setenv("HEAT_TPU_TWO_TIER", "0")
    hatched = np.asarray(tiered.Allreduce(x, op="sum"))
    assert hatched.tobytes() == ref.tobytes()
    # with the hatch on, the tiered comm resolves to the SAME cached flat
    # program; with it off, the programs key separately
    assert tiered._collective_fn("allreduce", 0, 2, "sum") is flat._collective_fn(
        "allreduce", 0, 2, "sum"
    )
    monkeypatch.delenv("HEAT_TPU_TWO_TIER")
    assert tiered._collective_fn("allreduce", 0, 2, "sum") is not flat._collective_fn(
        "allreduce", 0, 2, "sum"
    )


@pytest.mark.skipif(len(_DEVS) < 4, reason="needs a multi-device mesh to factor")
@pytest.mark.fusion
def test_collective_nodes_ride_tiered_comms():
    # a fused chain + ring shift over a TIERED comm lands bit-identically to
    # the flat comm (ppermute is pure data movement: the ici-inner ring order
    # is already topology-optimal), and the node keys carry the tier
    # annotation so the two comms never share trace-cache entries
    tiered = MeshCommunication.two_tier(dcn=2)
    flat = MeshCommunication(devices=_DEVS)
    data = np.arange(2 * len(_DEVS) * 3, dtype=np.float32).reshape(-1, 3)
    outs = {}
    for name, c in (("tiered", tiered), ("flat", flat)):
        x = ht.array(data, split=0, comm=c)
        y = (x * 2.0 + 1.0)
        outs[name] = comm_mod.shift(y, 1).numpy()
    assert outs["tiered"].tobytes() == outs["flat"].tobytes()


# ------------------------------------------------------------------ watchdog
@pytest.mark.skipif(len(_DEVS) < 2, reason="collectives need a multi-device mesh")
def test_collective_watchdog_counts_overruns_and_never_interrupts(monkeypatch):
    c = MeshCommunication(devices=_DEVS)
    x = np.arange(len(_DEVS) * 2, dtype=np.float32).reshape(len(_DEVS), 2)
    ref = np.asarray(c.Allreduce(x, op="sum"))
    with monitoring.capture():
        # no knob: no counting
        c.Allreduce(x, op="sum")
        assert "comm.collective_timeout" not in report.telemetry()["counters"]
        # an unmeetable deadline: the dispatch still completes with the exact
        # result (never interrupted), the overrun is counted and exported
        monkeypatch.setenv("HEAT_TPU_COLLECTIVE_TIMEOUT_MS", "0.0000001")
        got = np.asarray(c.Allreduce(x, op="sum"))
        assert got.tobytes() == ref.tobytes()
        t = report.telemetry()
        # the labelled telemetry alias was retired (ISSUE 15 satellite) —
        # the per-kind breakdown lives on the registry counter, the uniform
        # {count,p50_us,p99_us} block carries the latency surface
        assert "comm_collective_timeout" not in t
        counter = registry.REGISTRY.counter("comm.collective_timeout")
        assert counter.get("allreduce") >= 1
        assert t["comm_collective_timeout_latency"]["count"] >= 1
        # a generous deadline: no overrun counted
        monkeypatch.setenv("HEAT_TPU_COLLECTIVE_TIMEOUT_MS", "60000")
        before = counter.get("allreduce")
        c.Allreduce(x, op="sum")
        assert counter.get("allreduce") == before


# ------------------------------------------------------------------ wiring validation
def test_distributed_init_rejects_partial_wiring():
    with pytest.raises(ValueError, match="incomplete distributed wiring"):
        distributed_init(num_processes=2)
    with pytest.raises(ValueError, match="incomplete distributed wiring"):
        distributed_init(coordinator_address="127.0.0.1:1")
    with pytest.raises(ValueError, match="incomplete distributed wiring"):
        distributed_init(coordinator_address="127.0.0.1:1", num_processes=2)
    with pytest.raises(ValueError, match="out of range"):
        distributed_init("127.0.0.1:1", num_processes=2, process_id=2)
    with pytest.raises(ValueError, match="out of range"):
        distributed_init("127.0.0.1:1", num_processes=2, process_id=-1)
    with pytest.raises(ValueError, match="num_processes"):
        distributed_init("127.0.0.1:1", num_processes=0, process_id=0)
    with pytest.raises(ValueError, match="local_devices"):
        distributed_init(
            "127.0.0.1:1", num_processes=1, process_id=0, local_devices=0
        )


def test_distributed_init_warns_when_gloo_config_missing(monkeypatch):
    # the communication.py gloo-missing branch: a jax whose config lacks the
    # CPU-collectives option degrades to a RuntimeWarning instead of a hang
    class _Unbuilt:
        mesh_built = False

    monkeypatch.setattr(comm_mod, "WORLD", _Unbuilt())
    monkeypatch.setattr(comm_mod, "SELF", _Unbuilt())

    def no_such_option(*a, **kw):
        raise AttributeError("unrecognized config option")

    monkeypatch.setattr(jax.config, "update", no_such_option)
    initialized = {}
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: initialized.update(kw)
    )
    with pytest.warns(RuntimeWarning, match="gloo"):
        distributed_init("127.0.0.1:1", num_processes=1, process_id=0)
    assert initialized == {
        "coordinator_address": "127.0.0.1:1",
        "num_processes": 1,
        "process_id": 0,
    }


# ------------------------------------------------------------------ peer detection
def test_supervisor_detects_lost_peer_by_exact_probe_count(tmp_path):
    s0 = elastic.ElasticSupervisor(str(tmp_path), 0, 2, miss_threshold=3)
    s1 = elastic.ElasticSupervisor(str(tmp_path), 1, 2, miss_threshold=3)
    with monitoring.capture():
        for _ in range(4):
            assert s0.beat() and s1.beat()
            assert not s0.probe() and not s1.probe()
        assert s0.state == "healthy"
        # peer 1 "dies": its heartbeat file freezes. Exactly miss_threshold
        # conclusive no-advance probes later — not one earlier — it is lost.
        for i in range(3):
            s0.beat()
            lost = s0.probe()
            assert (lost == frozenset({1})) == (i == 2), (i, lost)
        assert s0.state == "degraded"
        assert s0.lost_peers() == frozenset({1})
        assert s0.shrunk_world_size() == 1
        # the verdict is final: more probes change nothing
        assert s0.probe() == frozenset({1})
        t = report.telemetry()["robustness_elastic"]
        assert t["peer-lost"] == 1 and t["degraded"] == 1


def test_peer_beat_advance_resets_miss_count(tmp_path):
    s0 = elastic.ElasticSupervisor(str(tmp_path), 0, 2, miss_threshold=3)
    s1 = elastic.ElasticSupervisor(str(tmp_path), 1, 2, miss_threshold=3)
    s1.beat()
    s0.probe()  # sees beat 1
    assert not s0.probe() and not s0.probe()  # 2 misses: below threshold
    s1.beat()  # the slow peer advances
    assert not s0.probe()  # advance resets the count
    assert not s0.probe() and not s0.probe()  # 2 fresh misses: still alive
    assert s0.probe() == frozenset({1})  # third consecutive: lost


def test_probe_fault_is_inconclusive_and_heartbeat_fault_absorbed(tmp_path):
    with monitoring.capture():
        s = elastic.ElasticSupervisor(str(tmp_path), 0, 2, miss_threshold=2)
        # 4 injected probe faults (below the breaker threshold of 5): NO miss
        # advance — a flaky disk or chaos schedule cannot fabricate a loss
        with faultinject.inject("distributed.peer", OSError, at_calls=[1, 2, 3, 4]) as plan:
            for _ in range(4):
                assert not s.probe()
            assert plan.fired == [1, 2, 3, 4]
        assert not s.probe()  # first conclusive miss
        assert s.probe() == frozenset({1})  # second: lost
        # heartbeat faults are absorbed: training never dies for liveness IO
        s2 = elastic.ElasticSupervisor(str(tmp_path / "hb2"), 0, 1)
        with faultinject.inject("distributed.heartbeat", OSError, at_calls=[1]):
            assert s2.beat() is False
        assert s2.beat() is True
        t = report.telemetry()["robustness_elastic"]
        assert t["probe-failed"] == 4 and t["heartbeat-failed"] == 1
        assert report.telemetry()["faults_injected"]["distributed.peer"] == 4


def test_peer_breaker_opens_and_fails_safe(tmp_path):
    with monitoring.capture():
        s = elastic.ElasticSupervisor(str(tmp_path), 0, 2, miss_threshold=1)
        with faultinject.inject("distributed.peer", OSError, at_calls="*"):
            for _ in range(5):
                s.probe()  # 5 consecutive failures: breaker opens
        assert breaker.breaker("distributed.peer").state() == "open"
        # open probe breaker: reads are skipped, misses never advance, nobody
        # is EVER declared lost — fail-safe by construction
        for _ in range(10):
            assert not s.probe()
        t = report.telemetry()["robustness_elastic"]
        assert t["probe-skipped"] == 10
        assert "peer-lost" not in t


def test_forced_open_breakers_keep_supervisor_inert(tmp_path, monkeypatch):
    monkeypatch.setenv("HEAT_TPU_BREAKER_FORCE_OPEN", "*")
    with monitoring.capture():
        s = elastic.ElasticSupervisor(str(tmp_path), 0, 2, miss_threshold=1)
        assert s.beat() is False  # skipped, not failed
        assert s.probe() == frozenset()
        t = report.telemetry()["robustness_elastic"]
        assert t["heartbeat-skipped"] == 1 and t["probe-skipped"] == 1
        assert "peer-lost" not in t and s.state == "healthy"


def test_chaos_schedules_distributed_sites_without_fabricating_loss(tmp_path):
    # the distributed.* sites are chaos-schedulable (opt-in, like
    # collective.dispatch); a live peer under standing chaos is never lost —
    # probe faults are inconclusive and heartbeat faults only skip one beat
    with monitoring.capture():
        with chaos.install("20260805:0.3:distributed.heartbeat,distributed.peer") as handle:
            s0 = elastic.ElasticSupervisor(str(tmp_path), 0, 2, miss_threshold=3)
            s1 = elastic.ElasticSupervisor(str(tmp_path), 1, 2, miss_threshold=3)
            for _ in range(20):
                s0.beat()
                s1.beat()
                assert not s0.probe()
                assert not s1.probe()
            fired = handle.fired()
        assert any(fired.values())  # the schedule genuinely exercised the sites
        t = report.telemetry()
        assert sum(t["chaos_fires"].values()) == sum(len(v) for v in fired.values())
        assert "peer-lost" not in t["robustness_elastic"]


def test_supervisor_validates_arguments(tmp_path):
    with pytest.raises(ValueError):
        elastic.ElasticSupervisor(str(tmp_path), 2, 2)
    with pytest.raises(ValueError):
        elastic.ElasticSupervisor(str(tmp_path), 0, 1, miss_threshold=0)
    assert elastic.survivors(str(tmp_path), 2) == []
    s = elastic.ElasticSupervisor(str(tmp_path), 1, 2)
    s.beat()
    assert elastic.survivors(str(tmp_path), 2) == [1]


# ------------------------------------------------------------------ drain + save
def test_drain_and_save_flushes_pending_and_checkpoints(tmp_path):
    fusion.clear_cache()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    s = elastic.ElasticSupervisor(str(tmp_path / "hb"), 0, 1, manager=mgr)
    with monitoring.capture():
        x = ht.arange(16, split=0, dtype=ht.float32)
        y = x * 2.0 + 1.0  # a pending fused chain
        path = s.drain_and_save({"y": y, "step": 5}, step=5)
        t = report.telemetry()
        assert t["robustness_elastic"] == {"draining": 1, "saving": 1, "saved": 1}
        assert t["counters"]["fusion.flushes"] >= 1  # the drain flushed it
    assert s.state == "saved" and s.saved_step == 5
    assert mgr.latest_valid_step() == 5
    back = mgr.restore_latest_valid(
        {"y": ht.zeros(16, split=0, dtype=ht.float32), "step": 0}
    )
    assert np.array_equal(back["y"].numpy(), np.arange(16, dtype=np.float32) * 2.0 + 1.0)
    assert path == str(tmp_path / "ck" / "ckpt_000000000005.h5")


# -------------------------------------------------------- in-process elastic proof
class _TinyNet:
    """Minimal .init/.apply module (no flax dependency in the hot loop)."""

    def init(self, rng, x):
        k = jax.random.PRNGKey(0) if isinstance(rng, int) else rng
        return {"w": jax.random.normal(k, (x.shape[1], 1), jnp.float32) * 0.1}

    def apply(self, params, x):
        return x @ params["w"]


def _mse(params, apply_fn, x, y):
    return ((apply_fn(params, x) - y) ** 2).mean()


def _batch():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 4)).astype(np.float32)
    return x, x.sum(axis=1, keepdims=True).astype(np.float32)


def test_dryrun_elastic_restart_onto_shrunk_mesh(tmp_path):
    # the single-process proof of the whole elastic flow (the PR 3
    # dryrun_multichip precedent): an 8-device world loses a simulated peer,
    # the survivor drains + saves through the preemption-safe path, and the
    # run resumes on a SHRUNK mesh with exact params/step/RNG
    if len(_DEVS) < 2:
        pytest.skip("needs a multi-device mesh to shrink")
    x, y = _batch()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    sup = elastic.ElasticSupervisor(
        str(tmp_path / "hb"), 0, 2, miss_threshold=2, manager=mgr
    )
    big = MeshCommunication(devices=_DEVS)
    dp = DataParallel(_TinyNet(), comm=big, optimizer=optax.sgd(0.05))
    dp.init(0, x)
    dp.make_train_step(_mse)
    dp.attach_elastic(sup)
    with monitoring.capture():
        dp.train_step(x, y)  # poll: miss 1 (peer 1 never beats), then the step runs
        with pytest.raises(elastic.PeerLostError) as ei:
            dp.train_step(x, y)  # poll: miss 2 = threshold -> drain+save+raise
        t = report.telemetry()["robustness_elastic"]
        assert t["restart-pending"] == 1 and t["peer-lost"] == 1
    err = ei.value
    assert err.survivors == 1 and err.saved_path is not None
    saved_params = np.asarray(dp.params["w"])
    saved_rng = ht.random.get_state()
    # --- the "respawned" shrunk run: half the devices
    small = MeshCommunication(devices=_DEVS[: len(_DEVS) // 2])
    dp2 = DataParallel(_TinyNet(), comm=small, optimizer=optax.sgd(0.05))
    dp2.init(1, x)  # different seed: restore must overwrite everything
    dp2.make_train_step(_mse)
    state = mgr.restore_latest_valid(dp2.checkpoint_state())
    dp2.load_state(state)
    assert dp2.step_count == err.saved_step
    assert np.asarray(dp2.params["w"]).tobytes() == saved_params.tobytes()
    assert ht.random.get_state() == saved_rng
    # training continues on the shrunk mesh
    loss = dp2.train_step(x, y)
    assert np.isfinite(float(loss))


def test_daso_elastic_poll_drains_and_raises(tmp_path):
    x, y = _batch()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    sup = elastic.ElasticSupervisor(
        str(tmp_path / "hb"), 0, 2, miss_threshold=1, manager=mgr
    )
    daso = DASO(
        local_optimizer=optax.sgd(1e-2),
        total_epochs=2,
        warmup_epochs=0,
        cooldown_epochs=0,
        max_global_skips=2,
    )
    params = _TinyNet().init(0, x)
    daso.init(params)
    daso.make_train_step(_mse, _TinyNet().apply)
    daso.step(x, y)
    daso.attach_elastic(sup)
    with pytest.raises(elastic.PeerLostError) as ei:
        daso.step(x, y)
    assert ei.value.saved_step == 1
    assert mgr.latest_valid_step() == 1
    assert sup.state == "restart-pending"
    # the saved DASO state restores with the loop position intact
    target = {k: v for k, v in daso.checkpoint_state().items()}
    back = mgr.restore_latest_valid(target)
    assert back["step"] == 1 and back["epoch"] == 0


def test_telemetry_exports_elastic_counters(tmp_path):
    with monitoring.capture():
        s = elastic.ElasticSupervisor(str(tmp_path), 0, 2, miss_threshold=1)
        s.beat()
        s.probe()
        t = report.telemetry()
        assert "robustness_elastic" in t
        assert t["robustness_elastic"]["peer-lost"] == 1


# ------------------------------------------------------ kill -9 acceptance (2 procs)
# jax 0.4.x ships a gloo TCP transport with a framing bug (see
# tests/test_multihost.py); 2-process runs generally work, but transport
# flakiness under host load gets the documented skip, not a red build
_LEGACY_GLOO = tuple(int(v) for v in jax.__version__.split(".")[:2]) < (0, 5)

_ELASTIC_WORKER = textwrap.dedent(
    """
    import json, os, signal, sys, time, zlib
    pid = int(sys.argv[1]); nprocs = int(sys.argv[2]); port = sys.argv[3]; tmp = sys.argv[4]
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    import heat_tpu as ht
    from heat_tpu.core.communication import MeshCommunication, distributed_init
    from heat_tpu.nn.data_parallel import DataParallel
    from heat_tpu.robustness import elastic
    from heat_tpu.utils.checkpoint import CheckpointManager
    import jax, jax.numpy as jnp, optax

    if nprocs > 1:
        distributed_init(f"127.0.0.1:{port}", num_processes=nprocs, process_id=pid,
                         local_devices=2)
        # prove the pod is genuinely wired: one cross-host psum
        g = ht.arange(4 * jax.device_count(), split=0, dtype=ht.float32)
        n = 4 * jax.device_count()
        assert float(ht.sum(g).item()) == n * (n - 1) / 2.0
    else:
        from heat_tpu.core._compat import set_cpu_device_count
        set_cpu_device_count(2)

    class Tiny:
        def init(self, rng, x):
            k = jax.random.PRNGKey(0) if isinstance(rng, int) else rng
            return {"w": jax.random.normal(k, (x.shape[1], 1), jnp.float32) * 0.1}
        def apply(self, params, x):
            return x @ params["w"]

    def mse(p, apply_fn, x, y):
        return ((apply_fn(p, x) - y) ** 2).mean()

    rng = np.random.default_rng(0)
    xb = rng.standard_normal((8, 4)).astype(np.float32)
    yb = xb.sum(axis=1, keepdims=True).astype(np.float32)
    # steady-state training is LOCAL (this host's 2 devices) — the DASO
    # local-sync tier; cross-host traffic is the startup psum above plus the
    # elastic checkpoint protocol. A collective against a dead peer would
    # hang, so the supervisor poll must precede any global dispatch.
    local = MeshCommunication(devices=jax.local_devices())
    dp = DataParallel(Tiny(), comm=local, optimizer=optax.sgd(0.05))
    dp.init(0, xb)
    dp.make_train_step(mse)
    hb, ck = f"{tmp}/hb", f"{tmp}/ck"

    # warm the jitted step BEFORE supervision starts: both workers compile the
    # same program concurrently, so the first heartbeat lands only once the
    # steady-state (fast) step cadence is established — scheduler skew on a
    # loaded 1-core host then cannot mimic a dead peer
    dp.train_step(xb, yb)

    if nprocs > 1 and pid == 1:
        # the victim: beats while training, then takes a real kill -9 —
        # no atexit, no flush, the heartbeat file freezes mid-run
        sup = elastic.ElasticSupervisor(hb, process_id=1, num_processes=2)
        for _ in range(3):
            sup.beat()
            dp.train_step(xb, yb)
            time.sleep(0.02)
        sup.beat()
        print("victim about to die", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
    elif nprocs > 1:
        # the survivor: full supervision; a generous miss threshold tolerates
        # scheduler skew on a loaded host (a live-but-slow peer resets the
        # count on its next beat; only a dead one misses 50 straight)
        mgr = CheckpointManager(ck)
        sup = elastic.ElasticSupervisor(hb, process_id=0, num_processes=2,
                                        miss_threshold=50, manager=mgr)
        dp.attach_elastic(sup)
        try:
            for _ in range(4000):
                dp.train_step(xb, yb)
                time.sleep(0.02)
            raise SystemExit("peer loss never detected")
        except elastic.PeerLostError as e:
            manifest = {
                "step": e.saved_step,
                "survivors": e.survivors,
                "crc": zlib.crc32(np.asarray(dp.params["w"]).tobytes()),
                "rng": list(ht.random.get_state()),
            }
            with open(f"{tmp}/manifest.json", "w") as f:
                json.dump(manifest, f)
            print(f"survivor saved step {e.saved_step}", flush=True)
            os._exit(elastic.ELASTIC_RESTART_EXIT)
    else:
        # the shrunk relaunch: restore the survivor's checkpoint onto the
        # (N-1)-process world and train on
        with open(f"{tmp}/manifest.json") as f:
            manifest = json.load(f)
        mgr = CheckpointManager(ck)
        dp.init(1, xb)  # different seed: restore must overwrite everything
        state = mgr.restore_latest_valid(dp.checkpoint_state())
        dp.load_state(state)
        assert dp.step_count == manifest["step"], (dp.step_count, manifest)
        assert zlib.crc32(np.asarray(dp.params["w"]).tobytes()) == manifest["crc"]
        assert list(ht.random.get_state()) == manifest["rng"]
        for _ in range(2):
            loss = dp.train_step(xb, yb)
        assert np.isfinite(float(loss))
        print("resume ok", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(worker, args, env):
    return subprocess.Popen(
        [sys.executable, str(worker)] + [str(a) for a in args],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


@pytest.mark.slow  # multi-process spawn + heartbeat timeouts; the dedicated
# CI kill9 leg runs this test directly (ISSUE 16 tier-1 rebalance)
def test_kill9_elastic_restart_shrinks_mesh(tmp_path):
    """ISSUE 11 acceptance: kill -9 of one worker in a 2-process localhost
    ``jax.distributed`` run → the survivor detects the loss via heartbeats,
    drains + saves, exits ``ELASTIC_RESTART_EXIT``; the relaunch restores the
    latest valid checkpoint onto the 1-process world and keeps training with
    exact params/step/RNG."""
    worker = tmp_path / "worker.py"
    worker.write_text(_ELASTIC_WORKER)
    env = {
        k: v
        for k, v in os.environ.items()
        # the parent's 8-device flag and any standing chaos/fault/breaker CI
        # envs must not leak into the workers: each process provisions its own
        # 2-device world and the test asserts exact elastic behavior
        if k
        not in (
            "XLA_FLAGS",
            "PYTHONPATH",
            "HEAT_TPU_CHAOS",
            "HEAT_TPU_FAULT_PLAN",
            "HEAT_TPU_BREAKER_FORCE_OPEN",
        )
    }
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = _free_port()
    procs = [
        _spawn(worker, [pid, 2, port, tmp_path], env) for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    blob = "\n".join(outs)
    if _LEGACY_GLOO and (
        "Connection reset by peer" in blob
        or "heartbeat timeout" in blob
        or "preamble" in blob
    ) and procs[0].returncode not in (elastic.ELASTIC_RESTART_EXIT,):
        # the jax<0.5 gloo tcp framing race (reproduced standalone, see
        # test_multihost.py) — environment defect; the dryrun proof above
        # pins the elastic contract unconditionally
        pytest.skip("jax<0.5 gloo tcp framing race killed the pod")
    assert procs[1].returncode == -signal.SIGKILL, f"victim:\n{outs[1][-2000:]}"
    assert procs[0].returncode == elastic.ELASTIC_RESTART_EXIT, (
        f"survivor:\n{outs[0][-3000:]}"
    )
    assert "survivor saved step" in outs[0]
    manifest = json.loads((tmp_path / "manifest.json").read_text())
    assert manifest["survivors"] == 1 and manifest["step"] >= 1
    # --- phase B: the shrunk relaunch
    resumed = _spawn(worker, [0, 1, 0, tmp_path], env)
    out, _ = resumed.communicate(timeout=300)
    assert resumed.returncode == 0, f"resumed worker:\n{out[-3000:]}"
    assert "resume ok" in out
