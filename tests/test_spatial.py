"""Tests for pairwise distances incl. the ppermute ring (parity model: reference
heat/spatial/tests/test_distance.py)."""

import numpy as np
import pytest

import heat_tpu as ht


def _cdist_np(a, b):
    return np.sqrt(((a[:, None, :] - b[None, :, :]) ** 2).sum(-1))


@pytest.mark.parametrize("split", [None, 0])
@pytest.mark.parametrize("quad", [False, True])
def test_cdist(split, quad):
    rng = np.random.default_rng(10)
    a = rng.normal(size=(16, 4)).astype(np.float32)  # divisible by 8 -> ring path
    h = ht.array(a, split=split)
    d = ht.spatial.cdist(h, quadratic_expansion=quad)
    np.testing.assert_allclose(d.numpy(), _cdist_np(a, a), atol=5e-3)
    assert d.shape == (16, 16)
    assert d.split == split


def test_cdist_two_operands():
    rng = np.random.default_rng(11)
    a = rng.normal(size=(16, 3)).astype(np.float32)
    b = rng.normal(size=(8, 3)).astype(np.float32)
    d = ht.spatial.cdist(ht.array(a, split=0), ht.array(b, split=0))
    np.testing.assert_allclose(d.numpy(), _cdist_np(a, b), atol=5e-3)
    # ragged (non divisible) shapes take the broadcast fallback
    c = rng.normal(size=(10, 3)).astype(np.float32)
    d2 = ht.spatial.cdist(ht.array(c, split=0), ht.array(b, split=0))
    np.testing.assert_allclose(d2.numpy(), _cdist_np(c, b), atol=5e-3)


@pytest.mark.parametrize("quad", [False, True])
def test_rbf(quad):
    rng = np.random.default_rng(12)
    a = rng.normal(size=(16, 4)).astype(np.float32)
    sigma = 2.0
    k = ht.spatial.rbf(ht.array(a, split=0), sigma=sigma, quadratic_expansion=quad)
    expected = np.exp(-_cdist_np(a, a) ** 2 / (2 * sigma**2))
    np.testing.assert_allclose(k.numpy(), expected, atol=5e-3)


def test_manhattan():
    rng = np.random.default_rng(13)
    a = rng.normal(size=(16, 4)).astype(np.float32)
    d = ht.spatial.manhattan(ht.array(a, split=0))
    expected = np.abs(a[:, None, :] - a[None, :, :]).sum(-1)
    np.testing.assert_allclose(d.numpy(), expected, atol=1e-4)


def test_cdist_input_validation():
    with pytest.raises(NotImplementedError):
        ht.spatial.cdist(ht.ones((2, 2, 2)))
    with pytest.raises(TypeError):
        ht.spatial.cdist(np.ones((4, 4)))
