"""Tests for pairwise distances incl. the ppermute ring (parity model: reference
heat/spatial/tests/test_distance.py)."""

import numpy as np
import pytest

import heat_tpu as ht


def _cdist_np(a, b):
    return np.sqrt(((a[:, None, :] - b[None, :, :]) ** 2).sum(-1))


@pytest.mark.parametrize("split", [None, 0])
@pytest.mark.parametrize("quad", [False, True])
def test_cdist(split, quad):
    rng = np.random.default_rng(10)
    a = rng.normal(size=(16, 4)).astype(np.float32)  # divisible by 8 -> ring path
    h = ht.array(a, split=split)
    d = ht.spatial.cdist(h, quadratic_expansion=quad)
    np.testing.assert_allclose(d.numpy(), _cdist_np(a, a), atol=5e-3)
    assert d.shape == (16, 16)
    assert d.split == split


def test_cdist_two_operands():
    rng = np.random.default_rng(11)
    a = rng.normal(size=(16, 3)).astype(np.float32)
    b = rng.normal(size=(8, 3)).astype(np.float32)
    d = ht.spatial.cdist(ht.array(a, split=0), ht.array(b, split=0))
    np.testing.assert_allclose(d.numpy(), _cdist_np(a, b), atol=5e-3)
    # ragged (non divisible) shapes take the broadcast fallback
    c = rng.normal(size=(10, 3)).astype(np.float32)
    d2 = ht.spatial.cdist(ht.array(c, split=0), ht.array(b, split=0))
    np.testing.assert_allclose(d2.numpy(), _cdist_np(c, b), atol=5e-3)


@pytest.mark.parametrize("quad", [False, True])
def test_rbf(quad):
    rng = np.random.default_rng(12)
    a = rng.normal(size=(16, 4)).astype(np.float32)
    sigma = 2.0
    k = ht.spatial.rbf(ht.array(a, split=0), sigma=sigma, quadratic_expansion=quad)
    expected = np.exp(-_cdist_np(a, a) ** 2 / (2 * sigma**2))
    np.testing.assert_allclose(k.numpy(), expected, atol=5e-3)


def test_manhattan():
    rng = np.random.default_rng(13)
    a = rng.normal(size=(16, 4)).astype(np.float32)
    d = ht.spatial.manhattan(ht.array(a, split=0))
    expected = np.abs(a[:, None, :] - a[None, :, :]).sum(-1)
    np.testing.assert_allclose(d.numpy(), expected, atol=1e-4)


def test_cdist_input_validation():
    with pytest.raises(NotImplementedError):
        ht.spatial.cdist(ht.ones((2, 2, 2)))
    with pytest.raises(TypeError):
        ht.spatial.cdist(np.ones((4, 4)))


@pytest.mark.parametrize("p", [3, 5, 8])
@pytest.mark.parametrize("metric", ["cdist", "rbf", "manhattan"])
def test_symmetric_half_ring_matches_full(p, metric):
    """cdist(X) takes the half-ring (transpose send-back) path for p>2; results
    must match the two-operand full ring and scipy-style ground truth."""
    import jax as _jax
    from heat_tpu.core.communication import MeshCommunication

    devs = _jax.devices()
    if len(devs) < p:
        pytest.skip("needs more devices")
    comm = MeshCommunication(devices=devs[:p])
    rng = np.random.default_rng(p)
    a = rng.standard_normal((p * 6, 4)).astype(np.float32)
    x = ht.array(a, split=0, comm=comm)
    if metric == "cdist":
        got = ht.spatial.cdist(x)
        want = np.sqrt(((a[:, None] - a[None]) ** 2).sum(-1))
        tol = 1e-4
    elif metric == "rbf":
        got = ht.spatial.rbf(x, sigma=0.7)
        want = np.exp(-((a[:, None] - a[None]) ** 2).sum(-1) / (2 * 0.7**2))
        tol = 1e-5
    else:
        got = ht.spatial.manhattan(x)
        want = np.abs(a[:, None] - a[None]).sum(-1)
        tol = 1e-4
    np.testing.assert_allclose(got.numpy(), want, atol=tol, rtol=tol)
    assert got.split == 0
    # and the explicit two-operand form agrees
    full = ht.spatial.cdist(x, ht.array(a, split=0, comm=comm)) if metric == "cdist" else None
    if full is not None:
        np.testing.assert_allclose(full.numpy(), want, atol=tol, rtol=tol)


def test_cdist_deep_matrix():
    # shapes x splits x metrics x expansion grid vs scipy-style ground truth
    rng = np.random.default_rng(51)
    p = ht.get_comm().size
    for n, m, f in [(2 * p, 3 * p, 4), (13, 9, 3), (p, p, 8)]:
        x_np = rng.normal(size=(n, f)).astype(np.float32)
        y_np = rng.normal(size=(m, f)).astype(np.float32)
        d_true = np.sqrt(((x_np[:, None] - y_np[None]) ** 2).sum(-1))
        for sx, sy in [(0, 0), (0, None), (None, 0), (None, None)]:
            for quad in (False, True):
                d = ht.spatial.cdist(
                    ht.array(x_np, split=sx), ht.array(y_np, split=sy),
                    quadratic_expansion=quad,
                )
                np.testing.assert_allclose(d.numpy(), d_true, rtol=2e-2, atol=2e-2)
    # manhattan ground truth
    x_np = rng.normal(size=(2 * p, 3)).astype(np.float32)
    m_true = np.abs(x_np[:, None] - x_np[None]).sum(-1)
    got = ht.spatial.manhattan(ht.array(x_np, split=0), ht.array(x_np, split=0))
    np.testing.assert_allclose(got.numpy(), m_true, rtol=1e-4, atol=1e-4)
    # rbf kernel value range
    k = ht.spatial.rbf(ht.array(x_np, split=0), sigma=2.0)
    kn = k.numpy()
    assert np.allclose(np.diag(kn), 1.0, atol=1e-5)
    assert (kn <= 1.0 + 1e-6).all() and (kn >= 0).all()


def test_self_cdist_zero_diagonal_and_symmetry():
    rng = np.random.default_rng(52)
    x_np = rng.normal(size=(17, 5)).astype(np.float32)
    d = ht.spatial.cdist(ht.array(x_np, split=0)).numpy()
    assert np.allclose(np.diag(d), 0.0, atol=1e-5)
    np.testing.assert_allclose(d, d.T, rtol=1e-5, atol=1e-5)
