"""
Operator edge-case matrix: dtype casts, bitwise/shift ops, out=/where=
parameters, keepdims/tuple-axis reductions, and mixed-operand binaries over
split × even/ragged shapes — the reference's per-module edge density
(reference heat/core/tests/test_arithmetics.py, test_logical.py,
test_relational.py, test_types.py cast tests) on the golden harness.
"""

import numpy as np
import pytest

import jax
from heat_tpu.core import _compat

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication


def _comm():
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs a multi-device mesh")
    return MeshCommunication(devices=devs)


SPLITS = [None, 0, 1]
SHAPES = [(16, 6), (13, 5)]


def _mk(shape, split, comm, dtype=np.float32, lo=1, hi=9):
    a = (np.arange(np.prod(shape)) % (hi - lo) + lo).astype(dtype).reshape(shape)
    return a, ht.array(a.copy(), split=split, comm=comm)


# ----------------------------------------------------------------- dtype casts
CASTS = [
    (ht.float32, np.float32),
    (ht.float64, np.float64),
    (ht.int32, np.int32),
    (ht.int64, np.int64),
    (ht.uint8, np.uint8),
    (ht.bool, np.bool_),
    (ht.bfloat16, None),
    (ht.float16, np.float16),
]


@pytest.mark.parametrize("split", [None, 0])
@pytest.mark.parametrize("ht_t,np_t", CASTS)
def test_astype_matrix(split, ht_t, np_t):
    import contextlib

    comm = _comm()
    # the 64-bit slices run under real x64 (VERDICT r3 weak #4: without this
    # they silently truncated to 32 bits and tested f32 twice)
    ctx = (
        _compat.enable_x64(True)
        if ht_t in (ht.float64, ht.int64)
        else contextlib.nullcontext()
    )
    with ctx:
        a, x = _mk((13, 4), split, comm)
        y = x.astype(ht_t)
        assert y.dtype == ht_t
        if ht_t is ht.float64:
            assert y.larray.dtype == np.float64  # genuinely 64-bit, not truncated
        if ht_t is ht.int64:
            assert y.larray.dtype == np.int64
        assert y.shape == x.shape and y.split == split
        if np_t is not None and np_t is not np.bool_:
            np.testing.assert_allclose(
                y.numpy().astype(np.float64), a.astype(np_t).astype(np.float64)
            )
        # in-place variant updates metadata
        z = ht.array(a.copy(), split=split, comm=comm)
        r = z.astype(ht_t, copy=False)
        assert r is z and z.dtype == ht_t


@pytest.mark.parametrize("split", [None, 0])
def test_scalar_casts(split):
    comm = _comm()
    one = ht.array(np.array([2.5], np.float32), split=split, comm=comm)
    assert float(one) == 2.5
    assert int(one) == 2
    assert bool(one) is True
    assert complex(one) == 2.5 + 0j
    idx = ht.array(np.array([3], np.int32), split=split, comm=comm)
    assert np.arange(10)[int(idx)] == 3  # __index__
    with pytest.raises(ValueError):
        float(ht.ones((2, 2), comm=comm))
    with pytest.raises((TypeError, IndexError)):
        np.arange(10)[one]  # float can't be an index


# ----------------------------------------------------------- bitwise and shifts
@pytest.mark.parametrize("split", SPLITS)
def test_bitwise_and_shift_ops(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm, dtype=np.int32)
    b, y = _mk((13, 5), split, comm, dtype=np.int32, lo=2, hi=11)
    np.testing.assert_array_equal(ht.bitwise_and(x, y).numpy(), a & b)
    np.testing.assert_array_equal(ht.bitwise_or(x, y).numpy(), a | b)
    np.testing.assert_array_equal(ht.bitwise_xor(x, y).numpy(), a ^ b)
    np.testing.assert_array_equal(ht.invert(x).numpy(), ~a)
    np.testing.assert_array_equal(ht.left_shift(x, 2).numpy(), a << 2)
    np.testing.assert_array_equal(ht.right_shift(x, 1).numpy(), a >> 1)
    np.testing.assert_array_equal((x & y).numpy(), a & b)
    np.testing.assert_array_equal((x | y).numpy(), a | b)
    np.testing.assert_array_equal((x ^ y).numpy(), a ^ b)
    with pytest.raises(TypeError):
        ht.bitwise_and(x.astype(ht.float32), y)


# ----------------------------------------------------------------- mod / floor
@pytest.mark.parametrize("split", SPLITS)
def test_division_family(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    b, y = _mk((13, 5), split, comm, lo=2, hi=7)
    np.testing.assert_allclose(ht.div(x, y).numpy(), a / b, rtol=1e-6)
    np.testing.assert_allclose(ht.floordiv(x, y).numpy(), a // b)
    np.testing.assert_allclose(ht.mod(x, y).numpy(), a % b)
    np.testing.assert_allclose(ht.fmod(x, y).numpy(), np.fmod(a, b))
    np.testing.assert_allclose(ht.remainder(x, y).numpy(), np.remainder(a, b))
    np.testing.assert_allclose((x // y).numpy(), a // b)
    np.testing.assert_allclose((x % y).numpy(), a % b)
    np.testing.assert_allclose((x ** 2).numpy(), a ** 2)
    np.testing.assert_allclose((2 ** x).numpy().astype(np.float64), (2.0 ** a).astype(np.float64), rtol=2e-5)
    np.testing.assert_allclose((-x).numpy(), -a)
    np.testing.assert_allclose((+x).numpy(), +a)
    np.testing.assert_allclose(abs(-x).numpy(), a)


# ------------------------------------------------------------------ out= where=
@pytest.mark.parametrize("split", [None, 0])
def test_out_parameter(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    b, y = _mk((13, 5), split, comm, lo=3, hi=8)
    out = ht.zeros((13, 5), split=split, comm=comm)
    r = ht.add(x, y, out=out)
    assert r is out
    np.testing.assert_array_equal(out.numpy(), a + b)
    out2 = ht.zeros((13, 5), split=split, comm=comm)
    ht.exp(x / 10.0, out=out2)
    np.testing.assert_allclose(out2.numpy(), np.exp(a / 10.0), rtol=1e-5)
    with pytest.raises(ValueError):
        ht.add(x, y, out=ht.zeros((2, 2), comm=comm))
    with pytest.raises(TypeError):
        ht.add(x, y, out="nope")


@pytest.mark.parametrize("split", [None, 0])
def test_where_parameter(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    b, y = _mk((13, 5), split, comm, lo=3, hi=8)
    mask = (np.arange(13) % 2 == 0)[:, None] & np.ones((13, 5), bool)
    got = ht.add(x, y, where=ht.array(mask, comm=comm))
    want = np.where(mask, a + b, 0)
    np.testing.assert_array_equal(got.numpy(), want)


# ------------------------------------------------------- reductions: keep/tuple
@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("split", SPLITS)
def test_reduction_keepdims_and_tuple_axes(shape, split):
    comm = _comm()
    a, x = _mk(shape, split, comm)
    np.testing.assert_allclose(ht.sum(x, axis=(0, 1)).numpy(), a.sum(axis=(0, 1)), rtol=1e-5)
    np.testing.assert_allclose(
        ht.sum(x, axis=(0, 1), keepdim=True).numpy(), a.sum(axis=(0, 1), keepdims=True), rtol=1e-5
    )
    np.testing.assert_allclose(ht.sum(x, axis=-1).numpy(), a.sum(axis=-1), rtol=1e-5)
    np.testing.assert_allclose(
        ht.max(x, axis=0, keepdim=True).numpy(), a.max(axis=0, keepdims=True)
    )
    np.testing.assert_allclose(
        ht.min(x, axis=-2, keepdim=True).numpy(), a.min(axis=0, keepdims=True)
    )
    np.testing.assert_allclose(ht.mean(x, axis=(0,)).numpy(), a.mean(axis=0), rtol=1e-5)
    # split survives reduction over the other axis
    if split == 0:
        assert ht.sum(x, axis=1).split == 0
        assert ht.sum(x, axis=0).split is None
    if split == 1:
        assert ht.sum(x, axis=0).split == 0  # shifted left
        assert ht.sum(x, axis=0, keepdim=True).split == 1


@pytest.mark.parametrize("split", SPLITS)
def test_logical_reductions_matrix(split):
    comm = _comm()
    a = (np.arange(65) % 5 > 0).reshape(13, 5)
    x = ht.array(a, split=split, comm=comm)
    assert bool(ht.all(x)) == a.all()
    assert bool(ht.any(x)) == a.any()
    np.testing.assert_array_equal(ht.all(x, axis=0).numpy(), a.all(axis=0))
    np.testing.assert_array_equal(ht.any(x, axis=1).numpy(), a.any(axis=1))
    inv = ht.logical_not(x)
    np.testing.assert_array_equal(ht.logical_and(x, inv).numpy(), np.zeros_like(a))
    np.testing.assert_array_equal(ht.logical_or(x, inv).numpy(), np.ones_like(a))
    np.testing.assert_array_equal(ht.logical_not(x).numpy(), ~a)
    np.testing.assert_array_equal(ht.logical_xor(x, x).numpy(), np.zeros_like(a))


@pytest.mark.parametrize("split", SPLITS)
def test_isclose_family(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    noisy = a + 1e-7
    y = ht.array(noisy, split=split, comm=comm)
    assert bool(ht.allclose(x, y, atol=1e-5))
    assert not bool(ht.allclose(x, y + 1.0))
    np.testing.assert_array_equal(
        ht.isclose(x, y, atol=1e-5).numpy(), np.isclose(a, noisy, atol=1e-5)
    )
    f = a.copy()
    f[0, 0] = np.inf
    f[1, 1] = -np.inf
    f[2, 2] = np.nan
    z = ht.array(f, split=split, comm=comm)
    np.testing.assert_array_equal(ht.isfinite(z).numpy(), np.isfinite(f))
    np.testing.assert_array_equal(ht.isinf(z).numpy(), np.isinf(f))
    np.testing.assert_array_equal(ht.isnan(z).numpy(), np.isnan(f))
    np.testing.assert_array_equal(ht.isposinf(z).numpy(), np.isposinf(f))
    np.testing.assert_array_equal(ht.isneginf(z).numpy(), np.isneginf(f))


# ----------------------------------------------------------- mixed-split binary
@pytest.mark.parametrize("s1", SPLITS)
@pytest.mark.parametrize("s2", SPLITS)
def test_mixed_split_binary(s1, s2):
    comm = _comm()
    a, x = _mk((13, 5), s1, comm)
    b, y = _mk((13, 5), s2, comm, lo=2, hi=6)
    got = x + y
    np.testing.assert_array_equal(got.numpy(), a + b)
    # dominance: leftmost non-None split wins (reference _operations.py:57-71)
    expect = s1 if s1 is not None else s2
    assert got.split == expect
    got2 = x * y - y
    np.testing.assert_array_equal(got2.numpy(), a * b - b)


@pytest.mark.parametrize("split", [0, 1])
def test_broadcast_binary_combinations(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    row = np.arange(5, dtype=np.float32)
    col = np.arange(13, dtype=np.float32)[:, None]
    np.testing.assert_array_equal((x + row).numpy(), a + row)
    np.testing.assert_array_equal((x * col).numpy(), a * col)
    np.testing.assert_array_equal((row + x).numpy(), row + a)
    hrow = ht.array(row, comm=comm)
    np.testing.assert_array_equal((x - hrow).numpy(), a - row)
    hcol = ht.array(col, split=0 if split == 0 else None, comm=comm)
    np.testing.assert_array_equal((x / (hcol + 1)).numpy(), a / (col + 1))
    # scalar operands keep weak typing
    assert (x + 1).dtype == x.dtype
    assert (x * 2.0).dtype == x.dtype


# -------------------------------------------------------------------- rounding
@pytest.mark.parametrize("split", [None, 0])
def test_rounding_family(split):
    comm = _comm()
    a = np.linspace(-3.7, 3.7, 28, dtype=np.float32).reshape(7, 4)
    x = ht.array(a, split=split, comm=comm)
    np.testing.assert_array_equal(ht.floor(x).numpy(), np.floor(a))
    np.testing.assert_array_equal(ht.ceil(x).numpy(), np.ceil(a))
    np.testing.assert_array_equal(ht.trunc(x).numpy(), np.trunc(a))
    np.testing.assert_allclose(ht.round(x).numpy(), np.round(a))
    np.testing.assert_array_equal(ht.sign(x).numpy(), np.sign(a))
    np.testing.assert_array_equal(ht.abs(x).numpy(), np.abs(a))
    np.testing.assert_array_equal(ht.fabs(x).numpy(), np.fabs(a))
    np.testing.assert_allclose(ht.clip(x, -1.0, 2.0).numpy(), np.clip(a, -1.0, 2.0))
    frac, whole = ht.modf(x)
    wf, ww = np.modf(a)
    np.testing.assert_allclose(frac.numpy(), wf, atol=1e-6)
    np.testing.assert_allclose(whole.numpy(), ww)


# ------------------------------------------------------------------ cumulative
@pytest.mark.parametrize("shape", SHAPES)
@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("axis", [0, 1])
def test_cumulative_matrix(shape, split, axis):
    comm = _comm()
    a, x = _mk(shape, split, comm)
    np.testing.assert_allclose(ht.cumsum(x, axis=axis).numpy(), a.cumsum(axis=axis), rtol=1e-5)
    small = a / a.max()
    y = ht.array(small, split=split, comm=comm)
    np.testing.assert_allclose(ht.cumprod(y, axis=axis).numpy(), small.cumprod(axis=axis), rtol=1e-4)


# ---------------------------------------------------------------------- diff
@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("n", [1, 2])
def test_diff_matrix(split, n):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    for axis in (0, 1, -1):
        np.testing.assert_allclose(
            ht.diff(x, n=n, axis=axis).numpy(), np.diff(a, n=n, axis=axis)
        )


# ---------------------------------------------------------------- statistics
@pytest.mark.parametrize("split", [None, 0])
def test_statistics_edge(split):
    comm = _comm()
    rng = np.random.default_rng(11)
    a = rng.standard_normal((13, 5)).astype(np.float32)
    x = ht.array(a, split=split, comm=comm)
    np.testing.assert_allclose(ht.average(x).numpy(), np.average(a), rtol=1e-5)
    w = np.abs(rng.standard_normal(5)).astype(np.float32)
    avg, wsum = ht.average(x, axis=1, weights=ht.array(w, comm=comm), returned=True)
    np.testing.assert_allclose(avg.numpy(), np.average(a, axis=1, weights=w), rtol=1e-5)
    np.testing.assert_allclose(ht.var(x, axis=0, ddof=1).numpy(), a.var(axis=0, ddof=1), rtol=1e-4)
    np.testing.assert_allclose(ht.std(x, axis=1).numpy(), a.std(axis=1), rtol=1e-4)
    np.testing.assert_allclose(ht.cov(ht.array(a.T, comm=comm)).numpy(), np.cov(a.T), rtol=1e-4)
    # and on a resplit/transposed distributed operand
    np.testing.assert_allclose(ht.cov(x.resplit(None).T).numpy(), np.cov(a.T), rtol=1e-4)
    i = rng.integers(0, 9, size=29)
    y = ht.array(i, split=split if split != 1 else 0, comm=comm)
    np.testing.assert_array_equal(ht.bincount(y).numpy(), np.bincount(i))
    np.testing.assert_allclose(
        ht.skew(x, axis=0, unbiased=False).numpy(),
        ((a - a.mean(0)) ** 3).mean(0) / (((a - a.mean(0)) ** 2).mean(0) ** 1.5),
        rtol=1e-3,
    )


@pytest.mark.parametrize("split", [None, 0])
def test_maximum_minimum_elementwise(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    b, y = _mk((13, 5), split, comm, lo=3, hi=8)
    np.testing.assert_array_equal(ht.maximum(x, y).numpy(), np.maximum(a, b))
    np.testing.assert_array_equal(ht.minimum(x, y).numpy(), np.minimum(a, b))
    f = a.copy()
    f[0, 0] = np.nan
    z = ht.array(f, split=split, comm=comm)
    got = ht.maximum(z, y).numpy()
    assert np.isnan(got[0, 0])  # NaN propagates like np.maximum


# ------------------------------------------------------------ equal / relational
@pytest.mark.parametrize("split", SPLITS)
def test_relational_matrix(split):
    comm = _comm()
    a, x = _mk((13, 5), split, comm)
    b = a.copy()
    b[0, 0] += 1
    y = ht.array(b, split=split, comm=comm)
    np.testing.assert_array_equal((x == y).numpy(), a == b)
    np.testing.assert_array_equal((x != y).numpy(), a != b)
    np.testing.assert_array_equal((x <= y).numpy(), a <= b)
    np.testing.assert_array_equal((x >= y).numpy(), a >= b)
    assert bool(ht.equal(x, x)) is True
    assert bool(ht.equal(x, y)) is False
    assert bool(ht.equal(x, ht.ones((2, 2), comm=comm))) is False


@pytest.mark.parametrize("split", [None, 0])
def test_comparison_dunder_matrix(split):
    a_np = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    b_np = np.array([4.0, 2.0, 1.0, 4.0], np.float32)
    a, b = ht.array(a_np, split=split), ht.array(b_np, split=split)
    for op in ("__lt__", "__le__", "__gt__", "__ge__", "__eq__", "__ne__"):
        got = getattr(a, op)(b)
        want = getattr(a_np, op)(b_np)
        np.testing.assert_array_equal(got.numpy(), want, err_msg=op)
        assert got.dtype is ht.bool
        # scalar operand both ways
        gs = getattr(a, op)(2.0)
        np.testing.assert_array_equal(gs.numpy(), getattr(a_np, op)(2.0), err_msg=op)
    # reflected against numpy scalars / arrays
    np.testing.assert_array_equal((2.0 < a).numpy(), 2.0 < a_np)
    np.testing.assert_array_equal((b_np >= a).numpy(), b_np >= a_np)


@pytest.mark.parametrize("split", [None, 0])
def test_int_dunder_matrix(split):
    a_np = np.array([6, 7, 12, 3], np.int32)
    b_np = np.array([2, 3, 5, 3], np.int32)
    a, b = ht.array(a_np, split=split), ht.array(b_np, split=split)
    for op in ("__and__", "__or__", "__xor__", "__lshift__", "__rshift__",
               "__mod__", "__floordiv__"):
        got = getattr(a, op)(b)
        want = getattr(a_np, op)(b_np)
        np.testing.assert_array_equal(got.numpy(), want, err_msg=op)
    np.testing.assert_array_equal((~a).numpy(), ~a_np)
    np.testing.assert_array_equal((-a).numpy(), -a_np)
    np.testing.assert_array_equal((+a).numpy(), +a_np)
    np.testing.assert_array_equal(abs(ht.array(-a_np, split=split)).numpy(), a_np)
    # reflected integer ops
    np.testing.assert_array_equal((10 % a).numpy(), 10 % a_np)
    np.testing.assert_array_equal((2 ** b).numpy(), 2 ** b_np)


def test_mixed_dtype_binary_promotion_matrix():
    i = ht.array(np.array([1, 2, 3], np.int32), split=0)
    f = ht.array(np.array([0.5, 1.5, 2.5], np.float32), split=0)
    b = ht.array(np.array([True, False, True]), split=0)
    assert (i + f).dtype is ht.float32
    assert (b + b).dtype is ht.bool or np.issubdtype(np.dtype((b + b).dtype.char()), np.integer)
    assert (b + i).dtype is ht.int32
    assert (i * 2.5).dtype is ht.float32  # weak python scalar keeps array dtype class
    assert (f + 1).dtype is ht.float32
    np.testing.assert_allclose((i + f).numpy(), [1.5, 3.5, 5.5], rtol=1e-6)
