"""
Autoregressive decode serving suite (``heat_tpu/nn/generation.py`` +
``heat_tpu/serving/generation_scheduler.py`` + the flash M=1 decode case,
ISSUE 19).

Guarantees pinned here:

* **Fused ≡ eager** (the acceptance bar): the fused decode chain's logits
  and advanced caches match the eager per-op reference across split
  {None, 0, 1} × even/ragged lengths × f32/bf16, within the
  ``integrity.tolerance_for`` carve-outs — and the *decisions* (greedy
  token sequences) are bit-identical, including through the flash
  interpret route.
* **Zero-compile steady state** (the tentpole): 32+ consecutive scheduler
  steps — with sequences joining and leaving the fixed-B batch mid-window
  — compile ZERO kernels and never break the chain on a collective, while
  ``fusion.donated{steady_state}`` proves the persistent KV-cache buffers
  re-donate on every trace-cache hit; a second PROCESS replaying the same
  decode against a warmed ``HEAT_TPU_CACHE_DIR`` also compiles zero.
* **Iteration-level scheduling**: FIFO admission under per-tenant slot
  budgets (``shed-budget`` counted, deferred not dropped), retirement on
  EOS / max-new / step deadlines with the slot row recycled recompile-free,
  bucketed cache growth counted, and a mixed batch's per-slot sequences
  bit-identical to the B=1 ``generate_reference`` replay.
* **Default off** (the acceptance bar): with ``HEAT_TPU_GENERATION``
  unset, ``decode_step`` runs the eager per-op reference (no generation
  flush, no donation tick) and a standard fused workload's results and
  compile counts are byte-identical whether or not the knob exists.

The live streaming-wire legs boot real worker subprocesses (full jax
imports) and are marked ``slow`` to protect the tier-1 wall-clock budget;
the CI ``generation-smoke`` job runs the WHOLE marker (slow included)
plus the SIGKILL smoke script.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import fusion
from heat_tpu.core.pallas import flash as plflash
from heat_tpu.monitoring import registry
from heat_tpu.nn import generation as gen
from heat_tpu.robustness import faultinject, integrity
from heat_tpu.serving import loadgen
from heat_tpu.serving.generation_scheduler import GenerationScheduler

pytestmark = pytest.mark.generation


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh counters/caches; the generation knob is deliberately left at
    its default (off) — engagement-asserting tests pin it ON themselves
    (the PR 5/8 pin-the-gate precedent)."""
    registry.reset()
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    monkeypatch.delenv("HEAT_TPU_CACHE_DIR", raising=False)
    monkeypatch.delenv("HEAT_TPU_SHAPE_BUCKETS", raising=False)
    monkeypatch.delenv("HEAT_TPU_GENERATION_BUCKETS", raising=False)
    monkeypatch.delenv("HEAT_TPU_GENERATION_SEED", raising=False)
    monkeypatch.delenv("HEAT_TPU_TENANCY", raising=False)
    monkeypatch.delenv("HEAT_TPU_TUNING", raising=False)
    fusion.clear_cache()
    yield
    fusion.clear_cache()
    registry.reset()


@pytest.fixture
def no_faults(monkeypatch):
    """Pin injection/chaos/breakers/audit off for count-asserting tests
    (the PR 6/9/12 precedent)."""
    from heat_tpu.robustness import breaker

    monkeypatch.delenv("HEAT_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("HEAT_TPU_CHAOS", raising=False)
    monkeypatch.delenv("HEAT_TPU_BREAKER_FORCE_OPEN", raising=False)
    monkeypatch.delenv("HEAT_TPU_AUDIT_RATE", raising=False)
    faultinject.clear()
    breaker.reset()
    fusion.clear_cache()


@pytest.fixture
def gen_on(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_GENERATION", "1")
    # CPU test host: force admits the donation mask so the bookkeeping
    # (and its refcount tripwire) is exercised; jax ignores the mask on
    # CPU with a warning and results are bit-identical
    monkeypatch.setenv("HEAT_TPU_FUSION_DONATE", "force")


def _compiles() -> int:
    return registry.REGISTRY.counter("fusion.kernels_compiled").get()


def _steps_tokens(model, sched_tokens):
    return [int(t) for t in sched_tokens]


# ------------------------------------------------------------- capacities
def test_capacity_bucketing_pow2_and_floor():
    assert gen.capacity_for(1) == gen.MIN_CAPACITY
    assert gen.capacity_for(16) == 16
    assert gen.capacity_for(17) == 32
    assert gen.capacity_for(100) == 128
    assert gen.capacity_for(1025) == 2048  # linear 1024-multiples above 1024


def test_capacity_env_spec(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_GENERATION_BUCKETS", "24,48,96")
    assert gen.capacity_for(20) == 24
    assert gen.capacity_for(25) == 48
    # above the last edge: tail multiples, still floored at MIN_CAPACITY
    assert gen.capacity_for(97) >= 97


# ------------------------------------------------------- flash decode case
def test_shape_ok_decode_relaxation():
    # pre-existing square rails unchanged
    assert plflash.shape_ok(128, 128, 64)
    assert not plflash.shape_ok(320, 320, 64)
    # sq=1 decode: any %8 capacity up to MAX_SEQ_DECODE
    assert plflash.shape_ok(1, 320, 64)
    assert plflash.shape_ok(1, 1536, 64)
    assert plflash.shape_ok(1, gen_cap := plflash.MAX_SEQ_DECODE, 64)
    assert not plflash.shape_ok(1, gen_cap + 8, 64)
    assert not plflash.shape_ok(1, 324, 64)  # not lane-aligned, > single tile
    assert plflash.shape_ok(1, 20, 64)  # small: single whole-sequence tile
    assert not plflash.shape_ok(1, 0, 64)
    assert not plflash.shape_ok(0, 128, 64)
    assert not plflash.shape_ok(1, 128, plflash.MAX_HEAD_DIM + 1)


def test_attention_decode_matches_dense_ragged():
    """The M=1 kernel (interpreted) vs the dense masked-softmax reference
    at ragged per-request lengths spanning 1..capacity."""
    b, cap, h, d = 4, 64, 2, 8
    rng = np.random.default_rng(5)
    q = np.asarray(rng.standard_normal((b, 1, h, d)), np.float32)
    k = np.asarray(rng.standard_normal((b, cap, h, d)), np.float32)
    v = np.asarray(rng.standard_normal((b, cap, h, d)), np.float32)
    lengths = np.asarray([1, 7, 33, 64], np.int32)
    scale = d ** -0.5
    out = np.asarray(
        plflash.attention_decode(q, k, v, lengths, scale=scale, interpret=True)
    )
    s = np.einsum("bqhd,bchd->bhqc", q, k) * scale
    mask = np.arange(cap)[None, :] < lengths[:, None]
    s = np.where(mask[:, None, None, :], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqc,bchd->bqhd", p, v)
    assert np.allclose(out, ref, rtol=1e-5, atol=1e-6)


# ------------------------------------------------- fused vs eager matrix
def _run_steps(model, split, lengths0, n_steps, capacity=32):
    """Drive ``n_steps`` decode steps from a fixed starting state; returns
    (logits_list, final_cache). Engagement is whatever the ambient knob
    says — callers pin it."""
    B = len(lengths0)
    cache = gen.KVCache.alloc(model, B, capacity=capacity, split=split)
    # pre-fill each slot's history so ragged lengths are real: feed
    # deterministic tokens one step at a time up to each slot's length
    warm = int(max(lengths0))
    for t in range(warm):
        adv = (np.arange(B) * 0 + (t < np.asarray(lengths0))).astype(np.int32)
        tok = np.full(B, (t * 7 + 3) % model.vocab, np.int32)
        lg, cache = gen.decode_step(model, cache, tok, advance=adv)
        gen.read_logits(lg)
    assert list(cache.lengths) == [int(x) for x in lengths0]
    outs = []
    for t in range(n_steps):
        tok = np.full(B, (t * 5 + 1) % model.vocab, np.int32)
        lg, cache = gen.decode_step(model, cache, tok)
        outs.append(gen.read_logits(lg))
    return outs, cache


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize(
    "lengths0",
    [(3,) * 8, (1, 4, 2, 7, 3, 6, 2, 5)],
    ids=["even", "ragged"],
)
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"], ids=["f32", "bf16"])
def test_fused_vs_eager_matrix(monkeypatch, split, lengths0, dtype, no_faults):
    """The differential acceptance bar: fused-chain logits and caches match
    the eager reference within the documented per-dtype carve-outs (the
    chain's intermediates carry the MODEL dtype even though logits emit
    f32, so the bf16 carve-out governs bf16 runs), and the greedy
    decisions are bit-identical. B = the 8-device mesh width so split=0
    shards evenly — the serving scheduler itself always runs split=None."""
    model = gen.ToyModel(dtype=dtype)
    monkeypatch.delenv("HEAT_TPU_GENERATION", raising=False)
    eager, ecache = _run_steps(model, split, lengths0, 4)
    monkeypatch.setenv("HEAT_TPU_GENERATION", "1")
    fusion.clear_cache()
    fused, fcache = _run_steps(model, split, lengths0, 4)
    ctol = integrity.tolerance_for(model.jnp_dtype)
    for a, b in zip(eager, fused):
        assert np.allclose(a, b, rtol=ctol, atol=ctol)
        assert np.array_equal(gen.greedy(a), gen.greedy(b))
    for ec, fc in ((ecache.k, fcache.k), (ecache.v, fcache.v)):
        ea = np.asarray(ec.larray, np.float32)
        fa = np.asarray(fc.larray, np.float32)
        assert np.allclose(ea, fa, rtol=ctol, atol=ctol)


def test_fused_flash_route_matches_dense(monkeypatch, gen_on, no_faults):
    """The interpret-forced flash route's token decisions match the dense
    attend's — the kernel's reassociation carve-out never flips a greedy
    argmax at toy scale."""
    model = gen.ToyModel()
    monkeypatch.delenv("HEAT_TPU_PALLAS_INTERPRET", raising=False)
    dense, _ = _run_steps(model, None, (2, 5, 1, 3), 4)
    monkeypatch.setenv("HEAT_TPU_PALLAS_INTERPRET", "1")
    fusion.clear_cache()
    gen._FNS.clear()
    try:
        flashy, _ = _run_steps(model, None, (2, 5, 1, 3), 4)
    finally:
        gen._FNS.clear()
    for a, b in zip(dense, flashy):
        assert np.array_equal(gen.greedy(a), gen.greedy(b))


def test_mixed_batch_slots_match_b1_reference(gen_on, no_faults):
    """Per-slot batch independence: every sequence decoded in a mixed batch
    is bit-identical to its own single-sequence reference replay."""
    model = gen.ToyModel()
    sched = GenerationScheduler(model=model, slots=3, capacity=32)
    specs = [([3, 1, 4], 8), ([9], 6), ([2, 7, 1, 8], 5)]
    handles = [sched.submit(p, max_new=m) for p, m in specs]
    sched.run(max_steps=60)
    for h, (p, m) in zip(handles, specs):
        assert h.result(timeout=0) == gen.generate_reference(model, p, max_new=m)
        assert h.digest() == gen.digest_of_tokens(h.tokens)


# ------------------------------------------------- steady-state contracts
def test_zero_compile_steady_state_with_join_leave(gen_on, no_faults):
    """The tentpole: 32+ consecutive decode steps — admission, retirement
    and slot recycling happening mid-window — at ZERO compiled kernels and
    zero collective chain breaks, with the persistent cache re-donating on
    every step (``fusion.donated{steady_state}`` strictly increasing)."""
    with registry.capture():
        compiles = registry.REGISTRY.counter("fusion.kernels_compiled")
        reasons = registry.REGISTRY.counter("fusion.flush_reason")
        donated = registry.REGISTRY.counter("fusion.donated")
        model = gen.ToyModel()
        sched = GenerationScheduler(model=model, slots=4, capacity=64)
        sched.submit([3, 1, 4], max_new=40)
        sched.submit([1, 5], max_new=40)
        sched.submit([9, 2, 6], max_new=8)   # leaves mid-window
        sched.submit([3, 5, 8], max_new=8)   # leaves mid-window
        for _ in range(4):
            sched.step()  # warmup: the single compile happens here
        assert compiles.get() >= 1
        before_steady = donated.get("steady_state")
        for i in range(34):
            if i == 14:  # join the recycled slots mid-window
                sched.submit([2, 7], max_new=10)
                sched.submit([1, 8, 2], max_new=10)
            c0, r0 = compiles.get(), reasons.get("collective")
            sched.step()
            assert compiles.get() == c0, f"step {i} compiled a kernel"
            assert reasons.get("collective") == r0
        assert donated.get("steady_state") > before_steady
        assert sched.occupancy() > 0.0


def test_steady_state_redonation_regression(gen_on, no_faults):
    """Satellite 2 regression: N decode steps re-donate the SAME logical
    cache buffers every step — ``fusion.donated`` grows by 2 buffers/step
    (k and v) and every post-warmup donation rides a trace-cache hit."""
    with registry.capture():
        donated = registry.REGISTRY.counter("fusion.donated")
        model = gen.ToyModel()
        cache = gen.KVCache.alloc(model, 2, capacity=32)
        per_step = []
        for t in range(8):
            before = donated.get("buffers")
            tok = np.full(2, (t + 1) % model.vocab, np.int32)
            lg, cache = gen.decode_step(model, cache, tok)
            gen.read_logits(lg)  # old cache rebound above: buffers are dead
            per_step.append(donated.get("buffers") - before)
        # step 1 donates nothing (zeros factories are fresh un-dead leaves);
        # every subsequent step donates exactly k and v
        assert per_step[2:] == [2] * 6
        steady = donated.get("steady_state")
        assert steady >= 2 * 6  # all post-warmup donations were cache HITS


@pytest.mark.slow
def test_cross_process_zero_compile_against_warmed_dir(tmp_path, gen_on):
    """A fresh PROCESS replaying the decode loop against a warmed
    ``HEAT_TPU_CACHE_DIR`` compiles ZERO kernels — the fused decode chain
    rides the L2 disk cache like any other serving kernel."""
    script = (
        "import os, sys\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import numpy as np\n"
        "from heat_tpu.nn import generation as gen\n"
        "from heat_tpu.monitoring import registry\n"
        "registry.enable()\n"
        "model = gen.ToyModel()\n"
        "cache = gen.KVCache.alloc(model, 2, capacity=32)\n"
        "for t in range(6):\n"
        "    tok = np.full(2, (t + 1) % 5, np.int32)\n"
        "    lg, cache = gen.decode_step(model, cache, tok)\n"
        "    gen.read_logits(lg)\n"
        "print('COMPILES', registry.REGISTRY.counter('fusion.kernels_compiled').get())\n"
    )
    env = dict(os.environ)
    env.update({
        "HEAT_TPU_GENERATION": "1",
        "HEAT_TPU_CACHE_DIR": str(tmp_path / "l2"),
        "JAX_PLATFORMS": "cpu",
    })
    first = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True, text=True,
        timeout=300,
    )
    assert first.returncode == 0, first.stderr[-2000:]
    assert "COMPILES 1" in first.stdout
    second = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True, text=True,
        timeout=300,
    )
    assert second.returncode == 0, second.stderr[-2000:]
    assert "COMPILES 0" in second.stdout


def test_capacity_bucket_bounds_kernels(gen_on, no_faults):
    """Growing past a bucket edge compiles exactly ONE new kernel (the new
    capacity's chain) and the scheduler counts it ``grown``."""
    with registry.capture():
        gcount = registry.REGISTRY.counter("serving.generation")
        model = gen.ToyModel()
        sched = GenerationScheduler(model=model, slots=2, capacity=16)
        h = sched.submit([1, 2, 3], max_new=20)  # 3 + 20 > 16: must grow
        sched.run(max_steps=40)
        assert h.result(timeout=0) == gen.generate_reference(
            model, [1, 2, 3], max_new=20
        )
        assert gcount.get("grown") >= 1
        assert sched.cache.capacity == 32


# ------------------------------------------------------------- scheduler
def test_scheduler_submit_validation(gen_on):
    sched = GenerationScheduler(model=gen.ToyModel(), slots=1)
    with pytest.raises(ValueError):
        sched.submit([], max_new=4)
    with pytest.raises(ValueError):
        sched.submit([1], max_new=0)


def test_scheduler_retirement_reasons(gen_on, no_faults):
    model = gen.ToyModel()
    ref = gen.generate_reference(model, [3, 1], max_new=10)
    eos = ref[3]  # guaranteed to occur: deterministic greedy decode
    with registry.capture():
        sched = GenerationScheduler(model=model, slots=3, capacity=32)
        h_eos = sched.submit([3, 1], max_new=10, eos=eos)
        h_max = sched.submit([9], max_new=4)
        h_dead = sched.submit([2, 7], max_new=50, deadline_steps=5)
        sched.run(max_steps=80)
        assert h_eos.finish_reason == "eos"
        assert h_eos.tokens == gen.generate_reference(
            model, [3, 1], max_new=10, eos=eos
        )
        assert h_max.finish_reason == "maxlen" and len(h_max.tokens) == 4
        assert h_dead.finish_reason == "deadline" and len(h_dead.tokens) < 50
        gc = registry.REGISTRY.counter("serving.generation")
        for kind in ("retired-eos", "retired-maxlen", "retired-deadline"):
            assert gc.get(kind) == 1
        assert gc.get("admitted") == 3


def test_scheduler_tenant_budget_defers_not_drops(monkeypatch, gen_on,
                                                  no_faults):
    """With tenancy armed, a tenant at its weighted slot share waits
    (counted ``shed-budget`` once) while other tenants admit — and still
    completes once a slot frees."""
    monkeypatch.setenv("HEAT_TPU_TENANCY", "alpha:1,beta:1")
    model = gen.ToyModel()
    with registry.capture():
        sched = GenerationScheduler(model=model, slots=2, capacity=32)
        a1 = sched.submit([3], max_new=3, tenant="alpha")
        a2 = sched.submit([5], max_new=3, tenant="alpha")  # over alpha's share
        b1 = sched.submit([7], max_new=3, tenant="beta")
        sched.step()
        gc = registry.REGISTRY.counter("serving.generation")
        assert gc.get("admitted") == 2  # a1 + b1; a2 deferred
        assert gc.get("shed-budget") == 1
        sched.run(max_steps=40)
        for h, p in ((a1, [3]), (a2, [5]), (b1, [7])):
            assert h.result(timeout=0) == gen.generate_reference(
                model, p, max_new=3
            )


def test_scheduler_occupancy_gauge(gen_on, no_faults):
    with registry.capture():
        sched = GenerationScheduler(model=gen.ToyModel(), slots=4, capacity=16)
        sched.submit([1], max_new=2)
        sched.step()
        g = registry.REGISTRY.gauge("serving.batch_occupancy")
        assert g.get() == 25.0
        assert sched.occupancy() == 25.0


def test_handle_result_timeout(gen_on):
    sched = GenerationScheduler(model=gen.ToyModel(), slots=1)
    h = sched.submit([1], max_new=4)
    with pytest.raises(TimeoutError):
        h.result(timeout=0.01)  # never stepped
    sched.run(max_steps=20)
    assert len(h.result(timeout=0)) == 4


# ---------------------------------------------------------------- loadgen
def test_gen_trace_deterministic_and_digests():
    t1, t2 = loadgen.gen_trace(seed=9, n=6), loadgen.gen_trace(seed=9, n=6)
    assert t1 == t2
    assert loadgen.gen_trace(seed=10, n=6) != t1
    expected = loadgen.expected_generation(t1)
    for req in t1:
        key = loadgen.gen_request_key(req)
        ref = gen.generate_reference(
            gen.ToyModel.from_env(), req["prompt"],
            max_new=req.get("max_new", 16), eos=req.get("eos"),
        )
        assert expected[key] == gen.digest_of_tokens(ref)


# ------------------------------------------------------------- off = inert
def test_off_knob_decode_is_eager_reference(monkeypatch, no_faults):
    """Knob off: ``decode_step`` never records a fused chain — no
    generation flush, no donation, logits concrete immediately."""
    monkeypatch.delenv("HEAT_TPU_GENERATION", raising=False)
    assert not gen.enabled()
    with registry.capture():
        model = gen.ToyModel()
        cache = gen.KVCache.alloc(model, 2, capacity=16)
        lg, cache = gen.decode_step(model, cache, np.asarray([1, 2], np.int32))
        gen.read_logits(lg)
        reasons = registry.REGISTRY.counter("fusion.flush_reason")
        assert reasons.get("generation") == 0
        assert registry.REGISTRY.counter("fusion.donated").get("buffers") == 0


def test_off_knob_standard_workload_byte_identical(monkeypatch, no_faults):
    """The off-inertness differential: a standard fused workload's results
    and compile counts are byte-identical whether the generation knob is
    absent or armed — arming it must not perturb non-generation flushes."""

    def work():
        x = ht.arange(48, dtype=ht.float32, split=0).reshape((6, 8))
        y = ht.sin(x * 2.0 + 1.0) / 3.0
        return np.asarray(y.larray).tobytes()

    monkeypatch.delenv("HEAT_TPU_GENERATION", raising=False)
    with registry.capture():
        fusion.clear_cache()
        base = work()
        base_compiles = _compiles()
    registry.reset()
    monkeypatch.setenv("HEAT_TPU_GENERATION", "1")
    with registry.capture():
        fusion.clear_cache()
        armed = work()
        armed_compiles = _compiles()
    assert base == armed
    assert base_compiles == armed_compiles


# --------------------------------------------------------- live wire legs
@pytest.mark.slow
def test_generation_streaming_live_fleet(tmp_path, gen_on):
    """The streaming wire mode end-to-end: a real 2-worker ingress serves
    the seeded generative trace over NDJSON with every wire digest AND
    every client-recomputed digest matching the local reference oracle."""
    from heat_tpu.serving.server import Ingress

    ing = Ingress(
        workers=2,
        cache_dir=str(tmp_path / "cache"),
        env={"JAX_PLATFORMS": "cpu", "HEAT_TPU_GENERATION": "1",
             "HEAT_TPU_FUSION_DONATE": "force"},
    ).start()
    try:
        reqs = loadgen.gen_trace(seed=13, n=10)
        expected = loadgen.expected_generation(reqs)
        stats = loadgen.run_generate(
            ing.url(), reqs, concurrency=4, expected=expected
        )
        assert stats["mismatches"] == 0 and stats["errors"] == 0
        assert stats["ok"] == len(reqs) and stats["tokens"] > 0
        assert stats["decode_tokens_per_s"] > 0
        assert stats["inter_token_p99_us"] >= stats["inter_token_p50_us"] >= 0
    finally:
        ing.stop()


@pytest.mark.slow
def test_generation_off_worker_answers_404(tmp_path, monkeypatch):
    """Off-knob wire inertness: a fleet booted WITHOUT the generation knob
    answers ``/v1/generate`` with 404 ``generation-off`` through the
    ingress relay — the endpoint does not exist until armed."""
    import urllib.error
    import urllib.request

    from heat_tpu.serving.server import Ingress

    monkeypatch.delenv("HEAT_TPU_GENERATION", raising=False)
    ing = Ingress(
        workers=1,
        cache_dir=str(tmp_path / "cache"),
        env={"JAX_PLATFORMS": "cpu"},
    ).start()
    try:
        req = urllib.request.Request(
            ing.url("/v1/generate"),
            data=json.dumps({"prompt": [1, 2], "max_new": 4}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 404
        body = json.loads(exc.value.read().decode())
        assert body["reason"] == "generation-off"
    finally:
        ing.stop()
