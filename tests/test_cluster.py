"""Tests for clustering (parity model: reference heat/cluster/tests/)."""

import numpy as np
import pytest

import heat_tpu as ht


def _blobs(n=64, seed=0):
    rng = np.random.default_rng(seed)
    c1 = rng.normal(loc=(-5, -5), scale=0.5, size=(n // 2, 2))
    c2 = rng.normal(loc=(5, 5), scale=0.5, size=(n // 2, 2))
    data = np.concatenate([c1, c2]).astype(np.float32)
    labels = np.array([0] * (n // 2) + [1] * (n // 2))
    perm = rng.permutation(n)
    return data[perm], labels[perm]


def _cluster_accuracy(pred, truth):
    match = (pred == truth).mean()
    return max(match, 1 - match)


@pytest.mark.parametrize("init", ["random", "probability_based"])
def test_kmeans(init):
    data, truth = _blobs()
    x = ht.array(data, split=0)
    km = ht.cluster.KMeans(n_clusters=2, init=init, max_iter=50, random_state=42)
    km.fit(x)
    assert km.cluster_centers_.shape == (2, 2)
    assert km.labels_.shape == (64,)
    pred = km.labels_.numpy()
    assert _cluster_accuracy(pred, truth) > 0.95
    assert km.inertia_ < 100
    assert km.n_iter_ >= 1
    pred2 = km.predict(x)
    np.testing.assert_array_equal(pred2.numpy(), pred)


def test_kmeans_explicit_init_and_errors():
    data, _ = _blobs()
    x = ht.array(data, split=0)
    init_centers = ht.array(data[:2])
    km = ht.cluster.KMeans(n_clusters=2, init=init_centers, max_iter=10)
    km.fit(x)
    assert km.cluster_centers_.shape == (2, 2)
    with pytest.raises(ValueError):
        ht.cluster.KMeans(n_clusters=2, init=ht.ones((3, 3))).fit(x)
    with pytest.raises(ValueError):
        ht.cluster.KMeans(n_clusters=2, init="bogus").fit(x)
    with pytest.raises(ValueError):
        km.fit(data)


def test_kmedians():
    data, truth = _blobs(seed=1)
    x = ht.array(data, split=0)
    km = ht.cluster.KMedians(n_clusters=2, init="random", max_iter=50, random_state=1)
    km.fit(x)
    assert _cluster_accuracy(km.labels_.numpy(), truth) > 0.95


def test_kmedoids():
    data, truth = _blobs(seed=2)
    x = ht.array(data, split=0)
    km = ht.cluster.KMedoids(n_clusters=2, init="random", max_iter=50, random_state=2)
    km.fit(x)
    assert _cluster_accuracy(km.labels_.numpy(), truth) > 0.95
    # medoids are actual data points
    centers = km.cluster_centers_.numpy()
    for c in centers:
        assert (np.abs(data - c).sum(axis=1) < 1e-5).any()


@pytest.mark.slow  # ~8 s Lanczos eigensolve; the unfiltered device-matrix CI
# job keeps coverage (ISSUE 16 tier-1 rebalance)
def test_spectral():
    data, truth = _blobs(n=32, seed=3)
    x = ht.array(data, split=0)
    sp = ht.cluster.Spectral(n_clusters=2, gamma=0.1, n_lanczos=20)
    sp.fit(x)
    assert sp.labels_.shape == (32,)
    assert _cluster_accuracy(sp.labels_.numpy(), truth) > 0.9


def test_get_set_params():
    km = ht.cluster.KMeans(n_clusters=4)
    params = km.get_params()
    assert params["n_clusters"] == 4
    km.set_params(n_clusters=7)
    assert km.n_clusters == 7
    with pytest.raises(ValueError):
        km.set_params(bogus=1)
    assert "KMeans" in repr(km)


def test_estimator_contracts():
    # BaseEstimator API surface across the ML families (reference
    # core/base.py + per-estimator tests): get/set_params round-trip and
    # unfitted predict errors
    rng = np.random.default_rng(41)
    x = ht.array(rng.normal(size=(32, 4)).astype(np.float32), split=0)
    ests = [
        ht.cluster.KMeans(n_clusters=3),
        ht.cluster.KMedians(n_clusters=3),
        ht.cluster.KMedoids(n_clusters=3),
    ]
    for est in ests:
        params = est.get_params()
        assert params["n_clusters"] == 3
        est.set_params(n_clusters=2)
        assert est.get_params()["n_clusters"] == 2
        est.set_params(**params)
        with pytest.raises((RuntimeError, AttributeError, ValueError)):
            est.predict(x)  # not fitted

    km = ht.cluster.KMeans(n_clusters=2, max_iter=10).fit(x)
    labels = km.predict(x)
    assert set(np.unique(labels.numpy())).issubset({0, 1})
    assert km.cluster_centers_.shape == (2, 4)


def test_kmeans_init_modes_converge():
    rng = np.random.default_rng(42)
    centers = np.array([[6.0, 6.0], [-6.0, -6.0], [6.0, -6.0]], np.float32)
    blobs = np.concatenate(
        [c + rng.normal(scale=0.4, size=(40, 2)).astype(np.float32) for c in centers]
    )
    x = ht.array(blobs, split=0)
    for init in ("random", "kmeans++", "batchparallel"):
        km = ht.cluster.KMeans(n_clusters=3, init=init, max_iter=50, random_state=0)
        km.fit(x)
        # every true blob center is within 1.0 of a fitted center
        got = km.cluster_centers_.numpy()
        for c in centers:
            assert np.min(np.linalg.norm(got - c, axis=1)) < 1.0, (init, got)
        assert km.n_iter_ <= 50


def test_kmedians_kmedoids_recover_blobs():
    rng = np.random.default_rng(43)
    centers = np.array([[8.0, 0.0], [-8.0, 0.0], [0.0, 8.0]], np.float32)
    blobs = np.concatenate(
        [c + rng.normal(scale=0.5, size=(50, 2)).astype(np.float32) for c in centers]
    )
    x = ht.array(blobs, split=0)
    for cls, attr in (
        (ht.cluster.KMedians, "cluster_centers_"),
        (ht.cluster.KMedoids, "cluster_centers_"),
    ):
        est = cls(n_clusters=3, max_iter=60, random_state=1, init="kmeans++")
        est.fit(x)
        got = getattr(est, attr).numpy()
        for c in centers:
            assert np.min(np.linalg.norm(got - c, axis=1)) < 1.5, (cls.__name__, got)
        labels = est.predict(x).numpy().reshape(-1)
        # each blob is dominated by one label
        for b in range(3):
            seg = labels[b * 50 : (b + 1) * 50]
            assert np.bincount(seg, minlength=3).max() >= 40, (cls.__name__, seg)


def test_kmedoids_centers_are_data_points():
    rng = np.random.default_rng(44)
    x_np = rng.normal(size=(40, 3)).astype(np.float32)
    x = ht.array(x_np, split=0)
    km = ht.cluster.KMedoids(n_clusters=4, max_iter=30, random_state=2).fit(x)
    centers = km.cluster_centers_.numpy()
    for c in centers:
        d = np.abs(x_np - c).sum(axis=1).min()
        assert d < 1e-5, "a medoid must be an actual sample"


def test_functional_value_and_iteration_metadata():
    rng = np.random.default_rng(45)
    x = ht.array(rng.normal(size=(64, 2)).astype(np.float32), split=0)
    km = ht.cluster.KMeans(n_clusters=2, max_iter=50, tol=1e-6, random_state=3).fit(x)
    # inertia equals the sum of squared distances to assigned centers
    labels = km.predict(x).numpy().reshape(-1)
    centers = km.cluster_centers_.numpy()
    inertia_true = sum(
        ((x.numpy()[labels == k] - centers[k]) ** 2).sum() for k in range(2)
    )
    # the fit loop's GEMMs deliberately run at the fast TPU default (one bf16
    # pass, doc/performance.md) — the inertia functional is ~1e-2-relative on
    # a real accelerator, libm-tight on the CPU mesh
    from _accel import ON_ACCELERATOR

    rel = abs(km.inertia_ - inertia_true) / max(inertia_true, 1e-9)
    assert rel < (5e-2 if ON_ACCELERATOR else 1e-3)
    assert 1 <= km.n_iter_ <= 50


def test_spectral_parameters_and_predict():
    rng = np.random.default_rng(46)
    a = rng.normal(size=(30, 2)).astype(np.float32) + 4
    b = rng.normal(size=(30, 2)).astype(np.float32) - 4
    x = ht.array(np.concatenate([a, b]), split=0)
    sp = ht.cluster.Spectral(n_clusters=2, gamma=1.0, n_lanczos=20)
    labels = sp.fit_predict(x).numpy().reshape(-1)
    first, second = labels[:30], labels[30:]
    purity = max(
        (first == 0).mean() + (second == 1).mean(),
        (first == 1).mean() + (second == 0).mean(),
    ) / 2
    assert purity > 0.9
    assert sp.get_params()["n_clusters"] == 2
