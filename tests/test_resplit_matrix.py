"""
Resplit/redistribute matrix: every (from_split, to_split) transition over
divisible and ragged shapes, values + metadata + physical placement asserted
(the reference's test_dndarray resplit blocks over its Alltoallw machinery;
here each transition is one XLA resharding placement).
"""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core.communication import get_comm

SPLITS = [None, 0, 1]


@pytest.mark.parametrize("shape", [(16, 8), (13, 7), (8, 16)])
@pytest.mark.parametrize("src", SPLITS)
@pytest.mark.parametrize("dst", SPLITS)
def test_resplit_matrix(shape, src, dst):
    rng = np.random.default_rng(abs(hash((shape, src, dst))) % 2**31)
    a_np = rng.normal(size=shape).astype(np.float32)
    a = ht.array(a_np, split=src)
    r = ht.resplit(a, dst)
    assert r.split == dst
    np.testing.assert_array_equal(r.numpy(), a_np)
    if dst is not None and get_comm().is_distributed():
        # genuinely sharded: one shard per device, extent = ceil(n/p) on dst
        p = get_comm().size
        # slices are unhashable before Python 3.12: set-ify a plain triple
        shards = {
            tuple((sl.start, sl.stop, sl.step) for sl in s.index)
            for s in r.parray.addressable_shards
        }
        assert len(shards) == p
        c = -(-shape[dst] // p)
        for s in r.parray.addressable_shards:
            assert s.data.shape[dst] == c
    # source unchanged
    assert a.split == src
    np.testing.assert_array_equal(a.numpy(), a_np)


@pytest.mark.parametrize("shape", [(16, 8), (13, 7)])
@pytest.mark.parametrize("src", [0, 1])
def test_resplit_inplace_matrix(shape, src):
    rng = np.random.default_rng(7)
    a_np = rng.normal(size=shape).astype(np.float32)
    for dst in SPLITS:
        a = ht.array(a_np, split=src)
        out = a.resplit_(dst)
        assert out is a and a.split == dst
        np.testing.assert_array_equal(a.numpy(), a_np)


def test_3d_resplit_chain():
    rng = np.random.default_rng(8)
    a_np = rng.normal(size=(6, 8, 10)).astype(np.float32)
    a = ht.array(a_np, split=0)
    for dst in (1, 2, None, 0, 2):
        a = ht.resplit(a, dst)
        assert a.split == dst
    np.testing.assert_array_equal(a.numpy(), a_np)


def test_float16_bfloat16_resplit_and_ops():
    # half dtypes through the placement machinery (first-class on TPU)
    rng = np.random.default_rng(9)
    a_np = rng.normal(size=(13, 5)).astype(np.float32)
    for dt in (ht.bfloat16, ht.float16):
        a = ht.array(a_np, split=0, dtype=dt)
        assert a.dtype is dt
        r = ht.resplit(a, 1)
        assert r.dtype is dt
        s = ht.sum(a, axis=0)
        assert s.shape == (5,)
        np.testing.assert_allclose(
            r.numpy().astype(np.float32), a.numpy().astype(np.float32), rtol=1e-2, atol=1e-2
        )
