"""Tests for linear algebra (parity model: reference
heat/core/linalg/tests/test_{basics,qr,solver}.py)."""

import warnings

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0, 1]


@pytest.mark.parametrize("sa", SPLITS)
@pytest.mark.parametrize("sb", SPLITS)
def test_matmul_split_matrix(sa, sb):
    rng = np.random.default_rng(4)
    a = rng.normal(size=(16, 8)).astype(np.float32)
    b = rng.normal(size=(8, 24)).astype(np.float32)
    ha = ht.array(a, split=sa)
    hb = ht.array(b, split=sb)
    res = ht.matmul(ha, hb)
    np.testing.assert_allclose(res.numpy(), a @ b, rtol=1e-4)
    if sa == 0:
        assert res.split == 0
    elif sb == 1:
        assert res.split == 1


def test_matmul_operator_and_vectors():
    a = ht.array(np.arange(6.0).reshape(2, 3))
    b = ht.array(np.arange(3.0))
    np.testing.assert_allclose((a @ b).numpy(), a.numpy() @ b.numpy())
    np.testing.assert_allclose(ht.dot(b, b).numpy(), 5.0)


@pytest.mark.parametrize("split", [None, 0])
def test_qr(split):
    rng = np.random.default_rng(5)
    a = rng.normal(size=(32, 4)).astype(np.float32)
    h = ht.array(a, split=split)
    q, r = ht.qr(h)
    np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-4)
    np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(4), atol=1e-4)
    assert np.allclose(r.numpy(), np.triu(r.numpy()), atol=1e-5)
    if split == 0:
        assert q.split == 0
    r_only = ht.qr(h, calc_q=False)
    assert r_only.Q is None
    np.testing.assert_allclose(np.abs(r_only.R.numpy()), np.abs(r.numpy()), atol=1e-4)
    with pytest.raises(ValueError):
        ht.qr(ht.ones(3))


@pytest.mark.parametrize("shape", [(512, 512), (1024, 256), (640, 64)])
def test_qr_split1_distributed(shape):
    """Column-sharded QR runs the distributed block Gram-Schmidt sweep
    (reference split=1 Householder sweep, qr.py:866): Q and R stay split=1,
    numerics match jnp.linalg.qr grade."""
    m, n = shape
    rng = np.random.default_rng(7)
    a = rng.normal(size=shape).astype(np.float32)
    h = ht.array(a, split=1)
    q, r = ht.qr(h)
    if h.comm.is_distributed() and n % h.comm.size == 0:
        assert q.split == 1 and r.split == 1
    qn, rn = q.numpy(), r.numpy()
    np.testing.assert_allclose(qn @ rn, a, atol=5e-4, rtol=1e-4)
    assert np.abs(qn.T @ qn - np.eye(n)).max() < 5e-5
    assert np.abs(np.tril(rn, -1)).max() == 0.0
    r_only = ht.qr(h, calc_q=False)
    assert r_only.Q is None
    np.testing.assert_allclose(np.abs(r_only.R.numpy()), np.abs(rn), atol=1e-4)


def test_det_inv_trace():
    rng = np.random.default_rng(6)
    a = rng.normal(size=(4, 4)).astype(np.float32) + 4 * np.eye(4, dtype=np.float32)
    h = ht.array(a)
    np.testing.assert_allclose(float(ht.det(h).larray), np.linalg.det(a), rtol=1e-3)
    np.testing.assert_allclose(ht.inv(h).numpy(), np.linalg.inv(a), rtol=1e-3, atol=1e-5)
    assert abs(ht.trace(h) - np.trace(a)) < 1e-4
    with pytest.raises(ValueError):
        ht.det(ht.ones((2, 3)))


# ragged leg exercises the same panel elimination with remainder handling only;
# slow-marked as a redundant differential — the unfiltered device-matrix CI job
# still runs it (ISSUE 16 tier-1 rebalance)
@pytest.mark.parametrize("n", [64, pytest.param(67, marks=pytest.mark.slow)])
@pytest.mark.parametrize("split", [0, 1, None])
def test_det_inv_distributed(n, split):
    """Split matrices run the blocked panel elimination (no full gather —
    tests/test_hlo_contract.py pins the HLO); values must match numpy."""
    rng = np.random.default_rng(7)
    a = (rng.normal(size=(n, n)).astype(np.float32) + 3 * np.eye(n, dtype=np.float32)) / 2.2
    h = ht.array(a, split=split)
    ref64 = np.linalg.det(a.astype(np.float64))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the distributed path must not fall back
        d = ht.det(h)
        iv = ht.inv(h)
    assert d.split is None
    np.testing.assert_allclose(float(d.larray), ref64, rtol=2e-3)
    assert iv.split == split
    np.testing.assert_allclose(
        iv.numpy(), np.linalg.inv(a.astype(np.float64)), rtol=5e-3, atol=5e-4
    )


def test_det_inv_batched_split():
    """Stacks split along a batch axis stay on the local (vmapped) path."""
    rng = np.random.default_rng(8)
    a = rng.normal(size=(8, 5, 5)).astype(np.float32) + 3 * np.eye(5, dtype=np.float32)
    h = ht.array(a, split=0)
    np.testing.assert_allclose(ht.det(h).numpy(), np.linalg.det(a), rtol=2e-3)
    np.testing.assert_allclose(ht.inv(h).numpy(), np.linalg.inv(a), rtol=5e-3, atol=1e-4)


# ragged leg slow-marked as a redundant differential (see det_inv above)
@pytest.mark.parametrize("n", [48, pytest.param(51, marks=pytest.mark.slow)])
@pytest.mark.parametrize("split", [0, 1, None])
def test_solve_distributed(n, split):
    """solve rides the blocked panel elimination for split matrices (numpy-API
    completion — the reference has only iterative cg/lanczos solvers)."""
    rng = np.random.default_rng(11)
    a_np = rng.standard_normal((n, n)).astype(np.float32) + 3 * np.eye(n, dtype=np.float32)
    b1 = rng.standard_normal(n).astype(np.float32)
    bk = rng.standard_normal((n, 3)).astype(np.float32)
    a = ht.array(a_np, split=split)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the distributed path must not fall back
        x1 = ht.solve(a, ht.array(b1, split=0 if split == 0 else None))
        xk = ht.solve(a, ht.array(bk, split=0 if split == 0 else None))
    assert x1.shape == (n,) and xk.shape == (n, 3)
    np.testing.assert_allclose(
        x1.numpy(), np.linalg.solve(a_np.astype(np.float64), b1), rtol=5e-3, atol=1e-3
    )
    np.testing.assert_allclose(
        xk.numpy(), np.linalg.solve(a_np.astype(np.float64), bk), rtol=5e-3, atol=1e-3
    )


@pytest.mark.slow  # ~7 s complex panel sweep; unfiltered device-matrix CI job
# keeps coverage (ISSUE 16 tier-1 rebalance)
def test_det_inv_solve_complex_distributed():
    """Complex split matrices through the panel elimination (ADVICE r4 medium:
    the certified residual must be computed as sum(|t|^2), not sum(t*t), or
    the complex path crashes in float(rel))."""
    from _accel import COMPLEX_SUPPORTED

    if not COMPLEX_SUPPORTED:
        pytest.skip("backend has no complex support")
    rng = np.random.default_rng(3)
    n = 32
    a_np = (
        rng.normal(size=(n, n)) + 1j * rng.normal(size=(n, n))
    ).astype(np.complex64) + 3 * np.eye(n, dtype=np.complex64)
    b_np = (rng.normal(size=(n, 3)) + 1j * rng.normal(size=(n, 3))).astype(np.complex64)
    h = ht.array(a_np, split=0)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the distributed path must not fall back
        iv = ht.inv(h)
        x = ht.solve(h, ht.array(b_np, split=0))
        d = ht.det(h)
    a128 = a_np.astype(np.complex128)
    np.testing.assert_allclose(iv.numpy(), np.linalg.inv(a128), rtol=5e-3, atol=5e-4)
    np.testing.assert_allclose(x.numpy(), np.linalg.solve(a128, b_np), rtol=5e-3, atol=1e-3)
    np.testing.assert_allclose(complex(d.larray), np.linalg.det(a128), rtol=2e-3)


def test_solve_inv_illconditioned_certified_fallback():
    """Block-local pivoting bounds the panel path at ~cond*eps*growth; the
    kernels certify their own residual and an ill-conditioned system must
    fall back (warned) to the fully-pivoted replicated path instead of
    returning a silently bad answer."""
    if not ht.get_comm().is_distributed():
        pytest.skip("needs a multi-device mesh")
    rng = np.random.default_rng(13)
    n = 64
    # condition the matrix badly on purpose: geometric singular-value decay
    u, _ = np.linalg.qr(rng.standard_normal((n, n)))
    v, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -6, n)  # cond = 1e6 >> f32 comfort
    a_np = (u * s) @ v.T
    a_np = a_np.astype(np.float32)
    b_np = rng.standard_normal(n).astype(np.float32)
    with pytest.warns(UserWarning, match="falling back"):
        x = ht.solve(ht.array(a_np, split=0), ht.array(b_np))
    # the fallback is backward-stable: residual small against ||A|| ||x||
    # (at cond 1e6 no two f32 backends agree on x itself)
    xn = x.numpy()
    resid = np.abs(a_np @ xn - b_np).max() / max(np.abs(xn).max() * np.abs(a_np).max(), 1e-30)
    assert resid < 1e-5, resid


def test_solve_validation_and_singular():
    with pytest.raises(ValueError):
        ht.solve(ht.ones((3, 4)), ht.ones(3))
    with pytest.raises(ValueError):
        ht.solve(ht.ones((4, 4)), ht.ones(5))
    with pytest.raises(RuntimeError, match="[Ss]ingular"):
        ht.solve(ht.ones((8, 8), split=0), ht.ones(8))


@pytest.mark.parametrize("split", [0, 1, None])
def test_slogdet_matches_numpy_no_overflow(split):
    """slogdet of a matrix whose raw det overflows f32: the (sign, log) pair
    must still be exact (the panel kernel accumulates it natively)."""
    rng = np.random.default_rng(12)
    n = 96
    a_np = rng.standard_normal((n, n)).astype(np.float32) + 3 * np.eye(n, dtype=np.float32)
    s_np, l_np = np.linalg.slogdet(a_np.astype(np.float64))
    assert l_np > 88.7  # raw f32 det would be inf
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        s, l = ht.slogdet(ht.array(a_np, split=split))
    assert float(s.larray) == s_np
    # f32 log accumulation across p blocks: ~1e-5-relative per-block rounding
    np.testing.assert_allclose(float(l.larray), l_np, rtol=1e-4)


@pytest.mark.slow  # redundant with test_det_inv_distributed's pivot path;
# unfiltered device-matrix CI job keeps coverage (ISSUE 16 tier-1 rebalance)
def test_det_inv_singular_fallback():
    """A singular matrix: det warns (block pivot hit zero) but returns 0;
    inv raises like the reference (basics.py:331-423 'Inverse does not exist')."""
    ones = ht.ones((32, 32), split=0)
    if ones.comm.is_distributed():
        with pytest.warns(UserWarning, match="falling back"):
            d = ht.det(ones)
    else:
        d = ht.det(ones)
    assert float(d.larray) == 0.0
    with pytest.raises(RuntimeError, match="Inverse does not exist"):
        ht.inv(ones)


def test_norms():
    a = np.arange(1.0, 7.0, dtype=np.float32).reshape(2, 3)
    h = ht.array(a, split=0)
    np.testing.assert_allclose(float(ht.norm(h).larray), np.linalg.norm(a), rtol=1e-5)
    np.testing.assert_allclose(
        ht.vector_norm(ht.array(a[0])).numpy(), np.linalg.norm(a[0]), rtol=1e-5
    )
    np.testing.assert_allclose(ht.matrix_norm(h).numpy(), np.linalg.norm(a), rtol=1e-5)


def test_transpose_tril_triu():
    a = np.arange(12.0, dtype=np.float32).reshape(3, 4)
    h = ht.array(a, split=1)
    t = ht.transpose(h)
    np.testing.assert_array_equal(t.numpy(), a.T)
    assert t.split == 0
    np.testing.assert_array_equal(ht.tril(ht.array(a)).numpy(), np.tril(a))
    np.testing.assert_array_equal(ht.triu(ht.array(a), k=1).numpy(), np.triu(a, 1))


def test_outer_projection_vdot_vecdot_cross():
    x = np.array([1.0, 2.0, 3.0], np.float32)
    y = np.array([4.0, 5.0, 6.0], np.float32)
    hx, hy = ht.array(x, split=0), ht.array(y)
    np.testing.assert_allclose(ht.outer(hx, hy).numpy(), np.outer(x, y))
    np.testing.assert_allclose(
        ht.projection(hx, hy).numpy(), (x @ y) / (y @ y) * y, rtol=1e-5
    )
    np.testing.assert_allclose(float(ht.vdot(hx, hy).larray), np.vdot(x, y))
    np.testing.assert_allclose(ht.vecdot(hx, hy).numpy(), np.dot(x, y))
    np.testing.assert_allclose(ht.cross(hx, hy).numpy(), np.cross(x, y))
    with pytest.raises(RuntimeError):
        ht.projection(ht.ones((2, 2)), hy)


def test_cg():
    rng = np.random.default_rng(7)
    m = rng.normal(size=(6, 6)).astype(np.float32)
    A = m @ m.T + 6 * np.eye(6, dtype=np.float32)
    b = rng.normal(size=(6,)).astype(np.float32)
    hA, hb = ht.array(A), ht.array(b)
    x0 = ht.zeros((6,))
    x = ht.cg(hA, hb, x0)
    np.testing.assert_allclose(A @ x.numpy(), b, atol=1e-2)
    with pytest.raises(TypeError):
        ht.cg(A, hb, x0)


def test_lanczos():
    rng = np.random.default_rng(8)
    m = rng.normal(size=(10, 10)).astype(np.float32)
    A = (m + m.T) / 2
    hA = ht.array(A)
    V, T = ht.lanczos(hA, 10)
    # V T V^T ~ A for full Krylov dimension
    recon = V.numpy() @ T.numpy() @ V.numpy().T
    np.testing.assert_allclose(recon, A, atol=1e-2)


def test_svd():
    rng = np.random.default_rng(9)
    a = rng.normal(size=(32, 4)).astype(np.float32)
    h = ht.array(a, split=0)
    u, s, vh = ht.linalg.svd(h)
    np.testing.assert_allclose(
        u.numpy() @ np.diag(s.numpy()) @ vh.numpy(), a, atol=1e-3
    )
    np.testing.assert_allclose(s.numpy(), np.linalg.svd(a, compute_uv=False), rtol=1e-4)
    s_only = ht.linalg.svd(ht.array(a), compute_uv=False)
    np.testing.assert_allclose(s_only.numpy(), np.linalg.svd(a, compute_uv=False), rtol=1e-4)


def test_svd_wide_split1():
    # wide column-split input takes the transpose trick; Vh stays column-split
    rng = np.random.default_rng(10)
    a = rng.normal(size=(4, 32)).astype(np.float32)
    h = ht.array(a, split=1)
    u, s, vh = ht.linalg.svd(h)
    assert u.shape == (4, 4) and s.shape == (4,) and vh.shape == (4, 32)
    assert vh.split == 1
    np.testing.assert_allclose(
        u.numpy() @ np.diag(s.numpy()) @ vh.numpy(), a, atol=1e-3
    )
    np.testing.assert_allclose(s.numpy(), np.linalg.svd(a, compute_uv=False), rtol=1e-4)
    # orthonormality of both factors
    np.testing.assert_allclose(u.numpy().T @ u.numpy(), np.eye(4), atol=1e-4)
    np.testing.assert_allclose(vh.numpy() @ vh.numpy().T, np.eye(4), atol=1e-4)


def test_rsvd():
    rng = np.random.default_rng(11)
    # low-rank + noise: exact rank-r structure dominates
    r = 5
    base = rng.normal(size=(256, r)).astype(np.float32) @ rng.normal(size=(r, 48)).astype(np.float32)
    a = base + 1e-4 * rng.normal(size=(256, 48)).astype(np.float32)
    h = ht.array(a, split=0)
    u, s, vh = ht.linalg.rsvd(h, rank=r, n_iter=3, random_state=0)
    assert u.shape == (256, r) and s.shape == (r,) and vh.shape == (r, 48)
    assert u.split == 0  # factor stays row-distributed
    recon = u.numpy() @ np.diag(s.numpy()) @ vh.numpy()
    np.testing.assert_allclose(recon, a, atol=5e-2)
    np.testing.assert_allclose(
        s.numpy(), np.linalg.svd(a, compute_uv=False)[:r], rtol=1e-2
    )
    np.testing.assert_allclose(u.numpy().T @ u.numpy(), np.eye(r), atol=1e-3)
    with pytest.raises(ValueError):
        ht.linalg.rsvd(h, rank=0)
    with pytest.raises(ValueError):
        ht.linalg.rsvd(ht.array(a[0]), rank=2)


def test_qr_gather_fallback_warns():
    # VERDICT r2 weak #5: the fall-off from TSQR/BCGS2 must be visible
    p = ht.get_comm().size
    if p < 2:
        pytest.skip("needs a multi-device mesh")
    with pytest.warns(UserWarning, match="gathered factorization"):
        ht.linalg.qr(ht.random.randn(4 * p + 1, 3, split=0))  # ragged split 0
    with pytest.warns(UserWarning, match="short panels"):
        ht.linalg.qr(ht.random.randn(p, 2 * p, split=0))  # m/p < n
    with pytest.warns(UserWarning, match="calc_q=False"):
        ht.linalg.qr(ht.random.randn(16 * p, 4, split=0), calc_q=False)
    import warnings as _w

    # happy TSQR shape: NO warning
    with _w.catch_warnings():
        _w.simplefilter("error")
        res = ht.linalg.qr(ht.random.randn(8 * p, 4, split=0))
    assert res.Q.split == 0


def test_qr_matrix_shapes_and_accuracy():
    # deep QR grid: both splits, tall/square, divisible/ragged, calc_q on/off
    import warnings as _w

    p = ht.get_comm().size
    rng = np.random.default_rng(21)
    cases = [
        ((8 * p, 4), 0, True),
        ((8 * p, 4), 0, False),
        ((4 * p + 3, 3), 0, True),   # ragged -> gather fallback
        ((3 * p, 2 * p), 1, True),   # BCGS2
        ((3 * p, 2 * p), 1, False),
        ((6, 4), None, True),
    ]
    for shape, split, calc_q in cases:
        a_np = rng.normal(size=shape).astype(np.float32)
        a = ht.array(a_np, split=split)
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            res = ht.linalg.qr(a, calc_q=calc_q)
        r = res.R.numpy()
        assert np.allclose(np.triu(r), r, atol=1e-5), (shape, split)
        if calc_q:
            q = res.Q.numpy()
            np.testing.assert_allclose(q @ r, a_np, rtol=1e-3, atol=1e-3)
            np.testing.assert_allclose(
                q.T @ q, np.eye(q.shape[1]), rtol=1e-3, atol=2e-3
            )
        else:
            assert res.Q is None
            # R must match the calc_q factorization up to column signs
            with _w.catch_warnings():
                _w.simplefilter("ignore")
                r2 = ht.linalg.qr(a, calc_q=True).R.numpy()
            np.testing.assert_allclose(np.abs(r), np.abs(r2), rtol=1e-3, atol=1e-3)


def test_matmul_dtype_shape_grid():
    rng = np.random.default_rng(22)
    p = ht.get_comm().size
    for dt in (np.float32, np.int32):
        for (ma, mb), (sa, sb) in [
            (((2 * p, 8), (8, 6)), (0, None)),
            (((6, 2 * p), (2 * p, 4)), (1, 0)),
            (((5, 7), (7, 3)), (None, None)),
            (((2 * p + 1, 8), (8, 6)), (0, None)),  # ragged rows
        ]:
            a_np = (rng.normal(size=ma) * 4).astype(dt)
            b_np = (rng.normal(size=mb) * 4).astype(dt)
            c = ht.matmul(ht.array(a_np, split=sa), ht.array(b_np, split=sb))
            np.testing.assert_allclose(
                c.numpy().astype(np.float64),
                (a_np.astype(np.float64) @ b_np.astype(np.float64)),
                rtol=2e-3, atol=2e-3,
            )


@pytest.mark.slow  # ~8 s of cg/gmres edge sweeps; unfiltered device-matrix CI
# job keeps coverage (ISSUE 16 tier-1 rebalance)
def test_solver_edge_cases():
    rng = np.random.default_rng(23)
    p = ht.get_comm().size
    n = 4 * p
    # SPD system for cg
    m_np = rng.normal(size=(n, n)).astype(np.float32)
    a_np = m_np @ m_np.T + n * np.eye(n, dtype=np.float32)
    b_np = rng.normal(size=(n,)).astype(np.float32)
    x = ht.linalg.cg(
        ht.array(a_np, split=0), ht.array(b_np, split=0), ht.zeros((n,), split=0)
    )
    np.testing.assert_allclose(a_np @ x.numpy(), b_np, rtol=1e-2, atol=1e-2)
    # lanczos returns factors with the promised shapes
    V, T = ht.linalg.lanczos(ht.array(a_np, split=0), m=5)
    assert V.shape == (n, 5) and T.shape == (5, 5)


def test_linalg_basics_surface_matrix():
    rng = np.random.default_rng(101)
    a_np = rng.normal(size=(4, 4)).astype(np.float32)
    b_np = rng.normal(size=(4, 4)).astype(np.float32)
    v_np = rng.normal(size=4).astype(np.float32)
    w_np = rng.normal(size=4).astype(np.float32)
    for split in (None, 0):
        a, b = ht.array(a_np, split=split), ht.array(b_np, split=split)
        v, w = ht.array(v_np, split=split), ht.array(w_np, split=split)
        np.testing.assert_allclose(ht.linalg.det(a).numpy(), np.linalg.det(a_np), rtol=1e-3)
        np.testing.assert_allclose(
            ht.linalg.inv(a).numpy(), np.linalg.inv(a_np), rtol=1e-2, atol=1e-3
        )
        np.testing.assert_allclose(float(ht.linalg.vdot(v, w).numpy()), float(np.vdot(v_np, w_np)), rtol=1e-4)
        np.testing.assert_allclose(ht.linalg.outer(v, w).numpy(), np.outer(v_np, w_np), rtol=1e-4)
        np.testing.assert_allclose(float(ht.linalg.trace(a)), float(np.trace(a_np)), rtol=1e-4)
        np.testing.assert_allclose(
            float(ht.linalg.norm(v).numpy()), float(np.linalg.norm(v_np)), rtol=1e-4
        )
        np.testing.assert_allclose(
            float(ht.linalg.matrix_norm(a).numpy()), float(np.linalg.norm(a_np)), rtol=1e-4
        )
        np.testing.assert_allclose(ht.linalg.tril(a).numpy(), np.tril(a_np), rtol=1e-6)
        np.testing.assert_allclose(ht.linalg.triu(a).numpy(), np.triu(a_np), rtol=1e-6)
        np.testing.assert_allclose(
            ht.linalg.transpose(a).numpy(), a_np.T, rtol=1e-6
        )
    c1 = ht.array(np.array([1.0, 0.0, 0.0], np.float32))
    c2 = ht.array(np.array([0.0, 1.0, 0.0], np.float32))
    np.testing.assert_allclose(ht.linalg.cross(c1, c2).numpy(), [0.0, 0.0, 1.0], atol=1e-6)


def test_svd_reconstruction_and_rsvd():
    rng = np.random.default_rng(102)
    p = ht.get_comm().size
    m, n = 8 * p, 6
    a_np = rng.normal(size=(m, n)).astype(np.float32)
    a = ht.array(a_np, split=0)
    res = ht.linalg.svd(a)
    U, S, Vt = res
    np.testing.assert_allclose(
        U.numpy() @ np.diag(S.numpy()) @ Vt.numpy(), a_np, rtol=1e-2, atol=1e-2
    )
    s_np = np.linalg.svd(a_np, compute_uv=False)
    np.testing.assert_allclose(np.sort(S.numpy())[::-1], s_np, rtol=1e-2, atol=1e-2)
    # rsvd captures a low-rank matrix almost exactly
    lr_np = (rng.normal(size=(m, 3)) @ rng.normal(size=(3, n))).astype(np.float32)
    lr = ht.array(lr_np, split=0)
    Ur, Sr, Vtr = ht.linalg.rsvd(lr, rank=3, n_oversamples=4)
    np.testing.assert_allclose(
        Ur.numpy() @ np.diag(Sr.numpy()) @ Vtr.numpy(), lr_np, rtol=5e-2, atol=5e-2
    )
