"""
Differential and behavioral suite for the deferred-execution fusion engine
(``heat_tpu/core/fusion.py``, ``HEAT_TPU_FUSION``).

Layout of the guarantees pinned here:

* **Golden op table, bit-for-bit.** Every whitelisted elementwise op, executed
  once through the fused path and once with ``HEAT_TPU_FUSION=0``, must agree
  to the byte across split ∈ {None, 0, 1}, even and ragged/padded shapes, and
  f32/bf16. Scalars ride the trace as weak-typed runtime arguments (never
  baked constants), so there is no constant-folding drift (x/3.0 stays a
  division); integer ``power`` exponents are baked so both paths lower via
  ``lax.integer_pow``.
* **Chains.** Contraction-free chains (no multiply feeding an add/sub) are
  bit-for-bit too, as are *all* bf16 chains (XLA mandates the bf16 rounding
  after every op even inside a fused loop). The one documented numeric
  difference of a fused f32 kernel is *excess precision*: XLA contracts
  ``a*b + c`` into a single FMA (one rounding instead of two, strictly more
  accurate) — pinned here as a ≤2-ulp bound rather than hidden behind a loose
  tolerance. ``doc/fusion_notes.md`` carries the analysis.
* **Every flush trigger** materializes (reductions, cumulatives, ``.numpy()``,
  ``item()``, printing, indexing reads/writes, ``out=`` aliasing, ``resplit_``,
  halos, monitoring export).
* **Escape hatch**: under ``HEAT_TPU_FUSION=0`` nothing ever defers.
* **Monitoring**: the ``fusion.*`` counters and the chain-length histogram.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu import monitoring
from heat_tpu.core import fusion
from heat_tpu.core.communication import get_comm
from heat_tpu.monitoring import registry, report

pytestmark = pytest.mark.fusion


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    registry.reset()
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    monkeypatch.setenv("HEAT_TPU_FUSION_SINKS", "1")
    yield
    registry.reset()


@pytest.fixture
def no_faults(monkeypatch):
    """Pin fault injection OFF for compile/cache-count-asserting tests.

    The CI robustness leg runs this whole marker suite under a standing
    ``HEAT_TPU_FAULT_PLAN`` compile-fault plan (ISSUE 6): every fused flush
    then recovers through the ladder's per-op eager replay, so *results* stay
    bit-identical — which is exactly what the differential tests prove — but
    fused-kernel/compile/cache-hit counting is meaningless there. Same
    precedent as the view/GEMM hatch leg, where deferral-asserting tests pin
    the gates ON via monkeypatch. Clearing the trace cache also drops
    signatures the standing plan poisoned earlier in the process, so this
    test's chains re-attempt fused compilation. The ISSUE 9 chaos-smoke legs
    extend the same precedent: a standing ``HEAT_TPU_CHAOS`` schedule or
    ``HEAT_TPU_BREAKER_FORCE_OPEN`` pin routes flushes through the degraded
    paths (bit-identical results, meaningless compile counts), so this
    fixture also pins chaos off and resets the circuit breakers."""
    from heat_tpu.robustness import breaker, faultinject

    monkeypatch.delenv("HEAT_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("HEAT_TPU_CHAOS", raising=False)
    monkeypatch.delenv("HEAT_TPU_BREAKER_FORCE_OPEN", raising=False)
    # ISSUE 12: a standing shadow-replay audit re-dispatches every recorded
    # op eagerly (its own jit compiles), so compile/cache-count assertions
    # are meaningless under the integrity-smoke audit leg too
    monkeypatch.delenv("HEAT_TPU_AUDIT_RATE", raising=False)
    monkeypatch.delenv("HEAT_TPU_COLLECTIVE_CHECKSUM", raising=False)
    faultinject.clear()
    breaker.reset()
    fusion.clear_cache()


def _bitwise_equal(a: np.ndarray, b: np.ndarray) -> bool:
    return a.shape == b.shape and a.dtype == b.dtype and a.tobytes() == b.tobytes()


def _both(monkeypatch, fn):
    """Run ``fn`` once eagerly (HEAT_TPU_FUSION=0) and once fused; return both
    results as numpy arrays."""
    monkeypatch.setenv("HEAT_TPU_FUSION", "0")
    eager = fn().numpy()
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    fused = fn().numpy()
    return eager, fused


def _operands(shape, split, dtype):
    rng = np.random.default_rng(42)
    a = ht.array(rng.standard_normal(shape).astype(np.float32), split=split).astype(dtype)
    b = ht.array(
        (rng.standard_normal(shape) + 2.5).astype(np.float32), split=split
    ).astype(dtype)
    # concrete operands: the table below measures op-level parity, not chains
    a.parray, b.parray  # noqa: B018
    return a, b


# every entry runs ONE recordable op (plus the | separators for readability);
# composed entries like sqrt(abs(.)) keep the domain valid, not chains
_GOLDEN_BINARY = [
    ("add", lambda a, b: a + b),
    ("sub", lambda a, b: a - b),
    ("mul", lambda a, b: a * b),
    ("div", lambda a, b: a / b),
    ("div_scalar", lambda a, b: a / 3.0),
    ("floordiv", lambda a, b: a // b),
    ("mod", lambda a, b: a % b),
    ("pow_int", lambda a, b: a ** 3),
    ("pow_npint", lambda a, b: a ** np.int64(2)),
    ("maximum", lambda a, b: ht.maximum(a, b)),
    ("minimum", lambda a, b: ht.minimum(a, b)),
    ("arctan2", lambda a, b: ht.arctan2(a, b)),
    ("hypot", lambda a, b: ht.hypot(a, b)),
    ("copysign", lambda a, b: ht.copysign(a, b)),
    ("logaddexp", lambda a, b: ht.logaddexp(a, b)),
    ("lt", lambda a, b: a < b),
    ("le", lambda a, b: a <= b),
    ("gt", lambda a, b: a > b),
    ("eq", lambda a, b: a == b),
    ("ne", lambda a, b: a != b),
]

_GOLDEN_UNARY = [
    ("abs", lambda a: ht.abs(a)),
    ("neg", lambda a: -a),
    ("sqrt_abs", lambda a: ht.sqrt(ht.abs(a))),
    ("exp", lambda a: ht.exp(a)),
    ("expm1", lambda a: ht.expm1(a)),
    ("log_abs", lambda a: ht.log(ht.abs(a) + 1.0)),
    ("sin", lambda a: ht.sin(a)),
    ("cos", lambda a: ht.cos(a)),
    ("tan", lambda a: ht.tan(a)),
    ("tanh", lambda a: ht.tanh(a)),
    ("floor", lambda a: ht.floor(a)),
    ("ceil", lambda a: ht.ceil(a)),
    ("trunc", lambda a: ht.trunc(a)),
    ("round", lambda a: ht.round(a)),
    ("sign", lambda a: ht.sign(a)),
    ("square", lambda a: ht.square(a)),
    ("isnan", lambda a: ht.isnan(a / a)),
    ("isfinite", lambda a: ht.isfinite(a)),
]


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize(
    "shape", [(16, 8), (13, 7)], ids=["even", "ragged"]
)
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_golden_binary_bitwise(monkeypatch, split, shape, dtype):
    a, b = _operands(shape, split, dtype)
    for name, op in _GOLDEN_BINARY:
        eager, fused = _both(monkeypatch, lambda: op(a, b))
        assert _bitwise_equal(eager, fused), f"{name} split={split} {shape} {dtype}"


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_golden_unary_bitwise(monkeypatch, split, shape, dtype):
    a, _ = _operands(shape, split, dtype)
    for name, op in _GOLDEN_UNARY:
        eager, fused = _both(monkeypatch, lambda: op(a))
        assert _bitwise_equal(eager, fused), f"{name} split={split} {shape} {dtype}"


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
def test_int_bool_ops_bitwise(monkeypatch, split, shape):
    rng = np.random.default_rng(3)
    ia = ht.array(rng.integers(1, 100, size=shape).astype(np.int32), split=split)
    ib = ht.array(rng.integers(1, 17, size=shape).astype(np.int32), split=split)
    ba = ia % 2 == 0
    bb = ib % 3 == 0
    ba.parray, bb.parray  # noqa: B018
    cases = [
        lambda: ia + ib, lambda: ia * ib, lambda: ia // ib, lambda: ia % ib,
        lambda: ia & ib, lambda: ia | ib, lambda: ia ^ ib,
        lambda: ia << 2, lambda: ia >> 1,
        lambda: ba & bb, lambda: ba | bb, lambda: ~ba,
        lambda: ia / ib,  # exact -> float promotion rides the cast-back rule
    ]
    for i, op in enumerate(cases):
        eager, fused = _both(monkeypatch, op)
        assert _bitwise_equal(eager, fused), f"case {i} split={split} {shape}"


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_contraction_free_chain_bitwise(monkeypatch, split, shape, dtype):
    # an 8-op chain with no multiply feeding an add/sub: no FMA contraction is
    # possible, so fused and op-at-a-time execution must agree to the byte
    a, b = _operands(shape, split, dtype)

    def chain():
        x = a / b
        x = ht.abs(x)
        x = ht.sqrt(x + 1.0)
        x = x / 3.0
        x = ht.maximum(x, b)
        x = -x
        x = ht.tanh(x)
        return x / 7.0

    eager, fused = _both(monkeypatch, chain)
    assert _bitwise_equal(eager, fused)


@pytest.mark.parametrize("split", [None, 0])
def test_bf16_fma_chain_bitwise(monkeypatch, split):
    # bf16 rounding is mandated after every op even inside a fused loop, so
    # even multiply->add chains stay bit-for-bit in bf16
    a, b = _operands((33, 9), split, ht.bfloat16)
    eager, fused = _both(monkeypatch, lambda: (a * b + b) * a - b)
    assert _bitwise_equal(eager, fused)


@pytest.mark.parametrize("split", [None, 0])
def test_f32_fma_chain_excess_precision_bound(monkeypatch, split):
    # the ONE permitted fused-vs-eager difference: XLA contracts f32
    # multiply->add into an FMA inside a fused kernel — a*b is NOT rounded to
    # f32 before the add (single rounding, strictly more accurate). The
    # fused-vs-eager gap is therefore bounded by one rounding of the product:
    # |fused - eager| <= eps_f32 * (|a*b| + |c|). Pinned exactly, not hidden
    # behind a loose tolerance.
    a, b = _operands((64, 16), split, ht.float32)
    eager, fused = _both(monkeypatch, lambda: a * b + 2.0)
    an, bn = a.numpy().astype(np.float64), b.numpy().astype(np.float64)
    f64 = an * bn + 2.0
    # fused (FMA) is at least as accurate as the double-rounded eager result
    assert np.abs(fused.astype(np.float64) - f64).max() <= np.abs(
        eager.astype(np.float64) - f64
    ).max()
    bound = 2.0**-23 * (np.abs(an * bn) + 2.0) + 2.0**-149
    assert (np.abs(fused.astype(np.float64) - eager.astype(np.float64)) <= bound).all()


# ------------------------------------------------------------------ flush triggers
def _pending_chain(split=0, shape=(13, 5)):
    rng = np.random.default_rng(7)
    a = ht.array(rng.standard_normal(shape).astype(np.float32), split=split)
    a.parray  # noqa: B018 — concrete input
    y = (a + 1.0) * 2.0
    assert fusion.is_deferred(y)
    return a, y


def test_flush_on_numpy():
    a, y = _pending_chain()
    ref = (a.numpy() + 1.0) * 2.0
    assert _bitwise_equal(y.numpy(), ref)
    assert not fusion.is_deferred(y)


def test_reduction_is_sink_not_flush():
    # ISSUE 4: a reduction over a pending chain is a SINK — the chain stays
    # pending (and replayable) and the reduction result is itself deferred,
    # re-rooting a new chain for scalar epilogues
    a, y = _pending_chain()
    s = y.sum()
    assert fusion.is_deferred(y)
    assert fusion.is_deferred(s)
    np.testing.assert_allclose(float(s), ((a.numpy() + 1.0) * 2.0).sum(), rtol=1e-5)
    # the chain replays bit-exactly after the sink consumed it in-register
    assert _bitwise_equal(y.numpy(), (a.numpy() + 1.0) * 2.0)


def test_reduction_flushes_with_sinks_off(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_SINKS", "0")
    a, y = _pending_chain()
    s = y.sum()
    assert not fusion.is_deferred(y)
    assert not fusion.is_deferred(s)
    np.testing.assert_allclose(float(s), ((a.numpy() + 1.0) * 2.0).sum(), rtol=1e-5)


def test_cumsum_is_sink_not_flush():
    a, y = _pending_chain()
    c = ht.cumsum(y, axis=0)
    assert fusion.is_deferred(y)
    assert fusion.is_deferred(c)
    np.testing.assert_allclose(
        c.numpy(), np.cumsum((a.numpy() + 1.0) * 2.0, axis=0), rtol=1e-5
    )


def test_cumsum_flushes_with_sinks_off(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_SINKS", "0")
    a, y = _pending_chain()
    c = ht.cumsum(y, axis=0)
    assert not fusion.is_deferred(y)
    np.testing.assert_allclose(
        c.numpy(), np.cumsum((a.numpy() + 1.0) * 2.0, axis=0), rtol=1e-5
    )


def test_flush_on_item_and_bool():
    a = ht.array(np.float32(3.0))
    y = a * 2.0
    assert float(y) == 6.0
    z = a > 1.0
    assert bool(z)


def test_flush_on_print():
    _, y = _pending_chain()
    s = str(y)
    assert not fusion.is_deferred(y)
    assert "DNDarray" in s or "[" in s


def test_getitem_defers_basic_read_flushes_advanced(monkeypatch):
    # ISSUE 5: a basic (slice/int) read over a pending chain records a VIEW
    # node — the chain stays pending; an advanced key keeps the flush barrier
    monkeypatch.setenv("HEAT_TPU_FUSION_VIEWS", "1")
    a, y = _pending_chain()
    row = y[0]
    assert fusion.is_deferred(y)
    assert fusion.is_deferred(row)
    np.testing.assert_allclose(row.numpy(), (a.numpy()[0] + 1.0) * 2.0, rtol=1e-6)
    adv = y[np.array([0, 2])]
    assert not fusion.is_deferred(y)  # advanced key: flushed at the read
    np.testing.assert_allclose(
        adv.numpy(), ((a.numpy() + 1.0) * 2.0)[[0, 2]], rtol=1e-6
    )


def test_getitem_flushes_with_views_off(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_VIEWS", "0")
    a, y = _pending_chain()
    row = y[0]
    assert not fusion.is_deferred(y)
    assert not fusion.is_deferred(row)
    np.testing.assert_allclose(row.numpy(), (a.numpy()[0] + 1.0) * 2.0, rtol=1e-6)


def test_scalar_element_read_flushes():
    # 0-d element reads gain nothing from deferral (and per-element probing
    # would compile one kernel per index): they keep the flush barrier
    a, y = _pending_chain()
    v = y[0, 0]
    assert not fusion.is_deferred(y)
    np.testing.assert_allclose(float(v), (a.numpy()[0, 0] + 1.0) * 2.0, rtol=1e-6)


def test_flush_on_setitem():
    a, y = _pending_chain()
    y[0, 0] = 5.0
    assert not fusion.is_deferred(y)
    ref = (a.numpy() + 1.0) * 2.0
    ref[0, 0] = 5.0
    assert _bitwise_equal(y.numpy(), ref)


def test_resplit_records_collective_over_pending(monkeypatch):
    # ISSUE 7: resplit_ over a pending chain records a collective node (the
    # chain STAYS pending under the new split metadata) instead of flushing;
    # HEAT_TPU_FUSION_COLLECTIVES=0 restores the flush barrier
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    a, y = _pending_chain(split=0)
    y.resplit_(1)
    if get_comm().is_distributed():
        assert fusion.is_deferred(y)
    assert y.split == 1
    assert _bitwise_equal(y.numpy(), (a.numpy() + 1.0) * 2.0)
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "0")
    a, y = _pending_chain(split=0)
    y.resplit_(1)
    assert not fusion.is_deferred(y)
    assert _bitwise_equal(y.numpy(), (a.numpy() + 1.0) * 2.0)


def test_halo_defers_over_pending(monkeypatch):
    # ISSUE 7: get_halo over a pending chain records the exchange (chain +
    # ppermute compile at the first halo read); the hatch restores the flush
    if not get_comm().is_distributed():
        pytest.skip("halos require a multi-device mesh")
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    a, y = _pending_chain(split=0, shape=(16, 4))
    y.get_halo(1)
    assert fusion.is_deferred(y)
    assert y.halo_prev is not None  # materializes chain + exchange together
    assert tuple(y.array_with_halos.shape)[1] == 16 // get_comm().size + 2
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "0")
    a, y = _pending_chain(split=0, shape=(16, 4))
    y.get_halo(1)
    assert not fusion.is_deferred(y)


def test_flush_on_monitoring_export():
    _, y = _pending_chain()
    with monitoring.capture():
        snap = report.snapshot()
    assert not fusion.is_deferred(y)
    assert isinstance(snap, dict)


def test_matmul_records_producer_over_pending(monkeypatch):
    # ISSUE 5: matmul over a pending chain records a GEMM producer node —
    # the chain is absorbed, not flushed; HEAT_TPU_FUSION_GEMM=0 restores the
    # flush-at-GEMM barrier bit for bit
    monkeypatch.setenv("HEAT_TPU_FUSION_GEMM", "1")
    # 16 rows divide every CI mesh size (1/2/4/8): the operand is unpadded,
    # so the producer path records (padded operands keep the eager fallback)
    a, y = _pending_chain(split=0, shape=(16, 6))
    m = ht.matmul(y, ht.ones((6, 3), split=None))
    assert fusion.is_deferred(y)
    assert fusion.is_deferred(m)
    np.testing.assert_allclose(
        m.numpy(), ((a.numpy() + 1.0) * 2.0) @ np.ones((6, 3), np.float32), rtol=1e-5
    )
    monkeypatch.setenv("HEAT_TPU_FUSION_GEMM", "0")
    a2, y2 = _pending_chain(split=0, shape=(16, 6))
    m2 = ht.matmul(y2, ht.ones((6, 3), split=None))
    assert not fusion.is_deferred(y2)
    assert not fusion.is_deferred(m2)
    np.testing.assert_allclose(
        m2.numpy(), ((a2.numpy() + 1.0) * 2.0) @ np.ones((6, 3), np.float32), rtol=1e-5
    )


def test_sort_flushes_operand():
    # ops outside the elementwise/view/GEMM/sink families still flush
    a, y = _pending_chain(split=0, shape=(12, 6))
    v, _ = ht.sort(y, axis=1)
    assert not fusion.is_deferred(y)
    np.testing.assert_allclose(
        v.numpy(), np.sort((a.numpy() + 1.0) * 2.0, axis=1), rtol=1e-6
    )


# ------------------------------------------------------------------ out=/where aliasing
def test_out_flushes_operands_and_matches_eager(monkeypatch):
    def run():
        rng = np.random.default_rng(11)
        a = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        b = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        pending = a * 2.0  # operand carrying an unflushed expression
        out = ht.zeros((13, 5), split=0)
        ht.add(pending, b, out=out)
        return out

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_out_aliasing_self(monkeypatch):
    def run():
        rng = np.random.default_rng(12)
        a = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        b = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        x = a + 1.0
        ht.mul(x, b, out=x)  # out aliases an operand
        return x

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_write_into_pending_out_elides_graph():
    rng = np.random.default_rng(13)
    a = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
    b = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
    a.parray, b.parray  # noqa: B018
    with monitoring.capture():
        out = a * 3.0  # pending expression that is never needed
        assert fusion.is_deferred(out)
        ht.add(a, b, out=out)  # overwrites: dead graph must be DROPPED
        snap = registry.snapshot()
    assert not fusion.is_deferred(out)
    assert _bitwise_equal(out.numpy(), a.numpy() + b.numpy())
    counters = snap["counters"]
    assert counters.get("fusion.elided_writes", 0) >= 1


def test_where_kwarg_matches_eager(monkeypatch):
    def run():
        rng = np.random.default_rng(14)
        a = ht.array(rng.standard_normal((16, 8)).astype(np.float32), split=0)
        b = ht.array(rng.standard_normal((16, 8)).astype(np.float32), split=0)
        mask = a > 0
        return ht.add(a, b, where=mask)

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_where_select_matches_eager(monkeypatch):
    def run():
        rng = np.random.default_rng(15)
        a = ht.array(rng.standard_normal((16, 8)).astype(np.float32), split=0)
        b = ht.array(rng.standard_normal((16, 8)).astype(np.float32), split=0)
        return ht.where(a > b, a * 2.0, b - 1.0)

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_astype_glue_fuses_and_matches(monkeypatch):
    def run():
        rng = np.random.default_rng(16)
        a = ht.array(rng.standard_normal((13, 7)).astype(np.float32), split=0)
        return ((a + 1.0).astype(ht.bfloat16) * 2.0).astype(ht.float32) / 3.0

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


# ------------------------------------------------------------------ engine behavior
def test_escape_hatch_never_defers(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION", "0")
    a = ht.ones((8, 4), split=0)
    y = (a + 1.0) * 2.0
    assert not fusion.is_deferred(y)
    assert not fusion.enabled()


def test_deferred_metadata_without_materialization():
    a, y = _pending_chain(split=0, shape=(13, 5))
    # shape/dtype/split/pshape are statically known — reading them must not flush
    assert y.shape == (13, 5)
    assert y.split == 0
    assert y.dtype == ht.float32
    if get_comm().is_distributed():
        p = get_comm().size
        assert y.pshape[0] == -(-13 // p) * p
        assert y.is_padded
    assert fusion.is_deferred(y)


def test_chain_length_bound(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_MAX_CHAIN", "4")
    x = ht.ones((8,), split=0)
    x.parray  # noqa: B018
    for _ in range(11):
        x = x + 1.0
    # bounded recording flushed intermediate kernels; the value is exact
    assert _bitwise_equal(x.numpy(), np.full((8,), 12.0, np.float32))


def test_trace_cache_hits_and_lru(monkeypatch, no_faults):
    fusion.clear_cache()
    base = fusion.cache_info()
    a = ht.ones((8, 4), split=0)
    a.parray  # noqa: B018
    for _ in range(3):
        _ = ((a + 1.0) * 2.0).numpy()  # identical structure: one compile
    info = fusion.cache_info()
    assert info["hits"] >= base["hits"] + 2
    monkeypatch.setenv("HEAT_TPU_FUSION_CACHE_SIZE", "2")
    _ = (a - 1.0).numpy()
    _ = (a * 3.0).numpy()
    _ = (a / 2.0).numpy()
    assert fusion.cache_info()["entries"] <= 2


def test_monitoring_counters(monkeypatch, no_faults):
    rng = np.random.default_rng(17)
    a = ht.array(rng.standard_normal((16, 4)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        y = ht.sqrt(ht.abs(a * 2.0) + 1.0)
        _ = y.numpy()
        _ = ht.sqrt(ht.abs(a * 2.0) + 1.0).numpy()  # same structure: cache hit
        snap = registry.snapshot()
    c = snap["counters"]
    deferred = c["fusion.ops_deferred"]
    assert deferred["total"] >= 6
    assert set(deferred["labels"]) >= {"binary", "local"}
    assert c["fusion.flushes"] >= 2
    assert c.get("fusion.cache_hits", 0) >= 1
    assert c["fusion.kernels_compiled"] >= 1
    hist = snap["histograms"]["fusion.chain_length"]
    assert hist["count"] >= 2
    assert hist["sum"] >= 6


def test_pending_registry_and_flush_pending():
    _, y = _pending_chain()
    assert fusion.pending_count() >= 1
    n = fusion.flush_pending()
    assert n >= 1
    assert fusion.pending_count() == 0
    assert not fusion.is_deferred(y)


def test_deferred_operand_feeds_downstream_graph(monkeypatch):
    # a pending result used by several later chains: shared subgraph replays
    # correctly whichever root flushes first
    def run():
        rng = np.random.default_rng(18)
        a = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        shared = a * 2.0 + 1.0
        u = ht.sqrt(ht.abs(shared))
        v = shared - 3.0
        return ht.stack([u.resplit_(None), v.resplit_(None)], axis=0)

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_fusion_inside_jit_falls_back():
    # recording must refuse tracers: ops on DNDarrays built inside jit keep
    # eager template semantics (the tracer guard)
    import jax

    from heat_tpu.core.dndarray import DNDarray

    a = ht.ones((6,), split=None)

    def f(arr):
        d = DNDarray(arr, (6,), ht.float32, None, a.device, a.comm, True)
        out = d + 1.0
        assert not fusion.is_deferred(out)
        return out.parray

    y = jax.jit(f)(a.parray)
    np.testing.assert_allclose(np.asarray(y), np.full((6,), 2.0, np.float32))


# ------------------------------------------------------------------ reduction sinks (ISSUE 4)
#
# A reduction over a pending chain records a SINK node: the elementwise
# subgraph + pad handling + the reduction (+ the sharded combine) trace as ONE
# kernel, and the sink result roots a new pending chain for epilogues. The
# differential suite pins bit-for-bit parity vs HEAT_TPU_FUSION=0 across
# split/ragged/dtype/op/axis/keepdims/where, with exactly one carve-out: f32
# mul->add chains feeding an arithmetic sink contract to FMA / keep excess
# precision inside the fused kernel (bound pinned below). Sub-32-bit float
# arithmetic sinks intentionally flush instead (the fused producer would skip
# the final bf16 rounding before the f32-upcast accumulator), so their rows
# exercise the fall-back path and stay trivially bit-exact.


def _sink_chain(a, b):
    """Contraction-free chain (no multiply feeding an add/sub and no products
    feeding the sink's accumulator): bit-exact under fusion per the PR-3
    guarantee, so any sink divergence is the sink's own."""
    y = (a + b) / 1.7
    y = ht.abs(y) - 0.25
    return y


_SINK_REDUCES = [
    ("sum", lambda y, kw: ht.sum(y, **kw)),
    ("prod", lambda y, kw: ht.prod(y, **kw)),
    ("min", lambda y, kw: ht.min(y, **kw)),
    ("max", lambda y, kw: ht.max(y, **kw)),
    ("mean", lambda y, kw: ht.mean(y, **kw)),
    # var/std are NOT in the bitwise table: their internal (x-mu)**2 products
    # feed the sink's accumulator — the documented FMA/excess-precision
    # carve-out, bounded in test_f32_product_into_sum_sink_fma_bound
    ("any", lambda y, kw: (y > 0).any(**kw)),
    ("all", lambda y, kw: (y > 0).all(**kw)),
]


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_reduction_sink_differential(monkeypatch, split, shape, dtype):
    a, b = _operands(shape, split, dtype)
    # full axis/keepdims sweep on sum; the other ops cover the three
    # structurally distinct cases (full, split-axis, tuple) — each extra
    # combination costs two fresh XLA compiles, and tier-1's budget is fixed
    full_axes = [{}, {"axis": 0}, {"axis": 1}, {"axis": (0, 1)}, {"axis": 0, "keepdims": True}]
    rep_axes = [{}, {"axis": 0}, {"axis": (0, 1)}]
    for name, op in _SINK_REDUCES:
        for kw in (full_axes if name == "sum" else rep_axes):
            eager, fused = _both(monkeypatch, lambda: op(_sink_chain(a, b), dict(kw)))
            assert _bitwise_equal(eager, fused), (
                f"{name} kw={kw} split={split} {shape} {dtype}"
            )


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_cumulative_sink_differential(monkeypatch, split, shape, dtype):
    a, b = _operands(shape, split, dtype)
    # cumsum along axis 0 (the comm.Cum split-axis pipeline when split=0),
    # cumprod along axis 1 — the two structurally distinct cum paths
    for op, axis in ((ht.cumsum, 0), (ht.cumprod, 1)):
        eager, fused = _both(
            monkeypatch, lambda: op(_sink_chain(a, b), axis=axis)
        )
        assert _bitwise_equal(eager, fused), f"{op.__name__} axis={axis} split={split} {shape} {dtype}"


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
def test_arg_reduction_sink_differential(monkeypatch, split, shape):
    a, b = _operands(shape, split, ht.float32)
    for kw in ({}, {"axis": 0}, {"axis": 1}):
        for op in (ht.argmax, ht.argmin):
            eager, fused = _both(monkeypatch, lambda: op(_sink_chain(a, b), **kw))
            assert _bitwise_equal(eager, fused), f"{op.__name__} kw={kw} split={split} {shape}"


@pytest.mark.parametrize("split", [None, 0])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
def test_where_mask_reduction_sink_differential(monkeypatch, split, shape):
    # where= masks ride the sink trace as runtime leaf operands
    a, b = _operands(shape, split, ht.float32)
    mask = a > 0
    mask.parray  # noqa: B018
    for kw in ({}, {"axis": 0}, {"axis": 1, "keepdims": True}):
        eager, fused = _both(
            monkeypatch, lambda: ht.sum(_sink_chain(a, b), where=mask, **kw)
        )
        assert _bitwise_equal(eager, fused), f"where-sum kw={kw} split={split} {shape}"
        eager, fused = _both(
            monkeypatch,
            lambda: (_sink_chain(a, b) > 0).all(where=mask, **kw),
        )
        assert _bitwise_equal(eager, fused), f"where-all kw={kw} split={split} {shape}"


def test_ragged_padded_neutral_fill_min_prod_any_all(monkeypatch):
    # satellite: the canonical pad fill must be the op's OWN neutral element —
    # a 0-fill corrupts min/prod/all. Ragged split-axis arrays, reduced along
    # the split axis (the only case where the pad could reach the combine).
    if not get_comm().is_distributed():
        pytest.skip("padded layouts require a multi-device mesh")
    rng = np.random.default_rng(21)
    # strictly positive data: a 0-poisoned pad would flip min/prod/all results
    av = (rng.random((13, 5)) + 0.5).astype(np.float32)
    bv = (rng.random((13, 5)) + 0.5).astype(np.float32)

    def run(op_kw):
        a = ht.array(av, split=0)
        b = ht.array(bv, split=0)
        a.parray, b.parray  # noqa: B018
        assert a.is_padded
        y = ht.abs((a + b) / 1.7) + 0.5  # positive chain
        op, kw = op_kw
        return op(y, **kw)

    for case in (
        (ht.min, {"axis": 0}),
        (ht.min, {}),
        (ht.prod, {"axis": 0}),
        (ht.max, {"axis": 0}),
        (lambda y, **kw: (y > 0).all(**kw), {"axis": 0}),
        (lambda y, **kw: (y < 0).any(**kw), {"axis": 0}),
        (ht.sum, {"axis": 0}),
    ):
        eager, fused = _both(monkeypatch, lambda: run(case))
        assert _bitwise_equal(eager, fused), f"padded {case[0]} {case[1]}"
        # and against plain numpy on the logical values (0-poison would show)
        ref_y = np.abs((av + bv) / np.float32(1.7)) + np.float32(0.5)
        op, kw = case
        if op is ht.min:
            ref = ref_y.min(**kw)
        elif op is ht.prod:
            ref = ref_y.prod(**kw, dtype=np.float32)
        elif op is ht.max:
            ref = ref_y.max(**kw)
        elif op is ht.sum:
            ref = ref_y.sum(**kw, dtype=np.float32)
        else:
            continue
        np.testing.assert_allclose(np.asarray(fused, np.float64), ref.astype(np.float64), rtol=2e-5)


def test_f32_product_into_sum_sink_fma_bound(monkeypatch):
    # the ONE permitted sink divergence: a product feeding the sum's
    # accumulator inside the fused kernel may keep excess precision / contract
    # to FMA. Bounded by one rounding of each product:
    # |fused - eager| <= sum_i eps_f32 * |y_i| (+ accumulation slack).
    a, b = _operands((64, 16), None, ht.float32)

    def run():
        y = a * b  # product chain tail feeds the sink accumulator
        return ht.sum(y, axis=0)

    eager, fused = _both(monkeypatch, run)
    yv = (a.numpy().astype(np.float64)) * (b.numpy().astype(np.float64))
    bound = 2.0**-23 * np.abs(yv).sum(axis=0) * 4 + 2.0**-149
    assert (np.abs(fused.astype(np.float64) - eager.astype(np.float64)) <= bound).all()
    # var/std/norm/vecdot carve-outs obey the same excess-precision class
    for op in (
        lambda: ht.var(_sink_chain(a, b), axis=0),
        lambda: ht.norm(_sink_chain(a, b)),
        lambda: ht.vecdot(_sink_chain(a, b), _sink_chain(a, b), axis=0),
    ):
        e2, f2 = _both(monkeypatch, op)
        np.testing.assert_allclose(
            f2.astype(np.float64), e2.astype(np.float64), rtol=1e-5, atol=1e-12
        )


def test_moment_and_norm_sinks_defer_and_match(monkeypatch):
    def cases():
        rng = np.random.default_rng(23)
        # evenly divisible split extent: padded operands intentionally fall
        # back to the flushing path for moment/norm sinks (reassociation)
        a = ht.array(rng.standard_normal((16, 6)).astype(np.float32), split=0)
        a.parray  # noqa: B018
        y = (a + 2.0) / 3.0
        return y

    y = cases()
    for fn in (
        lambda v: v.mean(axis=0),
        lambda v: v.var(axis=1),
        lambda v: v.std(),
        lambda v: ht.norm(v),
        lambda v: ht.vector_norm(v, axis=1),
        lambda v: ht.matrix_norm(v),
    ):
        r = fn(y)
        assert fusion.is_deferred(r), fn
        assert fusion.is_deferred(y)  # sink did not flush the chain
    # numeric parity for a representative pair
    eager, fused = _both(monkeypatch, lambda: cases().mean(axis=0))
    assert _bitwise_equal(eager, fused)
    eager, fused = _both(monkeypatch, lambda: ht.vector_norm(cases(), axis=1))
    np.testing.assert_allclose(fused, eager, rtol=1e-6)


def test_epilogue_re_rooting_single_kernel(no_faults):
    # acceptance: chain -> reduce (+ scalar epilogues) compiles exactly ONE
    # XLA executable, asserted via the jax.monitoring compile-miss listener
    rng = np.random.default_rng(29)
    # unique shape: no jit/trace cache can already hold this program
    a = ht.array(rng.standard_normal((37, 11)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        y = ht.sqrt(ht.abs(a) + 1.0) * 0.5
        s = y.sum(axis=0)
        t = ht.sqrt(s / 37.0)  # epilogue chain re-rooted at the sink
        assert fusion.is_deferred(t)
        base = registry.REGISTRY.counter("jit.compiles").get()
        t.numpy()  # single fused kernel: chain + reduce + epilogue
        compiles = registry.REGISTRY.counter("jit.compiles").get() - base
        snap = registry.snapshot()
    assert compiles == 1, f"expected exactly one XLA compile, got {compiles}"
    sinks = snap["counters"]["fusion.reduction_sinks"]
    assert sinks["labels"].get("reduce", 0) >= 1


def test_sink_chain_replay_after_rebind():
    # donation safety: the chain stays replayable after the sink consumed it,
    # even when the chain was rebound (dead intermediate owners)
    rng = np.random.default_rng(31)
    a = ht.array(rng.standard_normal((9, 4)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    x = a * 2.0
    x = x + 1.0  # rebind: the (a*2.0) intermediate's owner dies
    s = float(x.sum())
    ref = (a.numpy() * 2.0 + 1.0)
    np.testing.assert_allclose(s, ref.sum(), rtol=1e-5)
    assert _bitwise_equal(x.numpy(), ref)


def test_flush_reason_taxonomy():
    rng = np.random.default_rng(33)
    a = ht.array(rng.standard_normal((8, 4)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        str(a * 1.5)                      # print
        # advanced-key read: basic reads now defer (ISSUE 5), an integer-array
        # key keeps the indexing barrier
        _ = (a * 2.5)[np.array([0, 2])]   # indexing
        out = ht.zeros((8, 4), split=0)
        ht.add(a * 3.5, a, out=out)       # out-alias (pending operand flush)
        (a * 4.5).numpy()                 # export
        ht.linalg.tril(a * 5.5)           # linalg entry point
        snap = registry.snapshot()
    labels = snap["counters"]["fusion.flush_reason"]["labels"]
    for want in ("print", "indexing", "out-alias", "export", "linalg"):
        assert labels.get(want, 0) >= 1, (want, labels)


def test_reduction_flush_reason_with_sinks_off(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_SINKS", "0")
    rng = np.random.default_rng(34)
    a = ht.array(rng.standard_normal((8, 4)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        _ = (a + 1.0).sum()
        snap = registry.snapshot()
    labels = snap["counters"]["fusion.flush_reason"]["labels"]
    assert labels.get("reduction", 0) >= 1, labels


def test_cum_collective_prep_flush_counted(monkeypatch):
    # satellite bugfix: the distributed split-axis cumulative (comm.Cum prep)
    # must report its operand flush in fusion.flushes AND attribute it to the
    # collective flush reason — with sinks off it is a genuine flush
    if not get_comm().is_distributed():
        pytest.skip("comm.Cum path requires a multi-device mesh")
    monkeypatch.setenv("HEAT_TPU_FUSION_SINKS", "0")
    rng = np.random.default_rng(35)
    a = ht.array(rng.standard_normal((16, 3)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        _ = ht.cumsum(a * 2.0, axis=0)
        snap = registry.snapshot()
    c = snap["counters"]
    assert c["fusion.flushes"] >= 1
    assert c["fusion.flush_reason"]["labels"].get("collective", 0) >= 1


def test_cum_sink_traces_collective_in_program():
    # with sinks ON the same path records a cum sink instead of flushing
    if not get_comm().is_distributed():
        pytest.skip("comm.Cum path requires a multi-device mesh")
    rng = np.random.default_rng(36)
    a = ht.array(rng.standard_normal((16, 3)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        c = ht.cumsum(a * 2.0, axis=0)
        assert fusion.is_deferred(c)
        cn = c.numpy()
        snap = registry.snapshot()
    assert snap["counters"]["fusion.reduction_sinks"]["labels"].get("cum", 0) >= 1
    np.testing.assert_allclose(cn, np.cumsum(a.numpy() * 2.0, axis=0), rtol=1e-5)


def test_sink_trace_cache_key_separates_reduce_params(no_faults):
    # axis / keepdims / op variants over the SAME chain structure must compile
    # distinct kernels (cache key carries the sink signature) yet cache-hit on
    # exact repetition
    fusion.clear_cache()
    rng = np.random.default_rng(37)
    a = ht.array(rng.standard_normal((10, 6)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    base = fusion.cache_info()

    def go():
        y = a * 1.25 + 0.5
        return y

    _ = go().sum(axis=0).numpy()
    _ = go().sum(axis=1).numpy()
    _ = go().sum(axis=0, keepdims=True).numpy()
    _ = ht.prod(go(), axis=0).numpy()
    info = fusion.cache_info()
    assert info["misses"] - base["misses"] >= 4
    _ = go().sum(axis=0).numpy()  # exact repeat: hit
    assert fusion.cache_info()["hits"] >= info["hits"] + 1


def test_monitoring_export_flushes_sink_results():
    _, y = _pending_chain()
    s = y.sum()
    assert fusion.is_deferred(s)
    with monitoring.capture():
        report.snapshot()
    assert not fusion.is_deferred(s)


def test_sinks_respect_global_fusion_off(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION", "0")
    a = ht.ones((6, 3), split=0)
    s = (a + 1.0).sum()
    assert not fusion.is_deferred(s)
    assert not fusion.sink_ready(a)


def test_out_kwarg_reduce_skips_sink():
    rng = np.random.default_rng(38)
    a = ht.array(rng.standard_normal((8, 4)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    out = ht.zeros((4,), split=None)
    y = a * 2.0
    r = ht.sum(y, axis=0, out=out)
    assert r is out
    assert not fusion.is_deferred(r)
    np.testing.assert_allclose(out.numpy(), (a.numpy() * 2.0).sum(axis=0), rtol=1e-5)


def test_sink_flush_materializes_live_chain_in_same_kernel(monkeypatch, no_faults):
    # multi-output sink flush: when the consumed chain's owner is still alive
    # at flush time, the chain materializes as a SECOND output of the same
    # kernel — one compile total, no replay compile when the owner is read,
    # and both outputs bit-exact vs eager
    rng = np.random.default_rng(41)
    a = ht.array(rng.standard_normal((41, 9)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        y = (a + 1.0) * 0.5  # held alive across the flush
        s = y.sum(axis=0)
        base = registry.REGISTRY.counter("jit.compiles").get()
        sn = s.numpy()
        flush_compiles = registry.REGISTRY.counter("jit.compiles").get() - base
        base = registry.REGISTRY.counter("jit.compiles").get()
        y.parray  # noqa: B018 — value came from the dual-output kernel
        replay_compiles = registry.REGISTRY.counter("jit.compiles").get() - base
    assert flush_compiles == 1, flush_compiles
    assert replay_compiles == 0, replay_compiles
    ref = (a.numpy() + 1.0) * 0.5
    assert _bitwise_equal(y.numpy(), ref)
    np.testing.assert_allclose(sn, ref.sum(axis=0), rtol=1e-5)


# ------------------------------------------------------------------ view nodes (ISSUE 5)
#
# Structural ops over a pending chain record VIEW nodes: transpose /
# broadcast_to / expand_dims / squeeze / flip / basic-slice reads /
# split-preserving reshape move data in-register inside the fused kernel
# instead of flushing the chain. The differential suite pins bit-for-bit
# parity vs HEAT_TPU_FUSION=0 across split/ragged/dtype for every node kind —
# views are pure data movement, so there is no numeric carve-out at all; the
# pad either rides through, is re-established in-trace (split-axis slices),
# or the op takes the counted eager fallback (asymmetric pad situations,
# stepped split-axis slices), which is trivially bit-exact.


_VIEW_CASES = [
    ("T_property", lambda ht_, y: y.T + 0.5),
    ("transpose", lambda ht_, y: ht_.transpose(y) * 0.3),
    ("flipud", lambda ht_, y: ht_.flipud(y) - 1.0),
    ("fliplr", lambda ht_, y: ht_.fliplr(y) - 1.0),
    ("flip_all", lambda ht_, y: ht_.flip(y) * 2.0),
    ("expand_dims", lambda ht_, y: ht_.expand_dims(y, 1) * 2.0),
    ("squeeze", lambda ht_, y: ht_.squeeze(ht_.expand_dims(y, 0) * 2.0, 0)),
    ("broadcast_to", lambda ht_, y: ht_.broadcast_to(y, (3,) + tuple(y.shape)) + 1.0),
    ("reshape_flat", lambda ht_, y: y.reshape((y.shape[0] * y.shape[1],)) * 0.5),
    ("flatten", lambda ht_, y: y.flatten() * 0.5),
    ("slice_rows", lambda ht_, y: y[2:9] + 0.25),
    ("slice_cols", lambda ht_, y: y[:, 1:5] + 0.25),
    ("slice_step", lambda ht_, y: y[::2] + 0.25),
    ("slice_neg", lambda ht_, y: y[::-1] + 0.25),
    ("int_row", lambda ht_, y: y[3] + 0.25),
    ("newaxis", lambda ht_, y: y[None] + 0.25),
    ("mixed_key", lambda ht_, y: y[1:, None, 2] * 2.0),
]

#: views are dtype-transparent data movement (no arithmetic, no rounding), so
#: the bf16 rows cover each node KIND once instead of every variant — the
#: variant axes (flip direction, slice sign, property-vs-function) are dtype-
#: independent and stay in the f32 sweep; this keeps the matrix inside the
#: tier-1 budget (each extra case costs two fresh XLA compiles per combo)
_VIEW_KINDS_ONLY = [
    c for c in _VIEW_CASES
    if c[0] in (
        "transpose", "flip_all", "expand_dims", "squeeze", "broadcast_to",
        "reshape_flat", "slice_rows", "int_row",
    )
]


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_view_node_differential(monkeypatch, split, shape, dtype):
    a, b = _operands(shape, split, dtype)
    cases = _VIEW_CASES if dtype == ht.float32 else _VIEW_KINDS_ONLY
    for name, op in cases:
        # chain -> view -> epilogue: the view sits MID-chain, both its operand
        # and its consumer are recorded ops
        eager, fused = _both(monkeypatch, lambda: op(ht, (a + b) / 1.7))
        assert _bitwise_equal(eager, fused), f"{name} split={split} {shape} {dtype}"


@pytest.mark.parametrize("split", [None, 0, 1])
def test_view_chain_stays_pending(split, monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_VIEWS", "1")
    rng = np.random.default_rng(51)
    a = ht.array(rng.standard_normal((12, 6)).astype(np.float32), split=split)
    a.parray  # noqa: B018
    y = (a + 1.0) * 2.0
    t = y.T
    s = t[1:4]
    r = ht.sqrt(ht.abs(s))
    # nothing flushed: chain, views, and epilogue are all one pending DAG
    for v in (y, t, s, r):
        assert fusion.is_deferred(v), v.shape
    ref = np.sqrt(np.abs(((a.numpy() + 1.0) * 2.0).T[1:4]))
    np.testing.assert_allclose(r.numpy(), ref, rtol=1e-6)


def test_view_chain_single_compile(monkeypatch, no_faults):
    # acceptance: chain + transpose + slice + epilogue compile as exactly ONE
    # XLA program, and no flush is attributed to indexing
    monkeypatch.setenv("HEAT_TPU_FUSION_VIEWS", "1")
    rng = np.random.default_rng(53)
    # extents divide every CI mesh size: no pad anywhere, so the only XLA
    # compile in the window is the fused kernel itself
    a = ht.array(rng.standard_normal((48, 16)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        y = ht.sqrt(ht.abs(a) + 1.0) * 0.5
        y = y.T
        y = y[2:11]
        y = ht.tanh(y) * 0.3
        base = registry.REGISTRY.counter("jit.compiles").get()
        y.numpy()
        compiles = registry.REGISTRY.counter("jit.compiles").get() - base
        snap = registry.snapshot()
    assert compiles == 1, compiles
    labels = snap["counters"]["fusion.flush_reason"]["labels"]
    assert labels.get("indexing", 0) == 0, labels
    deferred = snap["counters"]["fusion.ops_deferred"]["labels"]
    assert deferred.get("view", 0) >= 2, deferred


def test_view_escape_hatch_never_defers(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_VIEWS", "0")
    a, y = _pending_chain()
    t = y.T
    assert not fusion.is_deferred(y)  # the view flushed the chain (old behavior)
    assert not fusion.is_deferred(t)
    assert _bitwise_equal(t.numpy(), ((a.numpy() + 1.0) * 2.0).T)


def test_view_flush_triggers_over_view_chain(monkeypatch):
    # the flush-trigger matrix applies unchanged to view-rooted chains:
    # print, index-write, and io/export all materialize the pending DAG
    monkeypatch.setenv("HEAT_TPU_FUSION_VIEWS", "1")

    def fresh():
        a, y = _pending_chain(split=0, shape=(12, 6))
        return a, y.T[1:4]

    a, v = fresh()
    assert fusion.is_deferred(v)
    s = str(v)  # print
    assert not fusion.is_deferred(v) and ("[" in s or "DNDarray" in s)

    a, v = fresh()
    v[0, 0] = 7.0  # index write
    assert not fusion.is_deferred(v)
    ref = ((a.numpy() + 1.0) * 2.0).T[1:4].copy()
    ref[0, 0] = 7.0
    assert _bitwise_equal(v.numpy(), ref)

    a, v = fresh()
    _ = v.numpy()  # export
    assert not fusion.is_deferred(v)


def test_view_replay_after_rebind():
    # a view over a rebound chain stays replayable: rebinding the operand
    # array does not corrupt the recorded subgraph (donation privacy)
    rng = np.random.default_rng(57)
    a = ht.array(rng.standard_normal((9, 4)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    x = a * 2.0
    t = x.T  # view over the pending chain
    x = x + 1.0  # rebind: the (a*2.0) owner dies, but t still references it
    ref = a.numpy() * 2.0
    assert _bitwise_equal(t.numpy(), ref.T)
    assert _bitwise_equal(x.numpy(), ref + 1.0)


def test_view_lru_key_separates_metadata(monkeypatch, no_faults):
    # distinct view parameters over the SAME chain structure must compile
    # distinct kernels (cache key carries the view node metadata) yet
    # cache-hit on exact repetition
    monkeypatch.setenv("HEAT_TPU_FUSION_VIEWS", "1")
    fusion.clear_cache()
    rng = np.random.default_rng(59)
    a = ht.array(rng.standard_normal((10, 6)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    base = fusion.cache_info()

    def go():
        return a * 1.25 + 0.5

    _ = go().T.numpy()
    _ = go()[2:5].numpy()
    _ = go()[3:6].numpy()  # different slice bounds: different kernel
    _ = ht.flipud(go()).numpy()
    info = fusion.cache_info()
    assert info["misses"] - base["misses"] >= 4
    _ = go()[2:5].numpy()  # exact repeat: hit
    assert fusion.cache_info()["hits"] >= info["hits"] + 1


def test_view_fallback_counters(monkeypatch):
    # asymmetric-pad (flip over a padded split axis) and stepped-split-slice
    # fallbacks are counted; both still produce bit-exact eager results
    monkeypatch.setenv("HEAT_TPU_FUSION_VIEWS", "1")
    if not get_comm().is_distributed():
        pytest.skip("padded layouts require a multi-device mesh")
    rng = np.random.default_rng(61)
    av = rng.standard_normal((13, 5)).astype(np.float32)
    with monitoring.capture():
        a = ht.array(av, split=0)
        a.parray  # noqa: B018
        assert a.is_padded
        f = ht.flipud(a + 1.0)  # flip over the padded split axis
        s = (a + 1.0)[::2]  # stepped split-axis slice
        snap = registry.snapshot()
    labels = snap["counters"]["fusion.view_fallbacks"]["labels"]
    assert labels.get("asymmetric-pad", 0) >= 1, labels
    assert labels.get("stepped-split-slice", 0) >= 1, labels
    assert _bitwise_equal(f.numpy(), np.flipud(av + 1.0))
    assert _bitwise_equal(s.numpy(), (av + 1.0)[::2])


@pytest.mark.parametrize("split", [None, 0])
def test_view_feeds_reduction_sink(monkeypatch, split):
    # a view mid-chain composes with PR 4's sinks: chain -> transpose ->
    # slice -> sum is still one pending DAG, bit-for-bit vs eager
    def run():
        rng = np.random.default_rng(63)
        a = ht.array(rng.standard_normal((16, 8)).astype(np.float32), split=split)
        a.parray  # noqa: B018
        y = (a + 1.0) / 1.7
        return y.T[1:5].sum(axis=0)

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


# ------------------------------------------------------------------ GEMM producers (ISSUE 5)


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_gemm_producer_differential(monkeypatch, split, shape, dtype):
    # x @ w (+ epilogue) bit-for-bit vs HEAT_TPU_FUSION=0 across the matrix;
    # bf16 rows and padded operands exercise the documented fallbacks and are
    # trivially bit-exact
    a, b = _operands(shape, split, dtype)
    w = ht.array(
        np.random.default_rng(65).standard_normal((shape[1], 4)).astype(np.float32),
        split=None,
    ).astype(dtype)
    w.parray  # noqa: B018
    # 2-D ht.linalg.dot routes through this same matmul path and is covered
    # by the 1-D dot test below; a fourth case here would cost 24 more compiles
    cases = [
        ("plain", lambda: a @ w),
        ("pending_operand", lambda: ((a + b) / 1.7) @ w),
        ("epilogue", lambda: ht.tanh(a @ w + 0.5)),
    ]
    for name, op in cases:
        eager, fused = _both(monkeypatch, op)
        assert _bitwise_equal(eager, fused), f"{name} split={split} {shape} {dtype}"


@pytest.mark.parametrize("split", [None, 0])
def test_dot_1d_producer_differential(monkeypatch, split):
    rng = np.random.default_rng(67)
    av = rng.standard_normal(24).astype(np.float32)
    bv = rng.standard_normal(24).astype(np.float32)

    def run():
        a = ht.array(av, split=split)
        b = ht.array(bv, split=split)
        a.parray, b.parray  # noqa: B018
        return ht.linalg.dot(a + 1.0, b) * 2.0

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_gemm_epilogue_single_compile(monkeypatch, no_faults):
    # acceptance: the canonical act(x @ w + b) training pattern compiles as
    # exactly ONE XLA program — the bias add and activation land in the
    # GEMM's epilogue
    monkeypatch.setenv("HEAT_TPU_FUSION_GEMM", "1")
    rng = np.random.default_rng(69)
    x = ht.array(rng.standard_normal((47, 31)).astype(np.float32))
    w = ht.array(rng.standard_normal((31, 23)).astype(np.float32))
    b = ht.array(rng.standard_normal((23,)).astype(np.float32))
    x.parray, w.parray, b.parray  # noqa: B018
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        y = ht.tanh(x @ w + b)
        assert fusion.is_deferred(y)
        base = registry.REGISTRY.counter("jit.compiles").get()
        yn = y.numpy()
        compiles = registry.REGISTRY.counter("jit.compiles").get() - base
        snap = registry.snapshot()
    assert compiles == 1, f"expected exactly one XLA compile, got {compiles}"
    assert snap["counters"]["fusion.ops_deferred"]["labels"].get("gemm", 0) >= 1
    ref = np.tanh(x.numpy() @ w.numpy() + b.numpy())
    np.testing.assert_allclose(yn, ref, rtol=1e-5, atol=1e-6)


def test_gemm_loss_epilogue_rides_sink(monkeypatch, no_faults):
    # act(x@w+b) -> mean: the GEMM producer, elementwise epilogue, and the
    # mean sink are one pending DAG flushed as one kernel
    monkeypatch.setenv("HEAT_TPU_FUSION_GEMM", "1")
    rng = np.random.default_rng(71)
    x = ht.array(rng.standard_normal((49, 13)).astype(np.float32))
    w = ht.array(rng.standard_normal((13, 11)).astype(np.float32))
    x.parray, w.parray  # noqa: B018
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        loss = ht.tanh(x @ w + 0.25).mean()
        assert fusion.is_deferred(loss)
        base = registry.REGISTRY.counter("jit.compiles").get()
        ln = loss.numpy()
        compiles = registry.REGISTRY.counter("jit.compiles").get() - base
    assert compiles == 1, compiles
    ref = np.tanh(x.numpy() @ w.numpy() + np.float32(0.25)).mean(dtype=np.float32)
    np.testing.assert_allclose(ln, ref, rtol=1e-5)


def test_gemm_operands_stay_pending_and_replay(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_GEMM", "1")
    rng = np.random.default_rng(73)
    a = ht.array(rng.standard_normal((8, 5)).astype(np.float32), split=0)
    w = ht.array(rng.standard_normal((5, 3)).astype(np.float32))
    a.parray, w.parray  # noqa: B018
    y = (a + 1.0) * 0.5  # pending chain
    m = y @ w
    _ = m.numpy()
    # the consumed chain is still pending and replays bit-exactly
    assert fusion.is_deferred(y)
    assert _bitwise_equal(y.numpy(), (a.numpy() + 1.0) * np.float32(0.5))


def test_gemm_escape_hatch_and_linalg_reason(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_GEMM", "0")
    rng = np.random.default_rng(75)
    a = ht.array(rng.standard_normal((8, 5)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        y = (a + 1.0) * 2.0
        m = y @ ht.ones((5, 3))
        assert not fusion.is_deferred(y)
        assert not fusion.is_deferred(m)
        snap = registry.snapshot()
    labels = snap["counters"]["fusion.flush_reason"]["labels"]
    assert labels.get("linalg", 0) >= 1, labels


def test_linalg_entry_points_attribute_linalg_reason():
    # satellite regression: qr/svd/solve/det route their operand flushes
    # through the linalg flush reason instead of "other"
    rng = np.random.default_rng(77)
    av = rng.standard_normal((8, 8)).astype(np.float32)
    av += 8.0 * np.eye(8, dtype=np.float32)  # well-conditioned for solve/det
    bv = rng.standard_normal(8).astype(np.float32)
    cases = [
        lambda y: ht.linalg.qr(y, calc_q=False),
        lambda y: ht.linalg.svd(y, compute_uv=False),
        lambda y: ht.linalg.det(y),
        lambda y: ht.linalg.solve(y, ht.array(bv)),
    ]
    for i, op in enumerate(cases):
        with monitoring.capture():
            a = ht.array(av, split=None)
            a.parray  # noqa: B018
            y = a + 0.0
            assert fusion.is_deferred(y)
            op(y)
            assert not fusion.is_deferred(y), i
            snap = registry.snapshot()
        labels = snap["counters"]["fusion.flush_reason"]["labels"]
        assert labels.get("linalg", 0) >= 1, (i, labels)
        registry.reset()


def test_view_gemm_monitoring_export(monkeypatch):
    # satellite: the deferred-node kinds and view fallbacks ride
    # report.telemetry() like the PR-4 sink counters
    monkeypatch.setenv("HEAT_TPU_FUSION_VIEWS", "1")
    monkeypatch.setenv("HEAT_TPU_FUSION_GEMM", "1")
    rng = np.random.default_rng(79)
    # mesh-divisible extents keep every view result unpadded, so the GEMM
    # producer records instead of taking the padded fallback
    a = ht.array(rng.standard_normal((8, 16)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        y = ((a + 1.0).T[0:8]).T @ ht.array(np.ones((8, 3), np.float32))
        _ = y.numpy()
        tele = report.telemetry()
    assert tele.get("fusion_ops_deferred", {}).get("view", 0) >= 2, tele
    assert tele.get("fusion_ops_deferred", {}).get("gemm", 0) >= 1, tele


# ------------------------------------------------------------------ collective nodes (ISSUE 7)
#
# Collectives over a pending chain record COLLECTIVE nodes: resplit_ /
# redistribute_ / get_halo / communication.shift / DNDarray Alltoall no
# longer flush the chain — the split-axis chain, the cross-device transfer,
# and the follow-on chain compile as ONE shard_map program. The differential
# suite pins bit-for-bit parity vs HEAT_TPU_FUSION_COLLECTIVES=0 across
# split/ragged/dtype for every node kind (collectives are pure data
# movement; the in-trace pad rules replay the eager fill/slice exactly), and
# the single-compile asserts pin the one-executable contract for
# chain->resplit->chain->reduce, the kmeans step, the lasso sweep, and the
# TSQR merge.


def _coll_both(monkeypatch, fn):
    """Run ``fn`` once with collectives-as-barriers and once recorded."""
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "0")
    eager = fn()
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    fused = fn()
    return eager, fused


def _coll_operand(shape, split, dtype, seed=51):
    rng = np.random.default_rng(seed)
    a = ht.array(rng.standard_normal(shape).astype(np.float32), split=split).astype(dtype)
    a.parray  # noqa: B018
    return a


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_resplit_mid_chain_differential(monkeypatch, split, shape, dtype):
    # chain -> resplit -> chain, bit-for-bit vs the flush-barrier path, for
    # every split transition the mid-chain resplit can take from `split`
    targets = {None: [0, 1], 0: [1, None], 1: [0, None]}[split]
    for to in targets:
        def run(_to=to):
            a = _coll_operand(shape, split, dtype)
            y = (a + 1.25) * 0.5
            y.resplit_(_to)
            y = y - 0.75
            assert y.split == _to
            return y.numpy()

        eager, fused = _coll_both(monkeypatch, run)
        assert _bitwise_equal(eager, fused), (split, to)


@pytest.mark.parametrize("split", [0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_shift_mid_chain_differential(monkeypatch, split, shape, dtype):
    if not get_comm().is_distributed():
        pytest.skip("ring shift requires a multi-device mesh")
    for steps in (1, -1):
        def run(_s=steps):
            a = _coll_operand(shape, split, dtype, seed=53)
            y = (a + 1.0) * 2.0
            y = ht.shift(y, _s)
            return (y + 0.5).numpy()

        eager, fused = _coll_both(monkeypatch, run)
        assert _bitwise_equal(eager, fused), (split, steps)


@pytest.mark.parametrize("split,shape", [(0, (16, 4)), (0, (13, 4)), (1, (4, 16)), (1, (4, 13))],
                         ids=["s0-even", "s0-ragged", "s1-even", "s1-ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_halo_mid_chain_differential(monkeypatch, split, shape, dtype):
    if not get_comm().is_distributed():
        pytest.skip("halos require a multi-device mesh")

    def run():
        a = _coll_operand(shape, split, dtype, seed=57)
        y = (a * 2.0) + 1.0
        y.get_halo(1)
        return (
            np.asarray(y.halo_prev),
            np.asarray(y.halo_next),
            np.asarray(y.array_with_halos),
            y.numpy(),
        )

    eager, fused = _coll_both(monkeypatch, run)
    for e, f, name in zip(eager, fused, ("prev", "next", "stacked", "chain")):
        assert _bitwise_equal(e, f), (name, split, shape)


def test_alltoall_defers_and_matches(monkeypatch):
    if not get_comm().is_distributed():
        pytest.skip("alltoall requires a multi-device mesh")
    comm = get_comm()
    p = comm.size

    def run():
        a = _coll_operand((2 * p, 3 * p), 0, ht.float32, seed=59)
        y = a * 1.5
        z = comm.Alltoall(y, split_axis=1, concat_axis=0)
        assert z.split == 1
        return (z + 0.25).numpy()

    eager, fused = _coll_both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)
    # deferral actually happened with the gate on
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    a = _coll_operand((2 * p, 3 * p), 0, ht.float32, seed=59)
    z = comm.Alltoall(a * 1.5, split_axis=1, concat_axis=0)
    assert fusion.is_deferred(z)
    # the raw-array shim keeps its jax.Array contract
    raw = comm.Alltoall(jnp.ones((2 * p, 3 * p), jnp.float32), split_axis=1, concat_axis=0)
    assert not isinstance(raw, ht.DNDarray)


def test_chain_resplit_chain_reduce_single_compile(monkeypatch, no_faults):
    # acceptance (ISSUE 7): chain -> resplit -> chain -> reduce == ONE XLA
    # program — the recorded collective does not break the fused flush
    if not get_comm().is_distributed():
        pytest.skip("resharding requires a multi-device mesh")
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    rng = np.random.default_rng(61)
    a = ht.array(rng.standard_normal((24, 16)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        y = ht.sqrt(ht.abs(a) + 1.0)
        y.resplit_(1)
        z = (y * 0.25).sum()
        assert fusion.is_deferred(z)
        base = registry.REGISTRY.counter("jit.compiles").get()
        zn = z.numpy()
        compiles = registry.REGISTRY.counter("jit.compiles").get() - base
        snap = registry.snapshot()
    assert compiles == 1, f"expected exactly one XLA compile, got {compiles}"
    labels = snap["counters"]["fusion.flush_reason"]["labels"]
    assert labels.get("collective", 0) == 0, labels
    assert snap["counters"]["fusion.ops_deferred"]["labels"].get("collective", 0) >= 1
    ref = (np.sqrt(np.abs(a.numpy()) + 1.0) * 0.25).sum()
    np.testing.assert_allclose(float(zn), ref, rtol=1e-5)


def test_kmeans_step_single_program(monkeypatch, no_faults):
    # acceptance (ISSUE 7): the DNDarray-surface kmeans iteration — distance
    # chain + GEMMs + argmin sink + one-hot update + recorded centers resplit
    # — compiles as ONE XLA program with flush_reason{collective} == 0
    from heat_tpu.cluster.kmeans import KMeans, _kmeans_step

    if not get_comm().is_distributed():
        pytest.skip("the step's recorded resplit needs a multi-device mesh")
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    rng = np.random.default_rng(63)
    n, f, k = 64, 8, 8
    data = rng.standard_normal((n, f)).astype(np.float32)
    cent = rng.standard_normal((k, f)).astype(np.float32)
    x = ht.array(data, split=0)
    x.parray  # noqa: B018
    c_split = ht.array(cent, split=0)
    c_split.parray  # noqa: B018
    km = KMeans(n_clusters=k)
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        nc, lab, sh = km.step(x, centers=c_split)
        assert fusion.is_deferred(sh)
        base = registry.REGISTRY.counter("jit.compiles").get()
        shv = sh.numpy()
        compiles = registry.REGISTRY.counter("jit.compiles").get() - base
        base = registry.REGISTRY.counter("jit.compiles").get()
        ncv, labv = nc.numpy(), lab.numpy()
        extra = registry.REGISTRY.counter("jit.compiles").get() - base
        snap = registry.snapshot()
    assert compiles == 1, f"expected one XLA compile for the step, got {compiles}"
    assert extra == 0, "centers/labels must ride the same kernel"
    labels = snap["counters"]["fusion.flush_reason"]["labels"]
    assert labels.get("collective", 0) == 0, labels
    nc_ref, lab_ref, sh_ref, _ = _kmeans_step(jnp.asarray(data), jnp.asarray(cent))
    assert np.array_equal(labv, np.asarray(lab_ref))
    np.testing.assert_allclose(ncv, np.asarray(nc_ref), rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(float(shv), float(sh_ref), rtol=2e-4)


def test_lasso_sweep_single_program(monkeypatch, no_faults):
    # acceptance (ISSUE 7): one coordinate-descent sweep on the op surface
    # flushes as ONE cached XLA program with flush_reason{collective} == 0,
    # and the fused engine converges to the jitted engine's coefficients
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    rng = np.random.default_rng(67)
    n, f = 64, 4
    X = rng.standard_normal((n, f)).astype(np.float32)
    beta = np.array([1.5, 0.0, -2.0, 0.5], np.float32)
    yv = X @ beta + 0.01 * rng.standard_normal(n).astype(np.float32)
    x = ht.array(X, split=0)
    y = ht.array(yv, split=0)
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        las = ht.regression.Lasso(lam=0.05, max_iter=1, tol=-1.0, sweep_engine="fused")
        base = registry.REGISTRY.counter("jit.compiles").get()
        las.fit(x, y)
        snap = registry.snapshot()
    labels = snap["counters"]["fusion.flush_reason"]["labels"]
    assert labels.get("collective", 0) == 0, labels
    assert snap["counters"]["fusion.flushes"] == 1, snap["counters"]["fusion.flushes"]
    las_jit = ht.regression.Lasso(lam=0.05, max_iter=1, tol=-1.0)
    las_jit.fit(x, y)
    np.testing.assert_allclose(
        las.theta.numpy(), las_jit.theta.numpy(), rtol=1e-4, atol=1e-6
    )


def test_tsqr_traces_pending_chain(monkeypatch, no_faults):
    # ISSUE 7: a pending chain traces INTO the TSQR merge program
    # (flush_through) — one executable, Q/R bitwise vs the flush-first path
    comm = get_comm()
    if not comm.is_distributed():
        pytest.skip("TSQR requires a multi-device mesh")
    p = comm.size
    rng = np.random.default_rng(69)
    A = rng.standard_normal((8 * p, 4)).astype(np.float32)

    def run():
        a = ht.array(A, split=0)
        a.parray  # noqa: B018
        y = (a * 0.5) + 0.25
        res = ht.linalg.qr(y)
        return res.Q.numpy(), res.R.numpy()

    (qe, re_), (qf, rf) = _coll_both(monkeypatch, run)
    assert _bitwise_equal(qe, qf)
    assert _bitwise_equal(re_, rf)
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        a = ht.array(A, split=0)
        a.parray  # noqa: B018
        y = (a * 0.5) + 0.25
        base = registry.REGISTRY.counter("jit.compiles").get()
        res = ht.linalg.qr(y)
        compiles = registry.REGISTRY.counter("jit.compiles").get() - base
        base = registry.REGISTRY.counter("jit.compiles").get()
        y.parray  # noqa: B018 — the chain value rode the same kernel
        extra = registry.REGISTRY.counter("jit.compiles").get() - base
    assert compiles == 1, compiles
    assert extra == 0, extra


def test_redistribute_telemetry_attribution():
    # ISSUE 7 satellite: redistribute_ counts comm.redistribution, NOT a
    # same->same comm.resharding (which must stay "genuine split changes")
    a = ht.ones((16, 4), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        a.redistribute_()
        b = ht.ones((16, 4), split=0)
        b.resplit_(1)
        snap = registry.snapshot()
    counters = snap["counters"]
    if get_comm().is_distributed():
        assert counters["comm.redistribution"] == 1, counters.get("comm.redistribution")
        resh = counters.get("comm.resharding", {"labels": {}})["labels"]
        assert "0->0" not in resh, resh
        assert resh.get("0->1", 0) == 1, resh
    else:
        assert "comm.redistribution" not in counters


def test_redistribute_keeps_chain_pending(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    if not get_comm().is_distributed():
        pytest.skip("redistribute placement needs a multi-device mesh")
    a, y = _pending_chain(split=0)
    y.redistribute_()
    assert fusion.is_deferred(y)
    assert _bitwise_equal(y.numpy(), (a.numpy() + 1.0) * 2.0)
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "0")
    a, y = _pending_chain(split=0)
    y.redistribute_()
    assert not fusion.is_deferred(y)


def test_collective_fallback_counts_and_stays_correct(monkeypatch):
    # a collective whose in-trace form is rejected falls back to the flush
    # barrier, counted in fusion.collective_fallbacks — results unchanged
    if not get_comm().is_distributed():
        pytest.skip("resharding requires a multi-device mesh")
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    orig = fusion._eval_node

    def boom(fn, okey, *args, **kw):
        if isinstance(okey, tuple) and okey and okey[0] == "collective":
            raise RuntimeError("forced abstract-eval failure")
        return orig(fn, okey, *args, **kw)

    monkeypatch.setattr(fusion, "_eval_node", boom)
    with monitoring.capture():
        a, y = _pending_chain(split=0)
        y.resplit_(1)
        assert not fusion.is_deferred(y)  # fell back to the flush barrier
        snap = registry.snapshot()
    fb = snap["counters"]["fusion.collective_fallbacks"]["labels"]
    assert fb.get("abstract-eval", 0) >= 1, fb
    assert _bitwise_equal(y.numpy(), (a.numpy() + 1.0) * 2.0)


def test_collective_monitoring_export(monkeypatch):
    # satellite: ops_deferred{collective} and collective_fallbacks ride
    # report.telemetry() in the PR 4/5 labelled style
    if not get_comm().is_distributed():
        pytest.skip("resharding requires a multi-device mesh")
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    with monitoring.capture():
        _, y = _pending_chain(split=0)
        y.resplit_(1)
        _ = y.numpy()
        tele = report.telemetry()
    assert tele.get("fusion_ops_deferred", {}).get("collective", 0) >= 1, tele
