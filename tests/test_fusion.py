"""
Differential and behavioral suite for the deferred-execution fusion engine
(``heat_tpu/core/fusion.py``, ``HEAT_TPU_FUSION``).

Layout of the guarantees pinned here:

* **Golden op table, bit-for-bit.** Every whitelisted elementwise op, executed
  once through the fused path and once with ``HEAT_TPU_FUSION=0``, must agree
  to the byte across split ∈ {None, 0, 1}, even and ragged/padded shapes, and
  f32/bf16. Scalars ride the trace as weak-typed runtime arguments (never
  baked constants), so there is no constant-folding drift (x/3.0 stays a
  division); integer ``power`` exponents are baked so both paths lower via
  ``lax.integer_pow``.
* **Chains.** Contraction-free chains (no multiply feeding an add/sub) are
  bit-for-bit too, as are *all* bf16 chains (XLA mandates the bf16 rounding
  after every op even inside a fused loop). The one documented numeric
  difference of a fused f32 kernel is *excess precision*: XLA contracts
  ``a*b + c`` into a single FMA (one rounding instead of two, strictly more
  accurate) — pinned here as a ≤2-ulp bound rather than hidden behind a loose
  tolerance. ``doc/fusion_notes.md`` carries the analysis.
* **Every flush trigger** materializes (reductions, cumulatives, ``.numpy()``,
  ``item()``, printing, indexing reads/writes, ``out=`` aliasing, ``resplit_``,
  halos, monitoring export).
* **Escape hatch**: under ``HEAT_TPU_FUSION=0`` nothing ever defers.
* **Monitoring**: the ``fusion.*`` counters and the chain-length histogram.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu import monitoring
from heat_tpu.core import fusion
from heat_tpu.core.communication import get_comm
from heat_tpu.monitoring import registry, report

pytestmark = pytest.mark.fusion


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    registry.reset()
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    yield
    registry.reset()


def _bitwise_equal(a: np.ndarray, b: np.ndarray) -> bool:
    return a.shape == b.shape and a.dtype == b.dtype and a.tobytes() == b.tobytes()


def _both(monkeypatch, fn):
    """Run ``fn`` once eagerly (HEAT_TPU_FUSION=0) and once fused; return both
    results as numpy arrays."""
    monkeypatch.setenv("HEAT_TPU_FUSION", "0")
    eager = fn().numpy()
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    fused = fn().numpy()
    return eager, fused


def _operands(shape, split, dtype):
    rng = np.random.default_rng(42)
    a = ht.array(rng.standard_normal(shape).astype(np.float32), split=split).astype(dtype)
    b = ht.array(
        (rng.standard_normal(shape) + 2.5).astype(np.float32), split=split
    ).astype(dtype)
    # concrete operands: the table below measures op-level parity, not chains
    a.parray, b.parray  # noqa: B018
    return a, b


# every entry runs ONE recordable op (plus the | separators for readability);
# composed entries like sqrt(abs(.)) keep the domain valid, not chains
_GOLDEN_BINARY = [
    ("add", lambda a, b: a + b),
    ("sub", lambda a, b: a - b),
    ("mul", lambda a, b: a * b),
    ("div", lambda a, b: a / b),
    ("div_scalar", lambda a, b: a / 3.0),
    ("floordiv", lambda a, b: a // b),
    ("mod", lambda a, b: a % b),
    ("pow_int", lambda a, b: a ** 3),
    ("pow_npint", lambda a, b: a ** np.int64(2)),
    ("maximum", lambda a, b: ht.maximum(a, b)),
    ("minimum", lambda a, b: ht.minimum(a, b)),
    ("arctan2", lambda a, b: ht.arctan2(a, b)),
    ("hypot", lambda a, b: ht.hypot(a, b)),
    ("copysign", lambda a, b: ht.copysign(a, b)),
    ("logaddexp", lambda a, b: ht.logaddexp(a, b)),
    ("lt", lambda a, b: a < b),
    ("le", lambda a, b: a <= b),
    ("gt", lambda a, b: a > b),
    ("eq", lambda a, b: a == b),
    ("ne", lambda a, b: a != b),
]

_GOLDEN_UNARY = [
    ("abs", lambda a: ht.abs(a)),
    ("neg", lambda a: -a),
    ("sqrt_abs", lambda a: ht.sqrt(ht.abs(a))),
    ("exp", lambda a: ht.exp(a)),
    ("expm1", lambda a: ht.expm1(a)),
    ("log_abs", lambda a: ht.log(ht.abs(a) + 1.0)),
    ("sin", lambda a: ht.sin(a)),
    ("cos", lambda a: ht.cos(a)),
    ("tan", lambda a: ht.tan(a)),
    ("tanh", lambda a: ht.tanh(a)),
    ("floor", lambda a: ht.floor(a)),
    ("ceil", lambda a: ht.ceil(a)),
    ("trunc", lambda a: ht.trunc(a)),
    ("round", lambda a: ht.round(a)),
    ("sign", lambda a: ht.sign(a)),
    ("square", lambda a: ht.square(a)),
    ("isnan", lambda a: ht.isnan(a / a)),
    ("isfinite", lambda a: ht.isfinite(a)),
]


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize(
    "shape", [(16, 8), (13, 7)], ids=["even", "ragged"]
)
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_golden_binary_bitwise(monkeypatch, split, shape, dtype):
    a, b = _operands(shape, split, dtype)
    for name, op in _GOLDEN_BINARY:
        eager, fused = _both(monkeypatch, lambda: op(a, b))
        assert _bitwise_equal(eager, fused), f"{name} split={split} {shape} {dtype}"


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_golden_unary_bitwise(monkeypatch, split, shape, dtype):
    a, _ = _operands(shape, split, dtype)
    for name, op in _GOLDEN_UNARY:
        eager, fused = _both(monkeypatch, lambda: op(a))
        assert _bitwise_equal(eager, fused), f"{name} split={split} {shape} {dtype}"


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
def test_int_bool_ops_bitwise(monkeypatch, split, shape):
    rng = np.random.default_rng(3)
    ia = ht.array(rng.integers(1, 100, size=shape).astype(np.int32), split=split)
    ib = ht.array(rng.integers(1, 17, size=shape).astype(np.int32), split=split)
    ba = ia % 2 == 0
    bb = ib % 3 == 0
    ba.parray, bb.parray  # noqa: B018
    cases = [
        lambda: ia + ib, lambda: ia * ib, lambda: ia // ib, lambda: ia % ib,
        lambda: ia & ib, lambda: ia | ib, lambda: ia ^ ib,
        lambda: ia << 2, lambda: ia >> 1,
        lambda: ba & bb, lambda: ba | bb, lambda: ~ba,
        lambda: ia / ib,  # exact -> float promotion rides the cast-back rule
    ]
    for i, op in enumerate(cases):
        eager, fused = _both(monkeypatch, op)
        assert _bitwise_equal(eager, fused), f"case {i} split={split} {shape}"


@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_contraction_free_chain_bitwise(monkeypatch, split, shape, dtype):
    # an 8-op chain with no multiply feeding an add/sub: no FMA contraction is
    # possible, so fused and op-at-a-time execution must agree to the byte
    a, b = _operands(shape, split, dtype)

    def chain():
        x = a / b
        x = ht.abs(x)
        x = ht.sqrt(x + 1.0)
        x = x / 3.0
        x = ht.maximum(x, b)
        x = -x
        x = ht.tanh(x)
        return x / 7.0

    eager, fused = _both(monkeypatch, chain)
    assert _bitwise_equal(eager, fused)


@pytest.mark.parametrize("split", [None, 0])
def test_bf16_fma_chain_bitwise(monkeypatch, split):
    # bf16 rounding is mandated after every op even inside a fused loop, so
    # even multiply->add chains stay bit-for-bit in bf16
    a, b = _operands((33, 9), split, ht.bfloat16)
    eager, fused = _both(monkeypatch, lambda: (a * b + b) * a - b)
    assert _bitwise_equal(eager, fused)


@pytest.mark.parametrize("split", [None, 0])
def test_f32_fma_chain_excess_precision_bound(monkeypatch, split):
    # the ONE permitted fused-vs-eager difference: XLA contracts f32
    # multiply->add into an FMA inside a fused kernel — a*b is NOT rounded to
    # f32 before the add (single rounding, strictly more accurate). The
    # fused-vs-eager gap is therefore bounded by one rounding of the product:
    # |fused - eager| <= eps_f32 * (|a*b| + |c|). Pinned exactly, not hidden
    # behind a loose tolerance.
    a, b = _operands((64, 16), split, ht.float32)
    eager, fused = _both(monkeypatch, lambda: a * b + 2.0)
    an, bn = a.numpy().astype(np.float64), b.numpy().astype(np.float64)
    f64 = an * bn + 2.0
    # fused (FMA) is at least as accurate as the double-rounded eager result
    assert np.abs(fused.astype(np.float64) - f64).max() <= np.abs(
        eager.astype(np.float64) - f64
    ).max()
    bound = 2.0**-23 * (np.abs(an * bn) + 2.0) + 2.0**-149
    assert (np.abs(fused.astype(np.float64) - eager.astype(np.float64)) <= bound).all()


# ------------------------------------------------------------------ flush triggers
def _pending_chain(split=0, shape=(13, 5)):
    rng = np.random.default_rng(7)
    a = ht.array(rng.standard_normal(shape).astype(np.float32), split=split)
    a.parray  # noqa: B018 — concrete input
    y = (a + 1.0) * 2.0
    assert fusion.is_deferred(y)
    return a, y


def test_flush_on_numpy():
    a, y = _pending_chain()
    ref = (a.numpy() + 1.0) * 2.0
    assert _bitwise_equal(y.numpy(), ref)
    assert not fusion.is_deferred(y)


def test_flush_on_reduction():
    a, y = _pending_chain()
    s = y.sum()
    assert not fusion.is_deferred(y)
    np.testing.assert_allclose(float(s), ((a.numpy() + 1.0) * 2.0).sum(), rtol=1e-5)


def test_flush_on_cumsum():
    a, y = _pending_chain()
    c = ht.cumsum(y, axis=0)
    assert not fusion.is_deferred(y)
    np.testing.assert_allclose(
        c.numpy(), np.cumsum((a.numpy() + 1.0) * 2.0, axis=0), rtol=1e-5
    )


def test_flush_on_item_and_bool():
    a = ht.array(np.float32(3.0))
    y = a * 2.0
    assert float(y) == 6.0
    z = a > 1.0
    assert bool(z)


def test_flush_on_print():
    _, y = _pending_chain()
    s = str(y)
    assert not fusion.is_deferred(y)
    assert "DNDarray" in s or "[" in s


def test_flush_on_getitem():
    a, y = _pending_chain()
    row = y[0]
    assert not fusion.is_deferred(y)
    np.testing.assert_allclose(row.numpy(), (a.numpy()[0] + 1.0) * 2.0, rtol=1e-6)


def test_flush_on_setitem():
    a, y = _pending_chain()
    y[0, 0] = 5.0
    assert not fusion.is_deferred(y)
    ref = (a.numpy() + 1.0) * 2.0
    ref[0, 0] = 5.0
    assert _bitwise_equal(y.numpy(), ref)


def test_flush_on_resplit():
    a, y = _pending_chain(split=0)
    y.resplit_(1)
    assert not fusion.is_deferred(y)
    assert y.split == 1
    assert _bitwise_equal(y.numpy(), (a.numpy() + 1.0) * 2.0)


def test_flush_on_halo():
    if not get_comm().is_distributed():
        pytest.skip("halos require a multi-device mesh")
    a, y = _pending_chain(split=0, shape=(16, 4))
    y.get_halo(1)
    assert not fusion.is_deferred(y)


def test_flush_on_monitoring_export():
    _, y = _pending_chain()
    with monitoring.capture():
        snap = report.snapshot()
    assert not fusion.is_deferred(y)
    assert isinstance(snap, dict)


def test_nonelementwise_op_flushes_operand():
    a, y = _pending_chain(split=0, shape=(12, 6))
    m = ht.matmul(y, ht.ones((6, 3), split=None))
    assert not fusion.is_deferred(y)
    np.testing.assert_allclose(
        m.numpy(), ((a.numpy() + 1.0) * 2.0) @ np.ones((6, 3), np.float32), rtol=1e-5
    )


# ------------------------------------------------------------------ out=/where aliasing
def test_out_flushes_operands_and_matches_eager(monkeypatch):
    def run():
        rng = np.random.default_rng(11)
        a = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        b = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        pending = a * 2.0  # operand carrying an unflushed expression
        out = ht.zeros((13, 5), split=0)
        ht.add(pending, b, out=out)
        return out

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_out_aliasing_self(monkeypatch):
    def run():
        rng = np.random.default_rng(12)
        a = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        b = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        x = a + 1.0
        ht.mul(x, b, out=x)  # out aliases an operand
        return x

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_write_into_pending_out_elides_graph():
    rng = np.random.default_rng(13)
    a = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
    b = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
    a.parray, b.parray  # noqa: B018
    with monitoring.capture():
        out = a * 3.0  # pending expression that is never needed
        assert fusion.is_deferred(out)
        ht.add(a, b, out=out)  # overwrites: dead graph must be DROPPED
        snap = registry.snapshot()
    assert not fusion.is_deferred(out)
    assert _bitwise_equal(out.numpy(), a.numpy() + b.numpy())
    counters = snap["counters"]
    assert counters.get("fusion.elided_writes", 0) >= 1


def test_where_kwarg_matches_eager(monkeypatch):
    def run():
        rng = np.random.default_rng(14)
        a = ht.array(rng.standard_normal((16, 8)).astype(np.float32), split=0)
        b = ht.array(rng.standard_normal((16, 8)).astype(np.float32), split=0)
        mask = a > 0
        return ht.add(a, b, where=mask)

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_where_select_matches_eager(monkeypatch):
    def run():
        rng = np.random.default_rng(15)
        a = ht.array(rng.standard_normal((16, 8)).astype(np.float32), split=0)
        b = ht.array(rng.standard_normal((16, 8)).astype(np.float32), split=0)
        return ht.where(a > b, a * 2.0, b - 1.0)

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_astype_glue_fuses_and_matches(monkeypatch):
    def run():
        rng = np.random.default_rng(16)
        a = ht.array(rng.standard_normal((13, 7)).astype(np.float32), split=0)
        return ((a + 1.0).astype(ht.bfloat16) * 2.0).astype(ht.float32) / 3.0

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


# ------------------------------------------------------------------ engine behavior
def test_escape_hatch_never_defers(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION", "0")
    a = ht.ones((8, 4), split=0)
    y = (a + 1.0) * 2.0
    assert not fusion.is_deferred(y)
    assert not fusion.enabled()


def test_deferred_metadata_without_materialization():
    a, y = _pending_chain(split=0, shape=(13, 5))
    # shape/dtype/split/pshape are statically known — reading them must not flush
    assert y.shape == (13, 5)
    assert y.split == 0
    assert y.dtype == ht.float32
    if get_comm().is_distributed():
        p = get_comm().size
        assert y.pshape[0] == -(-13 // p) * p
        assert y.is_padded
    assert fusion.is_deferred(y)


def test_chain_length_bound(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FUSION_MAX_CHAIN", "4")
    x = ht.ones((8,), split=0)
    x.parray  # noqa: B018
    for _ in range(11):
        x = x + 1.0
    # bounded recording flushed intermediate kernels; the value is exact
    assert _bitwise_equal(x.numpy(), np.full((8,), 12.0, np.float32))


def test_trace_cache_hits_and_lru(monkeypatch):
    fusion.clear_cache()
    base = fusion.cache_info()
    a = ht.ones((8, 4), split=0)
    a.parray  # noqa: B018
    for _ in range(3):
        _ = ((a + 1.0) * 2.0).numpy()  # identical structure: one compile
    info = fusion.cache_info()
    assert info["hits"] >= base["hits"] + 2
    monkeypatch.setenv("HEAT_TPU_FUSION_CACHE_SIZE", "2")
    _ = (a - 1.0).numpy()
    _ = (a * 3.0).numpy()
    _ = (a / 2.0).numpy()
    assert fusion.cache_info()["entries"] <= 2


def test_monitoring_counters(monkeypatch):
    rng = np.random.default_rng(17)
    a = ht.array(rng.standard_normal((16, 4)).astype(np.float32), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        y = ht.sqrt(ht.abs(a * 2.0) + 1.0)
        _ = y.numpy()
        _ = ht.sqrt(ht.abs(a * 2.0) + 1.0).numpy()  # same structure: cache hit
        snap = registry.snapshot()
    c = snap["counters"]
    deferred = c["fusion.ops_deferred"]
    assert deferred["total"] >= 6
    assert set(deferred["labels"]) >= {"binary", "local"}
    assert c["fusion.flushes"] >= 2
    assert c.get("fusion.cache_hits", 0) >= 1
    assert c["fusion.kernels_compiled"] >= 1
    hist = snap["histograms"]["fusion.chain_length"]
    assert hist["count"] >= 2
    assert hist["sum"] >= 6


def test_pending_registry_and_flush_pending():
    _, y = _pending_chain()
    assert fusion.pending_count() >= 1
    n = fusion.flush_pending()
    assert n >= 1
    assert fusion.pending_count() == 0
    assert not fusion.is_deferred(y)


def test_deferred_operand_feeds_downstream_graph(monkeypatch):
    # a pending result used by several later chains: shared subgraph replays
    # correctly whichever root flushes first
    def run():
        rng = np.random.default_rng(18)
        a = ht.array(rng.standard_normal((13, 5)).astype(np.float32), split=0)
        shared = a * 2.0 + 1.0
        u = ht.sqrt(ht.abs(shared))
        v = shared - 3.0
        return ht.stack([u.resplit_(None), v.resplit_(None)], axis=0)

    eager, fused = _both(monkeypatch, run)
    assert _bitwise_equal(eager, fused)


def test_fusion_inside_jit_falls_back():
    # recording must refuse tracers: ops on DNDarrays built inside jit keep
    # eager template semantics (the tracer guard)
    import jax

    from heat_tpu.core.dndarray import DNDarray

    a = ht.ones((6,), split=None)

    def f(arr):
        d = DNDarray(arr, (6,), ht.float32, None, a.device, a.comm, True)
        out = d + 1.0
        assert not fusion.is_deferred(out)
        return out.parray

    y = jax.jit(f)(a.parray)
    np.testing.assert_allclose(np.asarray(y), np.full((6,), 2.0, np.float32))
