"""
Ragged (non-divisible) split-axis matrix: prime-ish axis lengths × mesh sizes.

Round-2 contract (VERDICT item 1): a split axis of ANY length is *genuinely
distributed* — physically sharded over the mesh via the padded physical layout —
never silently replicated. The reference chunks any length with the remainder
spread over low ranks (heat/core/communication.py:161-210); here the physical
shards are all ceil(n/p) with the pad at the global end, and every op masks or
slices the pad. These tests assert BOTH golden numerics vs numpy AND the physical
placement (`parray.addressable_shards`).
"""

import numpy as np
import pytest

import jax

import heat_tpu as ht
from heat_tpu.core.communication import MeshCommunication

SIZES = [7, 29, 1003, 2**17 + 1]
MESHES = [2, 3, 5, 8]


def _comm(p):
    devs = jax.devices()
    if len(devs) < p:
        pytest.skip(f"needs {p} devices, have {len(devs)}")
    return MeshCommunication(devices=devs[:p])


def _assert_sharded(x, p):
    """The array must be physically partitioned: p equal shards of ~n/p rows."""
    shards = x.parray.addressable_shards
    assert len(shards) == p, f"expected {p} shards, got {len(shards)}"
    sizes = {sh.data.shape for sh in shards}
    assert len(sizes) == 1, f"unequal physical shards: {sizes}"
    split = x.split
    n = x.shape[split]
    per = next(iter(sizes))[split]
    assert per == -(-n // p), f"shard extent {per} != ceil({n}/{p})"


@pytest.mark.parametrize("n", SIZES)
@pytest.mark.parametrize("p", MESHES)
def test_creation_physically_sharded(n, p):
    comm = _comm(p)
    x = ht.arange(n, dtype=ht.float32, split=0, comm=comm)
    assert x.shape == (n,)
    _assert_sharded(x, p)
    np.testing.assert_allclose(x.numpy(), np.arange(n, dtype=np.float32))

    o = ht.ones((n, 3), split=0, comm=comm)
    _assert_sharded(o, p)
    assert o.shape == (n, 3)

    f = ht.full((3, n), 2.5, split=1, comm=comm)
    _assert_sharded(f, p)
    np.testing.assert_allclose(f.numpy(), np.full((3, n), 2.5, np.float32))

    e = ht.eye((n, 5), split=0, comm=comm)
    _assert_sharded(e, p)
    np.testing.assert_allclose(e.numpy(), np.eye(n, 5, dtype=np.float32))

    ht.random.seed(11)
    r = ht.random.rand(n, split=0, comm=comm)
    _assert_sharded(r, p)
    ht.random.seed(11)
    r_ref = ht.random.rand(n)  # default comm / different device count
    np.testing.assert_allclose(r.numpy(), r_ref.numpy())  # count-invariant draws


@pytest.mark.parametrize("n", SIZES)
@pytest.mark.parametrize("p", [3, 8])
def test_reductions_golden(n, p):
    comm = _comm(p)
    a = np.linspace(-3, 5, n, dtype=np.float32)
    x = ht.array(a, split=0, comm=comm)
    _assert_sharded(x, p)
    np.testing.assert_allclose(ht.sum(x).item(), a.sum(), rtol=1e-4)
    np.testing.assert_allclose(ht.mean(x).item(), a.mean(), rtol=1e-5)
    assert ht.max(x).item() == a.max()
    assert ht.min(x).item() == a.min()
    assert ht.argmax(x).item() == a.argmax()
    assert ht.argmin(x).item() == a.argmin()
    # prod over a shifted/normalised array to stay finite
    b = 1.0 + np.linspace(0, 1, n, dtype=np.float32) / n
    y = ht.array(b, split=0, comm=comm)
    np.testing.assert_allclose(ht.prod(y).item(), b.prod(), rtol=1e-3)
    # logical reductions
    m = ht.array(a > 0, split=0, comm=comm)
    assert bool(ht.any(m).item()) == bool((a > 0).any())
    assert bool(ht.all(m).item()) == bool((a > 0).all())


@pytest.mark.parametrize("n", [7, 1003])
@pytest.mark.parametrize("p", MESHES)
def test_elementwise_and_binary(n, p):
    comm = _comm(p)
    a = np.arange(n, dtype=np.float32)
    b = np.flip(a).copy()
    x = ht.array(a, split=0, comm=comm)
    y = ht.array(b, split=0, comm=comm)
    z = x * 2.0 + y
    _assert_sharded(z, p)
    np.testing.assert_allclose(z.numpy(), a * 2 + b)
    # mixed split/replicated
    w = x + ht.array(b, comm=comm)
    np.testing.assert_allclose(w.numpy(), a + b)
    # raw numpy operand
    v = x + b
    np.testing.assert_allclose(v.numpy(), a + b)
    # unary through __local_op
    np.testing.assert_allclose(ht.exp(x / n).numpy(), np.exp(a / n), rtol=1e-5)
    # comparison
    np.testing.assert_array_equal((x > y).numpy(), a > b)


@pytest.mark.parametrize("n", [7, 1003])
@pytest.mark.parametrize("p", [3, 8])
def test_indexing_keeps_distribution(n, p):
    comm = _comm(p)
    a = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
    x = ht.array(a, split=0, comm=comm)
    _assert_sharded(x, p)

    s = x[2:-2]
    assert s.split == 0
    np.testing.assert_allclose(s.numpy(), a[2:-2])
    if s.shape[0] >= p:
        _assert_sharded(s, p)

    st = x[::2]
    assert st.split == 0
    np.testing.assert_allclose(st.numpy(), a[::2])

    rv = x[::-1]
    assert rv.split == 0
    np.testing.assert_allclose(rv.numpy(), a[::-1])

    np.testing.assert_allclose(x[-1].numpy(), a[-1])
    np.testing.assert_allclose(x[0, 1].numpy(), a[0, 1])
    np.testing.assert_allclose(x[:, 1].numpy(), a[:, 1])
    assert x[:, 1].split == 0  # split axis passes through

    idx = np.array([0, n // 2, n - 1, -1])
    g = x[idx]
    np.testing.assert_allclose(g.numpy(), a[idx])
    assert g.split == 0  # single 1-D advanced key on the split axis

    mask = (np.arange(n) % 3) == 0
    bm = x[mask]
    np.testing.assert_allclose(bm.numpy(), a[mask])


@pytest.mark.parametrize("n", [7, 1003])
@pytest.mark.parametrize("p", [3, 8])
def test_setitem_golden(n, p):
    comm = _comm(p)
    a = np.zeros((n, 2), dtype=np.float32)
    x = ht.array(a, split=0, comm=comm)

    x[1] = 5.0
    a[1] = 5.0
    x[3:9] = 7.0
    a[3:9] = 7.0
    x[-1] = 9.0
    a[-1] = 9.0
    x[:, 1] = 2.0
    a[:, 1] = 2.0
    np.testing.assert_allclose(x.numpy(), a)
    _assert_sharded(x, p)

    mask = a > 4
    x[ht.array(mask, comm=comm)] = 0.0
    a[mask] = 0.0
    np.testing.assert_allclose(x.numpy(), a)

    vals = np.arange(2 * n, dtype=np.float32).reshape(n, 2)
    x[:] = ht.array(vals, split=0, comm=comm)
    np.testing.assert_allclose(x.numpy(), vals)


@pytest.mark.parametrize("n", [29, 1003])
@pytest.mark.parametrize("p", [3, 8])
def test_cum_and_axis_ops(n, p):
    comm = _comm(p)
    a = np.arange(n * 2, dtype=np.float32).reshape(n, 2) / n
    x = ht.array(a, split=0, comm=comm)
    np.testing.assert_allclose(ht.cumsum(x, axis=0).numpy(), a.cumsum(axis=0), rtol=1e-4)
    np.testing.assert_allclose(ht.cumsum(x, axis=1).numpy(), a.cumsum(axis=1), rtol=1e-5)
    # reduce over the non-split axis keeps the (padded) split axis sharded
    s1 = ht.sum(x, axis=1)
    assert s1.split == 0
    _assert_sharded(s1, p)
    np.testing.assert_allclose(s1.numpy(), a.sum(axis=1), rtol=1e-5)


@pytest.mark.parametrize("n", [29, 1003])
@pytest.mark.parametrize("p", [3, 8])
def test_resplit_and_transpose(n, p):
    comm = _comm(p)
    a = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    x = ht.array(a, split=0, comm=comm)
    x.resplit_(1)
    assert x.split == 1
    assert x.pshape[1] % p == 0  # physical layout is evenly sharded
    np.testing.assert_allclose(x.numpy(), a)
    x.resplit_(0)
    _assert_sharded(x, p)
    np.testing.assert_allclose(x.numpy(), a)
    t = ht.transpose(x, None)
    np.testing.assert_allclose(t.numpy(), a.T)
    x.resplit_(None)
    assert x.split is None
    np.testing.assert_allclose(x.numpy(), a)


@pytest.mark.parametrize("n", [29, 1003])
@pytest.mark.parametrize("p", [5, 8])
def test_matmul_ragged(n, p):
    comm = _comm(p)
    rng = np.random.default_rng(3)
    a = rng.standard_normal((n, 8), dtype=np.float32)
    b = rng.standard_normal((8, 4), dtype=np.float32)
    x = ht.array(a, split=0, comm=comm)
    y = ht.array(b, comm=comm)
    m = ht.matmul(x, y)
    assert m.shape == (n, 4) and m.split == 0
    np.testing.assert_allclose(m.numpy(), a @ b, rtol=1e-4, atol=1e-4)
    # contraction across the ragged split axis (split=1 @ split=0)
    xt = ht.array(a.T.copy(), split=1, comm=comm)
    g = ht.matmul(xt, x)  # (8, n) x (n, 8) over the ragged axis
    np.testing.assert_allclose(g.numpy(), a.T @ a, rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("n", [29, 1003])
@pytest.mark.parametrize("p", [3, 8])
def test_manipulations_ragged(n, p):
    comm = _comm(p)
    a = np.arange(n, dtype=np.float32)
    x = ht.array(a, split=0, comm=comm)
    c = ht.concatenate([x, x], axis=0)
    assert c.shape == (2 * n,)
    np.testing.assert_allclose(c.numpy(), np.concatenate([a, a]))
    v, idx = ht.sort(x[::-1])
    np.testing.assert_allclose(v.numpy(), np.sort(a))
    u = ht.unique(ht.array(np.floor(a / 2), split=0, comm=comm))
    np.testing.assert_allclose(np.asarray(u.numpy()), np.unique(np.floor(a / 2)))
    np.testing.assert_allclose(
        ht.percentile(x, [25.0, 50.0, 75.0]).numpy(),
        np.percentile(a, [25.0, 50.0, 75.0]),
        rtol=1e-4,
    )
    r = ht.reshape(ht.array(np.arange(n * 2, dtype=np.float32), split=0, comm=comm), (n, 2))
    np.testing.assert_allclose(r.numpy(), np.arange(n * 2, dtype=np.float32).reshape(n, 2))
    np.testing.assert_allclose(ht.roll(x, 3).numpy(), np.roll(a, 3))
    np.testing.assert_allclose(ht.flip(x, 0).numpy(), np.flip(a))
    p_ = ht.pad(x, (2, 3))
    np.testing.assert_allclose(p_.numpy(), np.pad(a, (2, 3)))


@pytest.mark.parametrize("p", [3, 8])
def test_tiny_axis_smaller_than_mesh(p):
    """n < p: some shards are pure pad; everything still works."""
    comm = _comm(p)
    n = 2
    a = np.array([3.0, 4.0], dtype=np.float32)
    x = ht.array(a, split=0, comm=comm)
    assert x.shape == (n,)
    np.testing.assert_allclose(x.numpy(), a)
    assert ht.sum(x).item() == 7.0
    assert ht.max(x).item() == 4.0
    y = x * 2
    np.testing.assert_allclose(y.numpy(), a * 2)


@pytest.mark.parametrize("p", [2, 8])
def test_ragged_vector_collectives(p):
    comm = _comm(p)
    a = np.arange(13, dtype=np.float32)
    g = comm.Allgatherv(a, split=0)
    np.testing.assert_allclose(np.asarray(g), a)
    s = comm.Scatterv(a, split=0)
    assert len(s.addressable_shards) == p
    np.testing.assert_allclose(np.asarray(jax.device_put(s, comm.sharding(1, None)))[:13], a)
    m = np.arange(21, dtype=np.float32).reshape(7, 3)
    r = comm.Alltoallv(m, split_axis=1, concat_axis=0)
    np.testing.assert_allclose(np.asarray(r)[:7, :3], m)


@pytest.mark.parametrize("p", [3, 8])
def test_distributed_sort_edge_values(p):
    """NaN/inf float data and sentinel-valued int data sort and dedup exactly
    like numpy, even on ragged (padded) axes where the pad carries sentinels."""
    comm = _comm(p)
    rng = np.random.default_rng(9)
    f = rng.standard_normal(1003).astype(np.float32)
    f[::100] = np.nan
    f[1], f[2] = np.inf, -np.inf
    x = ht.array(f, split=0, comm=comm)
    v, i = ht.sort(x)
    np.testing.assert_array_equal(v.numpy(), np.sort(f))
    np.testing.assert_array_equal(ht.unique(x).numpy(), np.unique(f))
    vd, _ = ht.sort(x, descending=True)
    np.testing.assert_array_equal(
        np.nan_to_num(vd.numpy(), nan=7e33), np.nan_to_num(np.sort(f)[::-1], nan=7e33)
    )
    ii = rng.integers(0, 50, size=1003).astype(np.int32)
    ii[::7] = np.iinfo(np.int32).max  # genuine sentinel values in the data
    w = ht.array(ii, split=0, comm=comm)
    np.testing.assert_array_equal(ht.sort(w)[0].numpy(), np.sort(ii))
    np.testing.assert_array_equal(ht.unique(w).numpy(), np.unique(ii))
    np.testing.assert_array_equal(ht.sort(w, descending=True)[0].numpy(), np.sort(ii)[::-1])


@pytest.mark.parametrize("n", SIZES)
def test_statistics_ragged(n):
    comm = _comm(8)
    rng = np.random.default_rng(5)
    a = rng.standard_normal(n).astype(np.float32)
    x = ht.array(a, split=0, comm=comm)
    np.testing.assert_allclose(ht.std(x).item(), a.std(), rtol=1e-3)
    np.testing.assert_allclose(ht.var(x).item(), a.var(), rtol=1e-3)
    np.testing.assert_allclose(ht.median(x).item(), np.median(a), rtol=1e-4)
