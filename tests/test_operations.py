"""Tests for arithmetic/relational/logical/rounding/exponential/trig/complex ops
(parity model: reference heat/core/tests/test_{arithmetics,relational,logical,
rounding,exponential,trigonometrics,complex_math}.py)."""

import numpy as np
import pytest

import heat_tpu as ht
from _accel import requires_complex, tol as _tol

SPLITS = [None, 0, 1]


def _pair(split):
    rng = np.random.default_rng(0)
    a = rng.uniform(0.5, 2.0, (8, 4)).astype(np.float32)
    b = rng.uniform(0.5, 2.0, (8, 4)).astype(np.float32)
    return ht.array(a, split=split), ht.array(b, split=split), a, b


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize(
    "ht_op,np_op",
    [
        (ht.add, np.add),
        (ht.sub, np.subtract),
        (ht.mul, np.multiply),
        (ht.div, np.true_divide),
        (ht.pow, np.power),
        (ht.fmod, np.fmod),
        (ht.mod, np.mod),
        (ht.floordiv, np.floor_divide),
        (ht.maximum, np.maximum),
        (ht.minimum, np.minimum),
        (ht.atan2, np.arctan2),
        (ht.logaddexp, np.logaddexp),
    ],
)
def test_binary_ops(split, ht_op, np_op):
    ha, hb, a, b = _pair(split)
    res = ht_op(ha, hb)
    np.testing.assert_allclose(res.numpy(), np_op(a, b), **_tol(np_op.__name__, rtol=1e-5))
    assert res.split == split


def test_binary_broadcast_and_scalars():
    a = ht.array(np.arange(12.0).reshape(3, 4), split=0)
    b = ht.array(np.arange(4.0))
    np.testing.assert_allclose((a + b).numpy(), a.numpy() + b.numpy())
    np.testing.assert_allclose((a + 2).numpy(), a.numpy() + 2)
    np.testing.assert_allclose((2 + a).numpy(), a.numpy() + 2)
    np.testing.assert_allclose((a - 1.5).numpy(), a.numpy() - 1.5)
    assert (a + b).split == 0
    res = ht.add(1, 2)
    assert res.numpy().item() == 3


def test_operator_dunders():
    a = ht.array(np.array([4.0, 9.0]))
    np.testing.assert_allclose((-a).numpy(), [-4.0, -9.0])
    np.testing.assert_allclose((+a).numpy(), [4.0, 9.0])
    np.testing.assert_allclose(abs(-a).numpy(), [4.0, 9.0])
    np.testing.assert_allclose((a**0.5).numpy(), [2.0, 3.0], **_tol("pow"))
    np.testing.assert_allclose((a % 2).numpy(), [0.0, 1.0])


def test_bitwise():
    a = ht.array(np.array([0b1100, 0b1010]))
    b = ht.array(np.array([0b1010, 0b0110]))
    np.testing.assert_array_equal(ht.bitwise_and(a, b).numpy(), [0b1000, 0b0010])
    np.testing.assert_array_equal(ht.bitwise_or(a, b).numpy(), [0b1110, 0b1110])
    np.testing.assert_array_equal(ht.bitwise_xor(a, b).numpy(), [0b0110, 0b1100])
    np.testing.assert_array_equal(ht.invert(ht.array(np.array([0], np.int32))).numpy(), [-1])
    np.testing.assert_array_equal(ht.left_shift(a, 1).numpy(), [0b11000, 0b10100])
    np.testing.assert_array_equal(ht.right_shift(a, 2).numpy(), [0b11, 0b10])
    with pytest.raises(TypeError):
        ht.bitwise_and(ht.ones(3), ht.ones(3))


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_sum_prod(split, axis):
    ha, _, a, _ = _pair(split)
    np.testing.assert_allclose(ht.sum(ha, axis=axis).numpy(), a.sum(axis=axis), rtol=1e-5)
    np.testing.assert_allclose(ht.prod(ha, axis=axis).numpy(), a.prod(axis=axis), rtol=1e-4)


def test_reduction_split_semantics():
    a = ht.ones((8, 4), split=0)
    assert ht.sum(a, axis=0).split is None
    assert ht.sum(a, axis=1).split == 0
    assert ht.sum(a).split is None
    assert ht.sum(a, axis=1, keepdim=True).shape == (8, 1)
    b = ht.ones((8, 4), split=1)
    assert ht.sum(b, axis=0).split == 0


@pytest.mark.parametrize("axis", [0, 1])
def test_cumops(axis):
    ha, _, a, _ = _pair(0)
    np.testing.assert_allclose(ht.cumsum(ha, axis).numpy(), np.cumsum(a, axis), rtol=1e-5)
    np.testing.assert_allclose(ht.cumprod(ha, axis).numpy(), np.cumprod(a, axis), rtol=1e-4)


def test_diff():
    a = np.cumsum(np.ones((5, 4)), axis=0).astype(np.float32)
    h = ht.array(a, split=0)
    np.testing.assert_allclose(ht.diff(h, axis=0).numpy(), np.diff(a, axis=0))
    np.testing.assert_allclose(ht.diff(h, n=2, axis=1).numpy(), np.diff(a, n=2, axis=1))
    with pytest.raises(ValueError):
        ht.diff(h, n=-1)


@pytest.mark.parametrize("split", SPLITS)
def test_relational(split):
    ha, hb, a, b = _pair(split)
    for ht_op, np_op in [
        (ht.eq, np.equal),
        (ht.ne, np.not_equal),
        (ht.lt, np.less),
        (ht.le, np.less_equal),
        (ht.gt, np.greater),
        (ht.ge, np.greater_equal),
    ]:
        np.testing.assert_array_equal(ht_op(ha, hb).numpy().astype(bool), np_op(a, b))
    assert ht.equal(ha, ha)
    assert not ht.equal(ha, hb)


def test_logical():
    a = ht.array(np.array([[True, False], [True, True]]))
    assert not bool(ht.all(a))
    assert bool(ht.any(a))
    np.testing.assert_array_equal(ht.all(a, axis=0).numpy(), [True, False])
    np.testing.assert_array_equal(ht.logical_not(a).numpy(), [[False, True], [False, False]])
    b = ht.array(np.array([[False, True], [True, False]]))
    np.testing.assert_array_equal(ht.logical_and(a, b).numpy(), [[False, False], [True, False]])
    np.testing.assert_array_equal(ht.logical_or(a, b).numpy(), [[True, True], [True, True]])
    np.testing.assert_array_equal(ht.logical_xor(a, b).numpy(), [[True, True], [False, True]])


def test_isclose_allclose_isnan():
    a = ht.array(np.array([1.0, np.nan, np.inf, -np.inf]))
    np.testing.assert_array_equal(ht.isnan(a).numpy(), [False, True, False, False])
    np.testing.assert_array_equal(ht.isinf(a).numpy(), [False, False, True, True])
    np.testing.assert_array_equal(ht.isfinite(a).numpy(), [True, False, False, False])
    np.testing.assert_array_equal(ht.isposinf(a).numpy(), [False, False, True, False])
    np.testing.assert_array_equal(ht.isneginf(a).numpy(), [False, False, False, True])
    x = ht.ones((3,))
    assert ht.allclose(x, x + 1e-9)
    assert not ht.allclose(x, x + 1.0)
    assert ht.isclose(x, x + 1e-9).numpy().all()
    np.testing.assert_array_equal(ht.signbit(ht.array(np.array([-1.0, 1.0]))).numpy(), [True, False])


@pytest.mark.parametrize(
    "ht_op,np_op,domain",
    [
        (ht.exp, np.exp, (0.1, 2)),
        (ht.expm1, np.expm1, (0.1, 2)),
        (ht.exp2, np.exp2, (0.1, 2)),
        (ht.log, np.log, (0.1, 2)),
        (ht.log2, np.log2, (0.1, 2)),
        (ht.log10, np.log10, (0.1, 2)),
        (ht.log1p, np.log1p, (0.1, 2)),
        (ht.sqrt, np.sqrt, (0.1, 2)),
        (ht.square, np.square, (0.1, 2)),
        (ht.sin, np.sin, (-1, 1)),
        (ht.cos, np.cos, (-1, 1)),
        (ht.tan, np.tan, (-1, 1)),
        (ht.sinh, np.sinh, (-1, 1)),
        (ht.cosh, np.cosh, (-1, 1)),
        (ht.tanh, np.tanh, (-1, 1)),
        (ht.arcsin, np.arcsin, (-0.9, 0.9)),
        (ht.arccos, np.arccos, (-0.9, 0.9)),
        (ht.arctan, np.arctan, (-1, 1)),
        (ht.arcsinh, np.arcsinh, (-1, 1)),
        (ht.arccosh, np.arccosh, (1.1, 3)),
        (ht.arctanh, np.arctanh, (-0.9, 0.9)),
        (ht.floor, np.floor, (-2, 2)),
        (ht.ceil, np.ceil, (-2, 2)),
        (ht.trunc, np.trunc, (-2, 2)),
        (ht.fabs, np.fabs, (-2, 2)),
        (ht.abs, np.abs, (-2, 2)),
        (ht.sign, np.sign, (-2, 2)),
        (ht.deg2rad, np.deg2rad, (0, 180)),
        (ht.rad2deg, np.rad2deg, (0, 3)),
    ],
)
def test_elementwise(ht_op, np_op, domain):
    rng = np.random.default_rng(1)
    a = rng.uniform(*domain, (6, 3)).astype(np.float32)
    h = ht.array(a, split=0)
    np.testing.assert_allclose(ht_op(h).numpy(), np_op(a), **_tol(np_op.__name__, rtol=1e-5))
    assert ht_op(h).split == 0


def test_rounding_extra():
    a = ht.array(np.array([-1.7, 1.2, 3.5]))
    np.testing.assert_allclose(ht.round(a).numpy(), np.round([-1.7, 1.2, 3.5]))
    np.testing.assert_allclose(ht.clip(a, -1, 2).numpy(), np.clip([-1.7, 1.2, 3.5], -1, 2))
    frac, integ = ht.modf(a)
    nf, ni = np.modf(np.array([-1.7, 1.2, 3.5], np.float32))
    np.testing.assert_allclose(frac.numpy(), nf, rtol=1e-6)
    np.testing.assert_allclose(integ.numpy(), ni)
    with pytest.raises(ValueError):
        ht.clip(a, None, None)


@requires_complex
def test_complex_math():
    a = ht.array(np.array([1 + 1j, -2 + 2j], np.complex64))
    np.testing.assert_allclose(ht.angle(a).numpy(), np.angle(a.numpy()), rtol=1e-6)
    np.testing.assert_allclose(
        ht.angle(a, deg=True).numpy(), np.angle(a.numpy(), deg=True), rtol=1e-5
    )
    np.testing.assert_allclose(ht.conj(a).numpy(), np.conj(a.numpy()))
    np.testing.assert_allclose(ht.real(a).numpy(), a.numpy().real)
    np.testing.assert_allclose(ht.imag(a).numpy(), a.numpy().imag)
    r = ht.ones((2,))
    assert ht.real(r) is r
    np.testing.assert_array_equal(ht.imag(r).numpy(), [0.0, 0.0])


def test_out_kwarg():
    a = ht.ones((4,))
    out = ht.zeros((4,))
    ht.add(a, a, out=out)
    np.testing.assert_array_equal(out.numpy(), [2.0] * 4)
    ht.exp(ht.zeros((4,)), out=out)
    np.testing.assert_array_equal(out.numpy(), [1.0] * 4)


def test_where_kwarg():
    a = ht.array(np.array([1.0, 2.0, 3.0]))
    res = ht.add(a, a, where=ht.array(np.array([True, False, True])))
    np.testing.assert_array_equal(res.numpy(), [2.0, 0.0, 6.0])


def test_division_semantics_matrix():
    # zero-division, mod sign conventions, floor_divide — numpy semantics
    # (reference test_arithmetics.py edge blocks)
    a_np = np.array([5.0, -5.0, 0.0, 7.5], np.float32)
    b_np = np.array([2.0, 0.0, 0.0, -2.0], np.float32)
    a, b = ht.array(a_np, split=0), ht.array(b_np, split=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        np.testing.assert_array_equal(ht.divide(a, b).numpy(), a_np / b_np)
        fd = ht.floor_divide(a, b).numpy()
        fd_np = np.floor_divide(a_np, b_np)
        finite_div = b_np != 0
        np.testing.assert_array_equal(fd[finite_div], fd_np[finite_div])
        # x/0 in floor_divide: numpy says ±inf/nan, XLA says nan — both
        # non-finite; only the finiteness contract is portable
        assert not np.isfinite(fd[~finite_div]).any()
    # mod follows the divisor's sign (python/numpy), fmod the dividend's (C)
    x_np = np.array([5.0, -5.0, 5.0, -5.0], np.float32)
    y_np = np.array([3.0, 3.0, -3.0, -3.0], np.float32)
    x, y = ht.array(x_np, split=0), ht.array(y_np, split=0)
    np.testing.assert_array_equal(ht.mod(x, y).numpy(), np.mod(x_np, y_np))
    np.testing.assert_array_equal(ht.fmod(x, y).numpy(), np.fmod(x_np, y_np))
    # integer division truncation vs floor
    i_np = np.array([7, -7, 7, -7], np.int32)
    j_np = np.array([2, 2, -2, -2], np.int32)
    i, j = ht.array(i_np, split=0), ht.array(j_np, split=0)
    np.testing.assert_array_equal(
        ht.floor_divide(i, j).numpy(), np.floor_divide(i_np, j_np)
    )


def test_inplace_operator_surface():
    a_np = np.arange(8, dtype=np.float32)
    a = ht.array(a_np.copy(), split=0)
    a += 2
    a *= 3
    a -= 1
    a /= 2
    e = a_np.copy()
    e += 2; e *= 3; e -= 1; e /= 2
    np.testing.assert_allclose(a.numpy(), e, rtol=1e-6)
    assert a.split == 0
    b = ht.array((a_np + 1).copy(), split=0)
    b //= 2
    b **= 2
    e2 = (a_np + 1).copy(); e2 //= 2; e2 **= 2
    np.testing.assert_allclose(b.numpy(), e2, rtol=1e-6)
    c = ht.array(np.arange(8, dtype=np.int32), split=0)
    c %= 3
    c <<= 1
    c >>= 1
    c &= 3
    c |= 4
    c ^= 1
    e3 = np.arange(8, dtype=np.int32)
    e3 %= 3; e3 <<= 1; e3 >>= 1; e3 &= 3; e3 |= 4; e3 ^= 1
    np.testing.assert_array_equal(c.numpy(), e3)


def test_where_nonzero_matrix():
    rng = np.random.default_rng(61)
    for shape, split in [((13,), 0), ((6, 5), 0), ((6, 5), 1)]:
        a_np = rng.normal(size=shape).astype(np.float32)
        a = ht.array(a_np, split=split)
        nz = ht.nonzero(a > 0).numpy()
        want = np.stack(np.nonzero(a_np > 0), axis=1)  # heat's (k, ndim) layout
        np.testing.assert_array_equal(nz.reshape(want.shape) if nz.ndim == 1 else nz, want)
        w3 = ht.where(a > 0, a, -a)
        np.testing.assert_allclose(w3.numpy(), np.abs(a_np), rtol=1e-6)


def test_diff_gradient_edges():
    rng = np.random.default_rng(62)
    a_np = rng.normal(size=(13, 5)).astype(np.float32)
    for split in (0, 1, None):
        a = ht.array(a_np, split=split)
        for n in (1, 2):
            for axis in (0, 1):
                np.testing.assert_allclose(
                    ht.diff(a, n=n, axis=axis).numpy(),
                    np.diff(a_np, n=n, axis=axis),
                    rtol=1e-5, atol=1e-5,
                )


def test_clip_round_nan_propagation():
    a_np = np.array([1.5, np.nan, -2.5, np.inf, -np.inf], np.float32)
    a = ht.array(a_np, split=0)
    np.testing.assert_array_equal(
        ht.clip(a, -2.0, 2.0).numpy(), np.clip(a_np, -2.0, 2.0)
    )
    assert np.isnan(ht.round(a).numpy()[1])
    assert bool(ht.isnan(a).numpy()[1])
    assert bool(ht.isinf(a).numpy()[3])
    assert not bool(ht.isfinite(a).numpy()[4])
    np.testing.assert_array_equal(
        ht.nan_to_num(a).numpy(), np.nan_to_num(a_np)
    )


@requires_complex
def test_complex_math_matrix():
    z_np = np.array([1 + 2j, -3 + 0.5j, 0 - 1j, 2.5 + 0j], np.complex64)
    for split in (None, 0):
        z = ht.array(z_np, split=split)
        np.testing.assert_allclose(ht.real(z).numpy(), z_np.real, rtol=1e-6)
        np.testing.assert_allclose(ht.imag(z).numpy(), z_np.imag, rtol=1e-6)
        np.testing.assert_allclose(ht.conj(z).numpy(), np.conj(z_np), rtol=1e-6)
        np.testing.assert_allclose(ht.angle(z).numpy(), np.angle(z_np), rtol=1e-5)
        np.testing.assert_allclose(
            ht.angle(z, deg=True).numpy(), np.degrees(np.angle(z_np)), rtol=1e-5
        )
        np.testing.assert_allclose(ht.abs(z).numpy(), np.abs(z_np), rtol=1e-5)
        s = ht.sum(z)
        np.testing.assert_allclose(np.asarray(s.larray), z_np.sum(), rtol=1e-5)
    assert ht.conjugate is ht.conj or ht.conjugate(z).numpy() is not None


def test_power_and_hypot_edges():
    a_np = np.array([0.0, 2.0, -2.0, 9.0], np.float32)
    a = ht.array(a_np, split=0)
    np.testing.assert_allclose(ht.pow(a, 2).numpy(), a_np**2, rtol=1e-6)
    np.testing.assert_allclose(ht.pow(a, 0).numpy(), np.ones_like(a_np), rtol=1e-6)
    np.testing.assert_allclose((a ** 0.5).numpy(), a_np**0.5, rtol=1e-5, equal_nan=True)
    b = ht.array(np.array([3.0, 4.0, 5.0, 12.0], np.float32), split=0)
    c = ht.array(np.array([4.0, 3.0, 12.0, 5.0], np.float32), split=0)
    np.testing.assert_allclose(
        ht.hypot(b, c).numpy(), np.hypot(b.numpy(), c.numpy()), rtol=1e-6
    )
    np.testing.assert_allclose(
        ht.copysign(b, -c).numpy(), np.copysign(b.numpy(), -c.numpy()), rtol=1e-6
    )
