"""
sp / ep / pp training-step validation on the test mesh — the same programs
``__graft_entry__.dryrun_multichip`` runs for the driver, exercised continuously:
ring-attention sequence parallelism, all_to_all expert parallelism, and the
ppermute GPipe pipeline, each jitted with gradients flowing through the
collectives.
"""

import sys
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from heat_tpu.core import _compat

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft
from heat_tpu.core.communication import get_comm


@pytest.fixture(scope="module")
def comm():
    return get_comm()


def test_sp_ring_attention_step(comm):
    graft._sp_train_step(comm)


def test_ep_moe_all_to_all_step(comm):
    graft._ep_train_step(comm)


def test_pp_ppermute_pipeline_step(comm):
    graft._pp_train_step(comm)


def test_tp_2d_mesh_matmul_values():
    # 2-D tensor parallelism: megatron column->row pair over a (2, p//2)
    # mesh produces the same values as the replicated matmul
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 4 or len(devs) % 2 != 0:
        pytest.skip("needs an even device count >= 4 for the 2-D mesh")
    mesh = Mesh(np.asarray(devs).reshape(2, len(devs) // 2), ("dp", "tp"))
    rng = np.random.default_rng(66)
    x_np = rng.normal(size=(8, 16)).astype(np.float32)
    w1_np = rng.normal(size=(16, 32)).astype(np.float32)
    w2_np = rng.normal(size=(32, 16)).astype(np.float32)
    x = jax.device_put(jnp.asarray(x_np), NamedSharding(mesh, P("dp", None)))
    w1 = jax.device_put(jnp.asarray(w1_np), NamedSharding(mesh, P(None, "tp")))
    w2 = jax.device_put(jnp.asarray(w2_np), NamedSharding(mesh, P("tp", None)))

    @jax.jit
    def f(x, w1, w2):
        return jax.nn.relu(x @ w1) @ w2

    got = np.asarray(f(x, w1, w2))
    want = np.maximum(x_np @ w1_np, 0.0) @ w2_np
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    t = f.lower(x, w1, w2).compile().as_text()
    assert "all-reduce" in t  # the row-parallel contraction


def test_pipeline_ppermute_stage_chain():
    # pp: a 4-stage ppermute chain moves activations stage-to-stage and
    # reproduces the sequential composition
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    p = len(devs)
    if p < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = Mesh(np.asarray(devs), ("pp",))
    scale = np.arange(1, p + 1, dtype=np.float32)

    def stage(x, s):
        return x * s

    def local(x_blk, s_blk):
        # x enters at stage 0; each hop applies the next stage's transform
        def body(c, _):
            y = stage(c, s_blk[0])
            y = jax.lax.ppermute(y, "pp", [(i, (i + 1) % p) for i in range(p)])
            return y, None

        out, _ = jax.lax.scan(body, x_blk, None, length=p)
        return out

    f = jax.jit(
        _compat.shard_map(local, mesh=mesh, in_specs=(P(), P("pp")), out_specs=P(),
                      check_vma=False)
    )
    x = jnp.ones((4,), jnp.float32)
    got = np.asarray(f(x, jnp.asarray(scale)))
    # after p hops every stage's factor has been applied exactly once
    want = np.ones(4, np.float32) * np.prod(scale)
    np.testing.assert_allclose(got, want, rtol=1e-5)
