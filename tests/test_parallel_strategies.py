"""
sp / ep / pp training-step validation on the test mesh — the same programs
``__graft_entry__.dryrun_multichip`` runs for the driver, exercised continuously:
ring-attention sequence parallelism, all_to_all expert parallelism, and the
ppermute GPipe pipeline, each jitted with gradients flowing through the
collectives.
"""

import sys
import os

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft
from heat_tpu.core.communication import get_comm


@pytest.fixture(scope="module")
def comm():
    return get_comm()


def test_sp_ring_attention_step(comm):
    graft._sp_train_step(comm)


def test_ep_moe_all_to_all_step(comm):
    graft._ep_train_step(comm)


def test_pp_ppermute_pipeline_step(comm):
    graft._pp_train_step(comm)
