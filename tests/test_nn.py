"""Tests for NN data-parallel training and DASO (parity model: reference
heat/nn/tests/test_data_parallel.py and heat/optim/tests/test_dp_optimizer.py —
train tiny models and assert convergence/replica consistency)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import heat_tpu as ht


def _toy_data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    w = rng.normal(size=(8, 1)).astype(np.float32)
    y = (x @ w + 0.1 * rng.normal(size=(n, 1))).astype(np.float32)
    return x, y


def _mlp():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)

    return MLP()


def _mse(params, apply_fn, x, y):
    pred = apply_fn(params, x)
    return jnp.mean((pred - y) ** 2)


def test_data_parallel_training():
    x, y = _toy_data()
    dp = ht.nn.DataParallel(_mlp(), optimizer=optax.adam(1e-2))
    dp.init(0, x[:2])
    dp.make_train_step(_mse)
    losses = []
    for _ in range(60):
        losses.append(float(dp.train_step(x, y)))
    assert losses[-1] < losses[0] * 0.2
    out = dp(x)
    assert out.shape == (64, 1)


def test_data_parallel_requires_setup():
    dp = ht.nn.DataParallel(_mlp())
    with pytest.raises(RuntimeError):
        dp.train_step(np.zeros((4, 8), np.float32))
    with pytest.raises(ValueError):
        dp.make_train_step(_mse)


def test_nn_fallthrough():
    import flax.linen as nn

    assert ht.nn.Dense is nn.Dense
    assert ht.nn.functional.relu is jax.nn.relu
    with pytest.raises(AttributeError):
        ht.nn.functional.definitely_not_a_function
    with pytest.raises(AttributeError):
        ht.nn.DefinitelyNotAModule


def test_daso_training():
    x, y = _toy_data(n=64, seed=1)
    model = _mlp()
    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(1e-2),
        total_epochs=4,
        warmup_epochs=1,
        cooldown_epochs=1,
        max_global_skips=4,
    )
    assert daso.nodes * daso.local_size == ht.get_comm().size
    params = model.init(jax.random.PRNGKey(0), x[:2])
    daso.init(params)
    daso.make_train_step(_mse, model.apply)
    daso.last_batch = 4
    losses = []
    for epoch in range(4):
        for b in range(4):
            loss = daso.step(x, y)
        losses.append(float(loss))
        daso.epoch_loss_logic(losses[-1])
    assert losses[-1] < losses[0]
    merged = daso.merged_params
    out = model.apply(merged, x)
    assert out.shape == (64, 1)


def test_daso_consume_time_blend():
    """The global sync dispatches the node-MEAN only; at consume time it blends
    0.25*current_local + 0.75*received — local updates made during the wait are
    retained (reference dp_optimizer.py:502-652). Two runs that share the same
    dispatch state but diverge in the intervening batches must consume into
    different params (the old dispatch-time blend made them identical)."""
    x, y = _toy_data(n=64, seed=3)
    x2 = x + 1.0  # different intervening batch

    def run(intermediate_x):
        model = _mlp()
        daso = ht.optim.DASO(
            local_optimizer=optax.sgd(5e-2),
            total_epochs=10,
            warmup_epochs=0,
            cooldown_epochs=0,
            max_global_skips=4,
        )
        daso.batches_to_wait = 2
        daso.global_skip = 100  # one dispatch at batch 0, none after
        params = model.init(jax.random.PRNGKey(0), x[:2])
        daso.init(params)
        daso.make_train_step(_mse, model.apply)
        daso.step(x, y)              # batch 0: local step + dispatch mean
        daso.step(intermediate_x, y)  # batch 1: local-only (countdown 2->1)
        daso.step(intermediate_x, y)  # batch 2: consume = blend(current, mean)
        return jax.tree.map(lambda a: np.asarray(a), daso.merged_params)

    p_a = run(x)
    p_b = run(x2)
    leaves_a = jax.tree.leaves(p_a)
    leaves_b = jax.tree.leaves(p_b)
    assert any(
        not np.allclose(a, b) for a, b in zip(leaves_a, leaves_b)
    ), "intervening local updates were discarded at consume time"


def test_daso_warmup_sync_converges_replicas():
    """Warmup-phase blocking blends pull the per-node replicas together."""
    x, y = _toy_data(n=64, seed=4)
    model = _mlp()
    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(1e-2),
        total_epochs=4,
        warmup_epochs=4,
        cooldown_epochs=0,
        max_global_skips=4,
    )
    params = model.init(jax.random.PRNGKey(0), x[:2])
    daso.init(params)
    daso.make_train_step(_mse, model.apply)
    for _ in range(6):
        daso.step(x, y)
    # every node slot ends close to the node-mean after repeated 3/4 blends
    for leaf in jax.tree.leaves(daso.params):
        arr = np.asarray(leaf)
        mean = arr.mean(axis=0, keepdims=True)
        np.testing.assert_allclose(arr, np.broadcast_to(mean, arr.shape), rtol=0.15, atol=0.05)


def test_shard_batch_ragged_policies():
    """'cycle' trains every row (wrap-around pad); 'trim' drops the remainder."""
    daso = ht.optim.DASO(local_optimizer=optax.sgd(0.1), total_epochs=2)
    world = daso.nodes * daso.local_size
    if world == 1:
        pytest.skip("needs a multi-device mesh")
    n = world + 1  # ragged
    a = np.arange(n, dtype=np.float32)[:, None]
    with pytest.warns(RuntimeWarning):
        (cyc,) = daso.shard_batch(a)
    target = -(-n // world) * world
    assert cyc.shape[0] == target
    got = np.asarray(cyc)[:, 0]
    np.testing.assert_array_equal(np.unique(got), np.unique(a))  # all rows present
    daso._ragged_warned = True
    (trm,) = daso.shard_batch(a, ragged="trim")
    assert trm.shape[0] == (n // world) * world


def test_daso_skip_logic():
    daso = ht.optim.DASO(local_optimizer=optax.sgd(0.1), total_epochs=10, max_global_skips=8)
    daso.stability.patience = 0  # force plateau on second call
    daso.epoch_loss_logic(1.0)
    daso.epoch_loss_logic(1.0)  # not improving -> plateau -> skip reduction
    assert daso.global_skip in (4, 8)
    # cycle reset when bottomed out
    daso.global_skip = 1
    daso.epoch_loss_logic(1.0)  # bottomed out -> reset to max
    daso.epoch_loss_logic(1.0)  # decay again
    assert daso.global_skip == 4


def test_data_parallel_optimizer():
    dpo = ht.optim.DataParallelOptimizer(optax.sgd(0.1))
    params = {"w": jnp.ones((3,))}
    dpo.init(params)
    grads = {"w": jnp.ones((3,))}
    new_params, _ = dpo.step(grads, params)
    np.testing.assert_allclose(np.asarray(new_params["w"]), 0.9)
    with pytest.raises(TypeError):
        ht.optim.DataParallelOptimizer(optax.sgd(0.1), blocking="yes")


def test_detect_metric_plateau():
    dmp = ht.optim.DetectMetricPlateau(patience=1)
    assert not dmp.test_if_improving(1.0)
    assert not dmp.test_if_improving(0.5)
    assert not dmp.test_if_improving(0.5)
    assert dmp.test_if_improving(0.5)  # patience exceeded
    state = dmp.get_state()
    dmp2 = ht.optim.DetectMetricPlateau()
    dmp2.set_state(state)
    assert dmp2.best == dmp.best
    with pytest.raises(ValueError):
        ht.optim.DetectMetricPlateau(mode="bogus")
    with pytest.raises(ValueError):
        ht.optim.DetectMetricPlateau(threshold_mode="bogus")


def test_optim_fallthrough():
    assert ht.optim.sgd is optax.sgd
    assert ht.optim.SGD is optax.sgd
    assert ht.optim.Adam is optax.adam
    with pytest.raises(AttributeError):
        ht.optim.DefinitelyNotAnOptimizer


def test_daso_vs_dp_convergence():
    # VERDICT r2 #6: the reference's DASO-vs-plain-DP comparison (reference
    # optim/tests/test_dp_optimizer.py:205): train the same tiny model with
    # both optimizers and assert DASO's final loss is in the same regime —
    # hierarchical skipping/blending must not break convergence.
    x, y = _toy_data(n=64, seed=3)
    model = _mlp()
    init_params = model.init(jax.random.PRNGKey(7), x[:2])

    dp = ht.nn.DataParallel(model, optimizer=optax.sgd(5e-2))
    dp.params = jax.device_put(init_params)
    dp.opt_state = dp.optimizer.init(dp.params)
    dp._ready = True
    dp.make_train_step(_mse)
    dp_losses = [float(dp.train_step(x, y)) for _ in range(48)]

    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(5e-2),
        total_epochs=6,
        warmup_epochs=2,
        cooldown_epochs=2,
        max_global_skips=4,
    )
    daso.init(init_params)
    daso.make_train_step(_mse, model.apply)
    daso.last_batch = 8
    daso_losses = []
    for epoch in range(6):
        for b in range(8):
            loss = daso.step(x, y)
        daso_losses.append(float(loss))
        daso.epoch_loss_logic(daso_losses[-1])
    # both converge from the same init; DASO lands within 3x of DP's final loss
    assert dp_losses[-1] < dp_losses[0] * 0.5
    assert daso_losses[-1] < daso_losses[0] * 0.5
    assert daso_losses[-1] < max(dp_losses[-1] * 3.0, dp_losses[0] * 0.1)
