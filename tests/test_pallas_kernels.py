"""Fused pallas Lloyd-iteration kernel vs the XLA two-GEMM step (interpret mode on
the CPU mesh; the compiled path runs on real TPU via bench.py / KMeans.fit)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from heat_tpu.cluster._pallas import fused_step_available, kmeans_step_fused
from heat_tpu.cluster.kmeans import _kmeans_step


def test_fused_step_matches_xla():
    n, f, k = 8192, 16, 5
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
    c0 = x[:k]
    want_c, want_l, want_s, want_i = _kmeans_step(x, c0)
    got_c, got_l, got_s, got_i = kmeans_step_fused(x, c0, tile_rows=1024, interpret=True)
    np.testing.assert_allclose(np.asarray(got_c), np.asarray(want_c), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(want_l))
    np.testing.assert_allclose(float(got_s), float(want_s), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(float(got_i), float(want_i), rtol=1e-4)


def test_fused_step_rejects_ragged():
    x = jnp.zeros((1000, 8), jnp.float32)
    with pytest.raises(ValueError):
        kmeans_step_fused(x, x[:3], tile_rows=512, interpret=True)


def test_fused_availability_gate():
    # on the CPU test mesh the compiled kernel must report unavailable
    if jax.default_backend() != "tpu":
        assert not fused_step_available(1 << 20)
    assert not fused_step_available(1000)  # ragged row count never eligible
