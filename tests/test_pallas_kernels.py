"""Fused pallas Lloyd-iteration kernel vs the XLA two-GEMM step (interpret mode on
the CPU mesh; the compiled path runs on real TPU via bench.py / KMeans.fit)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from heat_tpu.cluster._pallas import fused_step_available, kmeans_step_fused
from heat_tpu.cluster.kmeans import _kmeans_step


def test_fused_step_matches_xla():
    n, f, k = 8192, 16, 5
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
    c0 = x[:k]
    # the kernel streams bf16 (matching the TPU MXU's default bf16 pass over f32
    # operands); the f32 reference is therefore computed on bf16-rounded operands
    xb = x.astype(jnp.bfloat16).astype(jnp.float32)
    c0b = c0.astype(jnp.bfloat16).astype(jnp.float32)
    want_c, want_l, want_s, want_i = _kmeans_step(xb, c0b)
    got_c, got_l, got_s, got_i = kmeans_step_fused(x, c0, tile_rows=1024, interpret=True)
    np.testing.assert_allclose(np.asarray(got_c), np.asarray(want_c), rtol=1e-2, atol=1e-3)
    agree = float(np.mean(np.asarray(got_l) == np.asarray(want_l)))
    assert agree > 0.999, f"label agreement {agree}"  # rare boundary flips from dot rounding
    np.testing.assert_allclose(float(got_s), float(want_s), rtol=5e-2, atol=1e-4)
    np.testing.assert_allclose(float(got_i), float(want_i), rtol=1e-2)


def test_fused_step_rejects_ragged():
    x = jnp.zeros((1000, 8), jnp.float32)
    with pytest.raises(ValueError):
        kmeans_step_fused(x, x[:3], tile_rows=512, interpret=True)


def test_fused_availability_gate():
    # on the CPU test mesh the compiled kernel must report unavailable
    if jax.default_backend() != "tpu":
        assert not fused_step_available(1 << 20)
    assert not fused_step_available(1000)  # ragged row count never eligible
