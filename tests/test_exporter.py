"""
Fleet telemetry plane (ISSUE 14): Prometheus exposition + served endpoints
(``heat_tpu/monitoring/exporter.py``), the cross-process telemetry spool +
aggregator (``monitoring/aggregate.py``), and the SLO burn-rate engine
(``monitoring/slo.py``). Covers: parse-clean exposition with the full
metric catalog present at zero, catalog↔source drift, label escaping and
the label-sum == total residual rule, the HTTP routes + request counters,
readiness flips on forced-open breakers / elastic degradation / SLO burn,
off-mode inertness (zero threads/sockets/files, bit-for-bit results), the
per-flush-count spool cadence and its scheduler/cache trigger sites, the
aggregator's torn/stale/superseded tolerance (incl. a live two-writer +
aggregator race), fleet exposition with per-process labels and the fleet
scale signal, SLO window/burn math + env config, the uniform latency
export shape (satellite), merged multi-process Chrome traces with
process/thread metadata (satellite), the bench telemetry sidecar
(satellite), and the standalone spool-scrape CLI.
"""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import fusion
from heat_tpu.monitoring import aggregate, events, exporter, flight, registry, report, slo
from heat_tpu.monitoring import instrument as instr
from heat_tpu.monitoring.registry import REGISTRY
from heat_tpu.robustness import breaker as rbreaker
from heat_tpu.robustness import elastic as relastic

pytestmark = pytest.mark.exporter

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Every knob off on both sides; the armed CI legs set them ambiently,
    so counting tests pin their own state via monkeypatch (the flight-suite
    precedent)."""
    for var in (
        "HEAT_TPU_METRICS_PORT",
        "HEAT_TPU_METRICS_HOST",
        "HEAT_TPU_TELEMETRY_DIR",
        "HEAT_TPU_TELEMETRY_EVERY",
        "HEAT_TPU_SLO",
        "HEAT_TPU_READY_MIN_HIT_RATE",
        "HEAT_TPU_READY_MAX_BURN",
        "HEAT_TPU_BREAKER_FORCE_OPEN",
        "HEAT_TPU_FLIGHT",
        "HEAT_TPU_CACHE_DIR",
        "HEAT_TPU_FAULT_PLAN",
        "HEAT_TPU_CHAOS",
    ):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    monkeypatch.setattr(relastic, "_LAST_STATE", None)
    registry.reset()
    events.clear()
    flight.clear()
    slo.reset()
    aggregate.reset()
    rbreaker.reset()
    fusion.clear_cache()
    yield
    exporter.stop()
    fusion.clear_cache()
    rbreaker.reset()
    slo.reset()
    aggregate.reset()
    flight.clear()
    events.clear()
    registry.reset()
    monkeypatch.setattr(relastic, "_LAST_STATE", None)


def _fresh(shape=(6, 10), seed=0, split=None):
    data = np.random.default_rng(seed).normal(size=shape).astype(np.float32)
    return ht.array(data, split=split)


def _chain(x):
    return (x * 2.0 + 1.0) / 3.0 - 0.25


def _get(url, timeout=10):
    """(status, body) — 4xx/5xx bodies read instead of raised."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ------------------------------------------------------------- exposition
def test_exposition_parse_clean_with_live_counters():
    with registry.capture():
        _chain(_fresh(seed=1)).numpy()
        y = _chain(_fresh(seed=2)).sum()
        float(y.larray)
    text = exporter.exposition()
    assert exporter.validate_exposition(text) == []
    lines = text.splitlines()
    # unlabelled counter: a bare sample with the recorded value
    flushes = REGISTRY.counter("fusion.flushes").get()
    assert f"heat_tpu_fusion_flushes_total {flushes}" in lines
    # labelled counter: one series per label; the label sum equals the total
    reason_lines = [l for l in lines if l.startswith("heat_tpu_fusion_flush_reason_total{")]
    assert reason_lines
    total = sum(int(l.rsplit(" ", 1)[1]) for l in reason_lines)
    assert total == REGISTRY.counter("fusion.flush_reason").get()
    # histogram: summary exposition with quantiles + _sum/_count
    assert any(l.startswith('heat_tpu_fusion_chain_length{quantile="0.5"}') for l in lines)
    assert any(l.startswith("heat_tpu_fusion_chain_length_sum") for l in lines)
    assert any(l.startswith("heat_tpu_fusion_chain_length_count") for l in lines)
    # the point-in-time scale signal always rides along
    assert any(l.startswith("heat_tpu_scale_signal ") for l in lines)


def test_exposition_catalog_complete_at_zero():
    """Acceptance: a fresh process's first scrape already carries every
    ledger metric (zero-valued) — the scrape schema never depends on which
    code paths have run."""
    text = exporter.exposition()
    assert exporter.validate_exposition(text) == []
    for name, kind in exporter.CATALOG:
        mname = exporter.metric_name(name, "_total" if kind == "counter" else "")
        probe = f"{mname}_count 0" if kind == "histogram" else f"{mname} 0"
        assert probe in text.splitlines(), (name, probe)


def test_catalog_matches_source():
    """Drift guard: the exposition catalog is the code-side twin of the doc
    ledger — every statically-named REGISTRY metric in heat_tpu/ (same grep
    as the ledger guard) must appear, minus the ``{...}`` f-string
    templates the exposition cannot pre-render."""
    metric_re = re.compile(r'REGISTRY\.(counter|gauge|histogram)\(\s*f?"([^"]+)"')
    found = set()
    for dirpath, _dirs, files in os.walk(os.path.join(_REPO, "heat_tpu")):
        if "__pycache__" in dirpath:
            continue
        for fname in files:
            if fname.endswith(".py"):
                with open(os.path.join(dirpath, fname)) as f:
                    src = f.read()
                for kind, name in metric_re.findall(src):
                    if "{" not in name:
                        found.add((name, kind))
    assert found == set(exporter.CATALOG)


def test_label_escaping_and_unattributed_residual():
    with registry.capture():
        c = REGISTRY.counter("serving.shed")
        c.inc(3, label='weird"label\\x')
        c.inc(2)  # unattributed: no label
    text = exporter.exposition()
    assert exporter.validate_exposition(text) == []
    lines = text.splitlines()
    assert 'heat_tpu_serving_shed_total{label="weird\\"label\\\\x"} 3' in lines
    # the residual keeps sum(series) == counter total
    assert 'heat_tpu_serving_shed_total{label=""} 2' in lines


def test_gauge_bracket_names_become_labels():
    with registry.capture():
        REGISTRY.gauge("memory.bytes_in_use[0]").set(1234)
        name, window = "dispatch_p99_us", "short"
        REGISTRY.gauge(f"slo.burn[{name}:{window}]").set(0.5)
    text = exporter.exposition()
    assert exporter.validate_exposition(text) == []
    assert 'heat_tpu_memory_bytes_in_use{device="0"} 1234' in text.splitlines()
    assert (
        'heat_tpu_slo_burn{objective="dispatch_p99_us",window="short"} 0.5'
        in text.splitlines()
    )


# ------------------------------------------------------------- HTTP plane
def test_server_routes_and_request_counters():
    srv = exporter.MetricsServer(port=0)
    try:
        with registry.capture():
            code, text = _get(srv.url("/metrics"))
            assert code == 200 and exporter.validate_exposition(text) == []
            code, body = _get(srv.url("/healthz"))
            h = json.loads(body)
            assert code == 200 and h["ok"] is True and h["pid"] == os.getpid()
            code, body = _get(srv.url("/readyz"))
            r = json.loads(body)
            assert code == 200 and r["ready"] is True and r["reasons"] == []
            code, body = _get(srv.url("/statusz"))
            assert code == 200 and json.loads(body)["ok"] is True
            code, body = _get(srv.url("/trace"))
            assert code == 200 and "traceEvents" in json.loads(body)
            code, body = _get(srv.url("/nonsense"))
            assert code == 404
        reqs = REGISTRY.counter("exporter.requests")
        for route in ("metrics", "healthz", "readyz", "statusz", "trace", "not-found"):
            assert reqs.get(route) == 1, route
    finally:
        srv.stop()


def test_readyz_flips_on_breakers_elastic_and_back(monkeypatch):
    srv = exporter.MetricsServer(port=0)
    try:
        assert _get(srv.url("/readyz"))[0] == 200
        # forced-open breakers (the CI degraded leg): every known site is a
        # reason even though no breaker object was ever instantiated
        monkeypatch.setenv("HEAT_TPU_BREAKER_FORCE_OPEN", "*")
        code, body = _get(srv.url("/readyz"))
        payload = json.loads(body)
        assert code == 503 and payload["ready"] is False
        assert set(payload["reasons"]) == {
            f"breaker:{s}" for s in rbreaker.BREAKER_SITES
        }
        monkeypatch.delenv("HEAT_TPU_BREAKER_FORCE_OPEN")
        assert _get(srv.url("/readyz"))[0] == 200
        # elastic degradation (the supervisor's _to hook updates the
        # process-wide readiness input unconditionally)
        monkeypatch.setattr(relastic, "_LAST_STATE", None)
        relastic._note_state("draining")
        code, body = _get(srv.url("/readyz"))
        assert code == 503 and json.loads(body)["reasons"] == ["elastic:draining"]
        relastic._note_state("healthy")
        assert _get(srv.url("/readyz"))[0] == 200
    finally:
        srv.stop()


def test_readyz_slo_burn_ceiling(monkeypatch):
    """HEAT_TPU_READY_MAX_BURN wires the SLO engine into readiness: a
    long-window burn above the ceiling flips /readyz."""
    monkeypatch.setenv("HEAT_TPU_READY_MAX_BURN", "1.0")
    eng = slo.engine()
    hot = {"serving_dispatch_latency": {"count": 5, "p50_us": 1.0, "p99_us": 5e8},
           "counters": {}}
    for _ in range(8):
        eng.observe(hot)
    ready, reasons = exporter.readiness()
    assert not ready and any(r.startswith("slo-burn:dispatch_p99_us") for r in reasons)


def test_off_mode_zero_threads_sockets_files(tmp_path):
    """Acceptance: all knobs unset = zero threads, zero sockets, zero
    files, and results bit-for-bit with the armed run (differential)."""
    assert exporter.maybe_start() is None
    assert not exporter.running() and exporter.port() is None
    assert not any(t.name == "heat-tpu-exporter" for t in threading.enumerate())
    # spool off: the trigger is one env read, no file anywhere
    aggregate.maybe_snapshot()
    assert aggregate.write_snapshot() is None
    assert list(tmp_path.iterdir()) == []
    base = _chain(_fresh(seed=11, split=0)).numpy()
    # arm everything, recompute: bit-identical (pure observer)
    os.environ["HEAT_TPU_TELEMETRY_DIR"] = str(tmp_path)
    os.environ["HEAT_TPU_TELEMETRY_EVERY"] = "1"
    try:
        srv = exporter.start(port=0)
        fusion.clear_cache()
        armed = _chain(_fresh(seed=11, split=0)).numpy()
        aggregate.maybe_snapshot()
        assert list(tmp_path.glob("*.json"))
        assert _get(srv.url("/healthz"))[0] == 200
    finally:
        os.environ.pop("HEAT_TPU_TELEMETRY_DIR", None)
        os.environ.pop("HEAT_TPU_TELEMETRY_EVERY", None)
        exporter.stop()
    np.testing.assert_array_equal(base, armed)


# ------------------------------------------------------------- spool
def test_spool_cadence_first_then_every_nth(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("HEAT_TPU_TELEMETRY_EVERY", "3")
    with registry.capture():
        for _ in range(7):  # writes at triggers 1, 3, 6
            aggregate.maybe_snapshot()
    files = list(tmp_path.glob("*.json"))
    assert len(files) == 1  # one file per process, overwritten in place
    snap = json.loads(files[0].read_text())
    assert snap["seq"] == 3
    assert snap["pid"] == os.getpid()
    assert files[0].name == f"{snap['pid']}-{snap['nonce']}.json"
    assert snap["labels"]["pid"] == str(os.getpid())
    for key in ("metrics", "telemetry", "flight", "slo", "time", "schema"):
        assert key in snap, key
    assert REGISTRY.counter("telemetry_spool.snapshots").get("written") == 3


def test_spool_triggered_by_scheduler_and_cache(monkeypatch, tmp_path):
    """The two runtime trigger sites: a dispatched scheduler flush and an
    L2 persist both advance the cadence."""
    from heat_tpu import serving

    monkeypatch.setenv("HEAT_TPU_TELEMETRY_DIR", str(tmp_path / "spool"))
    monkeypatch.setenv("HEAT_TPU_TELEMETRY_EVERY", "1")
    with serving.FlushScheduler(max_workers=2) as sched:
        x = _chain(_fresh(seed=21))
        sched.schedule(x).result()
    files = list((tmp_path / "spool").glob("*.json"))
    assert len(files) == 1, "scheduler dispatch must trigger a snapshot"
    first = json.loads(files[0].read_text())["seq"]
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path / "cache"))
    fusion.clear_cache()
    _chain(_fresh(seed=22)).numpy()  # L2 miss -> compile -> persist -> trigger
    later = json.loads(files[0].read_text())["seq"]
    assert later > first, "L2 persist must trigger a snapshot"


def test_spool_snapshot_is_barrier_free(monkeypatch, tmp_path):
    """Publishing telemetry must not flush pending fused chains — the
    snapshot is a pure observation of the schedule, not a participant."""
    monkeypatch.setenv("HEAT_TPU_TELEMETRY_DIR", str(tmp_path))
    x = _chain(_fresh(seed=31))  # pending
    assert x._expr() is not None
    assert aggregate.write_snapshot(str(tmp_path)) is not None
    assert x._expr() is not None, "write_snapshot flushed a pending chain"


def test_aggregator_tolerates_torn_stale_superseded(tmp_path):
    def put(name, payload):
        (tmp_path / name).write_text(payload if isinstance(payload, str) else json.dumps(payload))

    now = time.time()
    base = {"schema": 1, "host": "h", "metrics": {"counters": {"fusion.flushes": 4}},
            "telemetry": {"serving_queue_depth": 2,
                          "serving_dispatch_latency": {"count": 9, "p50_us": 50.0, "p99_us": 100.0}},
            "seq": 1}
    put("111-aaaa.json", dict(base, pid=111, nonce="aaaa", time=now))
    put("222-bbbb.json", dict(base, pid=222, nonce="bbbb", time=now,
                              metrics={"counters": {"fusion.flushes": 6}}))
    put("333-cccc.json", '{"pid": 333, "nonce": "cc')         # torn mid-replace
    put(".tmp-999.json", "ignored")                            # writer tempfile
    put("444-dddd.json", dict(base, pid=444, nonce="dddd", time=now - 3600))  # stale
    put("111-eeee.json", dict(base, pid=111, nonce="eeee", time=now + 1))     # pid reuse
    with registry.capture():
        snaps, skips = aggregate.read_snapshots(str(tmp_path), max_age_s=600)
    assert skips == {"merged": 2, "torn": 1, "stale": 1, "superseded": 1}
    keys = {(s["pid"], s["nonce"]) for s in snaps}
    assert keys == {(111, "eeee"), (222, "bbbb")}  # newest nonce won the pid
    mc = REGISTRY.counter("telemetry_spool.merge")
    assert mc.get("torn") == 1 and mc.get("stale") == 1 and mc.get("superseded") == 1
    view = aggregate.fleet_view(str(tmp_path), max_age_s=600)
    assert set(view["processes"]) == {"111-eeee", "222-bbbb"}
    assert view["metrics"]["counters"]["fusion.flushes"] == 10
    # fleet scale signal: (sum queue depth) x (max p99)
    assert view["scale_signal"] == pytest.approx((2 + 2) * 100.0)


def test_fleet_exposition_per_process_labels(tmp_path):
    now = time.time()
    for pid, n in ((111, "aaaa"), (222, "bbbb")):
        (tmp_path / f"{pid}-{n}.json").write_text(json.dumps({
            "schema": 1, "pid": pid, "nonce": n, "time": now, "seq": 1,
            "metrics": {"counters": {"fusion.flushes": pid},
                        "gauges": {"serving.queue_depth": 1},
                        "histograms": {}},
            "telemetry": {"serving_queue_depth": 1,
                          "serving_dispatch_latency": {"count": 3, "p50_us": 10.0, "p99_us": 20.0}},
        }))
    text = exporter.fleet_exposition(str(tmp_path))
    assert exporter.validate_exposition(text) == []
    lines = text.splitlines()
    assert 'heat_tpu_fusion_flushes_total{pid="111",nonce="aaaa"} 111' in lines
    assert 'heat_tpu_fusion_flushes_total{pid="222",nonce="bbbb"} 222' in lines
    assert "heat_tpu_fleet_processes 2" in lines
    assert any(l.startswith("heat_tpu_scale_signal ") for l in lines)
    assert 'heat_tpu_telemetry_spool_skips{kind="merged"} 2' in lines


def test_registry_merge_snapshots():
    a = {"counters": {"x": 3, "y": {"total": 5, "labels": {"a": 2, "b": 3}}},
         "gauges": {"g": 1.5},
         "histograms": {"h": {"buckets": [1.0, 2.0], "counts": [1, 0, 2], "count": 3, "sum": 4.0}}}
    b = {"counters": {"x": 4, "y": {"total": 1, "labels": {"b": 1}}},
         "gauges": {"g": 2.5},
         "histograms": {"h": {"buckets": [1.0, 2.0], "counts": [0, 1, 0], "count": 1, "sum": 1.5}}}
    m = registry.merge_snapshots([a, b])
    assert m["counters"]["x"] == 7
    assert m["counters"]["y"] == {"total": 6, "labels": {"a": 2, "b": 4}}
    assert m["gauges"]["g"] == 4.0
    assert m["histograms"]["h"] == {
        "buckets": [1.0, 2.0], "counts": [1, 1, 2], "count": 4, "sum": 5.5}
    # disagreeing bounds: totals stay exact, buckets are dropped (a quantile
    # over mixed layouts would be fabricated)
    c = {"histograms": {"h": {"buckets": [9.0], "counts": [1, 0], "count": 1, "sum": 9.0}}}
    m2 = registry.merge_snapshots([a, c])
    assert m2["histograms"]["h"]["count"] == 4
    assert m2["histograms"]["h"]["buckets"] == []


def test_two_writers_and_aggregator_race(tmp_path):
    """Satellite: two writer processes + this process aggregating, racing
    over one spool dir, with torn/stale/duplicate garbage injected mid-race
    — every merged view stays well-formed and the skips are counted."""
    prog = (
        "import os\n"
        "os.environ['HEAT_TPU_TELEMETRY_DIR'] = r'%s'\n"
        "os.environ['HEAT_TPU_TELEMETRY_EVERY'] = '1'\n"
        "os.environ['HEAT_TPU_MONITORING'] = '1'\n"
        "from heat_tpu.monitoring import aggregate, registry\n"
        "from heat_tpu.monitoring.registry import REGISTRY\n"
        "for i in range(12):\n"
        "    REGISTRY.counter('fusion.flushes').inc()\n"
        "    aggregate.maybe_snapshot()\n"
        "print('done')\n" % str(tmp_path)
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("HEAT_TPU_METRICS_PORT", None)
    procs = [
        subprocess.Popen([sys.executable, "-c", prog], env=env, cwd=_REPO,
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for _ in range(2)
    ]
    # garbage the aggregator must shrug off, injected while writers run
    (tmp_path / "777-torn.json").write_text('{"pid": 777, "non')
    (tmp_path / "888-gone.json").write_text(json.dumps(
        {"schema": 1, "pid": 888, "nonce": "gone", "time": time.time() - 9999,
         "metrics": {}, "telemetry": {}, "seq": 1}))
    deadline = time.time() + 240
    while any(p.poll() is None for p in procs) and time.time() < deadline:
        view = aggregate.fleet_view(str(tmp_path), max_age_s=600)
        assert isinstance(view["processes"], dict)  # never raises, always shaped
        time.sleep(0.05)
    outs = [p.communicate(timeout=240) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, err[-800:]
        assert "done" in out
    snaps, skips = aggregate.read_snapshots(str(tmp_path), max_age_s=600)
    pids = {s["pid"] for s in snaps}
    assert {p.pid for p in procs} <= pids
    assert skips["torn"] == 1 and skips["stale"] == 1
    view = aggregate.fleet_view(str(tmp_path), max_age_s=600)
    assert view["metrics"]["counters"]["fusion.flushes"] == 24


# ------------------------------------------------------------- SLO engine
def _tel(p99=None, hit_rate=None, qd=0, misses=0, flushes=10, shed=0):
    tel = {"counters": {"fusion.flushes": flushes, "serving.shed": shed,
                        "serving.deadline_miss": misses},
           "serving_queue_depth": qd}
    if p99 is not None:
        tel["serving_dispatch_latency"] = {"count": 5, "p50_us": p99 / 2, "p99_us": p99}
    if hit_rate is not None:
        tel["serving_cache_slo"] = {"hit_rate": hit_rate}
    return tel


def test_slo_windows_and_burn_math():
    eng = slo.SloEngine(objectives=(
        slo.Objective("dispatch_p99_us", op="<=", target=100.0, budget=0.25),),
        windows=(("short", 4), ("long", 8)))
    for p99 in (50, 50, 200, 50, 50, 50, 200, 50):  # 2/8 violations, 1/4 short
        eng.observe(_tel(p99=p99))
    ev = eng.evaluate()
    row = ev["objectives"]["dispatch_p99_us"]
    assert row["windows"]["short"] == {"samples": 4, "violations": 1, "burn": 1.0}
    assert row["windows"]["long"] == {"samples": 8, "violations": 2, "burn": 1.0}
    assert row["ok"] is False  # burn >= 1.0: the budget is fully consumed
    assert row["value"] == 50.0


def test_slo_measurement_extractors():
    eng = slo.SloEngine()
    s1 = eng.observe(_tel(p99=10.0, hit_rate=0.9, qd=3, misses=2, flushes=100, shed=5))
    assert s1["dispatch_p99_us"] == 10.0
    assert s1["cache_hit_rate"] == 0.9
    assert s1["shed_ratio"] == pytest.approx(0.05)
    assert s1["queue_depth"] == 3.0
    assert s1["deadline_misses"] == 2.0  # first sample: the lifetime total
    s2 = eng.observe(_tel(p99=10.0, misses=5))
    assert s2["deadline_misses"] == 3.0  # counter delta, not the total
    s3 = eng.observe({"counters": {}})
    assert s3["dispatch_p99_us"] is None  # unavailable, never a violation
    assert slo.scale_signal(_tel(p99=200.0, qd=4)) == 800.0
    assert slo.scale_signal({"counters": {}}) == 0.0


def test_slo_gauges_and_telemetry_export():
    with registry.capture():
        eng = slo.engine()
        eng.observe(_tel(p99=5e8, qd=2))  # violates the default 100ms target
        ev = eng.evaluate()
    assert ev["scale_signal"] == 2 * 5e8
    g = REGISTRY.gauge("slo.burn[dispatch_p99_us:short]").get()
    assert g > 1.0
    assert REGISTRY.counter("slo.evaluations").get() == 1
    tel = report.telemetry()
    assert tel["slo_scale_signal"] == 2 * 5e8


def test_slo_env_config(monkeypatch):
    monkeypatch.setenv(
        "HEAT_TPU_SLO",
        json.dumps([{"name": "qd", "metric": "queue_depth", "op": "<=",
                     "target": 1, "budget": 0.5}]),
    )
    objs = slo.objectives_from_env()
    assert len(objs) == 1 and objs[0].name == "qd" and objs[0].target == 1.0
    monkeypatch.setenv("HEAT_TPU_SLO", "{not json")
    with pytest.raises(ValueError):
        slo.objectives_from_env()
    # a malformed config must not take /metrics down with it
    assert exporter.validate_exposition(exporter.exposition()) == []
    with pytest.raises(ValueError):
        slo.Objective("x", op="==", target=1)
    with pytest.raises(ValueError):
        slo.Objective("x", budget=0.0)


# ------------------------------------------------------------- satellites
def test_latency_export_contract(monkeypatch):
    """Satellite: the three latency surfaces export through ONE shared
    {count, p50_us, p99_us} shape. The labelled `comm_collective_timeout`
    telemetry key — the PR 14 one-release alias — is RETIRED (ISSUE 15
    satellite): the per-kind breakdown stays on the registry counter, the
    uniform latency block is the telemetry surface."""
    with registry.capture():
        instr.serving_dispatch(0.002)
        instr.fusion_compile_latency(0.05)
        instr.collective_timeout("allreduce", seconds=0.3)
        tel = report.telemetry()
    shape = {"count", "p50_us", "p99_us"}
    for key in ("serving_dispatch_latency", "fusion_compile_latency",
                "comm_collective_timeout_latency"):
        assert set(tel[key]) == shape, key
        assert tel[key]["count"] == 1
        assert tel[key]["p99_us"] >= tel[key]["p50_us"] > 0
    assert "comm_collective_timeout" not in tel  # the alias shipped one release
    # the per-kind breakdown is still first-class on the registry counter
    assert REGISTRY.counter("comm.collective_timeout").get("allreduce") == 1
    assert tel["counters"]["comm.collective_timeout"] == 1


def test_merged_chrome_traces_render_separate_tracks(monkeypatch):
    """Satellite: per-process pid tags + process_name/thread_name metadata
    survive an aggregator merge — Perfetto renders one track per process."""
    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    with registry.capture():
        with events.span("req"):
            _chain(_fresh(seed=41)).numpy()
        mine = flight.export_chrome_trace()
    other = {
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 99999, "tid": 0,
             "args": {"name": "heat_tpu pid 99999"}},
            {"name": "flush deadbeef", "cat": "flight.flush", "ph": "X",
             "ts": 1.0, "dur": 2.0, "pid": 99999, "tid": 7, "args": {}},
        ]
    }
    merged = json.loads(aggregate.merge_chrome_traces([mine, other, "{not json"]))
    evs = merged["traceEvents"]
    metas = [e for e in evs if e["ph"] == "M"]
    timed = [e for e in evs if e["ph"] != "M"]
    assert evs[: len(metas)] == metas  # metadata leads after the merge
    assert {e["pid"] for e in metas if e["name"] == "process_name"} == {os.getpid(), 99999}
    assert {e["pid"] for e in timed} == {os.getpid(), 99999}
    ts = [e["ts"] for e in timed]
    assert ts == sorted(ts)  # re-sorted across processes


def test_bench_sidecar_snapshot(tmp_path):
    """Satellite: the bench writes the full labelled snapshot + flight
    summary beside its JSON output via write_snapshot(path=...)."""
    with registry.capture():
        _chain(_fresh(seed=51)).numpy()
        out = tmp_path / "BENCH_TELEMETRY.json"
        payload = aggregate.write_snapshot(path=str(out))
    assert payload is not None and out.exists()
    snap = json.loads(out.read_text())
    assert snap["metrics"]["counters"]["fusion.flushes"] >= 1
    # labels preserved — the whole point of the sidecar vs the compact block
    assert "labels" in snap["metrics"]["counters"]["fusion.flush_reason"]
    assert set(snap["flight"]) == {"enabled", "records", "evicted", "signatures",
                                   "modeled_utilization"}
    assert snap["telemetry"]["counters"]["fusion.flushes"] >= 1


def test_exporter_cli_once_over_spool(tmp_path):
    (tmp_path / "111-aaaa.json").write_text(json.dumps({
        "schema": 1, "pid": 111, "nonce": "aaaa", "time": time.time(), "seq": 2,
        "metrics": {"counters": {"fusion.flushes": 7}}, "telemetry": {}}))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "heat_tpu.monitoring.exporter",
         "--spool", str(tmp_path), "--once"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr[-800:]
    assert exporter.validate_exposition(out.stdout) == []
    assert 'heat_tpu_fusion_flushes_total{pid="111",nonce="aaaa"} 7' in out.stdout
    assert "heat_tpu_fleet_processes 1" in out.stdout
    bad = subprocess.run(
        [sys.executable, "-m", "heat_tpu.monitoring.exporter", "--bogus"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=240)
    assert bad.returncode == 2 and "usage:" in bad.stderr
