"""
Measured-autotuning suite (``heat_tpu/tuning/``, ISSUE 18).

Guarantees pinned here:

* **Off-mode inertness** (the contract): with ``HEAT_TPU_TUNING`` unset,
  no consumer ever calls :func:`tuning.lookup`, no tune file is written,
  and consumer outputs are bit-for-bit the static-knob outputs.
* **The lookup funnel**: armed lookups probe once, persist the winner
  beside the L2 dir, and every later lookup — in-process or a fresh
  process sharing the tune dir — serves from memo/disk with
  ``tuning.probed == 0`` (the cross-process acceptance bar).
* **Store lifecycle**: corrupt, truncated, foreign-fingerprint, or
  out-of-rails tune entries are quarantined into ``<tune>/quarantine/``
  (never deleted, never served, never a crash) and the lookup falls back
  to the static default.
* **Probe determinism**: under a pinned ``probe._timer`` the whole probe —
  call count, medians, winner — is deterministic, and ties keep the
  earliest candidate.
* **Tuned ≡ static semantics**: a tuned knob changes the schedule, not the
  result — bit-identical for exact dtypes, within the PR 12
  ``integrity.tolerance_for`` comparator for floats, across the
  split/ragged/dtype matrix per wired consumer.
* **Miner optimality**: mined bucket edges never use more kernels than the
  pow2 policy on the recorded mix and never pad more; the CLI prints the
  explicit-edges spec + one JSON stats line (exit 0/2).

Marked ``tuning`` for the CI smoke selection; the real-probe cross-process
leg is additionally ``slow``.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

import heat_tpu as ht
from heat_tpu import monitoring, tuning
from heat_tpu.core import fusion
from heat_tpu.core.linalg import blocked
from heat_tpu.core.pallas import flash as pflash
from heat_tpu.core.pallas import kmeans as pkmeans
from heat_tpu.core.pallas import ragged as pragged
from heat_tpu.monitoring import aggregate, registry
from heat_tpu.robustness import integrity
from heat_tpu.serving import batching as sbatching
from heat_tpu.serving import buckets as sbuckets
from heat_tpu.serving import cache as scache
from heat_tpu.serving import corpus as scorpus
from heat_tpu.tuning import knobs as tknobs
from heat_tpu.tuning import probe as tprobe
from heat_tpu.tuning import store as tstore

pytestmark = pytest.mark.tuning

_ENV = (
    "HEAT_TPU_TUNING",
    "HEAT_TPU_TUNING_DIR",
    "HEAT_TPU_TUNING_BUDGET",
    "HEAT_TPU_TUNING_MIN_SAMPLES",
    "HEAT_TPU_CACHE_DIR",
    "HEAT_TPU_SHAPE_CORPUS",
    "HEAT_TPU_TELEMETRY_DIR",
    "HEAT_TPU_SERVING_BATCH_MAX",
    "HEAT_TPU_SERVING_BATCH_LINGER_MS",
    "HEAT_TPU_FUSION_MAX_CHAIN",
    "HEAT_TPU_FUSION_CACHE",
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh memo + counters both sides; every tuning env cleared so the
    CI standing-gate leg's ambient HEAT_TPU_TUNING=1 cannot cross-couple
    tests (arming tests pin the gate themselves, the PR 5 precedent)."""
    registry.reset()
    tuning.reset()
    for k in _ENV:
        monkeypatch.delenv(k, raising=False)
    yield
    tuning.reset()
    registry.reset()


def _cnt(kind):
    return registry.REGISTRY.counter("tuning.lookup").get(label=kind)


def _fake_knob(monkeypatch, name="test.fake", value=7, default=3, fail=False):
    """Register a synthetic knob so funnel tests never pay a real probe."""
    calls = {"compute": 0}

    def compute(ctx):
        calls["compute"] += 1
        if fail:
            raise RuntimeError("probe boom")
        return value, {"budget": 1}

    knob = tknobs.Knob(
        name=name,
        kind="timed",
        grid=(1, 2, 3),
        default=default,
        compute=compute,
        normalize=lambda v: int(v),
        doc="synthetic test knob",
    )
    monkeypatch.setitem(tknobs.KNOBS, name, knob)
    return knob, calls


# --------------------------------------------------------------- the funnel
def test_lookup_off_serves_static_default_without_probe(monkeypatch, tmp_path):
    _, calls = _fake_knob(monkeypatch)
    monkeypatch.setenv("HEAT_TPU_TUNING_DIR", str(tmp_path / "tune"))
    with monitoring.capture():
        assert tuning.lookup("test.fake") == 3
    assert calls["compute"] == 0
    assert not (tmp_path / "tune").exists()  # zero files with the gate unset
    assert _cnt("probed") == 0 and _cnt("served") == 0


def test_lookup_unknown_knob_raises():
    with pytest.raises(KeyError):
        tuning.lookup("no.such.knob")


def test_funnel_probe_persist_then_disk_serve(monkeypatch, tmp_path):
    _, calls = _fake_knob(monkeypatch)
    d = tmp_path / "tune"
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")
    monkeypatch.setenv("HEAT_TPU_TUNING_DIR", str(d))
    with monitoring.capture():
        assert tuning.lookup("test.fake") == 7  # probe -> persist -> serve
        assert calls["compute"] == 1
        assert _cnt("probed") == 1 and _cnt("served") == 1
        files = [n for n in os.listdir(d) if n.endswith(".json")]
        assert len(files) == 1

        assert tuning.lookup("test.fake") == 7  # memo hit
        assert calls["compute"] == 1
        assert _cnt("served") == 2 and _cnt("probed") == 1

        tuning.reset()  # "new process": memo gone, disk entry remains
        assert tuning.lookup("test.fake") == 7
        assert calls["compute"] == 1  # disk hit — no second measurement
        assert _cnt("served") == 3 and _cnt("probed") == 1
    assert tuning.chosen() == {"test.fake": 7}


def test_funnel_failed_probe_falls_back_and_memoizes(monkeypatch, tmp_path):
    _, calls = _fake_knob(monkeypatch, fail=True)
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")
    monkeypatch.setenv("HEAT_TPU_TUNING_DIR", str(tmp_path / "tune"))
    with monitoring.capture():
        assert tuning.lookup("test.fake") == 3  # static default
        assert tuning.lookup("test.fake") == 3
    assert calls["compute"] == 1  # a knob that cannot measure is memoized
    assert _cnt("fallback") == 2 and _cnt("probed") == 0 and _cnt("served") == 0
    assert tuning.chosen() == {}  # fallbacks are not "chosen" values
    assert not (tmp_path / "tune").exists()


def test_armed_snapshot_carries_chosen_knobs(monkeypatch, tmp_path):
    _fake_knob(monkeypatch)
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")
    monkeypatch.setenv("HEAT_TPU_TUNING_DIR", str(tmp_path / "tune"))
    with monitoring.capture():
        tuning.lookup("test.fake")
        armed = aggregate.build_snapshot()
    assert armed.get("tuning") == {"test.fake": 7}
    monkeypatch.delenv("HEAT_TPU_TUNING")  # gate back off: key absent
    assert "tuning" not in aggregate.build_snapshot()


# ------------------------------------------------------------ store lifecycle
def _write_entry(d, digest, record):
    blob = scache.with_footer(json.dumps(record, sort_keys=True).encode())
    os.makedirs(d, exist_ok=True)
    with open(tstore.entry_path(str(d), digest), "wb") as f:
        f.write(blob)


def test_store_roundtrip(tmp_path):
    d = str(tmp_path / "tune")
    digest = tstore.key_digest("test.fake", (1, 2, 3), None)
    assert digest and len(digest) == 64
    assert tstore.load(d, digest) is None  # plain miss, nothing quarantined
    assert tstore.save(d, digest, "test.fake", None, 7, {"budget": 1})
    rec = tstore.load(d, digest)
    assert rec["value"] == 7 and rec["knob"] == "test.fake"
    assert rec["fingerprint"] == list(tstore.device_fingerprint())


@pytest.mark.parametrize("damage", ["corrupt", "truncated", "foreign", "layout"])
def test_store_damage_quarantines_never_serves(tmp_path, damage):
    d = str(tmp_path / "tune")
    digest = tstore.key_digest("test.fake", (1, 2, 3), None)
    path = tstore.entry_path(d, digest)
    if damage == "foreign":
        _write_entry(d, digest, {
            "format": tstore.FORMAT,
            "fingerprint": ["jax", "jaxlib", "tpu", "v999", "TPU v999"],
            "knob": "test.fake", "shape_class": None, "value": 7, "stats": {},
        })
    elif damage == "layout":
        _write_entry(d, digest, ["not", "a", "record"])
    else:
        assert tstore.save(d, digest, "test.fake", None, 7, {})
        with open(path, "rb") as f:
            blob = f.read()
        blob = blob[:40] if damage == "truncated" else blob[:-8] + b"\x00" * 8
        with open(path, "wb") as f:
            f.write(blob)
    with monitoring.capture():
        assert tstore.load(d, digest) is None  # never served, never a crash
    assert not os.path.exists(path)  # moved aside, not deleted
    q = os.listdir(os.path.join(d, "quarantine"))
    assert len(q) == 1
    assert _cnt("quarantined") == 1


def test_out_of_rails_entry_quarantined_then_remeasured(monkeypatch, tmp_path):
    _, calls = _fake_knob(monkeypatch)
    d = tmp_path / "tune"
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")
    monkeypatch.setenv("HEAT_TPU_TUNING_DIR", str(d))
    knob = tknobs.Knob(
        name="test.fake", kind="timed", grid=(1, 2, 3), default=3,
        compute=tknobs.KNOBS["test.fake"].compute,
        normalize=lambda v: (_ for _ in ()).throw(ValueError("rails"))
        if int(v) > 100 else int(v),
        doc="railed",
    )
    monkeypatch.setitem(tknobs.KNOBS, "test.fake", knob)
    digest = tstore.key_digest("test.fake", (1, 2, 3), None)
    assert tstore.save(str(d), digest, "test.fake", None, 999, {})  # poisoned
    with monitoring.capture():
        assert tuning.lookup("test.fake") == 7  # rails reject -> re-measure
    assert _cnt("quarantined") == 1 and _cnt("probed") == 1
    assert calls["compute"] == 1
    assert os.listdir(d / "quarantine")  # the poisoned entry, preserved


# --------------------------------------------------------- off-mode inertness
def test_off_mode_inertness_no_consumer_reaches_lookup(monkeypatch, tmp_path):
    """With the gate unset every wired consumer resolves its static value
    without ever calling lookup — the one-env-read contract."""
    reached = []

    def recorder(name, shape_class=None, context=None):
        reached.append(name)
        raise AssertionError("tuning.lookup reached with the gate unset")

    monkeypatch.setattr(tuning, "lookup", recorder)
    monkeypatch.setenv("HEAT_TPU_TUNING_DIR", str(tmp_path / "tune"))

    assert pflash._tile_prefs(False) == (pflash.TILE_Q, pflash.TILE_K)
    assert pragged._tile_r_pref(False) == pragged.TILE_R
    assert pkmeans._tile_n_pref(False) == pkmeans.TILE_N
    assert blocked.panel_width(512, 512) == blocked.default_panel_width(512, 512)
    for op in ("qr", "lu", "svd"):
        assert blocked._crossover(op) == blocked.CROSSOVER[op]
    assert sbuckets.effective("pow2") == sbuckets.policy("pow2")
    assert sbatching.batch_max() == 8
    assert sbatching.linger_s() == pytest.approx(0.002)
    assert fusion._max_chain() == 64
    assert fusion._cache_max() == 4096
    assert reached == []
    assert not (tmp_path / "tune").exists()  # zero tune files


def test_off_mode_inert_bitwise_parity(monkeypatch):
    """The full consumer path is bit-for-bit the pre-tuning path when off:
    the same factorization with lookup replaced by a bomb produces the
    identical bits (it is never consulted)."""
    rng = np.random.default_rng(11)
    a = jnp.asarray(rng.standard_normal((160, 96)).astype(np.float32))
    q0, r0 = blocked.qr(a)

    def bomb(name, shape_class=None, context=None):  # pragma: no cover
        raise AssertionError("lookup reached with the gate unset")

    monkeypatch.setattr(tuning, "lookup", bomb)
    q1, r1 = blocked.qr(a)
    np.testing.assert_array_equal(np.asarray(q0), np.asarray(q1))
    np.testing.assert_array_equal(np.asarray(r0), np.asarray(r1))


# ---------------------------------------------------------- probe determinism
def _scripted_timer(deltas):
    """A fake perf counter: each measure_once (two timer calls) consumes one
    scripted duration, making every probe fully deterministic."""
    it = iter(deltas)
    state = {"t": 0.0, "phase": 0}

    def timer():
        if state["phase"] == 0:
            state["phase"] = 1
            return state["t"]
        state["phase"] = 0
        state["t"] += next(it)
        return state["t"]

    return timer


def test_probe_pinned_timer_is_deterministic(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_TUNING_BUDGET", "2")
    candidates = [("a", lambda: (lambda: None)), ("b", lambda: (lambda: None))]
    # warm a, warm b (untimed values still consume deltas), then two
    # interleaved rounds: a=10, b=1 each round -> b wins with median 1.0
    # (binary-exact deltas: the fake clock must not round)
    deltas = [1.0, 1.0, 10.0, 1.0, 10.0, 1.0]
    winners = []
    for _ in range(2):
        monkeypatch.setattr(tprobe, "_timer", _scripted_timer(deltas))
        value, stats = tprobe.pick(candidates)
        winners.append(value)
        assert stats["budget"] == 2 and stats["dropped"] == 0
        assert stats["winner_median_s"] == pytest.approx(1.0)
    assert winners == ["b", "b"]  # same script, same winner, every run


def test_probe_tie_keeps_earliest_candidate(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_TUNING_BUDGET", "1")
    monkeypatch.setattr(tprobe, "_timer", _scripted_timer([1.0, 1.0, 4.0, 4.0]))
    value, _stats = tprobe.pick(
        [("a", lambda: (lambda: None)), ("b", lambda: (lambda: None))]
    )
    assert value == "a"  # strict <: a dead heat prefers grid order


def test_probe_drops_failing_builders(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_TUNING_BUDGET", "1")
    monkeypatch.setattr(tprobe, "_timer", _scripted_timer([0.1, 3.0]))

    def broken():
        raise RuntimeError("backend rejects this tile")

    value, stats = tprobe.pick([("bad", broken), ("ok", lambda: (lambda: None))])
    assert value == "ok" and stats["dropped"] == 1
    with pytest.raises(tprobe.ProbeError):
        tprobe.pick([("bad", broken)])


def test_probe_budget_floor_and_default(monkeypatch):
    monkeypatch.delenv("HEAT_TPU_TUNING_BUDGET", raising=False)
    assert tprobe.budget() == 3
    monkeypatch.setenv("HEAT_TPU_TUNING_BUDGET", "0")
    assert tprobe.budget() == 1
    monkeypatch.setenv("HEAT_TPU_TUNING_BUDGET", "junk")
    assert tprobe.budget() == 3


# -------------------------------------------------------------- mined knobs
def _write_cost_cards(base, n, ratio=8.0):
    d = os.path.join(base, "cost")
    os.makedirs(d, exist_ok=True)
    for i in range(n):
        card = {"available": True, "flops": 1.0e9,
                "bytes_accessed": ratio * 1.0e6, "output_bytes": 1.0e6}
        with open(os.path.join(d, f"card{i}.json"), "wb") as f:
            f.write(scache.with_footer(json.dumps(card).encode()))


def _write_spool_snapshot(d, coalesced, flushes_saved):
    os.makedirs(d, exist_ok=True)
    snap = {"pid": 1234, "nonce": "t", "time": 1.0, "metrics": {"counters": {
        "serving.batch": {"total": coalesced,
                          "labels": {"coalesced": coalesced,
                                     "flushes_saved": flushes_saved}}}}}
    with open(os.path.join(d, "1234-t.json"), "w") as f:
        json.dump(snap, f)


def _record_corpus(cdir, shapes, tag):
    for i, shape in enumerate(shapes):
        entry = {"leaf_descs": ((tuple(shape), "float32", False, None),)}
        assert scorpus.record(cdir, f"tuning-{tag}-{i}", entry)


def test_mined_fusion_bounds_from_cost_cards(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    _write_cost_cards(str(tmp_path), 6, ratio=8.0)
    assert tuning.lookup("fusion.max_chain") == 128  # traffic-heavy mix
    assert tuning.lookup("fusion.cache_size") == 256  # pow2ceil(12) -> floor
    assert fusion._max_chain() == 128  # the consumer serves the tuned bound
    monkeypatch.setenv("HEAT_TPU_FUSION_MAX_CHAIN", "17")
    assert fusion._max_chain() == 17  # explicit env always beats tuned


def test_mined_fusion_bounds_fall_back_on_thin_evidence(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    _write_cost_cards(str(tmp_path), 3)  # below the 4-card floor
    with monitoring.capture():
        assert tuning.lookup("fusion.max_chain") == 64
    assert _cnt("fallback") == 1 and _cnt("probed") == 0


def test_mined_batching_from_spool(monkeypatch, tmp_path):
    spool = str(tmp_path / "spool")
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")
    monkeypatch.setenv("HEAT_TPU_TELEMETRY_DIR", spool)
    _write_spool_snapshot(spool, coalesced=40, flushes_saved=35)  # g = 8
    assert tuning.lookup("serving.batching.linger_ms") == 2.0
    assert tuning.lookup("serving.batching.max") == 16  # pow2ceil(16)
    assert sbatching.batch_max() == 16
    monkeypatch.setenv("HEAT_TPU_SERVING_BATCH_MAX", "5")
    assert sbatching.batch_max() == 5  # explicit env always wins


def test_mined_batching_thin_spool_keeps_defaults(monkeypatch, tmp_path):
    spool = str(tmp_path / "spool")
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")
    monkeypatch.setenv("HEAT_TPU_TELEMETRY_DIR", spool)
    _write_spool_snapshot(spool, coalesced=4, flushes_saved=2)  # < min_samples
    assert sbatching.batch_max() == 8
    assert sbatching.linger_s() == pytest.approx(0.002)


def test_mined_bucket_edges_refine_armed_policy(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("HEAT_TPU_TUNING_MIN_SAMPLES", "4")
    cdir = scorpus.corpus_dir(str(tmp_path))
    _record_corpus(str(tmp_path), [(200,), (200,), (384,), (1000,)], "edges")
    dims = sbuckets.corpus_dims(cdir)
    mined = sbuckets.mine_edges(dims)
    edges, tail = sbuckets.effective("pow2")
    assert edges == mined and tail == mined[-1]
    assert sbuckets.effective("0") is None  # tuning never forces bucketing on
    assert tuning.lookup("serving.buckets.edges") == mined


# ---------------------------------------------------------- miner optimality
@pytest.mark.parametrize("dims", [
    {200: 3, 130: 1, 384: 2, 1000: 1},
    {64: 10, 65: 10, 1023: 1},
    {7: 1, 9: 2, 15: 4, 17: 8, 4096: 1},
    {512: 5},
])
def test_mined_edges_dominate_pow2(dims):
    pow2 = tuple(sorted({sbuckets._pow2_edge(d) for d in dims}))
    mined = sbuckets.mine_edges(dims)
    assert mined[-1] == max(dims)  # every recorded dim is covered
    assert len(mined) <= len(pow2)
    assert sbuckets.waste_of(dims, mined, mined[-1]) <= sbuckets.waste_of(
        dims, pow2, pow2[-1]
    )


def test_mined_edges_respect_explicit_k():
    dims = {100: 4, 300: 2, 900: 1}
    assert sbuckets.mine_edges(dims, k=1) == (900,)
    assert len(sbuckets.mine_edges(dims, k=2)) <= 2


def test_miner_cli_spec_and_stats(tmp_path):
    cdir = scorpus.corpus_dir(str(tmp_path))
    _record_corpus(str(tmp_path), [(384, 200), (384,), (130,), (1000,)], "cli")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "heat_tpu.serving.buckets", "--from-corpus", cdir],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stderr
    spec_line, stats_line = out.stdout.strip().splitlines()[-2:]
    edges = tuple(int(e) for e in spec_line.split(","))
    assert edges == tuple(sorted(edges)) and edges[-1] == 1000
    stats = json.loads(stats_line)
    assert tuple(stats["edges"]) == edges
    assert stats["kernel_count"] <= stats["pow2_kernel_count"]
    assert stats["pad_waste"] <= stats["pow2_pad_waste"]
    # the spec round-trips through the explicit-edges policy parser
    assert sbuckets.policy(spec_line) == (edges, edges[-1])

    missing = subprocess.run(
        [sys.executable, "-m", "heat_tpu.serving.buckets",
         "--from-corpus", str(tmp_path / "nope")],
        capture_output=True, text=True, env=env,
    )
    assert missing.returncode == 2
    assert "error" in json.loads(missing.stdout.strip().splitlines()[-1])


# ------------------------------------------------- cross-process acceptance
_MINED_SCRIPT = """
import json
from heat_tpu import monitoring, tuning
from heat_tpu.monitoring import registry

with monitoring.capture():
    vals = {}
    for name in ("fusion.max_chain", "fusion.cache_size",
                 "serving.buckets.edges"):
        vals[name] = tuning._jsonable(tuning.lookup(name))
    c = registry.REGISTRY.counter("tuning.lookup")
    print(json.dumps({"values": vals,
                      "probed": c.get(label="probed"),
                      "served": c.get(label="served"),
                      "fallback": c.get(label="fallback")}))
"""


def _run_lookup_process(env):
    out = subprocess.run(
        [sys.executable, "-c", _MINED_SCRIPT],
        capture_output=True, text=True, env=env,
    )
    assert out.returncode == 0, out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_second_process_serves_with_zero_probes(tmp_path):
    """The acceptance bar: a fresh process sharing the tune dir serves every
    knob from disk — ``tuning.probed == 0``."""
    base = str(tmp_path)
    _write_cost_cards(base, 6, ratio=8.0)
    _record_corpus(base, [(200,), (200,), (384,), (1000,)], "xproc")
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", HEAT_TPU_TUNING="1",
        HEAT_TPU_CACHE_DIR=base, HEAT_TPU_TUNING_MIN_SAMPLES="4",
    )
    env.pop("HEAT_TPU_TUNING_DIR", None)
    first = _run_lookup_process(env)
    assert first["probed"] == 3 and first["served"] == 3
    assert first["fallback"] == 0
    tune_files = [n for n in os.listdir(os.path.join(base, "tune"))
                  if n.endswith(".json")]
    assert len(tune_files) == 3

    second = _run_lookup_process(env)
    assert second["probed"] == 0  # every knob served from the shared dir
    assert second["served"] == 3 and second["fallback"] == 0
    assert second["values"] == first["values"]


_TIMED_SCRIPT = """
import json
from heat_tpu import monitoring, tuning
from heat_tpu.monitoring import registry

LOOKUPS = [
    ("pallas.flash.tile", None, {"interpret": True}),
    ("pallas.ragged.tile_r", None, {"interpret": True}),
    ("pallas.kmeans.tile_n", None, {"interpret": True}),
    ("linalg.blocked.panel", 128, {"m": 128, "n": 128, "k_bucket": 128}),
    ("linalg.blocked.crossover.qr", None, None),
]
with monitoring.capture():
    vals = {}
    for name, sc, ctx in LOOKUPS:
        vals[name] = tuning._jsonable(tuning.lookup(name, sc, ctx))
    c = registry.REGISTRY.counter("tuning.lookup")
    print(json.dumps({"values": vals,
                      "probed": c.get(label="probed"),
                      "served": c.get(label="served")}))
"""


@pytest.mark.slow
def test_second_process_serves_timed_knobs_with_zero_probes(tmp_path):
    """The full-acceptance variant with REAL probes (budget 1, interpret
    mode): pallas tiles, the panel width, and the qr crossover are measured
    once, persisted, and a second process serves them all from disk."""
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", HEAT_TPU_TUNING="1",
        HEAT_TPU_TUNING_BUDGET="1", HEAT_TPU_TUNING_DIR=str(tmp_path),
        HEAT_TPU_PALLAS_INTERPRET="1",
    )
    out = subprocess.run([sys.executable, "-c", _TIMED_SCRIPT],
                         capture_output=True, text=True, env=env, timeout=560)
    assert out.returncode == 0, out.stderr
    first = json.loads(out.stdout.strip().splitlines()[-1])
    assert first["probed"] == 5 and first["served"] == 5

    out2 = subprocess.run([sys.executable, "-c", _TIMED_SCRIPT],
                          capture_output=True, text=True, env=env, timeout=560)
    assert out2.returncode == 0, out2.stderr
    second = json.loads(out2.stdout.strip().splitlines()[-1])
    assert second["probed"] == 0 and second["served"] == 5
    assert second["values"] == first["values"]


# ------------------------------------------------- tuned-vs-static semantics
def _force_tuned(monkeypatch, forced):
    """Arm the gate and pin lookup to forced (non-default) knob values —
    the differential isolates the *value change*, not the probe."""
    monkeypatch.setenv("HEAT_TPU_TUNING", "1")

    def fake_lookup(name, shape_class=None, context=None):
        if name in forced:
            return forced[name]
        return tknobs.get(name).static_default(context)

    monkeypatch.setattr(tuning, "lookup", fake_lookup)


def _match_tree(got, ref):
    got = got if isinstance(got, (tuple, list)) else (got,)
    ref = ref if isinstance(ref, (tuple, list)) else (ref,)
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        assert integrity.outputs_match(np.asarray(g), np.asarray(r))


@pytest.mark.parametrize("shape,dtype", [
    # f32 below the static crossover: the tuned run (crossover 16) engages
    # the blocked kernel where the static run rides jnp.linalg.qr
    ((160, 96), np.float32),
    ((150, 91), np.float32),  # ragged: min-dim not a panel multiple
    # bf16 above the crossover (CPU lapack has no bf16 qr to fall back to):
    # both runs are blocked — the differential isolates the panel change
    ((192, 160), jnp.bfloat16),
    ((190, 149), jnp.bfloat16),
])
def test_tuned_vs_static_differential_blocked(monkeypatch, shape, dtype):
    """A tuned panel width + a lowered crossover change which kernel runs,
    never what it computes: tuned blocked output matches the static path
    under the PR 12 comparator."""
    rng = np.random.default_rng(17)
    a = jnp.asarray(rng.standard_normal(shape).astype(np.float32)).astype(dtype)
    static = blocked.qr(a)
    _force_tuned(monkeypatch, {
        "linalg.blocked.panel": 32,
        "linalg.blocked.crossover.qr": 16,  # tuned run engages blocked
    })
    tuned = blocked.qr(a)
    _match_tree(tuned, static)


@pytest.mark.parametrize("split", [None, 0, 1])
def test_tuned_vs_static_differential_split_matrix(monkeypatch, split):
    """The split matrix: a tuned panel on the distributed TSQR path matches
    the static-panel result per the comparator at every split."""
    rng = np.random.default_rng(23)
    # tall + divisible so split=0 takes the real TSQR path and split=1 the
    # BCGS2 path on the 8-device test mesh (no gathered fallback)
    a_np = rng.standard_normal((512, 64)).astype(np.float32)
    q0, r0 = ht.linalg.qr(ht.array(a_np, split=split))
    static = (q0.numpy(), r0.numpy())
    _force_tuned(monkeypatch, {"linalg.blocked.panel": 32})
    q1, r1 = ht.linalg.qr(ht.array(a_np, split=split))
    _match_tree((q1.numpy(), r1.numpy()), static)


def test_tuned_vs_static_differential_flash_tile(monkeypatch):
    rng = np.random.default_rng(29)
    bh, s, d = 1, 256, 64
    q = jnp.asarray(rng.standard_normal((bh, s, d)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((bh, s, d)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((bh, s, d)).astype(np.float32))
    pos = jnp.arange(s, dtype=jnp.int32)
    m = jnp.full((bh, s), -jnp.inf, jnp.float32)
    l = jnp.zeros((bh, s), jnp.float32)
    o = jnp.zeros((bh, s, d), jnp.float32)

    def run():
        return pflash.tile_update(q, k, v, m, l, o, scale=0.125, causal=False,
                                  q_pos=pos, k_pos=pos, interpret=True)

    static = run()
    _force_tuned(monkeypatch, {"pallas.flash.tile": (64, 64)})
    tuned = run()
    _match_tree(tuned, static)


@pytest.mark.parametrize("dt_str", ["float32", "bfloat16"])
def test_tuned_vs_static_differential_ragged_tile(monkeypatch, dt_str):
    rng = np.random.default_rng(31)
    r, c, bound = 512, 128, 488
    x_np = rng.standard_normal((r, c)).astype(np.float32)
    x_np[bound:] = 0.0  # padded rows are neutral-filled by the wrapper
    x = jnp.asarray(x_np).astype(jnp.dtype(dt_str))

    def run(tile_r):
        call = pragged._reduce_call("sum", r, c, tile_r, dt_str, bound, c,
                                    "all", False, False, True)
        return call(x)

    _match_tree(run(256), run(128))  # tuned tile vs the static 128


def test_tuned_vs_static_differential_kmeans_tile():
    rng = np.random.default_rng(37)
    n, f, k, bound = 512, 32, 8, 500
    x_np = rng.standard_normal((n, f)).astype(np.float32)
    x_np[bound:] = 0.0
    x = jnp.asarray(x_np)
    centers = jnp.asarray(rng.standard_normal((k, f)).astype(np.float32))

    def run(tile_n):
        return pkmeans._step_call(n, f, k, "float32", bound, tile_n, True)(
            x, centers
        )

    tuned, static = run(256), run(128)
    _match_tree(tuned, static)  # labels bit-equal (int), sums/counts bounded
