"""
End-to-end fused-transformer suite (``heat_tpu/nn/transformer.py`` +
``heat_tpu/optim/fused_sgd.py`` + the wrapper-aware donation and
app-rebuilder rails, ISSUE 20).

Guarantees pinned here:

* **One fused executable per step** (the tentpole): a steady-state train
  step materializes as exactly ONE flush with a flat
  ``fusion.kernels_compiled`` counter after warmup, zero
  ``flush_reason{collective}`` ticks, and ``fusion.donated{steady_state}``
  growing by exactly 2 per step — the packed ``theta``/``mu`` buffers
  re-donating on every trace-cache hit (the multi-consumer leaf case the
  widened ``_donatable`` wrapper bound admits).
* **Fused ≡ eager** (the acceptance bar): losses and logits match the
  per-op eager reference (``HEAT_TPU_TRANSFORMER`` unset — the SAME
  memoized callables dispatched standalone) across split {None, 0, 1} ×
  even/ragged × f32/bf16, within ``integrity.tolerance_for``; the same
  matrix runs clean (zero mismatches) under the standing shadow-replay
  audit at rate 1 with action=raise.
* **Cross-process warm start**: the train-step signature lands in the L2
  shape corpus; ``serving.warmup`` rebuilds it in a process that never
  imported the recorder (the app-rebuilder registry), and a restarted
  worker replaying the loop against the warmed cache compiles ZERO kernels.
* **Tuning rails**: the ``transformer.mlp.tile`` / ``pallas.flash.train_tile``
  knobs enforce their rails, and with the gate unset no consumer ever
  reaches ``tuning.lookup`` (the lookup-bomb inertness contract).
* **Default off**: with ``HEAT_TPU_TRANSFORMER`` unset, ``train_step``
  runs the eager reference (no transformer flush, no donation tick) and a
  standard fused workload is byte-identical whether or not the knob exists.

The heavy train-loop and DASO legs are marked ``slow`` to protect the
tier-1 wall-clock budget; the CI ``transformer-smoke`` job runs the WHOLE
marker (slow included) plus the elastic kill -9 smoke script.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import factories, fusion
from heat_tpu.monitoring import registry
from heat_tpu.nn import transformer as tf
from heat_tpu.robustness import faultinject, integrity

pytestmark = pytest.mark.transformer

#: tiny geometry for the differential matrices (one block keeps the
#: value_and_grad compile cheap on the CPU tier-1 host)
SMALL = dict(vocab=32, dim=16, heads=2, depth=1, mlp_ratio=2, max_seq=16)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh counters/caches; the transformer knob is deliberately left at
    its default (off) — engagement-asserting tests pin it ON themselves
    (the PR 5/8 pin-the-gate precedent)."""
    registry.reset()
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    monkeypatch.delenv("HEAT_TPU_TRANSFORMER", raising=False)
    monkeypatch.delenv("HEAT_TPU_TRANSFORMER_SEED", raising=False)
    monkeypatch.delenv("HEAT_TPU_CACHE_DIR", raising=False)
    monkeypatch.delenv("HEAT_TPU_SHAPE_BUCKETS", raising=False)
    monkeypatch.delenv("HEAT_TPU_TUNING", raising=False)
    monkeypatch.delenv("HEAT_TPU_FLIGHT", raising=False)
    fusion.clear_cache()
    yield
    fusion.clear_cache()
    registry.reset()


@pytest.fixture
def no_faults(monkeypatch):
    """Pin injection/chaos/breakers/audit off for count-asserting tests
    (the PR 6/9/12 precedent)."""
    from heat_tpu.robustness import breaker

    monkeypatch.delenv("HEAT_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("HEAT_TPU_CHAOS", raising=False)
    monkeypatch.delenv("HEAT_TPU_BREAKER_FORCE_OPEN", raising=False)
    monkeypatch.delenv("HEAT_TPU_AUDIT_RATE", raising=False)
    monkeypatch.delenv("HEAT_TPU_AUDIT_ACTION", raising=False)
    faultinject.clear()
    breaker.reset()
    fusion.clear_cache()


@pytest.fixture
def tf_on(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_TRANSFORMER", "1")
    # CPU test host: force admits the donation mask so the bookkeeping
    # (and its refcount tripwire) is exercised; jax ignores the mask on
    # CPU with a warning and results are bit-identical
    monkeypatch.setenv("HEAT_TPU_FUSION_DONATE", "force")


def _compiles() -> int:
    return registry.REGISTRY.counter("fusion.kernels_compiled").get()


def _batch(cfg, B, S, seed=5, split=None):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, cfg.vocab, (B, S), dtype=np.int64).astype(np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)
    if split is None:
        return x, y
    return factories.array(x, split=split), factories.array(y, split=split)


# ------------------------------------------------------------------ config
def test_config_validation():
    with pytest.raises(ValueError):
        tf.TransformerConfig(dtype="float16")
    with pytest.raises(ValueError):
        tf.TransformerConfig(dim=30, heads=4)
    cfg = tf.TransformerConfig(**SMALL)
    assert cfg.head_dim == 8
    assert tf.param_count(cfg) > 0


def test_layout_contiguous_and_tree_views_match_packed():
    cfg = tf.TransformerConfig(**SMALL)
    lay, total = tf._layout(cfg.vocab, cfg.dim, cfg.heads, cfg.depth,
                            cfg.mlp_ratio, cfg.max_seq)
    off = 0
    for _name, shape, o, size in lay:
        assert o == off and size == int(np.prod(shape))
        off += size
    assert off == total == tf.param_count(cfg)
    # the DP/DASO pytree is a view of the SAME seeded packed init
    flat = tf._init_flat(cfg)
    tree = tf.init_tree(cfg)
    for name, shape, o, size in lay:
        np.testing.assert_array_equal(
            np.asarray(tree[name], np.float32),
            flat[o:o + size].reshape(shape),
        )


# -------------------------------------------------------- fused ≡ eager
def _matrix_params(fast):
    """The full split {None,0,1} × even/ragged × f32/bf16 matrix; combos
    outside ``fast`` ride the CI ``transformer-smoke`` job (slow-marked)
    to protect the tier-1 wall clock — the fast subset keeps one fused
    even leg per dtype and the ragged eager-fallthrough leg in tier-1."""
    out = []
    for split in (None, 0, 1):
        for shape, sid in (((8, 16), "even"), ((3, 11), "ragged")):
            for dtype, did in (("float32", "f32"), ("bfloat16", "bf16")):
                combo = (split, sid, did)
                out.append(pytest.param(
                    split, shape, dtype,
                    id=f"{did}-{sid}-{split}",
                    marks=() if combo in fast else (pytest.mark.slow,),
                ))
    return out


_DIFF_FAST = {(None, "even", "f32"), (None, "ragged", "f32"),
              (None, "even", "bf16")}
_AUDIT_FAST = {(None, "even", "f32"), (None, "even", "bf16")}


def _run_matrix(cfg, split, B, S, steps=2):
    state = tf.init_state(cfg)
    x, y = _batch(cfg, B, S, split=split)
    losses = []
    for _ in range(steps):
        loss, state = tf.train_step(state, x, y)
        losses.append(tf.read_loss(loss))
    logits = tf.read_logits(tf.infer_step(state, x))
    return losses, logits


@pytest.mark.parametrize("split,shape,dtype", _matrix_params(_DIFF_FAST))
def test_fused_matches_eager_matrix(monkeypatch, no_faults, split, shape,
                                    dtype):
    """The acceptance differential: the fused one-executable step's loss
    trajectory and the no-grad logits match the eager per-op reference
    within the PR 12 comparator tolerances (exact where the recorded and
    eager paths coincide)."""
    cfg = tf.TransformerConfig(dtype=dtype, **SMALL)
    B, S = shape
    monkeypatch.setenv("HEAT_TPU_TRANSFORMER", "1")
    monkeypatch.setenv("HEAT_TPU_FUSION_DONATE", "force")
    fused_losses, fused_logits = _run_matrix(cfg, split, B, S)
    fusion.clear_cache()
    monkeypatch.delenv("HEAT_TPU_TRANSFORMER")
    eager_losses, eager_logits = _run_matrix(cfg, split, B, S)
    tol = integrity.tolerance_for(cfg.jnp_dtype) or 1e-6
    np.testing.assert_allclose(fused_losses, eager_losses, rtol=tol, atol=tol)
    np.testing.assert_allclose(
        fused_logits, eager_logits, rtol=tol,
        atol=tol * max(1.0, float(np.max(np.abs(eager_logits)))),
    )


# -------------------------------------------- one executable per step
def test_steady_state_one_executable_zero_compiles(tf_on, no_faults):
    """The tentpole regression: after warmup every train step is ONE flush,
    ZERO fresh compiles, ZERO collective chain breaks — and the packed
    theta+mu pair re-donates (exactly 2 buffers) on every trace-cache hit."""
    with registry.capture():
        compiles = registry.REGISTRY.counter("fusion.kernels_compiled")
        flushes = registry.REGISTRY.counter("fusion.flushes")
        reasons = registry.REGISTRY.counter("fusion.flush_reason")
        donated = registry.REGISTRY.counter("fusion.donated")
        tfc = registry.REGISTRY.counter("nn.transformer")

        cfg = tf.TransformerConfig(**SMALL)
        state = tf.init_state(cfg)
        x, y = _batch(cfg, 4, 16)
        per_step = []
        losses = []
        for _ in range(8):
            c0, f0, d0 = compiles.get(), flushes.get(), donated.get("steady_state")
            loss, state = tf.train_step(state, x, y)
            losses.append(tf.read_loss(loss))
            per_step.append(
                (compiles.get() - c0, flushes.get() - f0,
                 donated.get("steady_state") - d0)
            )
        assert all(c == 0 for c, _, _ in per_step[2:]), per_step
        assert all(f == 1 for _, f, _ in per_step), per_step
        # the re-donation regression, extended to the train loop (PR 19
        # precedent): exactly theta+mu per steady step, never less
        assert [d for _, _, d in per_step[2:]] == [2] * 6, per_step
        assert reasons.get("collective") == 0
        assert reasons.get("transformer") == 8
        assert tfc.get("step-fused") == 8 and tfc.get("step-eager") == 0
        assert losses[-1] < losses[0] and np.isfinite(losses[-1])


def test_infer_steady_state_zero_compiles(tf_on, no_faults):
    with registry.capture():
        cfg = tf.TransformerConfig(**SMALL)
        state = tf.init_state(cfg)
        x, _ = _batch(cfg, 4, 16)
        tf.read_logits(tf.infer_step(state, x))
        before = _compiles()
        out = [tf.read_logits(tf.infer_step(state, x)) for _ in range(3)]
        assert _compiles() == before
        for o in out[1:]:
            assert o.tobytes() == out[0].tobytes()


def test_checkpoint_roundtrip_resumes_identically(tf_on, no_faults):
    """PR 6 wiring: a state serialized mid-train and restored continues
    with a bit-identical packed vector and the same loss trajectory."""
    cfg = tf.TransformerConfig(**SMALL)
    state = tf.init_state(cfg)
    x, y = _batch(cfg, 4, 16)
    for _ in range(3):
        loss, state = tf.train_step(state, x, y)
        tf.read_loss(loss)
    snap = state.checkpoint_state()
    restored = tf.TrainState.from_checkpoint(snap, cfg)
    assert restored.step == state.step == 3
    np.testing.assert_array_equal(
        np.asarray(restored.theta.larray, np.float32),
        np.asarray(state.theta.larray, np.float32),
    )
    la, ra = state, restored
    for _ in range(2):
        l1, la = tf.train_step(la, x, y)
        l2, ra = tf.train_step(ra, x, y)
        assert abs(tf.read_loss(l1) - tf.read_loss(l2)) < 1e-6


# ------------------------------------------------------------- audit leg
@pytest.mark.parametrize("split,shape,dtype", _matrix_params(_AUDIT_FAST))
def test_audit_clean_train_step_zero_mismatches(monkeypatch, split, shape,
                                                dtype):
    """The shadow-replay correctness leg: a full fused transformer step
    (grad + momentum + update + loss sink) under ``HEAT_TPU_AUDIT_RATE=1``
    with ``ACTION=raise`` completes with ZERO mismatches — any divergence
    between the fused program and its eager replay raises."""
    monkeypatch.setenv("HEAT_TPU_TRANSFORMER", "1")
    monkeypatch.setenv("HEAT_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("HEAT_TPU_AUDIT_ACTION", "raise")
    cfg = tf.TransformerConfig(dtype=dtype, **SMALL)
    B, S = shape
    with registry.capture():
        state = tf.init_state(cfg)
        x, y = _batch(cfg, B, S, split=split)
        loss, state = tf.train_step(state, x, y)
        assert np.isfinite(tf.read_loss(loss))
        ic = registry.REGISTRY.counter("robustness.integrity")
        if split is None or B % 8 == 0 or (split == 1 and S % 8 == 0):
            assert ic.get("audit") >= 1  # the fused chain WAS audited
        assert ic.get("mismatch") == 0


# ------------------------------------------------------------ tuning rails
def test_mlp_tile_knob_rails():
    from heat_tpu.tuning import knobs

    k = knobs.get("transformer.mlp.tile")
    assert k.normalize(128) == 128
    assert k.default == 128
    for bad in (7, 9, 4, 8192):
        with pytest.raises(ValueError):
            k.normalize(bad)


def test_flash_train_tile_knob_rails():
    from heat_tpu.tuning import knobs

    k = knobs.get("pallas.flash.train_tile")
    assert k.normalize((128, 128)) == (128, 128)
    assert k.default == (128, 128)
    for bad in ((7, 128), (128, 12), (0, 0)):
        with pytest.raises(ValueError):
            k.normalize(bad)


def test_off_mode_lookup_bomb_inert(monkeypatch, no_faults):
    """With the tuning gate unset neither the MLP-tile nor the flash
    train-tile consumer ever reaches ``tuning.lookup`` — and the fused
    step's result is byte-identical to the pre-knob path."""
    from heat_tpu import tuning
    from heat_tpu.core.pallas import flash as pflash

    monkeypatch.setenv("HEAT_TPU_TRANSFORMER", "1")
    cfg = tf.TransformerConfig(**SMALL)
    state = tf.init_state(cfg)
    x, y = _batch(cfg, 4, 16)
    loss, _ = tf.train_step(state, x, y)
    base = tf.read_loss(loss)

    def bomb(name, shape_class=None, context=None):  # pragma: no cover
        raise AssertionError("tuning.lookup reached with the gate unset")

    monkeypatch.setattr(tuning, "lookup", bomb)
    assert tf._mlp_tile_pref() == 128
    assert pflash._train_tile_pref(False) is None
    fusion.clear_cache()
    state = tf.init_state(cfg)
    loss, _ = tf.train_step(state, x, y)
    assert tf.read_loss(loss) == base


def test_flash_train_tile_pref_served_when_armed(monkeypatch):
    """Gate on: the training-shape flash call consults the train-tile knob
    (context-keyed on interpret) and applies the served pair."""
    from heat_tpu import tuning
    from heat_tpu.core.pallas import flash as pflash

    seen = []

    def lookup(name, shape_class=None, context=None):
        seen.append((name, dict(context or {})))
        return (64, 64)

    monkeypatch.setattr(tuning, "enabled", lambda: True)
    monkeypatch.setattr(tuning, "lookup", lookup)
    assert pflash._train_tile_pref(True) == (64, 64)
    assert seen == [("pallas.flash.train_tile", {"interpret": True})]


# ------------------------------------------------------------- off = inert
def test_off_knob_train_step_is_eager_reference(no_faults):
    """Knob off: ``train_step`` never records a fused chain — no
    transformer flush, no donation, the loss concrete immediately — and
    still trains (loss falls)."""
    assert not tf.enabled()
    with registry.capture():
        cfg = tf.TransformerConfig(**SMALL)
        state = tf.init_state(cfg)
        x, y = _batch(cfg, 4, 16)
        losses = []
        for _ in range(3):
            loss, state = tf.train_step(state, x, y)
            losses.append(tf.read_loss(loss))
        reasons = registry.REGISTRY.counter("fusion.flush_reason")
        tfc = registry.REGISTRY.counter("nn.transformer")
        assert reasons.get("transformer") == 0
        assert registry.REGISTRY.counter("fusion.donated").get("buffers") == 0
        assert tfc.get("step-eager") == 3 and tfc.get("step-fused") == 0
        assert losses[-1] < losses[0]


def test_off_knob_standard_workload_byte_identical(monkeypatch, no_faults):
    """The off-inertness differential: a standard fused workload's results
    and compile counts are byte-identical whether the transformer knob is
    absent or armed — arming it must not perturb non-transformer flushes."""

    def work():
        x = ht.arange(48, dtype=ht.float32, split=0).reshape((6, 8))
        y = ht.sin(x * 2.0 + 1.0) / 3.0
        return np.asarray(y.larray).tobytes()

    monkeypatch.delenv("HEAT_TPU_TRANSFORMER", raising=False)
    with registry.capture():
        fusion.clear_cache()
        base = work()
        base_compiles = _compiles()
    registry.reset()
    monkeypatch.setenv("HEAT_TPU_TRANSFORMER", "1")
    with registry.capture():
        fusion.clear_cache()
        armed = work()
        armed_compiles = _compiles()
    assert base == armed
    assert base_compiles == armed_compiles


# --------------------------------------------------- warmup + corpus
def test_warmup_rebuilds_train_step_from_corpus(monkeypatch, tmp_path,
                                                tf_on, no_faults):
    """The app-rebuilder satellite: the recorded train-step sink lands in
    the L2 shape corpus, and ``serving.warmup`` rebuilds it into a FRESH
    cache through the registered ``("transformer", opname)`` hooks — zero
    errors, nothing skipped as unbuildable."""
    from heat_tpu import serving
    from heat_tpu.serving import corpus as scorpus

    warm = tmp_path / "warm"
    cold = tmp_path / "cold"
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(warm))
    scorpus._seen.clear()
    cfg = tf.TransformerConfig(**SMALL)
    state = tf.init_state(cfg)
    x, y = _batch(cfg, 4, 16)
    for _ in range(3):
        loss, state = tf.train_step(state, x, y)
        tf.read_loss(loss)
    assert scorpus.size(str(warm / "corpus")) >= 1
    stats = serving.warmup(corpus=str(warm / "corpus"), cache_dir=str(cold))
    assert stats["errors"] == 0
    assert stats["compiled"] >= 1


@pytest.mark.slow
def test_cross_process_warm_restart_zero_compiles(tmp_path):
    """ISSUE 20 satellite 6: a restarted worker replaying the train loop
    against a warmed ``HEAT_TPU_CACHE_DIR`` reaches steady state at ZERO
    compiles (PR 17/19 precedent, extended to the train-step signature)."""
    script = (
        "import numpy as np\n"
        "from heat_tpu.nn import transformer as tf\n"
        "from heat_tpu.monitoring import registry\n"
        "registry.enable()\n"
        "cfg = tf.TransformerConfig(vocab=32, dim=16, heads=2, depth=1,"
        " mlp_ratio=2, max_seq=16)\n"
        "state = tf.init_state(cfg)\n"
        "rng = np.random.default_rng(5)\n"
        "x = rng.integers(0, cfg.vocab, (4, 16), dtype=np.int64).astype(np.int32)\n"
        "y = np.roll(x, -1, axis=1).astype(np.int32)\n"
        "for _ in range(4):\n"
        "    loss, state = tf.train_step(state, x, y)\n"
        "    tf.read_loss(loss)\n"
        "print('COMPILES', registry.REGISTRY.counter('fusion.kernels_compiled').get())\n"
    )
    env = dict(os.environ)
    env.update({
        "HEAT_TPU_TRANSFORMER": "1",
        "HEAT_TPU_FUSION_DONATE": "force",
        "HEAT_TPU_CACHE_DIR": str(tmp_path / "l2"),
        "JAX_PLATFORMS": "cpu",
    })
    first = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert first.returncode == 0, first.stderr[-2000:]
    assert "COMPILES" in first.stdout
    assert "COMPILES 0" not in first.stdout  # the cold process compiled
    second = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert second.returncode == 0, second.stderr[-2000:]
    assert "COMPILES 0" in second.stdout


# --------------------------------------------------------- trainer legs
def test_data_parallel_trainer_leg(no_faults):
    """The DP adapter: TransformerModule's flax-free init/apply under
    DataParallel trains the tree-form model (loss finite, step counted)."""
    import optax

    cfg = tf.TransformerConfig(**SMALL)
    module = tf.TransformerModule(cfg)
    dp = ht.nn.DataParallel(module, optimizer=optax.sgd(0.1, momentum=0.9))
    dp.init(0, np.zeros((2, 8), np.int32))
    dp.make_train_step(tf.tree_loss)
    x, y = _batch(cfg, 8, 8)
    losses = [float(dp.train_step(x, y)) for _ in range(3)]
    assert all(np.isfinite(v) for v in losses)
    assert dp.step_count == 3


@pytest.mark.slow
def test_daso_two_tier_trainer_leg(no_faults):
    """The DASO adapter: the hierarchical trainer over the two-tier
    ICI/DCN comm (local/global split pinned to ``comm.tiers``) trains the
    same tree-form model."""
    import optax

    from heat_tpu.core.communication import MeshCommunication

    cfg = tf.TransformerConfig(**SMALL)
    module = tf.TransformerModule(cfg)
    comm = MeshCommunication.two_tier(ici=4, dcn=2)
    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(0.1, momentum=0.9), total_epochs=1,
        comm=comm, warmup_epochs=0, cooldown_epochs=0,
    )
    assert (daso.nodes, daso.local_size) == (2, 4)
    daso.init(tf.init_tree(cfg))
    daso.make_train_step(tf.tree_loss, module.apply)
    x, y = _batch(cfg, 8, 8)
    losses = [float(daso.step(x, y)) for _ in range(3)]
    assert all(np.isfinite(v) for v in losses)
    assert daso.step_count == 3


@pytest.mark.slow
def test_transformer_smoke_script_passes(tmp_path):
    """The CI smoke entry point end-to-end: fused steady-state checks plus
    the elastic kill -9 drain/save/restore-shrunk choreography."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "transformer_smoke.py"),
         "--steps", "6"],
        env=env, capture_output=True, text=True, timeout=580,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert "all checks passed" in proc.stdout
