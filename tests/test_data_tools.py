"""
Deep coverage of the data pipeline (reference heat/utils/data/tests):
Dataset/DataLoader semantics, shuffle behaviors, PartialH5Dataset out-of-core
windows with the native prefetcher, and the loader iterators' batch policies.
"""

import numpy as np
import pytest

import heat_tpu as ht


def test_dataset_transform_and_shuffle():
    data = np.arange(64.0, dtype=np.float32).reshape(16, 4)
    ds = ht.utils.data.Dataset(ht.array(data, split=0), transform=lambda x: x * 2.0)
    assert len(ds) == 16
    np.testing.assert_allclose(np.asarray(ds[0]), data[0] * 2.0)
    before = np.asarray(ds.data).copy()
    ds.Shuffle()
    after = np.asarray(ds.data)
    assert sorted(after[:, 0].tolist()) == sorted(before[:, 0].tolist())  # permutation
    ds.Ishuffle()  # non-blocking variant must also keep the multiset


def test_dataloader_batches_cover_dataset():
    data = np.arange(60.0, dtype=np.float32).reshape(20, 3)
    dl = ht.utils.data.DataLoader(ht.array(data, split=0), batch_size=6, shuffle=False)
    seen = []
    for batch in dl:
        b = np.asarray(batch)
        assert b.shape[1] == 3
        seen.extend(b[:, 0].tolist())
    assert len(seen) in (18, 20)  # drop_last policy may drop the ragged tail
    assert len(set(seen)) == len(seen)
    assert len(dl) >= 3


def test_partial_h5_dataset_window_iteration(tmp_path):
    h5py = pytest.importorskip("h5py")
    path = str(tmp_path / "oo.h5")
    n, f = 64, 5
    data = np.arange(n * f, dtype=np.float32).reshape(n, f)
    with h5py.File(path, "w") as fh:
        fh.create_dataset("data", data=data)
        fh.create_dataset("labels", data=(np.arange(n) % 3).astype(np.int64))

    ds = ht.utils.data.PartialH5Dataset(
        path, use_gpu=False, dataset_names=["data", "labels"],
        initial_load=16, load_length=16,
    )
    try:
        assert len(ds) > 0
        first = ds[0]
        assert first is not None
        ds.load_next_group()
        loader = ht.utils.data.PartialH5DataLoaderIter(ds, batch_size=8)
        rows = 0
        for batch in loader:
            xb = batch[0] if isinstance(batch, (tuple, list)) else batch
            rows += np.asarray(xb).shape[0]
            if rows >= 16:
                break
        assert rows >= 8
    finally:
        ds.close()
    # double-close must be safe (drain lifecycle)
    ds.close()


@pytest.mark.parametrize("use_native", [True, False])
def test_partial_h5_window_advance_values(tmp_path, monkeypatch, use_native):
    """Value-exact window semantics through both read paths: steady-state
    in-place advance (drop oldest load_len rows, append slab) and the ragged
    final slab that shrinks the window (reference partial_dataset.py:120-180)."""
    h5py = pytest.importorskip("h5py")
    import heat_tpu.native as native_mod

    if not use_native:
        monkeypatch.setattr(native_mod, "available", lambda: False)
    path = str(tmp_path / "adv.h5")
    n, f = 50, 3
    data = np.arange(n * f, dtype=np.float32).reshape(n, f)
    with h5py.File(path, "w") as fh:
        fh.create_dataset("data", data=data)
    ds = ht.utils.data.PartialH5Dataset(
        path, use_gpu=False, dataset_names=["data"], initial_load=32, load_length=16
    )
    try:
        if not use_native:
            assert ds._prefetchers is None  # forced onto the h5py path
        elif native_mod.available():
            assert ds._prefetchers is not None  # native pread path engaged
        np.testing.assert_array_equal(ds._window["data"], data[:32])
        ds.load_next_group(); ds.load_queue.join()
        np.testing.assert_array_equal(ds._window["data"], data[16:48])
        ds.load_next_group(); ds.load_queue.join()  # ragged slab: rows 48:50
        np.testing.assert_array_equal(ds._window["data"], data[32:50])
    finally:
        ds.close()
