"""
The public testing-utilities surface (heat_tpu/testing.py — VERDICT r3 #8,
parity with reference heat/core/tests/test_suites/basic_test.py and its own
test file test_suites/test_basic_test.py): the helpers must be importable from
the installed package and must actually detect value, shape, and placement
errors — a green helper that can't fail protects nothing.
"""

import unittest

import numpy as np
import pytest

import heat_tpu as ht
import heat_tpu.testing as htt


def test_importable_from_package():
    # the installed-package path, not a tests/-private helper
    import importlib

    mod = importlib.import_module("heat_tpu.testing")
    for name in mod.__all__:
        assert hasattr(mod, name)


@pytest.mark.parametrize("split", [None, 0, 1])
def test_assert_array_equal_passes(split):
    a = np.arange(42, dtype=np.float32).reshape(6, 7)
    htt.assert_array_equal(ht.array(a, split=split), a)


@pytest.mark.parametrize("shape", [(13, 3), (8, 5), (7,)])
def test_assert_array_equal_ragged_and_1d(shape):
    a = np.arange(np.prod(shape), dtype=np.float32).reshape(shape)
    htt.assert_array_equal(ht.array(a, split=0), a)


def test_assert_array_equal_detects_value_mismatch():
    a = np.ones((5, 4), dtype=np.float32)
    with pytest.raises(AssertionError):
        htt.assert_array_equal(ht.array(a, split=0), a * 2)


def test_assert_array_equal_detects_shape_mismatch():
    a = np.ones((5, 4), dtype=np.float32)
    with pytest.raises(AssertionError):
        htt.assert_array_equal(ht.array(a), np.ones((4, 5), dtype=np.float32))


def test_assert_array_equal_rejects_non_dndarray():
    with pytest.raises(AssertionError):
        htt.assert_array_equal(np.ones(3), np.ones(3))


def test_assert_func_equal_elementwise_and_reduction():
    htt.assert_func_equal((4, 6), ht.exp, np.exp, rtol=1e-4, data_types=(np.float32,))
    htt.assert_func_equal(
        (9,), lambda x: ht.sum(x), np.sum, rtol=1e-4, data_types=(np.int32, np.float32)
    )


def test_assert_func_equal_detects_wrong_function():
    with pytest.raises(AssertionError):
        htt.assert_func_equal(
            (4, 4), ht.exp, np.log1p, rtol=1e-4, data_types=(np.float32,)
        )


def test_assert_func_equal_for_tensor_with_args():
    a = np.arange(24, dtype=np.float32).reshape(4, 6)
    htt.assert_func_equal_for_tensor(
        a,
        lambda x, **kw: ht.sum(x, **kw),
        np.sum,
        heat_args={"axis": 0},
        numpy_args={"axis": 0},
        rtol=1e-5,
    )


def test_default_dtypes_x64_aware():
    import jax

    dts = htt.default_dtypes()
    if jax.config.read("jax_enable_x64"):
        assert np.float64 in dts and np.int64 in dts
    else:
        # no silently-truncating 64-bit entries on the default path
        assert np.float64 not in dts and np.int64 not in dts
    assert np.float32 in dts and np.int32 in dts


def test_all_splits():
    assert htt.all_splits(2) == (None, 0, 1)
    assert htt.all_splits(0) == (None,)


def test_random_array_seeded():
    a = htt.random_array((5, 5), np.int32, seed=3)
    b = htt.random_array((5, 5), np.int32, seed=3)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.int32
    f = htt.random_array((5, 5), np.float32, seed=3)
    assert f.dtype == np.float32


class TestCaseSurface(htt.TestCase):
    """The unittest base class works as the reference's does
    (basic_test.py:12; tested like test_suites/test_basic_test.py)."""

    def test_comm_and_device(self):
        assert self.get_size() >= 1
        assert self.get_rank() == 0  # single controller
        assert self.comm is not None
        assert self.device is not None

    def test_methods_delegate(self):
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        self.assert_array_equal(ht.array(a, split=1), a)
        self.assert_func_equal_for_tensor(a, ht.sqrt, np.sqrt, rtol=1e-4)


def test_testcase_runs_under_unittest():
    suite = unittest.TestLoader().loadTestsFromTestCase(TestCaseSurface)
    result = unittest.TextTestRunner(verbosity=0).run(suite)
    assert result.wasSuccessful()
