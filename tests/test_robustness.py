"""
Fault-injection differential suite for the graceful-degradation runtime
(ISSUE 6: ``heat_tpu/robustness/`` + the fused-flush recovery ladder).

The guarantees pinned here:

* **Determinism.** Every fault plan fires by call count only — the same plan
  always fails the same calls, programmatic or env-driven — and with no plan
  installed the hooks are inert (no counting, no behavior change).
* **Fused-flush recovery ladder.** An injected ``fusion.compile`` /
  ``fusion.execute`` fault during a flush never raises to the caller: the
  result is bit-identical to ``HEAT_TPU_FUSION=0`` (per-op eager replay of
  the retained DAG), the failure/recovery/poisoning counters increment
  exactly as attributed, and a repeat of the same chain takes the
  poisoned-signature fast path without consulting the fault sites again.
* **IO.** Saves are write-then-rename atomic (a failing save never truncates
  an existing file), transient ``OSError`` is retried with bounded backoff
  (``io.retries{site}``), and non-transient exceptions propagate on the first
  try.
* **Checkpoints.** Per-leaf checksums catch bit flips; ``restore_latest_valid``
  walks back over corrupt/truncated newer files; orphaned tempfiles are
  cleaned at manager startup.
* **Preemption.** ``kill -TERM`` during a data-parallel / DASO / kmeans /
  lasso loop produces a valid checkpoint at the next step boundary with exact
  RNG/step state, and the loops stop cooperatively.
"""

import os
import signal
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import heat_tpu as ht
from heat_tpu import monitoring
from heat_tpu.core import fusion
from heat_tpu.monitoring import registry, report
from heat_tpu.nn.data_parallel import DataParallel
from heat_tpu.optim.dp_optimizer import DASO
from heat_tpu.robustness import breaker, chaos, faultinject, preemption, retry
from heat_tpu.utils.checkpoint import (
    CheckpointCorruptError,
    CheckpointManager,
    load_checkpoint,
    save_checkpoint,
    validate_checkpoint,
)

pytestmark = pytest.mark.robustness


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    registry.reset()
    faultinject.clear()
    monkeypatch.delenv("HEAT_TPU_FAULT_PLAN", raising=False)
    # this suite schedules its own faults/chaos/breaker states — standing CI
    # envs (the fault-plan leg precedent, extended to the ISSUE 9 chaos and
    # forced-open legs) are pinned off so every count assertion is exact
    monkeypatch.delenv("HEAT_TPU_CHAOS", raising=False)
    monkeypatch.delenv("HEAT_TPU_BREAKER_FORCE_OPEN", raising=False)
    monkeypatch.delenv("HEAT_TPU_IO_RETRY_BUDGET_MS", raising=False)
    # ISSUE 12: the integrity-smoke legs' standing knobs change flush paths
    monkeypatch.delenv("HEAT_TPU_AUDIT_RATE", raising=False)
    monkeypatch.delenv("HEAT_TPU_COLLECTIVE_CHECKSUM", raising=False)
    breaker.reset()
    # keep the deterministic backoff schedule but don't spend wall time on it
    monkeypatch.setenv("HEAT_TPU_IO_RETRY_DELAY", "0.001")
    fusion.clear_cache()
    yield
    faultinject.clear()
    breaker.reset()
    registry.reset()


def _bitwise_equal(a: np.ndarray, b: np.ndarray) -> bool:
    return a.shape == b.shape and a.dtype == b.dtype and a.tobytes() == b.tobytes()


# ------------------------------------------------------------------ fault injection
def test_sites_inert_without_plan():
    assert not faultinject.active()
    for site in faultinject.SITES:
        faultinject.check(site)  # no plan: must not raise...
        assert faultinject.call_count(site) == 0  # ...and must not even count


def test_programmatic_plan_is_deterministic_by_call_count():
    with faultinject.inject("io.write", ValueError, at_calls=[2, 4]) as plan:
        fired = []
        for call in range(1, 6):
            try:
                faultinject.check("io.write")
                fired.append(False)
            except ValueError:
                fired.append(True)
        assert fired == [False, True, False, True, False]
        assert plan.fired == [2, 4]
        assert faultinject.call_count("io.write") == 5
    # the context manager removed the plan: the site is inert again
    faultinject.check("io.write")
    assert not faultinject.active()


def test_inject_validates_site_and_raises_instance_verbatim():
    with pytest.raises(ValueError):
        faultinject.inject("no.such.site", RuntimeError)
    exc = RuntimeError("RESOURCE_EXHAUSTED: fake")
    with faultinject.inject("io.read", exc, at_calls="*"):
        with pytest.raises(RuntimeError) as ei:
            faultinject.check("io.read")
        assert ei.value is exc


def test_env_plan_parses_fires_and_counts(monkeypatch):
    monkeypatch.setenv(
        "HEAT_TPU_FAULT_PLAN",
        "io.write:OSError@1,3;checkpoint.write:RuntimeError(RESOURCE_EXHAUSTED)@2+",
    )
    assert faultinject.active()
    outcomes = []
    for _ in range(4):
        try:
            faultinject.check("io.write")
            outcomes.append(None)
        except OSError:
            outcomes.append("os")
    assert outcomes == ["os", None, "os", None]
    faultinject.check("checkpoint.write")  # call 1: below the 2+ threshold
    for _ in range(2):  # calls 2 and 3 both fire
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            faultinject.check("checkpoint.write")
    # sites without an env entry stay inert and uncounted
    faultinject.check("io.read")
    assert faultinject.call_count("io.read") == 0


def test_env_plan_rejects_malformed_entries(monkeypatch):
    for bad in ("fusion.compile", "no.site:OSError@1", "io.write:NoSuchExc@1"):
        monkeypatch.setenv("HEAT_TPU_FAULT_PLAN", bad)
        with pytest.raises(faultinject.FaultPlanError):
            faultinject.check("io.write")
        monkeypatch.setenv("HEAT_TPU_FAULT_PLAN", "")  # reset the parse cache


def test_malformed_plan_is_a_config_error_not_a_recoverable_fault(monkeypatch):
    # the ladder absorbs injected FAILURES; a broken plan must surface loudly
    # instead of silently demoting every flush to eager replay
    a = ht.ones((4, 3), split=0)
    a.parray  # noqa: B018
    monkeypatch.setenv("HEAT_TPU_FAULT_PLAN", "fusion.compile:NoSuchExc@1")
    with monitoring.capture():
        registry.reset()
        with pytest.raises(faultinject.FaultPlanError):
            (a + 1.0).numpy()
        snap = registry.snapshot()["counters"]
    assert "fusion.flush_recovered" not in snap


def test_collective_dispatch_site_fires_deterministically():
    a = ht.ones((8, 3), split=0)
    with faultinject.inject("collective.dispatch", RuntimeError, at_calls=[2]):
        _ = a.comm.Allreduce(a.larray)  # call 1: runs
        with pytest.raises(RuntimeError):
            a.comm.Allreduce(a.larray)  # call 2: injected
        _ = a.comm.Allreduce(a.larray)  # call 3: runs again


def test_collective_bearing_flush_recovers_through_ladder(monkeypatch):
    # ISSUE 7: a collective RECORDED in a fused flush consults the
    # collective.dispatch site on the fused attempt (where the ICI dispatch
    # now lives) and a failure there rides the recovery ladder — per-op eager
    # replay of the retained chain plus the collective's own cached program —
    # instead of surfacing as a raw crash; results stay bit-identical to the
    # HEAT_TPU_FUSION_COLLECTIVES=0 barrier path
    if not ht.get_comm().is_distributed():
        pytest.skip("resharding requires a multi-device mesh")
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    rng = np.random.default_rng(47)
    arr = rng.standard_normal((16, 8)).astype(np.float32)

    def run():
        a = ht.array(arr, split=0)
        a.parray  # noqa: B018
        y = (a + 1.0) * 2.0
        y.resplit_(1)
        return (y - 0.5).numpy()

    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "0")
    ref = run()
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "1")
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        with faultinject.inject("collective.dispatch", RuntimeError, at_calls=[1]) as plan:
            got = run()
            assert plan.fired == [1]
        snap = registry.snapshot()["counters"]
    assert _bitwise_equal(got, ref)
    assert snap["fusion.flush_recovered"] == 1
    assert snap["faults.injected"]["labels"] == {"collective.dispatch": 1}


# ------------------------------------------------------------------ recovery ladder
def _ladder_workload(a, b):
    # elementwise chain + view + GEMM epilogue + sink: every node kind rides
    # the same flush, so one recovered flush covers the whole DAG surface
    y = (a + 1.5) * b
    y = ht.abs(y).T[1:, :]
    return y.sum(axis=0)


def test_injected_compile_fault_never_raises_and_poisons(monkeypatch):
    # acceptance: an injected fusion.compile fault during a fused flush never
    # raises; the result is bit-identical to HEAT_TPU_FUSION=0;
    # fusion.flush_recovered increments; a repeat of the same chain hits the
    # poisoned-signature fast path (no second retry, no second fault check)
    rng = np.random.default_rng(3)
    a = ht.array(rng.standard_normal((12, 6)).astype(np.float32), split=0)
    b = ht.array(rng.standard_normal((12, 6)).astype(np.float32), split=0)
    a.parray, b.parray  # noqa: B018

    monkeypatch.setenv("HEAT_TPU_FUSION", "0")
    ref = _ladder_workload(a, b).numpy()
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")

    with monitoring.capture():
        registry.reset()
        with faultinject.inject("fusion.compile", RuntimeError, at_calls=[1]) as plan:
            got = _ladder_workload(a, b).numpy()
            assert plan.fired == [1]
            repeat = _ladder_workload(a, b).numpy()
            # the poisoned fast path never consulted the fault site again
            assert faultinject.call_count("fusion.compile") == 1
        snap = registry.snapshot()["counters"]
    assert _bitwise_equal(got, ref)
    assert _bitwise_equal(repeat, ref)
    assert snap["fusion.flush_failures"]["labels"] == {"compile": 1}
    assert snap["fusion.flush_recovered"] == 1
    assert snap["fusion.poisoned_signatures"] == 1
    assert snap["faults.injected"]["labels"] == {"fusion.compile": 1}
    assert fusion.cache_info()["poisoned"] >= 1


def test_execute_fault_with_oom_signature_counts_oom(monkeypatch):
    a = ht.ones((6, 4), split=0)
    a.parray  # noqa: B018
    with monitoring.capture():
        registry.reset()
        exc = RuntimeError("RESOURCE_EXHAUSTED: out of memory while allocating")
        with faultinject.inject("fusion.execute", exc, at_calls=[1]):
            got = ((a * 3.0) - 1.0).numpy()
        snap = registry.snapshot()["counters"]
    assert _bitwise_equal(got, np.full((6, 4), 2.0, np.float32))
    assert snap["fusion.flush_failures"]["labels"] == {"oom": 1}
    assert snap["fusion.flush_recovered"] == 1


def test_ladder_rung2_retries_with_donation_disabled():
    # unit-level: when the failed flush HAD donated buffers, the ladder's
    # second rung rebuilds the kernel donation-free before giving up on fused
    # execution — recovery at rung 2 does not poison the signature
    program = [(jnp.add, (("l", 0), ("l", 1)), {}, None)]
    leaves = [jnp.ones((3,), jnp.float32), jnp.full((3,), 2.0, jnp.float32)]

    def broken_fused(*args):
        raise RuntimeError("compile blew up")

    with monitoring.capture():
        registry.reset()
        values = fusion._flush_ladder(
            broken_fused, program, leaves, (0,), (0,), True, None
        )
        snap = registry.snapshot()["counters"]
    np.testing.assert_array_equal(np.asarray(values[0]), np.full((3,), 3.0))
    assert snap["fusion.flush_failures"]["total"] == 1
    assert snap["fusion.flush_recovered"] == 1
    assert "fusion.poisoned_signatures" not in snap
    assert fusion.cache_info()["poisoned"] == 0


def test_standing_env_compile_plan_keeps_results_bit_identical(monkeypatch):
    # the CI robustness leg in miniature: with EVERY fused compile failing,
    # the whole op surface must still produce HEAT_TPU_FUSION=0 results
    rng = np.random.default_rng(11)
    a = ht.array(rng.standard_normal((10, 8)).astype(np.float32), split=0)
    b = ht.array(rng.standard_normal((10, 8)).astype(np.float32), split=0)
    w = ht.array(rng.standard_normal((8, 5)).astype(np.float32))
    a.parray, b.parray, w.parray  # noqa: B018
    workloads = [
        lambda: ht.sqrt(ht.abs(a * b) + 1.0) - 0.5,
        lambda: ((a + b) * 2.0).T[2:, :],
        lambda: ht.where(a > 0, a, b) / 3.0,
        lambda: (ht.abs(a) + 1.0).sum(axis=1),
        lambda: ht.tanh(a @ w + 0.25),
    ]
    for i, fn in enumerate(workloads):
        monkeypatch.delenv("HEAT_TPU_FAULT_PLAN", raising=False)
        monkeypatch.setenv("HEAT_TPU_FUSION", "0")
        ref = fn().numpy()
        monkeypatch.setenv("HEAT_TPU_FUSION", "1")
        monkeypatch.setenv("HEAT_TPU_FAULT_PLAN", "fusion.compile:RuntimeError@*")
        got = fn().numpy()
        assert _bitwise_equal(got, ref), f"workload {i} diverged under standing plan"


# ------------------------------------------------------------------ retry policy
def test_retry_policy_backoff_schedule_is_deterministic():
    pol = retry.RetryPolicy(max_attempts=4, base_delay=0.1, multiplier=2.0, max_delay=0.3)
    assert [pol.delay(k) for k in (1, 2, 3)] == [0.1, 0.2, 0.3]
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky, site="unit", sleep=slept.append) == "ok"
    assert calls["n"] == 3
    assert slept == [0.1, 0.2]


def test_retry_policy_exhaustion_and_selectivity():
    pol = retry.RetryPolicy(max_attempts=2, base_delay=0.0)
    calls = {"n": 0}

    def always_os():
        calls["n"] += 1
        raise OSError("persistent")

    with pytest.raises(OSError):
        pol.call(always_os, sleep=lambda _t: None)
    assert calls["n"] == 2  # bounded
    calls["n"] = 0

    def type_err():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        pol.call(type_err, sleep=lambda _t: None)
    assert calls["n"] == 1  # non-retry_on exceptions propagate immediately


# ------------------------------------------------------------------ atomic IO
def test_csv_save_retries_transient_and_never_truncates(tmp_path):
    a = ht.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    path = str(tmp_path / "x.csv")
    with monitoring.capture():
        registry.reset()
        with faultinject.inject("io.write", OSError, at_calls=[1]):
            ht.save_csv(a, path)  # first attempt faulted, retry landed it
        snap = registry.snapshot()["counters"]
    assert snap["io.retries"]["labels"] == {"save_csv": 1}
    assert np.allclose(ht.load_csv(path).numpy(), a.numpy())

    # a persistent failure exhausts the retries and raises — but the
    # write-then-rename idiom leaves the existing file byte-for-byte intact
    with faultinject.inject("io.write", OSError, at_calls="*"):
        with pytest.raises(OSError):
            ht.save_csv(a * 2.0, path)
    assert np.allclose(ht.load_csv(path).numpy(), a.numpy())
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


def test_csv_load_retries_transient(tmp_path):
    a = ht.array(np.arange(4, dtype=np.float32).reshape(2, 2))
    path = str(tmp_path / "y.csv")
    ht.save_csv(a, path)
    with monitoring.capture():
        registry.reset()
        with faultinject.inject("io.read", OSError, at_calls=[1]):
            b = ht.load_csv(path)
        snap = registry.snapshot()["counters"]
    assert np.allclose(b.numpy(), a.numpy())
    assert snap["io.retries"]["labels"] == {"load_csv": 1}


@pytest.mark.skipif(not ht.io.supports_hdf5(), reason="h5py not available")
def test_hdf5_save_is_atomic_under_midwrite_death(tmp_path):
    a = ht.array(np.arange(24, dtype=np.float32).reshape(6, 4), split=0)
    path = str(tmp_path / "x.h5")
    ht.save_hdf5(a, path, "data")
    # non-transient mid-write death on every attempt: the tempfile is
    # discarded, the existing file (and its readable dataset) survive
    with faultinject.inject("io.write", ValueError, at_calls="*"):
        with pytest.raises(ValueError):
            ht.save_hdf5(a * 7.0, path, "data")
    b = ht.load_hdf5(path, "data", split=0)
    assert _bitwise_equal(b.numpy(), a.numpy())
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


# ------------------------------------------------------------------ checkpoint integrity
def _state(v: float, split=0):
    return {
        "w": ht.array(np.full((6, 2), v, np.float32), split=split),
        "k": jnp.asarray([v], jnp.float32),
        "step": int(v),
    }


def test_checksum_detects_bitflip_and_manager_falls_back(tmp_path):
    import h5py

    mgr = CheckpointManager(str(tmp_path), max_to_keep=4)
    mgr.save(1, _state(1.0))
    mgr.save(2, _state(2.0))
    latest = mgr._path(2)
    with h5py.File(latest, "r+") as f:  # bit flip inside a valid hdf5 file
        f["w"][0, 0] = 777.0
    assert not validate_checkpoint(latest)
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(latest, _state(0.0))
    with monitoring.capture():
        registry.reset()
        restored = mgr.restore_latest_valid(_state(0.0))
        snap = registry.snapshot()["counters"]
    assert mgr.last_restored_step == 1
    assert restored["step"] == 1
    assert np.allclose(restored["w"].numpy(), 1.0)
    assert snap["checkpoint.ops"]["labels"].get("corrupt-skipped", 0) >= 1
    assert snap["checkpoint.ops"]["labels"].get("restore", 0) == 1


def test_truncated_partial_checkpoint_is_skipped(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(5, _state(5.0))
    mgr.save(9, _state(9.0))
    latest = mgr._path(9)
    size = os.path.getsize(latest)
    with open(latest, "r+b") as f:  # a writer killed mid-write (no h5 footer)
        f.truncate(size // 2)
    assert not validate_checkpoint(latest)
    assert mgr.latest_valid_step() == 5
    restored = mgr.restore_latest_valid(_state(0.0))
    assert restored["step"] == 5


def test_orphaned_tempfiles_cleaned_at_startup(tmp_path):
    (tmp_path / "tmpdead1.ckpt.tmp").write_bytes(b"partial")
    (tmp_path / "tmpdead2.ckpt.tmp").write_bytes(b"partial")
    with monitoring.capture():
        registry.reset()
        CheckpointManager(str(tmp_path))
        snap = registry.snapshot()["counters"]
    assert snap["checkpoint.ops"]["labels"]["orphan-cleaned"] == 2
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".ckpt.tmp")]


def test_checkpoint_write_fault_retried_then_atomic_on_hard_failure(tmp_path):
    path = str(tmp_path / "c.h5")
    with monitoring.capture():
        registry.reset()
        with faultinject.inject("checkpoint.write", OSError, at_calls=[1]):
            save_checkpoint(path, _state(3.0))
        snap = registry.snapshot()["counters"]
    assert snap["io.retries"]["labels"] == {"checkpoint.write": 1}
    assert snap["checkpoint.ops"]["labels"]["write"] == 1
    assert validate_checkpoint(path)
    # hard failure: the established checkpoint survives, no tempfile litter
    with faultinject.inject("checkpoint.write", ValueError, at_calls="*"):
        with pytest.raises(ValueError):
            save_checkpoint(path, _state(4.0))
    restored = load_checkpoint(path, _state(0.0))
    assert restored["step"] == 3
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".ckpt.tmp")]


# ------------------------------------------------------------------ preemption
class _TinyModule:
    """Minimal init/apply pair (a linear layer) for the trainer wrappers."""

    def init(self, rng, x):
        del rng
        return {"w": jnp.zeros((x.shape[1], 1), jnp.float32)}

    def apply(self, params, x):
        return x @ params["w"]


def _mse(params, apply_fn, x, y):
    return jnp.mean((apply_fn(params, x) - y) ** 2)


def _batch(n=16, f=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, f)).astype(np.float32)
    y = (x @ rng.standard_normal((f, 1))).astype(np.float32)
    return x, y


def test_sigterm_during_data_parallel_training_leaves_valid_checkpoint(tmp_path):
    # acceptance: kill -TERM mid-training produces a checkpoint from which
    # restore_latest_valid resumes with exact RNG/step state; a deliberately
    # corrupted latest checkpoint is skipped for the previous valid one
    import h5py

    x, y = _batch()
    dp = DataParallel(_TinyModule(), optimizer=optax.sgd(0.1))
    dp.init(0, x)
    dp.make_train_step(_mse)
    mgr = CheckpointManager(str(tmp_path), max_to_keep=5)

    with preemption.PreemptionGuard(manager=mgr) as guard:
        dp.train_step(x, y)
        dp.train_step(x, y)
        mgr.save(dp.step_count, dp.checkpoint_state())  # periodic checkpoint
        rng_before = ht.random.get_state()
        os.kill(os.getpid(), signal.SIGTERM)  # the preemption notice
        dp.train_step(x, y)  # the next step boundary lands the checkpoint
        assert guard.handled and guard.saved_step == 3
        assert preemption.stop_requested()  # the user loop breaks here
    assert validate_checkpoint(mgr._path(3))
    saved_params = jax.tree.map(np.asarray, dp.params)

    # scramble the live state, then resume from the preemption checkpoint
    ht.random.seed(12345)
    dp.train_step(x, y)
    restored = mgr.restore_latest_valid(dp.checkpoint_state())
    dp.load_state(restored)
    assert mgr.last_restored_step == 3
    assert dp.step_count == 3
    for got, want in zip(
        jax.tree.leaves(jax.tree.map(np.asarray, dp.params)),
        jax.tree.leaves(saved_params),
    ):
        assert _bitwise_equal(np.asarray(got), np.asarray(want))
    # exact RNG resume: the stream continues from the save point
    assert tuple(ht.random.get_state()) == tuple(rng_before)

    # corrupt the latest: restore_latest_valid falls back to the step-2 save
    with h5py.File(mgr._path(3), "r+") as f:
        f["params/w"][0, 0] = 1e9
    restored = mgr.restore_latest_valid(dp.checkpoint_state())
    assert mgr.last_restored_step == 2
    dp.load_state(restored)
    assert dp.step_count == 2


def test_sigterm_during_daso_training_checkpoints_at_step_boundary(tmp_path):
    x, y = _batch(n=16)
    daso = DASO(optax.sgd(0.05), total_epochs=4, warmup_epochs=0, cooldown_epochs=0)
    params = {"w": jnp.zeros((x.shape[1], 1), jnp.float32)}
    daso.init(params)
    daso.make_train_step(_mse, _TinyModule().apply)
    mgr = CheckpointManager(str(tmp_path))

    with preemption.PreemptionGuard(manager=mgr) as guard:
        daso.step(x, y)
        guard.trigger(signal.SIGTERM)  # deterministic in-test injection
        daso.step(x, y)
        assert guard.handled and guard.saved_step == 2
    restored = mgr.restore_latest_valid(daso.checkpoint_state())
    daso.load_state(restored)
    assert daso.step_count == 2 and restored["epoch"] == daso.epoch


def test_preemption_guard_checkpoints_kmeans_fit(tmp_path):
    rng = np.random.default_rng(21)
    X = ht.array(rng.standard_normal((64, 4)).astype(np.float32), split=0)
    mgr = CheckpointManager(str(tmp_path))
    from heat_tpu.cluster import KMeans

    with preemption.PreemptionGuard(manager=mgr) as guard:
        guard.trigger()
        km = KMeans(n_clusters=3, max_iter=50, random_state=0).fit(X)
    assert guard.handled and guard.saved_step == 1
    assert km._n_iter == 1  # the fit stopped at the checkpointed boundary
    target = {"centers": jnp.zeros((3, 4), jnp.float32), "iteration": 0}
    restored = mgr.restore_latest_valid(target)
    assert restored["iteration"] == 1
    assert np.asarray(restored["centers"]).shape == (3, 4)


def test_preemption_guard_checkpoints_lasso_fit(tmp_path):
    rng = np.random.default_rng(23)
    X = ht.array(rng.standard_normal((32, 5)).astype(np.float32))
    ydat = ht.array(rng.standard_normal((32, 1)).astype(np.float32))
    mgr = CheckpointManager(str(tmp_path))
    from heat_tpu.regression import Lasso

    with preemption.PreemptionGuard(manager=mgr) as guard:
        guard.trigger()
        est = Lasso(lam=0.05, max_iter=50, tol=0.0).fit(X, ydat)
    assert guard.handled and guard.saved_step == 1
    assert est.n_iter == 1
    target = {"theta": jnp.zeros((6,), jnp.float32), "sweep": 0}
    restored = mgr.restore_latest_valid(target)
    assert restored["sweep"] == 1


def test_guard_restores_signal_handlers_and_nests():
    prev_term = signal.getsignal(signal.SIGTERM)
    prev_int = signal.getsignal(signal.SIGINT)
    with preemption.PreemptionGuard() as outer:
        assert preemption.active() is outer
        with preemption.PreemptionGuard() as inner:
            assert preemption.active() is inner  # innermost wins
            assert not preemption.should_checkpoint()
            inner.trigger()
            assert preemption.should_checkpoint()
            # no manager attached: handling degrades to a pure stop flag
            assert preemption.checkpoint_now({"x": 1}, step=7) is None
            assert not preemption.should_checkpoint()
            assert preemption.stop_requested()
        assert preemption.active() is outer
    assert preemption.active() is None
    assert signal.getsignal(signal.SIGTERM) is prev_term
    assert signal.getsignal(signal.SIGINT) is prev_int


def test_preemption_request_counter_labelled_by_signal():
    with monitoring.capture():
        registry.reset()
        with preemption.PreemptionGuard() as guard:
            guard.trigger(signal.SIGTERM)
        snap = registry.snapshot()["counters"]
    assert snap["preemption.requests"]["labels"] == {"SIGTERM": 1}


# ------------------------------------------------------------------ telemetry
def test_telemetry_exports_robustness_counters(tmp_path):
    a = ht.ones((6, 3), split=0)
    a.parray  # noqa: B018
    path = str(tmp_path / "t.csv")
    with monitoring.capture():
        registry.reset()
        with faultinject.inject("fusion.compile", RuntimeError, at_calls=[1]):
            _ = (a + 2.0).numpy()
        with faultinject.inject("io.write", OSError, at_calls=[1]):
            ht.save_csv(a, path)
        save_checkpoint(str(tmp_path / "c.h5"), {"s": 1})
        tele = report.telemetry()
    assert tele["fusion_flush_failures"] == {"compile": 1}
    assert tele["fusion_flush_recovered"] == 1
    assert tele["fusion_poisoned_signatures"] == 1
    assert tele["io_retries"] == {"save_csv": 1}
    assert tele["checkpoint_ops"]["write"] == 1
    assert tele["faults_injected"] == {"fusion.compile": 1, "io.write": 1}


# ------------------------------------------------------------------ circuit breakers
def test_breaker_state_machine_is_deterministic_by_calls(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("HEAT_TPU_BREAKER_COOLDOWN", "2")
    with monitoring.capture():
        registry.reset()
        b = breaker.breaker("io.write")
        assert b.state() == "closed" and b.allow()
        # two failures + a success: consecutive count resets, stays closed
        b.record_failure(); b.record_failure(); b.record_success()
        assert b.state() == "closed"
        # three consecutive failures open it
        for _ in range(3):
            b.record_failure()
        assert b.state() == "open"
        # cool-down measured in refused calls: the call that exhausts it is
        # granted as the half-open probe
        assert not b.allow()
        assert b.allow() and b.state() == "half-open"
        # a failed probe re-opens; the next cool-down replays identically
        b.record_failure()
        assert b.state() == "open"
        assert not b.allow()
        assert b.allow() and b.state() == "half-open"
        b.record_success()
        assert b.state() == "closed" and b.allow()
        snap = registry.snapshot()["counters"]["robustness.breaker"]["labels"]
    assert snap == {
        "io.write:open": 2,
        "io.write:half-open": 2,
        "io.write:closed": 1,
    }


def test_breaker_disabled_and_forced_open_envs(monkeypatch):
    b = breaker.breaker("io.read")
    monkeypatch.setenv("HEAT_TPU_BREAKERS", "0")
    for _ in range(50):
        b.record_failure()
    assert b.state() == "closed" and b.allow()  # disabled: inert
    monkeypatch.delenv("HEAT_TPU_BREAKERS")
    monkeypatch.setenv("HEAT_TPU_BREAKER_FORCE_OPEN", "io.read")
    assert b.state() == "forced-open" and not b.allow()
    assert breaker.breaker("io.write").allow()  # only the named site is pinned
    monkeypatch.setenv("HEAT_TPU_BREAKER_FORCE_OPEN", "*")
    assert not breaker.breaker("io.write").allow()
    with pytest.raises(ValueError):
        breaker.breaker("no.such.site")


def test_open_compile_breaker_routes_to_eager_replay(monkeypatch):
    """After N consecutive compile failures the breaker opens and L1-miss
    flushes skip the doomed fused attempt — no fault-site consult, results
    bit-identical to HEAT_TPU_FUSION=0 — until the half-open probe."""
    monkeypatch.setenv("HEAT_TPU_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("HEAT_TPU_BREAKER_COOLDOWN", "100")
    rng = np.random.default_rng(3)
    datas = [rng.normal(size=(4, 3 + k)).astype(np.float32) for k in range(4)]
    with monitoring.capture():
        registry.reset()
        with faultinject.inject("fusion.compile", RuntimeError, at_calls="*"):
            outs = []
            for d in datas:  # distinct shapes: every flush is an L1 miss
                outs.append(((ht.array(d) * 2.0 + 1.0) / 3.0).numpy())
            fired = faultinject.call_count("fusion.compile")
        assert breaker.breaker("fusion.compile").state() == "open"
        # the first two flushes attempted (and recovered through the ladder);
        # the rest were routed straight to eager replay without consulting
        # the site at all
        assert fired == 2
        assert registry.REGISTRY.counter("fusion.flush_recovered").get() == 2
        snap = registry.snapshot()["counters"]["robustness.breaker"]["labels"]
        assert snap["fusion.compile:open"] == 1
    monkeypatch.setenv("HEAT_TPU_FUSION", "0")
    for d, out in zip(datas, outs):
        ref = ((ht.array(d) * 2.0 + 1.0) / 3.0).numpy()
        assert _bitwise_equal(out, ref)


def test_compile_breaker_half_open_probe_recloses(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("HEAT_TPU_BREAKER_COOLDOWN", "1")
    rng = np.random.default_rng(5)
    with monitoring.capture():
        registry.reset()
        with faultinject.inject("fusion.compile", RuntimeError, at_calls=[1]):
            # flush 1: fails, recovers, opens the breaker (threshold 1)
            x = ht.array(rng.normal(size=(3, 5)).astype(np.float32))
            (x + 1.0).numpy()
            assert breaker.breaker("fusion.compile").state() == "open"
            # flush 2 (cool-down 1): granted as the probe, plan is spent, the
            # compile succeeds and the breaker closes again
            y = ht.array(rng.normal(size=(3, 6)).astype(np.float32))
            (y + 1.0).numpy()
        assert breaker.breaker("fusion.compile").state() == "closed"


def test_io_breaker_collapses_retry_to_single_attempt(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_BREAKER_THRESHOLD", "2")
    pol = retry.RetryPolicy(max_attempts=3, base_delay=0.0)
    calls = {"n": 0}

    def always_os():
        calls["n"] += 1
        raise OSError("persistent")

    # two exhausted calls (2 + 1 attempts): consecutive failures open the
    # write breaker after the threshold is reached mid-first-call
    with pytest.raises(OSError):
        pol.call(always_os, site="save_csv", sleep=lambda _t: None)
    assert calls["n"] == 3
    assert breaker.breaker("io.write").state() == "open"
    calls["n"] = 0
    with pytest.raises(OSError):
        pol.call(always_os, site="save_csv", sleep=lambda _t: None)
    assert calls["n"] == 1  # open breaker: fail fast, no backoff schedule
    # a success (after the cool-down grants attempts again) closes it
    breaker.reset("io.write")
    assert pol.call(lambda: "ok", site="save_csv") == "ok"


def test_forced_open_breakers_keep_results_bit_identical(monkeypatch):
    """The force-open CI leg in miniature: every degraded path at once must
    still produce the exact values (flushes via eager replay, IO single-
    attempt, cache reads skipped)."""
    rng = np.random.default_rng(11)
    d = rng.normal(size=(6, 7)).astype(np.float32)

    def workload():
        x = ht.array(d)
        y = ht.sin((x * 2.0 + 1.0) / 3.0)
        return (y - 0.25).numpy()

    baseline = workload()
    monkeypatch.setenv("HEAT_TPU_BREAKER_FORCE_OPEN", "*")
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        forced = workload()
        # and IO still works, one attempt per call
        path = str(_tmp_csv_dir() / "forced.csv")
        ht.save_csv(ht.array(d), path)
        assert registry.REGISTRY.counter("io.retries").get() == 0
    assert _bitwise_equal(baseline, forced)
    assert registry.REGISTRY.counter("fusion.kernels_compiled").get() == 0


def _tmp_csv_dir():
    import pathlib

    d = pathlib.Path(tempfile.mkdtemp(prefix="heat-tpu-breaker-"))
    return d


# ------------------------------------------------------------------ chaos harness
def test_chaos_spec_parsing_and_validation():
    seed, rate, sites, mode = chaos.parse("1234:0.25")
    assert seed == "1234" and rate == 0.25 and sites == chaos.DEFAULT_SITES
    assert mode is None
    _s, _r, sites, _m = chaos.parse("x:0.5:io.write,fusion.compile")
    assert sites == ("io.write", "fusion.compile")
    # the 4th field (ISSUE 12) selects the value-fault storm mode
    _s, _r, sites, mode = chaos.parse("x:0.5::corrupt")
    assert mode == "corrupt" and sites == chaos.DEFAULT_CORRUPT_SITES
    _s, _r, sites, mode = chaos.parse("x:0.5:fusion.execute:corrupt")
    assert sites == ("fusion.execute",)
    for bad in (
        "", "nocolon", "s:notafloat", "s:1.5", "s:0.1:bogus.site",
        "s:0.1::notamode",
        "s:0.1:io.write:corrupt",  # io.write is not a VALUE_SITES member
    ):
        with pytest.raises(faultinject.FaultPlanError):
            chaos.parse(bad)


def test_chaos_schedule_is_derandomized_and_capped():
    a = chaos.schedule_for("seed", 0.3, "io.write", horizon=2000)
    b = chaos.schedule_for("seed", 0.3, "io.write", horizon=2000)
    assert a == b and len(a) > 0  # exact replay, cross-process stable seeding
    assert a != chaos.schedule_for("seed", 0.3, "io.read", horizon=2000)
    run, prev, worst = 0, None, 0
    for c in a:
        run = run + 1 if c == (prev or -9) + 1 else 1
        worst = max(worst, run)
        prev = c
    assert worst <= chaos.MAX_CONSECUTIVE  # retries always get a clean attempt


def test_chaos_install_fires_exactly_on_schedule():
    with chaos.install("7:0.5:io.write") as handle:
        expected = chaos.schedule_for("7", 0.5, "io.write")
        seen = []
        for call in range(1, 41):
            try:
                faultinject.check("io.write")
            except OSError:
                seen.append(call)
        assert seen == [c for c in expected if c <= 40]
        assert handle.fired()["io.write"] == seen
    faultinject.check("io.write")  # removed on exit: inert again


def test_chaos_env_schedule_counts_fires(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_CHAOS", "9:1.0:io.write")
    with monitoring.capture():
        registry.reset()
        fired = 0
        for _ in range(6):
            try:
                faultinject.check("io.write")
            except OSError:
                fired += 1
        tele = report.telemetry()
    assert fired == 4  # rate 1.0, consecutive cap 2: fire,fire,skip pattern
    assert tele["chaos_fires"] == {"io.write": fired}
    assert tele["faults_injected"] == {"io.write": fired}


def test_chaos_workload_lands_bit_identical_through_degraded_paths(monkeypatch):
    """The acceptance bar in miniature: a multi-site seeded schedule plus a
    low breaker threshold — every flush and save still lands exactly, and
    the recovery/breaker/chaos counters prove the degraded paths (not luck)
    carried the load."""
    rng = np.random.default_rng(21)
    datas = [rng.normal(size=(4, 5 + k)).astype(np.float32) for k in range(6)]

    def workload(tmpdir):
        outs = []
        for i, d in enumerate(datas):
            x = ht.array(d)
            y = ht.sqrt(ht.abs((x * 2.0 + 1.0) / 3.0))
            outs.append(y.numpy())
            path = os.path.join(tmpdir, f"w{i}.csv")
            ht.save_csv(x, path)  # io.write chaos rides the retry policy
        return outs

    with tempfile.TemporaryDirectory() as td:
        baseline = workload(td)
    fusion.clear_cache()
    monkeypatch.setenv("HEAT_TPU_CHAOS", "42:0.5:fusion.compile,fusion.execute,io.write")
    monkeypatch.setenv("HEAT_TPU_BREAKER_THRESHOLD", "2")
    with monitoring.capture():
        registry.reset()
        with tempfile.TemporaryDirectory() as td:
            chaotic = workload(td)
        tele = report.telemetry()
    for a, b in zip(baseline, chaotic):
        assert _bitwise_equal(a, b)
    assert tele["fusion_flush_recovered"] > 0
    assert sum(tele["chaos_fires"].values()) > 0
    # rate 0.5 at threshold 2 trips at least one transition on this schedule
    assert sum(tele["robustness_breakers"].values()) > 0


# ------------------------------------------------------------------ retry budget
def test_retry_budget_truncates_schedule_deterministically():
    pol = retry.RetryPolicy(max_attempts=5, base_delay=0.1, multiplier=2.0, budget=0.25)
    calls = {"n": 0}
    slept = []

    def always_os():
        calls["n"] += 1
        raise OSError("persistent")

    with pytest.raises(OSError):
        pol.call(always_os, site="unit", sleep=slept.append)
    # planned schedule 0.1, 0.2, 0.4...: the 0.2 retry would blow the 0.25s
    # budget, so exactly two attempts run and one backoff is taken
    assert calls["n"] == 2
    assert slept == [0.1]


def test_retry_budget_default_off_preserves_schedule(monkeypatch):
    assert retry.policy().budget is None  # env unset: bit-for-bit PR 6 schedule
    monkeypatch.setenv("HEAT_TPU_IO_RETRY_BUDGET_MS", "250")
    assert retry.policy().budget == 0.25
    pol = retry.RetryPolicy(max_attempts=4, base_delay=0.1)
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky, site="unit", sleep=slept.append) == "ok"
    assert slept == [0.1, 0.2, 0.4]  # no budget on the policy object: unchanged


# ------------------------------------------------------------------ oom-bucketed rung
def test_oom_under_bucketing_retries_exact_shape_before_eager(monkeypatch):
    """An OOM-classified failure of a shape-bucketed flush drops the padded
    temporaries and retries the exact-shape kernel once (counted
    fusion.flush_failures{oom-bucketed}); the signature then skips bucketing
    and is NOT poisoned — the exact-shape kernel worked."""
    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "pow2")
    rng = np.random.default_rng(31)
    d = rng.normal(size=(5, 12)).astype(np.float32)  # buckets to (8, 16)

    def chain():
        x = ht.array(d)
        return ((x * 2.0 + 1.0) / 3.0).numpy()

    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "0")
    baseline = chain()
    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "pow2")
    fusion.clear_cache()
    with monitoring.capture():
        registry.reset()
        with faultinject.inject(
            "fusion.execute", RuntimeError("RESOURCE_EXHAUSTED"), at_calls=[1]
        ) as plan:
            out = chain()
        assert plan.fired == [1]
        snap = registry.snapshot()["counters"]
        labels = snap["fusion.flush_failures"]["labels"]
        assert labels.get("oom") == 1
        assert labels.get("oom-bucketed") == 1
        assert registry.REGISTRY.counter("fusion.flush_recovered").get() == 1
        info = fusion.cache_info()
        assert info["poisoned"] == 0  # the exact-shape kernel succeeded
        assert info["bucket_oom"] == 1
        # the signature now skips bucketing outright: no new bucket hit, no
        # fault-site consult on the (cached-by-new-exact-key) repeat — and the
        # repeat result is identical
        before_hits = registry.REGISTRY.counter("serving.bucket").get("hit")
        out2 = chain()
        assert registry.REGISTRY.counter("serving.bucket").get("hit") == before_hits
    assert _bitwise_equal(out, baseline)
    assert _bitwise_equal(out2, baseline)


def test_oom_bucketed_rung_exhausted_falls_to_eager(monkeypatch):
    """If the exact-shape retry ALSO fails, the ladder still lands on eager
    replay and the result is exact."""
    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "pow2")
    rng = np.random.default_rng(33)
    d = rng.normal(size=(5, 12)).astype(np.float32)
    with monitoring.capture():
        registry.reset()
        with faultinject.inject(
            "fusion.execute", RuntimeError("RESOURCE_EXHAUSTED"), at_calls=[1, 2]
        ):
            x = ht.array(d)
            out = ((x * 2.0 + 1.0) / 3.0).numpy()
        assert registry.REGISTRY.counter("fusion.flush_recovered").get() == 1
    monkeypatch.setenv("HEAT_TPU_SHAPE_BUCKETS", "0")
    fusion.clear_cache()
    x = ht.array(d)
    assert _bitwise_equal(out, ((x * 2.0 + 1.0) / 3.0).numpy())
