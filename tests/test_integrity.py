"""
Silent-data-corruption defense suite (ISSUE 12).

The detection property pinned here, end to end: **every fired value-level
fault at an audited site is detected** (counted, poisoned/quarantined, the
configured policy applied) and **clean runs report zero mismatches** (the
false-positive guard that pins the audit comparator's carve-out tolerances
against the differential matrix). The four audited sites and their
detectors:

=====================  ===============================================
``fusion.execute``     shadow-replay audit (``HEAT_TPU_AUDIT_RATE``)
``collective.dispatch``  checksum lane (``HEAT_TPU_COLLECTIVE_CHECKSUM``)
``serving.cache_read``  L2 sha256 footer
``io.read``            checkpoint CRC32 manifest
=====================  ===============================================

Plus: value-fault plan mechanics (determinism, scheduling, counters), the
``corrupt``-mode chaos storms (fires == detections), the offline scrubber,
and the ``python -m heat_tpu.utils.checkpoint validate`` CLI.
"""

import json
import os
import pickle

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import fusion
from heat_tpu.core.communication import MeshCommunication, get_comm
from heat_tpu.monitoring import registry
from heat_tpu.robustness import breaker, chaos, faultinject, integrity, scrub
from heat_tpu.robustness.integrity import IntegrityError
from heat_tpu.serving import cache as scache
from heat_tpu.utils import checkpoint as ckpt

pytestmark = pytest.mark.integrity


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    registry.reset()
    # this suite schedules its own faults and audit knobs — standing CI envs
    # (fault-plan / chaos / forced-open / audit legs) are pinned off so every
    # fires-vs-detections assertion is exact (the test_robustness precedent)
    for var in (
        "HEAT_TPU_FAULT_PLAN",
        "HEAT_TPU_CHAOS",
        "HEAT_TPU_BREAKER_FORCE_OPEN",
        "HEAT_TPU_AUDIT_RATE",
        "HEAT_TPU_AUDIT_ACTION",
        "HEAT_TPU_COLLECTIVE_CHECKSUM",
        "HEAT_TPU_CACHE_DIR",
    ):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    faultinject.clear()
    breaker.reset()
    fusion.clear_cache()
    yield
    faultinject.clear()
    breaker.reset()
    fusion.clear_cache()
    registry.reset()


def _integrity(label):
    return registry.REGISTRY.counter("robustness.integrity").get(label)


def _corrupted(site):
    return registry.REGISTRY.counter("faults.corrupted").get(site)


# ------------------------------------------------------------------ plan mechanics
def test_corrupt_plan_mechanics_and_determinism():
    rng = np.random.default_rng(0)
    base = rng.normal(size=(4, 6)).astype(np.float32)

    def run():
        a = ht.array(base)
        a.parray  # noqa: B018
        return ((a + 1.0) * 2.0).numpy()

    clean = run()
    outs = []
    for _ in range(2):
        fusion.clear_cache()
        with faultinject.corrupt("fusion.execute", "signflip", at_calls=[1], seed=7) as plan:
            outs.append(run())
        assert plan.fired == [1]
    # same seed + same call -> byte-identical perturbation, != the clean run
    assert outs[0].tobytes() == outs[1].tobytes()
    assert outs[0].tobytes() != clean.tobytes()
    # scheduling: only the named call corrupts; counters are the VALUE family
    fusion.clear_cache()
    with registry.capture():
        with faultinject.corrupt("fusion.execute", "bitflip", at_calls=[2]) as plan:
            first = run()
            fusion.clear_cache()
            second = run()
        assert plan.fired == [2]
        assert first.tobytes() == clean.tobytes()
        assert second.tobytes() != clean.tobytes()
        assert _corrupted("fusion.execute") == 1
        assert faultinject.value_call_count("fusion.execute") == 2
        # the exception-plan family never ticked
        assert registry.REGISTRY.counter("faults.injected").get() == 0
    # context exit uninstalls; unknown sites/modes are config errors
    assert not faultinject.active()
    with pytest.raises(ValueError):
        faultinject.corrupt("io.write", "bitflip")
    with pytest.raises(ValueError):
        faultinject.corrupt("fusion.execute", "scramble")


@pytest.mark.parametrize("mode", ["bitflip", "signflip", "nan"])
def test_perturb_modes_change_one_detectable_element(mode):
    import random

    x = np.linspace(-2.0, 3.0, 24, dtype=np.float32).reshape(4, 6)
    out = faultinject._perturb(x.copy(), mode, random.Random("s"))
    assert out.shape == x.shape and out.dtype == x.dtype
    diff = out != x
    assert diff.sum() == 1
    # the perturbed element clears the audit comparator's tolerance
    assert not integrity.outputs_match(out, x)
    # int payloads corrupt too (nan degrades to a bit flip), bytes flip a bit
    xi = np.arange(12, dtype=np.int32)
    oi = faultinject._perturb(xi.copy(), mode, random.Random("s"))
    assert (oi != xi).sum() == 1
    blob = faultinject._perturb(b"\x00" * 64, mode, random.Random("s"))
    assert blob != b"\x00" * 64 and len(blob) == 64


# ------------------------------------------------------------------ shadow-replay audit
@pytest.mark.parametrize("split", [None, 0, 1])
@pytest.mark.parametrize("shape", [(16, 8), (13, 7)], ids=["even", "ragged"])
@pytest.mark.parametrize("dtype", [ht.float32, ht.bfloat16], ids=["f32", "bf16"])
def test_audit_clean_run_zero_mismatches(monkeypatch, split, shape, dtype):
    """The false-positive guard: the representative differential matrix under
    HEAT_TPU_AUDIT_RATE=1 + ACTION=raise reports ZERO mismatches — any audit
    divergence raises, so a green run pins the carve-out tolerances as the
    comparator (FMA contraction, division merge, bf16 rounding)."""
    monkeypatch.setenv("HEAT_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("HEAT_TPU_AUDIT_ACTION", "raise")
    rng = np.random.default_rng(3)
    a = ht.array(rng.standard_normal(shape).astype(np.float32), split=split).astype(dtype)
    b = ht.array((rng.standard_normal(shape) + 2.5).astype(np.float32), split=split).astype(dtype)
    a.parray, b.parray  # noqa: B018
    with registry.capture():
        # fused chain with an FMA-contractable multiply->add + a sink
        y = ht.sqrt(ht.abs(a * b + 0.5)) * 1.5
        total = float(y.sum())
        assert np.isfinite(total)
        assert _integrity("audit") >= 1
        assert _integrity("mismatch") == 0


@pytest.mark.parametrize("mode", ["bitflip", "signflip", "nan"])
def test_audit_detects_each_mode_degrade(monkeypatch, mode):
    monkeypatch.setenv("HEAT_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("HEAT_TPU_AUDIT_ACTION", "degrade")
    rng = np.random.default_rng(11)
    base = rng.normal(size=(5, 9)).astype(np.float32)
    ref = np.sqrt(np.abs(base * 2.0 + 1.0))
    with registry.capture():
        a = ht.array(base)
        a.parray  # noqa: B018
        with faultinject.corrupt("fusion.execute", mode, at_calls=[1]) as plan:
            got = ht.sqrt(ht.abs(a * 2.0 + 1.0)).numpy()
        assert plan.fired == [1]
        # degrade serves the TRUSTED eager value: bit-identical to eager
        monkeypatch.setenv("HEAT_TPU_FUSION", "0")
        a2 = ht.array(base)
        eager = ht.sqrt(ht.abs(a2 * 2.0 + 1.0)).numpy()
        monkeypatch.setenv("HEAT_TPU_FUSION", "1")
        assert got.tobytes() == eager.tobytes()
        np.testing.assert_allclose(got, ref, rtol=1e-6)
        assert _integrity("mismatch") == 1
        assert _corrupted("fusion.execute") == 1
    # the signature is POISONED: identical future chains run permanently
    # eager (no fused attempt, no fault site, still correct)
    assert fusion.cache_info()["poisoned"] >= 1
    a3 = ht.array(base)
    a3.parray  # noqa: B018
    again = ht.sqrt(ht.abs(a3 * 2.0 + 1.0)).numpy()
    assert again.tobytes() == got.tobytes()


def test_audit_raise_policy_and_repoisoned_retry(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("HEAT_TPU_AUDIT_ACTION", "raise")
    base = np.arange(20, dtype=np.float32).reshape(4, 5)
    a = ht.array(base)
    a.parray  # noqa: B018
    y = (a + 3.0) * 0.5
    with faultinject.corrupt("fusion.execute", "nan", at_calls=[1]):
        with pytest.raises(IntegrityError):
            y.numpy()
    # the chain stays pending; the poisoned re-read replays eager and is clean
    assert fusion.is_deferred(y)
    got = y.numpy()
    assert got.tobytes() == ((base + 3.0) * 0.5).tobytes()


def test_audit_rate_sampling(monkeypatch):
    """HEAT_TPU_AUDIT_RATE=N audits every Nth fused flush (distinct
    signatures so poisoning never short-circuits the cadence)."""
    monkeypatch.setenv("HEAT_TPU_AUDIT_RATE", "3")
    rng = np.random.default_rng(13)
    with registry.capture():
        for i in range(6):
            a = ht.array(rng.normal(size=(3, 4 + i)).astype(np.float32))
            a.parray  # noqa: B018
            (a * 1.5 + 0.25).numpy()
        assert _integrity("audit") == 2
        assert _integrity("mismatch") == 0


def test_audit_off_is_inert():
    """No HEAT_TPU_AUDIT_RATE: no integrity counters, no replay — the
    knobs-off bit-parity contract (the whole differential suite passing
    unmodified is the wider proof; this pins the counter silence)."""
    with registry.capture():
        a = ht.array(np.arange(12, dtype=np.float32))
        a.parray  # noqa: B018
        (a * 2.0 + 1.0).numpy()
        assert registry.REGISTRY.counter("robustness.integrity").get() == 0


def test_audit_mismatch_evicts_l1_and_quarantines_l2(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("HEAT_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("HEAT_TPU_AUDIT_ACTION", "degrade")
    base = np.random.default_rng(17).normal(size=(6, 7)).astype(np.float32)
    with registry.capture():
        a = ht.array(base)
        a.parray  # noqa: B018
        clean = ((a * 4.0) - 1.0).numpy()
        (entry,) = (tmp_path / "exec").iterdir()
        fusion.clear_cache()
        a2 = ht.array(base)
        a2.parray  # noqa: B018
        with faultinject.corrupt("fusion.execute", "bitflip", at_calls=[1]):
            got = ((a2 * 4.0) - 1.0).numpy()
        assert got.tobytes() == clean.tobytes()  # degrade served eager
        # the suspect executable left the exec dir for quarantine, with its
        # corpus recipe; the trace-LRU entry is gone (poisoned signature)
        assert not entry.exists()
        qnames = {p.name for p in (tmp_path / "quarantine").iterdir()}
        assert entry.name in qnames
        assert any(n.endswith(".pkl") for n in qnames)
        assert registry.REGISTRY.counter("serving.disk_cache").get("audit-evict") == 2
        assert fusion.cache_info()["poisoned"] >= 1


# ------------------------------------------------------------------ checksummed collectives
def _multidev():
    comm = get_comm()
    if comm.size < 2:
        pytest.skip("needs a multi-device mesh")
    return comm


@pytest.mark.parametrize("kind", ["ppermute", "allgather", "alltoall"])
def test_collective_checksum_clean_and_detect(monkeypatch, kind):
    comm = _multidev()
    monkeypatch.setenv("HEAT_TPU_COLLECTIVE_CHECKSUM", "1")
    p = comm.size
    x = np.arange(p * 4 * p, dtype=np.float32).reshape(p * 4, p)

    def dispatch():
        if kind == "ppermute":
            return comm.Ppermute(x, shift=1, split=0)
        if kind == "allgather":
            return comm.Allgather(x, split=0)
        return comm.Alltoall(x, split_axis=1, concat_axis=0)

    with registry.capture():
        out = np.asarray(dispatch())
        assert _integrity("collective-verified") == 1
        assert _integrity("collective-mismatch") == 0
        with faultinject.corrupt("collective.dispatch", "bitflip", at_calls=[1]) as plan:
            with pytest.raises(IntegrityError):
                dispatch()
        assert plan.fired == [1]
        assert _integrity("collective-mismatch") == 1
        assert _corrupted("collective.dispatch") == 1
    # the clean dispatch was bit-identical to the unchecked one
    monkeypatch.delenv("HEAT_TPU_COLLECTIVE_CHECKSUM")
    assert out.tobytes() == np.asarray(dispatch()).tobytes()


def test_allreduce_sum_invariant_and_exact_ops(monkeypatch):
    comm = _multidev()
    monkeypatch.setenv("HEAT_TPU_COLLECTIVE_CHECKSUM", "1")
    p = comm.size
    rng = np.random.default_rng(23)
    x = rng.normal(size=(p * 3, 5)).astype(np.float32)
    with registry.capture():
        s = np.asarray(comm.Allreduce(x, op="sum", split=0))
        m = np.asarray(comm.Allreduce(x, op="max", split=0))
        b = np.asarray(comm.Allreduce(x > 0, op="lor", split=0))
        i = np.asarray(comm.Allreduce((x * 10).astype(np.int32), op="sum", split=0))
        assert _integrity("collective-verified") == 4
        # a corrupted sum payload breaks the f64 local-sum invariant
        with faultinject.corrupt("collective.dispatch", "signflip", at_calls=[1]):
            with pytest.raises(IntegrityError):
                comm.Allreduce(x, op="sum", split=0)
        assert _integrity("collective-mismatch") == 1
    # sanity against host reductions
    chunks = x.reshape(p, -1, 5)
    np.testing.assert_allclose(s, chunks.astype(np.float64).sum(axis=0), rtol=1e-5)
    assert m.tobytes() == np.maximum.reduce(chunks).tobytes()
    assert b.tobytes() == np.logical_or.reduce(chunks > 0).tobytes()


def test_halo_checksum_clean_and_detect(monkeypatch):
    comm = _multidev()
    monkeypatch.setenv("HEAT_TPU_COLLECTIVE_CHECKSUM", "1")
    # eager exchange path (the fused/deferred path is audit territory)
    monkeypatch.setenv("HEAT_TPU_FUSION_COLLECTIVES", "0")
    p = comm.size
    data = np.arange(p * 4 * 3, dtype=np.float32).reshape(p * 4, 3)
    with registry.capture():
        a = ht.array(data, split=0)
        a.get_halo(1)
        assert _integrity("collective-verified") == 1
        prev = np.asarray(a.halo_prev)
        assert prev[0].sum() == 0  # outer boundary is zeros
        with faultinject.corrupt("collective.dispatch", "nan", at_calls=[1]) as plan:
            b = ht.array(data, split=0)
            with pytest.raises(IntegrityError):
                b.get_halo(1)
        assert plan.fired == [1]
        assert _integrity("collective-mismatch") == 1


# ------------------------------------------------------------------ L2 footer
def test_cache_footer_detects_corruption_and_legacy(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    base = np.random.default_rng(29).normal(size=(5, 11)).astype(np.float32)

    def run():
        a = ht.array(base)
        a.parray  # noqa: B018
        return ((a * 2.0) + 0.5).numpy()

    with registry.capture():
        r1 = run()
        (entry,) = (tmp_path / "exec").iterdir()
        blob = entry.read_bytes()
        body, ok = scache.split_footer(blob)
        assert ok is True  # every stored entry carries a verified footer
        # corrupted-but-still-deserializable: flip one bit inside the body —
        # this used to load silently, now the footer catches it
        bad = bytearray(blob)
        bad[len(bad) // 3] ^= 0x08
        entry.write_bytes(bytes(bad))
        fusion.clear_cache()
        r2 = run()
        dc = registry.REGISTRY.counter("serving.disk_cache")
        assert dc.get("checksum") == 1
        assert entry.name in {p.name for p in (tmp_path / "quarantine").iterdir()}
        assert r2.tobytes() == r1.tobytes()  # recompile fallback, bit parity
        # injected value fault on the raw read bytes: same detection path
        fusion.clear_cache()
        with faultinject.corrupt("serving.cache_read", "bitflip", at_calls=[1]) as plan:
            r3 = run()
        assert plan.fired == [1] and dc.get("checksum") == 2
        assert r3.tobytes() == r1.tobytes()
        # legacy pre-footer entry (valid pickle, no footer): incompatible —
        # recompiled, re-stored footered, never served, never a crash
        (entry2,) = (tmp_path / "exec").iterdir()
        legacy = pickle.loads(entry2.read_bytes())
        entry2.write_bytes(pickle.dumps(legacy))
        fusion.clear_cache()
        inc0 = dc.get("incompatible")
        r4 = run()
        assert dc.get("incompatible") == inc0 + 1
        assert r4.tobytes() == r1.tobytes()
        body2, ok2 = scache.split_footer(entry2.read_bytes())
        assert ok2 is True  # the re-store upgraded the entry to footered


def test_corpus_footer_checksum_and_legacy(monkeypatch, tmp_path):
    from heat_tpu.serving import corpus as scorpus

    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    scorpus._seen.clear()
    base = np.random.default_rng(31).normal(size=(4, 13)).astype(np.float32)
    a = ht.array(base)
    a.parray  # noqa: B018
    ((a * 3.0) - 0.25).numpy()
    cdir = tmp_path / "corpus"
    (good,) = cdir.iterdir()
    recipe = pickle.loads(good.read_bytes())  # pickle ignores the footer
    with registry.capture():
        # a bit-flipped (but still unpicklable? no — still DESERIALIZABLE)
        # record is skipped by the footer check, counted checksum
        bad = bytearray(good.read_bytes())
        bad[len(bad) // 2] ^= 0x01
        (cdir / ("a" * 64 + ".pkl")).write_bytes(bytes(bad))
        # a legacy pre-footer record is yielded (counted legacy)
        (cdir / ("b" * 64 + ".pkl")).write_bytes(pickle.dumps(recipe))
        got = dict(scorpus.entries(str(cdir)))
        cc = registry.REGISTRY.counter("serving.corpus")
        assert cc.get("checksum") == 1
        assert cc.get("legacy") == 1
        assert set(got) == {good.name[:-4], "b" * 64}


# ------------------------------------------------------------------ checkpoint CRC + CLI
def test_io_read_value_fault_caught_by_crc(tmp_path):
    mgr = ckpt.CheckpointManager(str(tmp_path))
    state = {"w": np.arange(24, dtype=np.float32).reshape(4, 6), "step": 3}
    mgr.save(100, state)
    with registry.capture():
        with faultinject.corrupt("io.read", "bitflip", at_calls=[1]) as plan:
            with pytest.raises(ckpt.CheckpointCorruptError):
                mgr.restore(state)
        assert plan.fired == [1]
        assert _integrity("checkpoint-crc") == 1
        assert _corrupted("io.read") == 1
    # without the fault the checkpoint restores exactly
    out = mgr.restore(state)
    assert np.array_equal(out["w"], state["w"]) and out["step"] == 3


def test_checkpoint_validate_cli(tmp_path, capsys):
    mgr = ckpt.CheckpointManager(str(tmp_path))
    state = {"w": np.arange(8, dtype=np.float32)}
    mgr.save(5, state)
    mgr.save(9, state)
    # truncate the newest: the CLI reports the newest VALID step
    p9 = tmp_path / "ckpt_000000000009.h5"
    p9.write_bytes(p9.read_bytes()[: len(p9.read_bytes()) // 2])
    assert ckpt.main(["validate", str(tmp_path)]) == 0
    out = capsys.readouterr()
    assert out.out.strip() == "5"
    assert "step 9 FAILED" in out.err
    # no valid checkpoint -> exit 1; missing dir -> exit 1
    p5 = tmp_path / "ckpt_000000000005.h5"
    p5.write_bytes(b"")
    assert ckpt.main(["validate", str(tmp_path), "-q"]) == 1
    assert ckpt.main(["validate", str(tmp_path / "nope"), "-q"]) == 1


# ------------------------------------------------------------------ chaos corrupt mode
def test_chaos_corrupt_storm_fires_equal_detections(monkeypatch):
    """The seeded whole-suite corruption storm, in miniature: every fired
    value-fault at fusion.execute is detected by the audit (fires ==
    mismatches), and every served value is still correct (degrade = the
    trusted eager replay). Distinct shapes per iteration keep signatures
    separate so poisoning cannot short-circuit later fires."""
    monkeypatch.setenv("HEAT_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("HEAT_TPU_AUDIT_ACTION", "degrade")
    rng = np.random.default_rng(37)
    with registry.capture():
        with chaos.install("storm:0.5:fusion.execute:corrupt") as inst:
            for i in range(10):
                base = rng.normal(size=(3, 5 + i)).astype(np.float32)
                a = ht.array(base)
                a.parray  # noqa: B018
                got = (ht.abs(a) * 2.0 + float(i)).numpy()
                ref = np.abs(base) * 2.0 + np.float32(i)
                np.testing.assert_allclose(got, ref, rtol=1e-6)
        fired = inst.fired().get("fusion.execute", [])
        assert len(fired) >= 2  # the seeded schedule actually fired
        assert _integrity("mismatch") == len(fired)
        assert _corrupted("fusion.execute") == len(fired)
        assert registry.REGISTRY.counter("robustness.chaos").get(
            "fusion.execute"
        ) == len(fired)


def test_chaos_corrupt_mode_derandomized_and_capped():
    by_site = chaos.plans("seedx:0.3::corrupt")
    assert set(by_site) <= set(chaos.DEFAULT_CORRUPT_SITES)
    for site, plans_ in by_site.items():
        (plan,) = plans_
        assert isinstance(plan, chaos.ChaosValuePlan)
        assert plan.mode in faultinject.CORRUPT_MODES
        # identical derandomization on re-parse (cross-process replay)
        (again,) = chaos.plans("seedx:0.3::corrupt")[site]
        assert again.at_calls == plan.at_calls and again.mode == plan.mode


def test_chaos_env_corrupt_spec_routes_to_value_plans(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_AUDIT_RATE", "1")
    monkeypatch.setenv("HEAT_TPU_AUDIT_ACTION", "degrade")
    monkeypatch.setenv("HEAT_TPU_CHAOS", "envstorm:1.0:fusion.execute:corrupt")
    faultinject._CHAOS_CACHE = ("", {})
    faultinject.reset_counts()
    base = np.random.default_rng(41).normal(size=(4, 4)).astype(np.float32)
    with registry.capture():
        a = ht.array(base)
        a.parray  # noqa: B018
        got = (a * 2.5).numpy()
        np.testing.assert_allclose(got, base * np.float32(2.5), rtol=1e-6)
        # rate 1.0 fires on the first call (capped schedule), audit caught it
        assert _corrupted("fusion.execute") >= 1
        assert _integrity("mismatch") >= 1
        # the env schedule never raises at the site (value plans corrupt,
        # not raise): faults.injected stays silent
        assert registry.REGISTRY.counter("faults.injected").get() == 0


# ------------------------------------------------------------------ scrubber
def test_scrub_cache_and_checkpoints(monkeypatch, tmp_path, capsys):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path / "cache"))
    base = np.random.default_rng(43).normal(size=(7, 5)).astype(np.float32)
    a = ht.array(base)
    a.parray  # noqa: B018
    (a + 1.5).numpy()
    cache_dir = tmp_path / "cache"
    ckdir = tmp_path / "ckpts"
    mgr = ckpt.CheckpointManager(str(ckdir))
    mgr.save(1, {"w": base})
    mgr.save(2, {"w": base})
    # clean scrub: exit 0, nothing quarantined
    assert scrub.main(["--cache-dir", str(cache_dir), "--checkpoints", str(ckdir), "-q"]) == 0
    # corrupt one exec entry + truncate one checkpoint
    (entry,) = (cache_dir / "exec").iterdir()
    blob = bytearray(entry.read_bytes())
    blob[len(blob) // 2] ^= 0x20
    entry.write_bytes(bytes(blob))
    p2 = ckdir / "ckpt_000000000002.h5"
    p2.write_bytes(p2.read_bytes()[:100])
    with registry.capture():
        rc = scrub.main(["--cache-dir", str(cache_dir), "--checkpoints", str(ckdir)])
        assert rc == 1
        stats = json.loads(capsys.readouterr().out.strip())
        assert stats["corrupt"] == 2 and stats["quarantined"] == 2
        assert _integrity("scrub-corrupt") == 2
    assert entry.name in {p.name for p in (cache_dir / "quarantine").iterdir()}
    assert p2.name in {p.name for p in (ckdir / "quarantine").iterdir()}
    # the manager no longer sees the quarantined corpse; restore works
    assert mgr.latest_valid_step() == 1
    # second scrub over the cleaned inventory: exit 0
    assert scrub.main(["--cache-dir", str(cache_dir), "--checkpoints", str(ckdir), "-q"]) == 0
    # a missing directory scrubs to empty, and no target is a usage error
    assert scrub.main(["--cache-dir", str(tmp_path / "missing"), "-q"]) == 0
    monkeypatch.delenv("HEAT_TPU_CACHE_DIR")
    assert scrub.main([]) == 2


def test_allreduce_sum_bound_scales():
    b32 = integrity.allreduce_sum_bound(100.0, np.float32, 8)
    b64 = integrity.allreduce_sum_bound(100.0, np.float64, 8)
    assert b64 < b32 < 1.0
    assert integrity.allreduce_sum_bound(1e6, np.float32, 8) > b32
