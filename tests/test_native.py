"""Native C++ runtime helpers (heat_tpu/native): the threaded CSV parser and its
integration with ht.load_csv (reference io.py:713-925 byte-range parallel CSV)."""

import os

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain for the native fast path"
)


def test_parse_basic():
    raw = b"1.5,2,3\n4,5.25,-6\n"
    out = native.parse_csv(raw, ",", 0)
    np.testing.assert_allclose(out, [[1.5, 2, 3], [4, 5.25, -6]])


def test_parse_header_blank_crlf():
    raw = b"a;b\r\n# two header lines\r\n1;2\r\n\r\n  \r\n3;4\r\n-1e3;+2.5e-2\r\n"
    out = native.parse_csv(raw, ";", 2)
    np.testing.assert_allclose(out, [[1, 2], [3, 4], [-1000, 0.025]])


def test_parse_no_trailing_newline():
    out = native.parse_csv(b"1,2\n3,4", ",", 0)
    np.testing.assert_allclose(out, [[1, 2], [3, 4]])


def test_parse_malformed_returns_none():
    assert native.parse_csv(b"1,2\n3\n", ",", 0) is None  # ragged row
    assert native.parse_csv(b"1,x\n", ",", 0) is None  # bad float
    assert native.parse_csv(b"1,2\n", ",,", 0) is None  # multi-char sep


def test_parse_empty():
    out = native.parse_csv(b"", ",", 0)
    assert out.shape == (0, 0)


def test_matches_python_path_large(tmp_path):
    rng = np.random.default_rng(0)
    arr = rng.normal(size=(5000, 12))
    p = tmp_path / "big.csv"
    np.savetxt(p, arr, delimiter=",", fmt="%.10g")
    raw = p.read_bytes()
    out = native.parse_csv(raw, ",", 0)
    np.testing.assert_allclose(out, arr, rtol=1e-9)


def test_load_csv_uses_native_and_agrees(tmp_path):
    rng = np.random.default_rng(1)
    arr = rng.normal(size=(257, 7)).astype(np.float32)  # odd row count: chunk edges
    p = tmp_path / "data.csv"
    np.savetxt(p, arr, delimiter=";", fmt="%.8g")
    a = ht.load_csv(str(p), sep=";", split=0)
    np.testing.assert_allclose(a.numpy(), arr, rtol=1e-5)
    # latin-1 encoding forces the Python fallback; results agree
    b = ht.load_csv(str(p), sep=";", split=0, encoding="latin-1")
    np.testing.assert_allclose(b.numpy(), a.numpy())


# ---------------------------------------------------------------- SlabPrefetcher


def test_prefetch_ordered_delivery(tmp_path):
    rng = np.random.default_rng(2)
    blob = rng.integers(0, 256, size=100_000, dtype=np.uint8).tobytes()
    p = tmp_path / "blob.bin"
    p.write_bytes(blob)
    # random non-overlapping-ish slabs, deliberately more slabs than ring depth
    offsets, lengths = [], []
    pos = 0
    while pos + 500 < len(blob):
        ln = int(rng.integers(1, 4000))
        ln = min(ln, len(blob) - pos)
        offsets.append(pos)
        lengths.append(ln)
        pos += ln
    with native.SlabPrefetcher(str(p), offsets, lengths, depth=3, nthreads=2) as pf:
        got = list(pf)
    assert len(got) == len(offsets)
    for o, l, g in zip(offsets, lengths, got):
        assert g == blob[o : o + l]


def test_prefetch_next_into_and_reuse(tmp_path):
    p = tmp_path / "x.bin"
    data = bytes(range(256)) * 16
    p.write_bytes(data)
    offsets = [0, 1024, 2048, 3072]
    lengths = [1024] * 4
    buf = np.empty(1024, dtype=np.uint8)
    with native.SlabPrefetcher(str(p), offsets, lengths, depth=2, nthreads=4) as pf:
        for o in offsets:
            n = pf.next_into(buf)
            assert n == 1024
            assert buf.tobytes() == data[o : o + 1024]
        assert pf.next_into(buf) is None
        assert pf.next_into(buf) is None  # idempotent at end


def test_prefetch_errors(tmp_path):
    with pytest.raises(RuntimeError):
        native.SlabPrefetcher(str(tmp_path / "missing.bin"), [0], [4])
    p = tmp_path / "short.bin"
    p.write_bytes(b"abcd")
    # slab reaches past EOF: surfaced as IOError on the consuming call
    pf = native.SlabPrefetcher(str(p), [0, 2], [4, 100], depth=2)
    buf = np.empty(128, dtype=np.uint8)
    assert pf.next_into(buf) == 4
    with pytest.raises(IOError):
        pf.next_into(buf)
    pf.close()
    with pytest.raises(ValueError):
        native.SlabPrefetcher(str(p), [0], [-1])
    with pytest.raises(ValueError):
        native.SlabPrefetcher(str(p), [0, 1], [1])
    # too-small destination
    pf = native.SlabPrefetcher(str(p), [0], [4])
    with pytest.raises(ValueError):
        pf.next_into(np.empty(2, dtype=np.uint8))
    pf.close()


def test_prefetch_early_close_no_hang(tmp_path):
    p = tmp_path / "y.bin"
    p.write_bytes(b"\0" * 65536)
    pf = native.SlabPrefetcher(str(p), list(range(0, 65536, 1024)), [1024] * 64, depth=2)
    buf = np.empty(1024, dtype=np.uint8)
    assert pf.next_into(buf) == 1024
    pf.close()  # workers blocked on ring slots must exit promptly
    with pytest.raises(RuntimeError):
        pf.next_into(buf)


def test_partial_h5_native_path_agrees(tmp_path):
    h5py = pytest.importorskip("h5py")
    from heat_tpu.utils.data.partial_dataset import PartialH5Dataset, PartialH5DataLoaderIter

    rng = np.random.default_rng(3)
    data = rng.normal(size=(600, 5)).astype(np.float32)
    labels = rng.integers(0, 10, size=(600,)).astype(np.int64)
    f = tmp_path / "train.h5"
    with h5py.File(f, "w") as h:
        h.create_dataset("data", data=data)  # contiguous, uncompressed
        h.create_dataset("labels", data=labels)
    ds = PartialH5Dataset(str(f), dataset_names=["data", "labels"], initial_load=200, load_length=100)
    assert ds._prefetchers is not None  # native path engaged
    np.testing.assert_array_equal(ds[0:4][0], data[0:4])
    # three loads walk the window forward exactly like the h5py path
    for _ in range(3):
        ds._load_next()
    # equality against a pure-h5py reference dataset driven identically
    ds2 = PartialH5Dataset(str(f), dataset_names=["data", "labels"], initial_load=200, load_length=100)
    ds2._prefetchers = None  # force h5py path
    for _ in range(3):
        ds2._load_next()
    np.testing.assert_array_equal(ds._window["data"], ds2._window["data"])
    np.testing.assert_array_equal(ds._window["labels"], ds2._window["labels"])
    ds.close()
    ds2.close()


def test_partial_h5_compressed_falls_back(tmp_path):
    h5py = pytest.importorskip("h5py")
    from heat_tpu.utils.data.partial_dataset import PartialH5Dataset

    f = tmp_path / "c.h5"
    with h5py.File(f, "w") as h:
        h.create_dataset("data", data=np.arange(100.0).reshape(50, 2), compression="gzip")
    ds = PartialH5Dataset(str(f), dataset_names=["data"], initial_load=20, load_length=10)
    assert ds._prefetchers is None  # chunked/compressed layout: h5py path
    ds._load_next()
    np.testing.assert_array_equal(ds._window["data"][-10:], np.arange(40.0, 60.0).reshape(10, 2))
    ds.close()


def test_prefetch_post_open_truncation_is_recoverable(tmp_path):
    """A file truncated AFTER open must surface as IOError (-2 via the
    per-slab fstat re-check, _prefetch.cpp), never fault the mapping — and the
    rolled-back ticket must stay consumable once the file is restored.

    Deterministic by construction: slab 0 lies entirely inside the
    post-truncation range (the warmer may touch it at any time, safely), and
    with depth=1 the warmer cannot reach slab 1 before the first consume —
    by which time the truncation has already happened, so its fstat clamp
    skips the touch. No window ever touches past the live EOF."""
    data = bytes(range(256)) * 64  # 16 KiB
    p = tmp_path / "trunc.bin"
    p.write_bytes(data)
    pf = native.SlabPrefetcher(str(p), [0, 8192], [4096, 8192], depth=1, nthreads=1)
    os.truncate(p, 4096)  # before any consume: slab 1 now lies beyond EOF
    buf = np.empty(8192, dtype=np.uint8)
    assert pf.next_into(buf) == 4096
    with pytest.raises(IOError):
        pf.next_into(buf)
    # -2 rolls the ticket back (serialized consumer): restoring the file
    # makes the same slab deliverable on retry
    p.write_bytes(data)
    assert pf.next_into(buf) == 8192
    assert bytes(buf[:16]) == data[8192 : 8192 + 16]
    pf.close()


# ------------------------------------------------- pread mode (ADVICE r5 toggle)


def test_prefetch_pread_mode_ordered_delivery(tmp_path):
    """use_pread=True routes delivery through the gen-1 pread path (no mmap):
    same ordering, payloads, and end-of-stream contract as the mmap mode —
    for network/volatile storage where mmap fault-in can SIGBUS."""
    rng = np.random.default_rng(7)
    blob = rng.integers(0, 256, size=50_000, dtype=np.uint8).tobytes()
    p = tmp_path / "pread.bin"
    p.write_bytes(blob)
    offsets = list(range(0, 48_000, 4000))
    lengths = [4000] * len(offsets)
    with native.SlabPrefetcher(
        str(p), offsets, lengths, depth=3, nthreads=2, use_pread=True
    ) as pf:
        assert pf.use_pread
        got = list(pf)
    assert got == [blob[o : o + 4000] for o in offsets]


def test_prefetch_pread_env_toggle(tmp_path, monkeypatch):
    """HEAT_TPU_PREFETCH_PREAD=1 flips the default for every consumer that
    does not pass use_pread explicitly (the io pipeline's constructor call)."""
    p = tmp_path / "env.bin"
    p.write_bytes(bytes(range(256)) * 8)
    monkeypatch.setenv("HEAT_TPU_PREFETCH_PREAD", "1")
    with native.SlabPrefetcher(str(p), [0, 512], [512, 512]) as pf:
        assert pf.use_pread
        assert list(pf) == [p.read_bytes()[:512], p.read_bytes()[512:1024]]
    monkeypatch.setenv("HEAT_TPU_PREFETCH_PREAD", "0")
    with native.SlabPrefetcher(str(p), [0], [256]) as pf:
        assert not pf.use_pread  # explicit off wins over any ambient setting
        assert list(pf) == [p.read_bytes()[:256]]


def test_prefetch_pread_truncation_is_catchable(tmp_path):
    """The pread path's reason to exist: a slab that lies beyond EOF (or is
    truncated mid-epoch) surfaces as a catchable IOError — never a SIGBUS —
    and the rolled-back ticket stays consumable after the file is restored."""
    data = bytes(range(256)) * 32  # 8 KiB
    p = tmp_path / "ptrunc.bin"
    p.write_bytes(data)
    pf = native.SlabPrefetcher(
        str(p), [0, 4096], [4096, 4096], depth=1, nthreads=1, use_pread=True
    )
    os.truncate(p, 4096)
    buf = np.empty(4096, dtype=np.uint8)
    assert pf.next_into(buf) == 4096
    with pytest.raises(IOError):
        pf.next_into(buf)
    p.write_bytes(data)  # restore: the -2 rollback keeps slab 1 observable
    assert pf.next_into(buf) == 4096
    assert bytes(buf[:16]) == data[4096 : 4096 + 16]
    assert pf.next_into(buf) is None
    pf.close()
