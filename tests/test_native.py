"""Native C++ runtime helpers (heat_tpu/native): the threaded CSV parser and its
integration with ht.load_csv (reference io.py:713-925 byte-range parallel CSV)."""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import native


pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ toolchain for the native fast path"
)


def test_parse_basic():
    raw = b"1.5,2,3\n4,5.25,-6\n"
    out = native.parse_csv(raw, ",", 0)
    np.testing.assert_allclose(out, [[1.5, 2, 3], [4, 5.25, -6]])


def test_parse_header_blank_crlf():
    raw = b"a;b\r\n# two header lines\r\n1;2\r\n\r\n  \r\n3;4\r\n-1e3;+2.5e-2\r\n"
    out = native.parse_csv(raw, ";", 2)
    np.testing.assert_allclose(out, [[1, 2], [3, 4], [-1000, 0.025]])


def test_parse_no_trailing_newline():
    out = native.parse_csv(b"1,2\n3,4", ",", 0)
    np.testing.assert_allclose(out, [[1, 2], [3, 4]])


def test_parse_malformed_returns_none():
    assert native.parse_csv(b"1,2\n3\n", ",", 0) is None  # ragged row
    assert native.parse_csv(b"1,x\n", ",", 0) is None  # bad float
    assert native.parse_csv(b"1,2\n", ",,", 0) is None  # multi-char sep


def test_parse_empty():
    out = native.parse_csv(b"", ",", 0)
    assert out.shape == (0, 0)


def test_matches_python_path_large(tmp_path):
    rng = np.random.default_rng(0)
    arr = rng.normal(size=(5000, 12))
    p = tmp_path / "big.csv"
    np.savetxt(p, arr, delimiter=",", fmt="%.10g")
    raw = p.read_bytes()
    out = native.parse_csv(raw, ",", 0)
    np.testing.assert_allclose(out, arr, rtol=1e-9)


def test_load_csv_uses_native_and_agrees(tmp_path):
    rng = np.random.default_rng(1)
    arr = rng.normal(size=(257, 7)).astype(np.float32)  # odd row count: chunk edges
    p = tmp_path / "data.csv"
    np.savetxt(p, arr, delimiter=";", fmt="%.8g")
    a = ht.load_csv(str(p), sep=";", split=0)
    np.testing.assert_allclose(a.numpy(), arr, rtol=1e-5)
    # latin-1 encoding forces the Python fallback; results agree
    b = ht.load_csv(str(p), sep=";", split=0, encoding="latin-1")
    np.testing.assert_allclose(b.numpy(), a.numpy())
