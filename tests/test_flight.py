"""
Execution flight recorder (ISSUE 13, heat_tpu/monitoring/flight.py): ring
semantics (overflow evicts oldest, off-mode allocates nothing), per-flush
record fields and their agreement with the fusion/serving counters, XLA cost
cards persisted beside the L2 entries (zero-compile processes keep
attribution — subprocess acceptance test), Chrome-trace/Perfetto export
schema, the compile-latency histogram satellite, cross-thread span nesting
under the FlushScheduler (≥2 worker threads), the statusz CLI surface, the
counter-catalog drift guard, and the pure-observer contract (bit-identical
results with the recorder armed).
"""

import json
import os
import re
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import fusion
from heat_tpu.monitoring import events, flight, registry, report
from heat_tpu.robustness import faultinject

pytestmark = pytest.mark.flight

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh ring/counters/trace-cache on both sides; the recorder gate is
    opt-in per test (tier-1 runs with it off; the observability-smoke CI
    leg runs the fusion+serving suites with it ambient — count-asserting
    tests here pin their own gate via monkeypatch)."""
    from heat_tpu.robustness import breaker

    monkeypatch.delenv("HEAT_TPU_FLIGHT", raising=False)
    monkeypatch.delenv("HEAT_TPU_FLIGHT_RECORDS", raising=False)
    monkeypatch.delenv("HEAT_TPU_CACHE_DIR", raising=False)
    monkeypatch.delenv("HEAT_TPU_FAULT_PLAN", raising=False)
    monkeypatch.delenv("HEAT_TPU_CHAOS", raising=False)
    monkeypatch.delenv("HEAT_TPU_BREAKER_FORCE_OPEN", raising=False)
    monkeypatch.delenv("HEAT_TPU_AUDIT_RATE", raising=False)
    monkeypatch.setenv("HEAT_TPU_FUSION", "1")
    registry.reset()
    events.clear()
    flight.clear()
    faultinject.clear()
    breaker.reset()
    fusion.clear_cache()
    yield
    fusion.clear_cache()
    flight.clear()
    events.clear()
    registry.reset()


def _fresh(shape=(6, 10), seed=0, split=None):
    data = np.random.default_rng(seed).normal(size=shape).astype(np.float32)
    return ht.array(data, split=split)


def _chain(x):
    return (x * 2.0 + 1.0) / 3.0 - 0.25


def _flushes():
    return flight.records("flush")


# ---------------------------------------------------------------- ring + gate
def test_off_mode_is_inert_and_allocates_no_ring():
    assert not flight.flight_enabled()
    _chain(_fresh()).numpy()
    assert flight.records() == []
    assert not flight.ring_allocated()
    assert flight.evicted() == 0


def test_ring_overflow_evicts_oldest(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    monkeypatch.setenv("HEAT_TPU_FLIGHT_RECORDS", "4")
    for i in range(6):
        # six distinct single-flush programs (chain length varies)
        x = _fresh(seed=i)
        for _ in range(i + 1):
            x = x * 1.5
        x.numpy()
    recs = _flushes()
    assert len(recs) == 4
    assert flight.evicted() == 2
    # chronological order survives wraparound, and the two oldest (shortest)
    # chains are the evicted ones
    assert [r["chain"] for r in recs] == [3, 4, 5, 6]
    ts = [r["ts"] for r in recs]
    assert ts == sorted(ts)


def test_flush_record_fields_and_counter_agreement(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    with registry.capture():
        y = _chain(_fresh()).sum()
        float(y.larray)
        recs = _flushes()
        assert len(recs) == 1
        (rec,) = recs
        assert rec["cache"] == "compile"
        assert rec["rung"] == "fused"
        assert rec["chain"] == 5 and rec["kinds"] == {"binary": 4, "sink": 1}
        assert rec["reason"] in ("other", "export")
        assert rec["wall_s"] >= 0.0 and isinstance(rec["tid"], int)
        assert isinstance(rec["signature"], str) and len(rec["signature"]) == 64
        assert rec["donate"] == [] and rec["outputs"] == 1
        # an identical chain flushes from L1
        y2 = _chain(_fresh()).sum()
        float(y2.larray)
        recs = _flushes()
        assert recs[-1]["cache"] == "l1"
        assert recs[-1]["signature"] == rec["signature"]
        # cache-outcome fields agree with the fusion counters (acceptance
        # criterion a): compile-lane records == kernels_compiled, l1-lane
        # records == cache_hits
        c = registry.REGISTRY.counter
        assert sum(r["cache"] == "compile" for r in recs) == c(
            "fusion.kernels_compiled"
        ).get()
        assert sum(r["cache"] == "l1" for r in recs) == c("fusion.cache_hits").get()


def test_l2_outcome_agrees_with_disk_counter(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        _chain(_fresh(seed=3)).numpy()
        fusion.clear_cache()  # drop L1, keep disk
        _chain(_fresh(seed=3)).numpy()
        recs = _flushes()
        assert [r["cache"] for r in recs] == ["compile", "l2"]
        assert recs[0]["signature"] == recs[1]["signature"]
        disk = registry.REGISTRY.counter("serving.disk_cache")
        assert sum(r["cache"] == "l2" for r in recs) == disk.get("hit")


def test_ladder_recovery_and_poisoning_lanes(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    x = _fresh(seed=5)
    with faultinject.inject("fusion.execute", RuntimeError, at_calls=[1]):
        _chain(x).numpy()
    rec = _flushes()[-1]
    assert rec["rung"] == "eager-replay"
    assert rec["failures"] == ["compile"]
    # the poisoned signature routes the identical chain straight to eager
    _chain(x).numpy()
    rec2 = _flushes()[-1]
    assert rec2["cache"] == "eager"
    assert rec2["rung"] == "eager-replay"
    assert rec2["poisoned"] is True


def test_flight_is_a_pure_observer(monkeypatch):
    """Bit-identical results with the recorder armed (the observability-smoke
    CI leg runs the full fusion+serving suites under this gate)."""
    for split in (None, 0, 1):
        x = _fresh(shape=(7, 9), seed=11, split=split)
        monkeypatch.delenv("HEAT_TPU_FLIGHT", raising=False)
        fusion.clear_cache()
        ref_chain = _chain(x).numpy()
        ref_sum = np.asarray(_chain(x).sum().larray)
        monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
        fusion.clear_cache()
        got_chain = _chain(x).numpy()
        got_sum = np.asarray(_chain(x).sum().larray)
        assert ref_chain.tobytes() == got_chain.tobytes()
        assert ref_sum.tobytes() == got_sum.tobytes()
        assert len(_flushes()) >= 2


# ---------------------------------------------------------------- chrome trace
def test_chrome_trace_schema(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    with registry.capture():
        with events.span("workload"):
            y = _chain(_fresh(seed=7, split=0)).sum()
            float(y.larray)
        _chain(_fresh(seed=8)).numpy()
        trace = json.loads(flight.export_chrome_trace())
    all_evs = trace["traceEvents"]
    # ISSUE 14 satellite: metadata events lead — one process_name plus a
    # thread_name per distinct tid, all tagged with the real pid — so
    # aggregator-merged multi-process traces render as separate tracks
    meta = [e for e in all_evs if e["ph"] == "M"]
    evs = [e for e in all_evs if e["ph"] != "M"]
    assert all_evs[: len(meta)] == meta  # metadata strictly first
    assert {e["name"] for e in meta} == {"process_name", "thread_name"}
    assert all(e["pid"] == os.getpid() for e in meta)
    pname = next(e for e in meta if e["name"] == "process_name")
    assert str(os.getpid()) in pname["args"]["name"]
    assert {e["tid"] for e in meta if e["name"] == "thread_name"} == {
        e["tid"] for e in evs
    }
    assert isinstance(evs, list) and len(evs) >= 3  # span + >=2 flight records
    for e in evs:
        assert set(("name", "ph", "ts", "dur", "pid", "tid")) <= set(e)
        assert e["ph"] == "X"
        assert e["pid"] == os.getpid()
        assert isinstance(e["ts"], float) and isinstance(e["tid"], int)
        assert e["dur"] >= 0.0
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)  # monotone timestamps
    names = {e["name"] for e in evs}
    assert "workload" in names
    assert any(n.startswith("flush ") for n in names)


# ---------------------------------------------------------------- cost cards
def test_cost_cards_keep_attribution_across_processes(tmp_path):
    """Acceptance criterion (c): a fresh process serving every flush from
    the warmed L2 (``fusion.kernels_compiled == 0``) still attributes flops
    per signature — the compiling process persisted the cost card beside
    the entry."""
    prog = textwrap.dedent(
        """
        import os, json
        import numpy as np
        os.environ["HEAT_TPU_MONITORING"] = "1"
        os.environ["HEAT_TPU_FLIGHT"] = "1"
        import heat_tpu as ht
        from heat_tpu.monitoring import flight, registry
        x = ht.array(np.arange(60, dtype=np.float32).reshape(5, 12))
        r = ((x * 2.0 + 1.0) / 3.0).numpy()
        recs = flight.records("flush")
        totals = flight.totals()
        print(json.dumps({
            "compiles": registry.REGISTRY.counter("fusion.kernels_compiled").get(),
            "lanes": [rec["cache"] for rec in recs],
            "sigs": [rec["signature"] for rec in recs],
            "flops": [t.get("flops") for t in totals.values()],
            "checksum": float(r.sum()),
        }))
        """
    )
    env = dict(os.environ, HEAT_TPU_CACHE_DIR=str(tmp_path))
    for k in (
        "HEAT_TPU_FAULT_PLAN", "HEAT_TPU_CHAOS", "HEAT_TPU_SHAPE_BUCKETS",
        "HEAT_TPU_BREAKER_FORCE_OPEN", "HEAT_TPU_AUDIT_RATE",
    ):
        env.pop(k, None)

    def run():
        out = subprocess.run(
            [sys.executable, "-c", prog], env=env, cwd=_REPO,
            capture_output=True, text=True, timeout=240,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    first = run()
    assert first["compiles"] >= 1 and first["lanes"] == ["compile"]
    (sig,) = first["sigs"]
    card_path = os.path.join(str(tmp_path), "cost", sig + ".json")
    assert os.path.exists(card_path)
    card = json.load(open(card_path))
    assert card["available"] is True and card["flops"] > 0

    second = run()
    assert second["compiles"] == 0, second
    assert second["lanes"] == ["l2"] and second["sigs"] == [sig]
    assert second["flops"] == first["flops"] and second["flops"][0] > 0
    assert second["checksum"] == first["checksum"]


def test_cost_card_unavailable_fallback():
    class _NoCost:
        def cost_analysis(self):
            raise RuntimeError("backend refuses")

    assert flight.cost_card_from(_NoCost()) == {"available": False}
    assert flight.cost_card_from(object()) == {"available": False}


def test_totals_and_hottest_table(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    x = _fresh(seed=13)
    for _ in range(3):
        _chain(x).numpy()
    t = flight.totals()
    assert len(t) == 1
    (tot,) = t.values()
    assert tot["flushes"] == 3 and tot["wall_s"] > 0
    assert tot.get("flops", 0) > 0  # cost card folded in
    hot = flight.hottest(5)
    assert hot and hot[0]["flushes"] == 3
    text = report.render()
    assert "hottest signatures" in text
    tel = report.telemetry()
    assert tel["flight"]["records"] == 3
    assert tel["flight"]["signatures"] == 1


# ---------------------------------------------------------------- satellites
def test_compile_latency_histogram_and_telemetry(monkeypatch):
    with registry.capture():
        _chain(_fresh(seed=17)).numpy()  # one fresh in-memory compile
        h = registry.REGISTRY.histogram("fusion.compile_latency")
        assert h.count == 1 and h.sum > 0
        _chain(_fresh(seed=17)).numpy()  # L1 hit: no new observation
        assert h.count == 1
        tel = report.telemetry()
    assert tel["fusion_compile_latency"]["count"] == 1
    assert tel["fusion_compile_latency"]["p99_us"] >= tel["fusion_compile_latency"]["p50_us"] > 0


def test_compile_latency_observed_on_aot_path(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_CACHE_DIR", str(tmp_path))
    with registry.capture():
        _chain(_fresh(seed=19)).numpy()  # AOT compile through disk.store
        assert registry.REGISTRY.histogram("fusion.compile_latency").count == 1
        fusion.clear_cache()
        _chain(_fresh(seed=19)).numpy()  # L2 hit: no compile, no observation
        assert registry.REGISTRY.histogram("fusion.compile_latency").count == 1


def test_scheduler_span_nesting_across_worker_threads(monkeypatch):
    """ISSUE 13 satellite: per-thread span stacks + explicit cross-thread
    parent propagation — concurrent async flushes on ≥2 scheduler workers
    nest under the scheduling request, tagged with their own thread ids,
    and never under each other."""
    from heat_tpu.serving.scheduler import FlushScheduler

    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    with registry.capture():
        with FlushScheduler(max_workers=2) as sched:
            with events.span("request"):
                futs = [
                    sched.schedule(_chain(_fresh(seed=20 + i)))
                    for i in range(8)
                ]
            for f in futs:
                f.result()
    spans = [r for r in events.records() if r["name"] == "serving.flush"]
    assert len(spans) == 8
    main_tid = threading.get_ident()
    for s in spans:
        assert s["parent"] == "request"  # cross-thread propagation
        assert s["depth"] == 0  # worker stacks start empty: no corruption
        assert isinstance(s["tid"], int) and s["tid"] != main_tid
        assert s["attrs"]["queued_ms"] >= 0.0
    # the flush records carry the scheduler queue time + worker thread id
    frecs = _flushes()
    assert len(frecs) == 8
    for r in frecs:
        assert r["queue_s"] >= 0.0 and r["tid"] != main_tid


def test_every_event_record_carries_thread_id():
    with registry.capture():
        with events.span("outer"):
            events.event("tick")
        events.record("pre-timed", 0.01)
    recs = events.records()
    assert len(recs) == 3
    assert all(isinstance(r["tid"], int) for r in recs)


def test_elastic_transitions_land_in_ring(monkeypatch, tmp_path):
    from heat_tpu.robustness.elastic import ElasticSupervisor

    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    sup = ElasticSupervisor(str(tmp_path), process_id=0, num_processes=2)
    sup.drain_and_save(None, step=3)
    states = [r["state"] for r in flight.records("elastic")]
    assert states == ["draining", "saving", "saved"]
    assert flight.statusz()["elastic"] == "saved"


def test_eager_collective_dispatch_recorded(monkeypatch):
    from heat_tpu.core.communication import get_comm

    comm = get_comm()
    if not comm.is_distributed():
        pytest.skip("collective shims need a multi-device mesh")
    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    x = np.arange(comm.size * 4, dtype=np.float32)
    comm.Allreduce(x, "sum", split=0)
    recs = flight.records("collective")
    assert [r["collective"] for r in recs] == ["allreduce"]
    assert recs[0]["wall_s"] >= 0.0


# ---------------------------------------------------------------- statusz CLI
def test_statusz_payload_shape(monkeypatch, tmp_path):
    monkeypatch.setenv("HEAT_TPU_FLIGHT", "1")
    _chain(_fresh(seed=23)).numpy()
    payload = flight.statusz()
    assert payload["ok"] is True
    assert set(("telemetry", "breakers", "elastic", "cache_slo", "flight")) <= set(payload)
    assert isinstance(payload["breakers"], dict)
    assert payload["flight"]["records"] == 1
    assert payload["flight"]["enabled"] is True
    json.dumps(payload, default=str)  # serializable — the readiness wire shape


def test_flight_cli_statusz_and_usage(tmp_path):
    env = dict(os.environ)
    for k in ("HEAT_TPU_FAULT_PLAN", "HEAT_TPU_CHAOS", "HEAT_TPU_CACHE_DIR"):
        env.pop(k, None)
    out = subprocess.run(
        [sys.executable, "-m", "heat_tpu.monitoring.flight", "statusz", "--selftest"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    payload = json.loads(out.stdout)
    assert payload["ok"] is True and payload["flight"]["records"] >= 1
    assert payload["flight"]["enabled"] is True
    bad = subprocess.run(
        [sys.executable, "-m", "heat_tpu.monitoring.flight", "nonsense"],
        env=env, cwd=_REPO, capture_output=True, text=True, timeout=240,
    )
    assert bad.returncode == 2
    assert "usage:" in bad.stderr


# ---------------------------------------------------------------- ledger guard
_METRIC_RE = re.compile(r'REGISTRY\.(counter|gauge|histogram)\(\s*f?"([^"]+)"')
_LEDGER_ROW = re.compile(r"\|\s*`([^`]+)`\s*\|\s*(counter|gauge|histogram)\s*\|")


def _source_metrics():
    found = set()
    pkg = os.path.join(_REPO, "heat_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        if "__pycache__" in dirpath:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fname), "r") as f:
                src = f.read()
            for kind, name in _METRIC_RE.findall(src):
                found.add((name, kind))
    return found


def _ledger_metrics():
    path = os.path.join(_REPO, "doc", "observability_notes.md")
    text = open(path).read()
    m = re.search(r"<!-- ledger:begin -->(.*?)<!-- ledger:end -->", text, re.S)
    assert m, "counter ledger markers missing from doc/observability_notes.md"
    return {(name, kind) for name, kind in _LEDGER_ROW.findall(m.group(1))}


def test_counter_catalog_ledger_in_sync():
    """Drift guard (ISSUE 13 satellite): every statically-named
    ``REGISTRY.counter/gauge/histogram`` in ``heat_tpu/`` must appear in the
    doc ledger, and the ledger must carry no dead entries. (Names built from
    runtime variables — the ``memory.*`` gauges — are documented prose, not
    ledger rows: the grep cannot see them.)"""
    src = _source_metrics()
    ledger = _ledger_metrics()
    missing = sorted(src - ledger)
    dead = sorted(ledger - src)
    assert not missing, f"metrics missing from the doc ledger: {missing}"
    assert not dead, f"dead ledger entries (metric no longer in source): {dead}"
