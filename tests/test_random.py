"""Tests for the counter-based RNG (parity model: reference
heat/core/tests/test_random.py)."""

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import _compat


def test_seed_reproducibility():
    ht.random.seed(1234)
    a = ht.random.rand(16, 4, split=0)
    ht.random.seed(1234)
    b = ht.random.rand(16, 4, split=0)
    np.testing.assert_array_equal(a.numpy(), b.numpy())
    c = ht.random.rand(16, 4)
    assert not np.array_equal(a.numpy(), c.numpy())


def test_state_roundtrip():
    ht.random.seed(7)
    _ = ht.random.rand(8)
    state = ht.random.get_state()
    assert state[0] == "Threefry"
    x = ht.random.rand(8)
    ht.random.set_state(state)
    y = ht.random.rand(8)
    np.testing.assert_array_equal(x.numpy(), y.numpy())
    with pytest.raises(TypeError):
        ht.random.set_state("bogus")
    with pytest.raises(ValueError):
        ht.random.set_state(("NotThreefry", 0, 0))


def test_rand_range_dtype():
    ht.random.seed(0)
    a = ht.random.rand(100)
    assert a.dtype is ht.float32
    assert float(a.min().larray) >= 0.0
    assert float(a.max().larray) < 1.0
    import jax

    with _compat.enable_x64(True):  # the f64 draw path, genuinely 64-bit
        b = ht.random.rand(5, 5, dtype=ht.float64)
        assert b.shape == (5, 5)
        assert b.larray.dtype == np.float64


def test_randn_normal_standard_normal():
    ht.random.seed(0)
    a = ht.random.randn(2000)
    assert abs(float(ht.mean(a).larray)) < 0.1
    assert abs(float(ht.std(a).larray) - 1.0) < 0.1
    n = ht.random.normal(5.0, 2.0, (2000,))
    assert abs(float(ht.mean(n).larray) - 5.0) < 0.25
    s = ht.random.standard_normal((4, 4), split=0)
    assert s.shape == (4, 4) and s.split == 0
    with pytest.raises(ValueError):
        ht.random.normal(0.0, -1.0, (3,))


def test_randint():
    ht.random.seed(0)
    a = ht.random.randint(0, 10, size=(200,))
    arr = a.numpy()
    assert arr.min() >= 0 and arr.max() < 10
    assert a.dtype is ht.int32
    b = ht.random.randint(5, size=(50,))
    assert b.numpy().max() < 5
    with pytest.raises(ValueError):
        ht.random.randint(5, 5)


def test_randperm_permutation():
    ht.random.seed(0)
    p = ht.random.randperm(32)
    assert sorted(p.numpy().tolist()) == list(range(32))
    x = ht.arange(10)
    px = ht.random.permutation(x)
    assert sorted(px.numpy().tolist()) == list(range(10))
    pr = ht.random.permutation(8)
    assert sorted(pr.numpy().tolist()) == list(range(8))
    with pytest.raises(TypeError):
        ht.random.permutation("x")
    with pytest.raises(TypeError):
        ht.random.randperm(1.5)


def test_aliases():
    assert ht.random.random_sample is ht.random.random
    assert ht.random.ranf is ht.random.random
    assert ht.random.sample is ht.random.random
    assert ht.random.random_integer is ht.random.randint
    r = ht.random.random((3, 3))
    assert r.shape == (3, 3)


def test_randint_non_power_of_two_uniform():
    # the 64-bit-draw modulo reduction (bias ≤ rng/2^64): a 14-wide range over a
    # large sample must be near-uniform — the old single-word modulo had visible
    # structure only for enormous ranges, but this exercises the bit-loop path
    ht.random.seed(42)
    a = ht.random.randint(3, 17, (20000,), split=0)
    arr = a.numpy()
    assert arr.min() >= 3 and arr.max() < 17
    counts = np.bincount(arr - 3, minlength=14)
    expect = 20000 / 14
    assert counts.min() > expect * 0.85 and counts.max() < expect * 1.15


def test_randint_range_exceeding_uint32_requires_x64():
    if not __import__("jax").config.jax_enable_x64:
        with pytest.raises(ValueError):
            ht.random.randint(0, 1 << 40, (4,))


def test_rand_f64_53bit_and_randint_64bit_subprocess():
    # 64-bit draw quality needs x64, which must be configured before backend
    # init — validate in a subprocess (ADVICE r2: f64 draws were quantized to
    # 2^-24; randint had modulo bias and truncated ranges > 2^32)
    import subprocess
    import sys

    code = """
import numpy as np
import heat_tpu as ht
ht.random.seed(3)
a = ht.random.rand(100000, dtype=ht.float64, split=0).numpy()
assert a.dtype == np.float64
frac = a * (1 << 24)
assert not np.allclose(frac, np.round(frac)), 'f64 draws quantized to 2^-24'
b = ht.random.randint(0, 1 << 40, (2000,), dtype=ht.int64).numpy()
assert b.dtype == np.int64 and b.max() > (1 << 36) and b.min() >= 0
print('OK')
"""
    env = dict(
        __import__("os").environ,
        JAX_ENABLE_X64="1",
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
    )
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True, text=True, timeout=300
    )
    assert out.returncode == 0 and "OK" in out.stdout, out.stdout + out.stderr


def test_uniform_distribution_quality():
    # empirical CDF of rand must match U(0,1): KS-style bound over 50k draws
    ht.random.seed(101)
    u = np.sort(ht.random.rand(50000, split=0).numpy())
    n = len(u)
    ecdf = np.arange(1, n + 1) / n
    ks = np.max(np.abs(ecdf - u))
    assert ks < 1.63 / np.sqrt(n) * 2, ks  # ~alpha=0.01 with generous slack
    # moments
    assert abs(u.mean() - 0.5) < 0.01
    assert abs(u.var() - 1.0 / 12) < 0.005


def test_normal_distribution_quality():
    ht.random.seed(102)
    z = ht.random.randn(50000, split=0).numpy()
    assert abs(z.mean()) < 0.02
    assert abs(z.std() - 1.0) < 0.02
    assert abs((z < 0).mean() - 0.5) < 0.01
    # tails: P(|z| > 3) ~ 0.0027
    assert 0.0005 < (np.abs(z) > 3).mean() < 0.008


def test_device_count_invariance_subprocess():
    # the counter-based design's core claim: identical draws at ANY device
    # count (reference random.py:55-202 rank-range invariance)
    import os
    import subprocess
    import sys

    code = """
import numpy as np
import heat_tpu as ht
ht.random.seed(77)
a = ht.random.rand(1000, split=0).numpy()
ht.random.seed(77)
b = ht.random.randint(0, 1000, (500,), split=0).numpy()
np.save(r'{out}', np.concatenate([a, b.astype(np.float64)]))
"""
    outs = []
    for ndev in (1, 4):
        out_file = f"/tmp/rng_inv_{ndev}.npy"
        env = dict(
            os.environ,
            PYTHONPATH="",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={ndev}",
        )
        r = subprocess.run(
            [sys.executable, "-c", code.format(out=out_file)],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        outs.append(np.load(out_file))
    np.testing.assert_array_equal(outs[0], outs[1])


def test_randperm_uniformity_and_permutation_array():
    # every permutation position must be ~uniform over many draws
    ht.random.seed(200)
    n, reps = 8, 300
    counts = np.zeros((n, n), np.int64)  # counts[pos, val]
    for _ in range(reps):
        p = ht.random.randperm(n).numpy()
        counts[np.arange(n), p] += 1
    expect = reps / n
    assert counts.min() > expect * 0.4 and counts.max() < expect * 1.8, counts
    # permutation of a 2-D array shuffles rows, preserving row contents
    a_np = np.arange(20.0, dtype=np.float32).reshape(5, 4)
    perm = ht.random.permutation(ht.array(a_np, split=0))
    pn = perm.numpy()
    assert sorted(pn[:, 0].tolist()) == sorted(a_np[:, 0].tolist())
    for row in pn:
        assert row.tolist() in a_np.tolist()


def test_state_counter_advances_per_draw():
    ht.random.seed(5)
    s0 = ht.random.get_state()
    ht.random.rand(100)
    s1 = ht.random.get_state()
    assert s1[2] > s0[2]  # counter advanced
    ht.random.set_state(("Threefry", 5, s0[2]))
    a = ht.random.rand(100).numpy()
    ht.random.set_state(("Threefry", 5, s0[2]))
    b = ht.random.rand(100).numpy()
    np.testing.assert_array_equal(a, b)
