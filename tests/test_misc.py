"""Tests for indexing, printing, memory, devices, constants, tiling, utils.data
(parity model: reference heat/core/tests/ + heat/utils/tests/)."""

import numpy as np
import pytest

import heat_tpu as ht


def test_nonzero_where():
    a = ht.array(np.array([[0, 1], [2, 0]]), split=0)
    nz = ht.nonzero(a)
    np.testing.assert_array_equal(nz.numpy(), np.stack(np.nonzero(a.numpy()), axis=1))
    v = ht.array(np.array([0, 3, 0, 5]))
    np.testing.assert_array_equal(ht.nonzero(v).numpy(), np.nonzero(v.numpy())[0])
    w = ht.where(a > 0, a, -1)
    np.testing.assert_array_equal(w.numpy(), np.where(a.numpy() > 0, a.numpy(), -1))
    w2 = ht.where(a > 0)
    np.testing.assert_array_equal(w2.numpy(), np.stack(np.nonzero(a.numpy()), axis=1))
    with pytest.raises(TypeError):
        ht.where(a > 0, a)


def test_printing_options():
    opts = ht.get_printoptions()
    assert "precision" in opts
    ht.set_printoptions(precision=2)
    assert ht.get_printoptions()["precision"] == 2
    ht.set_printoptions(profile="full")
    ht.set_printoptions(profile="short")
    ht.set_printoptions(profile="default")
    ht.local_printing()
    ht.global_printing()
    ht.print0("rank0 print")


def test_memory():
    a = ht.ones((3,), split=0)
    b = ht.copy(a)
    b.lloc[0] = 5.0
    assert float(a.larray[0]) == 1.0
    assert ht.sanitize_memory_layout(a, "C") is a
    with pytest.raises(ValueError):
        ht.sanitize_memory_layout(a, "X")
    c = a.copy()
    np.testing.assert_array_equal(c.numpy(), a.numpy())


def test_devices():
    import jax

    assert ht.cpu.device_type == "cpu"
    d = ht.get_device()
    if jax.default_backend() == "cpu":
        assert d.device_type == "cpu"  # forced CPU mesh
    else:
        # on real hardware the default must be the accelerator, never cpu
        assert d.device_type != "cpu"
    assert ht.sanitize_device(None) is d
    assert ht.sanitize_device("cpu") is ht.cpu
    assert ht.sanitize_device(ht.cpu) is ht.cpu
    with pytest.raises(ValueError):
        ht.sanitize_device("quantum")
    ht.use_device("cpu")
    assert ht.get_device() is ht.cpu
    assert "cpu" in repr(ht.cpu)
    assert ht.cpu == ht.cpu
    assert hash(ht.cpu) == hash(ht.cpu)


def test_constants():
    assert ht.pi == np.pi
    assert ht.e == np.e
    assert ht.inf == np.inf
    assert np.isnan(ht.nan)
    assert ht.Inf is ht.inf


def test_tiling():
    from heat_tpu.core.tiling import SplitTiles, SquareDiagTiles

    a = ht.array(np.arange(64.0).reshape(16, 4), split=0)
    st = SplitTiles(a)
    assert st.arr is a
    p_sz = ht.get_comm().size
    assert st.tile_locations.shape == (p_sz, p_sz)
    t0 = st[0, 0]
    # first chunk takes the remainder (reference chunk layout: sizes differ by <= 1)
    _, lshape0, _ = ht.get_comm().chunk((16, 4), 0, rank=0)
    assert t0.shape[0] == lshape0[0]
    st[0, 0] = np.zeros_like(np.asarray(t0))
    assert float(a.larray[0, 0]) == 0.0
    sq = SquareDiagTiles(a, tiles_per_proc=1)
    assert sq.tile_rows >= 1 and sq.tile_columns >= 1
    tile = sq.get_tile(0, 0)
    sq.set_tile(0, 0, np.ones_like(np.asarray(tile)))
    assert float(a.larray[0, 0]) == 1.0
    with pytest.raises(ValueError):
        SquareDiagTiles(ht.ones(3))


def test_dataloader_dataset():
    data = np.arange(64.0, dtype=np.float32).reshape(16, 4)
    ds = ht.utils.data.Dataset(ht.array(data, split=0))
    assert len(ds) == 16
    loader = ht.utils.data.DataLoader(ds, batch_size=4)
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0].shape == (4, 4)
    # epoch 2 reshuffles
    ht.random.seed(0)
    batches2 = list(loader)
    assert len(batches2) == 4
    # DNDarray direct
    loader2 = ht.utils.data.DataLoader(ht.array(data), batch_size=5, drop_last=False)
    assert len(loader2) == 4
    with pytest.raises(TypeError):
        ht.utils.data.DataLoader()


def test_dataset_shuffle():
    data = np.arange(32.0, dtype=np.float32).reshape(16, 2)
    ds = ht.utils.data.Dataset(ht.array(data, split=0))
    ht.random.seed(5)
    ht.utils.data.dataset_shuffle(ds)
    shuffled = np.asarray(ds.htdata.larray)
    assert not np.array_equal(shuffled, data)
    np.testing.assert_array_equal(np.sort(shuffled[:, 0]), data[:, 0])
    ds.Shuffle()
    ds.Ishuffle()


def test_mnist_synthetic(tmp_path):
    ds = ht.utils.data.MNISTDataset(str(tmp_path), train=True)
    img, lbl = ds[0]
    assert img.shape == (28, 28)
    assert 0 <= int(lbl) <= 9
    assert len(ds) > 0
    assert ds.targets.shape[0] == len(ds)


def test_parter():
    p = ht.utils.data.parter(10)
    assert p.shape == (10, 10)
    s = np.linalg.svd(p.numpy(), compute_uv=False)
    assert abs(s[0] - np.pi) < 0.1


def test_partial_h5(tmp_path):
    import h5py

    path = str(tmp_path / "p.h5")
    with h5py.File(path, "w") as f:
        f["data"] = np.arange(200.0, dtype=np.float32).reshape(50, 4)
        f["labels"] = np.arange(50)
    ds = ht.utils.data.PartialH5Dataset(path, dataset_names=["data", "labels"], initial_load=20, load_length=10)
    assert len(ds) == 50
    x, y = ds[0]
    assert x.shape == (4,)
    it = ht.utils.data.PartialH5DataLoaderIter(ds, batch_size=5)
    batches = list(it)
    assert len(batches) == 4
    ds.Shuffle()
    ds.close()


def test_vision_transforms():
    from heat_tpu.utils import vision_transforms as vt

    f = vt.normalize(0.5, 0.5)
    np.testing.assert_allclose(np.asarray(f(np.array([1.0]))), [1.0])
    g = vt.to_tensor()
    out = np.asarray(g(np.array([255], np.uint8)))      # integer input: scaled
    np.testing.assert_allclose(out, [1.0])
    out = np.asarray(g(np.array([0.25], np.float32)))   # float input: passthrough
    np.testing.assert_allclose(out, [0.25])
    with pytest.raises(AttributeError):
        vt.DefinitelyNotATransform


def test_version():
    assert ht.__version__.startswith("0.")


def test_vision_transforms_native():
    """jnp-native JnpCompose/JnpToTensor/JnpNormalize/JnpLambda (reference
    vision_transforms.py is a torchvision passthrough; these work without it).
    Named classes are used directly so the test is valid even when torchvision
    is installed (the bare names then resolve to torchvision via __getattr__)."""
    from heat_tpu.utils import vision_transforms as vt

    img = (np.arange(24, dtype=np.uint8).reshape(4, 2, 3) * 10)  # HWC, 3 channels
    tf = vt.JnpCompose(
        [vt.JnpToTensor(), vt.JnpNormalize([0.5, 0.5, 0.5], [0.5, 0.5, 0.5])]
    )
    out = np.asarray(tf(img))
    want = (np.transpose(img, (2, 0, 1)).astype(np.float32) / 255.0 - 0.5) / 0.5
    assert out.shape == (3, 4, 2)  # torchvision ToTensor: HWC -> CHW
    np.testing.assert_allclose(out, want, atol=1e-6)
    chw = np.ones((3, 4, 4), np.float32)
    out = np.asarray(vt.JnpNormalize([1.0, 1.0, 0.0], [1.0, 2.0, 4.0])(chw))
    np.testing.assert_allclose(out[0], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[2], 0.25, atol=1e-6)
    # HWC float input: per-channel stats broadcast on the trailing axis
    hwc = np.ones((4, 4, 3), np.float32)
    out = np.asarray(vt.JnpNormalize([1.0, 1.0, 0.0], [1.0, 2.0, 4.0])(hwc))
    np.testing.assert_allclose(out[..., 0], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[..., 2], 0.25, atol=1e-6)
    # ToTensor transposes any channel count, not just 1/3/4
    assert vt.JnpToTensor()(np.zeros((4, 5, 2), np.float32)).shape == (2, 4, 5)
    assert float(np.asarray(vt.JnpLambda(lambda x: x + 1)(np.zeros(())))) == 1.0
    # without torchvision the bare names fall back to the Jnp classes
    try:
        import torchvision  # noqa: F401
    except ImportError:
        assert vt.Compose is vt.JnpCompose and vt.ToTensor is vt.JnpToTensor


def test_square_diag_tiles_full_api():
    # VERDICT r2 #7: the reference's full SquareDiagTiles API (tiling.py:331-1257)
    from heat_tpu.core.tiling import SquareDiagTiles

    p = ht.get_comm().size
    a = ht.zeros((4 * p, 10), split=0)
    t = SquareDiagTiles(a, tiles_per_proc=2)
    assert t.tile_rows == 2 * p
    assert t.tile_rows_per_process == [2] * p
    assert t.tile_columns_per_process == [t.tile_columns] * p
    assert sum(np.diff(t.row_indices)) + (4 * p - t.row_indices[-1]) == 4 * p
    # tile_map: owners ascend along the split axis; starts match indices
    tm = t.tile_map
    assert tm.shape == (t.tile_rows, t.tile_columns, 3)
    assert (np.diff(tm[:, 0, 2]) >= 0).all()
    assert tm[:, 0, 0].tolist() == t.row_indices
    assert 0 <= t.last_diagonal_process < p

    # get/set via global tile keys
    t[0, 0] = 22.0
    assert float(np.asarray(t[0, 0]).mean()) == 22.0
    if p > 1:
        with pytest.raises(ValueError):
            t[0 : 2 * p, 0]  # crosses device boundaries
        with pytest.raises(ValueError):
            t.get_start_stop((slice(0, 2 * p), 0))

    # local addressing: tile (0, k) of device r is global tile (2r, k)
    r = p - 1
    assert t.local_to_global((0, 1), rank=r) == (2 * r, 1)
    t.local_set((0, 0), 33.0, rank=r)
    assert float(np.asarray(t.local_get((0, 0), rank=r)).mean()) == 33.0
    assert float(np.asarray(t[2 * r, 0]).mean()) == 33.0
    # start/stop is owner-relative
    st0, sp0, st1, sp1 = t.get_start_stop((2 * r, 1))
    assert st0 == 0 and sp0 == 2

    # match_tiles: a square Q adopts A's boundaries on both axes
    q = ht.zeros((4 * p, 4 * p), split=0)
    qt = SquareDiagTiles(q, tiles_per_proc=2)
    qt.match_tiles(t)
    assert qt.row_indices == t.row_indices
    assert qt.col_indices[: len(t.row_indices)] == t.row_indices
    assert qt.tile_map.shape[0] == qt.tile_rows

    # split=1 variant
    b = ht.zeros((10, 4 * p), split=1)
    tb = SquareDiagTiles(b, tiles_per_proc=1)
    assert tb.tile_columns == p
    assert tb.tile_columns_per_process == [1] * p
    assert tb.local_to_global((0, 0), rank=r) == (0, r)
    with pytest.raises(TypeError):
        qt.match_tiles("nope")
    with pytest.raises(TypeError):
        SquareDiagTiles(a, tiles_per_proc=1.5)


def test_printoptions_modes():
    # printing modes + context manager (reference core/printing tests)
    from heat_tpu.core import printing

    a = ht.arange(2000, split=0).astype(ht.float32)
    s = str(a)
    assert "..." in s  # threshold summarization
    printing.set_printoptions(threshold=10**6)
    try:
        s_full = str(ht.arange(50, split=0))
        assert "..." not in s_full
    finally:
        printing.set_printoptions(threshold=1000)
    printing.set_printoptions(precision=2)
    try:
        s2 = str(ht.array(np.array([1.23456789], np.float32)))
        assert "1.23" in s2 and "1.2345" not in s2
    finally:
        printing.set_printoptions(precision=4)
    # print0 emits only once per logical controller
    printing.print0("ok")
    opts = printing.get_printoptions()
    assert "precision" in opts


def test_profiling_utils_smoke(tmp_path):
    from heat_tpu.utils import profiling

    t = profiling.Timer()
    out = ht.sum(ht.ones((64, 64), split=0))
    dt = t.lap(out.larray)
    assert dt > 0
    with profiling.annotate("block"):
        _ = ht.ones(8).numpy()


def test_sanitation_contract():
    from heat_tpu.core import sanitation

    a = ht.ones((4, 4), split=0)
    sanitation.sanitize_in(a)
    with pytest.raises(TypeError):
        sanitation.sanitize_in(np.ones(3))
    out = ht.zeros((4, 4), split=0)
    sanitation.sanitize_out(out, (4, 4), 0, a.device)
    with pytest.raises(ValueError):
        sanitation.sanitize_out(out, (5, 5), 0, a.device)
    with pytest.raises(TypeError):
        sanitation.sanitize_out("zz", (4, 4), 0, a.device)


def test_stride_tricks_surface():
    from heat_tpu.core import stride_tricks

    assert stride_tricks.sanitize_axis((4, 5), -1) == 1
    assert stride_tricks.sanitize_axis((4, 5), None) is None
    with pytest.raises(ValueError):
        stride_tricks.sanitize_axis((4, 5), 2)
    assert stride_tricks.broadcast_shapes((3, 1), (1, 4)) == (3, 4)
    with pytest.raises(ValueError):
        stride_tricks.broadcast_shapes((3, 2), (4, 2))
    assert stride_tricks.sanitize_shape(5) == (5,)
    assert stride_tricks.sanitize_shape((2, 3)) == (2, 3)


def test_local_to_global_clamps_to_own_tiles():
    # review r3: an over-long local slice must clamp to the device's OWN tile
    # range, not spill into the next rank's tiles
    from heat_tpu.core.tiling import SquareDiagTiles

    p = ht.get_comm().size
    if p < 2:
        pytest.skip("needs a multi-device mesh")
    a = ht.zeros((4 * p, 10), split=0)
    t = SquareDiagTiles(a, tiles_per_proc=2)
    g = t.local_to_global((slice(1, 99), 0), rank=0)
    assert g[0] == slice(1, 2)  # rank 0 owns global tiles [0, 2)
    # and the clamped request resolves on one device
    _ = t[g]
