"""
Benchmark: KMeans iterations/sec/chip (the BASELINE.json north-star workload —
reference benchmarks/kmeans/, SURVEY.md §3.4/§6).

Runs the jitted Lloyd iteration (heat_tpu.cluster.kmeans._kmeans_step: one MXU GEMM
for assignment + one for the masked centroid update) on synthetic Gaussian blobs on
the available accelerator and prints ONE JSON line.

``vs_baseline``: the reference (marianna13/heat) delegates all local compute to
PyTorch and cannot run here (no mpi4py in this image), so the baseline is the same
Lloyd iteration implemented on the reference's compute engine — torch on CPU, single
process (exactly what `mpirun -np 1 benchmarks/kmeans/heat-cpu.py` measures up to MPI
constants). vs_baseline = (our iters/sec) / (torch-CPU iters/sec).

Measurement integrity (round-4 rework; VERDICT r3 #1 "make the bench's
self-certification gate the headline"): the shared tunneled chip's throughput
varies run to run, and a dispatch-time fluctuation can make one differenced
pair report a rate the silicon cannot physically sustain (r03 shipped
max(rates) = 18.9k iters/s, implying 1,345 GB/s of HBM traffic on an 819 GB/s
chip). The bench now *acts* on its own physics check instead of merely
printing it:

* trials are interleaved (short, long) pairs, so slow drift cancels out of the
  differenced rate instead of biasing one leg;
* every pair is gated against a physical traffic model and ceiling — a pair
  implying traffic the silicon cannot sustain is *discarded* as a measurement
  artifact;
* gating continues over extra rounds until the fixed valid-pair target is
  reached (3 for the anchors, 7 for the headline) or the pair budget runs
  out — the target is never conditioned on the spread statistic, so
  ``jitter_pct`` stays an unbiased readout;
* the headline ``value`` is the **median of the valid pairs** — never a max;
* ``measurement_valid`` certifies the result: >= 3 valid pairs AND the
  median's own implied bandwidth at or below the roofline;
* ``jitter_pct`` is the relative inter-quartile spread of the valid pairs —
  a future reader can tell noise from regression without a second run;
* the torch-CPU baseline uses the same interleaved paired-differencing
  (VERDICT r3 weak #6 — the denominator now has the same integrity machinery
  as the numerator);
* two more independently-rooflined anchors ship in the same line (VERDICT r3
  #9): ``matmul_mfu_tflops`` against the MXU peak and ``cdist_gbps`` against
  the HBM roofline, so chip weather can be told apart from a regression on
  more than one workload.

Round-5 rework (VERDICT r4 #1 and #4; scripts/kmeans_hlo_audit.py):

* The rounds-1-4 KMeans bytes model (one bf16 HBM pass + labels, 71.3 MB/iter
  against nominal 819 GB/s — the "75% of HBM roofline" number) was a category
  error: the compiled loop pins the bf16 copy of x, x_norm and the label
  buffers in VMEM (HBM temp of the whole 30-iteration program: 2.3 MB), so
  steady-state HBM traffic per iteration is ~zero. The audited per-iteration
  traffic is 148.9 MB of VMEM (two GEMM-operand passes over bf16 x + three
  label passes + the min-distance write) — doc/kmeans_hlo_audit.md.
* The headline is therefore expressed against a *measured same-session* HBM
  stream probe (``hbm_stream_gbps``): ``kmeans_vs_hbm_stream`` is the ratio
  of the step's implied VMEM rate to that probe — >1 is operation no
  HBM-bound formulation could reach. Pairs are gated at a 4x-of-stream
  ceiling (no TPU generation streams VMEM faster than 4x its HBM); rates
  below 1x of stream are possible (loaded chip) and are reported, not gated —
  ``faster_than_hbm`` carries the claim.
* The allreduce metric now obeys its own gate: the 1-chip fallback is an HBM
  read+write roundtrip whose byte model is directly comparable to the HBM
  roofline, so its pairs are gated at the same 1.05x ceiling as every other
  metric (r4 shipped 114.2% with only a note). The ICI number it stands in
  for is explicitly not measurable at n=1 (``ici_gbps: null``); the 8-device
  dryrun psum (MULTICHIP_r05.json) is the multi-device correctness proxy.

Observability: the bench runs under ``heat_tpu.monitoring.capture()`` and the
output line carries a ``telemetry`` block — per-phase wall-time spans, jit
compile-cache misses (count + total compile seconds), collective/placement
counters, and device memory where the backend reports it. The phase spans sit
OUTSIDE every timed leg, so the headline statistics are untouched.
"""

import json
import os
import time

# virtual CPU devices for the scaling line must be configured before jax inits
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import numpy as np

N, F, K = 1_048_576, 32, 8
ITERS = 30
PAIRS_PER_ROUND = 5  # interleaved (short, long) timing pairs per gating round
MIN_VALID = 3  # keep collecting rounds until this many physically valid pairs
MAX_PAIRS = 15  # total pair budget across rounds

# nominal HBM bandwidth (GB/s) and bf16 matmul peak (TFLOP/s) by device kind;
# matched by substring of jax Device.device_kind. CPU / unknown -> None (the
# physics gate is disabled but the statistics machinery still runs).
HBM_ROOFLINES_GBPS = {"TPU v5 lite": 819.0, "TPU v5": 2765.0, "TPU v4": 1228.0}
MXU_PEAKS_TFLOPS = {"TPU v5 lite": 197.0, "TPU v5": 459.0, "TPU v4": 275.0}


def _add_benchmarks_path():
    import sys

    d = os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks")
    if d not in sys.path:
        sys.path.insert(0, d)


def _lookup(device, table):
    kind = str(getattr(device, "device_kind", device))
    best = None
    for key, val in table.items():
        if key in kind and (best is None or len(key) > best[0]):
            best = (len(key), val)
    return best[1] if best else None


def _data(rng, n=N):
    centers = rng.normal(scale=5.0, size=(K, F)).astype(np.float32)
    labels = rng.integers(0, K, size=n)
    return centers[labels] + rng.normal(scale=0.5, size=(n, F)).astype(np.float32)


def _gated_rates(
    run, calib_rate, bytes_per_iter, roofline_gbps, long_seconds=0.8, min_valid=None,
    gates=None,
):
    """
    Physics-gated per-iteration rates from interleaved (short, long) pairs.

    Differencing two dispatch lengths cancels the fixed per-dispatch cost
    (host->device RPC; tens of ms on tunneled runtimes). Interleaving the pairs
    — rather than all-short-then-all-long — keeps slow machine drift from
    biasing one leg. Lengths are sized from the calibration rate so the long
    leg is several hundred ms of device time on any backend.

    Each pair's rate is checked against a hardware roofline: one iteration
    provably consumes at least ``bytes_per_iter`` units of some resource
    (bytes moved for HBM-bound steps, flops issued for MXU-bound ones) whose
    sustained ceiling is ``roofline_gbps`` giga-units/s; a rate implying more
    than ``1.05x`` that ceiling is physically impossible and recorded as
    invalid. Rounds of pairs continue until at least ``min_valid`` (default
    ``MIN_VALID``) valid pairs exist or ``MAX_PAIRS`` is exhausted — a FIXED
    sample-size target, never a condition on the spread statistic itself
    (stopping on low spread would bias ``jitter_pct`` low by optional
    stopping). The headline passes a larger target so one transient
    host-load patch cannot dominate its median.

    ``gates`` generalises the single roofline to several: a list of
    ``(units_per_iter, ceiling_units_per_sec)`` pairs (ceiling ``None`` =
    ungated); a pair is discarded if ANY gate is exceeded. The default is the
    single ``(bytes_per_iter, roofline_gbps)`` gate. linalg_bench passes a
    dual MXU-flops + HBM-bytes gate through this same loop so both bench
    surfaces share one measurement semantics.

    Returns ``(valid_rates, n_total_pairs, n_discarded)``.
    """
    gate_list = (
        gates
        if gates is not None
        else [(bytes_per_iter, None if roofline_gbps is None else roofline_gbps * 1e9)]
    )
    # ``calib_rate`` comes from an un-differenced run and is dispatch-polluted
    # (the ~100 ms tunnel RPC makes it a 10-100x *under*estimate of the device
    # rate for millisecond workloads), so the legs it suggests can be far too
    # short to difference against dispatch jitter. Grow the long leg until the
    # differenced pair time is solidly positive and a good fraction of the
    # target device-seconds — only then are the timing pairs trustworthy.
    long = int(np.clip(calib_rate * 4.0, 10, 6000))
    short = max(1, long // 10)
    for _ in range(6):
        # warm both leg lengths: a lax.scan compiles once per static length, and
        # an unwarmed pair would fold compilation into its timings
        run(short, 0.0)
        run(long, 0.0)
        dt = run(long, 1e-7) - run(short, 2e-7)
        if dt >= 0.5 * long_seconds or long >= 6000:
            break
        if dt > 0.05:  # positive but short: extrapolate to the target, capped
            long = int(np.clip((long - short) * long_seconds / dt, long * 2, 6000))
        else:  # noise-dominated: just grow
            long = min(long * 4, 6000)
        short = max(1, long // 10)
    valid, total, discarded = [], 0, 0
    pair = 0
    target = MIN_VALID if min_valid is None else min_valid
    while len(valid) < target and total < MAX_PAIRS:
        for _ in range(PAIRS_PER_ROUND):
            t_short = run(short, 1e-6 * (2 * pair + 1))
            t_long = run(long, 1e-6 * (2 * pair + 2))
            pair += 1
            total += 1
            dt = t_long - t_short
            rate = (long - short) / dt if dt > 0 else float("inf")
            implied = bytes_per_iter * rate / 1e9
            if os.environ.get("BENCH_DEBUG"):
                import sys

                print(
                    f"  pair {pair}: short={t_short:.3f}s long={t_long:.3f}s "
                    f"rate={rate:.1f}/s implied={implied:.1f}",
                    file=sys.stderr,
                )
            if any(c is not None and u * rate > 1.05 * c for u, c in gate_list):
                discarded += 1  # measurement artifact, not a faster kernel
            elif not np.isfinite(rate) or rate <= 0:
                discarded += 1
            else:
                valid.append(rate)
            if total >= MAX_PAIRS:
                break
    return valid, total, discarded


def _perturb(eps, quantum):
    """
    Map a (possibly tiny) eps to a perturbation factor that SURVIVES the
    workload's dtype rounding: ``1 + round(eps / 1e-7) * quantum``, with
    ``quantum`` at least one representable step of the dtype near 1.0
    (bf16 ~ 2^-7, f32 ~ 2^-18 used here with margin). The raw eps values
    (1e-7..3e-5) round to exactly 1.0 in bf16 — and the sizing probes even in
    f32 — which would make "perturbed" executions bit-identical and
    replayable on the tunneled runtime (the exact artifact the eps machinery
    exists to prevent). Distinct eps inputs stay distinct factors.
    """
    return 1.0 + round(eps / 1e-7) * quantum


def _spread_pct(rates):
    """Relative inter-quartile spread (robust to a single stalled pair)."""
    if len(rates) < 2:
        return 0.0
    q25, q75 = np.percentile(rates, [25, 75])
    return 100.0 * float(q75 - q25) / float(np.median(rates))


def bench_hbm_stream():
    """
    Measured same-session HBM read-stream probe (VERDICT r4 #1: express the
    headline against a measured stream rate, not the nominal 819). A 512 MB
    f32 buffer — 4x too large for VMEM residency — is summed once per scan
    step with a per-step scale factor (nothing replayable, scalar fetch);
    bytes/step = one full read of the buffer. Gated at 1.05x the nominal HBM
    roofline like every other metric.
    """
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    roofline = _lookup(dev, HBM_ROOFLINES_GBPS)
    n_elem = 128 * 1024 * 1024  # 512 MB f32
    rng = np.random.default_rng(3)
    x = jax.device_put(
        jnp.asarray(rng.random(n_elem, dtype=np.float32)), dev
    )

    def prog(x, fac, steps):
        def body(carry, _):
            s, f = carry
            return (s + jnp.sum(x * f, dtype=jnp.float32), f * jnp.float32(1.0 + 2.0**-20)), None

        (s, _), _ = jax.lax.scan(body, (jnp.float32(0.0), fac), None, length=steps)
        return s

    pj = jax.jit(prog, static_argnums=2)

    def run(steps, eps):
        t0 = time.perf_counter()
        float(pj(x, jnp.float32(_perturb(eps, 2.0**-18)), steps))
        return time.perf_counter() - t0

    run(2, 0.0)  # compile + warm
    calib = 2.0 / run(2, 1e-7)
    bytes_per_step = n_elem * 4
    valid, total, discarded = _gated_rates(run, calib, bytes_per_step, roofline)
    if not valid:
        return None, None, False
    rate = float(np.median(valid))
    gbps = bytes_per_step * rate / 1e9
    pct = round(100.0 * gbps / roofline, 1) if roofline else None
    return round(gbps, 1), pct, len(valid) >= MIN_VALID


# Audited per-iteration traffic of the compiled Lloyd step at the bench shape
# (scripts/kmeans_hlo_audit.py, doc/kmeans_hlo_audit.md): two GEMM-operand
# passes over the VMEM-resident bf16 x + three s32 label passes + one bf16
# min-distance write. Steady-state HBM traffic is ~0 (working set pinned in
# VMEM; HBM temp of the whole program: 2.3 MB).
KM_VMEM_BYTES_PER_ITER = 2 * (N * F * 2) + 3 * (N * 4) + N * 2
# VMEM streams at most this multiple of the HBM stream rate on any TPU
# generation — the physical corridor ceiling for the pair gate now that the
# (fictitious) HBM ceiling no longer applies.
VMEM_OVER_HBM_MAX = 4.0


def bench_tpu(data_np, stream_gbps=None):
    import jax
    import jax.numpy as jnp

    from heat_tpu.cluster.kmeans import _kmeans_step, _kmeans_iterate

    dev = jax.devices()[0]
    nominal_hbm = _lookup(dev, HBM_ROOFLINES_GBPS)
    x = jax.device_put(jnp.asarray(data_np), dev)
    centers = x[:K]

    def run(iters, eps):
        # honest timing on async/remote runtimes: perturb the input so no cached
        # result can be replayed, and read the result back to host — the clock
        # only stops when real bytes arrive. The perturbation is quantized to
        # f32-representable steps (raw 1e-7-scale eps would round back to 1.0)
        c2 = centers * np.float32(_perturb(eps, 2.0**-18))
        t0 = time.perf_counter()
        np.asarray(_kmeans_iterate(x, c2, _kmeans_step, iters))
        return time.perf_counter() - t0

    # The two-GEMM XLA step is the sole candidate: measured at up to 104% of
    # nominal MXU MFU on large GEMMs (benchmarks/matmul_mfu_bench.py), XLA leaves
    # a hand-written kernel nothing to win on this workload — a fused pallas
    # Lloyd step was raced here in round 1 AND re-engineered and re-raced in
    # round 3 (bf16-streaming, K-on-sublanes layout, zero lane padding, perfect
    # label agreement) and still lost 3.2x: the skinny K=8 GEMMs collapse MXU
    # utilization inside a kernel, while XLA's full-height GEMMs pipeline at HBM
    # roofline (doc/kmeans_northstar.md).
    np.asarray(_kmeans_iterate(x, centers, _kmeans_step, ITERS))  # compile+warm
    calib = ITERS / run(ITERS, 1e-7)
    # Pair gate (r5): the audited traffic model is VMEM, so the ceiling is the
    # physical corridor VMEM_OVER_HBM_MAX x the *measured same-session* HBM
    # stream. _gated_rates discards pairs implying > 1.05x its roofline
    # argument, so the corridor ceiling is passed pre-divided by 1.05.
    ceiling = (
        VMEM_OVER_HBM_MAX * stream_gbps / 1.05
        if stream_gbps
        else (VMEM_OVER_HBM_MAX * nominal_hbm / 1.05 if nominal_hbm else None)
    )
    valid, total, discarded = _gated_rates(
        run, calib, KM_VMEM_BYTES_PER_ITER, ceiling, min_valid=7
    )
    if valid:
        value = float(np.median(valid))
    else:  # every pair gated out — report the calibration rate, flagged invalid
        value = calib
    implied_vmem_gbps = KM_VMEM_BYTES_PER_ITER * value / 1e9
    vs_stream = implied_vmem_gbps / stream_gbps if stream_gbps else None
    jitter = _spread_pct(valid)
    measurement_valid = (
        len(valid) >= MIN_VALID
        and jitter < 10.0
        and (vs_stream is None or vs_stream <= VMEM_OVER_HBM_MAX)
    )
    return {
        "value": value,
        "jitter_pct": jitter,
        "per_iter_us": 1e6 / value,
        "vmem_traffic_model_mb": round(KM_VMEM_BYTES_PER_ITER / 1e6, 1),
        "implied_vmem_gbps": implied_vmem_gbps,
        "kmeans_vs_hbm_stream": round(vs_stream, 2) if vs_stream else None,
        # >1: the step moves its traffic faster than the chip's measured HBM
        # stream — possible only because the working set is VMEM-resident
        "faster_than_hbm": bool(vs_stream and vs_stream > 1.0),
        "hbm_note": (
            "steady-state HBM/iter ~0: bf16 x + labels are VMEM-resident "
            "across the fori_loop (audit: doc/kmeans_hlo_audit.md)"
        ),
        "measurement_valid": bool(measurement_valid),
        "pairs_valid": len(valid),
        "pairs_discarded": discarded,
        "pairs_total": total,
        "device": f"{dev} [xla]",
    }


def bench_torch_cpu(data_np):
    """
    Reference-engine baseline with the same paired-differencing integrity as
    the numerator (VERDICT r3 weak #6): interleaved (short, long) dispatch
    pairs, median of the differenced rates. No physics gate — the host's
    memory bandwidth is not pinned down the way the chip's HBM is — but the
    median-of-pairs statistic alone removes the +/-25% swing the old
    3-iteration un-paired loop showed.
    """
    import torch

    x = torch.from_numpy(data_np)
    c0 = x[:K].clone()

    def step(x, c):
        # same quadratic-expansion formulation as the TPU path (fair GEMM-based compare)
        d2 = (x * x).sum(1, keepdim=True) - 2.0 * (x @ c.T) + (c * c).sum(1)[None, :]
        labels = torch.argmin(d2, dim=1)
        onehot = torch.nn.functional.one_hot(labels, K).to(x.dtype)
        counts = onehot.sum(0)
        sums = onehot.T @ x
        return torch.where(counts[:, None] > 0, sums / counts.clamp(min=1)[:, None], c)

    def run(iters, eps):
        c = c0 * (1.0 + eps)
        t0 = time.perf_counter()
        for _ in range(iters):
            c = step(x, c)
        float(c.sum())
        return time.perf_counter() - t0

    run(1, 0.0)  # warmup
    calib = 2.0 / run(2, 1e-7)
    long = int(np.clip(calib * 4.0, 4, 64))
    short = max(1, long // 4)
    rates = []
    for pair in range(3):
        t_short = run(short, 1e-6 * (2 * pair + 1))
        t_long = run(long, 1e-6 * (2 * pair + 2))
        dt = t_long - t_short
        rates.append((long - short) / dt if dt > 0 else long / t_long)
    return float(np.median(rates))


def bench_matmul_mfu():
    """
    Second physics anchor (VERDICT r3 #9): measured bf16 GEMM TFLOP/s of the
    framework's matmul path against the chip's MXU peak, using the same gated
    paired-differencing as the headline (benchmarks/matmul_mfu_bench.py's
    fixed 48-matmul chain gave ~33 ms legs — inside dispatch jitter, which
    produced >100%-of-peak readings; here the scan chain is sized adaptively
    and every pair is gated at 1.05x peak).
    """
    import jax
    import jax.numpy as jnp

    n = 4096
    dev = jax.devices()[0]
    peak = _lookup(dev, MXU_PEAKS_TFLOPS)
    rng = np.random.default_rng(1)
    a = jax.device_put(
        jnp.asarray(rng.standard_normal((n, n)).astype(np.float32) / np.sqrt(n), jnp.bfloat16), dev
    )
    b = jax.device_put(
        jnp.asarray(rng.standard_normal((n, n)).astype(np.float32) / np.sqrt(n), jnp.bfloat16), dev
    )

    def prog(a, b, scale, steps):
        def body(x, _):
            # data dependency + per-step perturbation: no step can be elided
            return jnp.matmul(x, b) * scale, None

        x, _ = jax.lax.scan(body, a * scale, None, length=steps)
        return jnp.sum(x.astype(jnp.float32))

    prog_jit = jax.jit(prog, static_argnums=3)

    def run(steps, eps):
        # bf16 spacing near 1.0 is 2^-8; quantize the perturbation to whole
        # bf16 steps so every distinct eps is a distinct executed program
        scale = jnp.bfloat16(_perturb(eps, 2.0**-7))
        t0 = time.perf_counter()
        float(prog_jit(a, b, scale, steps))
        return time.perf_counter() - t0

    run(2, 0.0)
    calib = 2.0 / run(2, 1e-4)
    flops = 2.0 * n * n * n  # one chained matmul per "iteration"
    roofline_gflops = peak * 1e3 if peak else None
    valid, total, discarded = _gated_rates(run, calib, flops, roofline_gflops)
    if not valid:
        return None, None, False
    rate = float(np.median(valid))
    tflops = flops * rate / 1e12
    pct = round(100.0 * tflops / peak, 1) if peak else None
    return round(tflops, 1), pct, len(valid) >= MIN_VALID


def bench_cdist():
    """
    Third physics anchor (VERDICT r3 #9): effective HBM bandwidth of a
    cdist-shaped workload (reference benchmarks/distance_matrix/). A plain
    ``sum(d2)`` consumer turned out NOT to pin bytes — XLA:TPU fuses the
    reduction into the GEMM's output tiles and never writes the (n, n) matrix
    (measured 9,600 steps/s implying an impossible 5.2 TB/s; the step was
    MXU-bound at ~84% of peak). The robust floor: weight the reduction by a
    real (n, n) input mask — ``sum(d2 * mask)`` must *read* all n^2 mask
    floats from HBM every step whether or not d2 materializes, so
    ``n^2 * 4`` bytes/step is a physical floor and the rate pins to the HBM
    roofline like the kmeans headline.
    """
    import jax
    import jax.numpy as jnp

    n, f = 8192, 128
    dev = jax.devices()[0]
    roofline = _lookup(dev, HBM_ROOFLINES_GBPS)
    rng = np.random.default_rng(2)
    x = jax.device_put(jnp.asarray(rng.standard_normal((n, f)).astype(np.float32)), dev)
    mask = jax.device_put(jnp.asarray(rng.random((n, n)).astype(np.float32)), dev)

    def prog(x, mask, fac, steps):
        def body(carry, _):
            s, xx = carry
            d2 = (
                (xx * xx).sum(1, keepdims=True)
                - 2.0 * (xx @ xx.T)
                + (xx * xx).sum(1)[None, :]
            )
            # perturb the carry so every scan step (and every call) computes
            # fresh values — nothing can be replayed, and the body is not
            # loop-invariant even if the factor were constant-folded
            return (s + (d2 * mask).sum(), xx * step_scale), None

        # per-step factor derived from the traced per-call factor: never
        # exactly 1.0 (>= 2^-20 above it — representable in f32), distinct
        # per call, and ~1.0028 total drift over a 1000-step leg
        step_scale = (fac - 1.0) * 0.25 + jnp.float32(1.0 + 2.0**-20)
        (s, _), _ = jax.lax.scan(body, (jnp.float32(0.0), x * fac), None, length=steps)
        return s

    prog_jit = jax.jit(prog, static_argnums=3)

    def run(steps, eps):
        # f32 spacing near 1.0 is 2^-23; quantize to 2^-18 steps so the raw
        # 1e-7-scale eps values do not round back to exactly 1.0
        t0 = time.perf_counter()
        float(prog_jit(x, mask, jnp.float32(_perturb(eps, 2.0**-18)), steps))
        return time.perf_counter() - t0

    run(2, 0.0)  # compile + warm
    calib = 2.0 / run(2, 1e-7)
    bytes_floor = n * n * 4 + 2 * n * f * 4
    valid, total, discarded = _gated_rates(run, calib, bytes_floor, roofline)
    if not valid:
        return None, None, False
    rate = float(np.median(valid))
    gbps = bytes_floor * rate / 1e9
    pct = round(100.0 * gbps / roofline, 1) if roofline else None
    return round(gbps, 1), pct, len(valid) >= MIN_VALID


def bench_allreduce():
    """
    The second BASELINE.json north-star: "DNDarray Allreduce ICI bandwidth
    (GB/s)" — the psum the __reduce_op path emits, measured at several buffer
    sizes (benchmarks/allreduce_bandwidth_bench.py wired in here so the driver
    captures both numbers in one JSON line). With one chip the psum degenerates
    and the number is the buffer's HBM-roundtrip bandwidth; the roofline is
    picked accordingly: TPU v5e ≈ 819 GB/s HBM, ≈ 186 GB/s accumulated ICI
    (4 links × ~46.5 GB/s) for multi-chip.
    """
    import jax

    _add_benchmarks_path()
    from allreduce_bandwidth_bench import bench_size
    from jax.sharding import Mesh

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("d",))
    plat = devs[0].platform
    if plat == "tpu":
        roofline = (
            _lookup(devs[0], HBM_ROOFLINES_GBPS) or 819.0
            if len(devs) == 1
            else 186.0 * len(devs) / 2
        )
        kind = "HBM roundtrip" if len(devs) == 1 else "ICI allreduce"
    else:
        roofline, kind = None, "host memory (CPU mesh)"
    # 256 MB only: the differenced-chain method needs the long leg's device time
    # (tens of ms) to dominate dispatch jitter — small buffers make dt fragile
    # and a max-over-sizes then reports whichever noise inflated most.
    # Pairs are gated at 1.05x the roofline (the roundtrip bytes model counts
    # both directions, so its rate is directly comparable to the HBM roofline).
    best, n_valid, n_discarded = bench_size(
        mesh, 256 * 1024 * 1024, trials=4, ceiling_gbps=roofline, return_stats=True
    )
    pct = round(100.0 * best / roofline, 1) if roofline else None
    ar_valid = n_valid >= 2 and (roofline is None or best <= 1.05 * roofline)
    return round(best, 2), pct, f"{kind}, {len(devs)} device(s)", ar_valid


def bench_scaling_8dev():
    """
    Multichip evidence within the single-chip constraint (VERDICT r2 #10): the
    SAME Lloyd step over the full dataset, once sharded over the 8-virtual-
    device CPU mesh (per-iteration psum of the (k,f) partial sums — the
    collectives are real) and once on a single CPU device. Both runs use the
    same host silicon (XLA multithreads the single-device program across cores
    too), so the ratio isolates the *sharding + collective* overhead rather
    than core contention.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from heat_tpu.cluster.kmeans import _kmeans_step, _kmeans_iterate

    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        return None, None
    n8 = 1 << 18  # bounded host work: the line must cost seconds, not minutes
    data = _data(np.random.default_rng(1), n=n8)
    mesh = Mesh(np.asarray(cpus[:8]), ("d",))
    xs = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P("d", None)))
    c0 = jax.device_put(jnp.asarray(data[:K]), NamedSharding(mesh, P(None, None)))
    x1 = jax.device_put(jnp.asarray(data), cpus[0])
    c1 = jax.device_put(jnp.asarray(data[:K]), cpus[0])

    def rate(x, c, iters=40):
        np.asarray(_kmeans_iterate(x, c, _kmeans_step, iters))
        best = float("inf")
        for t in range(3):
            t0 = time.perf_counter()
            np.asarray(_kmeans_iterate(x, c * (1.0 + 1e-6 * (t + 1)), _kmeans_step, iters))
            best = min(best, time.perf_counter() - t0)
        return iters / best

    r8 = rate(xs, c0)  # 8-device sharded, full N
    r1 = rate(x1, c1)  # 1 device, full N
    overhead_pct = 100.0 * (r1 / r8 - 1.0)
    return round(r8, 1), round(overhead_pct, 1)


def main():
    # Observability (heat_tpu/monitoring/): the whole bench runs under
    # capture() with one span per phase, and the output line carries a compact
    # `telemetry` block (jit compile-cache misses, collective/placement
    # counters, per-phase wall time, device memory where the backend reports
    # it). The timed kernels themselves are plain jitted XLA programs — the
    # phase-level spans add nothing inside any timed leg.
    from heat_tpu import monitoring
    from heat_tpu.monitoring import events as _mev

    rng = np.random.default_rng(0)
    data = _data(rng)
    with monitoring.capture():
        try:
            with _mev.span("bench.hbm_stream"):
                stream_gbps, stream_pct, stream_valid = bench_hbm_stream()
        except Exception:
            stream_gbps = stream_pct = stream_valid = None
        # a probe the bench itself flagged invalid must not set the headline's
        # gate ceiling or its vs-stream ratio — fall back to the nominal roofline
        with _mev.span("bench.kmeans"):
            km = bench_tpu(data, stream_gbps=stream_gbps if stream_valid else None)
        try:
            with _mev.span("bench.torch_cpu_baseline"):
                torch_ips = bench_torch_cpu(data)
            vs = km["value"] / torch_ips
        except Exception:
            torch_ips, vs = None, None
        try:
            with _mev.span("bench.matmul_mfu"):
                mfu_tflops, mfu_pct, mfu_valid = bench_matmul_mfu()
        except Exception:
            mfu_tflops = mfu_pct = mfu_valid = None
        try:
            with _mev.span("bench.cdist"):
                cdist_gbps, cdist_pct, cdist_valid = bench_cdist()
        except Exception:
            cdist_gbps = cdist_pct = cdist_valid = None
        try:
            with _mev.span("bench.allreduce"):
                ar_gbps, ar_pct, ar_note, ar_valid = bench_allreduce()
        except Exception:
            ar_gbps = ar_pct = ar_note = ar_valid = None
        try:
            with _mev.span("bench.scaling_8dev"):
                scale8_ips, scale8_overhead = bench_scaling_8dev()
        except Exception:
            scale8_ips = scale8_overhead = None
        # gated linalg anchors (VERDICT r4 #3) incl. the MXU-blocked
        # qr/solve/svd counterparts and their same-process speedup vs the
        # jnp.linalg baseline (benchmarks/linalg_bench.py); ~2 min of compile
        # on the tunneled chip; BENCH_FAST=1 skips them for quick interactive
        # runs
        linalg = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from linalg_bench import bench_linalg

                with _mev.span("bench.linalg"):
                    linalg = bench_linalg()
            except Exception as e:
                # explicit null-valued keys, like the neighbouring benches: a
                # crashed anchor must be distinguishable from a BENCH_FAST skip
                linalg = {
                    f"{op}_valid": None
                    for op in (
                        "qr", "svd", "solve", "det",
                        "qr_blocked", "svd_blocked", "solve_blocked",
                    )
                }
                linalg["linalg_error"] = repr(e)[:160]
        # deferred-execution fusion anchors (ISSUE 3): effective GB/s of an
        # 8-op elementwise chain through the fused path, the same-process
        # HEAT_TPU_FUSION=0 eager baseline, and their ratio (fusion_speedup),
        # plus the dispatch-layer ops/sec on a tiny operand; ISSUE 4 adds the
        # reduction-sink anchors (fused_reduction_gbps — chain+sum as ONE
        # kernel at the single-read floor — and reduction_sink_speedup vs the
        # same-process HEAT_TPU_FUSION_SINKS=0 baseline)
        elemwise = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from elementwise_bench import bench_elementwise

                with _mev.span("bench.elementwise"):
                    elemwise = bench_elementwise()
            except Exception as e:
                # explicit null-valued keys, like the neighbouring benches: a
                # crashed anchor must be distinguishable from a BENCH_FAST skip
                elemwise = {
                    "elementwise_chain_valid": None,
                    "dispatch_valid": None,
                    "fusion_speedup": None,
                    "fused_reduction_valid": None,
                    "reduction_sink_speedup": None,
                    "fused_view_chain_valid": None,
                    "view_fusion_speedup": None,
                    "ragged_reduce_gbps": None,
                    "ragged_reduce_speedup": None,
                    "ragged_reduce_valid": None,
                    "audit_overhead_pct": None,
                    "audit_overhead_valid": None,
                    "flight_overhead_pct": None,
                    "flight_overhead_valid": None,
                    "elementwise_error": repr(e)[:160],
                }
        # GEMM-producer epilogue anchors (ISSUE 5): act(x@w+b) through the
        # fusion engine's producer path — bias+activation fused into the
        # GEMM's XLA program — vs the same-process HEAT_TPU_FUSION_GEMM=0
        # baseline; *_valid gated on sample spread (the 1-core container is
        # GEMM-compute-bound, so the speedup understates TPU-host headroom)
        gemm_epi = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from matmul_mfu_bench import bench_epilogue

                with _mev.span("bench.matmul_epilogue"):
                    gemm_epi = bench_epilogue()
            except Exception as e:
                # explicit null-valued keys, like the neighbouring benches: a
                # crashed anchor must be distinguishable from a BENCH_FAST skip
                gemm_epi = {
                    "matmul_epilogue_valid": None,
                    "epilogue_fusion_speedup": None,
                    "matmul_epilogue_error": repr(e)[:160],
                }
        # collective-aware fusion anchors (ISSUE 7): chain + recorded
        # resharding/halo as ONE shard_map program vs the same-process
        # HEAT_TPU_FUSION_COLLECTIVES=0 barrier baseline, plus the
        # kmeans_step_executables count (the DNDarray-surface Lloyd step must
        # cost ONE cached executable per warm iteration); *_valid gated per
        # the 1-core-container methodology — a 1-device bench host reports
        # null like the ici_gbps anchor (the transfer is not measurable)
        coll_fusion = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from allreduce_bandwidth_bench import bench_fused_collectives, bench_two_tier
                from kmeans_bench import kmeans_step_anchor

                with _mev.span("bench.fused_collectives"):
                    coll_fusion = bench_fused_collectives()
                    coll_fusion.update(kmeans_step_anchor())
                    # ISSUE 11: hierarchical (dcn, ici) allreduce vs the flat
                    # single-level program over the same devices
                    coll_fusion.update(bench_two_tier())
            except Exception as e:
                # explicit null-valued keys, like the neighbouring benches: a
                # crashed anchor must be distinguishable from a BENCH_FAST skip
                coll_fusion = {
                    "fused_resplit_valid": None,
                    "resplit_fusion_speedup": None,
                    "fused_halo_valid": None,
                    "halo_fusion_speedup": None,
                    "kmeans_step_valid": None,
                    "kmeans_step_executables": None,
                    "two_tier_valid": None,
                    "two_tier_speedup": None,
                    "fused_collectives_error": repr(e)[:160],
                }
        # AOT serving runtime anchors (ISSUE 8): cold_restart_compiles — a
        # fresh process replaying the recorded shape corpus against a warmed
        # HEAT_TPU_CACHE_DIR must compile ZERO fused kernels (every flush an
        # L1 miss -> disk hit); dispatch_p50/p99_us — exact scheduler
        # submit-to-materialized percentiles at a fixed mixed-shape request
        # mix; bucket_kernel_count vs unbucketed — the HEAT_TPU_SHAPE_BUCKETS
        # policy bounding distinct kernels (bucket_valid additionally
        # requires pairwise bit-parity across the whole mix); ISSUE 17 adds
        # symbolic_kernel_count (one jax.export family for the whole mix,
        # zero pad waste), time_to_ready_s vs blind_warmup_s (predictive
        # warmup ordering) and autoscale_p99_held (the diurnal-ramp
        # closed-loop contract as a 0/1)
        serving_anchors = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from serving_bench import bench_serving

                with _mev.span("bench.serving"):
                    serving_anchors = bench_serving()
            except Exception as e:
                # explicit null-valued keys, like the neighbouring benches: a
                # crashed anchor must be distinguishable from a BENCH_FAST skip
                serving_anchors = {
                    "cold_restart_compiles": None,
                    "cold_restart_valid": None,
                    "dispatch_p50_us": None,
                    "dispatch_p99_us": None,
                    "dispatch_latency_valid": None,
                    "bucket_kernel_count": None,
                    "unbucketed_kernel_count": None,
                    "bucket_valid": None,
                    "janitor_bytes_after": None,
                    "janitor_evicted": None,
                    "janitor_valid": None,
                    "fleet_cold_compiles": None,
                    "fleet_cold_valid": None,
                    "fleet_p50_us": None,
                    "fleet_p99_us": None,
                    "fleet_goodput_rps": None,
                    "fleet_valid": None,
                    "symbolic_kernel_count": None,
                    "symbolic_valid": None,
                    "time_to_ready_s": None,
                    "blind_warmup_s": None,
                    "warmup_order_valid": None,
                    "autoscale_p99_us": None,
                    "autoscale_p99_held": None,
                    "autoscale_valid": None,
                    "serving_error": repr(e)[:160],
                }
        # pallas kernel tier anchors (ISSUE 10): ring_attention_step_gbps —
        # the per-hop fused flash update's effective throughput — and the
        # same-process tier-on/tier-off speedups for ring attention and the
        # fused kmeans assign+update step. On this container the kernels run
        # through the pallas INTERPRETER (HEAT_TPU_PALLAS_INTERPRET=1), so
        # the speedups understate the TPU-host headroom enormously (« 1 is
        # expected; the anchors pin the dispatch machinery — ROADMAP 5 owns
        # the real-chip measurement); *_valid gates on sample spread only
        pallas_anchors = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from attention_bench import bench_attention
                from kmeans_bench import kmeans_pallas_anchor

                with _mev.span("bench.pallas"):
                    pallas_anchors = bench_attention()
                    pallas_anchors.update(kmeans_pallas_anchor())
            except Exception as e:
                # explicit null-valued keys, like the neighbouring benches: a
                # crashed anchor must be distinguishable from a BENCH_FAST skip
                pallas_anchors = {
                    "ring_attention_step_gbps": None,
                    "ring_attention_step_valid": None,
                    "attention_pallas_speedup": None,
                    "attention_pallas_valid": None,
                    "kmeans_pallas_speedup": None,
                    "kmeans_pallas_valid": None,
                    "pallas_error": repr(e)[:160],
                }
        # out-of-core input pipeline (VERDICT r4 #8): native prefetcher vs h5py
        io_pipe = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from io_pipeline_bench import bench_io_pipeline

                with _mev.span("bench.io_pipeline"):
                    io_pipe = bench_io_pipeline()
            except Exception as e:
                io_pipe = {"io_pipeline_valid": None, "io_pipeline_error": repr(e)[:160]}
        # measured-autotuning anchors (ISSUE 18): paired same-process
        # tuned-vs-default percentages for the flash tile and the blocked QR
        # panel (winner-stability-gated), and the corpus-mined bucket edges
        # vs pow2 on the fixed serving mix (kernel count bounded, pad waste
        # strictly lower). The BENCH_TELEMETRY sidecar carries the live
        # tuning.chosen() payload whenever the run is made with
        # HEAT_TPU_TUNING=1, making a chip number attributable to its knobs.
        tuning_anchors = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from tuning_bench import bench_tuning

                with _mev.span("bench.tuning"):
                    tuning_anchors = bench_tuning()
            except Exception as e:
                # explicit null-valued keys, like the neighbouring benches: a
                # crashed anchor must be distinguishable from a BENCH_FAST skip
                tuning_anchors = {
                    "flash_tile_tuned_vs_default_pct": None,
                    "flash_tile_tuned": None,
                    "flash_tile_tuning_valid": None,
                    "qr_panel_tuned_vs_default_pct": None,
                    "qr_panel_tuned": None,
                    "qr_panel_default": None,
                    "qr_panel_tuning_valid": None,
                    "bucket_kernel_count_tuned": None,
                    "bucket_kernel_count_pow2": None,
                    "bucket_pad_waste_bytes_tuned": None,
                    "bucket_pad_waste_bytes_pow2": None,
                    "bucket_edges_tuned": None,
                    "bucket_tuning_valid": None,
                    "tuning_chosen": None,
                    "tuning_error": repr(e)[:160],
                }
        # autoregressive decode serving anchors (ISSUE 19): the 32-step
        # zero-compile steady-state window of the iteration-level scheduler
        # (with mid-window join/leave churn), generated-token throughput,
        # exact inter-token latency percentiles and batch occupancy —
        # decode_steady_valid additionally requires the persistent KV-cache
        # to re-donate on every trace-cache hit (fusion.donated{steady_state})
        generation_anchors = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from generation_bench import bench_generation

                with _mev.span("bench.generation"):
                    generation_anchors = bench_generation()
            except Exception as e:
                # explicit null-valued keys, like the neighbouring benches: a
                # crashed anchor must be distinguishable from a BENCH_FAST skip
                generation_anchors = {
                    "decode_tokens_per_s": None,
                    "inter_token_p50_us": None,
                    "inter_token_p99_us": None,
                    "batch_occupancy_pct": None,
                    "decode_steady_compiles": None,
                    "decode_steady_donated": None,
                    "decode_steady_valid": None,
                    "decode_throughput_valid": None,
                    "generation_error": repr(e)[:160],
                }
        # end-to-end fused-transformer anchors (ISSUE 20): the 16-step
        # steady-state train window must record as ONE fused executable per
        # step (executables_per_step == 1 with a zero kernels_compiled delta
        # and zero collective flushes, parameter buffers re-donated every
        # step), plus trained/inferred tokens-per-second and the flight
        # recorder's cost-card modeled MFU for the window
        transformer_anchors = {}
        if os.environ.get("BENCH_FAST") != "1":
            try:
                _add_benchmarks_path()
                from transformer_bench import bench_transformer

                with _mev.span("bench.transformer"):
                    transformer_anchors = bench_transformer()
            except Exception as e:
                # explicit null-valued keys, like the neighbouring benches: a
                # crashed anchor must be distinguishable from a BENCH_FAST skip
                transformer_anchors = {
                    "train_tokens_per_s": None,
                    "infer_tokens_per_s": None,
                    "executables_per_step": None,
                    "train_steady_compiles": None,
                    "train_steady_donated": None,
                    "train_steady_valid": None,
                    "modeled_mfu_pct": None,
                    "modeled_mfu_valid": None,
                    "transformer_error": repr(e)[:160],
                }
        telemetry = monitoring.report.telemetry()
    print(
        json.dumps(
            {
                "metric": "kmeans_iters_per_sec_per_chip",
                "value": round(km["value"], 3),
                "unit": "iters/s (n=1048576, f=32, k=8, fp32)",
                "vs_baseline": round(vs, 3) if vs is not None else None,
                "device": km["device"],
                "measurement_valid": km["measurement_valid"],
                "jitter_pct": round(km["jitter_pct"], 2),
                "per_iter_us": round(km["per_iter_us"], 2),
                "vmem_traffic_model_mb": km["vmem_traffic_model_mb"],
                "implied_vmem_gbps": round(km["implied_vmem_gbps"], 1),
                "kmeans_vs_hbm_stream": km["kmeans_vs_hbm_stream"],
                "faster_than_hbm": km["faster_than_hbm"],
                "hbm_note": km["hbm_note"],
                "hbm_stream_gbps": stream_gbps,
                "hbm_stream_roofline_pct": stream_pct,
                "hbm_stream_valid": stream_valid,
                "pairs_valid": km["pairs_valid"],
                "pairs_discarded": km["pairs_discarded"],
                "baseline_iters_per_sec_torch_cpu": round(torch_ips, 3) if torch_ips else None,
                "matmul_mfu_tflops": mfu_tflops,
                "matmul_mfu_roofline_pct": mfu_pct,
                "matmul_mfu_valid": mfu_valid,
                "cdist_gbps": cdist_gbps,
                "cdist_roofline_pct": cdist_pct,
                "cdist_valid": cdist_valid,
                "allreduce_gbps": ar_gbps,
                "allreduce_roofline_pct": ar_pct,
                "allreduce_note": ar_note,
                "allreduce_valid": ar_valid,
                # the BASELINE.json metric is ICI bandwidth: not measurable on
                # one chip — the 8-device dryrun's psum (MULTICHIP_r05.json)
                # is the multi-device correctness-side proxy
                "ici_gbps": None,
                "ici_note": "not measurable at n_devices=1; psum proven in multichip dryrun",
                "dp8_cpu_iters_per_sec": scale8_ips,
                "dp8_cpu_sharding_overhead_pct": scale8_overhead,
                **linalg,
                **elemwise,
                **gemm_epi,
                **coll_fusion,
                **serving_anchors,
                **pallas_anchors,
                **io_pipe,
                **tuning_anchors,
                **generation_anchors,
                **transformer_anchors,
                "telemetry": telemetry,
            }
        )
    )
    # telemetry sidecar (ISSUE 14 satellite): the full labelled registry
    # snapshot + flight summary + SLO view, written beside the BENCH_*.json
    # output the driver collects — so a perf regression in the trajectory
    # is attributable post-hoc (which counters moved: compiles, cache
    # outcomes, shed/deadline counts) without rerunning the bench. The
    # compact `telemetry` block above keeps only labelled breakdowns the
    # report chose to surface; the sidecar keeps everything. Best-effort:
    # the sidecar must never fail a bench run.
    try:
        from heat_tpu.monitoring import aggregate as _agg

        _agg.write_snapshot(
            path=os.environ.get("BENCH_TELEMETRY_OUT", "BENCH_TELEMETRY.json")
        )
    except Exception:
        pass


if __name__ == "__main__":
    main()
