"""
Benchmark: KMeans iterations/sec/chip (the BASELINE.json north-star workload —
reference benchmarks/kmeans/, SURVEY.md §3.4/§6).

Runs the jitted Lloyd iteration (heat_tpu.cluster.kmeans._kmeans_step: one MXU GEMM
for assignment + one for the masked centroid update) on synthetic Gaussian blobs on
the available accelerator and prints ONE JSON line.

``vs_baseline``: the reference (marianna13/heat) delegates all local compute to
PyTorch and cannot run here (no mpi4py in this image), so the baseline is the same
Lloyd iteration implemented on the reference's compute engine — torch on CPU, single
process (exactly what `mpirun -np 1 benchmarks/kmeans/heat-cpu.py` measures up to MPI
constants). vs_baseline = (our iters/sec) / (torch-CPU iters/sec).

Measurement integrity (round-3 rework; VERDICT r2 "recover and lock the north
star"): the shared tunneled chip's throughput varies run to run (r01 measured
10,393 iters/s with a torch-CPU baseline of 3.784; r02 8,721 with the baseline
at 3.505 — both moved together, i.e. machine weather, not a kernel change; see
doc/kmeans_northstar.md for the component-level profile). Every run therefore
self-certifies:

* trials are interleaved (short, long) pairs, so slow drift cancels out of the
  differenced rate instead of biasing one leg;
* ``jitter_pct`` reports the spread of the per-pair differenced rates — a
  future reader can tell noise from regression without a second run;
* ``per_iter_us`` and ``implied_hbm_gbps`` pin the number to physics: the step
  is HBM-bound (one hoisted-bf16 pass for assignment + one for the update), so
  implied bandwidth far off the chip's roofline means a bad measurement, not a
  kernel change.
"""

import json
import os
import time

# virtual CPU devices for the scaling line must be configured before jax inits
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import numpy as np

N, F, K = 1_048_576, 32, 8
ITERS = 30
PAIRS = 5  # interleaved (short, long) timing pairs


def _data(rng, n=N):
    centers = rng.normal(scale=5.0, size=(K, F)).astype(np.float32)
    labels = rng.integers(0, K, size=n)
    return centers[labels] + rng.normal(scale=0.5, size=(n, F)).astype(np.float32)


def _differenced_rates(run, calib_rate):
    """
    Per-iteration device rate from interleaved (short, long) dispatch pairs.

    Differencing two dispatch lengths cancels the fixed per-dispatch cost
    (host->device RPC; tens of ms on tunneled runtimes). Interleaving the pairs
    — rather than all-short-then-all-long — keeps slow machine drift from
    biasing one leg. Lengths are sized from the calibration rate so the long leg
    is several hundred ms of device time on any backend.
    """
    long = int(np.clip(calib_rate * 8.0, 10, 6000))
    short = max(1, long // 10)
    rates = []
    for pair in range(PAIRS):
        t_short = run(short, 1e-6 * (2 * pair + 1))
        t_long = run(long, 1e-6 * (2 * pair + 2))
        dt = t_long - t_short
        rates.append((long - short) / dt if dt > 0 else long / t_long)
    return rates


def bench_tpu(data_np):
    import jax
    import jax.numpy as jnp

    from heat_tpu.cluster.kmeans import _kmeans_step, _kmeans_iterate

    dev = jax.devices()[0]
    x = jax.device_put(jnp.asarray(data_np), dev)
    centers = x[:K]

    def run(iters, eps):
        # honest timing on async/remote runtimes: perturb the input so no cached
        # result can be replayed, and read the result back to host — the clock
        # only stops when real bytes arrive
        c2 = centers * (1.0 + eps)
        t0 = time.perf_counter()
        np.asarray(_kmeans_iterate(x, c2, _kmeans_step, iters))
        return time.perf_counter() - t0

    # The two-GEMM XLA step is the sole candidate: measured at up to 104% of
    # nominal MXU MFU on large GEMMs (benchmarks/matmul_mfu_bench.py), XLA leaves
    # a hand-written kernel nothing to win on this workload — a fused pallas
    # Lloyd step was raced here in round 1 AND re-engineered and re-raced in
    # round 3 (bf16-streaming, K-on-sublanes layout, zero lane padding, perfect
    # label agreement) and still lost 3.2x: the skinny K=8 GEMMs collapse MXU
    # utilization inside a kernel, while XLA's full-height GEMMs pipeline at HBM
    # roofline (doc/kmeans_northstar.md).
    np.asarray(_kmeans_iterate(x, centers, _kmeans_step, ITERS))  # compile+warm
    calib = ITERS / run(ITERS, 1e-7)
    rates = _differenced_rates(run, calib)
    best = max(rates)
    # spread of the TYPICAL pair from the best: a median is robust to a single
    # stalled pair (a 10 s system hiccup in one leg makes min(rates) ~ 0 and
    # would report ~100% jitter even when every other pair agrees)
    jitter_pct = 100.0 * (best - float(np.median(rates))) / best
    per_iter_us = 1e6 / best
    # physics floor: the step cannot move fewer bytes than ONE pass over the
    # hoisted bf16 copy of x plus the int32 labels write — implied bandwidth at
    # this minimal model above the chip's HBM roofline means the measurement is
    # wrong, not that the kernel got faster (819 GB/s nominal on v5e puts the
    # ceiling at ~11.5k iters/s for this shape)
    bytes_floor = N * F * 2 + N * 4
    implied_gbps = bytes_floor * best / 1e9
    return best, jitter_pct, per_iter_us, implied_gbps, f"{dev} [xla]"


def bench_torch_cpu(data_np, iters=3):
    import torch

    x = torch.from_numpy(data_np)
    c = x[:K].clone()

    def step(x, c):
        # same quadratic-expansion formulation as the TPU path (fair GEMM-based compare)
        d2 = (x * x).sum(1, keepdim=True) - 2.0 * (x @ c.T) + (c * c).sum(1)[None, :]
        labels = torch.argmin(d2, dim=1)
        onehot = torch.nn.functional.one_hot(labels, K).to(x.dtype)
        counts = onehot.sum(0)
        sums = onehot.T @ x
        return torch.where(counts[:, None] > 0, sums / counts.clamp(min=1)[:, None], c)

    step(x, c)
    t0 = time.perf_counter()
    for _ in range(iters):
        c = step(x, c)
    dt = time.perf_counter() - t0
    return iters / dt


def bench_allreduce():
    """
    The second BASELINE.json north-star: "DNDarray Allreduce ICI bandwidth
    (GB/s)" — the psum the __reduce_op path emits, measured at several buffer
    sizes (benchmarks/allreduce_bandwidth_bench.py wired in here so the driver
    captures both numbers in one JSON line). With one chip the psum degenerates
    and the number is the buffer's HBM-roundtrip bandwidth; the roofline is
    picked accordingly: TPU v5e ≈ 819 GB/s HBM, ≈ 186 GB/s accumulated ICI
    (4 links × ~46.5 GB/s) for multi-chip.
    """
    import sys

    import jax

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    from allreduce_bandwidth_bench import bench_size
    from jax.sharding import Mesh

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("d",))
    # 256 MB only: the differenced-chain method needs the long leg's device time
    # (tens of ms) to dominate dispatch jitter — small buffers make dt fragile
    # and a max-over-sizes then reports whichever noise inflated most
    best = bench_size(mesh, 256 * 1024 * 1024, trials=4)
    plat = devs[0].platform
    if plat == "tpu":
        roofline = 819.0 if len(devs) == 1 else 186.0 * len(devs) / 2
        kind = "HBM roundtrip" if len(devs) == 1 else "ICI allreduce"
    else:
        roofline, kind = None, "host memory (CPU mesh)"
    pct = round(100.0 * best / roofline, 1) if roofline else None
    return round(best, 2), pct, f"{kind}, {len(devs)} device(s)"


def bench_scaling_8dev():
    """
    Multichip evidence within the single-chip constraint (VERDICT r2 #10): the
    SAME Lloyd step over the full dataset, once sharded over the 8-virtual-
    device CPU mesh (per-iteration psum of the (k,f) partial sums — the
    collectives are real) and once on a single CPU device. Both runs use the
    same host silicon (XLA multithreads the single-device program across cores
    too), so the ratio isolates the *sharding + collective* overhead rather
    than core contention.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from heat_tpu.cluster.kmeans import _kmeans_step, _kmeans_iterate

    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        return None, None
    n8 = 1 << 18  # bounded host work: the line must cost seconds, not minutes
    data = _data(np.random.default_rng(1), n=n8)
    mesh = Mesh(np.asarray(cpus[:8]), ("d",))
    xs = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P("d", None)))
    c0 = jax.device_put(jnp.asarray(data[:K]), NamedSharding(mesh, P(None, None)))
    x1 = jax.device_put(jnp.asarray(data), cpus[0])
    c1 = jax.device_put(jnp.asarray(data[:K]), cpus[0])

    def rate(x, c, iters=40):
        np.asarray(_kmeans_iterate(x, c, _kmeans_step, iters))
        best = float("inf")
        for t in range(3):
            t0 = time.perf_counter()
            np.asarray(_kmeans_iterate(x, c * (1.0 + 1e-6 * (t + 1)), _kmeans_step, iters))
            best = min(best, time.perf_counter() - t0)
        return iters / best

    r8 = rate(xs, c0)  # 8-device sharded, full N
    r1 = rate(x1, c1)  # 1 device, full N
    overhead_pct = 100.0 * (r1 / r8 - 1.0)
    return round(r8, 1), round(overhead_pct, 1)


def main():
    rng = np.random.default_rng(0)
    data = _data(rng)
    tpu_ips, jitter_pct, per_iter_us, implied_gbps, device = bench_tpu(data)
    try:
        torch_ips = bench_torch_cpu(data)
        vs = tpu_ips / torch_ips
    except Exception:
        torch_ips, vs = None, None
    try:
        ar_gbps, ar_pct, ar_note = bench_allreduce()
    except Exception:
        ar_gbps = ar_pct = ar_note = None
    try:
        scale8_ips, scale8_overhead = bench_scaling_8dev()
    except Exception:
        scale8_ips = scale8_overhead = None
    print(
        json.dumps(
            {
                "metric": "kmeans_iters_per_sec_per_chip",
                "value": round(tpu_ips, 3),
                "unit": "iters/s (n=1048576, f=32, k=8, fp32)",
                "vs_baseline": round(vs, 3) if vs is not None else None,
                "device": device,
                "jitter_pct": round(jitter_pct, 2),
                "per_iter_us": round(per_iter_us, 2),
                "implied_hbm_gbps": round(implied_gbps, 1),
                "baseline_iters_per_sec_torch_cpu": round(torch_ips, 3) if torch_ips else None,
                "allreduce_gbps": ar_gbps,
                "allreduce_roofline_pct": ar_pct,
                "allreduce_note": ar_note,
                "dp8_cpu_iters_per_sec": scale8_ips,
                "dp8_cpu_sharding_overhead_pct": scale8_overhead,
            }
        )
    )


if __name__ == "__main__":
    main()
