"""
Benchmark: KMeans iterations/sec/chip (the BASELINE.json north-star workload —
reference benchmarks/kmeans/, SURVEY.md §3.4/§6).

Runs the jitted Lloyd iteration (heat_tpu.cluster.kmeans._kmeans_step: one MXU GEMM
for assignment + one for the masked centroid update) on synthetic Gaussian blobs on
the available accelerator and prints ONE JSON line.

``vs_baseline``: the reference (marianna13/heat) delegates all local compute to
PyTorch and cannot run here (no mpi4py in this image), so the baseline is the same
Lloyd iteration implemented on the reference's compute engine — torch on CPU, single
process (exactly what `mpirun -np 1 benchmarks/kmeans/heat-cpu.py` measures up to MPI
constants). vs_baseline = (our iters/sec) / (torch-CPU iters/sec).
"""

import json
import time

import numpy as np

N, F, K = 1_048_576, 32, 8
ITERS = 30


def _data(rng):
    centers = rng.normal(scale=5.0, size=(K, F)).astype(np.float32)
    labels = rng.integers(0, K, size=N)
    return centers[labels] + rng.normal(scale=0.5, size=(N, F)).astype(np.float32)


def bench_tpu(data_np):
    import jax
    import jax.numpy as jnp

    from heat_tpu.cluster.kmeans import _kmeans_step, _kmeans_iterate

    dev = jax.devices()[0]
    x = jax.device_put(jnp.asarray(data_np), dev)
    centers = x[:K]

    def time_once(xx, step, iters):
        # the whole fixed-count Lloyd loop runs on-device as one XLA program
        # (KMeans.fit's while_loop path, minus the convergence test).
        # Honest timing on async/remote runtimes: perturb the input so no cached
        # result can be replayed, and read the result back to host — the clock
        # only stops when real bytes arrive.
        np.asarray(_kmeans_iterate(xx, centers, step, iters))  # compile + warmup
        best = float("inf")
        for trial in range(3):
            c2 = centers * (1.0 + 1e-6 * (trial + 1))
            t0 = time.perf_counter()
            np.asarray(_kmeans_iterate(xx, c2, step, iters))
            best = min(best, time.perf_counter() - t0)
        return best

    def steady_rate(xx, step, calib_rate):
        # Steady-state device throughput: difference two dispatch lengths so the
        # fixed per-dispatch cost (host->device RPC; tens of ms on tunneled
        # runtimes) cancels, leaving pure per-iteration device time. Lengths are
        # sized from the calibration rate so the long leg is several hundred ms of
        # device time on any backend — big enough that ±15ms dispatch jitter
        # cannot flip rankings (a CPU fallback at ~10 iters/s measures 80 vs 8
        # iterations, not a fixed 3000).
        long = int(np.clip(calib_rate * 8.0, 10, 3000))
        short = max(1, long // 10)
        t_short = time_once(xx, step, short)
        t_long = time_once(xx, step, long)
        dt = t_long - t_short
        if dt <= 0:  # clock noise swamped the difference; report the conservative rate
            return long / t_long
        return (long - short) / dt

    # The two-GEMM XLA step is the sole candidate: measured at up to 104% of nominal MXU MFU
    # on large GEMMs (benchmarks/matmul_mfu_bench.py, 86-104% across runs), XLA leaves a hand-written
    # kernel nothing to win on this workload — a fused pallas Lloyd step raced
    # here through round 1 and lost ~3-6x at every shape (see
    # doc/performance.md, "Where pallas pays off").
    calib = ITERS / time_once(x, _kmeans_step, ITERS)
    rate = steady_rate(x, _kmeans_step, calib)
    return rate, f"{dev} [xla]"


def bench_torch_cpu(data_np, iters=3):
    import torch

    x = torch.from_numpy(data_np)
    c = x[:K].clone()
    # one warmup
    def step(x, c):
        # same quadratic-expansion formulation as the TPU path (fair GEMM-based compare)
        d2 = (x * x).sum(1, keepdim=True) - 2.0 * (x @ c.T) + (c * c).sum(1)[None, :]
        labels = torch.argmin(d2, dim=1)
        onehot = torch.nn.functional.one_hot(labels, K).to(x.dtype)
        counts = onehot.sum(0)
        sums = onehot.T @ x
        return torch.where(counts[:, None] > 0, sums / counts.clamp(min=1)[:, None], c)

    step(x, c)
    t0 = time.perf_counter()
    for _ in range(iters):
        c = step(x, c)
    dt = time.perf_counter() - t0
    return iters / dt


def bench_allreduce():
    """
    The second BASELINE.json north-star: "DNDarray Allreduce ICI bandwidth
    (GB/s)" — the psum the __reduce_op path emits, measured at several buffer
    sizes (benchmarks/allreduce_bandwidth_bench.py wired in here so the driver
    captures both numbers in one JSON line). With one chip the psum degenerates
    and the number is the buffer's HBM-roundtrip bandwidth; the roofline is
    picked accordingly: TPU v5e ≈ 819 GB/s HBM, ≈ 186 GB/s accumulated ICI
    (4 links × ~46.5 GB/s) for multi-chip.
    """
    import os
    import sys

    import jax

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    from allreduce_bandwidth_bench import bench_size
    from jax.sharding import Mesh

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("d",))
    best = 0.0
    for mb in (8, 64, 256):
        best = max(best, bench_size(mesh, mb * 1024 * 1024, trials=4))
    plat = devs[0].platform
    if plat == "tpu":
        roofline = 819.0 if len(devs) == 1 else 186.0 * len(devs) / 2
        kind = "HBM roundtrip" if len(devs) == 1 else "ICI allreduce"
    else:
        roofline, kind = None, "host memory (CPU mesh)"
    pct = round(100.0 * best / roofline, 1) if roofline else None
    return round(best, 2), pct, f"{kind}, {len(devs)} device(s)"


def main():
    rng = np.random.default_rng(0)
    data = _data(rng)
    tpu_ips, device = bench_tpu(data)
    try:
        torch_ips = bench_torch_cpu(data)
        vs = tpu_ips / torch_ips
    except Exception:
        torch_ips, vs = None, None
    try:
        ar_gbps, ar_pct, ar_note = bench_allreduce()
    except Exception:
        ar_gbps = ar_pct = ar_note = None
    print(
        json.dumps(
            {
                "metric": "kmeans_iters_per_sec_per_chip",
                "value": round(tpu_ips, 3),
                "unit": "iters/s (n=1048576, f=32, k=8, fp32)",
                "vs_baseline": round(vs, 3) if vs is not None else None,
                "device": device,
                "baseline_iters_per_sec_torch_cpu": round(torch_ips, 3) if torch_ips else None,
                "allreduce_gbps": ar_gbps,
                "allreduce_roofline_pct": ar_pct,
                "allreduce_note": ar_note,
            }
        )
    )


if __name__ == "__main__":
    main()
