"""
Distributed stencil demo: explicit heat-equation (diffusion) steps over a
domain sharded across the TPU mesh, using the DNDarray halo exchange.

The reference framework's stencil story is ``DNDarray.get_halo`` (reference
heat/core/dndarray.py:360-446): each rank receives its neighbors' boundary rows
and computes on ``[halo_prev; local; halo_next]``. Here the same call runs one
``shard_map``+``ppermute`` exchange and exposes the per-shard halo'd blocks as
``array_with_halos`` — shape ``(p, chunk + 2*halo, ...)``, sharded on axis 0 —
so the Laplacian below is computed entirely shard-locally; reshaping the
``(p, chunk)`` result back to ``(p*chunk,)`` keeps the sharding, i.e. the whole
time step never gathers the domain.

Run (CPU mesh):
    env PYTHONPATH= JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/stencil/demo_heat_equation.py
"""

import argparse

import numpy as np
import jax.numpy as jnp

import heat_tpu as ht


def step(u: "ht.DNDarray", alpha: float) -> "ht.DNDarray":
    """One explicit Euler step of u_t = alpha * u_xx (Dirichlet boundaries)."""
    u.get_halo(1)
    if u.split is not None and u.comm.is_distributed():
        blocks = u.array_with_halos  # (p, c+2) sharded on axis 0
        lap = blocks[:, :-2] - 2.0 * blocks[:, 1:-1] + blocks[:, 2:]  # (p, c)
        new = blocks[:, 1:-1] + alpha * lap
        flat = new.reshape(-1)  # (p*c,) — merging the leading sharded axis keeps placement
        out = ht.array(flat[: u.shape[0]], is_split=0, comm=u.comm)
    else:  # single device: no halos to exchange, plain local stencil
        v = u.larray
        lap = jnp.zeros_like(v).at[1:-1].set(v[:-2] - 2.0 * v[1:-1] + v[2:])
        out = ht.array(v + alpha * lap, comm=u.comm)
    # pin the physical endpoints (Dirichlet u=0)
    out[0] = 0.0
    out[-1] = 0.0
    return out


def reference_steps(u0: np.ndarray, alpha: float, steps: int) -> np.ndarray:
    u = u0.copy()
    for _ in range(steps):
        lap = np.zeros_like(u)
        lap[1:-1] = u[:-2] - 2 * u[1:-1] + u[2:]
        u = u + alpha * lap
        u[0] = u[-1] = 0.0
    return u


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--points", type=int, default=4096)
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--alpha", type=float, default=0.25)
    args = parser.parse_args()

    xgrid = np.linspace(0.0, 1.0, args.points).astype(np.float32)
    u0 = np.exp(-200.0 * (xgrid - 0.5) ** 2).astype(np.float32)  # heat pulse

    u = ht.array(u0, split=0)
    print(f"domain: {u.shape[0]} points over {u.comm.size} device(s), split={u.split}")
    for _ in range(args.steps):
        u = step(u, args.alpha)

    want = reference_steps(u0, args.alpha, args.steps)
    got = u.numpy()
    err = float(np.abs(got - want).max())
    print(f"{args.steps} steps done; max |Δ| vs serial reference = {err:.3e}")
    assert err < 1e-4, "distributed stencil diverged from the serial reference"
    print("OK")


if __name__ == "__main__":
    main()
