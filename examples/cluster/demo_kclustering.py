"""
k-clustering demo (reference examples/cluster/demo_kClustering.py): build four
spherical clusters along the space diagonal with the distributed RNG + ht ops, then
fit KMeans / KMedians / KMedoids and report the recovered centroids.

Runs on whatever mesh is available (single TPU chip, or a virtual CPU mesh via
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``).
"""

import heat_tpu as ht


def create_spherical_dataset(num_samples_cluster, radius=1.0, offset=4.0, random_state=1):
    """Four spherical clusters in 3-D centred at ±offset and ±2·offset along the
    space diagonal (the reference demo's dataset, built from the same ht ops)."""
    ht.random.seed(random_state)
    r = ht.random.rand(num_samples_cluster, split=0) * radius
    theta = ht.random.rand(num_samples_cluster, split=0) * ht.constants.pi
    phi = ht.random.rand(num_samples_cluster, split=0) * 2 * ht.constants.pi

    x = r * ht.sin(theta) * ht.cos(phi)
    y = r * ht.sin(theta) * ht.sin(phi)
    z = r * ht.cos(theta)

    clusters = [
        ht.stack((x + c, y + c, z + c), axis=1)
        for c in (offset, 2 * offset, -offset, -2 * offset)
    ]
    return ht.concatenate(clusters, axis=0)


def main():
    data = create_spherical_dataset(num_samples_cluster=4000, radius=1.0, offset=4.0)

    clusterers = {
        "kmeans": ht.cluster.KMeans(n_clusters=4, init="kmeans++"),
        "kmedians": ht.cluster.KMedians(n_clusters=4, init="kmedians++"),
        "kmedoids": ht.cluster.KMedoids(n_clusters=4, init="kmedoids++"),
    }

    print(f"4 spherical clusters, {data.shape[0]} samples, split={data.split}")
    for name, c in clusterers.items():
        c.fit(data)
        centers = c.cluster_centers_.numpy()
        order = centers.sum(axis=1).argsort()
        print(f"{name}: centroids (sorted along diagonal):")
        for row in centers[order]:
            print("   ", " ".join(f"{v:+.2f}" for v in row))


if __name__ == "__main__":
    main()
