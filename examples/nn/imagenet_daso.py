"""
Hierarchical (DASO) training example (reference examples/nn/imagenet-DASO.py:
ht.optim.DASO with intra-node NCCL sync + inter-node grouped-MPI bf16 sync, skip
schedules decayed on loss plateau).

TPU-native form: the device mesh is factored into ``(node, local)`` axes; the
"intra-node" sync is a ``psum`` over the ``local`` axis every batch (unless
local-skipped) and the "inter-node" sync is a bf16-downcast ``psum`` over the
``node`` axis every ``global_skip`` batches, applied ``batches_to_wait`` batches
later with the reference's (local/4 + global*3/4) blend. The same synthetic
ImageNet-shaped HDF5 as examples/nn/imagenet.py feeds the run.

Run: python examples/nn/imagenet_daso.py [--epochs 4]
"""

import argparse
import os
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

import heat_tpu as ht
from imagenet import build_model, loss_fn, synthesize_h5


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=4)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--classes", type=int, default=100)
    parser.add_argument("--file", type=str, default="/tmp/imagenet_demo.h5")
    args = parser.parse_args()

    if not os.path.exists(args.file):
        synthesize_h5(args.file, classes=args.classes)

    import h5py

    with h5py.File(args.file, "r") as f:
        images = np.asarray(f["images"])
        labels = np.asarray(f["labels"]).astype(np.int32)

    model = build_model(args.classes)
    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(1e-2, momentum=0.9),
        total_epochs=args.epochs,
        warmup_epochs=1,
        cooldown_epochs=1,
        max_global_skips=4,
        verbose=True,
    )

    params = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 3, 32, 32), jnp.float32))
    daso.init(params)
    daso.make_train_step(loss_fn, model.apply)

    n = (len(images) // args.batch_size) * args.batch_size
    for epoch in range(args.epochs):
        t0, total, steps = time.perf_counter(), 0.0, 0
        perm = np.random.permutation(len(images))[:n]
        for s in range(0, n, args.batch_size):
            idx = perm[s : s + args.batch_size]
            total += float(daso.step(images[idx], labels[idx]))
            steps += 1
        epoch_loss = total / steps
        daso.epoch_loss_logic(epoch_loss)  # plateau detection → skip decay
        daso.epoch += 1
        dt = time.perf_counter() - t0
        ht.print0(
            f"epoch {epoch}: loss={epoch_loss:.4f} global_skip={daso.global_skip} "
            f"({n / dt:.0f} samples/s, mesh {daso.nodes}x{daso.local_size})"
        )


if __name__ == "__main__":
    main()
