"""
End-to-end distributed transformer training (ISSUE 20): the same toy
next-token model under three trainers —

- ``--trainer fused`` (default): the packed one-executable-per-step loop
  (``heat_tpu.nn.transformer``): each step records ONE fused chain
  (forward + backward + momentum + parameter update + loss sink), the
  optimizer donates the previous step's parameter/momentum buffers, and
  after warmup ``fusion.kernels_compiled`` stays flat — run with
  ``HEAT_TPU_FLIGHT=1`` to see the modeled MFU the cost cards anchor.
- ``--trainer dp``: the SPMD :class:`~heat_tpu.nn.DataParallel` trainer
  over the unpacked param pytree (gradient psum over the batch axis).
- ``--trainer daso``: hierarchical :class:`~heat_tpu.optim.DASO` with the
  local/global split pinned to the two-tier ICI/DCN mesh
  (``MeshCommunication.two_tier`` — intra-node sync every step, bf16
  cross-node sync on the skip schedule).

All three checkpoint through :class:`~heat_tpu.utils.CheckpointManager`
(preemption-safe atomic writes) and poll an
:class:`~heat_tpu.robustness.elastic.ElasticSupervisor` at every step
boundary when ``--elastic-dir`` is given: a lost peer drains, saves, and
exits ``ELASTIC_RESTART_EXIT`` for the launcher to respawn shrunk.

Run: python examples/nn/transformer_train.py [--trainer fused] [--steps 50]
"""

import argparse
import os
import sys
import time

import numpy as np

import heat_tpu as ht
from heat_tpu.nn import transformer as tf
from heat_tpu.robustness.elastic import ELASTIC_RESTART_EXIT, PeerLostError


def batches(cfg, batch_size, seq, steps, seed=1234):
    """Seeded synthetic next-token stream: x uniform tokens, y = x rolled
    left (the model learns the shift — loss falls fast at toy scale)."""
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        x = rng.integers(0, cfg.vocab, (batch_size, seq), dtype=np.int64)
        y = np.roll(x, -1, axis=1)
        yield x.astype(np.int32), y.astype(np.int32)


def run_fused(args, cfg, mgr, sup):
    state = tf.init_state(cfg)
    if mgr is not None and mgr.latest_valid_step() is not None:
        restored = mgr.restore_latest_valid(state.checkpoint_state())
        state = tf.TrainState.from_checkpoint(restored, cfg)
        ht.print0(f"resumed from step {state.step}")

    t0, seen = time.perf_counter(), 0
    for x, y in batches(cfg, args.batch_size, args.seq, args.steps - state.step):
        if sup is not None:
            # elastic contract: poll BEFORE dispatch — the state saved on
            # peer loss is the previous step boundary's consistent snapshot
            sup.check(state.checkpoint_state, state.step)
        loss, state = tf.train_step(state, x, y)
        val = tf.read_loss(loss)
        seen += x.size
        if state.step % args.log_every == 0:
            ht.print0(f"step {state.step}: loss={val:.4f}")
        if mgr is not None and state.step % args.save_every == 0:
            mgr.save(state.step, state.checkpoint_state())
    dt = time.perf_counter() - t0
    ht.print0(f"fused: {seen / dt:.0f} tokens/s over {args.steps} steps")

    from heat_tpu.monitoring import flight

    if flight.flight_enabled():
        mfu = flight.modeled_utilization()
        if mfu is not None:
            ht.print0(f"modeled MFU: {100.0 * mfu:.2f}%")
    return state


def run_tree(args, cfg, mgr, sup):
    import optax

    module = tf.TransformerModule(cfg)
    if args.trainer == "dp":
        trainer = ht.nn.DataParallel(
            module, optimizer=optax.sgd(cfg.lr, momentum=cfg.momentum)
        )
        trainer.init(cfg.seed, np.zeros((2, args.seq), np.int32))
        trainer.make_train_step(tf.tree_loss)
        step_fn = trainer.train_step
    else:  # daso — local/global split pinned to the two-tier ICI/DCN mesh
        comm = ht.core.communication.MeshCommunication.two_tier()
        trainer = ht.optim.DASO(
            local_optimizer=optax.sgd(cfg.lr, momentum=cfg.momentum),
            total_epochs=1,
            comm=comm,
            warmup_epochs=0,
            cooldown_epochs=0,
        )
        trainer.init(tf.init_tree(cfg))
        trainer.make_train_step(tf.tree_loss, module.apply)
        step_fn = trainer.step

    if sup is not None:
        trainer.attach_elastic(sup)
    if mgr is not None and mgr.latest_valid_step() is not None:
        trainer.load_state(mgr.restore_latest_valid(trainer.checkpoint_state()))
        ht.print0(f"resumed from step {trainer.step_count}")

    t0, seen = time.perf_counter(), 0
    for x, y in batches(cfg, args.batch_size, args.seq,
                        args.steps - trainer.step_count):
        val = float(step_fn(x, y))
        seen += x.size
        if trainer.step_count % args.log_every == 0:
            ht.print0(f"step {trainer.step_count}: loss={val:.4f}")
        if mgr is not None and trainer.step_count % args.save_every == 0:
            mgr.save(trainer.step_count, trainer.checkpoint_state())
    dt = time.perf_counter() - t0
    ht.print0(f"{args.trainer}: {seen / dt:.0f} tokens/s over {args.steps} steps")
    return trainer


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--trainer", choices=("fused", "dp", "daso"),
                        default="fused")
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq", type=int, default=16)
    parser.add_argument("--dtype", choices=("float32", "bfloat16"),
                        default="float32")
    parser.add_argument("--ckpt-dir", type=str, default="")
    parser.add_argument("--elastic-dir", type=str, default="")
    parser.add_argument("--save-every", type=int, default=10)
    parser.add_argument("--log-every", type=int, default=10)
    args = parser.parse_args()

    if args.trainer == "fused":
        os.environ.setdefault("HEAT_TPU_TRANSFORMER", "1")
    cfg = tf.TransformerConfig(dtype=args.dtype)

    mgr = ht.utils.CheckpointManager(args.ckpt_dir) if args.ckpt_dir else None
    sup = None
    if args.elastic_dir:
        from heat_tpu.robustness.elastic import ElasticSupervisor

        sup = ElasticSupervisor(args.elastic_dir, manager=mgr)

    try:
        if args.trainer == "fused":
            run_fused(args, cfg, mgr, sup)
        else:
            run_tree(args, cfg, mgr, sup)
    except PeerLostError as e:
        ht.print0(f"peer lost: {e}")
        sys.exit(ELASTIC_RESTART_EXIT)


if __name__ == "__main__":
    main()
