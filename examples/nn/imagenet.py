"""
Out-of-core data-parallel image-classification training (reference
examples/nn/imagenet.py: PartialH5Dataset windowed HDF5 reads + ht.nn.DataParallel
+ DataParallelOptimizer, run under mpirun).

TPU-native form: one controller drives every device in the mesh; the HDF5 file is
read in windows by ``PartialH5Dataset`` (background prefetch thread), batches are
sharded over the ``data`` mesh axis, and the gradient all-reduce is the ``psum``
XLA emits from the DataParallel train step.

Since real ImageNet isn't bundled, a small ImageNet-shaped HDF5 file (images
3x32x32, 100 classes) is synthesized automatically when ``--file`` doesn't exist;
point ``--file`` at a real ``{"images","labels"}`` HDF5 to use actual data.

Run: python examples/nn/imagenet.py [--epochs 2] [--file /tmp/imagenet_demo.h5]
"""

import argparse
import os
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

import heat_tpu as ht


def synthesize_h5(path, n=4096, classes=100, seed=0):
    import h5py

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, classes, size=n).astype(np.int32)
    # class-dependent means make the task learnable
    means = rng.normal(scale=0.8, size=(classes, 3, 1, 1)).astype(np.float32)
    images = means[labels] + rng.normal(scale=0.3, size=(n, 3, 32, 32)).astype(np.float32)
    with h5py.File(path, "w") as f:
        f.create_dataset("images", data=images)
        f.create_dataset("labels", data=labels)
    return path


def build_model(classes):
    import flax.linen as nn

    class SmallConvNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            # NCHW -> NHWC (TPU conv layout)
            x = jnp.transpose(x, (0, 2, 3, 1))
            for feat in (32, 64):
                x = nn.Conv(feat, (3, 3), padding="SAME")(x)
                x = nn.relu(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(128)(x))
            return nn.Dense(classes)(x)

    return SmallConvNet()


def loss_fn(params, apply_fn, x, y):
    logits = apply_fn(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--classes", type=int, default=100)
    parser.add_argument("--file", type=str, default="/tmp/imagenet_demo.h5")
    parser.add_argument("--window", type=int, default=2048)
    args = parser.parse_args()

    if not os.path.exists(args.file):
        synthesize_h5(args.file, classes=args.classes)

    dataset = ht.utils.data.partial_dataset.PartialH5Dataset(
        args.file,
        dataset_names=["images", "labels"],
        initial_load=args.window,
        load_length=args.window // 2,
    )

    model = build_model(args.classes)
    dp = ht.nn.DataParallel(model, optimizer=optax.adam(1e-3))
    dp.init(0, np.zeros((2, 3, 32, 32), np.float32))
    dp.make_train_step(loss_fn)

    n_window = dataset._window["images"].shape[0]
    steps_per_window = max(n_window // args.batch_size, 1)

    for epoch in range(args.epochs):
        t0, total, steps = time.perf_counter(), 0.0, 0
        dataset.Shuffle()
        for s in range(steps_per_window):
            idx = slice(s * args.batch_size, (s + 1) * args.batch_size)
            x, y = dataset[idx]
            total += float(dp.train_step(x, y.astype(np.int32)))
            steps += 1
        dataset.load_next_group()  # background prefetch of the next window
        dt = time.perf_counter() - t0
        ht.print0(
            f"epoch {epoch}: loss={total / steps:.4f} "
            f"({steps * args.batch_size / dt:.0f} samples/s on {dp.comm.size} device(s))"
        )

    dataset.close()


if __name__ == "__main__":
    main()
