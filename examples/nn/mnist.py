"""
Data-parallel MNIST training example (parity: reference examples/nn/mnist.py, which
runs under ``mpirun -np N``). Single-controller SPMD: the same script uses every
visible device through the mesh — no launcher needed.

Run: python examples/nn/mnist.py [--epochs 3] [--data-dir ./data]
"""

import argparse
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax

import heat_tpu as ht


def build_model():
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(128)(x)
            x = nn.relu(x)
            x = nn.Dense(64)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    return Net()


def loss_fn(params, apply_fn, x, y):
    logits = apply_fn(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--data-dir", type=str, default="./data")
    args = parser.parse_args()

    dataset = ht.utils.data.MNISTDataset(args.data_dir, train=True)
    model = build_model()
    dp = ht.nn.DataParallel(model, optimizer=optax.adam(1e-3))
    dp.init(0, np.zeros((2, 28, 28), np.float32))
    dp.make_train_step(loss_fn)

    images = np.asarray(dataset.htdata.larray)
    labels = np.asarray(dataset.targets)
    n = (len(images) // args.batch_size) * args.batch_size

    for epoch in range(args.epochs):
        t0 = time.perf_counter()
        perm = np.random.permutation(len(images))[:n]
        total = 0.0
        for s in range(0, n, args.batch_size):
            idx = perm[s : s + args.batch_size]
            total += float(dp.train_step(images[idx], labels[idx]))
        dt = time.perf_counter() - t0
        ht.print0(
            f"epoch {epoch}: loss={total / (n // args.batch_size):.4f} "
            f"({n / dt:.0f} samples/s on {dp.comm.size} device(s))"
        )

    logits = dp(images[:2048])
    acc = (np.asarray(jnp.argmax(logits, axis=1)) == labels[:2048]).mean()
    ht.print0(f"train accuracy (first 2048): {acc:.3f}")


if __name__ == "__main__":
    main()
