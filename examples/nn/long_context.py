"""
Long-context attention demo: sequence parallelism over the device mesh.

The dense softmax(QK^T)V materialises an (S, S) score matrix — at S = 32k that
is 4 GB of f32 per head and does not fit. The framework ships two sequence-
parallel formulations that never materialise it (SURVEY §5 long-context;
generalizing the reference's ring `_dist` pattern, distance.py:279-346):

* ``ht.nn.ring_attention`` — blocks of K/V rotate around the mesh with
  ``ppermute`` while each device holds its Q block and folds incoming tiles
  into an online softmax (running max + normalizer). Communication is the
  ring; memory is O(S·d / p) per device.
* ``ht.nn.ulysses_attention`` — all-to-all re-shards from sequence-split to
  head-split, runs dense per-head attention locally, and all-to-alls back.

Run (virtual mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/nn/long_context.py --seq 4096
Run (real TPU): python examples/nn/long_context.py --seq 32768
"""

import argparse
import time

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq", type=int, default=4096)
    parser.add_argument("--heads", type=int, default=4)
    parser.add_argument("--dim", type=int, default=64)
    parser.add_argument("--causal", action="store_true")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    import heat_tpu as ht
    from heat_tpu.core.communication import get_comm

    comm = get_comm()
    p = comm.size
    s = (args.seq // max(p, 1)) * max(p, 1)
    print(f"devices={p}  seq={s}  heads={args.heads}  head_dim={args.dim}")

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (1, s, args.heads, args.dim)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32) * 0.3 for kk in ks)

    def timed(name, fn, *a, **kw):
        out = fn(*a, **kw)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = fn(*a, **kw)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        print(f"  {name:24s} {dt * 1e3:8.1f} ms")
        return np.asarray(out)

    print("sequence-parallel attention:")
    ring = timed(
        "ring_attention", ht.nn.ring_attention, q, k, v, comm=comm, causal=args.causal
    )
    uly = timed(
        "ulysses_attention", ht.nn.ulysses_attention, q, k, v, comm=comm,
        causal=args.causal,
    )
    np.testing.assert_allclose(ring, uly, rtol=2e-3, atol=2e-3)

    if s <= 8192:  # the dense reference still fits at small S
        dense = timed(
            "dense reference", ht.nn.scaled_dot_product_attention, q, k, v,
            causal=args.causal,
        )
        np.testing.assert_allclose(ring, dense, rtol=2e-3, atol=2e-3)
        print("  ring == ulysses == dense (rtol 2e-3)")
    else:
        print("  ring == ulysses (dense would not fit at this length)")


if __name__ == "__main__":
    main()
