"""
Transformer inference driver (ISSUE 20): load a checkpoint written by
``transformer_train.py`` (or seed a fresh model), run the no-grad fused
forward — one sink per batch, flash-attention-routed when the pallas tier
admits the shape — and report greedy next-token continuations plus
tokens/s.

Run: python examples/nn/transformer_infer.py [--ckpt-dir /tmp/ckpt]
"""

import argparse
import os
import time

import numpy as np

import heat_tpu as ht
from heat_tpu.nn import transformer as tf


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--ckpt-dir", type=str, default="")
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq", type=int, default=16)
    parser.add_argument("--iters", type=int, default=20)
    parser.add_argument("--dtype", choices=("float32", "bfloat16"),
                        default="float32")
    args = parser.parse_args()

    os.environ.setdefault("HEAT_TPU_TRANSFORMER", "1")
    cfg = tf.TransformerConfig(dtype=args.dtype)
    state = tf.init_state(cfg)
    if args.ckpt_dir:
        mgr = ht.utils.CheckpointManager(args.ckpt_dir)
        if mgr.latest_valid_step() is not None:
            state = tf.TrainState.from_checkpoint(
                mgr.restore_latest_valid(state.checkpoint_state()), cfg
            )
            ht.print0(f"loaded step {state.step}")

    rng = np.random.default_rng(99)
    x = rng.integers(0, cfg.vocab, (args.batch_size, args.seq),
                     dtype=np.int64).astype(np.int32)

    # warmup (compile), then the measured window
    tf.read_logits(tf.infer_step(state, x))
    t0 = time.perf_counter()
    for _ in range(args.iters):
        logits = tf.read_logits(tf.infer_step(state, x))
    dt = time.perf_counter() - t0
    nxt = np.argmax(logits[:, -1, :], axis=-1)
    ht.print0(f"greedy next tokens: {nxt.tolist()}")
    ht.print0(
        f"infer: {args.iters * x.size / dt:.0f} tokens/s "
        f"({args.batch_size}x{args.seq} per sink)"
    )


if __name__ == "__main__":
    main()
