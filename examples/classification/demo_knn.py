"""
k-nearest-neighbours demo (reference examples/classification/demo_knn.py): load the
bundled iris dataset, run leave-one-fold-out cross-validation with
``KNeighborsClassifier``, and print per-fold accuracy.

The reference loads ``heat/datasets/iris.h5`` and hand-builds folds with
Python lists; here the same flow runs through ``ht.datasets`` + ``ht.load_hdf5``
and stays in DNDarray land throughout.
"""

import numpy as np

import heat_tpu as ht
from heat_tpu.classification.kneighborsclassifier import KNeighborsClassifier


def calculate_accuracy(pred_y, true_y):
    """Fraction of correctly labelled samples (reference demo_knn.py:28-57)."""
    if pred_y.gshape != true_y.gshape:
        raise ValueError(f"expecting same lengths, got {pred_y.gshape}, {true_y.gshape}")
    return float(ht.sum(ht.where(pred_y == true_y, 1, 0)).item()) / pred_y.gshape[0]


def main(folds=5, n_neighbors=5):
    x = ht.load_hdf5(ht.datasets.path("iris.h5"), dataset="data", split=0)
    # iris.h5 rows are ordered by class: 50 of each
    labels = ht.array(np.repeat(np.arange(3, dtype=np.int32), 50), split=0)

    n = x.shape[0]
    rng = np.random.default_rng(0)
    perm = rng.permutation(n)
    fold_size = n // folds

    x_np, y_np = x.numpy(), labels.numpy()
    accuracies = []
    for k in range(folds):
        test_idx = np.sort(perm[k * fold_size : (k + 1) * fold_size])
        train_idx = np.sort(np.setdiff1d(perm, test_idx))

        x_train = ht.array(x_np[train_idx], split=0)
        y_train = ht.array(y_np[train_idx], split=0)
        x_test = ht.array(x_np[test_idx], split=0)
        y_test = ht.array(y_np[test_idx], split=0)

        knn = KNeighborsClassifier(n_neighbors=n_neighbors)
        knn.fit(x_train, y_train)
        pred = knn.predict(x_test)
        acc = calculate_accuracy(pred.astype(ht.int32), y_test)
        accuracies.append(acc)
        print(f"fold {k}: accuracy {acc:.3f}")

    print(f"mean accuracy over {folds} folds: {np.mean(accuracies):.3f}")


if __name__ == "__main__":
    main()
