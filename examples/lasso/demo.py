"""
Lasso-path demo (reference examples/lasso/demo.py): load the bundled diabetes
dataset from HDF5, sweep the regularisation strength, and record the coordinate-
descent Lasso coefficients at each lambda. Saves a lasso-path plot when matplotlib
is available, otherwise prints the path as text.
"""

import numpy as np

import heat_tpu as ht
from heat_tpu.regression.lasso import Lasso


def main():
    path = ht.datasets.path("diabetes.h5")
    x = ht.load_hdf5(path, dataset="x", split=0)
    y = ht.load_hdf5(path, dataset="y", split=0)

    # normalise features (reference demo.py:27)
    x = x / ht.sqrt(ht.mean(x**2, axis=0))

    estimator = Lasso(max_iter=100)
    lamda = np.logspace(0, 4, 10) / 10

    theta_list = []
    for la in lamda:
        estimator.lam = float(la)
        estimator.fit(x, y)
        theta_list.append(estimator.theta.numpy().flatten())

    theta_lasso = np.stack(theta_list).T[1:, :]  # drop intercept row

    try:
        import matplotlib

        matplotlib.use("Agg")
        from matplotlib import pyplot as plt

        plt.figure(figsize=(8, 5))
        for i, coef in enumerate(theta_lasso):
            plt.semilogx(lamda, coef, label=f"feature {i}")
        plt.xlabel("lambda")
        plt.ylabel("coefficient")
        plt.title("Lasso paths — heat_tpu implementation")
        plt.legend(fontsize=7)
        out = "lasso_paths.png"
        plt.savefig(out, dpi=120)
        print(f"saved {out}")
    except Exception:
        print("lambda:", " ".join(f"{v:8.3f}" for v in lamda))
        for i, coef in enumerate(theta_lasso):
            print(f"feat {i}:", " ".join(f"{v:8.3f}" for v in coef))


if __name__ == "__main__":
    main()
