#!/usr/bin/env python
"""
CI transformer smoke (ISSUE 20): the one-executable-per-step train loop,
end to end, plus the elastic mid-train choreography across real OS
processes.

Leg 1 — fused steady state, in process: a fused train run must record ONE
flush per step with a flat ``fusion.kernels_compiled`` counter after
warmup, zero collective flush reasons, parameter/momentum buffers
re-donated every step, a falling loss, and fused-vs-eager loss parity at
f32 tolerance.

Leg 2 — elastic kill -9, across processes: two workers train the fused
loop against a shared heartbeat directory; the victim takes a real
``kill -9`` mid-train (no atexit, its heartbeat file freezes), the
survivor's per-step supervisor poll detects the loss, drains the pending
fused chain, checkpoints through the preemption-safe manager, and exits
``ELASTIC_RESTART_EXIT``; the relaunched SHRUNK (1-process) run restores
the latest valid checkpoint at the saved step and keeps training.

Exit 0 clean; 1 on any failed assertion. Usage:

    python scripts/transformer_smoke.py [--steps N] [--no-kill]
"""

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_WORKER = textwrap.dedent(
    """
    import os, signal, sys, time

    import numpy as np

    sys.path.insert(0, os.environ["TF_SMOKE_REPO"])
    from heat_tpu.nn import transformer as tf
    from heat_tpu.robustness import elastic
    from heat_tpu.utils.checkpoint import CheckpointManager

    pid = int(sys.argv[1]); nprocs = int(sys.argv[2])
    hb, ck, steps = sys.argv[3], sys.argv[4], int(sys.argv[5])

    cfg = tf.TransformerConfig()
    state = tf.init_state(cfg)
    rng = np.random.default_rng(1234)

    def batch():
        x = rng.integers(0, cfg.vocab, (4, 16), dtype=np.int64)
        return x.astype(np.int32), np.roll(x, -1, axis=1).astype(np.int32)

    if nprocs > 1 and pid == 1:
        # the victim: beats while training, then takes a real kill -9 —
        # no atexit, no flush, the heartbeat file freezes mid-run
        sup = elastic.ElasticSupervisor(hb, process_id=1, num_processes=2)
        for _ in range(3):
            sup.beat()
            x, y = batch()
            loss, state = tf.train_step(state, x, y)
            tf.read_loss(loss)
            time.sleep(0.02)
        sup.beat()
        print("victim about to die", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
    elif nprocs > 1:
        # the survivor: full supervision; the generous miss threshold
        # tolerates scheduler skew (a live-but-slow peer resets the count
        # on its next beat; only a dead one misses 40 straight)
        mgr = CheckpointManager(ck)
        sup = elastic.ElasticSupervisor(
            hb, process_id=0, num_processes=2, miss_threshold=40,
            manager=mgr,
        )
        try:
            for _ in range(10_000):
                sup.check(state.checkpoint_state, state.step)
                x, y = batch()
                loss, state = tf.train_step(state, x, y)
                tf.read_loss(loss)
                time.sleep(0.01)
            print("survivor never saw the loss", flush=True)
            sys.exit(3)
        except elastic.PeerLostError as e:
            print(f"survivor saved step {e.saved_step}", flush=True)
            sys.exit(elastic.ELASTIC_RESTART_EXIT)
    else:
        # the shrunk relaunch: restore the drained checkpoint, keep training
        mgr = CheckpointManager(ck)
        restored = mgr.restore_latest_valid(state.checkpoint_state())
        state = tf.TrainState.from_checkpoint(restored, cfg)
        start = state.step
        for _ in range(steps):
            x, y = batch()
            loss, state = tf.train_step(state, x, y)
            val = tf.read_loss(loss)
        print(f"shrunk resumed from {start} reached {state.step} "
              f"loss {val:.4f}", flush=True)
        sys.exit(0 if (start >= 1 and state.step == start + steps
                       and np.isfinite(val)) else 4)
    """
)


def leg_fused(check, steps: int) -> None:
    import numpy as np

    from heat_tpu.core import fusion
    from heat_tpu.monitoring import registry
    from heat_tpu.nn import transformer as tf

    with registry.capture():
        compiles = registry.REGISTRY.counter("fusion.kernels_compiled")
        reasons = registry.REGISTRY.counter("fusion.flush_reason")
        donated = registry.REGISTRY.counter("fusion.donated")
        flushes = registry.REGISTRY.counter("fusion.flushes")

        cfg = tf.TransformerConfig()
        state = tf.init_state(cfg)
        rng = np.random.default_rng(7)
        x = rng.integers(0, cfg.vocab, (4, 16), dtype=np.int64).astype(np.int32)
        y = np.roll(x, -1, axis=1).astype(np.int32)

        losses, per_step = [], []
        for _ in range(steps):
            c0, f0 = compiles.get(), flushes.get()
            loss, state = tf.train_step(state, x, y)
            losses.append(tf.read_loss(loss))
            per_step.append((compiles.get() - c0, flushes.get() - f0))

        check(all(c == 0 for c, _ in per_step[2:]),
              "zero steady-state compiles")
        check(all(f == 1 for _, f in per_step),
              "one fused executable per step")
        check(reasons.get("collective") == 0, "zero collective flushes")
        check(donated.get("steady_state") >= 2 * (steps - 2),
              "theta+mu re-donated per steady step")
        check(losses[-1] < losses[0] and np.isfinite(losses[-1]),
              "loss falls and stays finite")

        # fused-vs-eager parity on a fresh model (the differential oracle)
        fusion.clear_cache()
        ref = tf.init_state(cfg)
        prev = os.environ.pop("HEAT_TPU_TRANSFORMER")
        try:
            for _ in range(3):
                loss, ref = tf.train_step(ref, x, y)
                eager_val = tf.read_loss(loss)
        finally:
            os.environ["HEAT_TPU_TRANSFORMER"] = prev
        check(abs(eager_val - losses[2]) < 1e-5,
              "fused == eager loss at f32 tolerance")


def leg_elastic(check, tmp: str) -> None:
    from heat_tpu.robustness import elastic

    worker = os.path.join(tmp, "worker.py")
    with open(worker, "w") as f:
        f.write(_WORKER)
    hb = os.path.join(tmp, "hb")
    ck = os.path.join(tmp, "ck")
    os.makedirs(hb, exist_ok=True)
    env = dict(os.environ, TF_SMOKE_REPO=REPO, JAX_PLATFORMS="cpu",
               HEAT_TPU_TRANSFORMER="1")

    def spawn(pid, nprocs, steps=4):
        return subprocess.Popen(
            [sys.executable, worker, str(pid), str(nprocs), hb, ck,
             str(steps)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )

    procs = [spawn(0, 2), spawn(1, 2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    check(procs[1].returncode == -signal.SIGKILL,
          f"victim died by SIGKILL (rc={procs[1].returncode})")
    check(procs[0].returncode == elastic.ELASTIC_RESTART_EXIT,
          f"survivor exited ELASTIC_RESTART_EXIT (rc={procs[0].returncode})")
    check("survivor saved step" in outs[0],
          "survivor drained and saved mid-train")

    shrunk = spawn(0, 1, steps=4)
    out, _ = shrunk.communicate(timeout=600)
    check(shrunk.returncode == 0,
          f"shrunk relaunch restored and trained (rc={shrunk.returncode})")
    print(textwrap.indent(out.strip(), "     "))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--no-kill", action="store_true")
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("HEAT_TPU_MONITORING", "1")
    os.environ["HEAT_TPU_TRANSFORMER"] = "1"
    os.environ["HEAT_TPU_FUSION_DONATE"] = "force"
    for var in ("HEAT_TPU_FAULT_PLAN", "HEAT_TPU_CHAOS",
                "HEAT_TPU_BREAKER_FORCE_OPEN", "HEAT_TPU_AUDIT_RATE"):
        os.environ.pop(var, None)

    failures = []

    def check(ok, what):
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    leg_fused(check, args.steps)
    if not args.no_kill:
        with tempfile.TemporaryDirectory(prefix="transformer-smoke-") as tmp:
            leg_elastic(check, tmp)

    if failures:
        print(f"transformer smoke: {len(failures)} failure(s)")
        return 1
    print("transformer smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
