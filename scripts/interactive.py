#!/usr/bin/env python
"""
Interactive distributed session (reference scripts/interactive.py: an MPI-aware
InteractiveConsole started under ``mpirun -stdin all``).

TPU-native form: there is one controller, so a plain REPL suffices — this script
drops into an InteractiveConsole with ``heat_tpu`` preloaded and a banner showing
the device mesh every op will run on. Useful for poking at shardings:

    $ python scripts/interactive.py
    >>> x = ht.arange(16, split=0)
    >>> x.larray.sharding
"""

import code
import sys


def main():
    import jax

    import heat_tpu as ht

    devices = jax.devices()
    banner = (
        f"heat_tpu {ht.__version__} interactive session\n"
        f"devices ({len(devices)}): {', '.join(str(d) for d in devices)}\n"
        f"`ht` and `jax` are preloaded; ht.* ops run SPMD over all devices."
    )
    console = code.InteractiveConsole(locals={"ht": ht, "jax": jax})
    try:
        console.interact(banner=banner, exitmsg="")
    except SystemExit:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
