"""CI smoke for the fleet telemetry plane (ISSUE 14): boot a fused
workload with the exporter armed, scrape ``/metrics`` + ``/healthz`` +
``/readyz`` over urllib, and assert (a) every exposition line parses as
Prometheus text, (b) every catalog metric is present, (c) readiness
matches the environment — ready in a clean process, 503 with per-site
breaker reasons under ``HEAT_TPU_BREAKER_FORCE_OPEN`` (pass
``--expect-not-ready`` on that leg).

Usage: python scripts/exporter_smoke.py [--expect-not-ready]
Exit: 0 ok, 1 assertion failed.
"""

import json
import os
import sys
import urllib.error
import urllib.request


def get(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def main() -> int:
    expect_not_ready = "--expect-not-ready" in sys.argv
    os.environ.setdefault("HEAT_TPU_MONITORING", "1")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    import heat_tpu as ht
    from heat_tpu.monitoring import exporter
    from heat_tpu.robustness import breaker

    srv = exporter.start(port=0)
    print(f"exporter on {srv.url('/')}")

    # a small fused chain+sink workload so the scrape carries live counters
    x = ht.array(np.linspace(0.0, 1.0, 4096, dtype=np.float32).reshape(64, 64))
    y = ((x * 2.0 + 1.0) / 3.0 - 0.25).sum()
    float(y.larray)

    code, text = get(srv.url("/metrics"))
    assert code == 200, f"/metrics returned {code}"
    bad = exporter.validate_exposition(text)
    assert not bad, f"unparseable exposition lines: {bad[:5]}"
    lines = text.splitlines()
    for name, kind in exporter.CATALOG:
        mname = exporter.metric_name(name, "_total" if kind == "counter" else "")
        present = any(
            line.startswith(mname + " ") or line.startswith(mname + "{")
            or line.startswith(mname + "_count") or line.startswith(mname + "_sum")
            for line in lines
        )
        assert present, f"catalog metric missing from /metrics: {name}"
    assert any(line.startswith("heat_tpu_scale_signal ") for line in lines)
    print(f"/metrics: {len(lines)} parse-clean lines, full catalog present")

    code, body = get(srv.url("/healthz"))
    payload = json.loads(body)
    assert code == 200 and payload["ok"] is True, f"/healthz: {code} {body[:200]}"
    print("/healthz ok")

    code, body = get(srv.url("/readyz"))
    payload = json.loads(body)
    if expect_not_ready:
        assert code == 503 and payload["ready"] is False, (
            f"expected 503 under forced-open breakers, got {code} {body[:200]}"
        )
        expected = {f"breaker:{s}" for s in breaker.BREAKER_SITES}
        assert expected <= set(payload["reasons"]), payload["reasons"]
        print(f"/readyz correctly not ready: {len(payload['reasons'])} reasons")
    else:
        assert code == 200 and payload["ready"] is True, (
            f"expected ready, got {code} {body[:200]}"
        )
        print("/readyz ready")

    exporter.stop()
    print("exporter smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
