#!/usr/bin/env python
"""
CI generation smoke (ISSUE 19): boot a real 2-worker ingress with the
generation knob armed, stream the seeded generative trace through
``/v1/generate``, and SIGKILL one worker mid-load.

Asserts, end to end:

* every completed stream's wire digest matches BOTH the server's final-line
  sha256 AND the locally recomputed ``generate_reference`` oracle (zero
  wrong results — the acceptance bar; mid-stream reroute resumes the
  deterministic decode on the surviving worker and skips the already-sent
  token prefix, so the client sequence stays gapless);
* one worker was SIGKILLed while streams were in flight and the run still
  completed with zero mismatches and zero transport errors;
* the off-knob control: a worker booted WITHOUT ``HEAT_TPU_GENERATION``
  answers ``/v1/generate`` 404 ``generation-off`` through the relay.

Exit 0 clean; 1 on any failed assertion. Usage:

    python scripts/generation_smoke.py [--requests N] [--no-kill]
"""

import argparse
import json
import os
import signal
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--no-kill", action="store_true")
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("HEAT_TPU_MONITORING", "1")
    for var in ("HEAT_TPU_FAULT_PLAN", "HEAT_TPU_CHAOS",
                "HEAT_TPU_BREAKER_FORCE_OPEN"):
        os.environ.pop(var, None)
    from heat_tpu.serving import loadgen
    from heat_tpu.serving.server import Ingress

    failures = []

    def check(ok, what):
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    reqs = loadgen.gen_trace(seed=20260806, n=args.requests)
    expected = loadgen.expected_generation(reqs)
    with tempfile.TemporaryDirectory(prefix="generation-smoke-") as tmp:
        env = {
            "JAX_PLATFORMS": "cpu",
            "HEAT_TPU_GENERATION": "1",
            "HEAT_TPU_FUSION_DONATE": "force",
        }
        ing = Ingress(
            workers=2, cache_dir=os.path.join(tmp, "cache"), env=env
        ).start()
        try:
            killed = {}
            if not args.no_kill:
                def killer():
                    time.sleep(0.4)
                    pids = ing.worker_pids()
                    if pids:
                        os.kill(pids[0], signal.SIGKILL)
                        killed["pid"] = pids[0]

                t = threading.Thread(target=killer)
                t.start()
            stats = loadgen.run_generate(
                ing.url(), reqs, concurrency=6, expected=expected
            )
            if not args.no_kill:
                t.join()
            print("loadgen:", json.dumps(stats, sort_keys=True))
            check(stats["mismatches"] == 0, "zero wrong results")
            check(stats["errors"] == 0, "zero transport errors")
            check(
                stats["ok"] + stats["shed"] == len(reqs),
                "every request accounted",
            )
            check(
                stats["ok"] > 0 and stats["decode_tokens_per_s"] > 0,
                "generative goodput > 0",
            )
            if not args.no_kill:
                check(bool(killed), "a worker was SIGKILLed mid-load")
        finally:
            ing.stop()

        # off-knob control: no generation env -> the endpoint does not exist
        ing = Ingress(
            workers=1,
            cache_dir=os.path.join(tmp, "cache-off"),
            env={"JAX_PLATFORMS": "cpu"},
        ).start()
        try:
            req = urllib.request.Request(
                ing.url("/v1/generate"),
                data=json.dumps({"prompt": [1, 2], "max_new": 4}).encode(),
                headers={"Content-Type": "application/json"},
            )
            try:
                urllib.request.urlopen(req, timeout=30)
                check(False, "off-knob worker answers 404 generation-off")
            except urllib.error.HTTPError as e:
                body = json.loads(e.read().decode())
                check(
                    e.code == 404 and body.get("reason") == "generation-off",
                    "off-knob worker answers 404 generation-off",
                )
        finally:
            ing.stop()
    if failures:
        print(f"generation smoke: {len(failures)} failure(s)")
        return 1
    print("generation smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
