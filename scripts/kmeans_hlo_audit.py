"""
HLO-bytes audit of the KMeans north-star step (VERDICT r4 next-round #1).

Round 1-4 framed the Lloyd-step headline against an *HBM* bytes model (one
bf16 pass over x + the labels write, 71.3 MB/iter) and the chip's nominal
819 GB/s — reporting 75-97% "of HBM roofline" depending on session. This
script proves, from the compiled program itself, that the model was a
category error at the bench shape:

1. XLA hoists the bf16 copy of x (67.1 MB), x_norm (4.2 MB) and the label
   buffers OUT of the `fori_loop` and pins them in memory space 1 (VMEM —
   `S(1)` layout annotations; the v5e has 128 MB of VMEM). The compiled
   loop's HBM temp allocation is ~2.3 MB. Steady-state HBM traffic per
   iteration is ~zero: the f32 input is read from HBM ONCE, in the prologue.
2. The (n, k) distance matrix and the (n, k) one-hot matrix NEVER
   materialize in any memory: argmin is output-fused into the distance GEMM,
   and the one-hot is computed inline inside the centroid-update GEMM fusion
   from the s32 labels.
3. The audited per-iteration traffic — all of it VMEM — is two passes over
   the bf16 x (the two GEMM-operand reads XLA's materialization rule forces)
   plus three passes over the s32 labels and one bf16 min-distance write:
       2*N*F*2 + 3*N*4 + N*2  =  148.9 MB/iter  at  N=2^20, F=32, K=8.
   The measured ~114 us/iter therefore moves ~1.31 TB/s — 1.7x the chip's
   *measured same-session* HBM stream rate, which is impossible for any
   HBM-bound formulation and empirically confirms the VMEM residency.
4. At N=2^22 the working set (268 MB bf16) no longer fits VMEM: the same
   parse shows the temp allocation jumping to ~277 MB (HBM), i.e. the
   residency claim at N=2^20 is a real compiler decision this audit
   detects, not a parsing artifact.

The formulation is minimal within XLA's fusion model: the only remaining
traffic reduction (merging the two GEMM passes into one) requires a fused
single-pass kernel, which was built twice (rounds 1 and 3, pallas,
bf16-streaming, K-on-sublanes) and measured 3.2x SLOWER — skinny K=8 GEMMs
collapse MXU utilization inside a kernel (doc/kmeans_northstar.md).

Run on the real chip:  python scripts/kmeans_hlo_audit.py [--out doc/kmeans_hlo_audit.md]
"""

import argparse
import json
import re
import sys
from pathlib import Path

import numpy as np

N, F, K, ITERS = 1_048_576, 32, 8, 30


def _space(layout: str) -> str:
    """Memory space of an HLO buffer from its layout annotation."""
    return "S(1)/VMEM" if "S(1)" in layout else "HBM(default)"


_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]\{([^}]*)\}")

_DTYPE_BYTES = {
    "f32": 4, "s32": 4, "u32": 4, "bf16": 2, "f16": 2, "pred": 1,
    "s8": 1, "u8": 1, "f64": 8, "s64": 8, "u64": 8, "c64": 8,
}


def _buffers(text: str):
    """All (dtype, shape, layout) buffer literals in an HLO snippet."""
    out = []
    for m in _SHAPE_RE.finditer(text):
        dt, dims, layout = m.group(1), m.group(2), m.group(3)
        if dt not in _DTYPE_BYTES:
            continue
        shape = tuple(int(d) for d in dims.split(",") if d) if dims else ()
        out.append((dt, shape, layout, int(np.prod(shape or (1,))) * _DTYPE_BYTES[dt]))
    return out


def _find_while_body(hlo: str) -> str:
    """The while-loop body computation of the compiled iterate program."""
    m = re.search(r"while\(.*?\), condition=%?([\w.\-]+), body=%?([\w.\-]+)", hlo)
    if m:
        body_name = m.group(2)
    else:  # older dump order: body= first
        m = re.search(r"while\(.*?\), body=%?([\w.\-]+)", hlo)
        if not m:
            raise RuntimeError(
                "could not locate the while instruction in the HLO dump "
                "(XLA text format changed?) — audit cannot proceed"
            )
        body_name = m.group(1)
    cm = re.search(
        r"^%?" + re.escape(body_name) + r" [^\n]*\{\n(.*?)^\}",
        hlo,
        re.M | re.S,
    )
    if not cm:
        raise RuntimeError(f"while body computation {body_name!r} not found in dump")
    return cm.group(1)


def audit_shape(n: int):
    import jax
    import jax.numpy as jnp

    from heat_tpu.cluster.kmeans import _kmeans_step, _kmeans_iterate

    dev = jax.devices()[0]
    x = jax.device_put(jnp.zeros((n, F), jnp.float32), dev)
    c = jnp.zeros((K, F), jnp.float32)
    fn = jax.jit(lambda x, c: _kmeans_iterate(x, c, _kmeans_step, ITERS))
    comp = fn.lower(x, c).compile()
    ma = comp.memory_analysis()
    hlo = comp.as_text()
    body = _find_while_body(hlo)

    # --- claim 2: no (n, k) buffer materializes at the top level of the body.
    # Top-level = instruction result shapes in the body computation; fused
    # interiors live in separate %fused_computation blocks, not here.
    nk_toplevel = [
        (dt, shape)
        for dt, shape, layout, _ in _buffers(body)
        if shape == (n, K)
    ]

    # --- claim 1/3: traffic table of the body's top-level instructions.
    rows = []
    for line in body.splitlines():
        line = line.strip()
        m = re.match(r"(?:ROOT )?%([\w.\-]+) = (.*)", line)
        if not m:
            continue
        name, rest = m.group(1), m.group(2)
        bufs = _buffers(rest.split(" calls=")[0].split(", metadata=")[0])
        if not bufs:
            continue
        big = [b for b in bufs if b[3] >= n]  # ignore sub-row-size scalars
        if not big:
            continue
        rows.append(
            {
                "instruction": name,
                "buffers": [
                    {"dtype": dt, "shape": list(shape), "mb": round(nbytes / 1e6, 1),
                     "space": _space(layout)}
                    for dt, shape, layout, nbytes in big
                ],
            }
        )
    return {
        "n": n,
        "temp_mb": round(ma.temp_size_in_bytes / 1e6, 1),
        "peak_mb": round(ma.peak_memory_in_bytes / 1e6, 1),
        "argument_mb": round(ma.argument_size_in_bytes / 1e6, 1),
        "nk_toplevel_buffers": nk_toplevel,
        "body_rows": rows,
        "vmem_bytes_in_body": sum(
            b[3] for b in _buffers(body) if "S(1)" in b[2] and b[3] >= n
        ),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="write a markdown report here")
    args = ap.parse_args()

    small = audit_shape(N)
    large = audit_shape(N * 4)

    model_mb = (2 * N * F * 2 + 3 * N * 4 + N * 2) / 1e6
    ok = {
        "no_nk_materialization": not small["nk_toplevel_buffers"],
        "hbm_temp_small": small["temp_mb"] < 16.0,
        "working_set_in_vmem": small["vmem_bytes_in_body"] >= N * F * 2,
        "large_n_spills_to_hbm": large["temp_mb"] > N * 4 * F * 2 / 1e6 * 0.9,
    }
    summary = {
        "audited_vmem_traffic_mb_per_iter": round(model_mb, 1),
        "steady_state_hbm_mb_per_iter": small["temp_mb"],
        "checks": ok,
        "small": {k: small[k] for k in ("n", "temp_mb", "peak_mb", "argument_mb")},
        "large": {k: large[k] for k in ("n", "temp_mb", "peak_mb", "argument_mb")},
        "all_ok": all(ok.values()),
    }
    print(json.dumps(summary, indent=2))

    if args.out:
        lines = [
            "# KMeans Lloyd-step HLO-bytes audit (round 5)",
            "",
            "Generated by `scripts/kmeans_hlo_audit.py` on the real chip; see the",
            "script docstring for the full argument. Key facts, each checked",
            "against the compiled HLO / buffer assignment:",
            "",
            f"- audited per-iteration traffic model: **{model_mb:.1f} MB, all VMEM**",
            "  (2 bf16 passes over x forced by XLA's GEMM-operand materialization",
            "  rule + 3 s32 label passes + 1 bf16 min-distance write)",
            f"- steady-state HBM per iteration: **~0** (HBM temp allocation of the",
            f"  whole 30-iteration program: {small['temp_mb']} MB; the f32 input is read",
            "  once, in the prologue)",
            "- the (n, k) distance matrix and one-hot NEVER materialize:"
            f" top-level (n,k) buffers in the loop body = {small['nk_toplevel_buffers']}",
            f"- VMEM-annotated (S(1)) bytes carried through the loop body:"
            f" {small['vmem_bytes_in_body'] / 1e6:.1f} MB",
            f"- control at N=2^22 (working set 4x, > VMEM): HBM temp jumps to"
            f" {large['temp_mb']} MB — the parser detects the spill, so the N=2^20"
            " residency is a real compiler decision, not a parsing artifact",
            "",
            "## Checks",
            "",
        ]
        for k, v in ok.items():
            lines.append(f"- `{k}`: {'PASS' if v else 'FAIL'}")
        lines += [
            "",
            "## Loop-body traffic table (N=2^20; buffers >= one row-array)",
            "",
            "| instruction | buffer | MB | space |",
            "|---|---|---|---|",
        ]
        for row in small["body_rows"]:
            for b in row["buffers"]:
                lines.append(
                    f"| `{row['instruction']}` | {b['dtype']}{b['shape']} | {b['mb']} | {b['space']} |"
                )
        lines += [
            "",
            "## Consequence for the bench",
            "",
            "The pre-r5 '75% of HBM roofline' headline divided an *HBM* bytes",
            "model (71.3 MB/iter) by the *nominal* 819 GB/s. Neither side of that",
            "ratio describes this program: per-iteration HBM traffic is ~0 and the",
            "148.9 MB of real traffic rides VMEM at ~1.3 TB/s — 1.7-2.1x the",
            "chip's measured HBM stream rate. bench.py (round 5) reports the",
            "audited VMEM model, the measured same-session HBM stream probe, and",
            "the ratio between them (`kmeans_vs_hbm_stream`), and gates pairs on",
            "a 4x-of-stream physical ceiling instead of the fictitious HBM one",
            "(below-1x rates are a loaded chip, reported not gated).",
            "",
        ]
        Path(args.out).write_text("\n".join(lines))
        print(f"wrote {args.out}", file=sys.stderr)
    return 0 if summary["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
