#!/usr/bin/env python
"""
CI fleet smoke (ISSUE 15): boot a real 2-worker ingress and drive the
recorded multi-tenant trace through it over HTTP.

Asserts, end to end:

* every response digest matches the locally computed reference (zero wrong
  results; sheds are allowed — they are the admission contract);
* the shared cache dir was written by the workers (the L2 is live);
* the workers published telemetry-spool snapshots and /readyz serves a
  fleet ``scale_signal`` from them;
* /readyz is green with both workers, /metrics parses as Prometheus text
  with per-process labels;
* with ``--batching`` (the default), the workers ran with
  ``HEAT_TPU_SERVING_BATCH=1`` + tenancy armed — the same trace must land
  identically (the wire-level twin of the differential suite). With
  ``--no-batching`` the workers run with the hatch pinned off.

Exit 0 clean; 1 on any failed assertion. Usage:

    python scripts/fleet_smoke.py [--no-batching] [--requests N]
"""

import argparse
import json
import os
import sys
import tempfile
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--no-batching", action="store_true")
    p.add_argument("--requests", type=int, default=48)
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("HEAT_TPU_MONITORING", "1")
    from heat_tpu.monitoring import exporter
    from heat_tpu.serving import loadgen
    from heat_tpu.serving.server import Ingress

    failures = []

    def check(ok, what):
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    reqs = loadgen.trace(n=args.requests)
    expected = loadgen.expected_digests(reqs)
    with tempfile.TemporaryDirectory(prefix="fleet-smoke-") as tmp:
        cache = os.path.join(tmp, "cache")
        spool = os.path.join(tmp, "spool")
        os.makedirs(spool)
        env = {
            "JAX_PLATFORMS": "cpu",
            "HEAT_TPU_TELEMETRY_EVERY": "1",
            "HEAT_TPU_TENANCY": "alpha:3,beta:1",
            "HEAT_TPU_SERVING_BATCH": "0" if args.no_batching else "1",
        }
        ing = Ingress(workers=2, cache_dir=cache, spool=spool, env=env).start()
        try:
            stats = loadgen.run(ing.url(), reqs, concurrency=6, expected=expected)
            print("loadgen:", json.dumps(stats, sort_keys=True))
            check(stats["mismatches"] == 0, "zero wrong results")
            check(stats["errors"] == 0, "zero transport errors")
            check(stats["ok"] + stats["shed"] == len(reqs), "every request accounted")
            check(stats["ok"] > 0 and stats["goodput_rps"] > 0, "goodput > 0")
            check(
                os.path.isdir(os.path.join(cache, "exec"))
                and len(os.listdir(os.path.join(cache, "exec"))) > 0,
                "workers warmed the shared L2",
            )
            with urllib.request.urlopen(ing.url("/readyz"), timeout=10) as r:
                ready = json.loads(r.read().decode())
            check(ready["ready"] and ready["workers"] == 2, "/readyz green, 2 workers")
            check(ready["scale_signal"] is not None, "spool-fed scale signal present")
            with urllib.request.urlopen(ing.url("/metrics"), timeout=10) as r:
                text = r.read().decode()
            check(exporter.validate_exposition(text) == [], "/metrics parse-clean")
            check("heat_tpu_fleet_processes 2" in text, "fleet exposition sees 2 workers")
        finally:
            ing.stop()
    if failures:
        print(f"fleet smoke: {len(failures)} failure(s)")
        return 1
    print("fleet smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
