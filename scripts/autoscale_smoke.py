#!/usr/bin/env python
"""
CI autoscale smoke (ISSUE 17): boot a 1-worker ingress with the closed
autoscaling loop armed and drive the recorded diurnal ramp
(night/ramp/peak/drain) through it over HTTP.

Asserts, end to end:

* every response digest matches the locally computed reference (zero wrong
  results — sheds are allowed, they are the admission contract);
* the worker pool GREW under the peak phase (live workers > 1 observed)
  and came back down by the end of the drain idle window — the worker
  count tracks offered load;
* the pool never left the ``[min_workers, max_workers]`` bounds;
* the controller's decision ledger (``/statusz`` → ``autoscale``) shows at
  least one grow and one shrink;
* worst per-phase p99 stays under the (generous, CI-calibrated) bound.

Workers boot through the predictive warmup driver (``--warmup-boot
predictive``): capacity added at the peak warms the corpus recorded during
the night/ramp phases before taking traffic.

Exit 0 clean; 1 on any failed assertion. Usage:

    python scripts/autoscale_smoke.py [--p99-bound-us N] [--max-workers N]
"""

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _get(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--max-workers", type=int, default=3)
    p.add_argument(
        "--p99-bound-us", type=float, default=30_000_000.0,
        help="worst per-phase p99 bound (generous: CI CPUs compile inline)",
    )
    p.add_argument(
        "--drain-wait-s", type=float, default=20.0,
        help="post-drain idle window for the shrink leg to land",
    )
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("HEAT_TPU_MONITORING", "1")
    from heat_tpu.serving import loadgen
    from heat_tpu.serving.server import Autoscaler, Ingress

    failures = []

    def check(ok, what):
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    with tempfile.TemporaryDirectory(prefix="autoscale-smoke-") as tmp:
        cache = os.path.join(tmp, "cache")
        spool = os.path.join(tmp, "spool")
        os.makedirs(spool)
        env = {
            "JAX_PLATFORMS": "cpu",
            "HEAT_TPU_TELEMETRY_EVERY": "1",
            "HEAT_TPU_SERVING_BATCH": "1",
        }
        scaler = Autoscaler(
            min_workers=1,
            max_workers=args.max_workers,
            # CPU-CI calibration: queue_depth × p99_us — a saturated single
            # worker sits well above 1000, an idle fleet at exactly 0
            grow_threshold=1_000.0,
            shrink_threshold=100.0,
            grow_ticks=2,
            shrink_ticks=4,
            cooldown_ticks=4,
        )
        ing = Ingress(
            workers=1,
            cache_dir=cache,
            spool=spool,
            max_age_s=10.0,
            env=env,
            autoscaler=scaler,
            warmup_boot="predictive",
        ).start()
        try:
            observed = []

            def on_phase(stats):
                live = _get(ing.url("/healthz"))["workers"]
                observed.append(live)
                print(
                    "phase %-5s: live=%d ok=%d shed=%d p99_us=%s"
                    % (stats["phase"], live, stats["ok"], stats["shed"],
                       stats["p99_us"])
                )

            result = loadgen.run_phases(
                ing.url(), settle_s=3.0, on_phase=on_phase
            )
            check(result["mismatches"] == 0, "zero wrong results across the ramp")
            check(result["errors"] == 0, "zero transport errors")
            check(max(observed) > 1, "pool grew under load (live > 1 observed)")
            check(
                all(1 <= n <= args.max_workers for n in observed),
                "worker count stayed within [1, %d]" % args.max_workers,
            )
            check(
                result["p99_us"] is not None
                and result["p99_us"] <= args.p99_bound_us,
                "worst phase p99 %.0fµs within bound" % (result["p99_us"] or -1),
            )
            # the drain leg: give the controller its idle window, then the
            # pool must have shrunk back toward the floor
            deadline = time.time() + args.drain_wait_s
            final = observed[-1]
            while time.time() < deadline:
                final = _get(ing.url("/healthz"))["workers"]
                if final < max(observed):
                    break
                time.sleep(1.0)
            check(final < max(observed), "pool shrank after the drain (%d -> %d)"
                  % (max(observed), final))
            status = _get(ing.url("/statusz"))
            decisions = (status.get("autoscale") or {}).get("decisions") or {}
            print("autoscale decisions:", json.dumps(decisions, sort_keys=True))
            check(decisions.get("grow", 0) >= 1, "controller recorded a grow")
            check(decisions.get("shrink", 0) >= 1, "controller recorded a shrink")
        finally:
            ing.stop()
    if failures:
        print(f"autoscale smoke: {len(failures)} failure(s)")
        return 1
    print("autoscale smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
