#!/usr/bin/env python
"""
Generated per-symbol API reference (VERDICT r4 #7).

The reference ships a Sphinx autodoc tree (/root/reference/doc/source/: conf.py
plus an RST stub per module); no doc generator is vendored in this image, so
this script IS the autodoc: it walks every ``heat_tpu`` module, pulls each
``__all__`` symbol's signature, docstring, and source location with
``inspect``, and emits deterministic markdown under ``doc/api/`` —

  doc/api/index.md             package tree + alphabetical symbol index
  doc/api/<dotted.module>.md   one page per module, one section per symbol;
                               classes additionally list their public methods

Deterministic by construction (sorted, no timestamps) so ``--check`` can diff
a fresh render against the committed tree in CI (ci.yaml docs job).

Run:    python scripts/gen_api_docs.py          # (re)render doc/api/
Check:  python scripts/gen_api_docs.py --check  # exit 1 if stale
"""

import importlib
import inspect
import os
import pkgutil
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "doc", "api")
sys.path.insert(0, REPO)

import heat_tpu  # noqa: E402

# Symbols whose presence in a module's ``__all__`` depends on the render
# host's optional packages or probed hardware — excluded from the render (with
# a static note instead) so `--check` agrees across environments:
# io.py extends __all__ when h5py/netCDF4 import (io.py:105,183); devices.py
# registers accelerator devices it can probe.
ENV_DEPENDENT = {
    "heat_tpu.core.io": {
        "load_hdf5": "requires h5py",
        "save_hdf5": "requires h5py",
        "load_netcdf": "requires netCDF4",
        "save_netcdf": "requires netCDF4",
    },
    "heat_tpu.core.devices": {
        "tpu": "present when a TPU backend is probed",
        "gpu": "present when a GPU backend is probed",
    },
    # the comm singletons repr their mesh size, which is ? before the lazy
    # device probe and the probed count after — init-order-dependent
    "heat_tpu.core.communication": {
        "WORLD": "MeshCommunication over all probed devices",
        "SELF": "single-device MeshCommunication",
        "MPI_WORLD": "alias of WORLD (reference-name parity)",
        "MPI_SELF": "alias of SELF (reference-name parity)",
    },
}


def _external_origin(obj):
    """Top-level package name when ``obj``'s source lives outside this repo
    (an optax/flax re-export whose docstring/signature we do not own), else
    None. Externally-resolved symbols are listed by name instead of rendered —
    their upstream docstrings change with the render host's installed
    versions, which used to break the docs-freshness gate for unrelated PRs."""
    try:
        f = inspect.getsourcefile(inspect.unwrap(obj))
    except (TypeError, OSError):
        return None
    if not f or f.startswith(REPO):
        return None
    mod = getattr(obj, "__module__", None) or ""
    return mod.split(".")[0] or "external"


def _modules():
    """Every importable heat_tpu module that exports an ``__all__``."""
    mods = []
    for m in pkgutil.walk_packages(heat_tpu.__path__, "heat_tpu."):
        try:
            mod = importlib.import_module(m.name)
        except Exception:
            continue
        if getattr(mod, "__all__", None):
            mods.append(mod)
    # testing isn't reached by walk_packages order guarantees everywhere;
    # sort for determinism
    return sorted(mods, key=lambda m: m.__name__)


def _sig(obj):
    try:
        s = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # default values that repr with a memory address would make the render
    # nondeterministic; scrub them
    return re.sub(r" at 0x[0-9a-f]+", "", s)


def _src(obj):
    """repo-relative ``path:line`` of the symbol's definition, if resolvable."""
    try:
        f = inspect.getsourcefile(inspect.unwrap(obj))
        _, line = inspect.getsourcelines(inspect.unwrap(obj))
    except (TypeError, OSError):
        return None
    if not f or not f.startswith(REPO):
        return None
    return f"{os.path.relpath(f, REPO)}:{line}"


def _doc(obj):
    d = inspect.getdoc(obj)
    return d.strip() if d else ""


def _class_section(name, obj, lines):
    lines.append(f"### `class {name}{_sig(obj)}`\n")
    src = _src(obj)
    if src:
        lines.append(f"*Source: `{src}`*\n")
    doc = _doc(obj)
    if doc:
        lines.append(doc + "\n")
    methods = []
    for mname, m in sorted(vars(obj).items()):
        if mname.startswith("_") and mname != "__init__":
            continue
        if isinstance(m, (staticmethod, classmethod)):
            m = m.__func__
        if isinstance(m, property):
            first = _doc(m.fget).split("\n")[0] if m.fget and _doc(m.fget) else ""
            methods.append((f"{mname}", "*(property)* " + first))
        elif callable(m):
            first = _doc(m).split("\n")[0] if _doc(m) else ""
            methods.append((f"{mname}{_sig(m)}", first))
    if methods:
        lines.append("| method | summary |")
        lines.append("|---|---|")
        for sig, first in methods:
            sig_c = sig.replace("|", "\\|")
            first_c = first.replace("|", "\\|")
            lines.append(f"| `{sig_c}` | {first_c} |")
        lines.append("")


def _symbol_section(name, obj, lines):
    if inspect.isclass(obj):
        _class_section(name, obj, lines)
        return
    if callable(obj):
        lines.append(f"### `{name}{_sig(obj)}`\n")
    else:
        lines.append(f"### `{name}`\n")
        lines.append(f"Constant: `{re.sub(r' at 0x[0-9a-f]+', '', repr(obj))}`\n")
        # no docstring for plain constants: inspect.getdoc falls through to
        # the builtin type's docstring (float/int), whose wording varies by
        # Python version — rendering it made the freshness gate host-dependent
        return
    src = _src(obj)
    if src:
        lines.append(f"*Source: `{src}`*\n")
    doc = _doc(obj)
    if doc:
        lines.append(doc + "\n")


def render():
    """Return ``{relative_path: content}`` for the whole doc/api tree."""
    pages = {}
    index_tree = ["# heat_tpu API reference\n",
                  "Generated by `scripts/gen_api_docs.py` — do not edit by "
                  "hand; re-run the script after changing any public "
                  "docstring or signature (CI diffs a fresh render).\n",
                  "## Modules\n"]
    symbol_index = {}  # symbol -> (module, anchor)
    for mod in _modules():
        mname = mod.__name__
        lines = [f"# `{mname}`\n"]
        mdoc = _doc(mod)
        if mdoc:
            lines.append(mdoc.split("\n\n")[0] + "\n")
        env_dep = ENV_DEPENDENT.get(mname, {})
        exported = sorted(set(mod.__all__) - set(env_dep))
        external = {}
        for sym in list(exported):
            obj = getattr(mod, sym, None)
            if obj is None:
                continue
            origin = _external_origin(obj)
            if origin is not None:
                external[sym] = origin
                exported.remove(sym)
                continue
            _symbol_section(sym, obj, lines)
            symbol_index.setdefault(sym, mname)
        if external:
            lines.append("### Re-exported symbols\n")
            lines.append(
                "Defined by an external dependency and re-exported here "
                "(not rendered: their docstrings/signatures track the "
                "installed upstream version, not this repo):\n"
            )
            for sym in sorted(external):
                lines.append(f"- `{sym}` — from `{external[sym]}`")
            lines.append("")
        if env_dep:
            lines.append("### Optional symbols\n")
            lines.append(
                "Exported only when their optional dependency/backend is "
                "available (not rendered: environment-dependent):\n"
            )
            for sym in sorted(env_dep):
                lines.append(f"- `{sym}` — {env_dep[sym]}")
            lines.append("")
        pages[f"{mname}.md"] = "\n".join(lines).rstrip() + "\n"
        index_tree.append(f"- [`{mname}`]({mname}.md) — {len(exported)} symbols")
    index_tree.append("\n## Symbol index\n")
    for sym in sorted(symbol_index, key=str.lower):
        mname = symbol_index[sym]
        index_tree.append(f"- [`{sym}`]({mname}.md) (`{mname}`)")
    pages["index.md"] = "\n".join(index_tree) + "\n"
    return pages


def main():
    check = "--check" in sys.argv
    pages = render()
    stale = []
    os.makedirs(OUT, exist_ok=True)
    current = {f for f in os.listdir(OUT) if f.endswith(".md")}
    for rel, content in pages.items():
        path = os.path.join(OUT, rel)
        old = open(path).read() if os.path.exists(path) else None
        if old != content:
            stale.append(rel)
            if not check:
                with open(path, "w") as fh:
                    fh.write(content)
    for orphan in sorted(current - set(pages)):
        stale.append(orphan + " (orphan)")
        if not check:
            os.remove(os.path.join(OUT, orphan))
    n_syms = sum(p.count("\n### ") for p in pages.values())
    if check and stale:
        print(f"doc/api is stale ({len(stale)} pages): {stale[:8]} ...")
        print("re-run: python scripts/gen_api_docs.py")
        return 1
    print(f"doc/api: {len(pages)} pages, {n_syms} symbol sections "
          f"({'clean' if not stale else f'{len(stale)} updated'})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
