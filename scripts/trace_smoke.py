#!/usr/bin/env python
"""
CI trace smoke (ISSUE 16): boot a real 2-worker ingress with
``HEAT_TPU_TRACE_SAMPLE=1``, drive it over HTTP, and WALK the merged
/trace document — the live twin of the test-suite schema assertions.

Asserts, end to end:

* every response digest matches the local reference and every answered
  request came back traced (``stages_ms`` on the wire);
* the sequential phase's server-side stage sum lands within 10% of the
  client-measured wire latency (the decomposition acceptance bar);
* /rpcz serves the top-N slowest recent traces, slowest first, each with
  the full ingress_route→respond breakdown, plus per-stage
  ``{count, p50_us, p99_us}``;
* the merged /trace renders ONE connected span tree per sampled request:
  an ``ingress.request`` root on the ingress pid, every worker-side
  ``serving.flush`` parented under the root's span id on a real worker
  pid, timestamps nesting monotonically — at least two distinct pids per
  tree (the cross-process contract).

Exit 0 clean; 1 on any failed assertion. Usage:

    python scripts/trace_smoke.py [--requests N]
"""

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def fetch_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def walk_trees(doc, ingress_pid, worker_pids, check):
    """The span-tree walk: one connected tree per trace id, real pids,
    monotone timestamps. Returns the trace ids that had a root."""
    evs = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    roots = {
        e["args"]["trace_id"]: e
        for e in evs
        if e.get("name") == "ingress.request" and "trace_id" in e.get("args", {})
    }
    check(bool(roots), "merged /trace has ingress.request roots")
    connected = monotone = cross = 0
    for tid, root in roots.items():
        flushes = [
            e
            for e in evs
            if e.get("name") == "serving.flush"
            and e.get("args", {}).get("trace_id") == tid
        ]
        if not flushes:
            continue
        if all(f["args"].get("parent_span_id") == root["args"]["span_id"] for f in flushes):
            connected += 1
        if root["pid"] == ingress_pid and all(f["pid"] in worker_pids for f in flushes):
            cross += 1
        if all(
            f["ts"] >= root["ts"] - 2000
            and f["ts"] + f["dur"] <= root["ts"] + root["dur"] + 2000
            for f in flushes
        ):
            monotone += 1
    n = len(roots)
    check(connected == n, f"every tree connected ({connected}/{n} flush→root links)")
    check(cross == n, f"every tree spans >=2 real pids ({cross}/{n})")
    check(monotone == n, f"every tree's timestamps nest ({monotone}/{n})")
    return set(roots)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=48)
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("HEAT_TPU_MONITORING", "1")
    os.environ["HEAT_TPU_TRACE_SAMPLE"] = "1"
    from heat_tpu.serving import loadgen
    from heat_tpu.serving.server import Ingress

    failures = []

    def check(ok, what):
        print(("ok   " if ok else "FAIL ") + what)
        if not ok:
            failures.append(what)

    with tempfile.TemporaryDirectory(prefix="trace-smoke-") as tmp:
        cache = os.path.join(tmp, "cache")
        spool = os.path.join(tmp, "spool")
        os.makedirs(spool)
        env = {
            "JAX_PLATFORMS": "cpu",
            "HEAT_TPU_MONITORING": "1",
            "HEAT_TPU_TELEMETRY_EVERY": "1",
        }
        ing = Ingress(workers=2, cache_dir=cache, spool=spool, env=env).start()
        try:
            # ---- phase 1: sequential, the strict decomposition check (no
            # concurrency, so the client wall IS the request wall)
            reqs = loadgen.trace(seed=5, n=min(12, args.requests))
            stats = loadgen.run(
                ing.url(), reqs, concurrency=1, expected=loadgen.expected_digests(reqs)
            )
            print("loadgen[seq]:", json.dumps(stats, sort_keys=True))
            check(stats["mismatches"] == 0 and stats["errors"] == 0, "zero wrong results (seq)")
            check(stats["ok"] == len(reqs), "every request answered (seq)")
            check(stats["traced"] == stats["ok"], "every answered request traced")
            ratio = stats.get("breakdown_ratio_p50", 0.0)
            check(
                0.9 <= ratio <= 1.05,
                f"stage sum within 10% of wire latency (median ratio {ratio})",
            )

            # ---- phase 2: concurrent load for the tree walk
            reqs2 = loadgen.trace(seed=6, n=args.requests)
            stats2 = loadgen.run(
                ing.url(), reqs2, concurrency=6, expected=loadgen.expected_digests(reqs2)
            )
            print("loadgen[conc]:", json.dumps(stats2, sort_keys=True))
            check(stats2["mismatches"] == 0 and stats2["errors"] == 0, "zero wrong results (conc)")
            check(stats2["traced"] == stats2["ok"], "every answered request traced (conc)")

            rz = fetch_json(ing.url("/rpcz"))
            check(rz["sampling"] == 1.0, "/rpcz reports sampling 1.0")
            check(rz["recent"] >= stats["ok"], "/rpcz ring holds recent traces")
            tops = rz["top"]
            check(
                bool(tops) and tops == sorted(tops, key=lambda e: -e["total_ms"]),
                "/rpcz top is slowest-first",
            )
            check(
                all("ingress_route" in e["stages_ms"] and "respond" in e["stages_ms"] for e in tops),
                "/rpcz entries carry the full breakdown",
            )
            check(
                all(rz["stages"][s]["p50_us"] <= rz["stages"][s]["p99_us"] for s in rz["stages"]),
                "/rpcz per-stage percentiles ordered",
            )

            # the sidecar of the last response races the walk (it is written
            # off the critical path) — poll the merged doc briefly
            want = stats["ok"] + stats2["ok"]
            doc = {}
            for _ in range(40):
                with urllib.request.urlopen(ing.url("/trace"), timeout=10) as r:
                    doc = json.loads(r.read().decode())
                evs = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
                root_ids = {
                    e["args"]["trace_id"]
                    for e in evs
                    if e.get("name") == "ingress.request" and "trace_id" in e.get("args", {})
                }
                flushed = {
                    e["args"]["trace_id"]
                    for e in evs
                    if e.get("name") == "serving.flush" and "trace_id" in e.get("args", {})
                }
                if len(root_ids) >= want and root_ids <= flushed:
                    break
                time.sleep(0.25)
            seen = walk_trees(doc, os.getpid(), set(ing.worker_pids()), check)
            check(len(seen) == want, f"one root per sampled request ({len(seen)}/{want})")
        finally:
            ing.stop()
    if failures:
        print(f"trace smoke: {len(failures)} failure(s)")
        return 1
    print("trace smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
